"""Layered configuration: programmatic dataclasses + GUBER_* env vars +
key=value config file.

The analog of the reference's config surface (config.go › Config /
BehaviorConfig / DaemonConfig / SetupDaemonConfig / SetDefaults —
reconstructed, mount empty): same knob names, same layering (defaults <
config file < environment), Go-style duration strings ("500ms", "30s")
accepted everywhere a duration appears.
"""
from __future__ import annotations

import dataclasses
import logging
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from .types import PeerInfo

log = logging.getLogger("gubernator_tpu")

#: The GUBER_* environment-variable registry: every env var the code
#: reads, with a one-line operator description.  guberlint's ``envreg``
#: pass enforces it both ways (a read without an entry and an entry
#: without a read are both violations), and tools/check_metrics.py
#: lints the prose docs against it — so the operator surface can never
#: drift from the code.  Keep entries alphabetized.
ENV_REGISTRY: Dict[str, str] = {
    "GUBER_ADMISSION_LIMIT": "dispatcher ingress bound in rows; 0 disables (default 8×max_wave)",
    "GUBER_ADVERTISE_ADDRESS": "address peers should dial for this daemon",
    "GUBER_ANALYTICS": "0 disables the key-analytics subsystem (sketch + phase ledger)",
    "GUBER_BATCH_LIMIT": "max requests per peer-forward batch",
    "GUBER_BATCH_ROWS": "device batch rows per shard (B)",
    "GUBER_BATCH_TIMEOUT": "peer-forward batch RPC timeout (duration)",
    "GUBER_BATCH_WAIT": "peer-forward batch coalescing wait (duration)",
    "GUBER_BENCH_B": "bench: device-batch size override",
    "GUBER_BENCH_CAP": "bench: table capacity override",
    "GUBER_BENCH_EXPECT_BACKEND": "bench: fail unless jax backend matches",
    "GUBER_BENCH_FAST": "bench: fast mode (fewer reps, smaller shapes)",
    "GUBER_BENCH_INNER": "bench: marks the re-exec'd child process",
    "GUBER_BENCH_KEYS": "bench: key-cardinality override",
    "GUBER_BENCH_NO_PALLAS": "bench: skip Pallas sections",
    "GUBER_BENCH_PARTIAL": "bench: emit partial BENCH row on timeout salvage",
    "GUBER_BENCH_SCAN": "bench: occupancy-scan section toggle",
    "GUBER_BENCH_SECTION": "bench: run only this section",
    "GUBER_BENCH_SECTION_OUT": "bench: per-section checkpoint JSON path",
    "GUBER_BENCH_SECTION_TIMEOUT": "bench: per-section timeout seconds",
    "GUBER_BENCH_SKIP_FILE": "bench: file listing sections to skip",
    "GUBER_BENCH_SKIP_GROUP": "bench: skip the group-spread check",
    "GUBER_BENCH_STEP_MODE": "bench: step-impl mode for the step sections",
    "GUBER_BENCH_TIMEOUT": "bench: whole-run watchdog seconds",
    "GUBER_CACHE_AUTOGROW_MAX": "auto-grow ceiling in TOTAL table rows; 0 disables",
    "GUBER_CACHE_SIZE": "table capacity per shard",
    "GUBER_CAP_AB_ANY_BACKEND": "tools/cap_ab: allow non-TPU backends",
    "GUBER_CLIENT_ADDRESS": "HTTP client-facing listen address",
    "GUBER_COALESCE_US": "dispatcher coalescing window in µs (0 disables the wait)",
    "GUBER_COMPILE_LEDGER": "0 disables the runtime jit-compile ledger (compileledger.py): per-fn XLA compile counts, gubernator_jit_compiles, the steady-state recompile verdict",
    "GUBER_CREATED_AT_FWD": "0 disables caller-clock forwarding (created_at stamp) — pre-fix cold-key-loss demo ONLY",
    "GUBER_DATA_CENTER": "data-center name for DC-aware picking",
    "GUBER_DEBUG_DUMP_DIR": "crash forensics: close() dumps the event ring + final SLO verdicts here as JSONL",
    "GUBER_DNS_FQDN": "DNS discovery: FQDN to resolve for peers",
    "GUBER_DNS_RESOLVE_INTERVAL": "DNS discovery: re-resolve interval (duration)",
    "GUBER_DRAIN_GRACE": "graceful-shutdown drain budget (duration); bounds every drain join",
    "GUBER_ENGINE": "serving engine: auto (default; fused pallas on TPU, classic xla elsewhere), pallas (fused everywhere — compiled XLA flavor off-TPU), xla/sharded (classic)",
    "GUBER_ETCD_ENDPOINTS": "etcd discovery: comma-separated endpoints",
    "GUBER_ETCD_PREFIX": "etcd discovery: key prefix for peer registration",
    "GUBER_EXTRAS_SMOKE": "tools/tpu_session: run the extras smoke block",
    "GUBER_FAULT": "fault-injection spec point[@tag]:mode[:arg[:prob]],... (faults.py)",
    "GUBER_FAULT_SEED": "fault-injection RNG seed for bit-for-bit chaos replay",
    "GUBER_FLEET_AUDIT": "conservation auditor on the GLOBAL lanes: 0 disables the audit taps + /debug/audit drift (default on)",
    "GUBER_FLEET_DRIFT_BOUND": "conservation drift staleness bound (duration) before the fleet_conservation SLO burns; default 2x GUBER_GLOBAL_SYNC_WAIT",
    "GUBER_GLOBAL_BATCH_LIMIT": "GLOBAL hit-flush batch limit",
    "GUBER_GLOBAL_BROADCAST_INTERVAL": "GLOBAL owner-broadcast tick interval (duration)",
    "GUBER_GLOBAL_MODE": "GLOBAL reconcile backend: grpc (default) or mesh (pod-local collective fold)",
    "GUBER_GLOBAL_SYNC_WAIT": "GLOBAL hit-flush coalescing wait (duration)",
    "GUBER_GLOBAL_TIMEOUT": "GLOBAL flush RPC timeout (duration)",
    "GUBER_GRPC_ADDRESS": "gRPC listen address",
    "GUBER_HANDOVER_ON_RESHARD": "stream moved rows to new owners on SetPeers",
    "GUBER_HTTP_ADDRESS": "HTTP (metrics/debug) listen address",
    "GUBER_INSTANCE_ID": "stable instance id (defaults to advertise address)",
    "GUBER_JAX_PLATFORM": "force the jax platform (cpu/tpu) before first import",
    "GUBER_K8S_INSECURE": "k8s discovery: skip API-server cert verification",
    "GUBER_K8S_NAMESPACE": "k8s discovery: namespace to watch",
    "GUBER_K8S_POD_SELECTOR": "k8s discovery: pod label selector",
    "GUBER_K8S_SERVICE": "k8s discovery: service name whose endpoints are peers",
    "GUBER_KSPLIT": "device step: probe K-split override (core/step.py)",
    "GUBER_LOG_LEVEL": "root log level",
    "GUBER_MEMBERLIST_KNOWN_HOSTS": "memberlist discovery: seed hosts",
    "GUBER_MEM_ADVISE_FLOOR": "memory ledger: per-consumer minimum rows in the advised split (default 64)",
    "GUBER_MEM_LEDGER": "0 disables the device-memory ledger plane (default 1)",
    "GUBER_MEM_PRESSURE": "hbm_pressure SLO target: byte-weighted occupancy fraction (default 0.85)",
    "GUBER_MESH_FALLBACK_AFTER": "consecutive mesh-GLOBAL fold failures before the tier stands down to the gRPC path",
    "GUBER_MESH_GLOBAL_CAP": "mesh-GLOBAL replica table capacity (keys; power of two)",
    "GUBER_MULTI_REGION_BATCH_LIMIT": "cross-region replication batch limit",
    "GUBER_MULTI_REGION_SYNC_WAIT": "cross-region flush coalescing wait (duration)",
    "GUBER_MULTI_REGION_TIMEOUT": "cross-region flush RPC timeout (duration)",
    "GUBER_NATIVE_SAN": "setup_native.py: build _native under tsan/asan (make tsan / make asan)",
    "GUBER_PALLAS_PROBE_OUT": "tools/pallas_probe: checkpoint JSON path",
    "GUBER_PALLAS_TILE": "Mosaic kernel block shape: requests per grid step (8-4096, default 128)",
    "GUBER_PALLAS_SWEEP": "1/0 force the fused Pallas sweep on/off (default: TPU only)",
    "GUBER_PEERS": "static peer list (host:port,... ) for static discovery",
    "GUBER_PEERS_FILE": "file-based discovery: path to the peer list",
    "GUBER_PEER_DEGRADED_FALLBACK": "0 restores legacy error rows instead of degraded serves",
    "GUBER_PEER_DISCOVERY_TYPE": "peer discovery backend (static/file/dns/etcd/k8s/memberlist)",
    "GUBER_PEER_EJECT_AFTER": "circuit-open streak before ring ejection (duration)",
    "GUBER_PEER_HEALTH_GATE": "0 disables the health-gated routing ring",
    "GUBER_PEER_READMIT_AFTER": "recovered time before an ejected peer readmits (duration)",
    "GUBER_PIPELINE": "1/0 force the launch/sync wave pipeline on/off (default: TPU only)",
    "GUBER_PIPELINE_DEPTH": "in-flight launched waves in the pipeline (min 1)",
    "GUBER_PROBES": "device step: open-addressing probe count (core/step.py)",
    "GUBER_PROFILE_DIR": "on-demand device-profiler capture directory",
    "GUBER_RESULT_TIMEOUT_S": "caller wave-result timeout seconds (finite, > 0)",
    "GUBER_SCENARIO_DIR": "scenario-lab spec library directory (default scenarios/)",
    "GUBER_SCENARIO_FAST": "1 forces fast mode in every scenario-lab entry point",
    "GUBER_SCENARIO_SEED": "overrides every scenario spec's seed (sweep knob)",
    "GUBER_SESSION_BENCH_TIMEOUT": "tools/tpu_session: bench stage timeout seconds",
    "GUBER_SESSION_EXTRAS_OUT": "tools/tpu_session: extras checkpoint JSON path",
    "GUBER_SKETCH_WIDTH": "heavy-hitter sketch counter width (default 4×TOPK)",
    "GUBER_SLO": "0 disables the in-process SLO burn-rate engine",
    "GUBER_SLO_BURN": "burn-rate breach threshold (multiple of the error-budget spend rate, default 2.0)",
    "GUBER_SLO_FAST": "SLO fast burn window (duration, default 1m)",
    "GUBER_SLO_P99_MS": "decision_p99 SLO target: device-phase p99 ms (default 250)",
    "GUBER_SLO_SLOW": "SLO slow burn window (duration, default 5m)",
    "GUBER_SLO_TICK": "SLO engine evaluation interval (duration, default 1s)",
    "GUBER_SNAPSHOT_PATH": "Loader snapshot path (save on close, load on start)",
    "GUBER_STALL_THRESHOLD_S": "wave stall-watchdog threshold seconds; <=0 disables",
    "GUBER_STEP_DONATE": "0 disables donated (aliased) step buffers",
    "GUBER_STEP_IMPL": "device step implementation (xla/pallas)",
    "GUBER_TENANT_DELIM": "tenant id = key-name prefix up to this delimiter (default /)",
    "GUBER_TENANT_MAX": "max distinct tenant buckets; overflow folds into __other__ (default 64)",
    "GUBER_TIER_COLD": "1 enables the host cold tier behind the device table",
    "GUBER_TIER_NATIVE": "0 forces the pure-python cold-store fallback",
    "GUBER_TIER_PROMOTE": "sketch-rank admission threshold for cold->hot promotion",
    "GUBER_TLS_AUTO": "generate a self-signed TLS setup at startup",
    "GUBER_TLS_CA": "TLS CA bundle path",
    "GUBER_TLS_CERT": "TLS server certificate path",
    "GUBER_TLS_CLIENT_AUTH": "TLS client-auth mode",
    "GUBER_TLS_CLIENT_AUTH_CA_CERT": "TLS client-auth CA path",
    "GUBER_TLS_INSECURE_SKIP_VERIFY": "peer clients skip TLS verification",
    "GUBER_TLS_KEY": "TLS server key path",
    "GUBER_TOPK": "heavy-hitter sketch tracked-key count K",
    "GUBER_TRACE_SAMPLE": "head-sampling rate for the trace plane (0 disables)",
    "GUBER_TRACE_SPANS": "span-recorder ring capacity (completed spans kept)",
    "GUBER_WAVE_BUCKETS": "comma-separated wave-size buckets for check_packed",
    "GUBER_XLA_CPU_TUNE": "0 skips the XLA:CPU thunk-runtime opt-out at import",
}

_DUR_RE = re.compile(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h)")
_DUR_UNIT_MS = {"ns": 1e-6, "us": 1e-3, "µs": 1e-3, "ms": 1.0,
                "s": 1000.0, "m": 60_000.0, "h": 3_600_000.0}


def parse_duration_ms(s: str | int | float) -> int:
    """Go-style duration string → integer milliseconds.

    Accepts bare numbers (already ms) and compound strings ("1m30s").
    Mirrors the reference's use of time.ParseDuration in config loading.
    """
    if isinstance(s, (int, float)):
        return int(s)
    s = s.strip()
    if not s:
        return 0
    if re.fullmatch(r"-?\d+", s):
        return int(s)
    total = 0.0
    pos = 0
    neg = s.startswith("-")
    if neg:
        pos = 1
    for m in _DUR_RE.finditer(s, pos):
        if m.start() != pos:
            raise ValueError(f"invalid duration: {s!r}")
        total += float(m.group(1)) * _DUR_UNIT_MS[m.group(2)]
        pos = m.end()
    if pos != len(s):
        raise ValueError(f"invalid duration: {s!r}")
    return int(-total if neg else total)


@dataclass
class BehaviorConfig:
    """Batch/global/multi-region timing knobs.

    reference: config.go › BehaviorConfig (same field names, ms integers
    instead of time.Duration).
    """

    #: How long to wait for more requests before flushing a peer batch.
    batch_timeout_ms: int = 500
    #: Time the owner waits to accumulate forwarded batches.
    batch_wait_ms: int = 500
    #: Max requests in one forwarded peer batch (reference default 1000).
    batch_limit: int = 1000

    #: How long to accumulate GLOBAL hit deltas before syncing to owner.
    global_sync_wait_ms: int = 100
    #: Deadline for global sync RPCs.
    global_timeout_ms: int = 500
    #: Max global hits per sync batch.
    global_batch_limit: int = 1000
    #: Interval between owner broadcasts of updated GLOBAL state.
    global_broadcast_interval_ms: int = 100

    #: Multi-region analogs (SURVEY.md §2.1 mutliregion.go).
    multi_region_sync_wait_ms: int = 300
    multi_region_timeout_ms: int = 900
    multi_region_batch_limit: int = 1000

    #: Columnar peer send lanes (peer_client.py › _SendLane): depth-K
    #: in-flight RPCs per peer per method — the forward hop's analog of
    #: the dispatcher's overlapped wave pipeline.
    peer_inflight: int = 4
    #: Send-buffer coalescing window (µs): how long a flush waits for
    #: straggler entries after draining the backlog — mirrors the
    #: dispatcher's GUBER_COALESCE_US rule (greedy backlog first, never
    #: overshoot the batch limit, tiny straggler window).
    peer_coalesce_us: int = 200
    #: Re-send attempts for a failed flush RPC before its requests get
    #: error responses (each retry backs off linearly).
    peer_retry_limit: int = 2
    peer_retry_backoff_ms: int = 25
    #: Consecutive flush failures (after retries) that OPEN the peer's
    #: circuit: sends fail fast instead of queuing behind a dead peer
    #: until the cooldown elapses (then one probe flush half-opens it).
    peer_circuit_threshold: int = 3
    peer_circuit_cooldown_ms: int = 2000

    #: Failure-domain resilience (ISSUE 5).  When a forward fails (RPC
    #: error after retries, or a circuit-open fail-fast), answer the
    #: row from the LOCAL shard with a DEGRADED response flag and
    #: reconcile the hits to the owner through the GLOBAL hit-flush
    #: queues — bounded staleness instead of per-request error rows.
    #: Rows with state-mutating flags (RESET_REMAINING /
    #: DRAIN_OVER_LIMIT) are never served degraded.
    peer_degraded_fallback: bool = True
    #: Health-gated routing ring: a peer whose circuit has been open
    #: continuously for peer_eject_after_ms is EJECTED from the routing
    #: ring (its keys deterministically rehome to the next ring point);
    #: it returns only after staying recovered for
    #: peer_readmit_after_ms (hysteresis against flapping).  False
    #: keeps the membership ring authoritative for routing.
    peer_health_gate: bool = True
    peer_eject_after_ms: int = 3000
    peer_readmit_after_ms: int = 3000


@dataclass
class Config:
    """Core-instance configuration.

    reference: config.go › Config (fields the TPU design keeps; cache
    workers/locks are replaced by the device table, SURVEY.md §7.1).
    """

    #: Rows in the device counter table (power of two).  The analog of
    #: the reference's CacheSize (default 50 000 → rounded up to 2^16).
    cache_size: int = 1 << 16
    #: Device batch rows per shard per step.
    batch_rows: int = 1024
    #: Upper bound (total rows) for on-device capacity auto-grow when
    #: the table fills with LIVE keys (0 disables; the reference's LRU
    #: never fails an insert, so enabling this matches that contract up
    #: to the bound).  Rounded to a power of two per shard.
    cache_autogrow_max: int = 0
    #: Stateful re-sharding (beyond-reference, opt-in): on membership
    #: change, rows whose ring owner moved are handed to the new owner
    #: over the peer wire instead of resetting (the reference loses
    #: re-homed state — SURVEY.md §5.3).  Requires the default picker
    #: hash (mixed fnv1a64).
    handover_on_reshard: bool = False
    behaviors: BehaviorConfig = field(default_factory=BehaviorConfig)
    #: This node's datacenter name (multi-region routing).
    data_center: str = ""
    #: Optional persistence hooks (store.py); any object implementing
    #: the Loader / Store protocols.
    loader: Optional[object] = None
    store: Optional[object] = None
    #: Seconds between expired-row sweeps (0 disables).
    sweep_interval_ms: int = 30_000
    #: Decision-step implementation: "xla" (default — unbounded values,
    #: auto-grow) or "pallas" (the hand-scheduled Mosaic kernel as the
    #: serving mode: lowering-independent throughput floor, bucketized
    #: table; counters must be < 2^30 and leaky eff < 2^31, no
    #: auto-grow — parallel/pallas_engine.py).  GUBER_STEP_IMPL
    #: overrides.
    step_impl: str = ""
    #: Serving-engine selector (ISSUE 8; GUBER_ENGINE overrides):
    #: "auto" (default) = the fused Pallas engine on TPU, the classic
    #: XLA sharded engine elsewhere; "pallas" = fused serving
    #: everywhere (off-TPU: the compiled XLA fused flavor — one fused
    #: program per wave with on-device tap + mesh scatter, small-shape
    #: wave buckets); "xla"/"sharded" = the classic engine explicitly.
    #: Construction failures fall back LOUDLY to the classic engine
    #: (engine_fallback event) — availability beats mode fidelity.
    engine: str = ""
    #: GLOBAL reconcile backend (ISSUE 7): "" / "grpc" keeps the
    #: reference's hit-queue + broadcast machinery; "mesh" serves
    #: pod-local GLOBAL keys from the mesh-resident replica tier
    #: (parallel/meshglobal.py) and reconciles them with ONE collective
    #: fold per tick — no gRPC peer fan-out.  Cross-pod owners and the
    #: degraded fallback keep the gRPC lanes either way.
    #: GUBER_GLOBAL_MODE overrides.
    global_mode: str = ""
    #: Replicated hot-set capacity for GLOBAL keys (0 disables the psum
    #: tier; see parallel/hotset.py).  Active only for pod-local
    #: deployments (no cross-host peers).
    hot_set_capacity: int = 1024
    #: GLOBAL hits on one key before it is promoted to the hot set.
    hot_promote_threshold: int = 64
    #: Host cold tier behind the device table (ISSUE 10): a key that
    #: misses (or overflows) the HBM-resident table is served EXACTLY
    #: from host memory instead of erroring table_full, and migrates to
    #: HBM only once its sketch rank clears tier_promote_threshold —
    #: key cardinality scales far past the device cap while the hot
    #: tier stays wave-sized.  GUBER_TIER_COLD overrides.
    tier_cold: bool = False
    #: Sketch-rank admission threshold for cold→hot promotion (see
    #: tiering.py).  GUBER_TIER_PROMOTE overrides.
    tier_promote_threshold: int = 8
    #: Local peer identity (set by the daemon).
    advertise_address: str = ""

    def set_defaults(self) -> "Config":
        """Normalize invalid values, like config.go › SetDefaults."""
        if self.cache_size <= 0:
            self.cache_size = 1 << 16
        # round up to a power of two (device probe masking requires it)
        self.cache_size = 1 << (self.cache_size - 1).bit_length()
        if self.batch_rows <= 0:
            self.batch_rows = 1024
        return self


@dataclass
class TLSSettings:
    """reference: tls.go › TLSConfig (declarative part)."""

    ca_file: str = ""
    cert_file: str = ""
    key_file: str = ""
    #: Generate a self-signed server certificate in memory.
    auto_tls: bool = False
    #: "none" | "request" | "require-any" | "verify" (client certs).
    client_auth: str = "none"
    client_auth_ca_file: str = ""
    insecure_skip_verify: bool = False


@dataclass
class DaemonConfig:
    """Everything needed to spawn a daemon.

    reference: config.go › DaemonConfig + SetupDaemonConfig env names
    (GUBER_* — reconstructed).
    """

    grpc_listen_address: str = "localhost:1051"
    http_listen_address: str = "localhost:1050"
    #: Optional SHARED client-facing gRPC address bound with SO_REUSEPORT.
    #: Several daemon processes on one host can bind the same
    #: client_listen_address; the kernel load-balances inbound client
    #: connections across them while each process keeps its unique
    #: grpc_listen_address for peer traffic.  This is the front-door
    #: scaling story for a GIL-bound host: N ingest processes share the
    #: port, ring-split batches, and forward over the peer wire lane.
    #: "" (default) disables the extra listener.
    client_listen_address: str = ""
    advertise_address: str = ""
    cache_size: int = 1 << 16
    cache_autogrow_max: int = 0
    #: Device wave rows per shard (Config.batch_rows).
    batch_rows: int = 1024
    handover_on_reshard: bool = False
    data_center: str = ""
    instance_id: str = ""
    behaviors: BehaviorConfig = field(default_factory=BehaviorConfig)
    tls: Optional[TLSSettings] = None
    log_level: str = "info"

    #: "none" | "static" | "file" | "dns" | "etcd" | "k8s" | "member-list"
    peer_discovery_type: str = "none"
    #: static discovery: explicit peer list.
    static_peers: List[str] = field(default_factory=list)
    #: file discovery: path to a JSON/lines peers file, re-read on change.
    peers_file: str = ""
    #: dns discovery.
    dns_fqdn: str = ""
    dns_resolve_interval_ms: int = 30_000
    #: etcd / k8s / member-list endpoints (gated: stub unless client
    #: libraries are installed — SURVEY.md §2.1 discovery rows).
    etcd_endpoints: List[str] = field(default_factory=list)
    etcd_prefix: str = "/gubernator/peers/"
    k8s_namespace: str = ""
    k8s_pod_selector: str = ""
    k8s_service: str = ""
    #: Explicit opt-out of API-server cert verification (GUBER_K8S_INSECURE).
    k8s_insecure_skip_verify: bool = False
    memberlist_known_hosts: List[str] = field(default_factory=list)

    #: Graceful-shutdown drain window (ms): Daemon.close reports
    #: "draining" on /healthz (503) for this long before stopping the
    #: listeners, so load balancers stop routing first.  0 skips the
    #: wait (the drain events still fire).
    drain_grace_ms: int = 0
    #: Path for Loader snapshots ("" disables checkpoint/resume).
    snapshot_path: str = ""
    #: Decision-step implementation ("" → "xla"; "pallas" = the Mosaic
    #: kernel serving mode — Config.step_impl).
    step_impl: str = ""
    #: Serving-engine selector ("" → "auto" — Config.engine).
    engine: str = ""
    #: GLOBAL reconcile backend ("" → "grpc"; "mesh" = pod-local
    #: collective fold — Config.global_mode).
    global_mode: str = ""

    def instance_config(self) -> Config:
        return Config(
            cache_size=self.cache_size,
            cache_autogrow_max=self.cache_autogrow_max,
            batch_rows=self.batch_rows,
            step_impl=self.step_impl,
            engine=self.engine,
            global_mode=self.global_mode,
            handover_on_reshard=self.handover_on_reshard,
            behaviors=self.behaviors,
            data_center=self.data_center,
            advertise_address=self.advertise_address or self.grpc_listen_address,
        ).set_defaults()


_MISSING = object()


class _Src:
    """One layered config source: conf-file dict then environment."""

    def __init__(self, conf: Dict[str, str]):
        self.conf = conf

    def get(self, name: str, default=_MISSING, cast: Callable = str):
        v = os.environ.get(name, _MISSING)
        if v is _MISSING:
            v = self.conf.get(name, _MISSING)
        if v is _MISSING:
            if default is _MISSING:
                return None
            return default
        if cast is bool:
            return str(v).strip().lower() in ("1", "true", "yes", "on")
        return cast(v)


def load_conf_file(path: str) -> Dict[str, str]:
    """Parse a `KEY=value` config file (reference example.conf format):
    blank lines and #-comments ignored."""
    out: Dict[str, str] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if "=" not in line:
                raise ValueError(f"invalid config line (want KEY=value): {line!r}")
            k, _, v = line.partition("=")
            out[k.strip()] = v.strip()
    return out


def setup_daemon_config(conf_file: str = "",
                        env: Optional[Dict[str, str]] = None) -> DaemonConfig:
    """Build a DaemonConfig from defaults < config file < environment.

    reference: config.go › SetupDaemonConfig.  ``env`` overrides
    os.environ for tests.
    """
    conf = load_conf_file(conf_file) if conf_file else {}
    if env is not None:
        conf = {**conf, **env}
        src = _Src(conf)
        # env-dict mode: don't consult os.environ (hermetic tests)
        src.get = lambda name, default=_MISSING, cast=str: (  # type: ignore
            (default if default is not _MISSING else None)
            if conf.get(name, _MISSING) is _MISSING
            else (str(conf[name]).strip().lower() in ("1", "true", "yes", "on")
                  if cast is bool else cast(conf[name])))
    else:
        src = _Src(conf)

    d = DaemonConfig()
    d.grpc_listen_address = src.get("GUBER_GRPC_ADDRESS", d.grpc_listen_address)
    d.http_listen_address = src.get("GUBER_HTTP_ADDRESS", d.http_listen_address)
    d.client_listen_address = src.get("GUBER_CLIENT_ADDRESS",
                                      d.client_listen_address)
    d.advertise_address = src.get("GUBER_ADVERTISE_ADDRESS", d.advertise_address)
    d.cache_size = src.get("GUBER_CACHE_SIZE", d.cache_size, int)
    d.batch_rows = src.get("GUBER_BATCH_ROWS", d.batch_rows, int)
    d.cache_autogrow_max = src.get("GUBER_CACHE_AUTOGROW_MAX",
                                   d.cache_autogrow_max, int)
    d.handover_on_reshard = src.get("GUBER_HANDOVER_ON_RESHARD",
                                    d.handover_on_reshard, bool)
    d.data_center = src.get("GUBER_DATA_CENTER", d.data_center)
    d.instance_id = src.get("GUBER_INSTANCE_ID", d.instance_id)
    d.log_level = src.get("GUBER_LOG_LEVEL", d.log_level)
    d.snapshot_path = src.get("GUBER_SNAPSHOT_PATH", d.snapshot_path)
    d.step_impl = src.get("GUBER_STEP_IMPL", d.step_impl)
    d.engine = src.get("GUBER_ENGINE", d.engine)
    d.global_mode = src.get("GUBER_GLOBAL_MODE", d.global_mode)

    b = d.behaviors
    b.batch_timeout_ms = src.get("GUBER_BATCH_TIMEOUT", b.batch_timeout_ms,
                                 parse_duration_ms)
    b.batch_wait_ms = src.get("GUBER_BATCH_WAIT", b.batch_wait_ms,
                              parse_duration_ms)
    b.batch_limit = src.get("GUBER_BATCH_LIMIT", b.batch_limit, int)
    b.global_sync_wait_ms = src.get("GUBER_GLOBAL_SYNC_WAIT",
                                    b.global_sync_wait_ms, parse_duration_ms)
    b.global_timeout_ms = src.get("GUBER_GLOBAL_TIMEOUT", b.global_timeout_ms,
                                  parse_duration_ms)
    b.global_batch_limit = src.get("GUBER_GLOBAL_BATCH_LIMIT",
                                   b.global_batch_limit, int)
    b.global_broadcast_interval_ms = src.get(
        "GUBER_GLOBAL_BROADCAST_INTERVAL", b.global_broadcast_interval_ms,
        parse_duration_ms)
    b.multi_region_sync_wait_ms = src.get(
        "GUBER_MULTI_REGION_SYNC_WAIT", b.multi_region_sync_wait_ms,
        parse_duration_ms)
    b.multi_region_timeout_ms = src.get(
        "GUBER_MULTI_REGION_TIMEOUT", b.multi_region_timeout_ms,
        parse_duration_ms)
    b.multi_region_batch_limit = src.get(
        "GUBER_MULTI_REGION_BATCH_LIMIT", b.multi_region_batch_limit, int)
    b.peer_degraded_fallback = src.get("GUBER_PEER_DEGRADED_FALLBACK",
                                       b.peer_degraded_fallback, bool)
    b.peer_health_gate = src.get("GUBER_PEER_HEALTH_GATE",
                                 b.peer_health_gate, bool)
    b.peer_eject_after_ms = src.get("GUBER_PEER_EJECT_AFTER",
                                    b.peer_eject_after_ms,
                                    parse_duration_ms)
    b.peer_readmit_after_ms = src.get("GUBER_PEER_READMIT_AFTER",
                                      b.peer_readmit_after_ms,
                                      parse_duration_ms)
    d.drain_grace_ms = src.get("GUBER_DRAIN_GRACE", d.drain_grace_ms,
                               parse_duration_ms)

    d.peer_discovery_type = src.get("GUBER_PEER_DISCOVERY_TYPE",
                                    d.peer_discovery_type)
    peers = src.get("GUBER_PEERS", "")
    if peers:
        d.static_peers = [p.strip() for p in peers.split(",") if p.strip()]
        if d.peer_discovery_type == "none":
            d.peer_discovery_type = "static"
    d.peers_file = src.get("GUBER_PEERS_FILE", d.peers_file)
    d.dns_fqdn = src.get("GUBER_DNS_FQDN", d.dns_fqdn)
    d.dns_resolve_interval_ms = src.get("GUBER_DNS_RESOLVE_INTERVAL",
                                        d.dns_resolve_interval_ms,
                                        parse_duration_ms)
    etcd = src.get("GUBER_ETCD_ENDPOINTS", "")
    if etcd:
        d.etcd_endpoints = [p.strip() for p in etcd.split(",") if p.strip()]
    d.etcd_prefix = src.get("GUBER_ETCD_PREFIX", d.etcd_prefix)
    d.k8s_namespace = src.get("GUBER_K8S_NAMESPACE", d.k8s_namespace)
    d.k8s_pod_selector = src.get("GUBER_K8S_POD_SELECTOR", d.k8s_pod_selector)
    d.k8s_service = src.get("GUBER_K8S_SERVICE", d.k8s_service)
    d.k8s_insecure_skip_verify = src.get("GUBER_K8S_INSECURE",
                                         d.k8s_insecure_skip_verify, bool)
    ml = src.get("GUBER_MEMBERLIST_KNOWN_HOSTS", "")
    if ml:
        d.memberlist_known_hosts = [p.strip() for p in ml.split(",") if p.strip()]

    if (src.get("GUBER_TLS_AUTO", False, bool)
            or src.get("GUBER_TLS_CERT", "") or src.get("GUBER_TLS_CA", "")):
        d.tls = TLSSettings(
            ca_file=src.get("GUBER_TLS_CA", ""),
            cert_file=src.get("GUBER_TLS_CERT", ""),
            key_file=src.get("GUBER_TLS_KEY", ""),
            auto_tls=src.get("GUBER_TLS_AUTO", False, bool),
            client_auth=src.get("GUBER_TLS_CLIENT_AUTH", "none"),
            client_auth_ca_file=src.get("GUBER_TLS_CLIENT_AUTH_CA_CERT", ""),
            insecure_skip_verify=src.get("GUBER_TLS_INSECURE_SKIP_VERIFY",
                                         False, bool),
        )
    return d


def parse_peer_list(specs: List[str], default_dc: str = "") -> List[PeerInfo]:
    """"host:grpc_port[;host:http_port][@dc]" strings → PeerInfo list."""
    out = []
    for s in specs:
        dc = default_dc
        if "@" in s:
            s, _, dc = s.partition("@")
        grpc_addr, _, http_addr = s.partition(";")
        out.append(PeerInfo(grpc_address=grpc_addr.strip(),
                            http_address=http_addr.strip(),
                            datacenter=dc.strip()))
    return out

"""MULTI_REGION behavior: async cross-datacenter hit replication.

reference: mutliregion.go (upstream's actual spelling) ›
mutliRegionManager{runAsyncReqs} + region_picker.go — reconstructed,
mount empty.

Requests flagged MULTI_REGION are served by the local region immediately
(local-region consistent hash picks the owner as usual); the local owner
then queues the hits here, and every ``multi_region_sync_wait`` tick the
aggregated hits are pushed to the same key's owner in every OTHER
region, keeping regional counters eventually consistent.  The flag is
stripped from the cross-region copy so hits don't ping-pong between
regions.

On TPU pods, each region is one pod; this manager is the DCN/host-gRPC
bridge tier of SURVEY.md §5.8 (intra-pod sync is the ICI psum path).
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Tuple

from .config import BehaviorConfig
from .interval import IntervalLoop
from .types import Behavior, RateLimitRequest

log = logging.getLogger("gubernator_tpu.multiregion")


class MultiRegionManager:
    #: Same health semantics as GlobalManager: a sync error only marks
    #: the daemon unhealthy for this long after the LAST failure (the
    #: loop retries every tick; a stale error must not fail readiness
    #: probes forever).
    ERROR_TTL_S = 60.0

    def __init__(self, instance, behaviors: BehaviorConfig):
        self.instance = instance
        self.behaviors = behaviors
        self._mu = threading.Lock()
        #: cross-lane arrival order (under _mu) — the prototype with the
        #: highest seq wins the flush-time merge (latest config wins
        #: across the object and wire lanes, as in GlobalManager)
        self._seq = 0
        #: key → (request prototype, accumulated hits, seq)
        self._hits: Dict[str, Tuple[RateLimitRequest, int, int]] = {}
        #: key-hash → (request TLV bytes, accumulated hits, seq) — the
        #: columnar wire lanes queue raw `requests` TLV slices;
        #: materialized via wire.req_from_tlv at flush cadence
        self._hits_raw: Dict[int, Tuple[bytes, int, int]] = {}
        self._err_mu = threading.Lock()
        self._last_error = ""
        self._last_error_at = 0.0
        self._loop = IntervalLoop(behaviors.multi_region_sync_wait_ms,
                                  self._run_async_reqs,
                                  name="multi-region-sync")

    @property
    def last_error(self) -> str:
        with self._err_mu:
            if (self._last_error and
                    time.monotonic() - self._last_error_at > self.ERROR_TTL_S):
                return ""
            return self._last_error

    def _record(self, errors) -> None:
        with self._err_mu:
            if errors:
                self._last_error = "; ".join(errors)
                self._last_error_at = time.monotonic()
            else:
                self._last_error = ""

    def queue_hits(self, req: RateLimitRequest) -> None:
        """reference: mutliregion.go › QueueHits."""
        with self._mu:
            self._seq += 1
            _, acc, _ = self._hits.get(req.key, (req, 0, 0))
            self._hits[req.key] = (req, acc + max(int(req.hits), 0),
                                   self._seq)
            n = len(self._hits) + len(self._hits_raw)
        if n >= self.behaviors.multi_region_batch_limit:
            self._loop.poke()

    def queue_hits_raw(self, khash: int, tlv: bytes, hits: int) -> None:
        """Wire-lane twin of ``queue_hits``: raw TLV prototype +
        aggregated hits per unique key, no per-request objects.  A
        hits=0 entry still refreshes the prototype — queue_hits stores
        the latest req unconditionally, and a query carrying a config
        change must win the flush-time merge the same way."""
        with self._mu:
            self._seq += 1
            _, acc, _ = self._hits_raw.get(khash, (tlv, 0, 0))
            self._hits_raw[khash] = (tlv, acc + max(hits, 0), self._seq)
            n = len(self._hits) + len(self._hits_raw)
        if n >= self.behaviors.multi_region_batch_limit:
            self._loop.poke()

    def _fault_tick(self) -> bool:
        """Chaos hook (ISSUE 7 satellite: multiregion reconciliation
        had zero fault coverage): True aborts this tick BEFORE the
        queues are popped, so an injected failure loses nothing — the
        aggregates flush on the next clean tick (conservation holds,
        asserted by the chaos cell)."""
        f = getattr(self.instance, "faults", None)
        if f is None or not f.armed:
            return False
        try:
            f.fire("mr_sync")
        except Exception as e:  # noqa: BLE001 - incl. FaultInjected
            msg = f"multi-region sync tick: {e!r}"
            log.warning(msg)
            self._record([msg])
            return True
        return False

    def _run_async_reqs(self) -> None:
        """Push aggregated hits to each other region's key owner.
        reference: mutliregion.go › runAsyncReqs."""
        if self._fault_tick():
            return
        with self._mu:
            hits, self._hits = self._hits, {}
            hits_raw, self._hits_raw = self._hits_raw, {}
        from .wire import req_from_tlv

        for khash, (tlv, acc, seq) in hits_raw.items():
            try:
                req = req_from_tlv(tlv)
            except Exception:  # noqa: BLE001 - parser-bug guard
                log.warning("dropping unparseable queued TLV for key "
                            "hash %d", khash)
                continue
            proto, a0, s0 = hits.get(req.key, (req, 0, seq))
            hits[req.key] = (req if seq >= s0 else proto, a0 + acc,
                             max(s0, seq))
        if not hits:
            return  # no attempts: leave the error state as-is (TTL expires it)
        local_dc = self.instance.config.data_center
        regions = self.instance.region_pickers()
        errors = []
        for dc, picker in regions.items():
            if dc == local_dc:
                continue
            by_peer: Dict[str, Tuple[object, list]] = {}
            for key, (req, acc, _seq) in hits.items():
                if acc <= 0:
                    continue
                try:
                    peer = picker.get(key)
                except RuntimeError:
                    continue  # region has no peers right now
                copy = RateLimitRequest(
                    name=req.name, unique_key=req.unique_key, hits=acc,
                    limit=req.limit, duration=req.duration,
                    algorithm=req.algorithm,
                    # strip MULTI_REGION: the receiving region must not
                    # re-replicate (infinite ping-pong / double count)
                    behavior=Behavior(int(req.behavior)
                                      & ~int(Behavior.MULTI_REGION)),
                    burst=req.burst)
                by_peer.setdefault(peer.info.grpc_address,
                                   (peer, []))[1].append(copy)
            for addr, (peer, reqs) in by_peer.items():
                try:
                    limit = self.behaviors.multi_region_batch_limit
                    for i in range(0, len(reqs), limit):
                        peer.get_peer_rate_limits(
                            reqs[i:i + limit],
                            timeout_s=self.behaviors.multi_region_timeout_ms
                            / 1000.0)
                except Exception as e:  # noqa: BLE001 - retried next tick
                    errors.append(f"multi-region sync {dc}/{addr}: {e}")
                    log.warning(errors[-1])
        self._record(errors)

    def poke(self) -> None:
        self._loop.poke()

    def close(self) -> None:
        self._loop.close()

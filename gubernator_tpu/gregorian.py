"""Calendar-period expiry for DURATION_IS_GREGORIAN.

Host-side only: the device compares integer millisecond timestamps, the
host does calendars (SURVEY.md §7.3).  Mirrors the behavior of the
reference's holster gregorian helpers (algorithms.go › tokenBucket's
GregorianExpiration call — reconstructed): the bucket expires at the END
of the current calendar period in UTC, so every key resets at the period
boundary.
"""
from __future__ import annotations

import calendar
import datetime as _dt

from .types import GREGORIAN_APPROX_MS, GregorianDuration

_UTC = _dt.timezone.utc


def _from_ms(ms: int) -> _dt.datetime:
    return _dt.datetime.fromtimestamp(ms / 1000.0, tz=_UTC)


def _to_ms(dt: _dt.datetime) -> int:
    return int(dt.timestamp() * 1000)


def gregorian_expiration(now_ms: int, ordinal: int) -> int:
    """Epoch-ms of the end of the calendar period containing ``now_ms``.

    ``ordinal`` is a GregorianDuration value.  Raises ValueError on an
    unknown ordinal (the reference surfaces this as a per-request error).
    """
    d = GregorianDuration(ordinal)  # raises ValueError if out of range
    now = _from_ms(now_ms)
    if d == GregorianDuration.MINUTES:
        start = now.replace(second=0, microsecond=0)
        end = start + _dt.timedelta(minutes=1)
    elif d == GregorianDuration.HOURS:
        start = now.replace(minute=0, second=0, microsecond=0)
        end = start + _dt.timedelta(hours=1)
    elif d == GregorianDuration.DAYS:
        start = now.replace(hour=0, minute=0, second=0, microsecond=0)
        end = start + _dt.timedelta(days=1)
    elif d == GregorianDuration.WEEKS:
        day0 = now.replace(hour=0, minute=0, second=0, microsecond=0)
        start = day0 - _dt.timedelta(days=now.weekday())  # Monday start
        end = start + _dt.timedelta(weeks=1)
    elif d == GregorianDuration.MONTHS:
        ndays = calendar.monthrange(now.year, now.month)[1]
        start = now.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
        end = start + _dt.timedelta(days=ndays)
    else:  # YEARS
        end = _dt.datetime(now.year + 1, 1, 1, tzinfo=_UTC)
    return _to_ms(end)


def gregorian_rate_duration_ms(ordinal: int) -> int:
    """Fixed-width ms used for leak-rate math when a Gregorian ordinal is
    given (actual expiry still follows the calendar)."""
    return GREGORIAN_APPROX_MS[GregorianDuration(ordinal)]

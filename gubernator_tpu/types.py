"""Wire-level types for the TPU-native gubernator framework.

These mirror the reference wire contract (SURVEY.md §2.4; reference
`proto/gubernator.proto` › Algorithm/Status/Behavior/RateLimitReq/
RateLimitResp — reconstructed, the reference mount was empty).  They are
plain Python enums/dataclasses so the core framework works without
protobuf; the gRPC front door converts to/from the generated pb2 classes.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List


class Algorithm(enum.IntEnum):
    """reference: gubernator.proto › Algorithm."""

    TOKEN_BUCKET = 0
    LEAKY_BUCKET = 1


class Status(enum.IntEnum):
    """reference: gubernator.proto › Status."""

    UNDER_LIMIT = 0
    OVER_LIMIT = 1


class Behavior(enum.IntFlag):
    """reference: gubernator.proto › Behavior (bit flags).

    BATCHING is the zero value (default behavior), as in the reference.
    """

    BATCHING = 0
    NO_BATCHING = 1
    GLOBAL = 2
    DURATION_IS_GREGORIAN = 4
    RESET_REMAINING = 8
    MULTI_REGION = 16
    DRAIN_OVER_LIMIT = 32


class GregorianDuration(enum.IntEnum):
    """Calendar periods for DURATION_IS_GREGORIAN.

    When Behavior.DURATION_IS_GREGORIAN is set, RateLimitRequest.duration
    holds one of these ordinals instead of milliseconds; the bucket expires
    at the end of the current calendar period (reference: holster gregorian
    helpers used by algorithms.go › tokenBucket).
    """

    MINUTES = 0
    HOURS = 1
    DAYS = 2
    WEEKS = 3
    MONTHS = 4
    YEARS = 5


#: reference: gubernator.go › maxBatchSize
MAX_BATCH_SIZE = 1000

# --- int64-safety input bounds (the "Input clamps" contract in oracle.py;
# reference algorithms.go takes int64 durations — these bounds keep every
# intermediate product inside int64 while admitting calendar-scale ms
# durations.  Applied identically by the oracle and the device packers
# (core/batch.py); parity tests enforce agreement.)

#: Millisecond durations clamp (~285k years); token-bucket expiry adds
#: this to epoch ms (< 2^41), so sums stay far below 2^63.
DURATION_MAX = 1 << 53

#: hits/limit/burst ceiling for TOKEN_BUCKET (sums/diffs stay < 2^54).
VALUE_MAX = 1 << 53

#: LEAKY_BUCKET effective-duration denominator ceiling (~1.09 years of
#: ms).  Calendar-scale leaky windows beyond this are what
#: DURATION_IS_GREGORIAN exists for (its rate denominators are all
#: < 2^35 too).
EFF_MAX = 1 << 35

#: Leaky token-duration fixed-point bound: per-request, hits/limit/burst
#: are clamped to TD_BOUND // eff so every td product (value × eff,
#: elapsed × limit) stays ≤ 2^61 and any sum of two stays < 2^63.
TD_BOUND = 1 << 61

#: Rescale-on-duration-change keeps the sub-token fractional part only
#: when both denominators are below this (frac × eff must fit int64);
#: above it the rescale floors to whole tokens — a < 1-token, defined
#: deviation applied identically by oracle and device.
FRAC_SAFE = 1 << 31

#: Millisecond durations for the fixed-width Gregorian periods (used for
#: leak-rate math; actual expiry is computed on the calendar).
GREGORIAN_APPROX_MS = {
    GregorianDuration.MINUTES: 60_000,
    GregorianDuration.HOURS: 3_600_000,
    GregorianDuration.DAYS: 86_400_000,
    GregorianDuration.WEEKS: 7 * 86_400_000,
    GregorianDuration.MONTHS: 30 * 86_400_000,
    GregorianDuration.YEARS: 365 * 86_400_000,
}


@dataclass(slots=True)
class RateLimitRequest:
    """reference: gubernator.proto › RateLimitReq.

    Identity of a rate limit is ``hash(name + "_" + unique_key)``
    (reference: gubernator.go › GetRateLimits key construction).
    """

    name: str = ""
    unique_key: str = ""
    hits: int = 1
    limit: int = 0
    duration: int = 0  # milliseconds, or GregorianDuration ordinal
    #: Algorithm/Behavior accept plain ints: the gRPC ingest path keeps
    #: raw wire values (enum construction costs µs per request), and
    #: Behavior bit-combos aren't valid single members anyway.
    algorithm: Algorithm | int = Algorithm.TOKEN_BUCKET
    behavior: Behavior | int = Behavior.BATCHING
    burst: int = 0  # 0 → defaults to limit (leaky bucket only)
    #: Epoch-ms timestamp the request was ACCEPTED at (proto field 10;
    #: 0 = unset → the serving daemon stamps its own clock).  The
    #: forward hop sets it so a request applies at the CALLER's clock
    #: wherever it lands: without it, a key served through two daemons
    #: mixes two time bases in one bucket row, and the later base sees
    #: the earlier-base row as expired — the bucket resets and every
    #: prior debit is silently discarded (the concurrent cold-key
    #: conservation loss; cross-daemon clock skew does the same to
    #: short-duration limits in production).
    created_at: int = 0
    metadata: Dict[str, str] = field(default_factory=dict)

    @property
    def key(self) -> str:
        return self.name + "_" + self.unique_key


@dataclass(slots=True)
class RateLimitResponse:
    """reference: gubernator.proto › RateLimitResp."""

    status: Status = Status.UNDER_LIMIT
    limit: int = 0
    remaining: int = 0
    reset_time: int = 0  # epoch ms
    error: str = ""
    metadata: Dict[str, str] = field(default_factory=dict)


@dataclass
class GetRateLimitsRequest:
    """reference: gubernator.proto › GetRateLimitsReq."""

    requests: List[RateLimitRequest] = field(default_factory=list)


@dataclass
class GetRateLimitsResponse:
    """reference: gubernator.proto › GetRateLimitsResp."""

    responses: List[RateLimitResponse] = field(default_factory=list)


@dataclass
class PeerInfo:
    """reference: peers.proto / config.go › PeerInfo."""

    grpc_address: str = ""
    http_address: str = ""
    datacenter: str = ""
    is_owner: bool = False


@dataclass
class HealthCheckResponse:
    """reference: gubernator.proto › HealthCheckResp."""

    status: str = "healthy"  # "healthy" | "unhealthy"
    message: str = ""
    peer_count: int = 0

"""Client library for the gubernator-tpu service.

The analog of the reference's Go client helpers + generated Python
client (SURVEY.md §2.1 "Python client"): a thin wrapper over the gRPC
V1 service, plus an HTTP/JSON fallback for environments without grpc.
"""
from __future__ import annotations

import json
import urllib.request
from typing import List, Optional, Sequence

import grpc

from .grpc_api import V1Stub
from .proto import gubernator_pb2 as pb
from .types import (
    HealthCheckResponse,
    RateLimitRequest,
    RateLimitResponse,
)
from .wire import req_to_pb, resp_from_pb


class Client:
    """gRPC client for V1.GetRateLimits / V1.HealthCheck."""

    def __init__(self, address: str,
                 tls_creds: Optional[grpc.ChannelCredentials] = None,
                 timeout_s: float = 30.0):
        self.address = address
        self.timeout_s = timeout_s
        if tls_creds is not None:
            self._channel = grpc.secure_channel(address, tls_creds)
        else:
            self._channel = grpc.insecure_channel(address)
        self._stub = V1Stub(self._channel)

    def get_rate_limits(self, reqs: Sequence[RateLimitRequest]
                        ) -> List[RateLimitResponse]:
        from .tracing import outbound_metadata

        msg = pb.GetRateLimitsReq()
        msg.requests.extend(req_to_pb(r) for r in reqs)
        # propagates the caller's W3C trace context when one is active
        # (e.g. a service calling gubernator inside its own request)
        resp = self._stub.GetRateLimits(msg, timeout=self.timeout_s,
                                        metadata=outbound_metadata())
        return [resp_from_pb(m) for m in resp.responses]

    def check(self, req: RateLimitRequest) -> RateLimitResponse:
        return self.get_rate_limits([req])[0]

    def health_check(self) -> HealthCheckResponse:
        h = self._stub.HealthCheck(pb.HealthCheckReq(),
                                   timeout=self.timeout_s)
        return HealthCheckResponse(status=h.status, message=h.message,
                                   peer_count=h.peer_count)

    def close(self) -> None:
        self._channel.close()

    def __enter__(self) -> "Client":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class HttpClient:
    """JSON client for the HTTP gateway (grpc-gateway mirror)."""

    def __init__(self, base_url: str, timeout_s: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s

    def get_rate_limits(self, reqs: Sequence[RateLimitRequest]
                        ) -> List[RateLimitResponse]:
        payload = {"requests": [{
            "name": r.name, "unique_key": r.unique_key, "hits": int(r.hits),
            "limit": int(r.limit), "duration": int(r.duration),
            "algorithm": int(r.algorithm), "behavior": int(r.behavior),
            "burst": int(r.burst), "metadata": r.metadata} for r in reqs]}
        req = urllib.request.Request(
            self.base_url + "/v1/GetRateLimits",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout_s) as f:
            body = json.loads(f.read())
        return [RateLimitResponse(
            status=o.get("status", 0), limit=o.get("limit", 0),
            remaining=o.get("remaining", 0),
            reset_time=o.get("reset_time", 0), error=o.get("error", ""),
            metadata=o.get("metadata", {})) for o in body["responses"]]

    def health_check(self) -> HealthCheckResponse:
        with urllib.request.urlopen(self.base_url + "/v1/HealthCheck",
                                    timeout=self.timeout_s) as f:
            o = json.loads(f.read())
        return HealthCheckResponse(status=o["status"],
                                   message=o.get("message", ""),
                                   peer_count=o.get("peer_count", 0))

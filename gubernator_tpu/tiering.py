"""Tiered key store (ISSUE 10): host cold tier behind the device table.

Device-table capacity was the last hard cap on key cardinality: every
engine pins its table at construction and a probe-window-exhausted
insert was an error row ("rate limit table full").  This module turns
that condition into a *tier boundary* instead: a host-memory cold tier
(raw-hash → packed bucket-state rows, store.py-interoperable) sits
behind every device hot tier, and a sketch-rank admission controller
migrates rows between them —

- a request whose key misses the device table (cold-resident, or
  brand-new with the table full) is served EXACTLY from the cold tier
  on the resolve path: ``_host_apply`` mirrors the device transition
  (core/step.py › _apply_position) in plain integer arithmetic, bit
  for bit over the packed input domain, so decisions are byte-identical
  to an uncapped single-tier run;
- when a cold key's heavy-hitter rank (analytics.py sketch) clears the
  admission threshold its row migrates to HBM, evicting the coldest
  resident row of its probe window back to host under a
  conservation-exact, created_at-preserving handoff (all eight value
  columns move verbatim, both directions).

Coherence: every membership change (serve, create, promote, demote)
happens inside the engine's ``check_packed`` resolve or under the
instance engine lock, so a key is resident in exactly ONE tier at any
decision point.  ``ShardedEngine.check_packed`` pre-masks cold-resident
rows out of the device wave (a cold key hitting a non-full device table
would otherwise insert fresh — a state fork) and serves them here on
the way out.  The pipelined launch/sync lane and the fused C++ ingest
lane re-enter ``check_packed`` for their cold rows, the same way their
table-full retry already does.

The cold store itself is the native open-addressed table in
ops/_native.cpp (``cold_*`` primitives, khash u64 → 8×i64 row) when the
built extension exports it; a plain dict fallback keeps every semantic
otherwise (GUBER_TIER_NATIVE=0 forces the fallback).
"""
from __future__ import annotations

import logging
import os
import threading
from typing import Callable, Dict, Optional

import numpy as np

from .types import FRAC_SAFE, TD_BOUND, Algorithm, Behavior

log = logging.getLogger("gubernator_tpu.tiering")

#: cold-row column order — store.py's snapshot layout minus the key
#: column, so snapshot/restore streams cold rows through the exact
#: Loader item codec the device tier already uses.
ROW_COLS = ("meta", "limit", "duration", "eff_ms", "burst", "remaining",
            "t_ms", "expire_at")

_LEAKY = int(Algorithm.LEAKY_BUCKET)
_GREG = int(Behavior.DURATION_IS_GREGORIAN)
_RESET = int(Behavior.RESET_REMAINING)
_DRAIN = int(Behavior.DRAIN_OVER_LIMIT)

#: the all-zero item a missing key adopts — identical to the device's
#: out-of-range gather fill (core/step.py › grow: zeros, eff_ms 1)
_ZERO_ROW = (0, 0, 0, 1, 0, 0, 0, 0)


def _host_apply(row, hits, limit, duration, eff, greg_end, behavior,
                alg, burst, req_now):
    """One request applied to one cold row — the exact host mirror of
    the device transition (core/step.py › _apply_position), in plain
    Python integers over the same packed-clamped input domain
    (core/batch.py › pack_columns keeps every td product ≤ TD_BOUND, so
    no intermediate here can exceed int64 where the device's can't).

    ``row`` is an 8-tuple in ROW_COLS order (None = missing key).
    Returns (status, out_remaining, reset_time, out_limit, new_row).
    """
    if row is None:
        row = _ZERO_ROW
    meta, i_limit, i_duration, i_eff, i_burst, i_rem, i_t, i_exp = row
    i_alg = meta & 1
    i_status = (meta >> 1) & 1

    now = req_now if req_now > i_t else i_t
    is_leaky = alg == _LEAKY
    is_greg = (behavior & _GREG) != 0

    # --- fresh determination (missing/expired/algorithm switch)
    fresh = (now >= i_exp) or (i_alg != alg)
    tok_dur_change = (not is_leaky) and (not fresh) and (duration != i_duration)
    exp1 = i_exp
    if tok_dur_change:
        exp1 = greg_end if is_greg else i_t + eff
        if exp1 <= now:
            fresh = True

    # --- adopt fresh or existing state
    eff_l = eff if is_leaky else 1
    if fresh:
        limit0 = limit
        eff0 = eff
        rem0 = (burst if is_leaky else limit) * eff_l
        t0 = now
        exp0 = now + eff if is_leaky else (greg_end if is_greg else now + eff)
        status0 = 0
    else:
        limit0 = i_limit
        eff0 = i_eff
        rem0 = i_rem
        t0 = i_t
        exp0 = exp1
        status0 = i_status

    # --- leaky denominator change → rescale td fixed point
    if is_leaky and (not fresh) and eff != eff0:
        d = eff0 if eff0 > 1 else 1
        whole = rem0 // d
        frac = rem0 % d
        cap_whole = TD_BOUND // (eff if eff > 1 else 1)
        if whole > cap_whole:
            whole = cap_whole
        frac_ok = eff0 <= FRAC_SAFE and eff <= FRAC_SAFE
        rem0 = whole * eff + ((frac if frac_ok else 0) * eff) // d
    if is_leaky or tok_dur_change:
        eff0 = eff

    # --- RESET_REMAINING (existing items only)
    reset_live = (behavior & _RESET) != 0 and not fresh
    if reset_live:
        rem0 = limit * eff_l
        status0 = 0
    limit_after_reset = limit if (reset_live and not is_leaky) else limit0

    # --- token limit change in place
    if (not is_leaky) and limit != limit_after_reset:
        rem0 = rem0 + limit - limit_after_reset
        if rem0 < 0:
            rem0 = 0
        elif rem0 > limit:
            rem0 = limit
    limit1 = limit

    # --- leaky replenish (exact: elapsed × limit td, clamped to burst)
    burst1 = burst if is_leaky else limit1
    if is_leaky:
        elapsed = now - t0
        cap_td = burst1 * eff0
        safe_el = TD_BOUND // (limit1 if limit1 > 1 else 1)
        if elapsed > safe_el:
            rem0 = cap_td
        else:
            rem0 = rem0 + elapsed * limit1
            if rem0 > cap_td:
                rem0 = cap_td
        t1 = now
    else:
        t1 = t0

    d0 = eff0 if eff0 > 1 else 1
    rate = eff0 // (limit1 if limit1 > 1 else 1) if limit1 > 0 else eff0
    exp_out = now + eff0 if is_leaky else exp0
    reset_time = now + rate if is_leaky else exp_out

    # --- hits
    cost = hits * (eff0 if is_leaky else 1)
    if hits == 0:  # query
        rem2, status1 = rem0, status0
    elif cost <= rem0:
        rem2, status1 = rem0 - cost, 0
    else:
        rem2 = 0 if (behavior & _DRAIN) != 0 else rem0
        status1 = 1

    out_rem = rem2 // d0 if is_leaky else rem2
    new_row = (alg | (status1 << 1), limit1, duration, eff0, burst1,
               rem2, t1, exp_out)
    return status1, out_rem, reset_time, limit1, new_row


class _DictColdStore:
    """Pure-Python cold store: khash → 8-tuple row.  The semantic
    reference for the native table, and the fallback when the built
    extension predates the ``cold_*`` exports (GUBER_TIER_NATIVE=0
    forces it).  NOT thread-safe — TierController._mu serializes."""

    native = False

    def __init__(self):
        self._d: Dict[int, tuple] = {}

    def __len__(self) -> int:
        return len(self._d)

    def get(self, kh: int):
        return self._d.get(kh)

    def put(self, kh: int, row) -> None:
        self._d[kh] = tuple(row)

    def pop(self, kh: int):
        return self._d.pop(kh, None)

    def contains_batch(self, khash: np.ndarray) -> np.ndarray:
        d = self._d
        return np.fromiter((int(k) in d for k in khash), bool,
                           count=len(khash))

    def snapshot(self):
        """(keys u64[n], rows i64[n, 8]) in arbitrary order."""
        n = len(self._d)
        keys = np.fromiter(self._d.keys(), np.uint64, count=n)
        rows = np.empty((n, len(ROW_COLS)), np.int64)
        for i, r in enumerate(self._d.values()):
            rows[i] = r
        return keys, rows


class _NativeColdStore:
    """ops/_native.cpp ``cold_*`` open-addressed table behind the same
    interface (khash u64 → packed 8×i64 row, linear probing, tombstone
    deletes, load-factor growth in C).  NOT thread-safe —
    TierController._mu serializes."""

    native = True

    def __init__(self, native_mod):
        self._m = native_mod
        self._h = native_mod.cold_new(1024)

    def __len__(self) -> int:
        return self._m.cold_len(self._h)

    def get(self, kh: int):
        b = self._m.cold_get(self._h, kh)
        if b is None:
            return None
        return tuple(int(v) for v in np.frombuffer(b, "<i8", count=8))

    def put(self, kh: int, row) -> None:
        self._m.cold_put(self._h,
                         int(kh),
                         np.asarray(row, "<i8").tobytes())

    def pop(self, kh: int):
        b = self._m.cold_pop(self._h, kh)
        if b is None:
            return None
        return tuple(int(v) for v in np.frombuffer(b, "<i8", count=8))

    def contains_batch(self, khash: np.ndarray) -> np.ndarray:
        out = np.zeros(len(khash), np.uint8)
        self._m.cold_contains(
            self._h, np.ascontiguousarray(khash, "<u8").tobytes(), out)
        return out != 0

    def snapshot(self):
        n, keys_b, rows_b = self._m.cold_snapshot(self._h)
        keys = np.frombuffer(keys_b, "<u8", count=n).copy()
        rows = np.frombuffer(rows_b, "<i8",
                             count=n * len(ROW_COLS)).reshape(
                                 n, len(ROW_COLS)).copy()
        return keys, rows


def _make_store():
    """Native cold store when the built extension exports the cold_*
    primitives and GUBER_TIER_NATIVE != 0; dict fallback otherwise."""
    if os.environ.get("GUBER_TIER_NATIVE", "1") != "0":
        try:
            from .ops import _native
        except ImportError:
            _native = None
        if _native is not None and hasattr(_native, "cold_new"):
            return _NativeColdStore(_native)
    return _DictColdStore()


class TierController:
    """The admission/demotion controller and the cold tier's single
    front door.  One instance per engine; ``engine.tier`` points here.

    Locking: all tier *membership* changes happen inside the engine's
    ``check_packed`` resolve or under the instance engine lock, which
    serializes them against each other; ``self._mu`` (leaf rank — see
    CONCURRENCY.md) additionally protects the store against concurrent
    READERS off the serving path (stats, snapshot, seeding probes).
    Never call an engine/device method while holding ``self._mu``.
    """

    def __init__(self, engine, rank_fn: Optional[Callable[[int], int]] = None,
                 promote_threshold: int = 8, metrics=None, recorder=None,
                 fault: Optional[Callable[[str], None]] = None,
                 skip_victim: Optional[Callable[[int], bool]] = None,
                 tap: Optional[Callable] = None,
                 rank_batch: Optional[Callable] = None):
        self._mu = threading.Lock()
        self._store = _make_store()  # guarded-by: self._mu
        self.rank_fn = rank_fn
        #: batched rank read (analytics.sketch_counts) — victim
        #: selection scans a whole probe window per promotion
        self.rank_batch = rank_batch
        self.promote_threshold = max(int(promote_threshold), 1)
        self.metrics = metrics
        self.recorder = recorder
        self._fault = fault
        self._skip_victim = skip_victim
        #: rank feed for fused-tap engines: their device tap gates out
        #: invalid rows, and cold rows ride the wave invalid — without
        #: this feed a cold key could never accrue admission rank.
        self._tap = tap
        self.cold_served = 0  # guarded-by: self._mu
        self.promotions = 0  # lock-free: resolve-path only (engine-lock serialized)
        self.demotions = 0  # lock-free: resolve-path only (engine-lock serialized)
        self.migrations_aborted = 0  # lock-free: resolve-path only (engine-lock serialized)
        engine.tier = self

    # ---- membership reads ----------------------------------------------

    def resident_mask(self, khash: np.ndarray) -> np.ndarray:
        """bool[n]: which of ``khash`` are cold-resident right now.
        The engine's pre-mask read — under the engine lock the answer
        stays true until the same call's resolve."""
        with self._mu:
            return self._store.contains_batch(khash)

    def cold_keys(self) -> int:
        with self._mu:
            return len(self._store)

    def mem_bytes(self) -> int:
        """Host bytes the cold tier holds (memory-ledger probe, ISSUE
        13): one 8-byte key plus the ROW_COLS int64 columns per row —
        exact for the native store, the Python-dict store's estimate
        uses the same row layout."""
        with self._mu:
            return len(self._store) * (len(ROW_COLS) + 1) * 8

    def stats(self) -> dict:
        with self._mu:
            return {"cold_keys": len(self._store),
                    "cold_served": self.cold_served,
                    "native": self._store.native,
                    "promotions": self.promotions,
                    "demotions": self.demotions,
                    "migrations_aborted": self.migrations_aborted}

    # ---- row handoff (seeding / snapshot / overflow) -------------------

    def peek_row(self, kh: int):
        """The key's cold row as a {col: int} dict, or None."""
        with self._mu:
            row = self._store.get(int(kh))
        if row is None:
            return None
        return dict(zip(ROW_COLS, row))

    def pop_row(self, kh: int):
        """Remove + return the key's cold row ({col: int} or None) —
        the mesh/hot-set pin seed path: the replica tier takes
        ownership, so the cold copy must not linger (a stale shadow
        would resurface after the pin retires)."""
        with self._mu:
            row = self._store.pop(int(kh))
        if row is None:
            return None
        return dict(zip(ROW_COLS, row))

    def put_row(self, kh: int, cols: dict) -> None:
        """Adopt one row (mesh demote / hot-set demote overflow: the
        device table had no slot — before the tier this row was silently
        dropped)."""
        with self._mu:
            self._store.put(int(kh),
                            tuple(int(cols[f]) for f in ROW_COLS))
        self._gauge()

    def adopt_rows(self, arrays: dict, idx) -> int:
        """Adopt restore-overflow rows (store.py column arrays, row
        indices ``idx`` did not place on device) — restore's no-phantom
        contract: every snapshot row lands in exactly one tier."""
        keys = np.asarray(arrays["key"], np.uint64)
        cols = [np.asarray(arrays[f], np.int64) for f in ROW_COLS]
        n = 0
        with self._mu:
            for i in idx:
                self._store.put(int(keys[i]),
                                tuple(int(c[i]) for c in cols))
                n += 1
        self._gauge()
        return n

    def snapshot_arrays(self) -> Optional[dict]:
        """Cold rows as store.py column arrays (key included), or None
        when empty — snapshot streams these alongside the device
        columns."""
        with self._mu:
            keys, rows = self._store.snapshot()
        if not len(keys):
            return None
        out = {"key": keys}
        for j, f in enumerate(ROW_COLS):
            col = rows[:, j]
            out[f] = col.astype(np.int32) if f == "meta" else col
        return out

    # ---- the resolve path ----------------------------------------------

    def resolve(self, engine, batch, khash: np.ndarray, now_ms: int,
                cols: tuple, cold_mask, orig_valid, mslot=None) -> tuple:
        """Serve every cold-lane row of a resolved wave: pre-masked
        cold-resident rows plus residual table-full rows (brand-new
        keys with the device table full → find-or-create here).  Runs
        inside ``check_packed`` under the engine lock; patches the five
        response columns in place and clears ``full``.

        Per-key requests apply in (arrival time, original index) order
        — the same lexicographic order the device's segment sort gives
        the hot tier, so duplicate-key batches keep sequential parity.
        """
        status, lim_o, rem_o, rst_o, full = cols
        need = full & orig_valid if orig_valid is not None else full.copy()
        if cold_mask is not None:
            need = need | cold_mask
        if mslot is not None:
            need = need & (np.asarray(mslot) < 0)
        if not need.any():
            return cols
        idxs = np.nonzero(need)[0]

        h_hits = np.asarray(batch.hits)
        h_lim = np.asarray(batch.limit)
        h_dur = np.asarray(batch.duration)
        h_eff = np.asarray(batch.eff_ms)
        h_greg = np.asarray(batch.greg_end)
        h_beh = np.asarray(batch.behavior)
        h_alg = np.asarray(batch.algorithm)
        h_bur = np.asarray(batch.burst)
        h_now = np.asarray(batch.now)

        def _eff_now(i: int) -> int:
            t = int(h_now[i])
            return t if t > 0 else int(now_ms)

        order = sorted(idxs.tolist(), key=lambda i: (_eff_now(i), i))
        served_khs = []
        with self._mu:
            store = self._store
            for i in order:
                kh = int(khash[i])
                st, orem, rst, olim, new_row = _host_apply(
                    store.get(kh), int(h_hits[i]), int(h_lim[i]),
                    int(h_dur[i]), int(h_eff[i]), int(h_greg[i]),
                    int(h_beh[i]), int(h_alg[i]), int(h_bur[i]),
                    _eff_now(i))
                store.put(kh, new_row)
                status[i] = st
                rem_o[i] = orem
                rst_o[i] = rst
                lim_o[i] = olim
                full[i] = False
                served_khs.append(kh)
            self.cold_served += len(order)
        m = self.metrics
        if m is not None:
            m.tier_cold_serves.inc(len(order))
        self._gauge()
        if self._tap is not None:
            try:
                self._tap(khash[idxs], h_hits[idxs], status[idxs])
            except Exception:  # pragma: no cover - analytics only
                log.exception("tier rank-feed tap")
        self._admit(engine, served_khs)
        return status, lim_o, rem_o, rst_o, full

    # ---- admission / migration -----------------------------------------

    def _admit(self, engine, khs) -> None:
        """Promote every just-served cold key whose sketch rank clears
        the admission threshold.  No rank feed (analytics off) → no
        admission: serving stays exact, just host-paced."""
        rank = self.rank_fn
        if rank is None or not khs:
            return
        thr = self.promote_threshold
        seen = set()
        for kh in khs:
            if kh in seen:
                continue
            seen.add(kh)
            try:
                r = rank(kh)
            except Exception:  # pragma: no cover - analytics only
                return
            if r >= thr:
                self.promote(engine, kh, r)

    def promote(self, engine, kh: int, rank: int) -> bool:
        """Migrate one cold row to the device tier, evicting the
        coldest resident row of its probe window back to host when no
        slot is free.  Conservation-exact: all eight value columns
        (including t_ms/created_at lineage and expire_at) move verbatim
        in both directions; runs under the engine lock, so no request
        can observe the key mid-flight."""
        with self._mu:
            row = self._store.get(int(kh))
        if row is None:
            return False
        if not getattr(engine, "tier_row_admissible", _always)(row):
            return False  # outside the engine's step domain (Pallas)
        try:
            if self._fault is not None:
                self._fault("tier_promote")
        except Exception:  # FaultInjected: admission aborts, row stays cold
            self.migrations_aborted += 1
            if self.metrics is not None:
                self.metrics.tier_migrations_aborted.inc()
            return False
        karr = np.array([kh], np.uint64)
        if not self._upsert(engine, karr, row):
            victim = self._pick_victim(engine, kh, rank)
            if victim is None:
                return False
            if not self.demote(engine, victim):
                return False
            if not self._upsert(engine, karr, row):
                # the freed slot is in kh's own probe window, so this
                # is unreachable; tolerate it without losing the row
                return False
        with self._mu:
            self._store.pop(int(kh))
        self.promotions += 1
        if self.metrics is not None:
            self.metrics.tier_promotions.inc()
        if self.recorder is not None:
            self.recorder.record("tier_promote", khash=f"0x{kh:016x}",
                                 rank=int(rank))
        self._gauge()
        return True

    def demote(self, engine, kh: int) -> bool:
        """Migrate one device row back to the cold tier (eviction half
        of an admission, or a cap-overflow demotion): gather the row,
        adopt it cold, then clear the device slot.  Byte-exact handoff;
        under the engine lock."""
        try:
            if self._fault is not None:
                self._fault("tier_demote")
        except Exception:  # FaultInjected: eviction aborts
            self.migrations_aborted += 1
            if self.metrics is not None:
                self.metrics.tier_migrations_aborted.inc()
            return False
        karr = np.array([kh], np.uint64)
        found, vcols = engine.gather_rows(karr)
        if not found[0]:
            return False
        row = tuple(int(vcols[f][0]) for f in ROW_COLS)
        with self._mu:
            self._store.put(int(kh), row)
        engine.remove_rows(karr)
        self.demotions += 1
        if self.metrics is not None:
            self.metrics.tier_demotions.inc()
        if self.recorder is not None:
            self.recorder.record("tier_demote", khash=f"0x{kh:016x}")
        self._gauge()
        return True

    def _pick_victim(self, engine, kh: int, rank: int):
        """The coldest (minimum sketch rank) resident key in ``kh``'s
        probe window — strictly colder than the promotee, never a
        replica-pinned key (its device row is the home copy of tiered
        coherence machinery above us)."""
        probe = getattr(engine, "probe_occupant_keys", None)
        if probe is None or self.rank_fn is None:
            return None
        occ = probe(int(kh))
        skip = self._skip_victim
        cands = []
        for k in occ:
            ik = int(k)
            if ik == 0 or ik == int(kh):
                continue
            if skip is not None and skip(ik):
                continue
            cands.append(ik)
        if not cands:
            return None
        if self.rank_batch is not None:  # one sketch-lock acquisition
            ranks = self.rank_batch(cands)
        else:
            ranks = [self.rank_fn(k) for k in cands]
        best = min(range(len(cands)), key=ranks.__getitem__)
        if ranks[best] >= rank:
            return None  # everything resident is at least as hot
        return cands[best]

    @staticmethod
    def _upsert(engine, karr: np.ndarray, row) -> bool:
        cols = {}
        for f, v in zip(ROW_COLS, row):
            cols[f] = np.array([v], np.int32 if f == "meta" else np.int64)
        return int(engine.upsert_rows(karr, cols)) > 0

    def _gauge(self) -> None:
        m = self.metrics
        if m is not None:
            with self._mu:
                n = len(self._store)
            m.tier_cold_keys.set(n)


def _always(_row) -> bool:
    return True

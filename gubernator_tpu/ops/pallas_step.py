"""Pallas TPU kernel: the TOKEN_BUCKET decision step (probe → gather →
update → scatter) as ONE hand-scheduled Mosaic program.

Why this exists (VERDICT r2 item 4, SURVEY §2.2 north star): the XLA
decision step's throughput is lowering-sensitive — the same program has
measured 500 M dec/s (donated) and 209 ms/step (copy-mode scatters
serialized) on the same chip on the same day.  This kernel owns its
memory traffic explicitly, so its rate is a measured FLOOR independent
of XLA's scatter/gather lowering choices.  bench.py enters it in the
per-run mode duel alongside copy/donate (`extra.step_mode` can report
"pallas").

Design (TPU-first, not a translation):

- **Bucketized AoS table.**  Instead of the XLA path's SoA columns +
  double-hash probing (9 scattered per-row touches), the Pallas table
  is `[CAP, 32] int32`: 8-slot buckets of 128-byte rows, so ONE 1 KiB
  DMA moves a key's entire probe window *with* its data.  Layout is a
  mode-level choice — decisions are layout-independent, and the parity
  tests assert exactly that.
- **Sequential grid + in-tile serial loop.**  TPU Pallas grids run
  sequentially, which gives cross-tile duplicate ordering for free;
  within a tile a `fori_loop` applies requests strictly in order
  against the live VMEM bucket copies (deduplicated via a host-computed
  first-occurrence map), reproducing the reference's sequential
  per-request semantics by construction — duplicates, config changes,
  RESET/DRAIN flags and all.
- **int64 as 2×i32 lanes** (as ops/pallas_sweep.py already does):
  Mosaic has no 64-bit vector lanes.  Times (now/t/expire/duration,
  ~2^41 ms) use paired-word add/compare; counter values (hits, limit,
  burst, remaining) are host-qualified to < 2^30 and use plain i32
  arithmetic.

Domain (host-checked by ``pallas_qualifies``): TOKEN_BUCKET and
LEAKY_BUCKET.  All behaviors are supported: RESET_REMAINING,
DRAIN_OVER_LIMIT, DURATION_IS_GREGORIAN (greg_end / eff_ms are
precomputed columns), hits==0 queries, mixed per-request `now`.

LEAKY's td fixed point (oracle.apply_leaky: remaining stored as
``remaining × eff`` in int64 "token-duration" units) runs in paired-i32
arithmetic:

- every REQUEST-only td product (``hits×eff``, ``burst×eff``,
  ``limit×eff``, ``eff//limit``, ``TD_BOUND//limit``) is precomputed as
  an int64 column by the XLA wrapper — real 64-bit hardware, masked to
  eff=1 on token rows exactly like core/step.py's ``eff_l`` operand
  masking;
- the two STATE-dependent ops run in-kernel: ``elapsed × limit`` via an
  unsigned 32×32→64 multiply built from 16-bit halves (``_umul32x32``),
  and ``td // eff`` (+ the rescale divmods) via a 32-step restoring
  division (``_udiv64_32``) whose quotient provably fits one word: the
  domain bounds counters < 2^30 and leaky eff < 2^31 (``EFF_BOUND``),
  so td < 2^30 × eff and every quotient < 2^31.

The divisions live only in the ``pl.when`` leaky branch — token tiles
pay nothing for them.

Use ``interpret=True`` (or the CPU backend) for the reference
interpreter used by the parity tests.
"""
from __future__ import annotations

import os
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..core.batch import RequestBatch
from ..core.step import StepOutput
from ..types import TD_BOUND, Behavior

SLOTS = 8  # probe window = one bucket
WORDS = 32  # i32 words per row (128 B — DMA-friendly, room to grow)
TILE = 128  # requests per grid step (default; see pallas_tile())


def pallas_tile() -> int:
    """Requests per Mosaic grid step — the kernel's block-shape knob
    (GUBER_PALLAS_TILE).  Bounded to [8, 4096]: the in-tile dedup map
    is O(tile²) host work and the VMEM scratch is tile×1 KiB, so an
    unbounded value would trade one launch for an unschedulable tile.
    Malformed/out-of-range values keep the default (a perf knob must
    never turn into a crash knob).  Resolved at engine/program BUILD
    time — a live env flip does not retrace compiled programs."""
    raw = os.environ.get("GUBER_PALLAS_TILE", "")
    if raw:
        try:
            t = int(raw)
            if 8 <= t <= 4096:
                return t
        except ValueError:
            pass
    return TILE

#: value bound for i32 counter arithmetic (limit-change adjustment adds
#: two limits before clipping, so 2^30 keeps every intermediate in i32)
VALUE_BOUND = 1 << 30

#: leaky eff_ms bound (~24.8 days): keeps the division divisor in one
#: i32 word and, with VALUE_BOUND, every td quotient < 2^31.  Also puts
#: both denominators under oracle FRAC_SAFE (2^31), so the kernel's
#: rescale ALWAYS keeps the sub-token fraction — no floor branch —
#: and under TD_BOUND//eff ≥ 2^30 ≥ any whole-token count, so the
#: oracle's whole-token clamp is a domain no-op.  Longer windows are
#: DURATION_IS_GREGORIAN's job (fixed-rate eff) or the XLA modes'.
EFF_BOUND = 1 << 31

_RESET = int(Behavior.RESET_REMAINING)
_DRAIN = int(Behavior.DRAIN_OVER_LIMIT)
_GREG = int(Behavior.DURATION_IS_GREGORIAN)

# ---- row word layout (i32 words within a 32-word slot) -----------------
W_KLO, W_KHI = 0, 1
W_REM, W_STATUS, W_LIMIT = 2, 3, 4
W_TLO, W_THI = 5, 6
W_XLO, W_XHI = 7, 8  # expire_at
W_ELO, W_EHI = 9, 10  # eff_ms
W_DLO, W_DHI = 11, 12  # duration
W_ALG = 13  # 0 token / 1 leaky (empty slot = 0: insert is fresh anyway)
W_TDLO, W_TDHI = 14, 15  # leaky remaining, td units (= remaining × eff)
# words 16..31: reserved
# (item.burst is NOT stored: oracle.apply_leaky overwrites it from the
# request before every read, so the replenish cap is the request-only
# burst×eff column)

#: python int, not a jnp constant: a module-level traced array would be
#: captured by the kernel closure, which pallas_call rejects
_FLIP = -2147483648


def _ult(a, b):
    """unsigned-i32 a < b on reinterpreted int32 words."""
    return (a ^ _FLIP) < (b ^ _FLIP)


def _uge(a, b):
    return ~_ult(a, b)


def _add64(ah, al, bh, bl):
    lo = al + bl
    carry = _ult(lo, al).astype(jnp.int32)
    return ah + bh + carry, lo


def _ge64(ah, al, bh, bl):
    """signed 64-bit (ah:al) >= (bh:bl)."""
    return (ah > bh) | ((ah == bh) & _uge(al, bl))


def _neq64(ah, al, bh, bl):
    return (ah != bh) | (al != bl)


def _sel(c, a, b):
    return jnp.where(c, a, b)


def _tsum8(v):
    """(8,) i32 → scalar sum via an explicit halving tree.  jnp.sum on
    a rank-1 vector goes through Mosaic's proxy lowering, which
    re-traces under the ambient x64 config and emits 64-bit converts
    that have no TPU lowering (observed on-chip 2026-08-01); elementwise
    adds + a final scalar extract lower natively."""
    assert SLOTS == 8, "halving trees are hardcoded to 8-slot buckets"
    m = v[:4] + v[4:]
    m = m[:2] + m[2:]
    return m[0] + m[1]


def _tmin8(v):
    """(8,) i32 → scalar min via a halving tree (see _tsum8)."""
    assert SLOTS == 8, "halving trees are hardcoded to 8-slot buckets"
    m = jnp.minimum(v[:4], v[4:])
    m = jnp.minimum(m[:2], m[2:])
    return jnp.minimum(m[0], m[1])


def _sel64(c, ah, al, bh, bl):
    return jnp.where(c, ah, bh), jnp.where(c, al, bl)


def _sub64(ah, al, bh, bl):
    """(ah:al) - (bh:bl), callers guarantee a >= b."""
    borrow = _ult(al, bl).astype(jnp.int32)
    return ah - bh - borrow, al - bl


def _umul32x32(a, b):
    """Unsigned 32×32→64 multiply from 16-bit halves: ``a`` is any u32
    word, ``b`` must be < 2^31 (true of every multiplier here: limit
    < VALUE_BOUND, eff < EFF_BOUND).  Mosaic's i32 multiply yields the
    low 32 product bits, which for 16-bit partials IS the exact
    unsigned value."""
    i32 = jnp.int32
    mask = i32(0xFFFF)
    ah, al = (a >> 16) & mask, a & mask
    bh, bl = (b >> 16) & mask, b & mask  # bh < 2^15 given b < 2^31
    t = al * bl           # < 2^32 (exact bits in the word)
    u = ah * bl           # < 2^32
    v = al * bh           # < 2^31
    w = ah * bh           # < 2^31
    lo1 = t + (u << 16)
    c1 = _ult(lo1, t).astype(i32)
    lo2 = lo1 + (v << 16)
    c2 = _ult(lo2, lo1).astype(i32)
    hi = w + ((u >> 16) & mask) + ((v >> 16) & mask) + c1 + c2
    return hi, lo2


def _umul64x32(ah, al, m):
    """(ah:al) × m for results the caller guarantees < 2^63 (here:
    elapsed ≤ TD_BOUND//limit, so elapsed×limit ≤ TD_BOUND < 2^62) —
    the ah×m high bits then provably vanish and the wrapping i32
    multiply is exact."""
    hi, lo = _umul32x32(al, m)
    return hi + ah * m, lo


def _udiv64_32(nh, nl, d):
    """(nh:nl) ÷ d → (quotient, remainder), both one u32 word.

    32-step restoring division (shift/compare/subtract only — Mosaic
    lowers no 64-bit divide, and i32 divide lowerings are float-backed).
    Exact under the precondition nh < d (⟺ quotient < 2^32), which the
    leaky domain guarantees: every dividend < 2^31 × divisor
    (td < 2^30×eff, frac×eff < eff×2^31).  Outside the precondition
    (e.g. a discarded token-lane divisor) the result is garbage but the
    loop is still well-defined — callers select it away."""
    i32 = jnp.int32

    def step(_, c):
        R, Q, L = c
        msb = (L >> 31) & i32(1)
        L = L << 1
        R = (R << 1) | msb
        geq = _uge(R, d)
        R = jnp.where(geq, R - d, R)
        Q = (Q << 1) | geq.astype(i32)
        return R, Q, L

    R, Q, _ = lax.fori_loop(0, 32, step, (nh, i32(0), nl))
    return Q, R


def _split64(x):
    u = x.astype(jnp.uint64)
    hi = (u >> jnp.uint64(32)).astype(jnp.uint32).astype(jnp.int32)
    lo = u.astype(jnp.uint32).astype(jnp.int32)
    return hi, lo


def _join64(hi, lo, dtype):
    u = (hi.astype(jnp.uint32).astype(jnp.uint64) << jnp.uint64(32)) | \
        lo.astype(jnp.uint32).astype(jnp.uint64)
    return u.astype(dtype)


class PallasTable(NamedTuple):
    """Bucketized AoS table: ``rows[CAP, WORDS]`` int32, CAP a power of
    two ≥ 8; bucket b = rows[8b : 8b+8].  Empty slot: key words 0."""

    rows: jax.Array


def init_pallas_table(capacity: int) -> PallasTable:
    if capacity < SLOTS or capacity & (capacity - 1):
        raise ValueError(f"capacity must be a power of two >= {SLOTS}")
    return PallasTable(rows=jnp.zeros((capacity, WORDS), jnp.int32))


def pallas_value_domain_mask(batch: RequestBatch):
    """Per-row value-domain mask (np bool[B]): True where the row's
    algorithm/counters/eff fit the kernel's i32 arithmetic.  Row-level
    twin of the value checks in ``pallas_qualifies`` — the serving
    engine uses it to scope out-of-domain rows instead of failing a
    whole coalesced wave (ordering is not row-separable and stays a
    batch-level property)."""
    import numpy as np

    alg = np.asarray(batch.algorithm)
    ok = (alg == 0) | (alg == 1)
    for col in (batch.hits, batch.limit, batch.burst):
        c = np.asarray(col)
        ok &= (c >= 0) & (c < VALUE_BOUND)
    eff = np.asarray(batch.eff_ms)
    ok &= (alg != 1) | ((eff >= 1) & (eff < EFF_BOUND))
    return ok


def pallas_qualifies(batch: RequestBatch) -> bool:
    """Host-side domain check (np, cheap): every valid row TOKEN_BUCKET
    or LEAKY_BUCKET with counter values inside the i32-arithmetic
    bound, leaky eff_ms inside the one-word divisor bound, and per-key
    arrival times non-decreasing in batch order (the kernel applies
    requests strictly in batch order, where the XLA path re-sorts each
    key's segment by arrival time — a time-inverted duplicate pair
    would serialize differently)."""
    import numpy as np

    v = np.asarray(batch.valid)
    alg = np.asarray(batch.algorithm)
    if (v & (alg != 0) & (alg != 1)).any():
        return False
    for col in (batch.hits, batch.limit, batch.burst):
        c = np.asarray(col)
        if ((v) & ((c < 0) | (c >= VALUE_BOUND))).any():
            return False
    leaky = v & (alg == 1)
    if leaky.any():
        eff = np.asarray(batch.eff_ms)
        if (leaky & ((eff < 1) | (eff >= EFF_BOUND))).any():
            return False
    if batch.now is not None:
        now = np.asarray(batch.now)
        if now.size and not (now == now.flat[0]).all():
            # drop invalid rows FIRST: an invalid row sitting between
            # two valid same-key rows would break the adjacency check
            # (both pairs span an invalid member), letting a
            # time-inverted duplicate through.  Then a stable key sort
            # preserves batch order within a key, so per-key
            # monotonicity = non-decreasing now on same-key neighbors.
            keys = np.asarray(batch.key)[v]
            now_v = now[v]
            order = np.argsort(keys, kind="stable")
            k_s, n_s = keys[order], now_v[order]
            same = k_s[1:] == k_s[:-1]
            if (same & (n_s[1:] < n_s[:-1])).any():
                return False
    return True


def _kernel(tile, bb_ref, brep_ref, klo_ref, khi_ref, hits_ref, lim_ref,
            dlo_ref, dhi_ref, elo_ref, ehi_ref, glo_ref, ghi_ref,
            beh_ref, nlo_ref, nhi_ref, valid_ref,
            alg_ref, htl_ref, hth_ref, cpl_ref, cph_ref,
            rsl_ref, rsh_ref, rate_ref, gdl_ref, gdh_ref,
            _table_in, table_ref, st_o, rem_o, rlo_o, rhi_o, lim_o,
            flg_o, scratch, sem_in, sem_out):
    """One grid step = one ``tile`` of requests, strictly in order.

    scratch[j*8:(j+1)*8] holds request j's bucket copy iff j is its
    tile-first occurrence (brep[j] == j); later same-bucket requests
    read/write the first copy, so in-tile duplicates see each other's
    updates exactly as a sequential loop would."""
    i32 = jnp.int32

    def first_live(j):
        return (brep_ref[0, 0, j] == j) & (valid_ref[0, 0, j] != 0)

    # 1) gather: one DMA per distinct live bucket in the tile
    def issue_in(j, c):
        @pl.when(first_live(j))
        def _():
            pltpu.make_async_copy(
                table_ref.at[pl.ds(bb_ref[0, 0, j], SLOTS)],
                scratch.at[pl.ds(j * SLOTS, SLOTS)],
                sem_in.at[j]).start()
        return c

    lax.fori_loop(0, tile, issue_in, 0)

    def wait_in(j, c):
        @pl.when(first_live(j))
        def _():
            pltpu.make_async_copy(
                table_ref.at[pl.ds(bb_ref[0, 0, j], SLOTS)],
                scratch.at[pl.ds(j * SLOTS, SLOTS)],
                sem_in.at[j]).wait()
        return c

    lax.fori_loop(0, tile, wait_in, 0)

    lane = lax.broadcasted_iota(i32, (SLOTS, WORDS), 1)

    # 2) apply requests in order against the live bucket copies
    def body(j, c):
        valid = valid_ref[0, 0, j] != 0

        @pl.when(valid)
        def _process():
            base = brep_ref[0, 0, j] * SLOTS
            tile = scratch[pl.ds(base, SLOTS), :]  # [SLOTS, WORDS]
            klo, khi = klo_ref[0, 0, j], khi_ref[0, 0, j]

            def col(w):
                return tile[:, w]

            match = (col(W_KLO) == klo) & (col(W_KHI) == khi)
            # all reductions in i32: Mosaic's bool reduce_or/any proxy
            # lowers through float64, which has no scalar conversion
            # on TPU (observed on-chip 2026-08-01)
            found = _tsum8(match.astype(i32)) > 0
            empty = (col(W_KLO) == 0) & (col(W_KHI) == 0)
            # first empty slot: lowest slot index among empties (iota +
            # min — stable, deterministic, no float cumsum)
            slot_iota = lax.broadcasted_iota(i32, (SLOTS,), 0)
            first_idx = _tmin8(jnp.where(empty, slot_iota, i32(SLOTS)))
            first_empty = empty & (slot_iota == first_idx)
            has_empty = first_idx < i32(SLOTS)
            insert = (~found) & has_empty
            err = (~found) & (~has_empty)  # bucket full
            slot1h = jnp.where(found, match, first_empty)  # [SLOTS]

            def pick(w):
                """matched/claimed slot's word w as a scalar (0 for a
                fresh insert: empty slots hold zero words)."""
                return _tsum8(jnp.where(slot1h, col(w), i32(0)))

            # item state (insert reads the zeroed empty slot → fresh
            # fires below, matching the XLA path's post-insert read)
            it_rem, it_status = pick(W_REM), pick(W_STATUS)
            it_limit, it_alg = pick(W_LIMIT), pick(W_ALG)
            it_tlo, it_thi = pick(W_TLO), pick(W_THI)
            it_xlo, it_xhi = pick(W_XLO), pick(W_XHI)
            it_elo, it_ehi = pick(W_ELO), pick(W_EHI)
            it_dlo, it_dhi = pick(W_DLO), pick(W_DHI)
            it_tdlo, it_tdhi = pick(W_TDLO), pick(W_TDHI)

            # request fields
            r_hits, r_lim = hits_ref[0, 0, j], lim_ref[0, 0, j]
            r_dlo, r_dhi = dlo_ref[0, 0, j], dhi_ref[0, 0, j]
            r_elo, r_ehi = elo_ref[0, 0, j], ehi_ref[0, 0, j]
            r_glo, r_ghi = glo_ref[0, 0, j], ghi_ref[0, 0, j]
            r_alg = alg_ref[0, 0, j]
            beh = beh_ref[0, 0, j]
            is_greg = (beh & _GREG) != 0
            reset = (beh & _RESET) != 0
            drain = (beh & _DRAIN) != 0

            # now = max(req.now, item.t)  (per-key monotonic clock)
            nhi0, nlo0 = nhi_ref[0, 0, j], nlo_ref[0, 0, j]
            use_req = _ge64(nhi0, nlo0, it_thi, it_tlo)
            nhi1, nlo1 = _sel64(use_req, nhi0, nlo0, it_thi, it_tlo)

            # fresh: empty / expired / algorithm switch
            fresh0 = ((~found) | _ge64(nhi1, nlo1, it_xhi, it_xlo)
                      | (it_alg != r_alg))
            is_query = r_hits == i32(0)
            dead = err
            flg_o[0, 0, j] = err.astype(i32) | (
                (insert & ~err).astype(i32) << 1)
            lim_o[0, 0, j] = _sel(dead, i32(0), r_lim)
            # default-zero the branch-written outputs: a valid row with
            # an out-of-domain algorithm (neither pl.when fires —
            # callers must gate on pallas_qualifies, but defense here
            # is one store) must return zeros, never uninitialized
            # output memory
            st_o[0, 0, j] = i32(0)
            rem_o[0, 0, j] = i32(0)
            rlo_o[0, 0, j] = i32(0)
            rhi_o[0, 0, j] = i32(0)

            @pl.when(r_alg == i32(0))
            def _token():
                fresh = fresh0
                # token duration change → recompute expiry from item.t
                dur_change = ((~fresh)
                              & _neq64(r_dhi, r_dlo, it_dhi, it_dlo))
                ne_hi, ne_lo = _add64(it_thi, it_tlo, r_ehi, r_elo)
                ne_hi, ne_lo = _sel64(is_greg, r_ghi, r_glo, ne_hi,
                                      ne_lo)
                x1hi, x1lo = _sel64(dur_change, ne_hi, ne_lo,
                                    it_xhi, it_xlo)
                fresh = fresh | (dur_change
                                 & ~_ge64(x1hi, x1lo, nhi1, nlo1)
                                 ) | (dur_change & _ge64(nhi1, nlo1,
                                                         x1hi, x1lo))
                # (exp1 <= now  ≡  now >= exp1; the first disjunct is
                # exp1 < now via !(exp1 >= now) — keep both for
                # exactness with oracle's `exp1 <= now`)

                # adopt fresh or existing
                xf_hi, xf_lo = _add64(nhi1, nlo1, r_ehi, r_elo)
                xf_hi, xf_lo = _sel64(is_greg, r_ghi, r_glo,
                                      xf_hi, xf_lo)
                limit0 = _sel(fresh, r_lim, it_limit)
                rem0 = _sel(fresh, r_lim, it_rem)
                t_hi, t_lo = _sel64(fresh, nhi1, nlo1, it_thi, it_tlo)
                x_hi, x_lo = _sel64(fresh, xf_hi, xf_lo, x1hi, x1lo)
                status0 = _sel(fresh, i32(0), it_status)
                e_hi, e_lo = _sel64(fresh | dur_change, r_ehi, r_elo,
                                    it_ehi, it_elo)

                # RESET_REMAINING on existing items
                reset_live = reset & (~fresh)
                rem0 = _sel(reset_live, r_lim, rem0)
                status0 = _sel(reset_live, i32(0), status0)
                limit_ar = _sel(reset_live, r_lim, limit0)

                # token limit change in place
                lim_change = r_lim != limit_ar
                rem_adj = jnp.clip(rem0 + r_lim - limit_ar, i32(0),
                                   r_lim)
                rem0 = _sel(lim_change, rem_adj, rem0)

                # hits
                ok = r_hits <= rem0
                rem2 = _sel((~is_query) & ok, rem0 - r_hits, rem0)
                rem2 = _sel((~is_query) & (~ok) & drain, i32(0), rem2)
                status1 = _sel(is_query, status0,
                               _sel(ok, i32(0), i32(1)))

                # write the slot back (unless the bucket was full)
                @pl.when(~err)
                def _writeback():
                    sel = slot1h[:, None]

                    def put(t, w, v):
                        return jnp.where(sel & (lane == w), v, t)

                    nt = tile
                    nt = put(nt, W_KLO, klo)
                    nt = put(nt, W_KHI, khi)
                    nt = put(nt, W_REM, rem2)
                    nt = put(nt, W_STATUS, status1)
                    nt = put(nt, W_LIMIT, r_lim)
                    nt = put(nt, W_TLO, t_lo)
                    nt = put(nt, W_THI, t_hi)
                    nt = put(nt, W_XLO, x_lo)
                    nt = put(nt, W_XHI, x_hi)
                    nt = put(nt, W_ELO, e_lo)
                    nt = put(nt, W_EHI, e_hi)
                    nt = put(nt, W_DLO, r_dlo)
                    nt = put(nt, W_DHI, r_dhi)
                    nt = put(nt, W_ALG, i32(0))
                    nt = put(nt, W_TDLO, i32(0))
                    nt = put(nt, W_TDHI, i32(0))
                    scratch[pl.ds(base, SLOTS), :] = nt

                # outputs (err rows zeroed, as the XLA step masks them)
                st_o[0, 0, j] = _sel(dead, i32(0), status1)
                rem_o[0, 0, j] = _sel(dead, i32(0), rem2)
                rlo_o[0, 0, j] = _sel(dead, i32(0), x_lo)
                rhi_o[0, 0, j] = _sel(dead, i32(0), x_hi)

            @pl.when(r_alg == i32(1))
            def _leaky():
                # request-only td columns (precomputed by the wrapper):
                # hits×eff, burst×eff (cap), limit×eff (reset value),
                # eff//limit (rate), TD_BOUND//limit (replenish guard)
                r_htl, r_hth = htl_ref[0, 0, j], hth_ref[0, 0, j]
                r_cpl, r_cph = cpl_ref[0, 0, j], cph_ref[0, 0, j]
                r_rsl, r_rsh = rsl_ref[0, 0, j], rsh_ref[0, 0, j]
                r_rate = rate_ref[0, 0, j]
                r_gdl, r_gdh = gdl_ref[0, 0, j], gdh_ref[0, 0, j]

                # denominator change → rescale the td fixed point to
                # the new eff.  In the kernel domain both denominators
                # are < EFF_BOUND ≤ FRAC_SAFE, so the sub-token
                # fraction is ALWAYS kept, and whole < 2^30 ≤
                # TD_BOUND//eff makes the oracle's whole-token clamp a
                # no-op (see EFF_BOUND).  Divides run unconditionally
                # (lane-selected away on ~eff_change); a token-item
                # divisor (alg switch) feeds garbage that fresh0
                # discards — _udiv64_32 is total, never faulting.
                eff_change = ((~fresh0)
                              & _neq64(r_ehi, r_elo, it_ehi, it_elo))
                whole, fracr = _udiv64_32(it_tdhi, it_tdlo, it_elo)
                fth, ftl = _umul32x32(fracr, r_elo)
                frac_term, _ = _udiv64_32(fth, ftl, it_elo)
                wh, wl = _umul32x32(whole, r_elo)
                resc_h, resc_l = _add64(wh, wl, i32(0), frac_term)
                td0h, td0l = _sel64(eff_change, resc_h, resc_l,
                                    it_tdhi, it_tdlo)

                # fresh adoption: bucket starts full (burst × eff)
                td0h, td0l = _sel64(fresh0, r_cph, r_cpl, td0h, td0l)
                status0 = _sel(fresh0, i32(0), it_status)
                t0h, t0l = _sel64(fresh0, nhi1, nlo1, it_thi, it_tlo)

                # RESET_REMAINING on existing items: limit × eff
                reset_live = reset & (~fresh0)
                td0h, td0l = _sel64(reset_live, r_rsh, r_rsl,
                                    td0h, td0l)
                status0 = _sel(reset_live, i32(0), status0)

                # replenish: elapsed × limit td, clamped to cap.
                # elapsed > TD_BOUND//limit ⇒ the true product already
                # exceeds the cap — bucket simply full (exact, as in
                # oracle.apply_leaky).  Fresh lanes: t0 = now ⇒
                # elapsed = 0 ⇒ no-op, mirroring the XLA step.
                elh, ell = _sub64(nhi1, nlo1, t0h, t0l)
                over_g = ~_ge64(r_gdh, r_gdl, elh, ell)
                ech, ecl = _sel64(over_g, r_gdh, r_gdl, elh, ell)
                adh, adl = _umul64x32(ech, ecl, r_lim)
                sh, sl = _add64(td0h, td0l, adh, adl)
                full = over_g | _ge64(sh, sl, r_cph, r_cpl)
                rph, rpl = _sel64(full, r_cph, r_cpl, sh, sl)

                # hits (cost = hits × eff, precomputed)
                ok = _ge64(rph, rpl, r_hth, r_htl)
                d2h, d2l = _sub64(rph, rpl, r_hth, r_htl)
                apply_ok = (~is_query) & ok
                td2h, td2l = _sel64(apply_ok, d2h, d2l, rph, rpl)
                drain_hit = (~is_query) & (~ok) & drain
                td2h, td2l = _sel64(drain_hit, i32(0), i32(0),
                                    td2h, td2l)
                status1 = _sel(is_query, status0,
                               _sel(ok, i32(0), i32(1)))

                # response: remaining in whole tokens, reset_time =
                # now + eff//limit (NOT the stored expire = now + eff)
                rem_out, _ = _udiv64_32(td2h, td2l, r_elo)
                x_hi, x_lo = _add64(nhi1, nlo1, r_ehi, r_elo)
                rsh_, rsl_ = _add64(nhi1, nlo1, i32(0), r_rate)

                @pl.when(~err)
                def _writeback():
                    sel = slot1h[:, None]

                    def put(t, w, v):
                        return jnp.where(sel & (lane == w), v, t)

                    nt = tile
                    nt = put(nt, W_KLO, klo)
                    nt = put(nt, W_KHI, khi)
                    nt = put(nt, W_REM, i32(0))
                    nt = put(nt, W_STATUS, status1)
                    nt = put(nt, W_LIMIT, r_lim)
                    nt = put(nt, W_TLO, nlo1)
                    nt = put(nt, W_THI, nhi1)
                    nt = put(nt, W_XLO, x_lo)
                    nt = put(nt, W_XHI, x_hi)
                    nt = put(nt, W_ELO, r_elo)
                    nt = put(nt, W_EHI, r_ehi)
                    nt = put(nt, W_DLO, r_dlo)
                    nt = put(nt, W_DHI, r_dhi)
                    nt = put(nt, W_ALG, i32(1))
                    nt = put(nt, W_TDLO, td2l)
                    nt = put(nt, W_TDHI, td2h)
                    scratch[pl.ds(base, SLOTS), :] = nt

                st_o[0, 0, j] = _sel(dead, i32(0), status1)
                rem_o[0, 0, j] = _sel(dead, i32(0), rem_out)
                rlo_o[0, 0, j] = _sel(dead, i32(0), rsl_)
                rhi_o[0, 0, j] = _sel(dead, i32(0), rsh_)

        @pl.when(~valid)
        def _invalid():
            st_o[0, 0, j] = i32(0)
            rem_o[0, 0, j] = i32(0)
            rlo_o[0, 0, j] = i32(0)
            rhi_o[0, 0, j] = i32(0)
            lim_o[0, 0, j] = i32(0)
            flg_o[0, 0, j] = i32(0)

        return c

    lax.fori_loop(0, tile, body, 0)

    # 3) scatter: write distinct live buckets back, then fence the tile
    # (the wait orders these stores before the NEXT tile's gathers)
    def issue_out(j, c):
        @pl.when(first_live(j))
        def _():
            pltpu.make_async_copy(
                scratch.at[pl.ds(j * SLOTS, SLOTS)],
                table_ref.at[pl.ds(bb_ref[0, 0, j], SLOTS)],
                sem_out.at[j]).start()
        return c

    lax.fori_loop(0, tile, issue_out, 0)

    def wait_out(j, c):
        @pl.when(first_live(j))
        def _():
            pltpu.make_async_copy(
                scratch.at[pl.ds(j * SLOTS, SLOTS)],
                table_ref.at[pl.ds(bb_ref[0, 0, j], SLOTS)],
                sem_out.at[j]).wait()
        return c

    lax.fori_loop(0, tile, wait_out, 0)


N_COLS = 26  # SMEM request columns (see _kernel signature order)


def _call_kernel(rows, cols, interpret: bool, tile: int = TILE):
    """cols: N_COLS int32 arrays shaped [G, 1, tile] (_kernel order).

    The singleton middle axis is load-bearing on real Mosaic: a block's
    last two dims must be divisible by (8, 128) or equal the array's —
    a [G, TILE] array with (1, TILE) blocks violates that (observed
    on-chip 2026-08-01), while [G, 1, TILE] with (1, 1, TILE) blocks
    has last-two dims (1, TILE) == the array's, which is allowed."""
    G = cols[0].shape[0]
    smem_tile = pl.BlockSpec((1, 1, tile), lambda i: (i, 0, 0),
                             memory_space=pltpu.SMEM)
    out_tile = pl.BlockSpec((1, 1, tile), lambda i: (i, 0, 0),
                            memory_space=pltpu.SMEM)
    table_spec = pl.BlockSpec(memory_space=pl.ANY)
    o32 = jax.ShapeDtypeStruct((G, 1, tile), jnp.int32)
    # jax.enable_x64 left the top-level namespace in jax 0.4.3x (this
    # image raises AttributeError on it); the experimental alias is the
    # stable spelling of the same x64-off trace scope.  The scope wraps
    # only the REAL Mosaic build: on jax 0.4.37 the interpreter's grid
    # loop captures x64 carries from the enclosing trace, and flipping
    # x64 off mid-trace emits mixed i32/i64 while-carries that fail MLIR
    # verification (this image's "jax 0.4.37 kills pallas" breakage);
    # the kernel body itself is explicitly typed, so the interpret path
    # needs no ambient-dtype pinning.
    import contextlib
    scope = (contextlib.nullcontext() if interpret
             else jax.experimental.enable_x64(False))
    with scope:
        return pl.pallas_call(
            partial(_kernel, tile),
            grid=(G,),
            in_specs=[smem_tile] * N_COLS + [table_spec],
            out_specs=[table_spec] + [out_tile] * 6,
            out_shape=[jax.ShapeDtypeStruct(rows.shape, jnp.int32)]
            + [o32] * 6,
            input_output_aliases={N_COLS: 0},
            scratch_shapes=[
                pltpu.VMEM((tile * SLOTS, WORDS), jnp.int32),
                pltpu.SemaphoreType.DMA((tile,)),
                pltpu.SemaphoreType.DMA((tile,)),
            ],
            interpret=interpret,
        )(*cols, rows)


def decide_batch_pallas_impl(table: PallasTable, batch: RequestBatch,
                             now_ms, *, interpret: bool = False,
                             tile: int = 0
                             ) -> tuple[PallasTable, StepOutput]:
    """Unjitted kernel step — for embedding in larger programs (the
    Pallas serving engine wraps it in shard_map; plain callers use the
    jitted/donated ``decide_batch_pallas`` below).

    Same contract as core/step.py › decide_batch for batches inside
    the kernel's domain (``pallas_qualifies``) — the parity tests
    assert identical decisions on shared request streams.  ``tile``
    (requests per grid step) 0 resolves the GUBER_PALLAS_TILE knob at
    trace time; engines resolve it once at build and pass it explicitly
    so a live env flip can't desync compiled programs.
    """
    i32, i64 = jnp.int32, jnp.int64
    TILE = tile if tile else pallas_tile()
    cap = table.rows.shape[0]
    n_buckets = cap // SLOTS
    B = batch.key.shape[0]
    G = -(-B // TILE)
    pad = G * TILE - B

    now = jnp.asarray(now_ms, i64)
    if batch.now is None:
        now_col = jnp.full((B,), now, i64)
    else:
        now_col = jnp.where(jnp.asarray(batch.now, i64) > 0,
                            jnp.asarray(batch.now, i64), now)

    key = batch.key.astype(jnp.uint64)
    valid = (batch.valid & (key != 0)).astype(i32)
    bucket = (key & jnp.uint64(n_buckets - 1)).astype(i32) * SLOTS

    def pad_to(x, fill=0):
        return jnp.pad(x, (0, pad), constant_values=fill) if pad else x

    khi, klo = _split64(key)
    dhi, dlo = _split64(batch.duration.astype(i64))
    ehi, elo = _split64(batch.eff_ms.astype(i64))
    ghi, glo = _split64(batch.greg_end.astype(i64))
    nhi, nlo = _split64(now_col)

    # Request-only leaky td products, in REAL int64 before the i32
    # split (eff masked to 1 on token rows so huge token hits/limits
    # can't wrap the unused product — same operand masking as
    # core/step.py's eff_l).
    alg = batch.algorithm.astype(i32)
    is_lk = alg == 1
    eff64 = batch.eff_ms.astype(i64)
    lim64 = batch.limit.astype(i64)
    eff_l = jnp.where(is_lk, eff64, 1)
    hth, htl = _split64(batch.hits.astype(i64) * eff_l)
    cph, cpl = _split64(batch.burst.astype(i64) * eff_l)
    rsh, rsl = _split64(lim64 * eff_l)
    rate = jnp.where(lim64 > 0, eff_l // jnp.maximum(lim64, 1),
                     eff_l).astype(i32)
    gdh, gdl = _split64(TD_BOUND // jnp.maximum(lim64, 1))

    bb = pad_to(bucket)
    cols1d = [
        bb,
        klo, khi,
        batch.hits.astype(i32), batch.limit.astype(i32),
        dlo, dhi, elo, ehi, glo, ghi,
        batch.behavior.astype(i32), nlo, nhi, valid,
        alg, htl, hth, cpl, cph, rsl, rsh, rate, gdl, gdh,
    ]
    cols1d = [bb] + [pad_to(c) for c in cols1d[1:]]

    # tile-relative first occurrence of each bucket (dedup map): the
    # kernel's serial loop routes same-bucket requests to one VMEM
    # copy.  Invalid rows get a UNIQUE sentinel so they can never
    # become a bucket's representative: first_live gates the DMA on
    # valid, so an invalid representative would starve a later valid
    # same-bucket request of its gather/writeback entirely.
    bt = bb.reshape(G, TILE)
    iota = jnp.arange(G * TILE, dtype=jnp.int64).reshape(G, TILE)
    vpad = pad_to(valid).reshape(G, TILE).astype(bool)
    rep_key = jnp.where(vpad, bt.astype(jnp.int64), -1 - iota)
    eq = rep_key[:, :, None] == rep_key[:, None, :]
    brep = jnp.argmax(eq, axis=-1).astype(i32)  # first True per row

    # [G, 1, TILE]: the singleton axis satisfies Mosaic's block-shape
    # rule (see _call_kernel)
    cols = [c.reshape(G, 1, TILE) for c in [bt, brep] + cols1d[1:]]
    rows2, st, rem, rlo, rhi, lim, flg = _call_kernel(
        table.rows, cols, interpret, TILE)

    def unpad(x):
        return x.reshape(-1)[:B]

    st = unpad(st)
    flg = unpad(flg)
    err = (flg & 1) != 0
    vb = valid.astype(bool)[:B] if pad else valid.astype(bool)
    live = vb & (~err)
    status = jnp.where(live, st, 0)
    remaining = jnp.where(live, unpad(rem).astype(i64), 0)
    reset_time = jnp.where(
        live, _join64(unpad(rhi), unpad(rlo), i64), 0)
    limit_out = jnp.where(live, unpad(lim).astype(i64), 0)
    over = (live & (status == 1)).sum(dtype=i64)
    inserts = ((flg >> 1) & 1).sum(dtype=i64)
    return PallasTable(rows=rows2), StepOutput(
        status=status.astype(i32), remaining=remaining,
        reset_time=reset_time, limit=limit_out,
        err=vb & err, over_count=over, insert_count=inserts)


def fused_tap_columns(batch: RequestBatch, out: StepOutput):
    """[4, B] int64 heavy-hitter tap emitted BY THE SAME device program
    as the decision step (ISSUE 8): rows are (khash bit-viewed i64,
    hits, over_limit, served).  The analytics worker drains this device
    array off the serving path (analytics.KeyAnalytics.tap_device) —
    the host-side column copies the dispatcher's tap_packed made per
    wave are deleted for fused engines.  ``served`` gates padding,
    invalid rows and table_full rows out of the sketch exactly as the
    host tap's job-scoped columns did."""
    i64 = jnp.int64
    served = batch.valid & (~out.err)
    return jnp.stack([
        lax.bitcast_convert_type(
            jnp.asarray(batch.key).astype(jnp.uint64), i64),
        jnp.asarray(batch.hits, i64),
        (out.status == 1).astype(i64),
        served.astype(i64)])


#: Jitted/donated entry point (the bench duel + battery callers):
#: table aliases in/out like decide_batch_donated.
decide_batch_pallas = jax.jit(decide_batch_pallas_impl,
                              static_argnames=("interpret", "tile"),
                              donate_argnums=(0,))

"""Pallas TPU kernel: the TOKEN_BUCKET decision step (probe → gather →
update → scatter) as ONE hand-scheduled Mosaic program.

Why this exists (VERDICT r2 item 4, SURVEY §2.2 north star): the XLA
decision step's throughput is lowering-sensitive — the same program has
measured 500 M dec/s (donated) and 209 ms/step (copy-mode scatters
serialized) on the same chip on the same day.  This kernel owns its
memory traffic explicitly, so its rate is a measured FLOOR independent
of XLA's scatter/gather lowering choices.  bench.py enters it in the
per-run mode duel alongside copy/donate (`extra.step_mode` can report
"pallas").

Design (TPU-first, not a translation):

- **Bucketized AoS table.**  Instead of the XLA path's SoA columns +
  double-hash probing (9 scattered per-row touches), the Pallas table
  is `[CAP, 32] int32`: 8-slot buckets of 128-byte rows, so ONE 1 KiB
  DMA moves a key's entire probe window *with* its data.  Layout is a
  mode-level choice — decisions are layout-independent, and the parity
  tests assert exactly that.
- **Sequential grid + in-tile serial loop.**  TPU Pallas grids run
  sequentially, which gives cross-tile duplicate ordering for free;
  within a tile a `fori_loop` applies requests strictly in order
  against the live VMEM bucket copies (deduplicated via a host-computed
  first-occurrence map), reproducing the reference's sequential
  per-request semantics by construction — duplicates, config changes,
  RESET/DRAIN flags and all.
- **int64 as 2×i32 lanes** (as ops/pallas_sweep.py already does):
  Mosaic has no 64-bit vector lanes.  Times (now/t/expire/duration,
  ~2^41 ms) use paired-word add/compare; counter values (hits, limit,
  burst, remaining) are host-qualified to < 2^30 and use plain i32
  arithmetic.

Domain (host-checked by ``pallas_qualifies``): TOKEN_BUCKET only —
LEAKY's td fixed point needs 64-bit multiply/divide, which this
prototype does not implement (the XLA modes serve it).  All TOKEN
behaviors are supported: RESET_REMAINING, DRAIN_OVER_LIMIT,
DURATION_IS_GREGORIAN (greg_end is a precomputed column), hits==0
queries, mixed per-request `now`.

Use ``interpret=True`` (or the CPU backend) for the reference
interpreter used by the parity tests.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..core.batch import RequestBatch
from ..core.step import StepOutput
from ..types import Behavior

SLOTS = 8  # probe window = one bucket
WORDS = 32  # i32 words per row (128 B — DMA-friendly, room to grow)
TILE = 128  # requests per grid step

#: value bound for i32 counter arithmetic (limit-change adjustment adds
#: two limits before clipping, so 2^30 keeps every intermediate in i32)
VALUE_BOUND = 1 << 30

_RESET = int(Behavior.RESET_REMAINING)
_DRAIN = int(Behavior.DRAIN_OVER_LIMIT)
_GREG = int(Behavior.DURATION_IS_GREGORIAN)

# ---- row word layout (i32 words within a 32-word slot) -----------------
W_KLO, W_KHI = 0, 1
W_REM, W_STATUS, W_LIMIT = 2, 3, 4
W_TLO, W_THI = 5, 6
W_XLO, W_XHI = 7, 8  # expire_at
W_ELO, W_EHI = 9, 10  # eff_ms
W_DLO, W_DHI = 11, 12  # duration
# words 13..31: reserved (leaky td state, burst, alg when the kernel
# grows past the token domain)

#: python int, not a jnp constant: a module-level traced array would be
#: captured by the kernel closure, which pallas_call rejects
_FLIP = -2147483648


def _ult(a, b):
    """unsigned-i32 a < b on reinterpreted int32 words."""
    return (a ^ _FLIP) < (b ^ _FLIP)


def _uge(a, b):
    return ~_ult(a, b)


def _add64(ah, al, bh, bl):
    lo = al + bl
    carry = _ult(lo, al).astype(jnp.int32)
    return ah + bh + carry, lo


def _ge64(ah, al, bh, bl):
    """signed 64-bit (ah:al) >= (bh:bl)."""
    return (ah > bh) | ((ah == bh) & _uge(al, bl))


def _neq64(ah, al, bh, bl):
    return (ah != bh) | (al != bl)


def _sel(c, a, b):
    return jnp.where(c, a, b)


def _tsum8(v):
    """(8,) i32 → scalar sum via an explicit halving tree.  jnp.sum on
    a rank-1 vector goes through Mosaic's proxy lowering, which
    re-traces under the ambient x64 config and emits 64-bit converts
    that have no TPU lowering (observed on-chip 2026-08-01); elementwise
    adds + a final scalar extract lower natively."""
    m = v[:4] + v[4:]
    m = m[:2] + m[2:]
    return m[0] + m[1]


def _tmin8(v):
    """(8,) i32 → scalar min via a halving tree (see _tsum8)."""
    m = jnp.minimum(v[:4], v[4:])
    m = jnp.minimum(m[:2], m[2:])
    return jnp.minimum(m[0], m[1])


def _sel64(c, ah, al, bh, bl):
    return jnp.where(c, ah, bh), jnp.where(c, al, bl)


def _split64(x):
    u = x.astype(jnp.uint64)
    hi = (u >> jnp.uint64(32)).astype(jnp.uint32).astype(jnp.int32)
    lo = u.astype(jnp.uint32).astype(jnp.int32)
    return hi, lo


def _join64(hi, lo, dtype):
    u = (hi.astype(jnp.uint32).astype(jnp.uint64) << jnp.uint64(32)) | \
        lo.astype(jnp.uint32).astype(jnp.uint64)
    return u.astype(dtype)


class PallasTable(NamedTuple):
    """Bucketized AoS table: ``rows[CAP, WORDS]`` int32, CAP a power of
    two ≥ 8; bucket b = rows[8b : 8b+8].  Empty slot: key words 0."""

    rows: jax.Array


def init_pallas_table(capacity: int) -> PallasTable:
    if capacity < SLOTS or capacity & (capacity - 1):
        raise ValueError(f"capacity must be a power of two >= {SLOTS}")
    return PallasTable(rows=jnp.zeros((capacity, WORDS), jnp.int32))


def pallas_qualifies(batch: RequestBatch) -> bool:
    """Host-side domain check (np, cheap): every valid row TOKEN_BUCKET
    with counter values inside the i32-arithmetic bound, and per-key
    arrival times non-decreasing in batch order (the kernel applies
    requests strictly in batch order, where the XLA path re-sorts each
    key's segment by arrival time — a time-inverted duplicate pair
    would serialize differently)."""
    import numpy as np

    v = np.asarray(batch.valid)
    alg = np.asarray(batch.algorithm)
    if (v & (alg != 0)).any():
        return False
    for col in (batch.hits, batch.limit, batch.burst):
        c = np.asarray(col)
        if ((v) & ((c < 0) | (c >= VALUE_BOUND))).any():
            return False
    if batch.now is not None:
        now = np.asarray(batch.now)
        if now.size and not (now == now.flat[0]).all():
            # drop invalid rows FIRST: an invalid row sitting between
            # two valid same-key rows would break the adjacency check
            # (both pairs span an invalid member), letting a
            # time-inverted duplicate through.  Then a stable key sort
            # preserves batch order within a key, so per-key
            # monotonicity = non-decreasing now on same-key neighbors.
            keys = np.asarray(batch.key)[v]
            now_v = now[v]
            order = np.argsort(keys, kind="stable")
            k_s, n_s = keys[order], now_v[order]
            same = k_s[1:] == k_s[:-1]
            if (same & (n_s[1:] < n_s[:-1])).any():
                return False
    return True


def _kernel(bb_ref, brep_ref, klo_ref, khi_ref, hits_ref, lim_ref,
            dlo_ref, dhi_ref, elo_ref, ehi_ref, glo_ref, ghi_ref,
            beh_ref, nlo_ref, nhi_ref, valid_ref,
            _table_in, table_ref, st_o, rem_o, rlo_o, rhi_o, lim_o,
            flg_o, scratch, sem_in, sem_out):
    """One grid step = one TILE of requests, strictly in order.

    scratch[j*8:(j+1)*8] holds request j's bucket copy iff j is its
    tile-first occurrence (brep[j] == j); later same-bucket requests
    read/write the first copy, so in-tile duplicates see each other's
    updates exactly as a sequential loop would."""
    i32 = jnp.int32

    def first_live(j):
        return (brep_ref[0, 0, j] == j) & (valid_ref[0, 0, j] != 0)

    # 1) gather: one DMA per distinct live bucket in the tile
    def issue_in(j, c):
        @pl.when(first_live(j))
        def _():
            pltpu.make_async_copy(
                table_ref.at[pl.ds(bb_ref[0, 0, j], SLOTS)],
                scratch.at[pl.ds(j * SLOTS, SLOTS)],
                sem_in.at[j]).start()
        return c

    lax.fori_loop(0, TILE, issue_in, 0)

    def wait_in(j, c):
        @pl.when(first_live(j))
        def _():
            pltpu.make_async_copy(
                table_ref.at[pl.ds(bb_ref[0, 0, j], SLOTS)],
                scratch.at[pl.ds(j * SLOTS, SLOTS)],
                sem_in.at[j]).wait()
        return c

    lax.fori_loop(0, TILE, wait_in, 0)

    lane = lax.broadcasted_iota(i32, (SLOTS, WORDS), 1)

    # 2) apply requests in order against the live bucket copies
    def body(j, c):
        valid = valid_ref[0, 0, j] != 0

        @pl.when(valid)
        def _process():
            base = brep_ref[0, 0, j] * SLOTS
            tile = scratch[pl.ds(base, SLOTS), :]  # [SLOTS, WORDS]
            klo, khi = klo_ref[0, 0, j], khi_ref[0, 0, j]

            def col(w):
                return tile[:, w]

            match = (col(W_KLO) == klo) & (col(W_KHI) == khi)
            # all reductions in i32: Mosaic's bool reduce_or/any proxy
            # lowers through float64, which has no scalar conversion
            # on TPU (observed on-chip 2026-08-01)
            found = _tsum8(match.astype(i32)) > 0
            empty = (col(W_KLO) == 0) & (col(W_KHI) == 0)
            # first empty slot: lowest slot index among empties (iota +
            # min — stable, deterministic, no float cumsum)
            slot_iota = lax.broadcasted_iota(i32, (SLOTS,), 0)
            first_idx = _tmin8(jnp.where(empty, slot_iota, i32(SLOTS)))
            first_empty = empty & (slot_iota == first_idx)
            has_empty = first_idx < i32(SLOTS)
            insert = (~found) & has_empty
            err = (~found) & (~has_empty)  # bucket full
            slot1h = jnp.where(found, match, first_empty)  # [SLOTS]

            def pick(w):
                """matched/claimed slot's word w as a scalar (0 for a
                fresh insert: empty slots hold zero words)."""
                return _tsum8(jnp.where(slot1h, col(w), i32(0)))

            # item state (insert reads the zeroed empty slot → fresh
            # fires below, matching the XLA path's post-insert read)
            it_rem, it_status, it_limit = (pick(W_REM), pick(W_STATUS),
                                           pick(W_LIMIT))
            it_tlo, it_thi = pick(W_TLO), pick(W_THI)
            it_xlo, it_xhi = pick(W_XLO), pick(W_XHI)
            it_elo, it_ehi = pick(W_ELO), pick(W_EHI)
            it_dlo, it_dhi = pick(W_DLO), pick(W_DHI)

            # request fields
            r_hits, r_lim = hits_ref[0, 0, j], lim_ref[0, 0, j]
            r_dlo, r_dhi = dlo_ref[0, 0, j], dhi_ref[0, 0, j]
            r_elo, r_ehi = elo_ref[0, 0, j], ehi_ref[0, 0, j]
            r_glo, r_ghi = glo_ref[0, 0, j], ghi_ref[0, 0, j]
            beh = beh_ref[0, 0, j]
            is_greg = (beh & _GREG) != 0
            reset = (beh & _RESET) != 0
            drain = (beh & _DRAIN) != 0

            # now = max(req.now, item.t)  (per-key monotonic clock)
            nhi0, nlo0 = nhi_ref[0, 0, j], nlo_ref[0, 0, j]
            use_req = _ge64(nhi0, nlo0, it_thi, it_tlo)
            nhi1, nlo1 = _sel64(use_req, nhi0, nlo0, it_thi, it_tlo)

            # fresh: empty/expired (alg change impossible: token-only)
            fresh = (~found) | _ge64(nhi1, nlo1, it_xhi, it_xlo)
            # token duration change → recompute expiry from item.t
            dur_change = (~fresh) & _neq64(r_dhi, r_dlo, it_dhi, it_dlo)
            ne_hi, ne_lo = _add64(it_thi, it_tlo, r_ehi, r_elo)
            ne_hi, ne_lo = _sel64(is_greg, r_ghi, r_glo, ne_hi, ne_lo)
            x1hi, x1lo = _sel64(dur_change, ne_hi, ne_lo, it_xhi, it_xlo)
            fresh = fresh | (dur_change & ~_ge64(x1hi, x1lo, nhi1, nlo1)
                             ) | (dur_change & _ge64(nhi1, nlo1, x1hi,
                                                     x1lo))
            # (exp1 <= now  ≡  now >= exp1; the first disjunct above is
            # exp1 < now via !(exp1 >= now) — keep both for exactness
            # with oracle's `exp1 <= now`)

            # adopt fresh or existing
            xf_hi, xf_lo = _add64(nhi1, nlo1, r_ehi, r_elo)
            xf_hi, xf_lo = _sel64(is_greg, r_ghi, r_glo, xf_hi, xf_lo)
            limit0 = _sel(fresh, r_lim, it_limit)
            rem0 = _sel(fresh, r_lim, it_rem)
            t_hi, t_lo = _sel64(fresh, nhi1, nlo1, it_thi, it_tlo)
            x_hi, x_lo = _sel64(fresh, xf_hi, xf_lo, x1hi, x1lo)
            status0 = _sel(fresh, i32(0), it_status)
            e_hi, e_lo = _sel64(fresh | dur_change, r_ehi, r_elo,
                                it_ehi, it_elo)

            # RESET_REMAINING on existing items
            reset_live = reset & (~fresh)
            rem0 = _sel(reset_live, r_lim, rem0)
            status0 = _sel(reset_live, i32(0), status0)
            limit_ar = _sel(reset_live, r_lim, limit0)

            # token limit change in place
            lim_change = r_lim != limit_ar
            rem_adj = jnp.clip(rem0 + r_lim - limit_ar, i32(0), r_lim)
            rem0 = _sel(lim_change, rem_adj, rem0)

            # hits
            is_query = r_hits == i32(0)
            ok = r_hits <= rem0
            rem2 = _sel((~is_query) & ok, rem0 - r_hits, rem0)
            rem2 = _sel((~is_query) & (~ok) & drain, i32(0), rem2)
            status1 = _sel(is_query, status0,
                           _sel(ok, i32(0), i32(1)))

            # write the slot back (unless the bucket was full)
            @pl.when(~err)
            def _writeback():
                sel = slot1h[:, None]

                def put(t, w, v):
                    return jnp.where(sel & (lane == w), v, t)

                nt = tile
                nt = put(nt, W_KLO, klo)
                nt = put(nt, W_KHI, khi)
                nt = put(nt, W_REM, rem2)
                nt = put(nt, W_STATUS, status1)
                nt = put(nt, W_LIMIT, r_lim)
                nt = put(nt, W_TLO, t_lo)
                nt = put(nt, W_THI, t_hi)
                nt = put(nt, W_XLO, x_lo)
                nt = put(nt, W_XHI, x_hi)
                nt = put(nt, W_ELO, e_lo)
                nt = put(nt, W_EHI, e_hi)
                nt = put(nt, W_DLO, r_dlo)
                nt = put(nt, W_DHI, r_dhi)
                scratch[pl.ds(base, SLOTS), :] = nt

            # outputs (err rows zeroed, as the XLA step masks them)
            dead = err
            st_o[0, 0, j] = _sel(dead, i32(0), status1)
            rem_o[0, 0, j] = _sel(dead, i32(0), rem2)
            rlo_o[0, 0, j] = _sel(dead, i32(0), x_lo)
            rhi_o[0, 0, j] = _sel(dead, i32(0), x_hi)
            lim_o[0, 0, j] = _sel(dead, i32(0), r_lim)
            flg_o[0, 0, j] = err.astype(i32) | (
                (insert & ~err).astype(i32) << 1)

        @pl.when(~valid)
        def _invalid():
            st_o[0, 0, j] = i32(0)
            rem_o[0, 0, j] = i32(0)
            rlo_o[0, 0, j] = i32(0)
            rhi_o[0, 0, j] = i32(0)
            lim_o[0, 0, j] = i32(0)
            flg_o[0, 0, j] = i32(0)

        return c

    lax.fori_loop(0, TILE, body, 0)

    # 3) scatter: write distinct live buckets back, then fence the tile
    # (the wait orders these stores before the NEXT tile's gathers)
    def issue_out(j, c):
        @pl.when(first_live(j))
        def _():
            pltpu.make_async_copy(
                scratch.at[pl.ds(j * SLOTS, SLOTS)],
                table_ref.at[pl.ds(bb_ref[0, 0, j], SLOTS)],
                sem_out.at[j]).start()
        return c

    lax.fori_loop(0, TILE, issue_out, 0)

    def wait_out(j, c):
        @pl.when(first_live(j))
        def _():
            pltpu.make_async_copy(
                scratch.at[pl.ds(j * SLOTS, SLOTS)],
                table_ref.at[pl.ds(bb_ref[0, 0, j], SLOTS)],
                sem_out.at[j]).wait()
        return c

    lax.fori_loop(0, TILE, wait_out, 0)


def _call_kernel(rows, cols, interpret: bool):
    """cols: 16 int32 arrays shaped [G, 1, TILE] (see _kernel order).

    The singleton middle axis is load-bearing on real Mosaic: a block's
    last two dims must be divisible by (8, 128) or equal the array's —
    a [G, TILE] array with (1, TILE) blocks violates that (observed
    on-chip 2026-08-01), while [G, 1, TILE] with (1, 1, TILE) blocks
    has last-two dims (1, TILE) == the array's, which is allowed."""
    G = cols[0].shape[0]
    smem_tile = pl.BlockSpec((1, 1, TILE), lambda i: (i, 0, 0),
                             memory_space=pltpu.SMEM)
    out_tile = pl.BlockSpec((1, 1, TILE), lambda i: (i, 0, 0),
                            memory_space=pltpu.SMEM)
    table_spec = pl.BlockSpec(memory_space=pl.ANY)
    o32 = jax.ShapeDtypeStruct((G, 1, TILE), jnp.int32)
    with jax.enable_x64(False):
        return pl.pallas_call(
            _kernel,
            grid=(G,),
            in_specs=[smem_tile] * 16 + [table_spec],
            out_specs=[table_spec] + [out_tile] * 6,
            out_shape=[jax.ShapeDtypeStruct(rows.shape, jnp.int32)]
            + [o32] * 6,
            input_output_aliases={16: 0},
            scratch_shapes=[
                pltpu.VMEM((TILE * SLOTS, WORDS), jnp.int32),
                pltpu.SemaphoreType.DMA((TILE,)),
                pltpu.SemaphoreType.DMA((TILE,)),
            ],
            interpret=interpret,
        )(*cols, rows)


@partial(jax.jit, static_argnames=("interpret",), donate_argnums=(0,))
def decide_batch_pallas(table: PallasTable, batch: RequestBatch, now_ms,
                        *, interpret: bool = False
                        ) -> tuple[PallasTable, StepOutput]:
    """Apply one TOKEN_BUCKET batch to the Pallas table.

    Same contract as core/step.py › decide_batch for batches inside
    the kernel's domain (``pallas_qualifies``) — the parity tests
    assert identical decisions on shared request streams.  The table
    buffer is donated (aliased in/out) like decide_batch_donated.
    """
    i32, i64 = jnp.int32, jnp.int64
    cap = table.rows.shape[0]
    n_buckets = cap // SLOTS
    B = batch.key.shape[0]
    G = -(-B // TILE)
    pad = G * TILE - B

    now = jnp.asarray(now_ms, i64)
    if batch.now is None:
        now_col = jnp.full((B,), now, i64)
    else:
        now_col = jnp.where(jnp.asarray(batch.now, i64) > 0,
                            jnp.asarray(batch.now, i64), now)

    key = batch.key.astype(jnp.uint64)
    valid = (batch.valid & (key != 0)).astype(i32)
    bucket = (key & jnp.uint64(n_buckets - 1)).astype(i32) * SLOTS

    def pad_to(x, fill=0):
        return jnp.pad(x, (0, pad), constant_values=fill) if pad else x

    khi, klo = _split64(key)
    dhi, dlo = _split64(batch.duration.astype(i64))
    ehi, elo = _split64(batch.eff_ms.astype(i64))
    ghi, glo = _split64(batch.greg_end.astype(i64))
    nhi, nlo = _split64(now_col)

    bb = pad_to(bucket)
    cols1d = [
        bb,
        klo, khi,
        batch.hits.astype(i32), batch.limit.astype(i32),
        dlo, dhi, elo, ehi, glo, ghi,
        batch.behavior.astype(i32), nlo, nhi, valid,
    ]
    cols1d = [bb] + [pad_to(c) for c in cols1d[1:]]

    # tile-relative first occurrence of each bucket (dedup map): the
    # kernel's serial loop routes same-bucket requests to one VMEM
    # copy.  Invalid rows get a UNIQUE sentinel so they can never
    # become a bucket's representative: first_live gates the DMA on
    # valid, so an invalid representative would starve a later valid
    # same-bucket request of its gather/writeback entirely.
    bt = bb.reshape(G, TILE)
    iota = jnp.arange(G * TILE, dtype=jnp.int64).reshape(G, TILE)
    vpad = pad_to(valid).reshape(G, TILE).astype(bool)
    rep_key = jnp.where(vpad, bt.astype(jnp.int64), -1 - iota)
    eq = rep_key[:, :, None] == rep_key[:, None, :]
    brep = jnp.argmax(eq, axis=-1).astype(i32)  # first True per row

    # [G, 1, TILE]: the singleton axis satisfies Mosaic's block-shape
    # rule (see _call_kernel)
    cols = [c.reshape(G, 1, TILE) for c in [bt, brep] + cols1d[1:]]
    rows2, st, rem, rlo, rhi, lim, flg = _call_kernel(
        table.rows, cols, interpret)

    def unpad(x):
        return x.reshape(-1)[:B]

    st = unpad(st)
    flg = unpad(flg)
    err = (flg & 1) != 0
    vb = valid.astype(bool)[:B] if pad else valid.astype(bool)
    live = vb & (~err)
    status = jnp.where(live, st, 0)
    remaining = jnp.where(live, unpad(rem).astype(i64), 0)
    reset_time = jnp.where(
        live, _join64(unpad(rhi), unpad(rlo), i64), 0)
    limit_out = jnp.where(live, unpad(lim).astype(i64), 0)
    over = (live & (status == 1)).sum(dtype=i64)
    inserts = ((flg >> 1) & 1).sum(dtype=i64)
    return PallasTable(rows=rows2), StepOutput(
        status=status.astype(i32), remaining=remaining,
        reset_time=reset_time, limit=limit_out,
        err=vb & err, over_count=over, insert_count=inserts)

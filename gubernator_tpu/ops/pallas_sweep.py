"""Pallas TPU kernel: fused expired-row sweep + live-row count.

The decision step itself is deliberately plain XLA (ARCHITECTURE.md §2:
scattered 72-byte row updates don't map onto TPU DMA, while XLA's dense
fusion already exceeds the perf target 11×).  The sweep is the opposite
case — a pure dense streaming pass over the table — which is exactly
the memory-bound shape Pallas is for, and fusing the occupancy count
into the same pass halves its HBM traffic vs. sweep-then-count.

TPU Mosaic has no 64-bit vector lanes, so the int64/uint64 columns are
bit-split into (hi, lo) int32 pairs on the way in and recombined on the
way out; the expiry comparison is done on the split words (signed hi,
unsigned lo).  Set ``interpret=True`` (or run on CPU) for the
reference-interpreter path used by tests.

Usage: ``sweep_expired_pallas(state, now_ms)`` — a drop-in equivalent
of core/table.py › sweep_expired that also returns the live-row count.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..core.table import TableState

LANES = 128
BLK = 8  # sublanes per block → (8, 128) int32 tiles


def _split64(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """int64/uint64 [n] → (hi int32, lo int32) bit halves."""
    u = x.astype(jnp.uint64)
    hi = (u >> jnp.uint64(32)).astype(jnp.uint32).astype(jnp.int32)
    lo = u.astype(jnp.uint32).astype(jnp.int32)
    return hi, lo


def _join64(hi: jax.Array, lo: jax.Array, dtype) -> jax.Array:
    u = (hi.astype(jnp.uint32).astype(jnp.uint64) << jnp.uint64(32)) | \
        lo.astype(jnp.uint32).astype(jnp.uint64)
    return u.astype(dtype)


def _sweep_kernel(now_ref, khi_ref, klo_ref, ehi_ref, elo_ref,
                  khi_out, klo_out, ehi_out, elo_out, live_ref):
    """One (BLK, LANES) tile: zero dead rows, accumulate live count."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        # pinned dtype: a bare 0 is weakly-typed and becomes an i64
        # constant under x64, which Mosaic refuses to store/return
        live_ref[0] = jnp.int32(0)

    now_hi, now_lo = now_ref[0], now_ref[1]
    ehi, elo = ehi_ref[:], elo_ref[:]
    # expire_at <= now on split words: signed hi compare, unsigned lo.
    # (lo words are reinterpreted-int32; flipping the sign bit makes
    # int32 compare order match the unsigned order.)
    flip = jnp.int32(-2147483648)
    expired = (ehi < now_hi) | ((ehi == now_hi) &
                                (elo ^ flip <= now_lo ^ flip))
    khi, klo = khi_ref[:], klo_ref[:]
    empty = (khi == 0) & (klo == 0)
    zero = jnp.zeros_like(khi)
    # zero exactly what sweep_expired zeroes (expired rows only — an
    # empty row's stale expire_at is never read, and bit-equality with
    # the XLA sweep is what the parity tests assert)
    khi_out[:] = jnp.where(expired, zero, khi)
    klo_out[:] = jnp.where(expired, zero, klo)
    ehi_out[:] = jnp.where(expired, zero, ehi)
    elo_out[:] = jnp.where(expired, zero, elo)
    # count in float32: with x64 enabled, jnp.sum on int32 routes through
    # an int64 accumulator (numpy promotion) even when dtype=int32 is
    # passed, and Mosaic cannot lower 64-bit; f32 is promotion-stable and
    # exact here (a tile holds BLK×LANES = 1024 ≪ 2^24 elements)
    live = ~(expired | empty)
    live_ref[0] += jnp.sum(live.astype(jnp.float32)).astype(jnp.int32)


def _sweep_2d(khi, klo, ehi, elo, now_hi_lo, *, interpret: bool):
    rows = khi.shape[0]
    grid = (rows // BLK,)
    tile = pl.BlockSpec((BLK, LANES), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    out_shape = jax.ShapeDtypeStruct((rows, LANES), jnp.int32)
    # x64 off while tracing the kernel: every operand is already int32,
    # but under x64 the BlockSpec index_map's literals trace as i64
    # scalars and Mosaic fails to legalize the index function's return
    with jax.experimental.enable_x64(False):
        return pl.pallas_call(
            _sweep_kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),  # now (2,) scalar
                tile, tile, tile, tile,
            ],
            out_specs=[tile, tile, tile, tile,
                       pl.BlockSpec(memory_space=pltpu.SMEM)],
            out_shape=[out_shape, out_shape, out_shape, out_shape,
                       jax.ShapeDtypeStruct((1,), jnp.int32)],
            interpret=interpret,
        )(now_hi_lo, khi, klo, ehi, elo)


@partial(jax.jit, static_argnames=("interpret",))
def sweep_expired_pallas(state: TableState, now_ms, *,
                         interpret: bool = False
                         ) -> tuple[TableState, jax.Array]:
    """Fused sweep + occupancy: (new state, live-row count).

    Semantically identical to core/table.py › sweep_expired (dead rows
    get key=0 AND expire_at=0 so later occupants are unconditionally
    fresh), plus the live count from the same pass.
    """
    cap = state.key.shape[0]
    if cap % (BLK * LANES):
        raise ValueError(f"capacity {cap} not a multiple of {BLK * LANES}")
    shape2d = (cap // LANES, LANES)

    khi, klo = _split64(state.key)
    ehi, elo = _split64(state.expire_at)
    nhi, nlo = _split64(jnp.asarray(now_ms, jnp.int64)[None])
    now_hi_lo = jnp.concatenate([nhi, nlo])

    khi2, klo2, ehi2, elo2, live = _sweep_2d(
        khi.reshape(shape2d), klo.reshape(shape2d),
        ehi.reshape(shape2d), elo.reshape(shape2d),
        now_hi_lo, interpret=interpret)

    new_key = _join64(khi2.reshape(-1), klo2.reshape(-1), jnp.uint64)
    new_exp = _join64(ehi2.reshape(-1), elo2.reshape(-1), jnp.int64)
    return state._replace(key=new_key, expire_at=new_exp), live[0]

"""Python face of the native extension (raises ImportError if unbuilt).

hashing.py imports this lazily and falls back to pure numpy; both
return RAW FNV-1a 64 values — the avalanche finalizer is applied by
hashing.mix64_np either way.
"""
from __future__ import annotations

from typing import Sequence

import numpy as np

from . import _native  # ImportError here means: run `make native`


def hash_keys(keys: Sequence[str]) -> np.ndarray:
    """Raw FNV-1a64 of each key string → uint64[n]."""
    buf, n = _native.fnv1a64_batch(keys)
    return np.frombuffer(buf, dtype="<u8", count=n).copy()


def hash_pairs(names: Sequence[str], unique_keys: Sequence[str]) -> np.ndarray:
    """Raw FNV-1a64 of name + "_" + unique_key without string joins."""
    buf, n = _native.fnv1a64_pair_batch(names, unique_keys)
    return np.frombuffer(buf, dtype="<u8", count=n).copy()

"""Python face of the native extension (raises ImportError if unbuilt).

hashing.py imports this lazily and falls back to pure numpy; both
return RAW FNV-1a 64 values — the avalanche finalizer is applied by
hashing.mix64_np either way.
"""
from __future__ import annotations

from typing import Sequence

import numpy as np

from . import _native  # ImportError here means: run `make native`


def hash_keys(keys: Sequence[str]) -> np.ndarray:
    """Raw FNV-1a64 of each key string → uint64[n]."""
    buf, n = _native.fnv1a64_batch(keys)
    return np.frombuffer(buf, dtype="<u8", count=n).copy()


def hash_pairs(names: Sequence[str], unique_keys: Sequence[str]) -> np.ndarray:
    """Raw FNV-1a64 of name + "_" + unique_key without string joins."""
    buf, n = _native.fnv1a64_pair_batch(names, unique_keys)
    return np.frombuffer(buf, dtype="<u8", count=n).copy()


def parse_get_rate_limits(data: bytes):
    """GetRateLimitsReq wire bytes → packed column dict, or None when the
    message needs the pb2 fallback (metadata, empty name/key, unknown
    fields).  ``khash_raw`` is RAW FNV-1a64 — apply hashing.mix64_np."""
    r = _native.parse_get_rate_limits(data)
    if r is None:
        return None
    (n, kh, hits, limit, dur, alg, beh, burst, beh_or, toff, tlen,
     created) = r
    return {
        "n": n,
        "khash_raw": np.frombuffer(kh, "<u8", count=n),
        "hits": np.frombuffer(hits, "<i8", count=n),
        "limit": np.frombuffer(limit, "<i8", count=n),
        "duration": np.frombuffer(dur, "<i8", count=n),
        "algorithm": np.frombuffer(alg, "<i4", count=n),
        "behavior": np.frombuffer(beh, "<i4", count=n),
        "burst": np.frombuffer(burst, "<i8", count=n),
        "behavior_or": int(beh_or),
        # per-request TLV ranges in the input bytes: a clustered daemon
        # forwards owner sub-batches by slicing these verbatim (peer
        # wire framing is byte-compatible, field 1 on both messages)
        "tlv_off": np.frombuffer(toff, "<u8", count=n),
        "tlv_len": np.frombuffer(tlen, "<u8", count=n),
        # caller's accepted-at clock (field 10, 0 = unset): forwarded
        # rows apply at THIS time base, not the owner's wall clock
        "created_at": np.frombuffer(created, "<i8", count=n),
    }


def stamp_req_tlvs(data: bytes, tlv_off: np.ndarray, tlv_len: np.ndarray,
                   created_at: np.ndarray, stamp_ms: int) -> bytes:
    """Join the given request TLV slices of ``data``, appending
    ``created_at = stamp_ms`` (field 10) to every slice that doesn't
    already carry a caller stamp (created_at[i] == 0).  The forward
    hop's bulk caller-clock stamp — see wire.tlv_with_created for the
    one-slice codec-free twin and types.RateLimitRequest.created_at
    for why the stamp exists."""
    return _native.stamp_req_tlvs(
        data,
        np.ascontiguousarray(tlv_off, "<i8"),
        np.ascontiguousarray(tlv_len, "<i8"),
        np.ascontiguousarray(created_at, "<i8"),
        int(stamp_ms))


def count_req_items(data: bytes):
    """Top-level-only TLV count of a GetRateLimitsReq /
    GetPeerRateLimitsReq, or None on framing the fast lane doesn't
    model.  Lets the fused ingest size its wave bucket (and lease the
    packed upload buffers) before the single full parse."""
    return _native.count_req_items(data)


def pack_wire_wave(data: bytes, now_ms: int, a64: np.ndarray,
                   a32: np.ndarray):
    """Fused wire ingest: parse + validate + clamp + key-hash (FNV-1a64
    → mix64, zero-remapped) one request message and write the rows
    straight into a leased packed wave-upload pair (``a64`` [8, m] i64,
    ``a32`` [3, m] i32 — parallel/sharded.py › PACK64/PACK32 layout,
    zeroed by the pool; only the eff_ms padding row is re-filled here).

    Returns None (caller releases the lease and falls back to the
    classic numpy pack) for anything the lane doesn't model: pb2
    framing, n > m, or any DURATION_IS_GREGORIAN row.  Otherwise
    (n, khash u64[n] MIXED, khash_raw u64[n], behavior_or, tlv_off,
    tlv_len).  Clamp bounds are passed from types.py so the constants
    have one home; clamp arithmetic is pinned bit-identical to
    core/batch.py › pack_columns by tests/test_native.py."""
    from ..types import DURATION_MAX, EFF_MAX, TD_BOUND, VALUE_MAX

    m = a64.shape[1]
    r = _native.pack_wire_wave(data, int(now_ms), a64, a32, m,
                               DURATION_MAX, VALUE_MAX, EFF_MAX,
                               TD_BOUND)
    if r is None:
        return None
    n, kh, kr, beh_or, toff, tlen = r
    return (n,
            np.frombuffer(kh, "<u8", count=n),
            np.frombuffer(kr, "<u8", count=n),
            int(beh_or),
            np.frombuffer(toff, "<u8", count=n),
            np.frombuffer(tlen, "<u8", count=n))


def split_resp_items(data: bytes):
    """RateLimitResp-list wire bytes → (tlv_off, tlv_len, status) per
    item, or None on malformed input (caller falls back to pb2).  Works
    for GetRateLimitsResp and GetPeerRateLimitsResp alike (both carry
    the repeated submessage on field 1)."""
    r = _native.split_resp_items(data)
    if r is None:
        return None
    n, toff, tlen, st = r
    return (np.frombuffer(toff, "<u8", count=n),
            np.frombuffer(tlen, "<u8", count=n),
            np.frombuffer(st, "<i4", count=n))


def build_rate_limit_resps(status: np.ndarray, limit: np.ndarray,
                           remaining: np.ndarray, reset_time: np.ndarray,
                           errors=None) -> bytes:
    """Packed response columns → GetRateLimitsResp wire bytes.
    ``errors``: optional sequence of str/None per response."""
    return _native.build_rate_limit_resps(
        np.ascontiguousarray(status, "<i4"),
        np.ascontiguousarray(limit, "<i8"),
        np.ascontiguousarray(remaining, "<i8"),
        np.ascontiguousarray(reset_time, "<i8"),
        errors if errors is not None else None)


def build_responses_from_columns(result_cols, row_lo: int, row_hi: int,
                                 errors=None) -> bytes:
    """Rows [row_lo, row_hi) of a wave's SHARED result columns →
    GetRateLimitsResp wire bytes, with zero per-request Python objects
    and zero intermediate slices — the caller-thread response-build
    lane of the overlapped wave pipeline (dispatcher.ResultView).

    ``result_cols`` is the dispatcher/engine 5-tuple (status i32,
    limit i64, remaining i64, reset i64, table_full bool); the bool
    column is ignored here (the caller folds it into ``errors``).
    ``errors``: optional sequence of str/None indexed relative to
    ``row_lo``."""
    st, lim, rem, rst = result_cols[:4]
    return _native.build_responses_from_columns(
        np.ascontiguousarray(st, "<i4"),
        np.ascontiguousarray(lim, "<i8"),
        np.ascontiguousarray(rem, "<i8"),
        np.ascontiguousarray(rst, "<i8"),
        int(row_lo), int(row_hi),
        errors if errors is not None else None)

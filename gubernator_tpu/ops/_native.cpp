// Native host ops for gubernator-tpu.
//
// The reference is pure Go (SURVEY.md §2.2) so there is no reference
// native component to mirror; this extension exists because the
// host-side request-ingest path (string hashing while the device runs
// the decision step) is the framework's CPU bottleneck, the role Go's
// compiled hashmap/hash code plays in the reference.
//
// Exposed primitives (wrapped by ops/native.py):
//   fnv1a64_batch([str|bytes, ...]) -> (bytes, n)   raw FNV-1a 64
//   fnv1a64_pair_batch(names, keys) -> (bytes, n)   hash(name + "_" + key)
//   parse_get_rate_limits(bytes) -> None | tuple    wire -> packed columns
//   build_rate_limit_resps(...) -> bytes            packed columns -> wire
//   build_responses_from_columns(...) -> bytes      shared-column rows
//                                                   [lo, hi) -> wire
//
// The avalanche finalizer stays in Python/numpy (hashing.mix64_np) so
// there is exactly one source of truth for it.
//
// The parse/build pair is the service-path fast lane: a
// GetRateLimitsReq wire message is decoded straight into fixed-dtype
// column buffers (key hash, hits, limit, duration, algorithm, behavior,
// burst) without constructing any per-request Python object, and the
// response columns from the device step are serialized straight back to
// a GetRateLimitsResp.  Anything the fast lane doesn't model (metadata,
// empty name/key, unknown fields) makes parse return None and the
// caller falls back to the pb2 path — identical behavior, just slower.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <vector>

static const uint64_t FNV_OFFSET = 0xCBF29CE484222325ULL;
static const uint64_t FNV_PRIME = 0x100000001B3ULL;

static inline uint64_t fnv1a64(const unsigned char* p, Py_ssize_t n,
                               uint64_t h = FNV_OFFSET) {
  for (Py_ssize_t i = 0; i < n; i++) {
    h ^= (uint64_t)p[i];
    h *= FNV_PRIME;
  }
  return h;
}

// Borrow a UTF-8 view of a str/bytes item.  Returns false on error.
static inline bool utf8_view(PyObject* obj, const unsigned char** p,
                             Py_ssize_t* n) {
  if (PyUnicode_Check(obj)) {
    const char* s = PyUnicode_AsUTF8AndSize(obj, n);
    if (s == nullptr) return false;
    *p = (const unsigned char*)s;
    return true;
  }
  if (PyBytes_Check(obj)) {
    *p = (const unsigned char*)PyBytes_AS_STRING(obj);
    *n = PyBytes_GET_SIZE(obj);
    return true;
  }
  PyErr_SetString(PyExc_TypeError, "expected str or bytes");
  return false;
}

static PyObject* fnv1a64_batch(PyObject*, PyObject* arg) {
  PyObject* seq = PySequence_Fast(arg, "expected a sequence");
  if (seq == nullptr) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  PyObject* out = PyBytes_FromStringAndSize(nullptr, n * 8);
  if (out == nullptr) {
    Py_DECREF(seq);
    return nullptr;
  }
  uint64_t* dst = (uint64_t*)PyBytes_AS_STRING(out);
  for (Py_ssize_t i = 0; i < n; i++) {
    const unsigned char* p;
    Py_ssize_t len;
    if (!utf8_view(PySequence_Fast_GET_ITEM(seq, i), &p, &len)) {
      Py_DECREF(seq);
      Py_DECREF(out);
      return nullptr;
    }
    dst[i] = fnv1a64(p, len);
  }
  Py_DECREF(seq);
  return Py_BuildValue("(Nn)", out, n);
}

// hash(name + "_" + unique_key) without building the joined string —
// the exact key-identity hash of the request path.
static PyObject* fnv1a64_pair_batch(PyObject*, PyObject* args) {
  PyObject *names_arg, *keys_arg;
  if (!PyArg_ParseTuple(args, "OO", &names_arg, &keys_arg)) return nullptr;
  PyObject* names = PySequence_Fast(names_arg, "expected a sequence");
  if (names == nullptr) return nullptr;
  PyObject* keys = PySequence_Fast(keys_arg, "expected a sequence");
  if (keys == nullptr) {
    Py_DECREF(names);
    return nullptr;
  }
  Py_ssize_t n = PySequence_Fast_GET_SIZE(names);
  if (PySequence_Fast_GET_SIZE(keys) != n) {
    Py_DECREF(names);
    Py_DECREF(keys);
    PyErr_SetString(PyExc_ValueError, "length mismatch");
    return nullptr;
  }
  PyObject* out = PyBytes_FromStringAndSize(nullptr, n * 8);
  if (out == nullptr) {
    Py_DECREF(names);
    Py_DECREF(keys);
    return nullptr;
  }
  uint64_t* dst = (uint64_t*)PyBytes_AS_STRING(out);
  const unsigned char underscore = '_';
  for (Py_ssize_t i = 0; i < n; i++) {
    const unsigned char *pn, *pk;
    Py_ssize_t ln, lk;
    if (!utf8_view(PySequence_Fast_GET_ITEM(names, i), &pn, &ln) ||
        !utf8_view(PySequence_Fast_GET_ITEM(keys, i), &pk, &lk)) {
      Py_DECREF(names);
      Py_DECREF(keys);
      Py_DECREF(out);
      return nullptr;
    }
    uint64_t h = fnv1a64(pn, ln);
    h = fnv1a64(&underscore, 1, h);
    dst[i] = fnv1a64(pk, lk, h);
  }
  Py_DECREF(names);
  Py_DECREF(keys);
  return Py_BuildValue("(Nn)", out, n);
}

// ---------------------------------------------------------------------------
// Protobuf wire fast lane (hand-rolled proto3 varint/length-delimited codec;
// field numbers from proto/gubernator.proto — the schema is frozen by the
// reference contract, SURVEY.md §2.4).

// Strict UTF-8 validation (RFC 3629: no surrogates, no overlongs, max
// U+10FFFF) — mirrors protobuf's string-field check so the fast lane
// accepts exactly what pb2 accepts.
static inline bool valid_utf8(const uint8_t* p, uint64_t n) {
  const uint8_t* end = p + n;
  while (p < end) {
    uint8_t c = *p;
    if (c < 0x80) {
      p++;
    } else if ((c & 0xE0) == 0xC0) {
      if (end - p < 2 || (p[1] & 0xC0) != 0x80 || c < 0xC2) return false;
      p += 2;
    } else if ((c & 0xF0) == 0xE0) {
      if (end - p < 3 || (p[1] & 0xC0) != 0x80 || (p[2] & 0xC0) != 0x80)
        return false;
      if (c == 0xE0 && p[1] < 0xA0) return false;          // overlong
      if (c == 0xED && p[1] >= 0xA0) return false;         // surrogate
      p += 3;
    } else if ((c & 0xF8) == 0xF0) {
      if (end - p < 4 || (p[1] & 0xC0) != 0x80 || (p[2] & 0xC0) != 0x80 ||
          (p[3] & 0xC0) != 0x80)
        return false;
      if (c == 0xF0 && p[1] < 0x90) return false;          // overlong
      if (c > 0xF4 || (c == 0xF4 && p[1] >= 0x90)) return false;  // >10FFFF
      p += 4;
    } else {
      return false;
    }
  }
  return true;
}

static inline bool read_varint(const uint8_t** p, const uint8_t* end,
                               uint64_t* out) {
  uint64_t v = 0;
  int shift = 0;
  const uint8_t* q = *p;
  while (q < end && shift < 64) {
    uint8_t b = *q++;
    v |= (uint64_t)(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *p = q;
      *out = v;
      return true;
    }
    shift += 7;
  }
  return false;
}

// parse_get_rate_limits(bytes) ->
//   None                                  (needs the pb2 fallback path)
// | (n, khash_raw u64le, hits i64le, limit i64le, duration i64le,
//    algorithm i32le, behavior i32le, burst i64le, behavior_or,
//    tlv_off u64le, tlv_len u64le, created_at i64le)
// created_at (field 10, 0 = unset) is the caller's accepted-at clock,
// stamped by the forward hop (stamp_req_tlvs) so the owner applies the
// request at the caller's time base — mixing bases resets buckets and
// silently drops debits (the cold-key conservation loss).
// tlv_off/tlv_len delimit each complete `requests` TLV (tag byte through
// payload end) in the input: a clustered daemon forwards a sub-batch to
// its owner by concatenating those slices verbatim — the peer wire's
// GetPeerRateLimitsReq.requests uses the same field number (1), so the
// framing is byte-compatible (proto/peers.proto).
static PyObject* parse_get_rate_limits(PyObject*, PyObject* arg) {
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return nullptr;
  const uint8_t* base = (const uint8_t*)view.buf;
  const uint8_t* p = base;
  const uint8_t* end = p + view.len;
  std::vector<uint64_t> khash;
  std::vector<int64_t> hits, limit, duration, burst, created;
  std::vector<int32_t> alg, beh;
  std::vector<uint64_t> tlv_off, tlv_len;
  khash.reserve(64);
  uint64_t beh_or = 0;
  bool fallback = false;
  while (p < end) {
    const uint8_t* tlv_start = p;
    uint64_t tag;
    if (!read_varint(&p, end, &tag) || tag != 0x0A) {  // field 1, LEN
      fallback = true;
      break;
    }
    uint64_t len;
    if (!read_varint(&p, end, &len) || (uint64_t)(end - p) < len) {
      fallback = true;
      break;
    }
    const uint8_t* q = p;
    const uint8_t* qend = p + len;
    p = qend;
    const uint8_t* name_p = nullptr;
    const uint8_t* key_p = nullptr;
    uint64_t name_len = 0, key_len = 0;
    int64_t f_hits = 0, f_limit = 0, f_dur = 0, f_burst = 0;
    int64_t f_created = 0;
    int32_t f_alg = 0, f_beh = 0;
    while (q < qend && !fallback) {
      uint64_t t;
      if (!read_varint(&q, qend, &t)) {
        fallback = true;
        break;
      }
      uint64_t field = t >> 3, wt = t & 7;
      if (wt == 2) {
        uint64_t l;
        if (!read_varint(&q, qend, &l) || (uint64_t)(qend - q) < l) {
          fallback = true;
          break;
        }
        if (field == 1) {
          name_p = q;
          name_len = l;
        } else if (field == 2) {
          key_p = q;
          key_len = l;
        } else {  // metadata (9) or unknown: not modeled here
          fallback = true;
          break;
        }
        q += l;
      } else if (wt == 0) {
        uint64_t v;
        if (!read_varint(&q, qend, &v)) {
          fallback = true;
          break;
        }
        switch (field) {
          case 3: f_hits = (int64_t)v; break;
          case 4: f_limit = (int64_t)v; break;
          case 5: f_dur = (int64_t)v; break;
          case 6: f_alg = (int32_t)v; break;
          case 7: f_beh = (int32_t)v; break;
          case 8: f_burst = (int64_t)v; break;
          case 10: f_created = (int64_t)v; break;
          default: fallback = true;
        }
      } else {
        fallback = true;
      }
    }
    if (fallback) break;
    if (name_p == nullptr || name_len == 0 || key_p == nullptr ||
        key_len == 0 ||
        // pb2 rejects invalid UTF-8 in string fields with DecodeError;
        // the fast lane must not accept what the fallback path rejects
        !valid_utf8(name_p, name_len) || !valid_utf8(key_p, key_len)) {
      // empty name/unique_key produce per-request error responses on
      // the pb2 path; keep that logic in one place
      fallback = true;
      break;
    }
    uint64_t h = fnv1a64(name_p, (Py_ssize_t)name_len);
    const unsigned char us = '_';
    h = fnv1a64(&us, 1, h);
    h = fnv1a64(key_p, (Py_ssize_t)key_len, h);
    khash.push_back(h);
    hits.push_back(f_hits);
    limit.push_back(f_limit);
    duration.push_back(f_dur);
    burst.push_back(f_burst);
    created.push_back(f_created);
    alg.push_back(f_alg);
    beh.push_back(f_beh);
    beh_or |= (uint64_t)(uint32_t)f_beh;
    tlv_off.push_back((uint64_t)(tlv_start - base));
    tlv_len.push_back((uint64_t)(qend - tlv_start));
  }
  PyBuffer_Release(&view);
  if (fallback) Py_RETURN_NONE;
  Py_ssize_t n = (Py_ssize_t)khash.size();
  // empty vectors may have null data(); Py_BuildValue "y#" would turn
  // a null pointer into None — hand it a valid empty buffer instead
  static const char kEmpty[1] = {0};
  const char* kh_p = n ? (const char*)khash.data() : kEmpty;
  const char* hi_p = n ? (const char*)hits.data() : kEmpty;
  const char* li_p = n ? (const char*)limit.data() : kEmpty;
  const char* du_p = n ? (const char*)duration.data() : kEmpty;
  const char* al_p = n ? (const char*)alg.data() : kEmpty;
  const char* be_p = n ? (const char*)beh.data() : kEmpty;
  const char* bu_p = n ? (const char*)burst.data() : kEmpty;
  const char* to_p = n ? (const char*)tlv_off.data() : kEmpty;
  const char* tl_p = n ? (const char*)tlv_len.data() : kEmpty;
  const char* cr_p = n ? (const char*)created.data() : kEmpty;
  PyObject* out = Py_BuildValue(
      "(ny#y#y#y#y#y#y#Ky#y#y#)", n, kh_p, n * 8, hi_p, n * 8, li_p,
      n * 8, du_p, n * 8, al_p, n * 4, be_p, n * 4, bu_p, n * 8,
      (unsigned long long)beh_or, to_p, n * 8, tl_p, n * 8, cr_p,
      n * 8);
  return out;
}

// stamp_req_tlvs(data, tlv_off i64[], tlv_len i64[], created i64[],
//                stamp_ms) -> bytes
// The forward hop's bulk TLV join: concatenates the given request TLV
// slices of `data`, appending `created_at = stamp_ms` (field 10) to
// every slice whose parsed created_at is 0 — so a forwarded request
// applies at the CALLER's clock on the owner (a slice that already
// carries a caller stamp forwards verbatim: first hop wins).  The
// arrays are pre-gathered by the caller (numpy fancy indexing), one
// entry per forwarded row.
static PyObject* stamp_req_tlvs(PyObject*, PyObject* args) {
  Py_buffer view, boff, blen, bcreated;
  long long stamp_ms;
  if (!PyArg_ParseTuple(args, "y*y*y*y*L", &view, &boff, &blen,
                        &bcreated, &stamp_ms))
    return nullptr;
  Py_ssize_t n = boff.len / (Py_ssize_t)sizeof(int64_t);
  const int64_t* toff = (const int64_t*)boff.buf;
  const int64_t* tlen = (const int64_t*)blen.buf;
  const int64_t* created = (const int64_t*)bcreated.buf;
  const uint8_t* base = (const uint8_t*)view.buf;
  bool bad = blen.len != boff.len || bcreated.len != boff.len;
  // field-10 varint suffix: tag 0x50 + up to 10 payload bytes
  uint8_t suffix[11];
  Py_ssize_t suffix_len = 0;
  suffix[suffix_len++] = 0x50;
  uint64_t v = (uint64_t)stamp_ms;
  while (v >= 0x80) {
    suffix[suffix_len++] = (uint8_t)((v & 0x7F) | 0x80);
    v >>= 7;
  }
  suffix[suffix_len++] = (uint8_t)v;
  std::vector<uint8_t> out;
  out.reserve((size_t)view.len + (size_t)n * (size_t)(suffix_len + 3));
  for (Py_ssize_t i = 0; i < n && !bad; i++) {
    const uint8_t* tlv = base + toff[i];
    const uint8_t* tend = tlv + tlen[i];
    if (toff[i] < 0 || tlen[i] < 2 || toff[i] + tlen[i] > view.len ||
        tlv[0] != 0x0A) {
      bad = true;
      break;
    }
    if (created[i] != 0) {  // caller already stamped: verbatim
      out.insert(out.end(), tlv, tend);
      continue;
    }
    const uint8_t* p = tlv + 1;
    uint64_t plen;
    if (!read_varint(&p, tend, &plen) ||
        (uint64_t)(tend - p) != plen) {
      bad = true;
      break;
    }
    uint64_t new_len = plen + (uint64_t)suffix_len;
    out.push_back(0x0A);
    uint64_t lv = new_len;
    while (lv >= 0x80) {
      out.push_back((uint8_t)((lv & 0x7F) | 0x80));
      lv >>= 7;
    }
    out.push_back((uint8_t)lv);
    out.insert(out.end(), p, tend);
    out.insert(out.end(), suffix, suffix + suffix_len);
  }
  PyBuffer_Release(&view);
  PyBuffer_Release(&boff);
  PyBuffer_Release(&blen);
  PyBuffer_Release(&bcreated);
  if (bad) {
    PyErr_SetString(PyExc_ValueError, "malformed request TLV slice");
    return nullptr;
  }
  return PyBytes_FromStringAndSize(
      out.empty() ? "" : (const char*)out.data(),
      (Py_ssize_t)out.size());
}

// count_req_items(bytes) -> n | None
// Top-level-only scan of a GetRateLimitsReq / GetPeerRateLimitsReq:
// counts the repeated field-1 TLVs without touching their payloads, so
// the fused ingest below can size its wave bucket (and lease the packed
// upload buffers) before the single full parse.  None on any framing
// the fast lane doesn't model (caller falls back to pb2).
static PyObject* count_req_items(PyObject*, PyObject* arg) {
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return nullptr;
  const uint8_t* p = (const uint8_t*)view.buf;
  const uint8_t* end = p + view.len;
  Py_ssize_t n = 0;
  bool fallback = false;
  while (p < end) {
    uint64_t tag, len;
    if (!read_varint(&p, end, &tag) || tag != 0x0A ||
        !read_varint(&p, end, &len) || (uint64_t)(end - p) < len) {
      fallback = true;
      break;
    }
    p += len;
    n++;
  }
  PyBuffer_Release(&view);
  if (fallback) Py_RETURN_NONE;
  return PyLong_FromSsize_t(n);
}

// splitmix64 avalanche finalizer — MUST stay bit-identical to
// hashing.mix64_np / hashing.mix64 (tests/test_native.py pins the
// parity); the fused ingest applies it inline so the packed key column
// needs no second numpy pass.
static inline uint64_t mix64(uint64_t x) {
  x ^= x >> 30;
  x *= 0xBF58476D1CE4E5B9ULL;
  x ^= x >> 27;
  x *= 0x94D049BB133111EBULL;
  x ^= x >> 31;
  return x;
}

// pack_wire_wave(data, now_ms, a64, a32, m,
//                duration_max, value_max, eff_max, td_bound) ->
//   None                              (needs the classic/pb2 path)
// | (n, khash u64le, khash_raw u64le, behavior_or,
//    tlv_off u64le, tlv_len u64le)
//
// The fused wire ingest: one pass over a GetRateLimitsReq /
// GetPeerRateLimitsReq that parses, validates, clamps (bit-identical to
// core/batch.py › pack_columns — the clamp bounds come in as arguments
// so types.py stays the single source of truth), key-hashes
// (FNV-1a64 + mix64, zero-remapped) and writes the rows STRAIGHT into a
// leased pair of packed wave-upload matrices (a64 [8,m] i64 row-major:
// key,hits,limit,duration,eff_ms,greg_end,burst,now; a32 [3,m] i32:
// behavior,algorithm,valid — parallel/sharded.py › PACK64/PACK32).
// Padding rows [n, m) keep empty_batch semantics: the buffers arrive
// zeroed from the pool and only eff_ms is re-filled to 1 here.
//
// Returns None (caller releases the lease and falls back) whenever the
// batch needs host-side Python: pb2-fallback framing (as
// parse_get_rate_limits), n > m, or any DURATION_IS_GREGORIAN row
// (calendar period ends are computed in Python).  GLOBAL/MULTI_REGION
// gating is the caller's policy — behavior_or is returned for it.
static PyObject* pack_wire_wave(PyObject*, PyObject* args) {
  Py_buffer view, b64, b32;
  long long now_ms;
  Py_ssize_t m;
  unsigned long long duration_max, value_max, eff_max, td_bound;
  if (!PyArg_ParseTuple(args, "y*Lw*w*nKKKK", &view, &now_ms, &b64, &b32,
                        &m, &duration_max, &value_max, &eff_max,
                        &td_bound))
    return nullptr;
  if (b64.len < m * 8 * (Py_ssize_t)sizeof(int64_t) ||
      b32.len < m * 3 * (Py_ssize_t)sizeof(int32_t)) {
    PyBuffer_Release(&view);
    PyBuffer_Release(&b64);
    PyBuffer_Release(&b32);
    PyErr_SetString(PyExc_ValueError, "packed buffers too small");
    return nullptr;
  }
  int64_t* a64 = (int64_t*)b64.buf;  // rows: key hits limit duration
                                     //       eff_ms greg_end burst now
  int32_t* a32 = (int32_t*)b32.buf;  // rows: behavior algorithm valid
  int64_t* r_key = a64;
  int64_t* r_hits = a64 + m;
  int64_t* r_limit = a64 + 2 * m;
  int64_t* r_dur = a64 + 3 * m;
  int64_t* r_eff = a64 + 4 * m;
  int64_t* r_burst = a64 + 6 * m;
  int64_t* r_now = a64 + 7 * m;
  int32_t* r_beh = a32;
  int32_t* r_alg = a32 + m;
  int32_t* r_valid = a32 + 2 * m;
  for (Py_ssize_t i = 0; i < m; i++) r_eff[i] = 1;  // padding eff_ms
  const uint8_t* base = (const uint8_t*)view.buf;
  const uint8_t* p = base;
  const uint8_t* end = p + view.len;
  std::vector<uint64_t> khash, khash_raw, tlv_off, tlv_len;
  khash.reserve(64);
  uint64_t beh_or = 0;
  const uint64_t GREG = 4;  // Behavior.DURATION_IS_GREGORIAN
  bool fallback = false;
  Py_ssize_t n = 0;
  while (p < end) {
    const uint8_t* tlv_start = p;
    uint64_t tag, len;
    if (!read_varint(&p, end, &tag) || tag != 0x0A ||
        !read_varint(&p, end, &len) || (uint64_t)(end - p) < len) {
      fallback = true;
      break;
    }
    const uint8_t* q = p;
    const uint8_t* qend = p + len;
    p = qend;
    const uint8_t* name_p = nullptr;
    const uint8_t* key_p = nullptr;
    uint64_t name_len = 0, key_len = 0;
    int64_t f_hits = 0, f_limit = 0, f_dur = 0, f_burst = 0;
    int64_t f_created = 0;
    int32_t f_alg = 0, f_beh = 0;
    while (q < qend && !fallback) {
      uint64_t t;
      if (!read_varint(&q, qend, &t)) {
        fallback = true;
        break;
      }
      uint64_t field = t >> 3, wt = t & 7;
      if (wt == 2) {
        uint64_t l;
        if (!read_varint(&q, qend, &l) || (uint64_t)(qend - q) < l) {
          fallback = true;
          break;
        }
        if (field == 1) {
          name_p = q;
          name_len = l;
        } else if (field == 2) {
          key_p = q;
          key_len = l;
        } else {
          fallback = true;
          break;
        }
        q += l;
      } else if (wt == 0) {
        uint64_t v;
        if (!read_varint(&q, qend, &v)) {
          fallback = true;
          break;
        }
        switch (field) {
          case 3: f_hits = (int64_t)v; break;
          case 4: f_limit = (int64_t)v; break;
          case 5: f_dur = (int64_t)v; break;
          case 6: f_alg = (int32_t)v; break;
          case 7: f_beh = (int32_t)v; break;
          case 8: f_burst = (int64_t)v; break;
          case 10: f_created = (int64_t)v; break;
          default: fallback = true;
        }
      } else {
        fallback = true;
      }
    }
    if (fallback) break;
    if (name_p == nullptr || name_len == 0 || key_p == nullptr ||
        key_len == 0 || !valid_utf8(name_p, name_len) ||
        !valid_utf8(key_p, key_len) ||
        ((uint64_t)(uint32_t)f_beh & GREG) || n >= m) {
      fallback = true;
      break;
    }
    uint64_t h = fnv1a64(name_p, (Py_ssize_t)name_len);
    const unsigned char us = '_';
    h = fnv1a64(&us, 1, h);
    h = fnv1a64(key_p, (Py_ssize_t)key_len, h);
    khash_raw.push_back(h);
    uint64_t hm = mix64(h);
    if (hm == 0) hm = 1;
    khash.push_back(hm);
    tlv_off.push_back((uint64_t)(tlv_start - base));
    tlv_len.push_back((uint64_t)(qend - tlv_start));
    // clamps: the exact pack_columns arithmetic (core/batch.py)
    int64_t dur = f_dur < (int64_t)duration_max ? f_dur
                                                : (int64_t)duration_max;
    int64_t eff = dur > 1 ? dur : 1;
    int leaky = f_alg == 1;
    uint64_t cap_v = value_max;
    if (leaky) {
      if (eff > (int64_t)eff_max) eff = (int64_t)eff_max;
      uint64_t c = td_bound / (uint64_t)eff;
      cap_v = c < value_max ? c : value_max;
    }
    int64_t lim = f_limit < 0 ? 0 : f_limit;
    if (lim > (int64_t)cap_v) lim = (int64_t)cap_v;
    int64_t hits = f_hits < 0 ? 0 : f_hits;
    if (hits > (int64_t)cap_v) hits = (int64_t)cap_v;
    int64_t burst = f_burst > 0
                        ? (f_burst < (int64_t)cap_v ? f_burst
                                                    : (int64_t)cap_v)
                        : lim;
    r_key[n] = (int64_t)hm;
    r_hits[n] = hits;
    r_limit[n] = lim;
    r_dur[n] = dur;
    r_eff[n] = eff;
    r_burst[n] = burst;
    // the caller's accepted-at clock wins when the forward hop stamped
    // it (created_at, field 10): applying a forwarded request at OUR
    // wall clock would mix time bases in the key's bucket row and a
    // later base reads the earlier one as expired — bucket reset,
    // debits silently gone (cold-key conservation loss)
    r_now[n] = f_created > 0 ? f_created : (int64_t)now_ms;
    r_beh[n] = f_beh;
    r_alg[n] = leaky ? 1 : 0;
    r_valid[n] = 1;
    beh_or |= (uint64_t)(uint32_t)f_beh;
    n++;
  }
  PyBuffer_Release(&view);
  PyBuffer_Release(&b64);
  PyBuffer_Release(&b32);
  if (fallback) Py_RETURN_NONE;
  static const char kEmptyW[1] = {0};
  const char* kh_p = n ? (const char*)khash.data() : kEmptyW;
  const char* kr_p = n ? (const char*)khash_raw.data() : kEmptyW;
  const char* to_p = n ? (const char*)tlv_off.data() : kEmptyW;
  const char* tl_p = n ? (const char*)tlv_len.data() : kEmptyW;
  return Py_BuildValue("(ny#y#Ky#y#)", n, kh_p, n * 8, kr_p, n * 8,
                       (unsigned long long)beh_or, to_p, n * 8, tl_p,
                       n * 8);
}

// split_resp_items(bytes) ->
//   None | (n, tlv_off u64le, tlv_len u64le, status i32le)
// Delimits each repeated field-1 submessage (RateLimitResp) of a
// GetRateLimitsResp / GetPeerRateLimitsResp (both use field 1 —
// proto/gubernator.proto, proto/peers.proto), and extracts each item's
// status (field 1 varint; 0 when omitted).  The clustered wire lane
// merges peer response TLVs into the client response by slicing these
// ranges — no pb2 objects.  Returns None on malformed input or unknown
// top-level fields (caller falls back to pb2).
static PyObject* split_resp_items(PyObject*, PyObject* arg) {
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return nullptr;
  const uint8_t* base = (const uint8_t*)view.buf;
  const uint8_t* p = base;
  const uint8_t* end = p + view.len;
  std::vector<uint64_t> tlv_off, tlv_len;
  std::vector<int32_t> status;
  bool fallback = false;
  while (p < end) {
    const uint8_t* tlv_start = p;
    uint64_t tag;
    if (!read_varint(&p, end, &tag) || tag != 0x0A) {  // field 1, LEN
      fallback = true;
      break;
    }
    uint64_t len;
    if (!read_varint(&p, end, &len) || (uint64_t)(end - p) < len) {
      fallback = true;
      break;
    }
    const uint8_t* q = p;
    const uint8_t* qend = p + len;
    p = qend;
    int32_t st = 0;
    // scan the submessage for field 1 (status); skip everything else
    while (q < qend) {
      uint64_t t;
      if (!read_varint(&q, qend, &t)) {
        fallback = true;
        break;
      }
      uint64_t field = t >> 3, wt = t & 7;
      if (wt == 0) {
        uint64_t v;
        if (!read_varint(&q, qend, &v)) {
          fallback = true;
          break;
        }
        if (field == 1) st = (int32_t)v;
      } else if (wt == 2) {
        uint64_t l;
        if (!read_varint(&q, qend, &l) || (uint64_t)(qend - q) < l) {
          fallback = true;
          break;
        }
        q += l;
      } else if (wt == 1) {
        if (qend - q < 8) {
          fallback = true;
          break;
        }
        q += 8;
      } else if (wt == 5) {
        if (qend - q < 4) {
          fallback = true;
          break;
        }
        q += 4;
      } else {
        fallback = true;
        break;
      }
    }
    if (fallback) break;
    tlv_off.push_back((uint64_t)(tlv_start - base));
    tlv_len.push_back((uint64_t)(qend - tlv_start));
    status.push_back(st);
  }
  PyBuffer_Release(&view);
  if (fallback) Py_RETURN_NONE;
  Py_ssize_t n = (Py_ssize_t)tlv_off.size();
  static const char kEmpty2[1] = {0};
  const char* to_p = n ? (const char*)tlv_off.data() : kEmpty2;
  const char* tl_p = n ? (const char*)tlv_len.data() : kEmpty2;
  const char* st_p = n ? (const char*)status.data() : kEmpty2;
  return Py_BuildValue("(ny#y#y#)", n, to_p, n * 8, tl_p, n * 8, st_p,
                       n * 4);
}

static inline void put_varint(std::vector<uint8_t>& out, uint64_t v) {
  while (v >= 0x80) {
    out.push_back((uint8_t)(v | 0x80));
    v >>= 7;
  }
  out.push_back((uint8_t)v);
}

static inline void put_field_varint(std::vector<uint8_t>& out, int field,
                                    uint64_t v) {
  if (v == 0) return;  // proto3: defaults are omitted
  put_varint(out, (uint64_t)(field << 3));
  put_varint(out, v);
}

// Shared serialization core: rows [lo, hi) of the given columns →
// GetRateLimitsResp wire bytes.  ``errors`` (or Py_None) is indexed
// RELATIVE to lo (errors[0] belongs to row lo).  Returns nullptr with
// a Python error set on failure.
static PyObject* build_resp_rows(const int32_t* status,
                                 const int64_t* limit,
                                 const int64_t* remaining,
                                 const int64_t* reset_time,
                                 Py_ssize_t lo, Py_ssize_t hi,
                                 PyObject* errors) {
  std::vector<uint8_t> out;
  out.reserve((size_t)(hi - lo) * 24);
  std::vector<uint8_t> sub;
  bool have_errors = errors != Py_None;
  for (Py_ssize_t i = lo; i < hi; i++) {
    sub.clear();
    put_field_varint(sub, 1, (uint64_t)(uint32_t)status[i]);
    put_field_varint(sub, 2, (uint64_t)limit[i]);
    put_field_varint(sub, 3, (uint64_t)remaining[i]);
    put_field_varint(sub, 4, (uint64_t)reset_time[i]);
    if (have_errors) {
      PyObject* e = PySequence_GetItem(errors, i - lo);
      if (e == nullptr) return nullptr;
      if (e != Py_None) {
        const unsigned char* ep;
        Py_ssize_t elen;
        if (!utf8_view(e, &ep, &elen)) {
          Py_DECREF(e);
          return nullptr;
        }
        if (elen > 0) {
          put_varint(sub, (5 << 3) | 2);
          put_varint(sub, (uint64_t)elen);
          sub.insert(sub.end(), ep, ep + elen);
        }
      }
      Py_DECREF(e);
    }
    out.push_back(0x0A);  // GetRateLimitsResp.responses
    put_varint(out, (uint64_t)sub.size());
    out.insert(out.end(), sub.begin(), sub.end());
  }
  return PyBytes_FromStringAndSize((const char*)out.data(),
                                   (Py_ssize_t)out.size());
}

// build_rate_limit_resps(status i32le, limit i64le, remaining i64le,
//                        reset_time i64le, errors|None) -> bytes
// errors: sequence of str/None per response (None/"" = no error field).
static PyObject* build_rate_limit_resps(PyObject*, PyObject* args) {
  Py_buffer st, li, re, rt;
  PyObject* errors;
  if (!PyArg_ParseTuple(args, "y*y*y*y*O", &st, &li, &re, &rt, &errors))
    return nullptr;
  Py_ssize_t n = st.len / 4;
  PyObject* out = nullptr;
  if (li.len != n * 8 || re.len != n * 8 || rt.len != n * 8) {
    PyErr_SetString(PyExc_ValueError, "column length mismatch");
  } else {
    out = build_resp_rows((const int32_t*)st.buf, (const int64_t*)li.buf,
                          (const int64_t*)re.buf, (const int64_t*)rt.buf,
                          0, n, errors);
  }
  PyBuffer_Release(&st);
  PyBuffer_Release(&li);
  PyBuffer_Release(&re);
  PyBuffer_Release(&rt);
  return out;
}

// build_responses_from_columns(status i32le, limit i64le,
//                              remaining i64le, reset_time i64le,
//                              row_lo, row_hi, errors|None) -> bytes
// The overlapped-pipeline caller-thread lane: the columns are a wave's
// SHARED result buffers (every job of the wave passes the same ones),
// and [row_lo, row_hi) selects this caller's rows — wire bytes are
// written straight from the packed result slice with zero per-request
// Python objects and zero intermediate slices.  ``errors`` is indexed
// relative to row_lo.
static PyObject* build_responses_from_columns(PyObject*, PyObject* args) {
  Py_buffer st, li, re, rt;
  Py_ssize_t lo, hi;
  PyObject* errors;
  if (!PyArg_ParseTuple(args, "y*y*y*y*nnO", &st, &li, &re, &rt, &lo, &hi,
                        &errors))
    return nullptr;
  Py_ssize_t n = st.len / 4;
  PyObject* out = nullptr;
  if (li.len != n * 8 || re.len != n * 8 || rt.len != n * 8) {
    PyErr_SetString(PyExc_ValueError, "column length mismatch");
  } else if (lo < 0 || hi < lo || hi > n) {
    PyErr_SetString(PyExc_ValueError, "row bounds out of range");
  } else {
    out = build_resp_rows((const int32_t*)st.buf, (const int64_t*)li.buf,
                          (const int64_t*)re.buf, (const int64_t*)rt.buf,
                          lo, hi, errors);
  }
  PyBuffer_Release(&st);
  PyBuffer_Release(&li);
  PyBuffer_Release(&re);
  PyBuffer_Release(&rt);
  return out;
}

// ---------------------------------------------------------------------------
// Cold-tier key store (tiering.py): open-addressed khash u64 -> packed
// 8x int64 bucket-state row (store.py column order minus the key).
// Linear probing over a power-of-two table with tombstone deletes and
// 0.7-load growth — the native backing for the host cold tier, so a
// 100M-key residency costs ~72 B/key flat instead of a Python dict of
// tuples.  NOT internally locked: the contract (documented on the
// tiering.py wrappers, soaked by tools/native_soak.py) is that the
// caller serializes mutations (TierController._mu).
static const Py_ssize_t COLD_ROW = 8;  // int64 values per row

struct ColdStore {
  std::vector<uint64_t> keys;
  std::vector<int64_t> rows;   // cap * COLD_ROW
  std::vector<uint8_t> state;  // 0 empty, 1 full, 2 tombstone
  size_t cap = 0;              // power of two
  size_t used = 0;             // full slots
  size_t filled = 0;           // full + tombstone (load-factor basis)
};

static const char* COLD_CAPSULE = "guber.cold_store";

static void cold_destroy(PyObject* capsule) {
  delete (ColdStore*)PyCapsule_GetPointer(capsule, COLD_CAPSULE);
}

static ColdStore* cold_from(PyObject* obj) {
  return (ColdStore*)PyCapsule_GetPointer(obj, COLD_CAPSULE);
}

static void cold_init(ColdStore* cs, size_t cap) {
  cs->cap = cap;
  cs->used = cs->filled = 0;
  cs->keys.assign(cap, 0);
  cs->rows.assign(cap * COLD_ROW, 0);
  cs->state.assign(cap, 0);
}

// Slot of `key`, or the first insertable slot (tombstone/empty) when
// absent.  cap is power-of-two so the linear probe visits every slot.
static size_t cold_find(const ColdStore* cs, uint64_t key, bool* present) {
  size_t mask = cs->cap - 1;
  size_t i = (size_t)key & mask;
  size_t first_free = (size_t)-1;
  for (size_t n = 0; n < cs->cap; n++, i = (i + 1) & mask) {
    uint8_t st = cs->state[i];
    if (st == 1 && cs->keys[i] == key) {
      *present = true;
      return i;
    }
    if (st == 2) {
      if (first_free == (size_t)-1) first_free = i;
      continue;
    }
    if (st == 0) {
      *present = false;
      return first_free != (size_t)-1 ? first_free : i;
    }
  }
  *present = false;
  return first_free;  // table of pure full+tombstone: growth precedes this
}

static void cold_grow(ColdStore* cs, size_t new_cap) {
  ColdStore next;
  cold_init(&next, new_cap);
  for (size_t i = 0; i < cs->cap; i++) {
    if (cs->state[i] != 1) continue;
    bool present;
    size_t j = cold_find(&next, cs->keys[i], &present);
    next.keys[j] = cs->keys[i];
    std::memcpy(&next.rows[j * COLD_ROW], &cs->rows[i * COLD_ROW],
                COLD_ROW * sizeof(int64_t));
    next.state[j] = 1;
  }
  next.used = next.filled = cs->used;
  *cs = std::move(next);
}

// cold_new(cap_hint) -> capsule
static PyObject* cold_new(PyObject*, PyObject* args) {
  Py_ssize_t hint = 0;
  if (!PyArg_ParseTuple(args, "|n", &hint)) return nullptr;
  size_t cap = 64;
  while ((Py_ssize_t)cap < hint) cap <<= 1;
  ColdStore* cs = new ColdStore();
  cold_init(cs, cap);
  PyObject* capsule = PyCapsule_New(cs, COLD_CAPSULE, cold_destroy);
  if (capsule == nullptr) delete cs;
  return capsule;
}

// cold_put(capsule, key u64, row 64 bytes) -> 1 inserted / 0 overwrote
static PyObject* cold_put(PyObject*, PyObject* args) {
  PyObject* obj;
  unsigned long long key;
  Py_buffer row;
  if (!PyArg_ParseTuple(args, "OKy*", &obj, &key, &row)) return nullptr;
  ColdStore* cs = cold_from(obj);
  if (cs == nullptr || row.len != COLD_ROW * (Py_ssize_t)sizeof(int64_t)) {
    if (cs != nullptr)
      PyErr_SetString(PyExc_ValueError, "cold row must be 64 bytes");
    PyBuffer_Release(&row);
    return nullptr;
  }
  if ((cs->filled + 1) * 10 >= cs->cap * 7)
    // mostly-live table doubles; mostly-tombstones rehashes in place
    cold_grow(cs, (cs->used + 1) * 10 >= cs->cap * 5 ? cs->cap * 2
                                                     : cs->cap);
  bool present;
  size_t i = cold_find(cs, (uint64_t)key, &present);
  if (!present) {
    if (cs->state[i] == 0) cs->filled++;
    cs->keys[i] = (uint64_t)key;
    cs->state[i] = 1;
    cs->used++;
  }
  std::memcpy(&cs->rows[i * COLD_ROW], row.buf,
              COLD_ROW * sizeof(int64_t));
  PyBuffer_Release(&row);
  return PyLong_FromLong(present ? 0 : 1);
}

// cold_get(capsule, key u64) -> bytes(64) | None
static PyObject* cold_get(PyObject*, PyObject* args) {
  PyObject* obj;
  unsigned long long key;
  if (!PyArg_ParseTuple(args, "OK", &obj, &key)) return nullptr;
  ColdStore* cs = cold_from(obj);
  if (cs == nullptr) return nullptr;
  bool present;
  size_t i = cold_find(cs, (uint64_t)key, &present);
  if (!present) Py_RETURN_NONE;
  return PyBytes_FromStringAndSize((const char*)&cs->rows[i * COLD_ROW],
                                   COLD_ROW * sizeof(int64_t));
}

// cold_pop(capsule, key u64) -> bytes(64) | None
static PyObject* cold_pop(PyObject*, PyObject* args) {
  PyObject* obj;
  unsigned long long key;
  if (!PyArg_ParseTuple(args, "OK", &obj, &key)) return nullptr;
  ColdStore* cs = cold_from(obj);
  if (cs == nullptr) return nullptr;
  bool present;
  size_t i = cold_find(cs, (uint64_t)key, &present);
  if (!present) Py_RETURN_NONE;
  PyObject* out = PyBytes_FromStringAndSize(
      (const char*)&cs->rows[i * COLD_ROW], COLD_ROW * sizeof(int64_t));
  if (out != nullptr) {
    cs->state[i] = 2;  // tombstone keeps later probe chains intact
    cs->used--;
  }
  return out;
}

// cold_len(capsule) -> resident key count
static PyObject* cold_len(PyObject*, PyObject* args) {
  PyObject* obj;
  if (!PyArg_ParseTuple(args, "O", &obj)) return nullptr;
  ColdStore* cs = cold_from(obj);
  if (cs == nullptr) return nullptr;
  return PyLong_FromSize_t(cs->used);
}

// cold_contains(capsule, keys u64le bytes, out u8 writable) -> None
// The engine pre-mask read: one call per wave, no per-key Python.
static PyObject* cold_contains(PyObject*, PyObject* args) {
  PyObject* obj;
  Py_buffer keys, out;
  if (!PyArg_ParseTuple(args, "Oy*w*", &obj, &keys, &out)) return nullptr;
  ColdStore* cs = cold_from(obj);
  Py_ssize_t n = keys.len / 8;
  if (cs == nullptr || out.len < n) {
    if (cs != nullptr)
      PyErr_SetString(PyExc_ValueError, "output mask too short");
    PyBuffer_Release(&keys);
    PyBuffer_Release(&out);
    return nullptr;
  }
  const uint64_t* kp = (const uint64_t*)keys.buf;
  uint8_t* op = (uint8_t*)out.buf;
  for (Py_ssize_t i = 0; i < n; i++) {
    bool present;
    cold_find(cs, kp[i], &present);
    op[i] = present ? 1 : 0;
  }
  PyBuffer_Release(&keys);
  PyBuffer_Release(&out);
  Py_RETURN_NONE;
}

// cold_snapshot(capsule) -> (n, keys u64le bytes, rows i64le bytes)
static PyObject* cold_snapshot(PyObject*, PyObject* args) {
  PyObject* obj;
  if (!PyArg_ParseTuple(args, "O", &obj)) return nullptr;
  ColdStore* cs = cold_from(obj);
  if (cs == nullptr) return nullptr;
  Py_ssize_t n = (Py_ssize_t)cs->used;
  PyObject* kb = PyBytes_FromStringAndSize(nullptr, n * 8);
  PyObject* rb =
      PyBytes_FromStringAndSize(nullptr, n * COLD_ROW * sizeof(int64_t));
  if (kb == nullptr || rb == nullptr) {
    Py_XDECREF(kb);
    Py_XDECREF(rb);
    return nullptr;
  }
  uint64_t* kp = (uint64_t*)PyBytes_AS_STRING(kb);
  int64_t* rp = (int64_t*)PyBytes_AS_STRING(rb);
  Py_ssize_t w = 0;
  for (size_t i = 0; i < cs->cap; i++) {
    if (cs->state[i] != 1) continue;
    kp[w] = cs->keys[i];
    std::memcpy(&rp[w * COLD_ROW], &cs->rows[i * COLD_ROW],
                COLD_ROW * sizeof(int64_t));
    w++;
  }
  return Py_BuildValue("(nNN)", w, kb, rb);
}

// cold_clear(capsule) -> None
static PyObject* cold_clear(PyObject*, PyObject* args) {
  PyObject* obj;
  if (!PyArg_ParseTuple(args, "O", &obj)) return nullptr;
  ColdStore* cs = cold_from(obj);
  if (cs == nullptr) return nullptr;
  cold_init(cs, 64);
  Py_RETURN_NONE;
}

static PyMethodDef methods[] = {
    {"fnv1a64_batch", fnv1a64_batch, METH_O,
     "Batch raw FNV-1a64 of str/bytes -> (le64 bytes, n)"},
    {"fnv1a64_pair_batch", fnv1a64_pair_batch, METH_VARARGS,
     "Batch FNV-1a64 of name+'_'+key pairs -> (le64 bytes, n)"},
    {"parse_get_rate_limits", parse_get_rate_limits, METH_O,
     "GetRateLimitsReq wire bytes -> packed column buffers (or None)"},
    {"count_req_items", count_req_items, METH_O,
     "Top-level scan: count repeated field-1 request TLVs (or None)"},
    {"pack_wire_wave", pack_wire_wave, METH_VARARGS,
     "Fused ingest: wire bytes -> clamped rows written into leased "
     "packed wave matrices (or None)"},
    {"stamp_req_tlvs", stamp_req_tlvs, METH_VARARGS,
     "Join request TLV slices, appending created_at (field 10) where "
     "unset — the forward hop's caller-clock stamp"},
    {"split_resp_items", split_resp_items, METH_O,
     "RateLimitResp-list wire bytes -> per-item TLV ranges + status"},
    {"build_rate_limit_resps", build_rate_limit_resps, METH_VARARGS,
     "Packed response columns -> GetRateLimitsResp wire bytes"},
    {"build_responses_from_columns", build_responses_from_columns,
     METH_VARARGS,
     "Rows [lo, hi) of shared result columns -> GetRateLimitsResp "
     "wire bytes"},
    {"cold_new", cold_new, METH_VARARGS,
     "Cold-tier store (tiering.py): new open-addressed khash->row "
     "table -> capsule"},
    {"cold_put", cold_put, METH_VARARGS,
     "cold_put(capsule, key, row64B) -> 1 inserted / 0 overwrote"},
    {"cold_get", cold_get, METH_VARARGS,
     "cold_get(capsule, key) -> 64-byte row | None"},
    {"cold_pop", cold_pop, METH_VARARGS,
     "cold_pop(capsule, key) -> 64-byte row | None (tombstone delete)"},
    {"cold_len", cold_len, METH_VARARGS,
     "cold_len(capsule) -> resident key count"},
    {"cold_contains", cold_contains, METH_VARARGS,
     "cold_contains(capsule, keys u64le, out u8) -> membership mask"},
    {"cold_snapshot", cold_snapshot, METH_VARARGS,
     "cold_snapshot(capsule) -> (n, keys bytes, rows bytes)"},
    {"cold_clear", cold_clear, METH_VARARGS,
     "cold_clear(capsule) -> reset to empty"},
    {nullptr, nullptr, 0, nullptr}};

static struct PyModuleDef moduledef = {PyModuleDef_HEAD_INIT, "_native",
                                       "native host ops", -1, methods};

PyMODINIT_FUNC PyInit__native(void) { return PyModule_Create(&moduledef); }

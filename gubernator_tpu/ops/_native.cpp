// Native host ops for gubernator-tpu.
//
// The reference is pure Go (SURVEY.md §2.2) so there is no reference
// native component to mirror; this extension exists because the
// host-side request-ingest path (string hashing while the device runs
// the decision step) is the framework's CPU bottleneck, the role Go's
// compiled hashmap/hash code plays in the reference.
//
// Exposed primitives (wrapped by ops/native.py):
//   fnv1a64_batch([str|bytes, ...]) -> (bytes, n)   raw FNV-1a 64
//   fnv1a64_pair_batch(names, keys) -> (bytes, n)   hash(name + "_" + key)
//
// The avalanche finalizer stays in Python/numpy (hashing.mix64_np) so
// there is exactly one source of truth for it.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <vector>

static const uint64_t FNV_OFFSET = 0xCBF29CE484222325ULL;
static const uint64_t FNV_PRIME = 0x100000001B3ULL;

static inline uint64_t fnv1a64(const unsigned char* p, Py_ssize_t n,
                               uint64_t h = FNV_OFFSET) {
  for (Py_ssize_t i = 0; i < n; i++) {
    h ^= (uint64_t)p[i];
    h *= FNV_PRIME;
  }
  return h;
}

// Borrow a UTF-8 view of a str/bytes item.  Returns false on error.
static inline bool utf8_view(PyObject* obj, const unsigned char** p,
                             Py_ssize_t* n) {
  if (PyUnicode_Check(obj)) {
    const char* s = PyUnicode_AsUTF8AndSize(obj, n);
    if (s == nullptr) return false;
    *p = (const unsigned char*)s;
    return true;
  }
  if (PyBytes_Check(obj)) {
    *p = (const unsigned char*)PyBytes_AS_STRING(obj);
    *n = PyBytes_GET_SIZE(obj);
    return true;
  }
  PyErr_SetString(PyExc_TypeError, "expected str or bytes");
  return false;
}

static PyObject* fnv1a64_batch(PyObject*, PyObject* arg) {
  PyObject* seq = PySequence_Fast(arg, "expected a sequence");
  if (seq == nullptr) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  PyObject* out = PyBytes_FromStringAndSize(nullptr, n * 8);
  if (out == nullptr) {
    Py_DECREF(seq);
    return nullptr;
  }
  uint64_t* dst = (uint64_t*)PyBytes_AS_STRING(out);
  for (Py_ssize_t i = 0; i < n; i++) {
    const unsigned char* p;
    Py_ssize_t len;
    if (!utf8_view(PySequence_Fast_GET_ITEM(seq, i), &p, &len)) {
      Py_DECREF(seq);
      Py_DECREF(out);
      return nullptr;
    }
    dst[i] = fnv1a64(p, len);
  }
  Py_DECREF(seq);
  return Py_BuildValue("(Nn)", out, n);
}

// hash(name + "_" + unique_key) without building the joined string —
// the exact key-identity hash of the request path.
static PyObject* fnv1a64_pair_batch(PyObject*, PyObject* args) {
  PyObject *names_arg, *keys_arg;
  if (!PyArg_ParseTuple(args, "OO", &names_arg, &keys_arg)) return nullptr;
  PyObject* names = PySequence_Fast(names_arg, "expected a sequence");
  if (names == nullptr) return nullptr;
  PyObject* keys = PySequence_Fast(keys_arg, "expected a sequence");
  if (keys == nullptr) {
    Py_DECREF(names);
    return nullptr;
  }
  Py_ssize_t n = PySequence_Fast_GET_SIZE(names);
  if (PySequence_Fast_GET_SIZE(keys) != n) {
    Py_DECREF(names);
    Py_DECREF(keys);
    PyErr_SetString(PyExc_ValueError, "length mismatch");
    return nullptr;
  }
  PyObject* out = PyBytes_FromStringAndSize(nullptr, n * 8);
  if (out == nullptr) {
    Py_DECREF(names);
    Py_DECREF(keys);
    return nullptr;
  }
  uint64_t* dst = (uint64_t*)PyBytes_AS_STRING(out);
  const unsigned char underscore = '_';
  for (Py_ssize_t i = 0; i < n; i++) {
    const unsigned char *pn, *pk;
    Py_ssize_t ln, lk;
    if (!utf8_view(PySequence_Fast_GET_ITEM(names, i), &pn, &ln) ||
        !utf8_view(PySequence_Fast_GET_ITEM(keys, i), &pk, &lk)) {
      Py_DECREF(names);
      Py_DECREF(keys);
      Py_DECREF(out);
      return nullptr;
    }
    uint64_t h = fnv1a64(pn, ln);
    h = fnv1a64(&underscore, 1, h);
    dst[i] = fnv1a64(pk, lk, h);
  }
  Py_DECREF(names);
  Py_DECREF(keys);
  return Py_BuildValue("(Nn)", out, n);
}

static PyMethodDef methods[] = {
    {"fnv1a64_batch", fnv1a64_batch, METH_O,
     "Batch raw FNV-1a64 of str/bytes -> (le64 bytes, n)"},
    {"fnv1a64_pair_batch", fnv1a64_pair_batch, METH_VARARGS,
     "Batch FNV-1a64 of name+'_'+key pairs -> (le64 bytes, n)"},
    {nullptr, nullptr, 0, nullptr}};

static struct PyModuleDef moduledef = {PyModuleDef_HEAD_INIT, "_native",
                                       "native host ops", -1, methods};

PyMODINIT_FUNC PyInit__native(void) { return PyModule_Create(&moduledef); }

"""Build the native extension in place:

    python gubernator_tpu/ops/setup_native.py build_ext --inplace
    (or `make native` from the repo root)

Sanitizer builds (never in place — the production .so stays untouched):

    GUBER_NATIVE_SAN=tsan python gubernator_tpu/ops/setup_native.py \
        build_ext --build-lib build/tsan
    GUBER_NATIVE_SAN=asan ... --build-lib build/asan
    (or `make tsan` / `make asan`, which also run the multithreaded
    native soak under the instrumented .so — tools/native_soak.py)

The sanitized objects land in their own build-temp dir so a tsan build
never poisons the production object cache (and vice versa).
"""
import os

from setuptools import Extension, setup

HERE = os.path.dirname(os.path.abspath(__file__))

SAN_FLAGS = {
    "": [],
    # -O1 -fno-omit-frame-pointer: the sanitizer runtimes want real
    # stacks; -O3 inlining makes reports unreadable
    "tsan": ["-fsanitize=thread", "-O1", "-g", "-fno-omit-frame-pointer"],
    "asan": ["-fsanitize=address", "-O1", "-g",
             "-fno-omit-frame-pointer"],
}

san = os.environ.get("GUBER_NATIVE_SAN", "")
if san not in SAN_FLAGS:
    raise SystemExit(
        f"GUBER_NATIVE_SAN={san!r}: want 'tsan', 'asan', or unset")
san_compile = SAN_FLAGS[san]
san_link = [f for f in san_compile if f.startswith("-fsanitize")]

script_args = None
if san:
    import sys

    # sanitized builds must not share the default build-temp with the
    # production build — same source, different instrumentation
    if "--build-temp" not in " ".join(sys.argv):
        sys.argv += ["--build-temp", os.path.join("build", f"tmp-{san}")]

setup(
    name="gubernator-tpu-native",
    script_args=script_args,
    ext_modules=[
        Extension(
            "gubernator_tpu.ops._native",
            sources=[os.path.relpath(os.path.join(HERE, "_native.cpp"))],
            extra_compile_args=(["-std=c++17"]
                                + (san_compile or ["-O3"])),
            extra_link_args=san_link,
        )
    ],
)

"""Build the native extension in place:

    python gubernator_tpu/ops/setup_native.py build_ext --inplace
    (or `make native` from the repo root)
"""
import os

from setuptools import Extension, setup

HERE = os.path.dirname(os.path.abspath(__file__))

setup(
    name="gubernator-tpu-native",
    script_args=None,
    ext_modules=[
        Extension(
            "gubernator_tpu.ops._native",
            sources=[os.path.relpath(os.path.join(HERE, "_native.cpp"))],
            extra_compile_args=["-O3", "-std=c++17"],
        )
    ],
)

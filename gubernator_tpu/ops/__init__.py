"""Accelerated ops.

- ``native``: C++ host fast paths (batch key hashing); importing it
  raises ImportError when the extension isn't built (``make native``)
  and callers fall back to numpy.
- ``pallas_sweep``: Pallas TPU kernel for the fused expired-row sweep
  (enabled via GUBER_PALLAS_SWEEP=1).
"""

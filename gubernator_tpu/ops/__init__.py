"""Host-side ops: optional native (C++) fast paths.

``from gubernator_tpu.ops import native`` raises ImportError when the
extension isn't built (``make native``); callers fall back to numpy.
"""

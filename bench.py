"""Benchmark: rate-limit decisions/sec/chip on the north-star workload.

Workload (BASELINE.json › north_star): TOKEN_BUCKET, 10M distinct keys
drawn Zipf(1.1), hits=1, limit=100, duration=10s — the reference's
`gubernator-cli` load shape at the 10M-key working set (client batches
of 1000).  The dispatcher coalesces client batches into one device batch
per step; each step is one jit program — probe → gather → branchless
update → scatter.  TWO table-update modes are measured and the faster
one is the headline (extra.step_mode records which):

- "copy": no donation; scatters fuse into a dense streaming copy of the
  table (~2 × CAP × row-bytes per launch).
- "donate": table aliases in/out; cond-gated cold columns pass through
  copy-free and hot scatters update in place where the lowering allows
  (core/step.py › decide_batch_donated) — per-step traffic ~B-sized.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extra": {...}}
vs_baseline is relative to the 50M decisions/s/chip north-star target
(BASELINE.json records no published reference numbers).
"""
import json
import os
import sys
import time

import numpy as np

# Persistent compile cache: the decision-step program is large and a
# cold TPU compile is minutes over the tunnel; cache across bench
# invocations and sessions (_jax_cache owns the dir choice).
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _jax_cache

_jax_cache.setup()


def log(*a):
    print(*a, file=sys.stderr, flush=True)


FAST = bool(os.environ.get("GUBER_BENCH_FAST"))
#: north star is 10M keys; CAP 2^26 (load 0.149) + the default 8-slot
#: probe window is the zero-loss flagship shape as of round 5: the
#: EXACT 10M-key populate inserts every key (0 errs,
#: tools/populate_errs_check.py; CAP 2^25/8-probe loses 71 keys and
#: the former CAP 2^24/8-probe shape lost 17,739 — VERDICT r3 item 9).
#: The r4 16-probe widening is GONE because the 2026-08-02 backend
#: compiler serializes 16-probe steps at CAP >= 2^25 (0.35M dec/s
#: on-chip, artifacts/tpu_session_r5_attempt1.json) while 8-probe
#: shapes lower well clear up to CAP 2^27 (564.7M dec/s, cfg5 row in
#: the same artifact) — doubling CAP instead of the probe window buys
#: zero-loss WITHOUT the pathological lowering.  The CPU fallback
#: (GUBER_BENCH_FAST) shrinks the workload — its config string says
#: so; it never silently stands in for the 10M-key number.
N_KEYS = int(os.environ.get("GUBER_BENCH_KEYS",
                            1_000_000 if FAST else 10_000_000))
CAP = int(os.environ.get("GUBER_BENCH_CAP", 1 << 21 if FAST else 1 << 26))
#: the probe window stays at the serving default (8) everywhere since
#: round 5 — bench no longer exports a probe override; GUBER_PROBES in
#: the environment therefore always means an operator choice, which
#: sections propagate untouched (when absent they pin children to the
#: serving default explicitly).
_PROBES_DEFAULTED = "GUBER_PROBES" not in os.environ
#: device batch = coalesced client batches of 1024 (GUBER_BENCH_B
#: overrides for batch-size sweeps)
B = int(os.environ.get("GUBER_BENCH_B", 8192 if FAST else 65536))
ZIPF_A = 1.1
LIMIT = 100
DURATION_MS = 10_000
NOW0 = 1_760_000_000_000
TARGET = 50e6


def _host_cores() -> int:
    """Schedulable cores for THIS process (affinity-aware where the
    platform supports it)."""
    return (len(os.sched_getaffinity(0))
            if hasattr(os, "sched_getaffinity")
            else (os.cpu_count() or 1))


def _keyhash(x: np.ndarray) -> np.ndarray:
    """Key-id → 64-bit hash (stand-in for host string hashing, which is
    not what this benchmark measures — see extra.host_hash_mkeys).
    Shared with tools/tpu_session.py so both measure the same key
    distribution."""
    from gubernator_tpu.hashing import mix64_np

    x = mix64_np((x + np.uint64(0x9E3779B97F4A7C15)).astype(np.uint64))
    return np.where(x == 0, np.uint64(1), x)


def pad_chunk(chunk: np.ndarray, size: int) -> np.ndarray:
    """Pad a trailing populate chunk to the device batch size by
    repeating its last id (shared with tools/tpu_session.py)."""
    if len(chunk) < size:
        chunk = np.concatenate(
            [chunk, np.full(size - len(chunk), chunk[-1], np.uint64)])
    return chunk


def main():
    import os

    plat = os.environ.get("GUBER_JAX_PLATFORM", "")
    import jax

    if plat:
        # must go through jax.config: the sandbox sitecustomize overwrites
        # the jax_platforms config at interpreter start (env is ignored)
        jax.config.update("jax_platforms", plat)
    import jax.numpy as jnp

    from gubernator_tpu.core.batch import RequestBatch
    from gubernator_tpu.core.step import decide_batch, decide_batch_donated
    from gubernator_tpu.core.table import init_table

    backend = jax.default_backend()
    log(f"backend={backend} devices={jax.devices()}")

    rng = np.random.default_rng(42)
    n_batches = 8
    draws = rng.zipf(ZIPF_A, size=n_batches * B) % N_KEYS
    key_batches = [jnp.asarray(_keyhash(draws[i * B:(i + 1) * B].astype(np.uint64)))
                   for i in range(n_batches)]

    i64 = jnp.int64
    const = dict(
        hits=jnp.ones(B, i64),
        limit=jnp.full(B, LIMIT, i64),
        duration=jnp.full(B, DURATION_MS, i64),
        eff_ms=jnp.full(B, DURATION_MS, i64),
        greg_end=jnp.zeros(B, i64),
        behavior=jnp.zeros(B, jnp.int32),
        algorithm=jnp.zeros(B, jnp.int32),
        burst=jnp.full(B, LIMIT, i64),
        valid=jnp.ones(B, bool),
    )

    def make_batch(keys):
        return RequestBatch(key=keys, **const)

    # Hot-loop time source: ONE host→device transfer, then a jitted
    # device-side bump per step.  A per-rep `jnp.asarray(now0 + r)` is a
    # synchronous host→device round trip on the tunneled backend — on a
    # degraded link (observed 2026-08-01: ~26-216 ms per transfer while
    # dispatch stayed fully async at 0.02 ms) it serializes the whole
    # sustained loop and the "rate" becomes a link measurement.  The
    # device bump keeps the loop transfer-free with identical time
    # semantics (now advances by 1 per step).
    _bump1 = _bump_fn()
    _bump1(jnp.asarray(0, i64)).block_until_ready()  # compile now, not
    # inside any timed region below

    populate_errs = {}

    def populate(step_fn, st, label):
        """Insert ALL N_KEYS distinct keys so the measured loop runs at
        the claimed working set (load factor N_KEYS/CAP), not at the few
        hundred thousand distinct keys a handful of Zipf draws covers —
        the sustained number must be the steady-state resident-table
        rate it claims to be.  Insert failures are COUNTED and reported
        (extra.populate_errs): the flagship claim is that the shape
        serves 100% of its working set, and a key that lost every claim
        round errs on every future request."""
        ids = np.arange(N_KEYS, dtype=np.uint64)
        now_pop = jnp.asarray(NOW0, i64)
        errs = 0
        for a in range(0, N_KEYS, B):
            chunk = pad_chunk(ids[a:a + B], B)
            st, out = step_fn(st, make_batch(jnp.asarray(_keyhash(chunk))),
                              now_pop)
            errs += int(np.asarray(out.err).sum())
        out.status.block_until_ready()
        populate_errs[label] = errs
        if errs:
            log(f"[{label}] WARNING: {errs} keys failed to insert "
                f"during populate — the rate below does not serve "
                f"100% of the working set")
        return st

    def measure_mode(step_fn, label, sustain_target=15_000_000,
                     init_fn=init_table):
        """Compile, populate the full working set, then time a sustained
        dispatch loop at steady state."""
        st = init_fn(CAP)
        t0 = time.perf_counter()
        st, out = step_fn(st, make_batch(key_batches[0]),
                          jnp.asarray(NOW0, i64))
        out.status.block_until_ready()
        log(f"[{label}] compile+first step in "
            f"{time.perf_counter() - t0:.1f}s")
        t0 = time.perf_counter()
        st = populate(step_fn, st, label)
        log(f"[{label}] populated {N_KEYS} keys "
            f"(load {N_KEYS/CAP:.2f}) in {time.perf_counter() - t0:.1f}s")
        now_dev = jnp.asarray(NOW0, i64)
        for i in range(1, n_batches):
            now_dev = _bump1(now_dev)
            st, out = step_fn(st, make_batch(key_batches[i]), now_dev)
        out.status.block_until_ready()
        reps = max(1, int(sustain_target / B / n_batches)) * n_batches
        now_dev = jnp.asarray(NOW0 + 100, i64)
        t0 = time.perf_counter()
        for r in range(reps):
            st, out = step_fn(st, make_batch(key_batches[r % n_batches]),
                              now_dev)
            now_dev = _bump1(now_dev)
        out.status.block_until_ready()
        dt = time.perf_counter() - t0
        rate = reps * B / dt
        log(f"[{label}] sustained: {reps * B} decisions in {dt:.3f}s "
            f"→ {rate/1e6:.2f}M/s")
        return rate, st

    # mode 1: dense-copy step (safe everywhere)
    dps_copy, state = measure_mode(decide_batch, "copy")
    # mode 2: donated step — in-place updates where the lowering allows;
    # this is the mode that breaks the CAP-linear streaming wall
    try:
        dps_donate, _ = measure_mode(decide_batch_donated, "donate")
    except Exception as e:  # noqa: BLE001
        dps_donate = 0.0
        log(f"donated-step mode failed: {e!r:.200}")
    # mode 3: hand Pallas kernel (ops/pallas_step.py) — its rate is a
    # FLOOR independent of XLA's scatter/gather lowering choices (the
    # 209 ms/step copy-mode episode).  Device backends only: interpret
    # mode is a python-level emulator, minutes per batch.  Its 8-slot
    # buckets overflow sooner than the XLA probe window, so the bucket
    # table gets 2× the capacity (its own layout, its own budget) and
    # a measured err fraction gates the duel: a rate that isn't
    # serving the whole working set must not win the headline.
    dps_pallas, pallas_err_frac = 0.0, None
    #: the kernel's bucketized table gets 2× the XLA CAP (one sizing
    #: policy — the reporting fields below must reference THIS variable)
    pallas_rows = min(CAP * 2, 1 << 26)
    if backend != "cpu" and not os.environ.get("GUBER_BENCH_NO_PALLAS"):
        st_p = st_p2 = sample = None
        try:
            from gubernator_tpu.ops.pallas_step import (
                decide_batch_pallas, init_pallas_table)

            dps_pallas, st_p = measure_mode(
                decide_batch_pallas, "pallas",
                sustain_target=4_000_000,
                init_fn=lambda cap: init_pallas_table(pallas_rows))
            st_p2, sample = decide_batch_pallas(
                st_p, make_batch(key_batches[0]),
                jnp.asarray(NOW0 + 10_000, i64))
            pallas_err_frac = round(
                float(np.asarray(sample.err).mean()), 6)
            log(f"[pallas] err fraction at steady state: "
                f"{pallas_err_frac}")
        except Exception as e:  # noqa: BLE001
            log(f"pallas-step mode failed: {e!r:.300}")
        finally:
            # drop the kernel's device buffers NOW, on every path (the
            # ~GB bucket table + outputs): the pre-child-section client
            # release below can only free what nothing references
            del st_p, st_p2, sample
    rates = {"copy": dps_copy, "donate": dps_donate,
             "pallas": dps_pallas}
    eligible = dict(rates)
    if pallas_err_frac is None or pallas_err_frac > 0.005:
        # bucket-overflow err rows aren't served decisions: a rate
        # that drops part of the working set can't win the headline
        eligible.pop("pallas")
        if pallas_err_frac:
            log(f"[pallas] disqualified from winning the duel: "
                f"err fraction {pallas_err_frac} > 0.005")
    step_mode = max(eligible, key=eligible.get)
    dps = eligible[step_mode]
    # sections serve through the engines, which run the XLA step — keep
    # their mode the best XLA lowering even if pallas wins the duel
    xla_mode = "donate" if dps_donate > dps_copy else "copy"
    step_best = (decide_batch_donated if xla_mode == "donate"
                 else decide_batch)
    log(f"headline mode: {step_mode} ({dps/1e6:.2f}M/s); "
        f"xla mode for sections: {xla_mode}")

    # Checkpoint the headline IMMEDIATELY: every section below (scan,
    # latency, client-batch) needs its own cold compile and any of them
    # can wedge the tunnel — the measured record must already be on
    # disk when that happens (observed 2026-07-31: the post-headline
    # latency sections stalling while the headline was only in stderr).
    result = {
        "metric": (f"rate-limit decisions/sec/chip @{N_KEYS//1_000_000}M-key"
                   f" Zipf({ZIPF_A})"),
        "value": round(dps),
        "unit": "decisions/s",
        "vs_baseline": round(dps / TARGET, 4),
        "extra": {
            "step_mode": step_mode,
            "copy_mode_decisions_per_s": round(dps_copy),
            "donate_mode_decisions_per_s": round(dps_donate),
            "pallas_mode_decisions_per_s": round(dps_pallas),
            "pallas_err_fraction": pallas_err_frac,
            # the kernel owns its table layout: bucketized AoS rows,
            # sized independently of the XLA CAP in `config` — the
            # headline must not be attributed to a table it didn't use
            "pallas_table_rows": (pallas_rows
                                  if pallas_err_frac is not None
                                  else None),
            "device_batch": B,
            "backend": backend,
            "populate_errs": dict(populate_errs),
            "probes": int(os.environ.get("GUBER_PROBES", "8")),
            "ksplit": int(os.environ.get("GUBER_KSPLIT", "0")),
            "config": (f"TOKEN_BUCKET {N_KEYS} keys Zipf({ZIPF_A}) hits=1 "
                       f"CAP={CAP} "
                       f"probes={os.environ.get('GUBER_PROBES', '8')}"),
            "baseline_is": ("north-star target 50M decisions/s/chip (no "
                            "published reference numbers; BASELINE.md)"),
            "baseline_configs": {},
        },
    }
    if step_mode == "pallas":
        result["extra"]["config"] += (
            f" (headline mode pallas: bucketized table "
            f"{pallas_rows} rows, not CAP)")
    _write_partial(result)

    # link round-trip floor: a trivial op's dispatch→sync time.  On a
    # direct-attached chip this is ~50 µs; over the axon tunnel it is
    # the WAN round trip (~0.5 ms, with multi-ms jitter tails).  The
    # client-batch percentiles below include this floor, so recording
    # it lets the p99<2ms target be decomposed into device+host work
    # vs link cost from this JSON alone.
    link_p50 = link_p99 = -1.0
    try:
        one = jnp.ones((), jnp.int32)
        trivial = jax.jit(lambda x: x + 1)
        trivial(one).block_until_ready()
        link = []
        for _ in range(60):
            t0 = time.perf_counter()
            trivial(one).block_until_ready()
            link.append((time.perf_counter() - t0) * 1e3)
        link_p50 = float(np.percentile(link, 50))
        link_p99 = float(np.percentile(link, 99))
        log(f"link round-trip: p50={link_p50:.3f}ms p99={link_p99:.3f}ms")
    except Exception as e:  # noqa: BLE001
        log(f"link-rtt probe failed: {e!r:.200}")

    # single-batch round-trip latency (host dispatch included), in the
    # winning mode — the copy cost it avoids is latency too
    p50 = p99 = -1.0
    try:
        lats = []
        for i in range(50):
            t0 = time.perf_counter()
            state, out = step_best(state,
                                   make_batch(key_batches[i % n_batches]),
                                   jnp.asarray(NOW0 + 500 + i, i64))
            out.status.block_until_ready()
            lats.append((time.perf_counter() - t0) * 1e3)
        p50 = float(np.percentile(lats, 50))
        p99 = float(np.percentile(lats, 99))
        log(f"latency: p50={p50:.3f}ms p99={p99:.3f}ms (batch={B})")
    except Exception as e:  # noqa: BLE001
        log(f"latency section failed: {e!r:.200}")

    # The parent's own device work is DONE.  Everything below is a
    # child-process section (fresh compiles, wedge-isolated).  Release
    # this process's device client first: if the tunnel is single-
    # client-exclusive, a held parent client would block every child's
    # backend init; on multi-client links the release is harmless.
    # Best-effort — buffers must drop first or the client stays alive.
    global _EXPECT_BACKEND
    _EXPECT_BACKEND = backend
    if backend != "cpu":
        try:
            import gc

            # closures (make_batch/populate/measure_mode) pin the
            # arrays through their cells — drop them all, or the
            # buffers keep the client alive through clear_backends
            del state, out, key_batches, const, make_batch, populate
            del measure_mode, step_best
            gc.collect()
            from jax.extend.backend import clear_backends

            clear_backends()
            log("released the parent device client before child sections")
        except Exception as e:  # noqa: BLE001
            log(f"device-client release failed (continuing): {e!r:.120}")

    # device-resident superstep (fresh compile — child-isolated on
    # device backends so a wedged scan compile can't cost later rows)
    scan_rows = _run_section("scan", inline=(backend == "cpu"))
    dps_scan = float(scan_rows.get("device_scan_decisions_per_s", 0.0))
    if "error" in scan_rows:
        log(f"device-scan section: {scan_rows['error']}")
    else:
        log(f"device-scan sustained: {dps_scan/1e6:.2f}M/s "
            f"(R={scan_rows.get('scan_R')})")

    # client-shaped latency: one max-size GetRateLimits batch (1000 reqs
    # in a 1024 bucket) per device call — the p99<2ms target's shape.
    # Fresh-compile section: on a device backend it runs in a CHILD
    # process so a wedged compile (observed 2026-07-31: this exact
    # shape hung the tunnel's compile server for 40+ min) costs this
    # row, not the rest of the run.
    os.environ["GUBER_BENCH_STEP_MODE"] = xla_mode
    if _WEDGED and backend != "cpu":
        # the scan section timed out AND the follow-up probe failed:
        # don't burn another section timeout + probe on a dead link —
        # the watchdog budget assumes at most ONE wedged section.
        # "skipped" (not "error"): collateral of an earlier wedge, the
        # same key run_secondary_configs uses (BASELINE.md documents
        # the distinction).
        lat_rows = {"skipped": "device link wedged in the scan "
                               "section; probe failed"}
    else:
        lat_rows = _run_section("lat_client", inline=(backend == "cpu"))
    p50_c = float(lat_rows.get("client_batch_p50_ms", -1.0))
    p99_c = float(lat_rows.get("client_batch_p99_ms", -1.0))
    if "error" in lat_rows or "skipped" in lat_rows:
        log(f"client-batch latency section: "
            f"{lat_rows.get('error', lat_rows.get('skipped'))}")
    else:
        log(f"client-batch latency: p50={p50_c:.3f}ms p99={p99_c:.3f}ms "
            f"(batch=1024)")

    # host-side string-hash throughput (the other half of a real dispatch)
    from gubernator_tpu.hashing import hash_keys
    names = [f"bench_k{i}" for i in range(100_000)]
    t0 = time.perf_counter()
    hash_keys(names)
    hash_mkeys = len(names) / (time.perf_counter() - t0) / 1e6

    result["extra"].update({
        "device_scan_decisions_per_s": round(dps_scan),
        "p50_ms": round(p50, 3),
        "p99_ms": round(p99, 3),
        "client_batch_p50_ms": round(p50_c, 3),
        "client_batch_p99_ms": round(p99_c, 3),
        "link_roundtrip_p50_ms": round(link_p50, 3),
        "link_roundtrip_p99_ms": round(link_p99, 3),
        "host_hash_mkeys_per_s": round(hash_mkeys, 2),
    })
    # a consumer of this JSON must be able to tell a wedged/failed
    # section (sentinel 0 / -1 values) from a measured one
    if "error" in scan_rows:
        result["extra"]["device_scan_error"] = scan_rows["error"]
    if "error" in lat_rows:
        result["extra"]["client_batch_error"] = lat_rows["error"]
    elif "skipped" in lat_rows:
        result["extra"]["client_batch_skipped"] = lat_rows["skipped"]
    # Checkpoint again after the latency sections and after every
    # secondary config: a late-stage device wedge (observed: the cap27
    # cold compile killing the tunnel's compile server) must not cost
    # the rows already measured — the watchdog salvages this file.
    _write_partial(result)

    def ck(cfgs):
        result["extra"]["baseline_configs"] = cfgs
        _write_partial(result)

    configs = run_secondary_configs(xla_mode, backend, checkpoint=ck)
    # north-star p99 decomposition (VERDICT r2 item 2): on a tunneled
    # device the svc percentiles include the WAN round trip; subtract
    # the measured trivial-op link floor to estimate what a
    # direct-attached chip would serve (recorded, never substituted
    # for the measured value)
    for row_key in ("6_service_path", "11_pallas_serving"):
        svc = configs.get(row_key, {})
        if (backend != "cpu" and link_p50 > 0
                and isinstance(svc, dict) and svc.get("svc_p99_ms")):
            svc["svc_p99_direct_attach_est_ms"] = round(
                max(float(svc["svc_p99_ms"]) - link_p50, 0.0), 3)
            svc["svc_p99_est_context"] = (
                "svc_p99_ms minus link_roundtrip_p50_ms (each synced "
                "call pays one link round trip); direct-attach "
                "estimate only")
    result["extra"]["baseline_configs"] = configs
    # provenance: was the tree guberlint-clean when this row was
    # measured?  A BENCH row from an unanalyzable tree (violated lock
    # discipline, drifted registries) is a number with an asterisk —
    # record the asterisk (CONCURRENCY.md; tools/guberlint).
    result["extra"]["lint_clean"] = _lint_clean()
    _write_partial(result)
    print(json.dumps(result))


def _lint_clean():
    """Provenance block: the guberlint verdict for the tree this row
    was measured on (clean flag + pass/violation counts) plus the
    process's compile-ledger verdict — the runtime retrace
    cross-check.  None when the linter itself could not run (never
    fails the bench)."""
    try:
        from tools.guberlint import PASS_NAMES, run_passes

        violations = run_passes()
        block = {"clean": not violations, "passes": len(PASS_NAMES),
                 "violations": len(violations)}
    except Exception as e:  # noqa: BLE001 - provenance only
        log(f"lint_clean probe failed: {(str(e) or repr(e))[:120]}")
        return None
    try:
        from gubernator_tpu.compileledger import LEDGER

        block["compile_ledger"] = LEDGER.verdict()
    except Exception as e:  # noqa: BLE001 - provenance only
        log(f"compile_ledger probe failed: {(str(e) or repr(e))[:120]}")
        block["compile_ledger"] = None
    return block


PARTIAL_PATH = os.environ.get("GUBER_BENCH_PARTIAL",
                              "/tmp/gubernator_bench_partial.json")


def _write_partial(result: dict) -> None:
    try:
        tmp = PARTIAL_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(result, f)
        os.replace(tmp, PARTIAL_PATH)
    except OSError as e:  # pragma: no cover - diagnostics only
        log(f"partial checkpoint write failed: {e}")


_BUMP_CACHE: dict = {}


def _bump_fn(delta=1):
    """Shared jitted device-side `now += delta` (one compile per delta
    per process — jit caches per function object, so per-call lambdas
    would re-trace every time)."""
    f = _BUMP_CACHE.get(delta)
    if f is None:
        import jax

        f = jax.jit(lambda t: t + delta)
        _BUMP_CACHE[delta] = f
    return f


def _sustain(decide_batch, jnp, state, batches, reps, now0):
    """Measure a sustained dispatch loop → decisions/s.  The advancing
    `now` lives on device (one transfer + a jitted bump per rep): per-rep
    host→device transfers are synchronous on the tunneled backend and
    would turn the loop into a link-RTT measurement."""
    i64 = jnp.int64
    bump = _bump_fn()
    # warm the bump OUTSIDE the timed region (its first call is a
    # synchronous remote compile over the tunnel): now0-1 → now0
    now_dev = bump(jnp.asarray(now0 - 1, i64))
    now_dev.block_until_ready()
    out = None
    t0 = time.perf_counter()
    for r in range(reps):
        state, out = decide_batch(state, batches[r % len(batches)],
                                  now_dev)
        now_dev = bump(now_dev)
    out.status.block_until_ready()
    dt = time.perf_counter() - t0
    return reps * batches[0].key.shape[0] / dt, state


# ---- sections -----------------------------------------------------------
#
# Every secondary config (and the client-batch latency probe) is a
# SECTION: a self-contained function that builds its own inputs, runs,
# and returns a dict of result rows.  On the CPU backend sections run
# inline (no wedge risk, no re-init cost).  On a device backend each
# runs in a CHILD process: a section needs its own cold compile, and a
# wedged tunnel compile (observed twice on 2026-07-31) otherwise stalls
# the whole run — child isolation turns "lost the rest of the bench"
# into "lost one row".  After a section timeout the parent probes the
# device link; if the probe fails, remaining device sections are
# skipped with an explicit note instead of burning their timeouts.


def _mk_batch(jnp, keys, **over):
    """RequestBatch with bench-default columns (scalar-now serving
    shape: the `now` column is 0 so _sustain's advancing scalar now
    drives time)."""
    from gubernator_tpu.core.batch import RequestBatch

    i64, i32 = jnp.int64, jnp.int32
    B2 = keys.shape[0]
    cols = dict(
        hits=jnp.ones(B2, i64), limit=jnp.full(B2, LIMIT, i64),
        duration=jnp.full(B2, DURATION_MS, i64),
        eff_ms=jnp.full(B2, DURATION_MS, i64),
        greg_end=jnp.zeros(B2, i64), behavior=jnp.zeros(B2, i32),
        algorithm=jnp.zeros(B2, i32), burst=jnp.full(B2, LIMIT, i64),
        valid=jnp.ones(B2, bool),
        now=jnp.zeros(B2, i64))
    cols.update(over)
    return RequestBatch(key=jnp.asarray(keys), **cols)


def _make_reqs(rng, name="svc"):
    """4 batches × 1000 Zipf-keyed RateLimitRequests.  Sections that
    must serve the SAME workload (svc object lane vs its wire lane;
    the cluster row vs round-2's recorded numbers) all draw these from
    a fresh seed-7 rng, so the bytes are identical across sections and
    rounds."""
    from gubernator_tpu.types import RateLimitRequest

    return [[RateLimitRequest(name=name, unique_key=f"k{int(k)}",
                              hits=1, limit=100, duration=60_000)
             for k in rng.zipf(ZIPF_A, size=1000) % 100_000]
            for _ in range(4)]


def _telemetry_rows(inst) -> dict:
    """Dispatcher wave-telemetry snapshot for a section's BENCH row
    (wave-size/step-duration percentiles, stall/timeout counts — see
    OBSERVABILITY.md).  A future perf round that loses a section to a
    slow wave diagnoses itself from this block instead of an empty
    TimeoutError (the round-5 failure shape)."""
    try:
        return inst.dispatcher.telemetry_snapshot()
    except Exception as e:  # noqa: BLE001 - telemetry must not cost rows
        return {"error": (str(e) or repr(e))[:200]}


def _analytics_rows(inst) -> dict:
    """ISSUE 4: the /debug/topkeys + /debug/phases snapshot for the
    BENCH row — which keys were hot and where the milliseconds went,
    auditable from the JSON alone.  Truncated to the heaviest 16."""
    ana = inst.analytics
    if ana is None:
        return {"skipped": "analytics disabled (GUBER_ANALYTICS=0)"}
    try:
        ana.flush(timeout=5.0)
        snap = ana.topkeys_snapshot(16)
        snap["phases"] = ana.phases_snapshot()["phases"]
        return snap
    except Exception as e:  # noqa: BLE001 - analytics must not cost rows
        return {"error": (str(e) or repr(e))[:200]}


def _analytics_ab(inst, call, pairs=5, reps=30) -> dict:
    """ISSUE 4 acceptance: the analytics tap must cost < 3 % throughput.
    Interleaved on/off timing pairs of the same call — detaching the
    ONE dispatcher.analytics reference darkens every tap — with the
    median of per-pair ratios cancelling the shared host's drift.  The
    ON arm flushes the worker's paced backlog before the OFF arm is
    timed (deferred fold work must not leak into the baseline), and an
    untimed warmup pair absorbs first-use costs (label children, fold
    buffers).  Skipped when analytics is off (no baseline)."""
    disp = inst.dispatcher
    ana = disp.analytics
    if ana is None:
        return {"skipped": "no analytics attached (GUBER_ANALYTICS=0)"}

    def rate():
        t0 = time.perf_counter()
        for r in range(reps):
            call(r)
        return reps / (time.perf_counter() - t0)

    try:
        ratios, on_r, off_r = [], [], []
        for pair in range(pairs + 1):
            disp.analytics = ana
            on = rate()
            ana.flush(timeout=5.0)
            disp.analytics = None
            off = rate()
            if pair == 0:
                continue  # warmup pair, untimed
            ratios.append(off / on)
            on_r.append(on)
            off_r.append(off)
        overhead = (float(np.median(ratios)) - 1.0) * 100
        row = {"overhead_pct": round(overhead, 2),
               "overhead_ok": bool(overhead < 3.0),
               "on_calls_per_s": round(float(np.median(on_r)), 1),
               "off_calls_per_s": round(float(np.median(off_r)), 1),
               "pairs": pairs, "reps": reps}
        if not row["overhead_ok"]:
            row["warning"] = ("analytics tap measured above the 3% "
                              "budget on this run; single-host noise — "
                              "re-run before acting on it")
        return row
    except Exception as e:  # noqa: BLE001 - diagnostics only
        return {"error": (str(e) or repr(e))[:200]}
    finally:
        disp.analytics = ana


def _tenant_ab(inst, call, pairs=5, reps=30) -> dict:
    """ISSUE 11 acceptance: tenant attribution must cost < 3 %
    throughput on top of the analytics tap.  Same interleaved-pair
    median discipline as ``_analytics_ab``, but the toggle is the
    ledger itself: detaching ``ana._tenants`` darkens every tenant
    fold/flag site while the rest of the analytics plane keeps
    running, so the measured delta is attribution alone."""
    disp = inst.dispatcher
    ana = disp.analytics
    if ana is None:
        return {"skipped": "no analytics attached (GUBER_ANALYTICS=0)"}
    ledger = ana._tenants
    if ledger is None:
        return {"skipped": "tenant ledger detached"}

    def rate():
        t0 = time.perf_counter()
        for r in range(reps):
            call(r)
        return reps / (time.perf_counter() - t0)

    try:
        ratios, on_r, off_r = [], [], []
        for pair in range(pairs + 1):
            ana._tenants = ledger
            on = rate()
            ana.flush(timeout=5.0)  # paced tenant folds out of OFF arm
            ana._tenants = None
            off = rate()
            if pair == 0:
                continue  # warmup pair, untimed
            ratios.append(off / on)
            on_r.append(on)
            off_r.append(off)
        overhead = (float(np.median(ratios)) - 1.0) * 100
        row = {"overhead_pct": round(overhead, 2),
               "overhead_ok": bool(overhead < 3.0),
               "on_calls_per_s": round(float(np.median(on_r)), 1),
               "off_calls_per_s": round(float(np.median(off_r)), 1),
               "pairs": pairs, "reps": reps}
        if not row["overhead_ok"]:
            row["warning"] = ("tenant attribution measured above the "
                              "3% budget on this run; single-host "
                              "noise — re-run before acting on it")
        return row
    except Exception as e:  # noqa: BLE001 - diagnostics only
        return {"error": (str(e) or repr(e))[:200]}
    finally:
        ana._tenants = ledger


def _faults_ab(inst, call, pairs=5, reps=30) -> dict:
    """ISSUE 5 acceptance: fault injection must be zero-cost while
    disarmed (<1% on the service path with GUBER_FAULT unset).

    Interleaved timing pairs of the same call in three states:
    *disarmed* (the shipping default — every instrumented site pays one
    attribute read), *detached* (the FaultSet reference removed /
    stubbed, the closest runtime proxy for uninstrumented code), and
    *armed* on an off-path point (``snapshot:error`` — the gate is hot,
    every site pays the lock + match).  ``disarmed_overhead_pct`` is
    the acceptance number (disarmed vs detached); ``armed_noop_pct``
    records what arming costs, i.e. what the disarmed gate saves.  The
    true pre-instrumentation baseline is the row's recorded pre-PR
    trajectory (concurrent16)."""
    disp = inst.dispatcher
    fs = inst.faults

    class _Detached:  # armed=False: byte-for-byte the disarmed branch
        armed = False

    dummy = _Detached()

    def rate():
        t0 = time.perf_counter()
        for r in range(reps):
            call(r)
        return reps / (time.perf_counter() - t0)

    def _state(which):
        if which == "det":
            inst.faults = dummy
            disp._faults = None
            return
        inst.faults = fs
        disp._faults = fs
        fs.arm("snapshot:error" if which == "arm" else "")

    def _measure(which):
        _state(which)
        try:
            return rate()
        finally:
            _state("dis")

    try:
        r_dis, r_det, r_arm = [], [], []
        for pair in range(pairs + 1):
            # alternate order per pair so monotonic host drift cancels
            # in the per-pair ratios instead of biasing them
            order = (("dis", "det", "arm") if pair % 2
                     else ("arm", "det", "dis"))
            got = {w: _measure(w) for w in order}
            if pair == 0:
                continue  # warmup pair, untimed
            r_dis.append(got["dis"])
            r_det.append(got["det"])
            r_arm.append(got["arm"])
        disarmed = (float(np.median([d / x for d, x
                                     in zip(r_det, r_dis)])) - 1) * 100
        armed = (float(np.median([d / x for d, x
                                  in zip(r_dis, r_arm)])) - 1) * 100
        row = {"disarmed_overhead_pct": round(disarmed, 2),
               "overhead_ok": bool(disarmed < 1.0),
               "armed_noop_pct": round(armed, 2),
               "disarmed_calls_per_s": round(float(np.median(r_dis)), 1),
               "pairs": pairs, "reps": reps}
        if not row["overhead_ok"]:
            row["warning"] = ("disarmed faultpoint checks measured "
                              "above the 1% budget on this run; "
                              "single-host noise — re-run before "
                              "acting on it")
        return row
    except Exception as e:  # noqa: BLE001 - diagnostics only
        return {"error": (str(e) or repr(e))[:200]}
    finally:
        inst.faults = fs
        disp._faults = fs
        try:
            fs.arm("")
        except Exception:  # noqa: BLE001
            pass


def _tracing_ab(inst, call, pairs=5, reps=30) -> dict:
    """ISSUE 12 acceptance: the trace plane must stay off the hot path
    — armed-but-unsampled (the shipping default, GUBER_TRACE_SAMPLE=0)
    < 1% on the service path, 1%-sampled < 3%.

    Interleaved timing pairs of the same call in three states: *off*
    (span recorder detached from the dispatcher AND the request
    context — the pre-instrumentation proxy), *armed* (recorder
    attached, sample=0: every request pays span buffering + a
    commit-and-drop), and *sampled* (sample=0.01: the realistic prod
    rate, ~1 in 100 traces retained into the ring).  Every arm wraps
    the call in ``tracing.request_context`` so the trace-id plumbing
    itself (pre-ISSUE 12 behavior) is in the baseline; only the span
    plane toggles.  Same alternating-order median-of-ratios discipline
    as ``_faults_ab``."""
    from gubernator_tpu.tracing import request_context

    disp = inst.dispatcher
    rec = inst.span_recorder
    old_sample = rec.sample

    state = {"rec": None}

    def rate():
        r_ctx = state["rec"]
        t0 = time.perf_counter()
        for r in range(reps):
            with request_context(None, recorder=r_ctx):
                call(r)
        return reps / (time.perf_counter() - t0)

    def _state(which):
        if which == "off":
            disp.span_recorder = None
            state["rec"] = None
            return
        disp.span_recorder = rec
        state["rec"] = rec
        rec.sample = 0.01 if which == "smp" else 0.0

    def _measure(which):
        _state(which)
        try:
            return rate()
        finally:
            _state("off")

    try:
        r_off, r_arm, r_smp = [], [], []
        for pair in range(pairs + 1):
            # alternate order per pair so monotonic host drift cancels
            # in the per-pair ratios instead of biasing them
            order = (("off", "arm", "smp") if pair % 2
                     else ("smp", "arm", "off"))
            got = {w: _measure(w) for w in order}
            if pair == 0:
                continue  # warmup pair, untimed
            r_off.append(got["off"])
            r_arm.append(got["arm"])
            r_smp.append(got["smp"])
        armed = (float(np.median([o / a for o, a
                                  in zip(r_off, r_arm)])) - 1) * 100
        sampled = (float(np.median([o / s for o, s
                                    in zip(r_off, r_smp)])) - 1) * 100
        row = {"armed_overhead_pct": round(armed, 2),
               "overhead_ok": bool(armed < 1.0),
               "sampled_overhead_pct": round(sampled, 2),
               "sampled_ok": bool(sampled < 3.0),
               "off_calls_per_s": round(float(np.median(r_off)), 1),
               "pairs": pairs, "reps": reps}
        if not (row["overhead_ok"] and row["sampled_ok"]):
            row["warning"] = ("trace plane measured above budget "
                              "(armed<1% / 1%-sampled<3%) on this "
                              "run; single-host noise — re-run "
                              "before acting on it")
        return row
    except Exception as e:  # noqa: BLE001 - diagnostics only
        return {"error": (str(e) or repr(e))[:200]}
    finally:
        disp.span_recorder = rec
        rec.sample = old_sample


def _memledger_ab(inst, call, pairs=5, reps=30) -> dict:
    """ISSUE 13 acceptance: the device-memory ledger must stay off the
    hot path — enrollment is registration-only and probes run on the
    SLO tick / scrape threads, so steady-state serving overhead must
    pin < 1%.

    Interleaved timing pairs of the same call in two states: *off*
    (ledger suspended — snapshots answer empty, nothing else changes)
    and *on* (the shipping default; one out-of-band pressure_sample
    between blocks keeps the plane exercised the way the 1 Hz SLO tick
    does without charging tick work to the serving thread).  Same
    alternating-order median-of-ratios discipline as ``_tracing_ab``."""
    led = getattr(inst, "memledger", None)
    if led is None:
        return {"error": "memory ledger disabled (GUBER_MEM_LEDGER=0)"}

    def rate():
        t0 = time.perf_counter()
        for r in range(reps):
            call(r)
        return reps / (time.perf_counter() - t0)

    def _measure(which):
        if which == "on":
            led.resume()
            led.pressure_sample()  # untimed: tick-thread work in prod
        else:
            led.suspend()
        try:
            return rate()
        finally:
            led.suspend()

    try:
        r_on, r_off = [], []
        for pair in range(pairs + 1):
            # alternate order per pair so monotonic host drift cancels
            order = ("off", "on") if pair % 2 else ("on", "off")
            got = {w: _measure(w) for w in order}
            if pair == 0:
                continue  # warmup pair, untimed
            r_on.append(got["on"])
            r_off.append(got["off"])
        overhead = (float(np.median([o / n for o, n
                                     in zip(r_off, r_on)])) - 1) * 100
        row = {"overhead_pct": round(overhead, 2),
               "overhead_ok": bool(overhead < 1.0),
               "on_calls_per_s": round(float(np.median(r_on)), 1),
               "off_calls_per_s": round(float(np.median(r_off)), 1),
               "pairs": pairs, "reps": reps}
        if not row["overhead_ok"]:
            row["warning"] = ("memory ledger measured above its <1% "
                              "budget on this run; single-host noise "
                              "— re-run before acting on it")
        return row
    except Exception as e:  # noqa: BLE001 - diagnostics only
        return {"error": (str(e) or repr(e))[:200]}
    finally:
        led.resume()


def _hbm_block(inst):
    """Standardized ledger sub-block for the engine rows (6/11/12/13,
    ISSUE 13): bytes + occupancy per consumer from ONE snapshot, so
    rows compare like-for-like instead of each growing ad-hoc
    occupancy fields."""
    led = getattr(inst, "memledger", None)
    if led is None:
        return None
    try:
        snap = led.snapshot()
        out = {"device_bytes": snap["device_bytes"],
               "host_bytes": snap["host_bytes"],
               "pressure": round(snap["pressure"], 4)}
        for name, rec in snap["consumers"].items():
            if "error" in rec:
                continue
            out[name] = {"bytes": rec["bytes"],
                         "capacity_rows": rec["capacity_rows"],
                         "occupied_rows": rec["occupied_rows"]}
        return out
    except Exception as e:  # noqa: BLE001 - diagnostics only
        return {"error": (str(e) or repr(e))[:200]}


def _serialize_reqs(reqs_lists):
    """[[RateLimitRequest]] → serialized GetRateLimitsReq bytes."""
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.wire import req_to_pb

    datas = []
    for rs in reqs_lists:
        m = pb.GetRateLimitsReq()
        m.requests.extend(req_to_pb(r) for r in rs)
        datas.append(m.SerializeToString())
    return datas


def _sec_lat_client():
    """Client-shaped device latency: one 1024-row batch per synced call
    (the p99<2ms target's shape) over a CAP-sized table."""
    import jax.numpy as jnp

    from gubernator_tpu.core.step import decide_batch, decide_batch_donated
    from gubernator_tpu.core.table import init_table

    step = (decide_batch_donated
            if os.environ.get("GUBER_BENCH_STEP_MODE") == "donate"
            else decide_batch)
    i64 = jnp.int64
    rng = np.random.default_rng(42)
    Bc = 1024
    keys = _keyhash((rng.zipf(ZIPF_A, size=Bc) % N_KEYS).astype(np.uint64))
    small = _mk_batch(jnp, keys)
    state = init_table(CAP)
    state, outc = step(state, small, jnp.asarray(NOW0, i64))
    outc.status.block_until_ready()
    lats = []
    for i in range(100):
        t0 = time.perf_counter()
        state, outc = step(state, small, jnp.asarray(NOW0 + 1 + i, i64))
        outc.status.block_until_ready()
        lats.append((time.perf_counter() - t0) * 1e3)
    return {"client_batch_p50_ms": round(float(np.percentile(lats, 50)), 3),
            "client_batch_p99_ms": round(float(np.percentile(lats, 99)), 3)}


def _sec_scan():
    """Device-resident superstep: lax.scan chains R batches in ONE
    launch, so per-launch dispatch latency (µs locally, ~0.5 ms over a
    tunneled link) amortizes across R×B decisions — the on-chip
    sustained rate, which is what N coalesced client batches see."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from gubernator_tpu.core.batch import RequestBatch
    from gubernator_tpu.core.step import decide_batch_impl
    from gubernator_tpu.core.table import init_table

    i64 = jnp.int64
    R = int(os.environ.get("GUBER_BENCH_SCAN", 16))
    rng = np.random.default_rng(42)
    n_batches = 8
    draws = rng.zipf(ZIPF_A, size=n_batches * B) % N_KEYS
    kb = [jnp.asarray(_keyhash(draws[i * B:(i + 1) * B].astype(np.uint64)))
          for i in range(n_batches)]
    const = dict(
        hits=jnp.ones(B, i64), limit=jnp.full(B, LIMIT, i64),
        duration=jnp.full(B, DURATION_MS, i64),
        eff_ms=jnp.full(B, DURATION_MS, i64),
        greg_end=jnp.zeros(B, i64), behavior=jnp.zeros(B, jnp.int32),
        algorithm=jnp.zeros(B, jnp.int32), burst=jnp.full(B, LIMIT, i64),
        valid=jnp.ones(B, bool))

    @jax.jit
    def decide_scan(st, keys_rb, now0):
        def body(carry, x):
            st, i = carry
            b = RequestBatch(key=x, **const)
            st, out = decide_batch_impl(st, b, now0 + i)
            return (st, i + 1), out.status.sum()
        (st, _), overs = lax.scan(body, (st, jnp.asarray(0, i64)), keys_rb)
        return st, overs

    keys_rb = jnp.stack(kb[:min(R, n_batches)] * (R // n_batches + 1))[:R]
    st_s = init_table(CAP)
    st_s, ov = decide_scan(st_s, keys_rb, jnp.asarray(NOW0, i64))
    ov.block_until_ready()  # compile + warm
    reps_s = max(1, int(30_000_000 / (R * B)))
    bump_R = _bump_fn(R)  # device-side now advance: the inter-launch
    # `jnp.asarray` transfer is synchronous over the tunnel
    # warm outside the timed region: NOW0+1000-R → NOW0+1000
    now_dev = bump_R(jnp.asarray(NOW0 + 1000 - R, i64))
    now_dev.block_until_ready()
    t0 = time.perf_counter()
    for r in range(reps_s):
        st_s, ov = decide_scan(st_s, keys_rb, now_dev)
        now_dev = bump_R(now_dev)
    ov.block_until_ready()
    dps_scan = reps_s * R * B / (time.perf_counter() - t0)
    return {"device_scan_decisions_per_s": round(dps_scan),
            "scan_R": R}


def _sec_cfg12():
    """Configs 1+2: single-key TOKEN smoke (the duplicate-segment worst
    case) and LEAKY 1k keys."""
    import jax.numpy as jnp

    from gubernator_tpu.core.step import decide_batch
    from gubernator_tpu.core.table import init_table

    i64, i32 = jnp.int64, jnp.int32
    rng = np.random.default_rng(7)
    out = {}
    Bs = 4096
    try:
        keys1 = np.full(Bs, 12345, np.uint64)
        st = init_table(1 << 12)
        b = _mk_batch(jnp, keys1, limit=jnp.full(Bs, 10**9, i64))
        st, _ = decide_batch(st, b, jnp.asarray(NOW0, i64))  # compile
        dps1, _ = _sustain(decide_batch, jnp, st, [b], 20, NOW0 + 1)
        out["1_single_key_smoke"] = {"decisions_per_s": round(dps1)}
    except Exception as e:  # noqa: BLE001
        out["1_single_key_smoke"] = {"error": (str(e) or repr(e))[:200]}
    try:
        keys2 = _keyhash(rng.integers(0, 1000, size=Bs).astype(np.uint64))
        st = init_table(1 << 12)
        b2 = _mk_batch(jnp, keys2, algorithm=jnp.ones(Bs, i32),
                       limit=jnp.full(Bs, 10**6, i64),
                       burst=jnp.full(Bs, 10**6, i64),
                       duration=jnp.full(Bs, 60_000, i64),
                       eff_ms=jnp.full(Bs, 60_000, i64))
        st, _ = decide_batch(st, b2, jnp.asarray(NOW0, i64))
        dps2, _ = _sustain(decide_batch, jnp, st, [b2], 20, NOW0 + 1)
        out["2_leaky_1k_keys"] = {"decisions_per_s": round(dps2)}
    except Exception as e:  # noqa: BLE001
        out["2_leaky_1k_keys"] = {"error": (str(e) or repr(e))[:200]}
    return out


def _sec_cfg4():
    """Config 4: GLOBAL multi-peer ≙ sharded mesh step over all local
    devices (4-chip ICI on a pod; 1 chip here → shard_map overhead)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from gubernator_tpu.core.batch import RequestBatch
    from gubernator_tpu.parallel import make_mesh
    from gubernator_tpu.parallel.mesh import shard_table
    from gubernator_tpu.parallel.sharded import make_sharded_step

    i64 = jnp.int64
    rng = np.random.default_rng(7)
    mesh = make_mesh()
    n = mesh.shape["shard"]
    step = make_sharded_step(mesh)
    stg = shard_table(mesh, 1 << 18)
    Bg = 16384 * n
    keysg = _keyhash(rng.zipf(ZIPF_A, size=Bg) % 100_000)
    bg = _mk_batch(jnp, keysg)
    sh = NamedSharding(mesh, P("shard"))
    bg = RequestBatch(*[jax.device_put(np.asarray(x), sh) for x in bg])
    stg, o, _ = step(stg, bg, jnp.asarray(NOW0, i64))
    bump = _bump_fn()  # transfer-free now advance, warmed pre-timing
    now_dev = bump(jnp.asarray(NOW0, i64))  # NOW0 → NOW0+1
    now_dev.block_until_ready()
    t0 = time.perf_counter()
    reps = 20
    for r in range(reps):
        stg, o, _ = step(stg, bg, now_dev)
        now_dev = bump(now_dev)
    o[0].block_until_ready()
    dps4 = reps * Bg / (time.perf_counter() - t0)
    row = {"decisions_per_s": round(dps4), "n_shards": int(n)}
    if n == 1:
        row["context"] = ("single device: pays shard_map overhead with "
                          "no scaling; per-shard cost is flat 1→8 on "
                          "the virtual mesh (BASELINE.md weak-scaling "
                          "table)")
    return {"4_global_sharded": row}


def _section_checkpoint(rows: dict) -> None:
    """Per-lane checkpoint (ADVICE r5): sections with several
    independent device waits (svc has three-plus lanes, each able to
    eat a full 900 s GUBER_RESULT_TIMEOUT_S wait) write finished lanes
    to the section-out path as they land, so a subprocess killed at the
    section budget keeps every lane measured before the kill —
    _run_section salvages the file on TimeoutExpired."""
    path = os.environ.get("GUBER_BENCH_SECTION_OUT")
    if not path:
        return
    try:
        with open(path + ".tmp", "w") as f:
            json.dump(rows, f)
        os.replace(path + ".tmp", path)
    except OSError as e:  # pragma: no cover - diagnostics only
        log(f"section checkpoint write failed: {e}")


def _sec_svc():
    """Service path: full V1Instance routing + dispatcher + response
    assembly (benchmark_test.go › BenchmarkServer_GetRateLimit analog),
    its C++ wire lane, the 16-thread concurrent front door, and the
    peer-forwarding apply path (BenchmarkServer_GetPeerRateLimit).
    Each lane checkpoints as it finishes (_section_checkpoint)."""
    from gubernator_tpu.config import Config
    from gubernator_tpu.instance import V1Instance
    from gubernator_tpu.parallel import make_mesh

    rng = np.random.default_rng(7)
    out = {}
    inst = V1Instance(Config(cache_size=1 << 16, sweep_interval_ms=0),
                      mesh=make_mesh(n=1))
    try:
        reqs5 = _make_reqs(rng)
        inst.get_rate_limits(reqs5[0], now_ms=NOW0)
        t0 = time.perf_counter()
        reps = 20
        for r in range(reps):
            inst.get_rate_limits(reqs5[r % 4], now_ms=NOW0 + 1 + r)
        dps_svc = reps * 1000 / (time.perf_counter() - t0)
        out["6_service_path"] = {"decisions_per_s": round(dps_svc),
                                 "batch": 1000}
        _section_checkpoint(out)
        # the C++ wire lane (bytes → columns → device → bytes), the
        # path a gRPC client actually exercises
        try:
            # same 4000 requests through the wire lane as through the
            # object lane above — both lanes serve identical batches
            datas = _serialize_reqs(reqs5)
            inst.get_rate_limits_wire(datas[0], now_ms=NOW0 + 100)
            t0 = time.perf_counter()
            for r in range(reps):
                inst.get_rate_limits_wire(datas[r % 4],
                                          now_ms=NOW0 + 101 + r)
            out["6_service_path"]["wire_lane_decisions_per_s"] = round(
                reps * 1000 / (time.perf_counter() - t0))
            # service-layer latency at the client-batch shape (the
            # p99 < 2 ms target's request): bytes → decisions → bytes
            lat = []
            for r in range(60):
                t0 = time.perf_counter()
                inst.get_rate_limits_wire(datas[r % 4],
                                          now_ms=NOW0 + 130 + r)
                lat.append((time.perf_counter() - t0) * 1e3)
            out["6_service_path"]["svc_p50_ms"] = round(
                float(np.percentile(lat, 50)), 3)
            out["6_service_path"]["svc_p99_ms"] = round(
                float(np.percentile(lat, 99)), 3)
        except Exception as e:  # noqa: BLE001
            out["6_service_path"]["wire_lane_error"] = (str(e) or repr(e))[:200]
        # ISSUE 14 acceptance: the compile ledger proves the warmed
        # service path is retrace-stable — mark steady AFTER the loops
        # above compiled everything, serve another measured burst, and
        # record the verdict (steady_recompiles must be empty; the
        # static twin is guberlint's retrace pass)
        try:
            led = inst.compile_ledger
            led.mark_steady()
            for r in range(10):
                inst.get_rate_limits_wire(datas[r % 4],
                                          now_ms=NOW0 + 300 + r)
            out["6_service_path"]["compile_ledger"] = led.verdict()
        except Exception as e:  # noqa: BLE001
            out["6_service_path"]["compile_ledger"] = {
                "error": (str(e) or repr(e))[:200]}
        _section_checkpoint(out)
        # concurrent front door: 16 caller threads through the full
        # wire lane — the dispatcher coalesces them into shared waves
        try:
            import threading as _th

            n_threads, reps_c = 16, 8
            if hasattr(inst.engine, "warmup"):
                inst.engine.warmup()  # big-bucket program, outside timing
            inst.get_rate_limits_wire(datas[0], now_ms=NOW0 + 150)

            def _worker(t):
                for r in range(reps_c):
                    inst.get_rate_limits_wire(datas[(t + r) % 4],
                                              now_ms=NOW0 + 160 + r)

            ths = [_th.Thread(target=_worker, args=(t,))
                   for t in range(n_threads)]
            t0 = time.perf_counter()
            for th in ths:
                th.start()
            for th in ths:
                th.join()
            out["6_service_path"]["concurrent16_decisions_per_s"] = round(
                n_threads * reps_c * 1000 / (time.perf_counter() - t0))
            # ISSUE 2 acceptance record: the pre-PR value measured on
            # the same 1-core build host (pre-PR tree + the jax-compat
            # shim only), so the overlapped-pipeline speedup is
            # auditable from this JSON alone
            out["6_service_path"][
                "concurrent16_pre_pr_decisions_per_s"] = 348177
            out["6_service_path"]["pre_pr_context"] = (
                "pre-PR baseline measured 2026-08-04 on the 1-core "
                "build host (CPU backend); comparable only on that "
                "host class — PERF.md §8")
        except Exception as e:  # noqa: BLE001
            out["6_service_path"]["concurrent_error"] = (str(e) or repr(e))[:200]
        _section_checkpoint(out)
        # host-glue decomposition (tools/hostpath_prof.py): the §4.2
        # buckets measured live on this instance — a perf round reads
        # parse/pack vs dispatcher/future vs build straight from the
        # BENCH row instead of re-deriving them with cProfile by hand
        try:
            from tools.hostpath_prof import profile_wire_calls

            out["6_service_path"]["host_glue"] = profile_wire_calls(
                inst, datas, reps=10, now0=NOW0 + 400)
        except Exception as e:  # noqa: BLE001
            out["6_service_path"]["host_glue_error"] = (
                str(e) or repr(e))[:200]
        # ISSUE 4: tap overhead A/B on the wire lane (<3%, skip-if-no-
        # baseline) — same request bytes as the measured loops above
        try:
            out["6_service_path"]["analytics_ab"] = _analytics_ab(
                inst, lambda r: inst.get_rate_limits_wire(
                    datas[r % 4], now_ms=NOW0 + 500 + r))
        except Exception as e:  # noqa: BLE001
            out["6_service_path"]["analytics_ab"] = {
                "error": (str(e) or repr(e))[:200]}
        # ISSUE 11 acceptance: tenant attribution overhead A/B on the
        # same wire-lane call (<3% on top of the analytics tap)
        try:
            out["6_service_path"]["tenant_ab"] = _tenant_ab(
                inst, lambda r: inst.get_rate_limits_wire(
                    datas[r % 4], now_ms=NOW0 + 600 + r))
        except Exception as e:  # noqa: BLE001
            out["6_service_path"]["tenant_ab"] = {
                "error": (str(e) or repr(e))[:200]}
        # ISSUE 5 acceptance: disarmed faultpoint checks must cost <1%
        # on the service path (same request bytes as the loops above)
        try:
            out["6_service_path"]["faults_ab"] = _faults_ab(
                inst, lambda r: inst.get_rate_limits_wire(
                    datas[r % 4], now_ms=NOW0 + 700 + r))
        except Exception as e:  # noqa: BLE001
            out["6_service_path"]["faults_ab"] = {
                "error": (str(e) or repr(e))[:200]}
        # ISSUE 12 acceptance: trace-plane overhead A/B on the same
        # wire-lane call (armed-unsampled <1%, 1%-sampled <3%)
        try:
            out["6_service_path"]["tracing_ab"] = _tracing_ab(
                inst, lambda r: inst.get_rate_limits_wire(
                    datas[r % 4], now_ms=NOW0 + 800 + r))
        except Exception as e:  # noqa: BLE001
            out["6_service_path"]["tracing_ab"] = {
                "error": (str(e) or repr(e))[:200]}
        # ISSUE 13 acceptance: device-memory ledger overhead A/B on
        # the same wire-lane call (steady-state <1%)
        try:
            out["6_service_path"]["memledger_ab"] = _memledger_ab(
                inst, lambda r: inst.get_rate_limits_wire(
                    datas[r % 4], now_ms=NOW0 + 900 + r))
        except Exception as e:  # noqa: BLE001
            out["6_service_path"]["memledger_ab"] = {
                "error": (str(e) or repr(e))[:200]}
        _section_checkpoint(out)
        # peer-forwarding path: what the owner-side apply of a
        # forwarded batch takes, via its wire lane (since ISSUE 3 the
        # fused C++ ingest: received TLV bytes → leased packed wave →
        # device → response bytes).  Same harness shape as the pre-PR
        # rounds (sequential 1000-req applies), so the pre/post ratio
        # is like-for-like.
        try:
            from gubernator_tpu.proto import peers_pb2 as peers_pb
            from gubernator_tpu.wire import req_to_pb

            pdatas = []
            for rs in reqs5:
                m = peers_pb.GetPeerRateLimitsReq()
                m.requests.extend(req_to_pb(r) for r in rs)
                pdatas.append(m.SerializeToString())
            inst.get_peer_rate_limits_wire(pdatas[0], now_ms=NOW0 + 200)
            t0 = time.perf_counter()
            for r in range(reps):
                inst.get_peer_rate_limits_wire(pdatas[r % 4],
                                               now_ms=NOW0 + 201 + r)
            out["8_peer_path"] = {
                "decisions_per_s": round(
                    reps * 1000 / (time.perf_counter() - t0)),
                "batch": 1000,
                # ISSUE 3 acceptance record: the same loop measured on
                # this host at the pre-PR tree (HEAD^ worktree, median
                # of 3 runs), so the columnar-ingest speedup audits
                # from this JSON alone
                "pre_pr_decisions_per_s": 342870,
                "pre_pr_context": (
                    "pre-PR baseline measured 2026-08-04 on this "
                    "1-core build host (CPU backend, median of 3 "
                    "same-harness runs; run-to-run spread ~±15% on "
                    "this shared host) — PERF.md §9")}
            # ISSUE 4: tap overhead A/B on the forwarded-hop apply path
            out["8_peer_path"]["analytics_ab"] = _analytics_ab(
                inst, lambda r: inst.get_peer_rate_limits_wire(
                    pdatas[r % 4], now_ms=NOW0 + 600 + r))
        except Exception as e:  # noqa: BLE001
            out["8_peer_path"] = {"error": (str(e) or repr(e))[:200]}
        if "6_service_path" in out:
            out["6_service_path"]["telemetry"] = _telemetry_rows(inst)
            # ISSUE 4: which keys were hot + where the ms went, straight
            # in the BENCH row (top-16 of the ledger + the phase ledger)
            out["6_service_path"]["analytics"] = _analytics_rows(inst)
            # ISSUE 13: the standardized per-consumer memory block
            out["6_service_path"]["hbm"] = _hbm_block(inst)
    finally:
        inst.close()
    return out


def _sec_cluster():
    """Clustered service path (VERDICT r1 item 4's bench criterion):
    client-facing GetRateLimits through daemon 0 of a real 3-daemon
    loopback cluster, keys ring-split across owners, forwards riding
    the raw-TLV peer wire."""
    from gubernator_tpu import cluster as cluster_mod

    # identical bytes to the svc section's wire batches (fresh seed-7
    # rng draws the same keys) — intra-run svc↔cluster identity.  NOTE:
    # the section refactor changed the RNG stream vs rounds ≤2 (one
    # shared seed-7 rng used to be consumed in order across cfg2/cfg4/
    # svc); rows 4/6/8/9/10 workload bytes are comparable only within
    # and after round 3 (recorded in BASELINE.md).
    datas = _serialize_reqs(_make_reqs(np.random.default_rng(7)))
    c3 = cluster_mod.start(3, cache_size=1 << 14, batch_rows=1024)
    try:
        inst0 = c3.instance_at(0)
        reps = 12
        inst0.get_rate_limits_wire(datas[0], now_ms=NOW0 + 300)
        t0 = time.perf_counter()
        for r in range(reps):
            inst0.get_rate_limits_wire(datas[r % 4],
                                       now_ms=NOW0 + 301 + r)
        dps_c3 = reps * 1000 / (time.perf_counter() - t0)
        lane = inst0.metrics.wire_lane_counter.labels(
            lane="wire_clustered")._value.get()
        # conservation (ISSUE 3 acceptance): one shared key drained
        # through ALL THREE daemons must debit exactly once per hit —
        # ring ownership + the pooled forward lanes must not lose,
        # duplicate, or misroute a request
        conserved = None
        try:
            from gubernator_tpu.proto import gubernator_pb2 as _pb

            def _one(hits):
                m = _pb.GetRateLimitsReq()
                rq = m.requests.add()
                rq.name, rq.unique_key = "c3cons", "shared"
                rq.hits, rq.limit, rq.duration = hits, 10**6, 600_000
                return m.SerializeToString()

            for d in range(3):
                c3.instance_at(d).get_rate_limits_wire(
                    _one(5), now_ms=NOW0 + 400 + d)
            q = _pb.GetRateLimitsResp.FromString(
                inst0.get_rate_limits_wire(_one(0), now_ms=NOW0 + 410))
            conserved = int(q.responses[0].remaining) == 10**6 - 15
        except Exception as e:  # noqa: BLE001
            conserved = f"check failed: {(str(e) or repr(e))[:120]}"
        row = {"decisions_per_s": round(dps_c3), "daemons": 3,
               "wire_clustered_requests": int(lane),
               "conservation_exact": conserved,
               "telemetry": _telemetry_rows(inst0)}
        # ISSUE 5: degraded-mode throughput vs the healthy baseline —
        # fault-kill one owner's forwards (faults.py) and remeasure the
        # same loop; rows owned by the dead peer answer locally with
        # the degraded flag instead of error rows.  The first reps pay
        # retry+backoff until the circuit opens, then fail-fast +
        # local serve — that transition is part of the number.
        try:
            vaddr = c3.peer_at(2).grpc_address
            inst0.faults.arm(f"peer_send@{vaddr}:error", seed=7)
            inst0.get_rate_limits_wire(datas[0], now_ms=NOW0 + 500)
            t0 = time.perf_counter()
            for r in range(reps):
                inst0.get_rate_limits_wire(datas[r % 4],
                                           now_ms=NOW0 + 501 + r)
            dps_deg = reps * 1000 / (time.perf_counter() - t0)
            fam = inst0.metrics.degraded_served.collect()[0]
            deg_rows = sum(s.value for s in fam.samples
                           if s.name.endswith("_total"))
            row["degraded"] = {
                "decisions_per_s": round(dps_deg),
                "vs_healthy": round(dps_deg / dps_c3, 3),
                "degraded_rows_served": int(deg_rows),
                "context": ("one of three owners' forwards fault-"
                            "killed (peer_send@addr:error); its keys "
                            "serve degraded from the local shard — "
                            "RESILIENCE.md")}
        except Exception as e:  # noqa: BLE001
            row["degraded"] = {"error": (str(e) or repr(e))[:200]}
        finally:
            try:
                inst0.faults.clear()
            except Exception:  # noqa: BLE001
                pass
        cores = _host_cores()
        if cores < 3:
            # VERDICT r2 weak #3: without this, the row reads as a
            # regression vs the single-daemon row
            row["context"] = (
                f"{cores}-core host serializes all 3 daemons; below "
                "the single-daemon row by construction, not a "
                "clustering regression (PERF.md §4.1)")
        return {"9_clustered_service": row}
    finally:
        c3.stop()


def _group_contention_probe(n_procs: int, reps_g: int) -> dict:
    """Small SO_REUSEPORT group on a starved host: verifies the group
    SURVIVES contention (no failed calls; a shared key drains exactly
    once per hit across connections/processes) and that the kernel
    actually spreads connections — the measurable ingredients of the
    ≥4-core scaling claim.  The rate is labeled as contention, never
    as scaling."""
    import threading as _th
    import urllib.request

    import grpc as _grpc

    from gubernator_tpu.cluster import start_subprocess_group

    gdatas = _serialize_reqs(_make_reqs(np.random.default_rng(7),
                                        name="grp"))
    grp = start_subprocess_group(n_procs, cache_size=1 << 14,
                                 batch_rows=1024)
    chans = []
    try:
        n_chan = 2 * n_procs
        chans = [_grpc.insecure_channel(
            grp.client_address,
            options=[("grpc.use_local_subchannel_pool", 1)])
            for _ in range(n_chan)]
        calls = [c.unary_unary("/pb.gubernator.V1/GetRateLimits")
                 for c in chans]
        for call in calls:
            call(gdatas[0], timeout=120)
        lat, errors = [[] for _ in range(n_chan)], []

        def _w(t):
            try:
                for r in range(reps_g):
                    t1 = time.perf_counter()
                    calls[t](gdatas[(t + r) % 4], timeout=120)
                    lat[t].append((time.perf_counter() - t1) * 1e3)
            except Exception as e:  # noqa: BLE001
                errors.append((str(e) or repr(e))[:120])

        ths = [_th.Thread(target=_w, args=(t,)) for t in range(n_chan)]
        t0 = time.perf_counter()
        for th in ths:
            th.start()
        for th in ths:
            th.join()
        wall = time.perf_counter() - t0
        flat = [x for ls in lat for x in ls]
        # spread check (VERDICT r5 #4): per-address scrape failures are
        # RECORDED (never `except: pass`), the expected lane labels
        # must actually exist in the exposition, and a check that
        # couldn't run reports `spread_check_failed` instead of a `0`
        # that contradicts the completed-calls count
        spread = 0
        spread_errors = []
        # a daemon that served ANY request shows one of these lanes
        lane_labels = ('lane="wire_local"', 'lane="wire_clustered"',
                       'lane="peer_wire"', 'lane="pb2_fallback"')
        for addr in grp.http_addresses:
            try:
                with urllib.request.urlopen(
                        f"http://{addr}/metrics", timeout=10) as f:
                    text = f.read().decode()
                lane_lines = [
                    line for line in text.splitlines()
                    if line.startswith(
                        "gubernator_wire_lane_requests_total")]
                if not any(lb in line for line in lane_lines
                           for lb in lane_labels):
                    # served traffic MUST label a lane; a scrape with
                    # none means the metric surface changed under us —
                    # flag it rather than counting a silent 0
                    spread_errors.append(
                        f"{addr}: no wire-lane labels in exposition "
                        f"({len(lane_lines)} lane lines)")
                    continue
                got = any(
                    line.split()[-1] not in ("0", "0.0")
                    for line in lane_lines
                    if ('lane="wire_local"' in line
                        or 'lane="wire_clustered"' in line))
                spread += bool(got)
            except Exception as e:  # noqa: BLE001
                spread_errors.append(
                    f"{addr}: scrape failed: {(str(e) or repr(e))[:120]}")
        # conservation: one key drained through every connection (the
        # kernel spreads them over processes) must debit exactly once
        # per hit — ring ownership, not per-process buckets
        conserved = None
        try:
            from gubernator_tpu.proto import gubernator_pb2 as _pb

            def _one(hits):
                m = _pb.GetRateLimitsReq()
                r = m.requests.add()
                r.name, r.unique_key = "grpcons", "shared"
                r.hits, r.limit, r.duration = hits, 10**6, 600_000
                return m.SerializeToString()

            for t in range(n_chan):
                calls[t](_one(3), timeout=120)
            q = _pb.GetRateLimitsResp.FromString(
                calls[0](_one(0), timeout=120))
            conserved = (int(q.responses[0].remaining)
                         == 10**6 - 3 * n_chan)
        except Exception as e:  # noqa: BLE001
            conserved = f"check failed: {(str(e) or repr(e))[:120]}"
        row = {f"contention_{n_procs}proc_decisions_per_s": round(
            len(flat) * 1000 / wall),
            "contention_completed_calls": len(flat),
            "contention_expected_calls": n_chan * reps_g,
            "conservation_exact": conserved,
            # a spread count the scrapes couldn't establish must say
            # so — a silent 0 next to N completed calls is a
            # contradiction, not a measurement (VERDICT r5 #4).  With
            # partial scrape failures a non-zero count still stands as
            # a lower bound (errors recorded beside it).
            "processes_seeing_traffic": (
                "spread_check_failed"
                if spread_errors and spread == 0 else spread),
            "processes": n_procs}
        if spread_errors:
            row["spread_check_errors"] = spread_errors[:4]
        if flat:
            row["contention_p99_ms"] = round(
                float(np.percentile(flat, 99)), 3)
            cores = _host_cores()
            if cores < n_procs + 1:
                # r3→r4 this row swung 951 → 10,487 ms on the same
                # probe: on a starved host the percentile is scheduler
                # noise — the booleans above are the row's information
                row["contention_p99_context"] = (
                    f"{cores}-core host runs {n_procs} daemons + "
                    "workers on one scheduler: the percentile is "
                    "variance-dominated and NOT comparable across "
                    "runs; conservation_exact and "
                    "processes_seeing_traffic are the stable signals")
        if errors:
            row["contention_worker_errors"] = errors[:3]
        return row
    finally:
        for c in chans:
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
        grp.stop()


def _sec_group():
    """SO_REUSEPORT front-door group (VERDICT r1 item 5): N daemon
    PROCESSES share one client gRPC port; kernel spreads connections;
    keys ring-split across per-process engines with raw-TLV peer
    forwards.  Runs on the CPU backend by design (subprocesses can't
    share the TPU chip; on a TPU host these are the ingest workers).
    Needs ≥4 host cores — on fewer the row self-skips honestly
    (measured 1-core thrash: 18k/s aggregate, p99 25 s)."""
    host_cores = _host_cores()
    if os.environ.get("GUBER_BENCH_SKIP_GROUP"):
        return {}
    if host_cores < 4:
        # Scaling is unmeasurable here, but the INGREDIENTS aren't:
        # run a small 2-process group anyway to verify correctness
        # under contention + kernel connection spreading, and record
        # the falsifiable aggregation model (BASELINE.md "Front-door
        # scaling model") its ≥4-core projection comes from.
        row = {
            "skipped_scaling": (
                f"host has {host_cores} core(s); the process-scaling "
                "number needs >=4 — rate below measures contention "
                "survival, not scaling"),
            "model": ("aggregate ~= N_procs * per_process_rate * "
                      "eff(0.5-0.7); per_process_rate = "
                      "6_service_path.concurrent16_decisions_per_s; "
                      "the (N-1)/N forward hop is inside eff"),
        }
        try:
            row.update(_group_contention_probe(n_procs=2, reps_g=8))
        except Exception as e:  # noqa: BLE001
            row["contention_error"] = (str(e) or repr(e))[:200]
        return {"10_reuseport_group": row}
    import threading as _th

    import grpc as _grpc

    from gubernator_tpu.cluster import start_subprocess_group

    gdatas = _serialize_reqs(_make_reqs(np.random.default_rng(7),
                                        name="grp"))
    n_procs = 2 if FAST else min(4, host_cores)
    grp = start_subprocess_group(n_procs, cache_size=1 << 16,
                                 batch_rows=1024)
    chans = []
    try:
        n_chan, reps_g = 4 * n_procs, 40
        chans = [_grpc.insecure_channel(
            grp.client_address,
            options=[("grpc.use_local_subchannel_pool", 1)])
            for _ in range(n_chan)]
        calls = [c.unary_unary("/pb.gubernator.V1/GetRateLimits")
                 for c in chans]
        # connect + warmup: timed traffic reuses these same
        # connections, and each warmup batch ring-forwards sub-batches
        # to EVERY process, so every engine has compiled its wave
        # program before timing starts
        for call in calls:
            call(gdatas[0], timeout=60)
        lat_g = [[] for _ in range(n_chan)]
        g_errors = []

        def _gworker(t):
            try:
                for r in range(reps_g):
                    t1 = time.perf_counter()
                    calls[t](gdatas[(t + r) % 4], timeout=60)
                    lat_g[t].append((time.perf_counter() - t1) * 1e3)
            except Exception as e2:  # noqa: BLE001
                g_errors.append(str(e2)[:120])

        ths = [_th.Thread(target=_gworker, args=(t,))
               for t in range(n_chan)]
        t0 = time.perf_counter()
        for th in ths:
            th.start()
        for th in ths:
            th.join()
        wall = time.perf_counter() - t0
        # numerator = calls that actually completed: a daemon dying
        # mid-run must not inflate the rate
        flat = [x for ls in lat_g for x in ls]
        row = {"decisions_per_s": round(len(flat) * 1000 / wall),
               "processes": n_procs, "connections": n_chan}
        if flat:
            row["p50_ms"] = round(float(np.percentile(flat, 50)), 3)
            row["p99_ms"] = round(float(np.percentile(flat, 99)), 3)
        if g_errors:
            row["worker_errors"] = g_errors[:3]
        return {"10_reuseport_group": row}
    finally:
        for c in chans:
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
        grp.stop()


def _sec_hot():
    """Hot-set psum tier: replica-local GLOBAL decisions + one psum
    fold per sync (the north-star replacement for global.go)."""
    import jax

    from gubernator_tpu.hashing import hash_key
    from gubernator_tpu.parallel import HotSetEngine, make_mesh
    from gubernator_tpu.types import RateLimitRequest

    mesh = make_mesh()
    hot = HotSetEngine(mesh, capacity=1024, batch_per_chip=2048)
    n = hot.n
    hreq = RateLimitRequest(name="hot", unique_key="k", hits=1,
                            limit=10**9, duration=600_000)
    hkh = hash_key("hot", "k")
    hot.pin(hreq, hkh, NOW0)
    wave = [hreq] * (n * 2048)
    khs = [hkh] * len(wave)
    hot.check_batch(wave, khs, NOW0)  # compile
    t0 = time.perf_counter()
    reps = 10
    for r in range(reps):
        hot.check_batch(wave, khs, NOW0 + 1 + r)
    dps_hot = reps * len(wave) / (time.perf_counter() - t0)
    hot.sync()
    jax.block_until_ready(hot.state)
    t0 = time.perf_counter()
    for _ in range(20):
        hot.sync()
    jax.block_until_ready(hot.state)  # async dispatch: wait for the fold
    sync_ms = (time.perf_counter() - t0) / 20 * 1e3
    return {"7_hot_psum": {"decisions_per_s": round(dps_hot),
                           "sync_ms": round(sync_ms, 3),
                           "n_replicas": int(n)}}


def _sec_cfg5():
    """Config 5: huge multi-tenant table (100M keys → CAP 2^27),
    Gregorian resets + RESET_REMAINING churn.  The TRUE BASELINE.json
    capacity is attempted — never silently downscaled (VERDICT r1
    item 3): the donated step keeps ONE copy of the ~9 GB table live,
    which is what makes 2^27 fit a 16 GB chip at all.  The CPU
    fallback uses a reduced capacity and says so via "cpu_reduced"."""
    import jax
    import jax.numpy as jnp

    from gubernator_tpu.core.step import decide_batch_donated
    from gubernator_tpu.core.table import init_table
    from gubernator_tpu.gregorian import gregorian_expiration
    from gubernator_tpu.types import Behavior, GregorianDuration

    i64 = jnp.int64
    rng = np.random.default_rng(7)
    cpu5 = jax.default_backend() == "cpu"
    cap5 = 1 << 22 if cpu5 else 1 << 27
    try:
        n_keys5 = int(cap5 * 0.75)
        st5 = init_table(cap5)
        greg_end = gregorian_expiration(NOW0, int(GregorianDuration.HOURS))
        beh = int(Behavior.DURATION_IS_GREGORIAN)
        batches = []
        for i in range(4):
            k = _keyhash(rng.integers(0, n_keys5, size=B).astype(np.uint64))
            beh_col = np.full(B, beh, np.int32)
            beh_col[::37] |= int(Behavior.RESET_REMAINING)  # churn
            batches.append(_mk_batch(
                jnp, k,
                duration=jnp.full(B, int(GregorianDuration.HOURS), i64),
                eff_ms=jnp.full(B, 3_600_000, i64),
                greg_end=jnp.full(B, greg_end, i64),
                behavior=jnp.asarray(beh_col)))
        st5, _ = decide_batch_donated(st5, batches[0],
                                      jnp.asarray(NOW0, i64))
        dps5, _ = _sustain(decide_batch_donated, jnp, st5, batches, 16,
                           NOW0 + 1)
        return {"5_gregorian_churn": {"decisions_per_s": round(dps5),
                                      "capacity": cap5,
                                      "cpu_reduced": cpu5}}
    except Exception as e:  # noqa: BLE001
        return {"5_gregorian_churn": {"error": (str(e) or repr(e))[:200],
                                      "capacity_attempted": int(cap5)}}


def _sec_pallas():
    """GUBER_ENGINE=pallas as THE serving engine (ISSUE 8): the full
    V1Instance wire path — bytes → dispatcher → ONE fused device
    program per wave (decision kernel + on-device heavy-hitter tap) →
    bytes — A/B'd against the classic XLA engine on IDENTICAL seeded
    traffic.  On TPU the fused engine embeds the Mosaic bucket kernel
    at the large-CAP shape the mode exists for; on CPU it embeds the
    COMPILED small-shape XLA kernel (XlaFusedEngine) — the old
    interpret-mode toy row measured nothing and is gone (its number is
    recorded under pre_pr).  The row carries the A/B bit-identity, the
    fused/xla throughput ratio, and PhaseLedger evidence that the pack
    phase collapsed into `device` (phase_deleted)."""
    import jax

    from gubernator_tpu.config import Config
    from gubernator_tpu.instance import V1Instance
    from gubernator_tpu.parallel import make_mesh
    from gubernator_tpu.parallel.pallas_engine import (
        PallasServingEngine, XlaFusedEngine)

    cpu = jax.default_backend() == "cpu"
    cap = 1 << 14 if cpu else 1 << 24  # 2 GiB of rows on-chip
    reps = 8 if FAST else (16 if cpu else 20)
    row = {"capacity": cap, "batch": 1000, "cpu_compiled": cpu,
           "engine": "xla_fused" if cpu else "pallas_fused",
           "compiled_kernels": True,
           # the row this one replaces: interpret-mode kernel at a toy
           # shape, self-described as measuring nothing (BENCH_r05)
           "pre_pr": {"wire_lane_decisions_per_s": 80411,
                      "mode": "interpret toy (BENCH_r05; 'measures "
                              "nothing')"}}
    datas = _serialize_reqs(_make_reqs(np.random.default_rng(7)))

    def drive(engine_sel):
        # env GUBER_STEP_IMPL / GUBER_ENGINE would override Config and
        # silently measure the wrong engine — pin both for this build
        prev_e = os.environ.get("GUBER_ENGINE")
        prev_i = os.environ.get("GUBER_STEP_IMPL")
        os.environ["GUBER_ENGINE"] = engine_sel
        os.environ.pop("GUBER_STEP_IMPL", None)
        try:
            inst = V1Instance(Config(cache_size=cap,
                                     sweep_interval_ms=0,
                                     engine=engine_sel),
                              mesh=make_mesh(n=1))
        finally:
            for k, v in (("GUBER_ENGINE", prev_e),
                         ("GUBER_STEP_IMPL", prev_i)):
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        try:
            inst.get_rate_limits_wire(datas[0], now_ms=NOW0)  # compile
            outs = []
            t0 = time.perf_counter()
            for r in range(reps):
                outs.append(inst.get_rate_limits_wire(
                    datas[r % len(datas)], now_ms=NOW0 + 1 + r))
            dps = reps * 1000 / (time.perf_counter() - t0)
            lat = []
            for r in range(8 if cpu else 60):
                t0 = time.perf_counter()
                inst.get_rate_limits_wire(datas[r % len(datas)],
                                          now_ms=NOW0 + 40 + r)
                lat.append((time.perf_counter() - t0) * 1e3)
            ana = inst.dispatcher.analytics
            phases = (ana.phases.snapshot() if ana is not None else {})
            # the exact wave-time partition is the proof of phase
            # deletion: sum(segments) == wave duration on every wave
            drift = 0.0
            for ev in inst.recorder.events(limit=256):
                if ev.get("kind") == "wave_completed" \
                        and ev.get("phases"):
                    drift = max(drift, abs(
                        sum(ev["phases"].values())
                        - ev["duration_ms"]))
            return {"dps": dps, "outs": outs, "phases": phases,
                    "lat": lat, "drift_ms": drift,
                    "engine_cls": type(inst.engine).__name__,
                    "fused_waves": getattr(inst.engine,
                                           "fused_wave_count", 0),
                    "hbm": _hbm_block(inst),
                    "telemetry": _telemetry_rows(inst)}
        finally:
            inst.close()

    fused = drive("pallas")
    xla = drive("xla")
    want = (XlaFusedEngine if cpu else PallasServingEngine).__name__
    assert fused["engine_cls"] == want, fused["engine_cls"]
    pmeans = {k: {p: v["p50_ms"] for p, v in d["phases"].items()
                  if p in ("pack", "device", "resolve")}
              for k, d in (("fused", fused), ("xla", xla))}
    row.update({
        "wire_lane_decisions_per_s": round(fused["dps"]),
        "xla_wire_decisions_per_s": round(xla["dps"]),
        "fused_vs_xla": round(fused["dps"] / max(xla["dps"], 1e-9), 3),
        "ab_identical": fused["outs"] == xla["outs"],
        "fused_waves": fused["fused_waves"],
        "svc_p50_ms": round(float(np.percentile(fused["lat"], 50)), 3),
        "svc_p99_ms": round(float(np.percentile(fused["lat"], 99)), 3),
        # ISSUE 13: the ad-hoc occupancy field became the standardized
        # per-consumer memory block (comparable across rows 6/11/12/13)
        "hbm": fused["hbm"],
        "telemetry": fused["telemetry"],
        # PhaseLedger evidence: the classic engine's waves carry a pack
        # segment; fused waves don't — `device` absorbed it, and the
        # per-wave partition stays exact (drift is float rounding)
        "phase_deleted": {
            "deleted_phase": "pack",
            "pack_absent_in_fused": "pack" not in fused["phases"],
            "pack_present_in_xla": "pack" in xla["phases"],
            "phase_p50_ms": pmeans,
            "partition_max_drift_ms": round(
                max(fused["drift_ms"], xla["drift_ms"]), 3)},
    })
    if cpu:
        row["context"] = (
            "CPU row serves from the COMPILED small-shape XLA fused "
            "flavor (GUBER_ENGINE=pallas off-TPU): decisions "
            "bit-identical to the classic engine by construction, so "
            "the A/B prices exactly what fusion deletes (host tap "
            "copies + the pack mark). The Mosaic bucket kernel at "
            "large CAP is the TPU row")
    return {"11_pallas_serving": row}


def _sec_mesh():
    """Pod-coherent GLOBAL over the mesh (ISSUE 7): the same seeded
    GLOBAL wire traffic served twice — GUBER_GLOBAL_MODE=mesh (the
    collective-reconcile tier, zero gRPC peer RPCs) vs grpc (the
    reference hit-queue path, hot set off so the sharded table serves)
    — with the A/B bit-identity, exact-conservation verdict, reconcile
    generations, and measured coherence staleness recorded in the row."""
    import jax

    from gubernator_tpu.config import BehaviorConfig, Config
    from gubernator_tpu.instance import V1Instance
    from gubernator_tpu.parallel import make_mesh
    from gubernator_tpu.types import Behavior, RateLimitRequest

    sync_ms = 100
    reps = 4 if FAST else 16
    rng = np.random.default_rng(7)
    # bounded key domain: every key pins into the mesh tier (the row
    # measures the collective path, not pin-fail fallbacks)
    batches = [[RateLimitRequest(
        name="mesh", unique_key=f"g{int(k) % 512}", hits=1, limit=10 ** 9,
        duration=600_000, behavior=Behavior.GLOBAL)
        for k in rng.zipf(ZIPF_A, size=1000)] for _ in range(4)]
    datas = _serialize_reqs(batches)

    def _drive(inst):
        inst.get_rate_limits_wire(datas[0], now_ms=NOW0)  # compile/pin
        t0 = time.perf_counter()
        outs = []
        for r in range(reps):
            outs.append(inst.get_rate_limits_wire(
                datas[r % len(datas)], now_ms=NOW0 + 1 + r))
        return reps * 1000 / (time.perf_counter() - t0), outs

    row = {"n_shards": len(jax.devices()), "batch": 1000,
           "key_domain": 512, "reconcile_interval_ms": sync_ms}
    mi = V1Instance(Config(cache_size=1 << 14, sweep_interval_ms=0,
                           global_mode="mesh",
                           behaviors=BehaviorConfig(
                               global_sync_wait_ms=sync_ms)),
                    mesh=make_mesh())
    try:
        dps_mesh, mesh_outs = _drive(mi)
        mi._mesh_reconcile_tick()  # deterministic final fold
        mge = mi._meshglobal
        mge.drain()
        s = mge.stats()
        gm = mi.global_manager
        row.update({
            "decisions_per_s": round(dps_mesh),
            "reconcile_generations": s["generation"],
            "pinned_keys": s["pinned_keys"],
            "staleness_ms": round(s["last_staleness_s"] * 1e3, 3),
            "staleness_within_interval":
                s["last_staleness_s"] * 1e3 <= sync_ms,
            "conservation_exact":
                s["folded_hits"] == s["injected_hits"],
            "injected_hits": s["injected_hits"],
            # mesh mode's whole point: nothing ever queued for gRPC
            "zero_peer_rpcs": (not gm._hits and not gm._hits_raw),
        })
        # ISSUE 11: the fitted collective cost model from this row's
        # live folds — α (launch + rendezvous) and β (per byte) per
        # (phase, ndev) bucket, the constants the hierarchical-
        # reconcile ROADMAP item prices levels with (see
        # tools/costmodel_dryrun.py for the held-out validation)
        ana = mi.analytics
        if ana is not None:
            row["cost_model"] = ana.costmodel_snapshot()
        # ISSUE 13: mesh-GLOBAL replica + accumulators in the ledger
        row["hbm"] = _hbm_block(mi)
    finally:
        mi.close()
    gi = V1Instance(Config(cache_size=1 << 14, sweep_interval_ms=0,
                           hot_set_capacity=0),
                    mesh=make_mesh())
    try:
        dps_grpc, grpc_outs = _drive(gi)
        row["grpc_decisions_per_s"] = round(dps_grpc)
        row["ab_identical"] = grpc_outs == mesh_outs
        row["mesh_vs_grpc"] = round(dps_mesh / max(dps_grpc, 1e-9), 3)
    finally:
        gi.close()
    if jax.default_backend() == "cpu":
        row["context"] = (
            "CPU A/B compares the mesh replica step against the "
            "IN-PROCESS sharded step (grpc mode never leaves the "
            "process here), so the ratio measures replica-table "
            "overhead only; the production win is vs per-peer gRPC "
            "round trips, which this host-only A/B cannot price. The "
            "coherence columns (conservation/staleness/zero RPCs) are "
            "the acceptance signal")
    return {"12_mesh_global": row}


def _sec_tiered():
    """Tiered key store (ISSUE 10): seeded skewed traffic whose key
    domain dwarfs a 4K-row device cap, served through the host cold
    tier and A/B'd byte-for-byte against an UNCAPPED single-tier
    oracle.  The verdict columns are the acceptance criteria: zero
    error rows, exact conservation summed across BOTH tiers, and
    bit-identical decisions; the capacity story (cold keys, hot-tier
    hit rate, migration counters) rides in the same row."""
    import jax

    from gubernator_tpu.config import Config
    from gubernator_tpu.instance import V1Instance
    from gubernator_tpu.parallel import make_mesh
    from gubernator_tpu.types import RateLimitRequest

    nkeys = 20_000 if FAST else 1_000_000
    rng = np.random.default_rng(13)
    # one full pass over the domain guarantees nkeys DISTINCT keys; a
    # zipf-hot overlay gives a band of keys the rank to clear admission
    stream = np.concatenate([
        rng.permutation(nkeys),
        (rng.zipf(ZIPF_A, size=nkeys // 5) - 1) % nkeys])
    B = 1000
    pad = (-len(stream)) % B
    if pad:
        stream = np.concatenate([stream, stream[:pad]])
    datas = _serialize_reqs(
        [[RateLimitRequest(name="tier", unique_key=f"t{int(k)}", hits=1,
                           limit=10 ** 9, duration=86_400_000)
          for k in stream[base:base + B]]
         for base in range(0, len(stream), B)])
    sent = len(stream)

    def _drive(inst):
        inst.get_rate_limits_wire(datas[0], now_ms=NOW0)  # compile
        t0 = time.perf_counter()
        outs = [inst.get_rate_limits_wire(d, now_ms=NOW0 + 1)
                for d in datas]
        return sent / (time.perf_counter() - t0), outs

    def _debits(inst) -> int:
        arrays = inst.engine.snapshot()
        total = int((10 ** 9 - arrays["remaining"]).sum())
        if inst._tier is not None:
            cold = inst._tier.snapshot_arrays()
            if cold is not None:
                total += int((10 ** 9 - cold["remaining"]).sum())
        return total

    row = {"n_shards": len(jax.devices()), "key_domain": nkeys,
           "requests": sent + B, "device_cap_rows": 4096}
    ti = V1Instance(Config(cache_size=4096, cache_autogrow_max=4096,
                           tier_cold=True, tier_promote_threshold=4,
                           hot_set_capacity=0, sweep_interval_ms=0),
                    mesh=make_mesh())
    try:
        dps_tier, tier_outs = _drive(ti)
        st = ti._tier.stats()
        # the warm-up batch's debits land in the same tables, so the
        # conservation target includes it
        row.update({
            "decisions_per_s": round(dps_tier),
            "error_rows": _count_error_rows(tier_outs),
            "conservation_exact": _debits(ti) == sent + B,
            "cold_keys": st["cold_keys"],
            "cold_served": st["cold_served"],
            "hot_hit_rate": round(1 - st["cold_served"]
                                  / max(sent + B, 1), 4),
            "promotions": st["promotions"],
            "demotions": st["demotions"],
            "migrations_aborted": st["migrations_aborted"],
            "cold_store_native": st["native"],
            # ISSUE 13: hot table + host cold tier, one ledger block
            "hbm": _hbm_block(ti),
        })
    finally:
        ti.close()
    # "uncapped" still needs placement headroom: at ~0.5 load an 8-probe
    # window can clog (~0.3% of 1M keys), and an oracle error row would
    # read as a tier A/B failure — autogrow keeps the oracle exact
    ocap = 1 << (2 * nkeys - 1).bit_length()
    oi = V1Instance(Config(cache_size=ocap, cache_autogrow_max=ocap * 8,
                           hot_set_capacity=0, sweep_interval_ms=0),
                    mesh=make_mesh())
    try:
        dps_oracle, oracle_outs = _drive(oi)
        row["oracle_decisions_per_s"] = round(dps_oracle)
        row["oracle_error_rows"] = _count_error_rows(oracle_outs)
        row["ab_identical"] = tier_outs == oracle_outs
        row["tier_vs_uncapped"] = round(
            dps_tier / max(dps_oracle, 1e-9), 3)
    finally:
        oi.close()
    return {"13_tiered_store": row}


def _count_error_rows(outs) -> int:
    from gubernator_tpu.proto import gubernator_pb2 as pb

    n = 0
    for data in outs:
        resp = pb.GetRateLimitsResp.FromString(data)
        n += sum(1 for r in resp.responses if r.error)
    return n


def _scenario_ab(inst, reqs, pairs=9, reps=150) -> dict:
    """ISSUE 16 acceptance: the scenario lab's only service-path cost
    is its JudgeTap — ``observe()`` is an O(1) retain under a lock;
    digesting/ledgers are deferred to settle-time ``finalize()``.
    Measured as interleaved pairs of the same object-lane call with
    the tap *on* (call + observe) and *off* (plain call), alternating
    order per pair, < 3% budget.  Two departures from the
    ``_tenant_ab``/``_tracing_ab`` template, both noise armor: the
    instance under the A/B runs the synchronous OracleEngine lane
    (the oracle call is strictly FASTER than the real service call,
    so a tap cost measured as a fraction of it is an UPPER bound on
    the true service-path overhead), and the estimator is the floor
    ratio — best rate per side across all pairs — because host noise
    is one-sided (a spike only ever slows a sample) while a real
    systematic tap cost slows EVERY sample, the floor included."""
    from gubernator_tpu.scenarios import NOW0 as S_NOW0
    from gubernator_tpu.scenarios import JudgeTap

    def _measure(which):
        judge = JudgeTap(delim="/")
        t0 = time.perf_counter()
        for r in range(reps):
            resps = inst.get_rate_limits(reqs, now_ms=S_NOW0 + r)
            if which == "on":
                judge.observe(reqs, resps, S_NOW0 + r)
        return reps / (time.perf_counter() - t0)

    try:
        r_on, r_off = [], []
        for pair in range(pairs + 1):
            order = ("off", "on") if pair % 2 else ("on", "off")
            got = {w: _measure(w) for w in order}
            if pair == 0:
                continue  # warmup pair, untimed
            r_on.append(got["on"])
            r_off.append(got["off"])
        overhead = (max(r_off) / max(r_on) - 1) * 100
        row = {"overhead_pct": round(overhead, 2),
               "overhead_ok": bool(overhead < 3.0),
               "on_calls_per_s": round(max(r_on), 1),
               "off_calls_per_s": round(max(r_off), 1),
               "pairs": pairs, "reps": reps, "rows": len(reqs)}
        if not row["overhead_ok"]:
            row["warning"] = ("judge tap measured above its <3% budget "
                              "on this run; single-host noise — re-run "
                              "before acting on it")
        return row
    except Exception as e:  # noqa: BLE001 - diagnostics only
        return {"error": (str(e) or repr(e))[:200]}


def _sec_scenarios():
    """Scenario lab (ISSUE 16): run the committed spec library in fast
    mode — every stack class, every oracle — and record per-scenario
    verdicts plus the judge-tap service-path A/B.  A scenario added to
    ``scenarios/`` shows up in the next BENCH round (and ``make
    bench-diff``) with no extra wiring."""
    from gubernator_tpu.config import Config
    from gubernator_tpu.instance import V1Instance
    from gubernator_tpu.scenarios import load_library, run_scenarios
    from gubernator_tpu.types import RateLimitRequest

    doc = run_scenarios(load_library(), fast=True)
    cells = {}
    for name, r in doc["scenarios"].items():
        cell = {"ok": r["ok"], "stack": r["stack"],
                "requests": r["requests"],
                "admitted_hits": r["admitted_hits"],
                "over_limit": r["over_limit"],
                "error_rows": r["error_rows"],
                "decision_digest": r["decision_digest"][:16],
                "oracle_ok": {k: v["ok"]
                              for k, v in r["oracles"].items()}}
        if "jain_index" in r:
            cell["jain_index"] = r["jain_index"]
        cells[name] = cell
    row = {"count": doc["count"], "all_ok": doc["all_ok"],
           "scenarios": cells}
    from gubernator_tpu.oracle import OracleEngine
    inst = V1Instance(Config(cache_size=1 << 12, sweep_interval_ms=0),
                      engine=OracleEngine())
    try:
        rng = np.random.default_rng(11)
        reqs = [RateLimitRequest(name="scnab", unique_key=f"k{int(k)}",
                                 hits=1, limit=10 ** 6,
                                 duration=86_400_000)
                for k in rng.integers(0, 64, size=128)]
        inst.get_rate_limits(reqs, now_ms=NOW0)  # warm the wave path
        row["runner_ab"] = _scenario_ab(
            inst, reqs, pairs=3 if FAST else 9,
            reps=20 if FAST else 150)
    finally:
        inst.close()
    return {"15_scenarios": row}


def _audit_ab(inst, datas, pairs=9, reps=60) -> dict:
    """ISSUE 19 acceptance: the conservation audit tap must cost < 1%
    on the service path.  Interleaved pairs of the same GLOBAL wire
    call with the tap attached (``gm.audit`` is an AuditTap) and
    detached (None darkens every tap site), alternating order per
    pair, floor-ratio estimator (the ``_scenario_ab`` noise armor —
    the budget is tight enough that median-of-ratios jitter on a
    shared host would dominate the verdict)."""
    from gubernator_tpu.fleet import AuditTap

    gm = inst._ensure_global_manager()
    old = gm.audit

    def _measure(which):
        gm.audit = AuditTap() if which == "on" else None
        t0 = time.perf_counter()
        for r in range(reps):
            inst.get_rate_limits_wire(datas[r % len(datas)],
                                      now_ms=NOW0 + r)
        return reps / (time.perf_counter() - t0)

    try:
        r_on, r_off = [], []
        for pair in range(pairs + 1):
            order = ("off", "on") if pair % 2 else ("on", "off")
            got = {w: _measure(w) for w in order}
            if pair == 0:
                continue  # warmup pair, untimed
            r_on.append(got["on"])
            r_off.append(got["off"])
        overhead = (max(r_off) / max(r_on) - 1) * 100
        row = {"overhead_pct": round(overhead, 2),
               "overhead_ok": bool(overhead < 1.0),
               "on_calls_per_s": round(max(r_on), 1),
               "off_calls_per_s": round(max(r_off), 1),
               "pairs": pairs, "reps": reps}
        if not row["overhead_ok"]:
            row["warning"] = ("audit tap measured above its <1% budget "
                              "on this run; single-host noise — re-run "
                              "before acting on it")
        return row
    except Exception as e:  # noqa: BLE001 - diagnostics only
        return {"error": (str(e) or repr(e))[:200]}
    finally:
        gm.audit = old


def _sec_fleet():
    """Fleet watchtower (ISSUE 19): the audit-tap A/B on the service
    path (< 1% budget) plus the fleet-merge wall time at 3 daemons —
    fetch every daemon's debug endpoints over HTTP and time ONLY the
    exact folds (fleet.py), the cost a control plane's fleet tick
    would pay per sweep."""
    import urllib.request

    from gubernator_tpu import cluster as cluster_mod
    from gubernator_tpu import fleet
    from gubernator_tpu.config import BehaviorConfig, Config
    from gubernator_tpu.instance import V1Instance
    from gubernator_tpu.types import Behavior, RateLimitRequest

    row = {}
    rng = np.random.default_rng(7)
    reqs = [[RateLimitRequest(name="fleetab", unique_key=f"k{int(k)}",
                              hits=1, limit=10 ** 6, duration=86_400_000,
                              behavior=Behavior.GLOBAL)
             for k in rng.zipf(ZIPF_A, size=1000) % 100_000]
            for _ in range(4)]
    datas = _serialize_reqs(reqs)
    inst = V1Instance(Config(cache_size=1 << 15, sweep_interval_ms=0))
    try:
        inst.get_rate_limits_wire(datas[0], now_ms=NOW0)  # warm
        row["audit_ab"] = _audit_ab(
            inst, datas, pairs=3 if FAST else 9,
            reps=10 if FAST else 60)
    finally:
        inst.close()

    c = cluster_mod.start(3, behaviors=BehaviorConfig(
        global_sync_wait_ms=50), cache_size=1 << 12)
    try:
        for i in range(3):
            ci = c.instance_at(i)
            ci.get_rate_limits(
                [RateLimitRequest(name="fleet", unique_key=f"m{j}",
                                  hits=1, limit=10 ** 6, duration=86_400_000,
                                  behavior=Behavior.GLOBAL)
                 for j in range(64)], now_ms=NOW0)
            ana = ci.analytics
            if ana is not None:
                ana.flush(timeout=5.0)
        # settle the flush discipline so the timed merge measures a
        # conserved steady state, not a mid-flush snapshot
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            insts = [c.instance_at(i) for i in range(3)]
            for ci in insts:
                if ci.global_manager is not None:
                    ci.global_manager.poke()
            time.sleep(0.1)
            if all(ci.audit_doc()["conserved"] for ci in insts):
                break

        def fetch(path):
            docs = []
            for i in range(3):
                url = c.http_address(i) + path
                with urllib.request.urlopen(url, timeout=5.0) as f:
                    docs.append(json.loads(f.read()))
            return docs

        raw = {p: fetch(p) for p in ("/debug/audit", "/debug/topkeys",
                                     "/debug/tenants", "/debug/slo",
                                     "/debug/memory")}
        t0 = time.perf_counter()
        fold = fleet.fold_audits(raw["/debug/audit"])
        fleet.ring_verdict(raw["/debug/audit"])
        fleet.merge_topkeys(raw["/debug/topkeys"])
        tns = fleet.merge_tenants(raw["/debug/tenants"])
        fleet.merge_slo(raw["/debug/slo"])
        fleet.merge_memory(raw["/debug/memory"])
        wall = (time.perf_counter() - t0) * 1000
        row["fleet_merge_wall_ms"] = round(wall, 3)
        row["merge"] = {"daemons": 3,
                        "drift": fold["drift"],
                        "conserved_ok": bool(fold["conserved"]),
                        "tenants_sum_ok": bool(tns["conserved"])}
    except Exception as e:  # noqa: BLE001 - diagnostics only
        row["merge"] = {"error": (str(e) or repr(e))[:200]}
    finally:
        c.stop()
    return {"16_fleet": row}


#: section name → (callable, result row keys for skip/error reporting)
_SECTIONS = {
    "lat_client": (_sec_lat_client,
                   ["client_batch_p50_ms", "client_batch_p99_ms"]),
    "scan": (_sec_scan, ["device_scan_decisions_per_s"]),
    "cfg12": (_sec_cfg12, ["1_single_key_smoke", "2_leaky_1k_keys"]),
    "cfg4": (_sec_cfg4, ["4_global_sharded"]),
    "svc": (_sec_svc, ["6_service_path", "8_peer_path"]),
    "cluster": (_sec_cluster, ["9_clustered_service"]),
    "group": (_sec_group, ["10_reuseport_group"]),
    "hot": (_sec_hot, ["7_hot_psum"]),
    "cfg5": (_sec_cfg5, ["5_gregorian_churn"]),
    "pallas": (_sec_pallas, ["11_pallas_serving"]),
    "mesh": (_sec_mesh, ["12_mesh_global"]),
    "tiered": (_sec_tiered, ["13_tiered_store"]),
    "scenarios": (_sec_scenarios, ["15_scenarios"]),
    "fleet": (_sec_fleet, ["16_fleet"]),
}

#: device sections that each pay a fresh compile, in run order
_SECTION_ORDER = ["cfg12", "cfg4", "svc", "cluster", "group", "hot",
                  "cfg5", "pallas", "mesh", "tiered", "scenarios",
                  "fleet"]

_WEDGED = False  # set when a section timeout + failed device probe
#: parent's backend, captured BEFORE the device client is released —
#: _run_section must not call jax.default_backend() itself (that would
#: re-initialize a client the parent just released)
_EXPECT_BACKEND = None


def _device_probe(timeout=150) -> bool:
    """Trivial-op probe in a throwaway subprocess: the axon tunnel has
    repeatedly been observed wedged such that backend init (or any new
    compile) hangs forever — don't spend a full section timeout
    discovering that.  A probe that answers with the CPU backend is a
    FAILED device probe: jax fell back silently."""
    import subprocess

    code = ("import jax, jax.numpy as jnp;"
            "jnp.arange(8).sum().block_until_ready();"
            "print(jax.default_backend())")
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           timeout=timeout, stdout=subprocess.PIPE,
                           stderr=subprocess.PIPE)
        backend = (r.stdout or b"").decode().strip()
        ok = r.returncode == 0 and backend not in ("", "cpu")
        log(f"device probe: ok={ok} backend={backend!r}")
        if r.returncode != 0:
            tail = (r.stderr or b"").decode(errors="replace")[-400:]
            log(f"device probe stderr tail: {tail}")
        return ok
    except Exception as e2:  # noqa: BLE001
        log(f"device probe failed: {e2!r:.120} (tunnel wedged?)")
        return False


def _run_section(name, inline):
    """Run one section; inline on CPU, in a child process on a device
    backend (wedged compiles cost one row, not the run)."""
    global _WEDGED
    fn, _rows = _SECTIONS[name]
    if inline:
        try:
            return fn()
        except Exception as e:  # noqa: BLE001
            # str() of TimeoutError/queue.Empty is "" — always keep
            # the type so the recorded row can be diagnosed
            return {"error": f"{name}: {(str(e) or repr(e))[:300]}"}
    import subprocess

    path = f"/tmp/guber_section.{os.getpid()}.{name}.json"
    env = dict(os.environ, GUBER_BENCH_SECTION=name,
               GUBER_BENCH_SECTION_OUT=path)
    env.pop("GUBER_BENCH_INNER", None)
    # a cold wave compile through the tunnel is 250-305 s: callers that
    # arrive before warmup (the section's first request IS the warmup)
    # must outwait it, or the whole section dies as an empty
    # TimeoutError at 120 s (round-5 live window, sections 6/8/9)
    env.setdefault("GUBER_RESULT_TIMEOUT_S", "900")
    if _PROBES_DEFAULTED:
        # sections model general serving at the serving default; since
        # round 5 the flagship uses the same window, but pinning the
        # children keeps operator GUBER_PROBES choices (the only other
        # way the env can be set) explicit end-to-end
        env["GUBER_PROBES"] = "8"
    if _EXPECT_BACKEND:
        env["GUBER_BENCH_EXPECT_BACKEND"] = _EXPECT_BACKEND
    # worst observed tunnel compile is ~305 s; budgets give margin per
    # cold compile a section legitimately needs (svc compiles BOTH
    # wave buckets; cluster/cfg5 one fresh shape each) PLUS dispatcher
    # wave-waits (GUBER_RESULT_TIMEOUT_S above): a wedged wave must
    # surface as that caller's TimeoutError row, not as this
    # subprocess timeout killing the section's already-written lanes.
    # svc is budgeted for THREE independent 900 s waits (its object,
    # wire, and concurrent lanes each submit fresh waves — ADVICE r5)
    # plus its two cold bucket compiles; even when the budget still
    # trips, the per-lane checkpoints (_section_checkpoint) keep every
    # finished lane — the TimeoutExpired path below salvages them.
    # One wedged section + the follow-up probe still fits the
    # watchdog's whole-run deadline (see _watchdog_main).  pallas: a
    # cold Mosaic kernel compile (~220-305 s over the tunnel) + the
    # fused occ/sat program + a 2 GiB table init + one wave-wait.
    budgets = {"svc": 3600, "cluster": 2100, "cfg5": 1200,
               "pallas": 2400}
    timeout = int(os.environ.get("GUBER_BENCH_SECTION_TIMEOUT",
                                 str(budgets.get(name, 900))))
    t0 = time.perf_counter()
    try:
        subprocess.run([sys.executable, os.path.abspath(__file__)],
                       env=env, timeout=timeout,
                       stdout=subprocess.DEVNULL)
        with open(path) as f:
            rows = json.load(f)
        log(f"[{name}] section done in {time.perf_counter() - t0:.1f}s")
        return rows
    except subprocess.TimeoutExpired:
        log(f"[{name}] section timed out after {timeout}s — probing link")
        if not _device_probe():
            _WEDGED = True
        err = (f"section timed out after {timeout}s "
               "(wedged device compile?)")
        # salvage the per-lane checkpoints the killed child already
        # wrote (_section_checkpoint): finished lanes survive the kill
        try:
            with open(path) as f:
                rows = json.load(f)
            if rows:
                rows["partial"] = err
                log(f"[{name}] salvaged {len(rows) - 1} checkpointed "
                    "row(s) from the killed section")
                return rows
        except (OSError, ValueError):
            pass
        return {"error": err}
    except Exception as e:  # noqa: BLE001
        return {"error": f"{name}: {(str(e) or repr(e))[:300]}"}
    finally:
        try:
            os.remove(path)
        except OSError:
            pass


def _section_main():
    """Child entry: run one section and write its rows atomically."""
    plat = os.environ.get("GUBER_JAX_PLATFORM", "")
    import jax

    if plat:
        # through jax.config: the sandbox sitecustomize overwrites the
        # jax_platforms config at interpreter start (env is ignored)
        jax.config.update("jax_platforms", plat)
    name = os.environ["GUBER_BENCH_SECTION"]
    fn, _rows = _SECTIONS[name]
    # a child whose backend init silently fell back to CPU must NOT
    # record its rates as device rows under the parent's backend label
    expect = os.environ.get("GUBER_BENCH_EXPECT_BACKEND", "")
    got = jax.default_backend()
    if expect and got != expect:
        rows = {"error": f"{name}: child backend is {got!r}, parent "
                         f"expected {expect!r} (silent fallback — row "
                         "dropped rather than mislabeled)"}
    else:
        try:
            rows = fn()
        except Exception as e:  # noqa: BLE001
            rows = {"error": f"{name}: {(str(e) or repr(e))[:300]}"}
    path = os.environ["GUBER_BENCH_SECTION_OUT"]
    with open(path + ".tmp", "w") as f:
        json.dump(rows, f)
    os.replace(path + ".tmp", path)


def run_secondary_configs(step_mode, backend, checkpoint=None):
    """BASELINE.md configs 1/2/4/5 (config 3 is the headline above)
    plus the service/cluster/group/hot rows.  Smaller rep counts —
    these document shape coverage, not the record.  ``checkpoint(out)``
    runs after each section so rows measured before a late-stage
    device failure survive (see _write_partial)."""
    # serving engines in the sections read this at construction: they
    # must run the best XLA mode — set it explicitly BOTH ways so a
    # pre-existing operator export can't make the rows measure a
    # different mode than reported (children inherit it).  The one
    # exception is the dedicated `pallas` section (11_pallas_serving),
    # which forces GUBER_STEP_IMPL=pallas for its own instance.
    os.environ["GUBER_STEP_DONATE"] = ("1" if step_mode == "donate"
                                      else "0")
    os.environ["GUBER_BENCH_STEP_MODE"] = step_mode
    # env beats Config in V1Instance's step_impl resolution, so an
    # operator's exported GUBER_STEP_IMPL=pallas would silently turn
    # every XLA-labeled serving row into a pallas measurement
    os.environ["GUBER_STEP_IMPL"] = "xla"
    inline = backend == "cpu"
    out = {}
    for name in _SECTION_ORDER:
        fn, row_keys = _SECTIONS[name]
        # the group section never compiles on the device in-parent (it
        # spawns CPU worker processes), so it is safe inline everywhere
        sec_inline = inline or name == "group"
        if _WEDGED and not sec_inline:
            for k in row_keys:
                out[k] = {"skipped": "device link wedged in an earlier "
                                     "section; probe failed"}
        else:
            rows = _run_section(name, inline=sec_inline)
            if "error" in rows and len(rows) == 1:
                for k in row_keys:
                    out[k] = {"error": rows["error"]}
            else:
                out.update(rows)
        if checkpoint is not None:
            checkpoint(dict(out))
    return out


def _watchdog_main():
    """Wrapper (the default entry): run the real bench in a subprocess
    with a deadline, and if the TPU attempt hangs or dies — the axon
    tunnel has twice been observed to wedge indefinitely after a
    timed-out compile — re-run on CPU so the driver always gets its one
    JSON line instead of a hung process.
    """
    import subprocess

    # Budget: two cold headline compiles (~300 s each) + scan/link/
    # latency + up to 9 section children (incl. the pallas serving
    # row, its own cold Mosaic compile), each paying backend init and
    # possibly a cold compile (~250-330 s/section on a cold cache), and
    # at most ONE wedged section (900-2400 s timeout + 150 s probe —
    # after a failed probe the remaining device sections are skipped).
    # Cold-cache worst case with a wedged svc section ≈
    # 600+400+9×330+2550 ≈ 6500 s — over the 5400 s default, which is
    # acceptable because every section checkpoints progressively (the
    # deadline then salvages everything measured so far and costs only
    # the wedged tail, exactly like a wedged link); the default can't
    # grow without breaking the session-stage coupling (stage timeout
    # 7800 s must cover deadline + the 1800 s CPU fallback).  Warm-
    # cache runs finish in a fraction of the budget.
    deadline = int(os.environ.get("GUBER_BENCH_TIMEOUT", "5400"))
    env = dict(os.environ, GUBER_BENCH_INNER="1")
    # every bench child that serves through a dispatcher must outwait a
    # cold wave compile (250-305 s over the tunnel; VERDICT r5 item 6):
    # the inner process and its section children inherit this unless
    # the operator already chose a value
    env.setdefault("GUBER_RESULT_TIMEOUT_S", "900")
    # per-run checkpoint file: a concurrent bench on the same host must
    # not be able to cross-salvage (or permission-break) our checkpoint
    if "GUBER_BENCH_PARTIAL" not in os.environ:
        env["GUBER_BENCH_PARTIAL"] = (
            f"/tmp/gubernator_bench_partial.{os.getpid()}.json")
    partial_path = env["GUBER_BENCH_PARTIAL"]

    def attempt(extra_env, timeout):
        e = dict(env, **extra_env)
        start = time.time()
        try:
            r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                               env=e, timeout=timeout,
                               stdout=subprocess.PIPE)
            line = (r.stdout or b"").decode().strip().splitlines()
            if r.returncode == 0 and line and line[-1].startswith("{"):
                return line[-1]
        except subprocess.TimeoutExpired:
            log(f"bench attempt timed out after {timeout}s")
        except Exception as e2:  # noqa: BLE001
            log(f"bench attempt failed: {e2!r}")
        return salvage_partial(start)

    def salvage_partial(start_ts):
        """A wedged late stage (e.g. the cap27 cold compile killing the
        tunnel's compile server — observed 2026-07-31) must not cost the
        rows the inner run already measured: use its checkpoint file if
        it was written by THIS attempt."""
        try:
            if os.path.getmtime(partial_path) < start_ts:
                return None  # stale: some earlier run's checkpoint
            with open(partial_path) as f:
                d = json.load(f)
            if d.get("value", 0) <= 0:
                return None
            d["extra"]["note"] = (
                "PARTIAL: the inner bench died/hung after the headline "
                "was measured (late-stage device wedge); rows recorded "
                "before the failure are preserved, missing "
                "baseline_configs entries were not reached")
            log("salvaged partial results from checkpoint "
                f"(backend={d['extra'].get('backend')})")
            return json.dumps(d)
        except (OSError, ValueError, KeyError):
            return None

    if os.environ.get("GUBER_JAX_PLATFORM", "") == "cpu" or _device_probe():
        out = attempt({}, deadline)
    else:
        log("skipping the device attempt: backend unreachable")
        out = None
    if out is None and os.environ.get("GUBER_JAX_PLATFORM", "") != "cpu":
        log("falling back to CPU (device backend unreachable or hung)")
        fast_env = {"GUBER_JAX_PLATFORM": "cpu",
                    "GUBER_BENCH_FAST": "1",
                    "GUBER_BENCH_SCAN": "4"}
        if _PROBES_DEFAULTED:
            # the FAST shape (1M keys / CAP 2^21, load 0.48) serves
            # 100% at the serving default window
            fast_env["GUBER_PROBES"] = "8"
        out = attempt(fast_env, 1800)
        if out is not None:
            d = json.loads(out)
            prior = d["extra"].get("note", "")
            d["extra"]["note"] = ("CPU FALLBACK: the TPU backend was "
                                  "unreachable/hung; see BASELINE.md for "
                                  "the recorded TPU numbers"
                                  + ("; " + prior if prior else ""))
            out = json.dumps(d)
    if out is None:
        out = json.dumps({
            "metric": "rate-limit decisions/sec/chip @10M-key Zipf(1.1)",
            "value": 0, "unit": "decisions/s", "vs_baseline": 0.0,
            "extra": {"error": "all bench attempts failed or timed out"}})
    print(out)


#: operator hold-off sentinel: repo-local by default (a fixed world-
#: writable /tmp path could be planted by any local user or survive
#: stale from a prior session and silently skip every future bench);
#: GUBER_BENCH_SKIP_FILE overrides for operators who need another path
_SKIP_SENTINEL = os.environ.get(
    "GUBER_BENCH_SKIP_FILE",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "artifacts", "BENCH_SKIP"))

if __name__ == "__main__":
    # operator hold-off: lets a supervising session stop an already-
    # launched benchmark (or its watchdog/section children — each one
    # re-enters here) from starting device work.  The battery spawns
    # bench.py as a child long after launch; killing that child mid-
    # compile is the known tunnel-wedge mechanism, a sentinel is safe.
    if os.path.exists(_SKIP_SENTINEL):
        log(f"SKIPPED: operator hold-off sentinel present at "
            f"{_SKIP_SENTINEL} — remove it to re-enable benching")
        print(json.dumps({"metric": "skipped", "value": 0, "unit": "",
                          "vs_baseline": 0.0,
                          "extra": {"skipped":
                                    f"{_SKIP_SENTINEL} present"}}))
    elif os.environ.get("GUBER_BENCH_SECTION"):
        _section_main()
    elif os.environ.get("GUBER_BENCH_INNER"):
        main()
    else:
        _watchdog_main()

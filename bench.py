"""Benchmark: rate-limit decisions/sec/chip on BASELINE config 3.

Workload: TOKEN_BUCKET, 1M distinct keys drawn Zipf(1.1), hits=1,
limit=100, duration=10s — the reference's `gubernator-cli` load shape
(BASELINE.md config 3).  Client batches of 1000 are coalesced into
device batches (the service's request-coalescing dispatcher does the
same), and a lax.scan pipelines batches on device so dispatch overhead
is amortized — the measured quantity is sustained decision throughput on
one chip, plus single-batch round-trip latency percentiles.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extra": {...}}
vs_baseline is relative to the 50M decisions/s/chip north-star target
(BASELINE.json records no published reference numbers).
"""
import json
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


CAP = 1 << 21          # 2M rows for 1M keys (load factor 0.5)
B = 4096               # device batch = 4 coalesced client batches of 1024
SCAN_BATCHES = 64      # batches per timed device program
N_KEYS = 1_000_000
ZIPF_A = 1.1
LIMIT = 100
DURATION_MS = 10_000
NOW0 = 1_760_000_000_000


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Key-id → 64-bit hash (stand-in for host string hashing, which is
    not what this benchmark measures)."""
    from gubernator_tpu.hashing import mix64_np

    x = mix64_np((x + np.uint64(0x9E3779B97F4A7C15)).astype(np.uint64))
    return np.where(x == 0, np.uint64(1), x)


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    from gubernator_tpu.core.batch import RequestBatch
    from gubernator_tpu.core.step import decide_batch_impl
    from gubernator_tpu.core.table import init_table

    backend = jax.default_backend()
    log(f"backend={backend} devices={jax.devices()}")

    rng = np.random.default_rng(42)
    draws = rng.zipf(ZIPF_A, size=SCAN_BATCHES * B * 2) % N_KEYS
    keys_np = _splitmix64(draws.astype(np.uint64))
    warm_keys = keys_np[: SCAN_BATCHES * B].reshape(SCAN_BATCHES, B)
    timed_keys = keys_np[SCAN_BATCHES * B:].reshape(SCAN_BATCHES, B)

    i64 = jnp.int64
    const = dict(
        hits=jnp.ones(B, i64),
        limit=jnp.full(B, LIMIT, i64),
        duration=jnp.full(B, DURATION_MS, i64),
        eff_ms=jnp.full(B, DURATION_MS, i64),
        greg_end=jnp.zeros(B, i64),
        behavior=jnp.zeros(B, jnp.int32),
        algorithm=jnp.zeros(B, jnp.int32),
        burst=jnp.full(B, LIMIT, i64),
        valid=jnp.ones(B, bool),
    )

    def make_batch(key_row):
        return RequestBatch(key=key_row, **const)

    from functools import partial

    @partial(jax.jit, donate_argnums=(0,))
    def run_scan(state, keys, now0):
        def body(carry, key_row):
            st, now = carry
            st, out = decide_batch_impl(st, make_batch(key_row), now)
            return (st, now + 1), out.over_count

        (state, _), overs = lax.scan(body, (state, now0), keys)
        return state, overs.sum()

    state = init_table(CAP)

    log("warmup/compile...")
    t0 = time.perf_counter()
    state, ov = run_scan(state, warm_keys, jnp.asarray(NOW0, i64))
    ov.block_until_ready()
    log(f"warmup done in {time.perf_counter() - t0:.1f}s over={int(ov)}")

    # sustained throughput: repeat the timed scan a few times
    reps = 3
    t0 = time.perf_counter()
    total = 0
    for r in range(reps):
        state, ov = run_scan(state, timed_keys,
                             jnp.asarray(NOW0 + 100 + r, i64))
        total += SCAN_BATCHES * B
    ov.block_until_ready()
    dt = time.perf_counter() - t0
    dps = total / dt
    log(f"sustained: {total} decisions in {dt:.3f}s → {dps/1e6:.2f}M/s")

    # single-batch round-trip latency (host dispatch included)
    from gubernator_tpu.core.step import decide_batch

    lat_batch = make_batch(jnp.asarray(keys_np[:B]))
    lats = []
    state, out = decide_batch(state, lat_batch, jnp.asarray(NOW0 + 500, i64))
    out.status.block_until_ready()
    for i in range(50):
        t0 = time.perf_counter()
        state, out = decide_batch(state, lat_batch,
                                  jnp.asarray(NOW0 + 501 + i, i64))
        out.status.block_until_ready()
        lats.append((time.perf_counter() - t0) * 1e3)
    p50 = float(np.percentile(lats, 50))
    p99 = float(np.percentile(lats, 99))
    log(f"latency: p50={p50:.3f}ms p99={p99:.3f}ms (batch={B})")

    print(json.dumps({
        "metric": "rate-limit decisions/sec/chip @1M-key Zipf(1.1)",
        "value": round(dps),
        "unit": "decisions/s",
        "vs_baseline": round(dps / 50e6, 4),
        "extra": {
            "p50_ms_batch4096": round(p50, 3),
            "p99_ms_batch4096": round(p99, 3),
            "backend": backend,
            "config": "TOKEN_BUCKET 1M keys Zipf(1.1) hits=1 B=4096 CAP=2M",
            "baseline_is": "north-star target 50M/s/chip (no published reference numbers; BASELINE.md)",
        },
    }))


if __name__ == "__main__":
    main()

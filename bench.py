"""Benchmark: rate-limit decisions/sec/chip on BASELINE config 3.

Workload: TOKEN_BUCKET, 1M distinct keys drawn Zipf(1.1), hits=1,
limit=100, duration=10s — the reference's `gubernator-cli` load shape
(BASELINE.md config 3; client batches of 1000).  The dispatcher coalesces
client batches into one device batch per step (the service does the same
under load); each step is one plain-jit program — probe → gather →
branchless update → scatter — whose table writes XLA fuses into a dense
streaming copy (the TPU-idiomatic fast path; see core/step.py ›
decide_batch for why the buffers are deliberately not donated).

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extra": {...}}
vs_baseline is relative to the 50M decisions/s/chip north-star target
(BASELINE.json records no published reference numbers).
"""
import json
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


CAP = 1 << 21          # 2M rows for 1M keys (load factor 0.5)
B = 65536              # device batch = 64 coalesced client batches of 1024
N_KEYS = 1_000_000
ZIPF_A = 1.1
LIMIT = 100
DURATION_MS = 10_000
NOW0 = 1_760_000_000_000
TARGET = 50e6


def _keyhash(x: np.ndarray) -> np.ndarray:
    """Key-id → 64-bit hash (stand-in for host string hashing, which is
    not what this benchmark measures — see extra.host_hash_mkeys)."""
    from gubernator_tpu.hashing import mix64_np

    x = mix64_np((x + np.uint64(0x9E3779B97F4A7C15)).astype(np.uint64))
    return np.where(x == 0, np.uint64(1), x)


def main():
    import jax
    import jax.numpy as jnp

    from gubernator_tpu.core.batch import RequestBatch
    from gubernator_tpu.core.step import decide_batch
    from gubernator_tpu.core.table import init_table

    backend = jax.default_backend()
    log(f"backend={backend} devices={jax.devices()}")

    rng = np.random.default_rng(42)
    n_batches = 8
    draws = rng.zipf(ZIPF_A, size=n_batches * B) % N_KEYS
    key_batches = [jnp.asarray(_keyhash(draws[i * B:(i + 1) * B].astype(np.uint64)))
                   for i in range(n_batches)]

    i64 = jnp.int64
    const = dict(
        hits=jnp.ones(B, i64),
        limit=jnp.full(B, LIMIT, i64),
        duration=jnp.full(B, DURATION_MS, i64),
        eff_ms=jnp.full(B, DURATION_MS, i64),
        greg_end=jnp.zeros(B, i64),
        behavior=jnp.zeros(B, jnp.int32),
        algorithm=jnp.zeros(B, jnp.int32),
        burst=jnp.full(B, LIMIT, i64),
        valid=jnp.ones(B, bool),
    )

    def make_batch(keys):
        return RequestBatch(key=keys, **const)

    state = init_table(CAP)

    log("warmup/compile...")
    t0 = time.perf_counter()
    state, out = decide_batch(state, make_batch(key_batches[0]),
                              jnp.asarray(NOW0, i64))
    out.status.block_until_ready()
    log(f"compile+first step in {time.perf_counter() - t0:.1f}s")
    # populate the table / steady state
    for i in range(1, n_batches):
        state, out = decide_batch(state, make_batch(key_batches[i]),
                                  jnp.asarray(NOW0 + i, i64))
    out.status.block_until_ready()

    # sustained throughput: host dispatch loop, ≥15M decisions
    reps = max(1, int(15_000_000 / B / n_batches)) * n_batches
    t0 = time.perf_counter()
    for r in range(reps):
        state, out = decide_batch(state, make_batch(key_batches[r % n_batches]),
                                  jnp.asarray(NOW0 + 100 + r, i64))
    out.status.block_until_ready()
    dt = time.perf_counter() - t0
    total = reps * B
    dps = total / dt
    log(f"sustained: {total} decisions in {dt:.3f}s → {dps/1e6:.2f}M/s")

    # single-batch round-trip latency (host dispatch included)
    lats = []
    for i in range(50):
        t0 = time.perf_counter()
        state, out = decide_batch(state, make_batch(key_batches[i % n_batches]),
                                  jnp.asarray(NOW0 + 500 + i, i64))
        out.status.block_until_ready()
        lats.append((time.perf_counter() - t0) * 1e3)
    p50 = float(np.percentile(lats, 50))
    p99 = float(np.percentile(lats, 99))
    log(f"latency: p50={p50:.3f}ms p99={p99:.3f}ms (batch={B})")

    # host-side string-hash throughput (the other half of a real dispatch)
    from gubernator_tpu.hashing import hash_keys
    names = [f"bench_k{i}" for i in range(100_000)]
    t0 = time.perf_counter()
    hash_keys(names)
    hash_mkeys = len(names) / (time.perf_counter() - t0) / 1e6

    print(json.dumps({
        "metric": "rate-limit decisions/sec/chip @1M-key Zipf(1.1)",
        "value": round(dps),
        "unit": "decisions/s",
        "vs_baseline": round(dps / TARGET, 4),
        "extra": {
            "p50_ms": round(p50, 3),
            "p99_ms": round(p99, 3),
            "device_batch": B,
            "host_hash_mkeys_per_s": round(hash_mkeys, 2),
            "backend": backend,
            "config": f"TOKEN_BUCKET {N_KEYS} keys Zipf({ZIPF_A}) hits=1 CAP={CAP}",
            "baseline_is": "north-star target 50M decisions/s/chip (no published reference numbers; BASELINE.md)",
        },
    }))


if __name__ == "__main__":
    main()

"""Benchmark: rate-limit decisions/sec/chip on the north-star workload.

Workload (BASELINE.json › north_star): TOKEN_BUCKET, 10M distinct keys
drawn Zipf(1.1), hits=1, limit=100, duration=10s — the reference's
`gubernator-cli` load shape at the 10M-key working set (client batches
of 1000).  The dispatcher coalesces client batches into one device batch
per step; each step is one jit program — probe → gather → branchless
update → scatter.  TWO table-update modes are measured and the faster
one is the headline (extra.step_mode records which):

- "copy": no donation; scatters fuse into a dense streaming copy of the
  table (~2 × CAP × row-bytes per launch).
- "donate": table aliases in/out; cond-gated cold columns pass through
  copy-free and hot scatters update in place where the lowering allows
  (core/step.py › decide_batch_donated) — per-step traffic ~B-sized.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extra": {...}}
vs_baseline is relative to the 50M decisions/s/chip north-star target
(BASELINE.json records no published reference numbers).
"""
import json
import os
import sys
import time

import numpy as np

# Persistent compile cache: the decision-step program is large and a
# cold TPU compile is minutes over the tunnel; cache across bench
# invocations and sessions (_jax_cache owns the dir choice).
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _jax_cache

_jax_cache.setup()


def log(*a):
    print(*a, file=sys.stderr, flush=True)


FAST = bool(os.environ.get("GUBER_BENCH_FAST"))
#: north star is 10M keys; CAP 2^24 = load factor ~0.6.  The CPU
#: fallback (GUBER_BENCH_FAST) shrinks the workload — its config string
#: says so; it never silently stands in for the 10M-key number.
N_KEYS = int(os.environ.get("GUBER_BENCH_KEYS",
                            1_000_000 if FAST else 10_000_000))
CAP = int(os.environ.get("GUBER_BENCH_CAP", 1 << 21 if FAST else 1 << 24))
#: device batch = coalesced client batches of 1024 (GUBER_BENCH_B
#: overrides for batch-size sweeps)
B = int(os.environ.get("GUBER_BENCH_B", 8192 if FAST else 65536))
ZIPF_A = 1.1
LIMIT = 100
DURATION_MS = 10_000
NOW0 = 1_760_000_000_000
TARGET = 50e6


def _keyhash(x: np.ndarray) -> np.ndarray:
    """Key-id → 64-bit hash (stand-in for host string hashing, which is
    not what this benchmark measures — see extra.host_hash_mkeys).
    Shared with tools/tpu_session.py so both measure the same key
    distribution."""
    from gubernator_tpu.hashing import mix64_np

    x = mix64_np((x + np.uint64(0x9E3779B97F4A7C15)).astype(np.uint64))
    return np.where(x == 0, np.uint64(1), x)


def pad_chunk(chunk: np.ndarray, size: int) -> np.ndarray:
    """Pad a trailing populate chunk to the device batch size by
    repeating its last id (shared with tools/tpu_session.py)."""
    if len(chunk) < size:
        chunk = np.concatenate(
            [chunk, np.full(size - len(chunk), chunk[-1], np.uint64)])
    return chunk


def main():
    import os

    plat = os.environ.get("GUBER_JAX_PLATFORM", "")
    import jax

    if plat:
        # must go through jax.config: the sandbox sitecustomize overwrites
        # the jax_platforms config at interpreter start (env is ignored)
        jax.config.update("jax_platforms", plat)
    import jax.numpy as jnp

    from gubernator_tpu.core.batch import RequestBatch
    from gubernator_tpu.core.step import decide_batch, decide_batch_donated
    from gubernator_tpu.core.table import init_table

    backend = jax.default_backend()
    log(f"backend={backend} devices={jax.devices()}")

    rng = np.random.default_rng(42)
    n_batches = 8
    draws = rng.zipf(ZIPF_A, size=n_batches * B) % N_KEYS
    key_batches = [jnp.asarray(_keyhash(draws[i * B:(i + 1) * B].astype(np.uint64)))
                   for i in range(n_batches)]

    i64 = jnp.int64
    const = dict(
        hits=jnp.ones(B, i64),
        limit=jnp.full(B, LIMIT, i64),
        duration=jnp.full(B, DURATION_MS, i64),
        eff_ms=jnp.full(B, DURATION_MS, i64),
        greg_end=jnp.zeros(B, i64),
        behavior=jnp.zeros(B, jnp.int32),
        algorithm=jnp.zeros(B, jnp.int32),
        burst=jnp.full(B, LIMIT, i64),
        valid=jnp.ones(B, bool),
    )

    def make_batch(keys):
        return RequestBatch(key=keys, **const)

    def populate(step_fn, st):
        """Insert ALL N_KEYS distinct keys so the measured loop runs at
        the claimed working set (load factor N_KEYS/CAP), not at the few
        hundred thousand distinct keys a handful of Zipf draws covers —
        the sustained number must be the steady-state resident-table
        rate it claims to be."""
        ids = np.arange(N_KEYS, dtype=np.uint64)
        for a in range(0, N_KEYS, B):
            chunk = pad_chunk(ids[a:a + B], B)
            st, out = step_fn(st, make_batch(jnp.asarray(_keyhash(chunk))),
                              jnp.asarray(NOW0, i64))
        out.status.block_until_ready()
        return st

    def measure_mode(step_fn, label, sustain_target=15_000_000):
        """Compile, populate the full working set, then time a sustained
        dispatch loop at steady state."""
        st = init_table(CAP)
        t0 = time.perf_counter()
        st, out = step_fn(st, make_batch(key_batches[0]),
                          jnp.asarray(NOW0, i64))
        out.status.block_until_ready()
        log(f"[{label}] compile+first step in "
            f"{time.perf_counter() - t0:.1f}s")
        t0 = time.perf_counter()
        st = populate(step_fn, st)
        log(f"[{label}] populated {N_KEYS} keys "
            f"(load {N_KEYS/CAP:.2f}) in {time.perf_counter() - t0:.1f}s")
        for i in range(1, n_batches):
            st, out = step_fn(st, make_batch(key_batches[i]),
                              jnp.asarray(NOW0 + i, i64))
        out.status.block_until_ready()
        reps = max(1, int(sustain_target / B / n_batches)) * n_batches
        t0 = time.perf_counter()
        for r in range(reps):
            st, out = step_fn(st, make_batch(key_batches[r % n_batches]),
                              jnp.asarray(NOW0 + 100 + r, i64))
        out.status.block_until_ready()
        dt = time.perf_counter() - t0
        rate = reps * B / dt
        log(f"[{label}] sustained: {reps * B} decisions in {dt:.3f}s "
            f"→ {rate/1e6:.2f}M/s")
        return rate, st

    # mode 1: dense-copy step (safe everywhere)
    dps_copy, state = measure_mode(decide_batch, "copy")
    # mode 2: donated step — in-place updates where the lowering allows;
    # this is the mode that breaks the CAP-linear streaming wall
    try:
        dps_donate, _ = measure_mode(decide_batch_donated, "donate")
    except Exception as e:  # noqa: BLE001
        dps_donate = 0.0
        log(f"donated-step mode failed: {e!r:.200}")
    step_mode = "donate" if dps_donate > dps_copy else "copy"
    dps = max(dps_copy, dps_donate)
    step_best = (decide_batch_donated if step_mode == "donate"
                 else decide_batch)
    log(f"headline mode: {step_mode} ({dps/1e6:.2f}M/s)")

    # Checkpoint the headline IMMEDIATELY: every section below (scan,
    # latency, client-batch) needs its own cold compile and any of them
    # can wedge the tunnel — the measured record must already be on
    # disk when that happens (observed 2026-07-31: the post-headline
    # latency sections stalling while the headline was only in stderr).
    result = {
        "metric": (f"rate-limit decisions/sec/chip @{N_KEYS//1_000_000}M-key"
                   f" Zipf({ZIPF_A})"),
        "value": round(dps),
        "unit": "decisions/s",
        "vs_baseline": round(dps / TARGET, 4),
        "extra": {
            "step_mode": step_mode,
            "copy_mode_decisions_per_s": round(dps_copy),
            "donate_mode_decisions_per_s": round(dps_donate),
            "device_batch": B,
            "backend": backend,
            "config": f"TOKEN_BUCKET {N_KEYS} keys Zipf({ZIPF_A}) hits=1 CAP={CAP}",
            "baseline_is": ("north-star target 50M decisions/s/chip (no "
                            "published reference numbers; BASELINE.md)"),
            "baseline_configs": {},
        },
    }
    _write_partial(result)

    # device-resident superstep: lax.scan chains R batches in ONE launch,
    # so per-launch dispatch latency (µs locally, ~0.5 ms over a
    # tunneled link) amortizes across R×B decisions — the on-chip
    # sustained rate, which is what N coalesced client batches see.
    R = int(os.environ.get("GUBER_BENCH_SCAN", 16))
    import jax as _jax
    from jax import lax as _lax

    from gubernator_tpu.core.step import decide_batch_impl

    @_jax.jit
    def decide_scan(st, keys_rb, now0):
        def body(carry, x):
            st, i = carry
            b = RequestBatch(key=x, **const)
            st, out = decide_batch_impl(st, b, now0 + i)
            return (st, i + 1), out.status.sum()
        (st, _), overs = _lax.scan(body, (st, jnp.asarray(0, i64)), keys_rb)
        return st, overs

    try:
        keys_rb = jnp.stack(key_batches[:min(R, n_batches)] *
                            (R // n_batches + 1))[:R]
        st_s = init_table(CAP)
        st_s, ov = decide_scan(st_s, keys_rb, jnp.asarray(NOW0, i64))
        ov.block_until_ready()  # compile + warm
        reps_s = max(1, int(30_000_000 / (R * B)))
        t0 = time.perf_counter()
        for r in range(reps_s):
            st_s, ov = decide_scan(st_s, keys_rb,
                                   jnp.asarray(NOW0 + 1000 + r * R, i64))
        ov.block_until_ready()
        dps_scan = reps_s * R * B / (time.perf_counter() - t0)
        log(f"device-scan sustained: {dps_scan/1e6:.2f}M/s (R={R})")
    except Exception as e:  # noqa: BLE001
        dps_scan = 0.0
        log(f"device-scan failed: {e!r:.200}")

    # link round-trip floor: a trivial op's dispatch→sync time.  On a
    # direct-attached chip this is ~50 µs; over the axon tunnel it is
    # the WAN round trip (~0.5 ms, with multi-ms jitter tails).  The
    # client-batch percentiles below include this floor, so recording
    # it lets the p99<2ms target be decomposed into device+host work
    # vs link cost from this JSON alone.
    link_p50 = link_p99 = -1.0
    try:
        one = jnp.ones((), jnp.int32)
        trivial = jax.jit(lambda x: x + 1)
        trivial(one).block_until_ready()
        link = []
        for _ in range(60):
            t0 = time.perf_counter()
            trivial(one).block_until_ready()
            link.append((time.perf_counter() - t0) * 1e3)
        link_p50 = float(np.percentile(link, 50))
        link_p99 = float(np.percentile(link, 99))
        log(f"link round-trip: p50={link_p50:.3f}ms p99={link_p99:.3f}ms")
    except Exception as e:  # noqa: BLE001
        log(f"link-rtt probe failed: {e!r:.200}")

    # single-batch round-trip latency (host dispatch included), in the
    # winning mode — the copy cost it avoids is latency too
    p50 = p99 = -1.0
    try:
        lats = []
        for i in range(50):
            t0 = time.perf_counter()
            state, out = step_best(state,
                                   make_batch(key_batches[i % n_batches]),
                                   jnp.asarray(NOW0 + 500 + i, i64))
            out.status.block_until_ready()
            lats.append((time.perf_counter() - t0) * 1e3)
        p50 = float(np.percentile(lats, 50))
        p99 = float(np.percentile(lats, 99))
        log(f"latency: p50={p50:.3f}ms p99={p99:.3f}ms (batch={B})")
    except Exception as e:  # noqa: BLE001
        log(f"latency section failed: {e!r:.200}")

    # client-shaped latency: one max-size GetRateLimits batch (1000 reqs
    # in a 1024 bucket) per device call — the p99<2ms target's shape
    p50_c = p99_c = -1.0
    try:
        Bc = 1024
        small = RequestBatch(
            key=key_batches[0][:Bc],
            **{k: (v[:Bc] if hasattr(v, "shape") else v)
               for k, v in const.items()})
        state_c = init_table(CAP)
        state_c, outc = step_best(state_c, small, jnp.asarray(NOW0, i64))
        outc.status.block_until_ready()
        lats_c = []
        for i in range(100):
            t0 = time.perf_counter()
            state_c, outc = step_best(state_c, small,
                                      jnp.asarray(NOW0 + i, i64))
            outc.status.block_until_ready()
            lats_c.append((time.perf_counter() - t0) * 1e3)
        p50_c = float(np.percentile(lats_c, 50))
        p99_c = float(np.percentile(lats_c, 99))
        log(f"client-batch latency: p50={p50_c:.3f}ms p99={p99_c:.3f}ms "
            f"(batch={Bc})")
    except Exception as e:  # noqa: BLE001
        log(f"client-batch latency section failed: {e!r:.200}")

    # host-side string-hash throughput (the other half of a real dispatch)
    from gubernator_tpu.hashing import hash_keys
    names = [f"bench_k{i}" for i in range(100_000)]
    t0 = time.perf_counter()
    hash_keys(names)
    hash_mkeys = len(names) / (time.perf_counter() - t0) / 1e6

    result["extra"].update({
        "device_scan_decisions_per_s": round(dps_scan),
        "p50_ms": round(p50, 3),
        "p99_ms": round(p99, 3),
        "client_batch_p50_ms": round(p50_c, 3),
        "client_batch_p99_ms": round(p99_c, 3),
        "link_roundtrip_p50_ms": round(link_p50, 3),
        "link_roundtrip_p99_ms": round(link_p99, 3),
        "host_hash_mkeys_per_s": round(hash_mkeys, 2),
    })
    # Checkpoint again after the latency sections and after every
    # secondary config: a late-stage device wedge (observed: the cap27
    # cold compile killing the tunnel's compile server) must not cost
    # the rows already measured — the watchdog salvages this file.
    _write_partial(result)

    def ck(cfgs):
        result["extra"]["baseline_configs"] = cfgs
        _write_partial(result)

    configs = run_secondary_configs(jnp, decide_batch, const, step_mode,
                                    checkpoint=ck)
    result["extra"]["baseline_configs"] = configs
    _write_partial(result)
    print(json.dumps(result))


PARTIAL_PATH = os.environ.get("GUBER_BENCH_PARTIAL",
                              "/tmp/gubernator_bench_partial.json")


def _write_partial(result: dict) -> None:
    try:
        tmp = PARTIAL_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(result, f)
        os.replace(tmp, PARTIAL_PATH)
    except OSError as e:  # pragma: no cover - diagnostics only
        log(f"partial checkpoint write failed: {e}")


def _sustain(decide_batch, jnp, state, batches, reps, now0):
    """Measure a sustained dispatch loop → decisions/s."""
    i64 = jnp.int64
    out = None
    t0 = time.perf_counter()
    for r in range(reps):
        state, out = decide_batch(state, batches[r % len(batches)],
                                  jnp.asarray(now0 + r, i64))
    out.status.block_until_ready()
    dt = time.perf_counter() - t0
    return reps * batches[0].key.shape[0] / dt, state


def run_secondary_configs(jnp, decide_batch, const_proto,
                          step_mode="copy", checkpoint=None):
    """BASELINE.md configs 1/2/4/5 (config 3 is the headline above).
    Smaller rep counts — these document shape coverage, not the record.
    ``checkpoint(out)`` is called after each config so rows measured
    before a late-stage device failure survive (see _write_partial)."""
    import jax

    # serving engines built below (V1Instance, the 3-daemon cluster)
    # read this at construction: they must run the mode that won —
    # set it explicitly BOTH ways so a pre-existing operator export
    # can't make the rows measure a different mode than reported
    os.environ["GUBER_STEP_DONATE"] = ("1" if step_mode == "donate"
                                      else "0")

    from gubernator_tpu.core.batch import RequestBatch
    from gubernator_tpu.core.table import init_table
    from gubernator_tpu.gregorian import gregorian_expiration
    from gubernator_tpu.types import Behavior, GregorianDuration

    i64, i32 = jnp.int64, jnp.int32
    out = {}

    def _ck():
        if checkpoint is not None:
            checkpoint(dict(out))
    rng = np.random.default_rng(7)

    def mk(keys, **over):
        B2 = keys.shape[0]
        cols = dict(
            hits=jnp.ones(B2, i64), limit=jnp.full(B2, LIMIT, i64),
            duration=jnp.full(B2, DURATION_MS, i64),
            eff_ms=jnp.full(B2, DURATION_MS, i64),
            greg_end=jnp.zeros(B2, i64), behavior=jnp.zeros(B2, i32),
            algorithm=jnp.zeros(B2, i32), burst=jnp.full(B2, LIMIT, i64),
            valid=jnp.ones(B2, bool),
            # 0 = use the step's scalar now argument (these configs
            # advance time per call through _sustain)
            now=jnp.zeros(B2, i64))
        cols.update(over)
        return RequestBatch(key=jnp.asarray(keys), **cols)

    # -- config 1: single key, TOKEN_BUCKET (examples_test.go smoke).
    # Every request in the batch is the same key: the worst case for the
    # duplicate-segment path (one segment of length B).
    try:
        Bs = 4096
        keys1 = np.full(Bs, 12345, np.uint64)
        st = init_table(1 << 12)
        b = mk(keys1, limit=jnp.full(Bs, 10**9, i64))
        st, _ = decide_batch(st, b, jnp.asarray(NOW0, i64))  # compile
        dps1, _ = _sustain(decide_batch, jnp, st, [b], 20, NOW0 + 1)
        out["1_single_key_smoke"] = {"decisions_per_s": round(dps1)}
    except Exception as e:  # noqa: BLE001
        out["1_single_key_smoke"] = {"error": str(e)[:200]}

    _ck()
    # -- config 2: LEAKY_BUCKET, 1k keys uniform.
    try:
        keys2 = _keyhash(rng.integers(0, 1000, size=Bs).astype(np.uint64))
        st = init_table(1 << 12)
        b2 = mk(keys2, algorithm=jnp.ones(Bs, i32),
                limit=jnp.full(Bs, 10**6, i64),
                burst=jnp.full(Bs, 10**6, i64),
                duration=jnp.full(Bs, 60_000, i64),
                eff_ms=jnp.full(Bs, 60_000, i64))
        st, _ = decide_batch(st, b2, jnp.asarray(NOW0, i64))
        dps2, _ = _sustain(decide_batch, jnp, st, [b2], 20, NOW0 + 1)
        out["2_leaky_1k_keys"] = {"decisions_per_s": round(dps2)}
    except Exception as e:  # noqa: BLE001
        out["2_leaky_1k_keys"] = {"error": str(e)[:200]}

    _ck()
    # -- config 4: GLOBAL multi-peer ≙ sharded mesh step over all local
    # devices (4-chip ICI on a pod; 1 chip here → measures shard_map
    # overhead on the same program).
    try:
        from gubernator_tpu.parallel import make_mesh
        from gubernator_tpu.parallel.sharded import make_sharded_step
        from gubernator_tpu.parallel.mesh import shard_table
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = make_mesh()
        n = mesh.shape["shard"]
        step = make_sharded_step(mesh)
        stg = shard_table(mesh, 1 << 18)
        Bg = 16384 * n
        keysg = _keyhash(rng.zipf(ZIPF_A, size=Bg) % 100_000)
        bg = mk(keysg)
        sh = NamedSharding(mesh, P("shard"))
        bg = RequestBatch(*[jax.device_put(np.asarray(x), sh) for x in bg])
        stg, o, _ = step(stg, bg, jnp.asarray(NOW0, i64))
        t0 = time.perf_counter()
        reps = 20
        for r in range(reps):
            stg, o, _ = step(stg, bg, jnp.asarray(NOW0 + 1 + r, i64))
        o[0].block_until_ready()
        dps4 = reps * Bg / (time.perf_counter() - t0)
        out["4_global_sharded"] = {"decisions_per_s": round(dps4),
                                   "n_shards": int(n)}
    except Exception as e:  # noqa: BLE001
        out["4_global_sharded"] = {"error": str(e)[:200]}

    _ck()
    # -- service path: full V1Instance routing + dispatcher + response
    # assembly (the analog of benchmark_test.go › BenchmarkServer_
    # GetRateLimit: what a client sees per node, host costs included).
    try:
        from gubernator_tpu.config import Config
        from gubernator_tpu.instance import V1Instance
        from gubernator_tpu.parallel import make_mesh
        from gubernator_tpu.types import RateLimitRequest

        inst = V1Instance(Config(cache_size=1 << 16, sweep_interval_ms=0),
                          mesh=make_mesh(n=1))
        reqs5 = [[RateLimitRequest(name="svc", unique_key=f"k{int(k)}",
                                   hits=1, limit=100, duration=60_000)
                  for k in rng.zipf(ZIPF_A, size=1000) % 100_000]
                 for _ in range(4)]
        inst.get_rate_limits(reqs5[0], now_ms=NOW0)
        t0 = time.perf_counter()
        reps = 20
        for r in range(reps):
            inst.get_rate_limits(reqs5[r % 4], now_ms=NOW0 + 1 + r)
        dps_svc = reps * 1000 / (time.perf_counter() - t0)
        out["6_service_path"] = {"decisions_per_s": round(dps_svc),
                                 "batch": 1000}
        # the C++ wire lane (bytes → columns → device → bytes), the
        # path a gRPC client actually exercises
        try:
            from gubernator_tpu.proto import gubernator_pb2 as pb
            from gubernator_tpu.wire import req_to_pb

            datas = []
            for rs in reqs5:
                m = pb.GetRateLimitsReq()
                m.requests.extend(req_to_pb(r) for r in rs)
                datas.append(m.SerializeToString())
            inst.get_rate_limits_wire(datas[0], now_ms=NOW0 + 100)
            t0 = time.perf_counter()
            for r in range(reps):
                inst.get_rate_limits_wire(datas[r % 4],
                                          now_ms=NOW0 + 101 + r)
            out["6_service_path"]["wire_lane_decisions_per_s"] = round(
                reps * 1000 / (time.perf_counter() - t0))
            # service-layer latency at the client-batch shape (the
            # p99 < 2 ms target's request): bytes → decisions → bytes
            # through the full V1Instance wire lane
            lat = []
            for r in range(60):
                t0 = time.perf_counter()
                inst.get_rate_limits_wire(datas[r % 4],
                                          now_ms=NOW0 + 130 + r)
                lat.append((time.perf_counter() - t0) * 1e3)
            out["6_service_path"]["svc_p50_ms"] = round(
                float(np.percentile(lat, 50)), 3)
            out["6_service_path"]["svc_p99_ms"] = round(
                float(np.percentile(lat, 99)), 3)
        except Exception as e:  # noqa: BLE001
            out["6_service_path"]["wire_lane_error"] = str(e)[:200]
        # concurrent front door: 16 caller threads through the full
        # wire lane — the dispatcher coalesces them into shared waves
        # (wave_buckets), which is what a loaded gRPC server does
        try:
            import threading as _th

            n_threads, reps_c = 16, 8
            if hasattr(inst.engine, "warmup"):
                inst.engine.warmup()  # big-bucket program, outside timing
            inst.get_rate_limits_wire(datas[0], now_ms=NOW0 + 150)

            def _worker(t):
                for r in range(reps_c):
                    inst.get_rate_limits_wire(datas[(t + r) % 4],
                                              now_ms=NOW0 + 160 + r)

            ths = [_th.Thread(target=_worker, args=(t,))
                   for t in range(n_threads)]
            t0 = time.perf_counter()
            for th in ths:
                th.start()
            for th in ths:
                th.join()
            out["6_service_path"]["concurrent16_decisions_per_s"] = round(
                n_threads * reps_c * 1000 / (time.perf_counter() - t0))
        except Exception as e:  # noqa: BLE001
            out["6_service_path"]["concurrent_error"] = str(e)[:200]
        # peer-forwarding path (benchmark_test.go ›
        # BenchmarkServer_GetPeerRateLimit analog): the owner-side
        # apply a forwarded batch takes, via its wire lane
        try:
            from gubernator_tpu.proto import peers_pb2 as peers_pb

            pdatas = []
            for rs in reqs5:
                m = peers_pb.GetPeerRateLimitsReq()
                m.requests.extend(req_to_pb(r) for r in rs)
                pdatas.append(m.SerializeToString())
            inst.get_peer_rate_limits_wire(pdatas[0], now_ms=NOW0 + 200)
            t0 = time.perf_counter()
            for r in range(reps):
                inst.get_peer_rate_limits_wire(pdatas[r % 4],
                                               now_ms=NOW0 + 201 + r)
            out["8_peer_path"] = {
                "decisions_per_s": round(
                    reps * 1000 / (time.perf_counter() - t0)),
                "batch": 1000}
        except Exception as e:  # noqa: BLE001
            out["8_peer_path"] = {"error": str(e)[:200]}
        inst.close()
    except Exception as e:  # noqa: BLE001
        out["6_service_path"] = {"error": str(e)[:200]}

    _ck()
    # -- clustered service path (VERDICT r1 item 4's bench criterion):
    # client-facing GetRateLimits through daemon 0 of a real 3-daemon
    # loopback cluster, keys ring-split across owners, forwards riding
    # the raw-TLV peer wire — the number a clustered deployment sees.
    try:
        from gubernator_tpu import cluster as cluster_mod
        from gubernator_tpu.proto import gubernator_pb2 as pb2c

        c3 = cluster_mod.start(3, cache_size=1 << 14, batch_rows=1024)
        try:
            inst0 = c3.instance_at(0)
            reps = 12
            inst0.get_rate_limits_wire(datas[0], now_ms=NOW0 + 300)
            t0 = time.perf_counter()
            for r in range(reps):
                inst0.get_rate_limits_wire(datas[r % 4],
                                           now_ms=NOW0 + 301 + r)
            dps_c3 = reps * 1000 / (time.perf_counter() - t0)
            lane = inst0.metrics.wire_lane_counter.labels(
                lane="wire_clustered")._value.get()
            out["9_clustered_service"] = {
                "decisions_per_s": round(dps_c3), "daemons": 3,
                "wire_clustered_requests": int(lane)}
        finally:
            c3.stop()
    except Exception as e:  # noqa: BLE001
        out["9_clustered_service"] = {"error": str(e)[:200]}

    _ck()
    # -- SO_REUSEPORT front-door group (VERDICT r1 item 5): N daemon
    # PROCESSES share one client gRPC port; kernel spreads connections;
    # keys ring-split across per-process engines with raw-TLV peer
    # forwards.  This is the aggregate host throughput a one-machine
    # deployment front door actually delivers — real sockets, real
    # serialization, every GIL boundary included.  Runs on the CPU
    # backend by design (subprocesses can't share the TPU chip; on a
    # TPU host these are the ingest workers).
    host_cores = len(os.sched_getaffinity(0)) if hasattr(
        os, "sched_getaffinity") else (os.cpu_count() or 1)
    if os.environ.get("GUBER_BENCH_SKIP_GROUP"):
        pass
    elif host_cores < 4:
        # process-level scaling needs cores to scale over: on a 1-2
        # core host N JAX processes thrash the scheduler (measured:
        # 18k/s aggregate, p99 25s on 1 core) — an honest skip beats a
        # garbage number.  The per-process ceiling is measured by
        # 6_service_path's concurrent row.
        out["10_reuseport_group"] = {
            "skipped": f"host has {host_cores} core(s); the SO_REUSEPORT "
                       "group measures process-level front-door scaling "
                       "and needs >=4"}
    else:
        try:
            import threading as _th

            import grpc as _grpc

            from gubernator_tpu.cluster import start_subprocess_group
            from gubernator_tpu.proto import gubernator_pb2 as pb_g
            from gubernator_tpu.types import RateLimitRequest
            from gubernator_tpu.wire import req_to_pb as req_to_pb_g

            # self-contained request batches: this row must not depend
            # on 6_service_path's locals surviving
            gdatas = []
            for _ in range(4):
                mm = pb_g.GetRateLimitsReq()
                mm.requests.extend(req_to_pb_g(RateLimitRequest(
                    name="grp", unique_key=f"k{int(k)}", hits=1,
                    limit=100, duration=60_000))
                    for k in rng.zipf(ZIPF_A, size=1000) % 100_000)
                gdatas.append(mm.SerializeToString())

            n_procs = 2 if FAST else min(4, host_cores)
            grp = start_subprocess_group(n_procs, cache_size=1 << 16,
                                         batch_rows=1024)
            chans = []
            try:
                n_chan, reps_g = 4 * n_procs, 40
                chans = [_grpc.insecure_channel(
                    grp.client_address,
                    options=[("grpc.use_local_subchannel_pool", 1)])
                    for _ in range(n_chan)]
                calls = [c.unary_unary("/pb.gubernator.V1/GetRateLimits")
                         for c in chans]
                # connect + warmup: timed traffic reuses these same
                # connections, and each warmup batch ring-forwards
                # sub-batches to EVERY process, so every engine has
                # compiled its wave program before timing starts
                for call in calls:
                    call(gdatas[0], timeout=60)
                lat_g = [[] for _ in range(n_chan)]

                g_errors = []

                def _gworker(t):
                    try:
                        for r in range(reps_g):
                            t1 = time.perf_counter()
                            calls[t](gdatas[(t + r) % 4], timeout=60)
                            lat_g[t].append((time.perf_counter() - t1) * 1e3)
                    except Exception as e2:  # noqa: BLE001
                        g_errors.append(str(e2)[:120])

                ths = [_th.Thread(target=_gworker, args=(t,))
                       for t in range(n_chan)]
                t0 = time.perf_counter()
                for th in ths:
                    th.start()
                for th in ths:
                    th.join()
                wall = time.perf_counter() - t0
                # numerator = calls that actually completed: a daemon
                # dying mid-run must not inflate the rate
                flat = [x for ls in lat_g for x in ls]
                row = {
                    "decisions_per_s": round(len(flat) * 1000 / wall),
                    "processes": n_procs, "connections": n_chan}
                if flat:
                    row["p50_ms"] = round(float(np.percentile(flat, 50)), 3)
                    row["p99_ms"] = round(float(np.percentile(flat, 99)), 3)
                if g_errors:
                    row["worker_errors"] = g_errors[:3]
                out["10_reuseport_group"] = row
            finally:
                for c in chans:
                    try:
                        c.close()
                    except Exception:  # noqa: BLE001
                        pass
                grp.stop()
        except Exception as e:  # noqa: BLE001
            out["10_reuseport_group"] = {"error": str(e)[:200]}

    _ck()
    # -- hot-set psum tier: replica-local GLOBAL decisions + one psum
    # fold per sync (the north-star replacement for global.go).
    try:
        from gubernator_tpu.hashing import hash_key
        from gubernator_tpu.parallel import HotSetEngine, make_mesh
        from gubernator_tpu.types import RateLimitRequest

        mesh = make_mesh()
        hot = HotSetEngine(mesh, capacity=1024, batch_per_chip=2048)
        n = hot.n
        hreq = RateLimitRequest(name="hot", unique_key="k", hits=1,
                                limit=10**9, duration=600_000)
        hkh = hash_key("hot", "k")
        hot.pin(hreq, hkh, NOW0)
        wave = [hreq] * (n * 2048)
        khs = [hkh] * len(wave)
        hot.check_batch(wave, khs, NOW0)  # compile
        t0 = time.perf_counter()
        reps = 10
        for r in range(reps):
            hot.check_batch(wave, khs, NOW0 + 1 + r)
        dps_hot = reps * len(wave) / (time.perf_counter() - t0)
        hot.sync()
        jax.block_until_ready(hot.state)
        t0 = time.perf_counter()
        for _ in range(20):
            hot.sync()
        jax.block_until_ready(hot.state)  # async dispatch: wait for the fold
        sync_ms = (time.perf_counter() - t0) / 20 * 1e3
        out["7_hot_psum"] = {"decisions_per_s": round(dps_hot),
                             "sync_ms": round(sync_ms, 3),
                             "n_replicas": int(n)}
    except Exception as e:  # noqa: BLE001
        out["7_hot_psum"] = {"error": str(e)[:200]}

    _ck()
    # -- config 5: huge multi-tenant table (100M keys → CAP 2^27),
    # Gregorian resets + RESET_REMAINING churn.  The TRUE BASELINE.json
    # capacity is attempted — never silently downscaled (VERDICT r1
    # item 3): the donated step keeps ONE copy of the ~9 GB table live
    # (in-place/pass-through updates), which is what makes 2^27 fit a
    # 16 GB chip at all.  A failure (OOM, lowering) is recorded as an
    # error row, honestly.  The CPU fallback uses a reduced capacity and
    # says so via "cpu_reduced".
    cpu5 = jax.default_backend() == "cpu"
    cap5 = 1 << 22 if cpu5 else 1 << 27
    try:
        from gubernator_tpu.core.step import decide_batch_donated
        n_keys5 = int(cap5 * 0.75)
        st5 = init_table(cap5)
        greg_end = gregorian_expiration(NOW0, int(GregorianDuration.HOURS))
        beh = int(Behavior.DURATION_IS_GREGORIAN)
        batches = []
        for i in range(4):
            k = _keyhash(rng.integers(0, n_keys5, size=B).astype(np.uint64))
            beh_col = np.full(B, beh, np.int32)
            beh_col[:: 37] |= int(Behavior.RESET_REMAINING)  # churn
            batches.append(mk(
                k, duration=jnp.full(B, int(GregorianDuration.HOURS), i64),
                eff_ms=jnp.full(B, 3_600_000, i64),
                greg_end=jnp.full(B, greg_end, i64),
                behavior=jnp.asarray(beh_col)))
        st5, _ = decide_batch_donated(st5, batches[0],
                                      jnp.asarray(NOW0, i64))
        dps5, _ = _sustain(decide_batch_donated, jnp, st5, batches, 16,
                           NOW0 + 1)
        out["5_gregorian_churn"] = {"decisions_per_s": round(dps5),
                                    "capacity": cap5,
                                    "cpu_reduced": cpu5}
    except Exception as e:  # noqa: BLE001
        out["5_gregorian_churn"] = {"error": str(e)[:200],
                                    "capacity_attempted": int(cap5)}
    return out


def _watchdog_main():
    """Wrapper (the default entry): run the real bench in a subprocess
    with a deadline, and if the TPU attempt hangs or dies — the axon
    tunnel has twice been observed to wedge indefinitely after a
    timed-out compile — re-run on CPU so the driver always gets its one
    JSON line instead of a hung process.
    """
    import subprocess

    # two headline compiles (copy + donated) can both be cold on TPU
    deadline = int(os.environ.get("GUBER_BENCH_TIMEOUT", "4500"))
    env = dict(os.environ, GUBER_BENCH_INNER="1")
    # per-run checkpoint file: a concurrent bench on the same host must
    # not be able to cross-salvage (or permission-break) our checkpoint
    if "GUBER_BENCH_PARTIAL" not in os.environ:
        env["GUBER_BENCH_PARTIAL"] = (
            f"/tmp/gubernator_bench_partial.{os.getpid()}.json")
    partial_path = env["GUBER_BENCH_PARTIAL"]

    def attempt(extra_env, timeout):
        e = dict(env, **extra_env)
        start = time.time()
        try:
            r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                               env=e, timeout=timeout,
                               stdout=subprocess.PIPE)
            line = (r.stdout or b"").decode().strip().splitlines()
            if r.returncode == 0 and line and line[-1].startswith("{"):
                return line[-1]
        except subprocess.TimeoutExpired:
            log(f"bench attempt timed out after {timeout}s")
        except Exception as e2:  # noqa: BLE001
            log(f"bench attempt failed: {e2!r}")
        return salvage_partial(start)

    def salvage_partial(start_ts):
        """A wedged late stage (e.g. the cap27 cold compile killing the
        tunnel's compile server — observed 2026-07-31) must not cost the
        rows the inner run already measured: use its checkpoint file if
        it was written by THIS attempt."""
        try:
            if os.path.getmtime(partial_path) < start_ts:
                return None  # stale: some earlier run's checkpoint
            with open(partial_path) as f:
                d = json.load(f)
            if d.get("value", 0) <= 0:
                return None
            d["extra"]["note"] = (
                "PARTIAL: the inner bench died/hung after the headline "
                "was measured (late-stage device wedge); rows recorded "
                "before the failure are preserved, missing "
                "baseline_configs entries were not reached")
            log("salvaged partial results from checkpoint "
                f"(backend={d['extra'].get('backend')})")
            return json.dumps(d)
        except (OSError, ValueError, KeyError):
            return None

    def device_probe(timeout=150) -> bool:
        """Trivial-op probe in a throwaway subprocess: the axon tunnel
        has repeatedly been observed wedged such that backend init
        hangs forever — don't spend the full deadline discovering
        that.  (150 s covers a healthy cold init + trivial compile many
        times over; this mirrors the probe protocol in ROUND_NOTES.)
        A probe that answers with the CPU backend is a FAILED device
        probe: jax fell back silently, and running the device-sized
        workload there would burn the deadline and mislabel the rows."""
        code = ("import jax, jax.numpy as jnp;"
                "jnp.arange(8).sum().block_until_ready();"
                "print(jax.default_backend())")
        try:
            r = subprocess.run([sys.executable, "-c", code],
                               timeout=timeout, stdout=subprocess.PIPE,
                               stderr=subprocess.PIPE)
            backend = (r.stdout or b"").decode().strip()
            ok = r.returncode == 0 and backend not in ("", "cpu")
            log(f"device probe: ok={ok} backend={backend!r}")
            if r.returncode != 0:
                tail = (r.stderr or b"").decode(errors="replace")[-400:]
                log(f"device probe stderr tail: {tail}")
            return ok
        except Exception as e2:  # noqa: BLE001
            log(f"device probe failed: {e2!r:.120} (tunnel wedged?)")
            return False

    if os.environ.get("GUBER_JAX_PLATFORM", "") == "cpu" or device_probe():
        out = attempt({}, deadline)
    else:
        log("skipping the device attempt: backend unreachable")
        out = None
    if out is None and os.environ.get("GUBER_JAX_PLATFORM", "") != "cpu":
        log("falling back to CPU (device backend unreachable or hung)")
        out = attempt({"GUBER_JAX_PLATFORM": "cpu",
                       "GUBER_BENCH_FAST": "1",
                       "GUBER_BENCH_SCAN": "4"}, 1800)
        if out is not None:
            d = json.loads(out)
            prior = d["extra"].get("note", "")
            d["extra"]["note"] = ("CPU FALLBACK: the TPU backend was "
                                  "unreachable/hung; see BASELINE.md for "
                                  "the recorded TPU numbers"
                                  + ("; " + prior if prior else ""))
            out = json.dumps(d)
    if out is None:
        out = json.dumps({
            "metric": "rate-limit decisions/sec/chip @10M-key Zipf(1.1)",
            "value": 0, "unit": "decisions/s", "vs_baseline": 0.0,
            "extra": {"error": "all bench attempts failed or timed out"}})
    print(out)


if __name__ == "__main__":
    if os.environ.get("GUBER_BENCH_INNER"):
        main()
    else:
        _watchdog_main()

"""Example: use the device engine directly (no service shell).

For applications embedding the rate limiter in-process, the way the
reference is embeddable as a Go library.
Run: python examples/embedded_engine.py
"""
import time

from gubernator_tpu.parallel import ShardedEngine, make_mesh
from gubernator_tpu.types import Algorithm, RateLimitRequest


def main() -> None:
    engine = ShardedEngine(make_mesh(), capacity_per_shard=1 << 16)
    now_ms = int(time.time() * 1000)

    reqs = [RateLimitRequest(name="api", unique_key=f"user:{i}", hits=1,
                             limit=100, duration=60_000,
                             algorithm=Algorithm.TOKEN_BUCKET)
            for i in range(1000)]
    t0 = time.perf_counter()
    resps = engine.check_batch(reqs, now_ms)
    print(f"first batch (incl. compile): {time.perf_counter() - t0:.2f}s")

    t0 = time.perf_counter()
    resps = engine.check_batch(reqs, now_ms + 10)
    dt = time.perf_counter() - t0
    over = sum(1 for r in resps if int(r.status) == 1)
    print(f"1000 decisions in {dt * 1e3:.2f}ms "
          f"({1000 / dt / 1e6:.2f}M/s), over_limit={over}")


if __name__ == "__main__":
    main()

"""Example: start one daemon, check a rate limit, shut down.

The analog of the reference's examples_test.go flow: spawn → client →
single TOKEN_BUCKET request → assert UNDER_LIMIT.
Run: python examples/single_daemon.py
"""
from gubernator_tpu.client import Client
from gubernator_tpu.config import DaemonConfig
from gubernator_tpu.daemon import spawn_daemon
from gubernator_tpu.netutil import free_port
from gubernator_tpu.types import RateLimitRequest, Status


def main() -> None:
    d = spawn_daemon(DaemonConfig(
        grpc_listen_address=f"127.0.0.1:{free_port()}",
        http_listen_address=f"127.0.0.1:{free_port()}",
        cache_size=1 << 12))
    try:
        with Client(d.advertise_address) as client:
            resp = client.check(RateLimitRequest(
                name="requests_per_sec", unique_key="account:1234",
                hits=1, limit=10, duration=1_000))
            assert resp.status == Status.UNDER_LIMIT
            print(f"status={resp.status.name} remaining={resp.remaining} "
                  f"limit={resp.limit}")
    finally:
        d.close()


if __name__ == "__main__":
    main()

"""Example: GLOBAL rate limits on the replicated hot-set psum tier.

The reference implements Behavior=GLOBAL with a hit queue + owner
broadcasts over gRPC (global.go).  On a pod, this framework replaces
that whole subsystem with a replicated table: every chip answers
GLOBAL checks from its own replica, and ONE ``lax.psum`` per sync tick
folds all replicas' consumption — traffic per tick is O(hot-set size),
independent of request rate.

Run: python examples/global_hotset.py
(set JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4
 to simulate a 4-chip pod on CPU)
"""
import time

from gubernator_tpu.config import BehaviorConfig, Config
from gubernator_tpu.instance import V1Instance
from gubernator_tpu.types import Behavior, RateLimitRequest


def main() -> None:
    inst = V1Instance(Config(
        cache_size=1 << 16,
        hot_set_capacity=1024,       # replicated GLOBAL tier size
        hot_promote_threshold=16,    # hits before a key goes hot
        behaviors=BehaviorConfig(global_sync_wait_ms=100)))
    now = int(time.time() * 1000)

    def wave(n, t):
        reqs = [RateLimitRequest(name="login", unique_key="tenant-42",
                                 hits=1, limit=100_000, duration=60_000,
                                 behavior=Behavior.GLOBAL)
                for _ in range(n)]
        return inst.get_rate_limits(reqs, now_ms=t)

    wave(32, now)  # crosses the promotion threshold
    hs = inst._hotset
    print(f"hot keys pinned: {len(hs.slots) if hs else 0}")

    t0 = time.perf_counter()
    rs = []
    for w in range(4):  # MAX_BATCH_SIZE is 1000, like the reference
        rs.extend(wave(1000, now + 1 + w))
    dt = time.perf_counter() - t0
    spread = {r.remaining for r in rs}
    print(f"4000 GLOBAL decisions in {dt * 1e3:.1f}ms "
          f"(replica-local, no queues); per-replica remaining span "
          f"[{min(spread)}, {max(spread)}] before the fold")

    hs.sync()  # one psum — the entire reconcile step
    rs = wave(1, now + 10)
    print(f"after one psum fold, merged remaining: {rs[0].remaining}")
    inst.close()


if __name__ == "__main__":
    main()

"""Example: serve from the Mosaic kernel (step_impl=pallas).

The pallas serving mode trades on-device auto-grow for a
lowering-independent step: the hand-scheduled kernel owns its table
scatters, so its cost does not depend on how the XLA backend of the
day lowers a 2^24-row scatter.  Use it when the XLA mode hits a
large-CAP lowering pathology (see `tools/cap_ab.py`), and size the
table up front — full 8-slot buckets turn NEW keys into table_full
errors, watched by the `gubernator_pallas_bucket_saturation` gauge.
Run: python examples/pallas_serving.py   (CPU runs the kernel in
interpret mode — correct but slow; the mode targets real TPUs.)
"""
import os
import time

from gubernator_tpu.config import Config
from gubernator_tpu.instance import V1Instance
from gubernator_tpu.types import RateLimitRequest


def main() -> None:
    # env beats Config in step_impl resolution — an exported
    # GUBER_STEP_IMPL would silently demo the wrong engine.  POP, not
    # set: this also runs via runpy inside the test process, where a
    # lingering export would flip the engine under every later test.
    os.environ.pop("GUBER_STEP_IMPL", None)
    # sizing rule (example.conf): cache_size >= 2.5x peak live keys
    inst = V1Instance(Config(cache_size=1 << 14, step_impl="pallas",
                             sweep_interval_ms=0))
    try:
        now_ms = int(time.time() * 1000)
        reqs = [RateLimitRequest(name="api", unique_key=f"user:{i}",
                                 hits=1, limit=100, duration=60_000)
                for i in range(512)]
        inst.get_rate_limits(reqs, now_ms=now_ms)  # compile + insert
        t0 = time.perf_counter()
        resps = inst.get_rate_limits(reqs, now_ms=now_ms + 10)
        dt = time.perf_counter() - t0
        under = sum(1 for r in resps if int(r.status) == 0)
        full, total = inst.engine.bucket_saturation()
        print(f"512 decisions in {dt * 1e3:.1f}ms over the kernel; "
              f"under_limit={under}, "
              f"bucket saturation {full}/{total} full")
    finally:
        inst.close()


if __name__ == "__main__":
    main()

"""Offline Mosaic lowering check — no TPU, no remote compile.

Cross-platform AOT lowering (``jit(f).trace(args).lower(
lowering_platforms=("tpu",))``) runs the full Pallas→Mosaic MLIR
pipeline client-side on the CPU backend and surfaces every lowering
error in seconds.  This is how the three on-chip-only kernel failures
of 2026-08-01 (block-shape rule, rank-1 reduction proxies emitting
64-bit converts, float cumsum) were fixed without burning flaky-tunnel
compile windows: each on-chip attempt costs a ~220 s remote compile
plus wedge risk, the offline check costs ~5 s.

Usage: python tools/lower_check.py   (exit 0 = kernel lowers)
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)  # the engine's contract

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def main() -> int:
    from gubernator_tpu.core.batch import RequestBatch
    from gubernator_tpu.core.step import decide_batch, decide_batch_donated
    from gubernator_tpu.core.table import init_table
    from gubernator_tpu.ops.pallas_step import (decide_batch_pallas,
                                                init_pallas_table)

    i64 = jnp.int64
    n = 512
    # uint64 like every real caller (bench._keyhash / the engines):
    # int64 keys would promote int64>>uint64 to float64 in _probe_slots
    keys = np.arange(1, n + 1, dtype=np.uint64) * np.uint64(2654435761)
    b = RequestBatch(
        key=jnp.asarray(keys), hits=jnp.ones(n, i64),
        limit=jnp.full(n, 100, i64), duration=jnp.full(n, 10_000, i64),
        eff_ms=jnp.full(n, 10_000, i64), greg_end=jnp.zeros(n, i64),
        behavior=jnp.zeros(n, jnp.int32), algorithm=jnp.zeros(n, jnp.int32),
        burst=jnp.full(n, 100, i64), valid=jnp.ones(n, bool))
    now = jnp.asarray(1_760_000_000_000, i64)
    ksplit = int(os.environ.get("GUBER_KSPLIT", "0"))
    cases = [
        ("pallas_step", decide_batch_pallas, init_pallas_table(1 << 12)),
        ("xla_step", decide_batch, init_table(1 << 12)),
        ("xla_step_donated", decide_batch_donated, init_table(1 << 12)),
    ]
    if ksplit:
        # the K-split rewrite only activates at CAP > 2^ksplit — lower
        # a genuinely split table (CAP 2^22 at the default window 21)
        cases = [(f"xla_step_donated_ksplit{ksplit}_cap22",
                  decide_batch_donated, init_table(1 << 22))]
    failures = 0
    for name, fn, state in cases:
        try:
            # fn is already jitted (with donate_argnums where relevant)
            # — re-wrapping in jax.jit would drop the donation and lower
            # a copy-mode duplicate instead of the aliased program
            fn.trace(state, b, now).lower(lowering_platforms=("tpu",))
            print(f"{name}: lowers for TPU")
        except Exception as e:  # noqa: BLE001
            failures += 1
            print(f"{name}: LOWERING FAILED: {str(e)[:400]}")
    if not ksplit:
        # the pallas SERVING engine's sharded step (kernel under
        # shard_map + packed wire layout) is its own lowering surface
        try:
            from gubernator_tpu.ops.pallas_step import WORDS
            from gubernator_tpu.parallel import make_mesh
            from gubernator_tpu.parallel.pallas_engine import (
                make_pallas_step_packed)

            mesh = make_mesh(n=1)
            step = make_pallas_step_packed(mesh)
            rows = jnp.zeros((1 << 12, WORDS), jnp.int32)
            a64 = jnp.zeros((8, n), jnp.int64)
            a32 = jnp.zeros((3, n), jnp.int32)
            step.trace(rows, a64, a32, now).lower(
                lowering_platforms=("tpu",))
            print("pallas_engine_step: lowers for TPU")
        except Exception as e:  # noqa: BLE001
            failures += 1
            print(f"pallas_engine_step: LOWERING FAILED: {str(e)[:400]}")
    if not ksplit:
        # cover the K-split serving fallback too (fresh process: the
        # constant is read at core.step import)
        import subprocess

        r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                           env=dict(os.environ, GUBER_KSPLIT="21"))
        failures += 1 if r.returncode else 0
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())

"""Diagnose the on-chip Pallas/Mosaic compile failure (round 5 live window).

The battery's tier-3 probe died server-side (`HTTP 500:
tpu_compile_helper subprocess exit code 1`) with the Mosaic diagnostic
truncated by the checkpoint writer.  This probe answers, in order, with
FULL untruncated error text written to /tmp/pallas_probe.json:

  1. toy          — a trivial Pallas add kernel: can axon compile ANY
                    Mosaic program at all?  (If this 500s, tier 3 is
                    environmentally blocked, not a kernel bug.)
  2. kernel_small — the real decision kernel at a TINY shape
                    (CAP 2^12 table): does the failure depend on our
                    kernel, independent of size?
  3. fused_small  — the fused serving program (ISSUE 8: kernel +
                    device tap in ONE launch) at a small shape: if 2
                    passes and this fails, the fusion wrapper broke,
                    not the kernel.
  4. kernel_big   — the real kernel at the battery's failing shape
                    (CAP 2^22 → 2^23-row bucket table) IF 1+2 passed:
                    is it a size/scratch limit?

So a regression bisects: environment (toy) vs kernel (kernel_small)
vs fusion (fused_small) vs table size (kernel_big).  ``--smoke`` runs
stages 1-3 at tiny shapes — the tier-1 CI invocation
(tests/test_pallas_probe.py), CPU-interpret friendly.

Single-client rule: run ONLY when no other jax process holds the relay.

    timeout 1800 python tools/pallas_probe.py

Results land at /tmp/pallas_probe.<pid>.json (PID-suffixed so parallel
probes can't clobber each other); GUBER_PALLAS_PROBE_OUT overrides —
driving batteries set it and read the same path back.
"""
import json
import os
import sys
import time
import traceback

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.abspath(os.path.join(_HERE, ".."))
sys.path.insert(0, _REPO)
import _jax_cache

_jax_cache.setup()

#: PID-suffixed by default (as bench.py's section files are): two
#: probes on one host must not clobber — or cross-salvage — each
#: other's checkpoints.  Drivers that consume the file (e.g.
#: tools/tpu_followup_r5b.py) pass an explicit path through
#: GUBER_PALLAS_PROBE_OUT.
OUT = os.environ.get("GUBER_PALLAS_PROBE_OUT",
                     f"/tmp/pallas_probe.{os.getpid()}.json")
res: dict = {"started": time.strftime("%Y-%m-%d %H:%M:%S")}


def save():
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(res, f, indent=1)
    os.replace(tmp, OUT)


def attempt(name, fn):
    t = time.time()
    try:
        out = fn()
        res[name] = {"ok": True, "seconds": round(time.time() - t, 1),
                     "out": out}
    except Exception as e:  # noqa: BLE001 — full diagnostic capture is the point
        res[name] = {"ok": False, "seconds": round(time.time() - t, 1),
                     "error_type": type(e).__name__,
                     "error": str(e),
                     "traceback": traceback.format_exc()[-4000:]}
    save()
    print(f"[pallas_probe] {name}: ok={res[name]['ok']} "
          f"({res[name]['seconds']}s)")
    return res[name]["ok"]


def toy():
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def k(x_ref, y_ref, o_ref):
        o_ref[...] = x_ref[...] + y_ref[...]

    x = jnp.arange(8 * 128, dtype=jnp.int32).reshape(8, 128)
    # off-TPU there is no Mosaic compiler — the interpreter is the
    # only executable path (the CI smoke exercises exactly that)
    out = pl.pallas_call(
        k, out_shape=jax.ShapeDtypeStruct((8, 128), jnp.int32),
        interpret=jax.default_backend() != "tpu")(x, x)
    return {"sum": int(out.sum()), "backend": jax.default_backend()}


def _kernel_at(log2cap, B=4096, reps=16):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench import _keyhash as keyhash
    from gubernator_tpu.core.batch import RequestBatch
    from gubernator_tpu.ops.pallas_step import (
        decide_batch_pallas, init_pallas_table)

    i64 = jnp.int64
    rng = np.random.default_rng(5)
    cap = 1 << log2cap
    n_keys = max(cap // 2, 1)
    pt = init_pallas_table(cap * 2)  # bucket layout, load /2 (as cap_ab)
    keys = keyhash((rng.zipf(1.1, size=B) % n_keys).astype(np.uint64))
    n = keys.shape[0]
    batch = RequestBatch(
        key=jnp.asarray(keys), hits=jnp.ones(n, i64),
        limit=jnp.full(n, 100, i64), duration=jnp.full(n, 10_000, i64),
        eff_ms=jnp.full(n, 10_000, i64), greg_end=jnp.zeros(n, i64),
        behavior=jnp.zeros(n, jnp.int32),
        algorithm=jnp.asarray(rng.integers(0, 2, size=n)
                              .astype(np.int32)),
        burst=jnp.full(n, 100, i64), valid=jnp.ones(n, bool))
    now0 = jnp.asarray(1_760_000_000_000, i64)
    interp = jax.default_backend() != "tpu"  # Mosaic is TPU-only
    t = time.time()
    pt, out = decide_batch_pallas(pt, batch, now0, interpret=interp)
    jax.block_until_ready(out.status)
    compile_s = round(time.time() - t, 1)
    t = time.time()
    for _ in range(reps):
        pt, out = decide_batch_pallas(pt, batch, now0, interpret=interp)
    jax.block_until_ready(out.status)
    dt = time.time() - t
    err = float(np.asarray(out.err).mean())
    return {"compile_s": compile_s,
            "ms_per_step": round(dt / reps * 1e3, 3),
            "decisions_per_s": round(reps * B / dt),
            "err_fraction": round(err, 4),
            "backend": jax.default_backend()}


def _fused_at(log2cap, B=512, reps=4):
    """The fused serving program (ISSUE 8) at a small shape: one
    launch = decide + device tap (+ mesh scatter when bound).  Bisects
    fused-program regressions from raw-kernel regressions: if
    kernel_small passes and this fails, the fusion wrapper (shard_map
    specs, tap stack, counters) broke, not the Mosaic kernel."""
    import jax
    import numpy as np

    from bench import _keyhash as keyhash
    from gubernator_tpu.parallel import make_mesh
    from gubernator_tpu.parallel.pallas_engine import PallasServingEngine

    rng = np.random.default_rng(5)
    taps = []
    eng = PallasServingEngine(make_mesh(n=1),
                              capacity_per_shard=1 << log2cap,
                              batch_per_shard=B)
    eng.tap_sink = taps.append
    from gubernator_tpu.core.batch import pack_columns

    keys = keyhash((rng.zipf(1.1, size=B) % (1 << (log2cap - 1)))
                   .astype(np.uint64))
    batch, _ = pack_columns(
        keys, np.ones(B, np.int64), np.full(B, 100, np.int64),
        np.full(B, 60_000, np.int64), np.zeros(B, np.int32),
        np.zeros(B, np.int32), np.full(B, 100, np.int64),
        1_760_000_000_000)
    t = time.time()
    eng.check_packed(batch, keys, 1_760_000_000_000)
    compile_s = round(time.time() - t, 1)
    t = time.time()
    for r in range(reps):
        eng.check_packed(batch, keys, 1_760_000_000_000 + 1 + r)
    dt = time.time() - t
    tap = np.asarray(taps[-1])
    served = int((tap[3] != 0).sum())
    if not served:
        raise RuntimeError("fused tap emitted no served rows")
    return {"compile_s": compile_s,
            "ms_per_wave": round(dt / reps * 1e3, 3),
            "decisions_per_s": round(reps * B / dt),
            "tap_rows_served": served,
            "fused_waves": eng.fused_wave_count,
            "backend": jax.default_backend()}


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes, skip kernel_big — the tier-1 "
                         "CI invocation (CPU interpret)")
    args = ap.parse_args(argv)

    from gubernator_tpu.cmd import maybe_pin_platform

    maybe_pin_platform()
    import jax

    res["backend_probe"] = jax.default_backend()
    res["smoke"] = bool(args.smoke)
    save()
    ok_toy = attempt("toy", toy)
    if args.smoke:
        ok_small = attempt("kernel_small",
                           lambda: _kernel_at(9, B=256, reps=2))
        attempt("fused_small", lambda: _fused_at(9, B=128, reps=2))
    else:
        ok_small = attempt("kernel_small", lambda: _kernel_at(12))
        attempt("fused_small", lambda: _fused_at(12, B=512, reps=4))
        if ok_toy and ok_small:
            attempt("kernel_big", lambda: _kernel_at(22))
    res["finished"] = time.strftime("%Y-%m-%d %H:%M:%S")
    save()
    print(json.dumps(res, indent=1)[:2000])
    return 0


if __name__ == "__main__":
    sys.exit(main())

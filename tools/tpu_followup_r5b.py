"""Round-5b follow-up battery: ONLY the questions the first live window
left open (the full tpu_session battery already banked its record in
artifacts/tpu_session_r5_attempt1.json — re-running it would spend the
next window re-measuring answered questions).

Stage order, each in its own subprocess (single-client tunnel):

  1. probe        — trivial op (is the tunnel really back?)
  2. capab_p8_25  — GUBER_PROBES=8 at CAP 2^25: the probe-window
                    hypothesis.  16-probe shapes collapse at CAP >=
                    2^25 (bench headline 0.35M dec/s) while 8-probe
                    shapes fly clear up to 2^27 (cfg5 564M); K-split
                    is ruled out at 2^25 (populate could not finish in
                    21 min).  This is the missing single-variable A/B.
  3. pallas_probe — toy Mosaic kernel vs the real kernel (tiny, then
                    big): is the server-side `tpu_compile_helper exit 1`
                    environmental or kernel-specific?
  4. bench        — IF stage 2 verdicts FIXED: the driver-shaped bench
                    at the 8-probe flagship (GUBER_PROBES=8 override,
                    zero-loss audited by extra.populate_errs) — the
                    north-star headline row.

Results checkpoint to /tmp/tpu_followup_r5b.json and mirror into
artifacts/tpu_followup_r5b.json after every stage.

    timeout 10800 python tools/tpu_followup_r5b.py
"""
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.abspath(os.path.join(_HERE, ".."))

OUT = "/tmp/tpu_followup_r5b.json"
MIRROR = os.path.join(_REPO, "artifacts", "tpu_followup_r5b.json")
results: dict = {"started": time.strftime("%Y-%m-%d %H:%M:%S")}
_child = None


def record(key, val):
    results[key] = val
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(results, f, indent=1)
    os.replace(tmp, OUT)
    try:
        shutil.copyfile(OUT, MIRROR)
    except OSError:
        pass
    print(f"[r5b] {key}: {json.dumps(val)[:300]}", flush=True)


def relay_alive(port=8103) -> bool:
    s = socket.socket()
    s.settimeout(3)
    try:
        s.connect(("127.0.0.1", port))
        return True
    except OSError:
        return False
    finally:
        s.close()


def _sigterm(_sig, _frm):
    if _child is not None and _child.poll() is None:
        try:
            os.killpg(_child.pid, signal.SIGKILL)
        except OSError:
            pass
    sys.exit(143)


def run_stage(key, argv, timeout, env_extra=None):
    """One stage, own process group; returns (ok, stdout_tail)."""
    global _child
    env = dict(os.environ, **(env_extra or {}))
    t0 = time.time()
    try:
        _child = subprocess.Popen(argv, env=env, cwd=_REPO,
                                  stdout=subprocess.PIPE,
                                  stderr=subprocess.STDOUT,
                                  start_new_session=True)
        out, _ = _child.communicate(timeout=timeout)
        rc = _child.returncode
    except subprocess.TimeoutExpired:
        try:
            os.killpg(_child.pid, signal.SIGKILL)
        except OSError:
            pass
        out, _ = _child.communicate()
        rc = -9
    finally:
        _child = None
    text = (out or b"").decode(errors="replace")
    record(key + "__stage", {"rc": rc,
                             "seconds": round(time.time() - t0, 1)})
    # keep enough tail for bench.py's single-line final JSON (~2.6 KB
    # in the round-5 record and growing) — a 2 KB cut truncated the
    # line mid-object and the '{'-prefix scan found nothing
    return rc == 0, text[-65536:]


def merge(key, path, t_after):
    try:
        if os.path.getmtime(path) < t_after:
            record(key, {"error": "stale checkpoint"})
            return
        with open(path) as f:
            record(key, json.load(f))
    except (OSError, ValueError) as e:
        record(key, {"error": f"no checkpoint: {e}"})


def main() -> int:
    signal.signal(signal.SIGTERM, _sigterm)
    if not relay_alive():
        record("abort", "relay dead at start")
        return 1

    ok, out = run_stage("probe", [
        sys.executable, "-c",
        "import jax, json; print(json.dumps({'backend': "
        "jax.default_backend(), 'sum': int(jax.numpy.arange(8).sum())}))"],
        timeout=150)
    if not ok or '"tpu"' not in out:
        record("abort", f"probe failed: {out[-200:]}")
        return 1

    # 2. the probe-window A/B at the flagship CAP
    t = time.time()
    run_stage("capab_p8_25",
              [sys.executable, os.path.join(_HERE, "cap_ab.py"), "25"],
              timeout=1500, env_extra={"GUBER_PROBES": "8"})
    merge("capab_p8_25", "/tmp/cap_ab.json", t)
    if not relay_alive():
        record("abort", "relay died during capab_p8_25")
        return 1

    # 3. the Mosaic compile diagnosis.  The probe PID-suffixes its
    # checkpoint by default; pass an explicit path through so we read
    # back exactly the file THIS child wrote (a fixed /tmp name could
    # be another probe's stale checkpoint — ADVICE r5)
    t = time.time()
    probe_out = f"/tmp/pallas_probe.{os.getpid()}.json"
    run_stage("pallas_probe",
              [sys.executable, os.path.join(_HERE, "pallas_probe.py")],
              timeout=1800,
              env_extra={"GUBER_PALLAS_PROBE_OUT": probe_out})
    merge("pallas_probe", probe_out, t)
    if not relay_alive():
        record("abort", "relay died during pallas_probe")
        return 1

    # 4. the headline: only if the 8-probe shape verifiably fixed the
    # pathology (re-measuring a known-0.35M shape wastes the window)
    verdict = (results.get("capab_p8_25") or {}).get("verdict", "")
    if verdict in ("FIXED", "improved"):
        partial = "/tmp/guber_bench_partial_r5b.json"
        t = time.time()
        # bench.py's round-5 defaults ARE the fixed flagship shape
        # (CAP 2^26, 8-probe, offline-audited zero-loss) — no overrides
        ok, out = run_stage(
            "bench", [sys.executable, os.path.join(_REPO, "bench.py")],
            timeout=7800,
            # device-side serving children must outwait a cold wave
            # compile (250-305 s) — VERDICT r5 item 6 / r5b stage 4
            env_extra={"GUBER_BENCH_PARTIAL": partial,
                       "GUBER_RESULT_TIMEOUT_S": "900"})
        lines = [ln for ln in out.strip().splitlines()
                 if ln.startswith("{")]
        if ok and lines:
            try:
                record("bench", json.loads(lines[-1]))
            except ValueError:
                merge("bench_partial", partial, t)
        else:
            merge("bench_partial", partial, t)
    else:
        record("bench", {"skipped": f"capab_p8_25 verdict was "
                                    f"{verdict!r}, not FIXED/improved"})

    # 5. opportunistic: the device-batch sweep (VERDICT r1 item 1 —
    # no on-chip point beyond B=65536 exists).  Two cold compiles; only
    # attempted while the relay is still healthy after the bench.
    if relay_alive() and "bench" in results \
            and "skipped" not in results["bench"]:
        ok, out = run_stage("b_sweep",
                            [sys.executable,
                             os.path.join(_HERE, "b_sweep.py"), "131072"],
                            timeout=2400)
        lines = [ln for ln in out.strip().splitlines()
                 if ln.startswith(("[", "{"))]
        if lines:
            try:
                record("b_sweep", json.loads(lines[-1]))
            except ValueError:
                record("b_sweep", {"error": "unparseable",
                                   "raw": lines[-1][:500]})
    record("finished", time.strftime("%Y-%m-%d %H:%M:%S"))
    return 0


if __name__ == "__main__":
    sys.exit(main())

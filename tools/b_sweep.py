"""Headline-only batch-size sweep on a live chip.

Measures the north-star sustained rate (10M resident keys, CAP 2^24)
at one or more device batch sizes WITHOUT the full bench's secondary
configs — each new B is two cold compiles (copy + donate) over the
tunnel, so this isolates the sweep VERDICT r1 item 1 asked for.

    timeout 3600 python tools/b_sweep.py 131072 [262144 ...]

Checkpoints one JSON object per B (atomic, pid-isolated so concurrent
sweeps can't clobber each other) and prints the full list at the end —
copy results that matter into BASELINE.md; /tmp does not survive the
session.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))
import bench  # noqa: E402  (sets the repo-local compile cache)

OUT = f"/tmp/b_sweep.{os.getpid()}.json"


def run_one(B: int) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gubernator_tpu.core.batch import RequestBatch
    from gubernator_tpu.core.step import decide_batch, decide_batch_donated
    from gubernator_tpu.core.table import init_table

    # workload identity comes from bench so the sweep measures EXACTLY
    # the headline's distribution (same constants, same env overrides);
    # the measurement loop mirrors bench.main's measure_mode/populate
    # (kept monolithic there — that file is the driver's entry point)
    N_KEYS, CAP, NOW0 = bench.N_KEYS, bench.CAP, bench.NOW0
    i64 = jnp.int64
    rng = np.random.default_rng(42)
    n_batches = 8
    draws = rng.zipf(bench.ZIPF_A, size=n_batches * B) % N_KEYS
    kb = [jnp.asarray(bench._keyhash(draws[i * B:(i + 1) * B].astype(np.uint64)))
          for i in range(n_batches)]
    const = dict(
        hits=jnp.ones(B, i64), limit=jnp.full(B, bench.LIMIT, i64),
        duration=jnp.full(B, bench.DURATION_MS, i64),
        eff_ms=jnp.full(B, bench.DURATION_MS, i64),
        greg_end=jnp.zeros(B, i64), behavior=jnp.zeros(B, jnp.int32),
        algorithm=jnp.zeros(B, jnp.int32),
        burst=jnp.full(B, bench.LIMIT, i64),
        valid=jnp.ones(B, bool))

    def mk(keys):
        return RequestBatch(key=keys, **const)

    row = {"B": B, "backend": jax.default_backend()}
    for label, fn in (("copy", decide_batch), ("donate", decide_batch_donated)):
        try:
            st = init_table(CAP)
            t0 = time.perf_counter()
            st, out = fn(st, mk(kb[0]), jnp.asarray(NOW0, i64))
            out.status.block_until_ready()
            row[f"{label}_compile_s"] = round(time.perf_counter() - t0, 1)
            ids = np.arange(N_KEYS, dtype=np.uint64)
            for a in range(0, N_KEYS, B):
                chunk = bench.pad_chunk(ids[a:a + B], B)
                st, out = fn(st, mk(jnp.asarray(bench._keyhash(chunk))),
                             jnp.asarray(NOW0, i64))
            out.status.block_until_ready()
            reps = max(8, int(30_000_000 / B))
            t0 = time.perf_counter()
            for r in range(reps):
                st, out = fn(st, mk(kb[r % n_batches]),
                             jnp.asarray(NOW0 + 100 + r, i64))
            out.status.block_until_ready()
            dt = time.perf_counter() - t0
            row[f"{label}_mdps"] = round(reps * B / dt / 1e6, 1)
            row[f"{label}_ms_per_step"] = round(dt / reps * 1e3, 3)
        except Exception as e:  # noqa: BLE001
            row[f"{label}_error"] = str(e)[:200]
        print(f"[b_sweep] {row}", file=sys.stderr, flush=True)
    return row


def main() -> None:
    bs = [int(a) for a in sys.argv[1:]] or [131072]
    rows = []
    for B in bs:
        rows.append(run_one(B))
        # atomic checkpoint (same pattern as bench._write_partial): a
        # timeout-kill mid-write must not cost completed rows
        tmp = OUT + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rows, f, indent=1)
        os.replace(tmp, OUT)
    print(json.dumps(rows))


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Multithreaded soak of ops/_native.cpp under TSan/ASan.

Loads a SANITIZED build of the extension (``make tsan`` / ``make
asan`` put it under build/<san>/) and hammers every exported primitive
from N concurrent threads over shared and per-thread buffers — the
exact concurrency shape the serving path produces (parse on gRPC
handler threads, pack into pool-leased matrices, response build on
caller threads, TLV stamping on the forward path).

Deliberately imports NOTHING from gubernator_tpu: the package import
pulls in jax, whose runtime under a preloaded sanitizer is pure noise.
Request bytes are built with a 30-line proto encoder instead; numpy is
the only dependency.

Self-re-exec: sanitizer runtimes must be loaded before CPython, so the
script re-launches itself with LD_PRELOAD=<libtsan/libasan> (plus the
suppressions file for TSan and detect_leaks=0 for ASan — CPython's
intentional leaks are not our bugs) unless the runtime is already in.

Exit status is the sanitizer's: a detected race/error fails the run
(`halt_on_error=1`).
"""
from __future__ import annotations

import argparse
import importlib.util
import os
import subprocess
import sys
import sysconfig
import threading

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)

_SAN_LIB = {"tsan": "libtsan.so", "asan": "libasan.so"}


def _find_so(san: str) -> str:
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    path = os.path.join(ROOT, "build", san, "gubernator_tpu", "ops",
                        f"_native{suffix}")
    if not os.path.exists(path):
        raise SystemExit(
            f"no sanitized extension at {path} — run `make {san}` "
            f"(or GUBER_NATIVE_SAN={san} setup_native.py build_ext "
            f"--build-lib build/{san})")
    return path


def _reexec_under(san: str) -> None:
    """Re-launch with the sanitizer runtime preloaded (idempotent)."""
    if os.environ.get("_GUBER_SOAK_PRELOADED") == san:
        return
    lib = subprocess.run(
        ["g++", f"-print-file-name={_SAN_LIB[san]}"],
        capture_output=True, text=True).stdout.strip()
    if not lib or not os.path.exists(lib):
        raise SystemExit(f"cannot locate {_SAN_LIB[san]} (need g++ "
                         f"with sanitizer runtimes)")
    env = dict(os.environ)
    env["_GUBER_SOAK_PRELOADED"] = san
    env["LD_PRELOAD"] = lib
    if san == "tsan":
        supp = os.path.join(HERE, "tsan.supp")
        env["TSAN_OPTIONS"] = (f"suppressions={supp} halt_on_error=1 "
                               f"report_signal_unsafe=0 "
                               f"second_deadlock_stack=1")
    else:
        # CPython leaks interned objects by design; arena-allocator
        # "leaks" would drown real extension bugs
        env["ASAN_OPTIONS"] = ("detect_leaks=0 "
                               "allocator_may_return_null=1")
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


def _varint(v: int) -> bytes:
    out = bytearray()
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    return bytes(out)


def _field(num: int, v: int) -> bytes:
    return bytes([num << 3]) + _varint(v)


def _req_tlv(name: bytes, key: bytes, hits: int, limit: int,
             duration: int, created: int = 0) -> bytes:
    payload = (b"\x0a" + _varint(len(name)) + name
               + b"\x12" + _varint(len(key)) + key
               + _field(3, hits) + _field(4, limit) + _field(5, duration))
    if created:
        payload += _field(10, created)
    return b"\x0a" + _varint(len(payload)) + payload


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--san", choices=("tsan", "asan"), required=True)
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--iters", type=int, default=300)
    args = ap.parse_args()
    _reexec_under(args.san)

    import numpy as np  # after re-exec: numpy loads under the runtime

    spec = importlib.util.spec_from_file_location(
        "gubernator_tpu.ops._native", _find_so(args.san))
    native = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(native)

    DAY = 24 * 3_600_000
    NOW = 1_700_000_000_000
    n_req = 48
    data = b"".join(
        _req_tlv(b"soak", f"k{i}".encode(), hits=2, limit=1000,
                 duration=DAY, created=(NOW + i if i % 3 == 0 else 0))
        for i in range(n_req))
    DURATION_MAX = (1 << 63) - 1
    VALUE_MAX = (1 << 62) - 1
    EFF_MAX = 1 << 31
    TD_BOUND = (1 << 62) - 1

    errs: list = []
    barrier = threading.Barrier(args.threads)
    # cold-store contract (tiering.py): the table is NOT internally
    # locked — TierController._mu serializes every access.  The soak
    # mirrors that exactly: a shared store behind ONE lock (the
    # sanitizer proves the external-locking discipline suffices) plus
    # an unshared per-thread store hammered lock-free.
    has_cold = hasattr(native, "cold_new")
    shared_cold = native.cold_new(64) if has_cold else None
    shared_cold_mu = threading.Lock()

    def cold_churn(store, base: int, i: int, np) -> None:
        row = np.arange(8, dtype="<i8") + i
        for j in range(16):
            kh = base + ((i * 16 + j) % 97) + 1
            native.cold_put(store, kh, row.tobytes())
            got = native.cold_get(store, kh)
            assert got is not None and len(got) == 64
            if j % 3 == 0:
                native.cold_pop(store, kh)
        keys = np.arange(base + 1, base + 33, dtype="<u8")
        out = np.zeros(32, np.uint8)
        native.cold_contains(store, keys.tobytes(), out)
        n, kb, rb = native.cold_snapshot(store)
        assert len(kb) == 8 * n and len(rb) == 64 * n
        assert native.cold_len(store) == n

    def worker(t: int) -> None:
        try:
            m = 64
            a64 = np.zeros((8, m), np.int64)
            a32 = np.zeros((3, m), np.int32)
            own_cold = native.cold_new(16) if has_cold else None
            barrier.wait(timeout=60)
            for i in range(args.iters):
                # parse: read-only over the SHARED request bytes
                parsed = native.parse_get_rate_limits(data)
                assert parsed is not None and parsed[0] == n_req
                toff = np.frombuffer(parsed[9], "<u8").astype(np.int64)
                tlen = np.frombuffer(parsed[10], "<u8").astype(np.int64)
                created = np.frombuffer(parsed[11], "<i8")
                # stamp: shared bytes in, fresh bytes out
                fwd = native.stamp_req_tlvs(
                    data, toff, tlen,
                    np.ascontiguousarray(created), NOW + i)
                assert native.count_req_items(fwd) == n_req
                # fused pack into THIS thread's leased matrices
                res = native.pack_wire_wave(
                    fwd, NOW + i, a64, a32, m, DURATION_MAX, VALUE_MAX,
                    EFF_MAX, TD_BOUND)
                assert res is not None and res[0] == n_req
                # response build out of shared-shape columns
                st = np.zeros(n_req, np.int32)
                lim = np.full(n_req, 1000, np.int64)
                rem = np.full(n_req, 998, np.int64)
                rst = np.full(n_req, NOW + DAY, np.int64)
                out = native.build_rate_limit_resps(st, lim, rem, rst,
                                                    None)
                sp = native.split_resp_items(out)
                assert sp is not None and sp[0] == n_req
                # hashing over shared string lists
                buf, n = native.fnv1a64_pair_batch(
                    ["soak"] * 8, [f"k{j}" for j in range(8)])
                assert n == 8
                # cold-store churn: per-thread store lock-free, the
                # shared store under the tier's external-lock contract
                if has_cold:
                    cold_churn(own_cold, t * 1_000_000, i, np)
                    with shared_cold_mu:
                        cold_churn(shared_cold, 77_000_000, i, np)
        except Exception as e:  # noqa: BLE001 - reported below
            errs.append(f"thread {t}: {e!r}")

    threads = [threading.Thread(target=worker, args=(t,),
                                name=f"native-soak-{t}")
               for t in range(args.threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=600)
    if any(th.is_alive() for th in threads):
        print("FAIL: soak threads stuck", file=sys.stderr)
        return 1
    if errs:
        print("FAIL:", *errs[:5], sep="\n  ", file=sys.stderr)
        return 1
    print(f"native soak clean under {args.san}: {args.threads} threads "
          f"x {args.iters} iters")
    return 0


if __name__ == "__main__":
    sys.exit(main())

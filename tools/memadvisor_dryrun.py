"""Memory-advisor dryrun (ISSUE 13): does the advised split win?

The acceptance oracle for ``memledger.advise()``: a seeded
shifting-Zipf workload (the hot band drifts across the key domain
every phase, so yesterday's residents keep getting demoted) runs
against the DEFAULT static split of the device-row budget — half to
the hot table, half to the mesh-GLOBAL tier — while only a handful of
GLOBAL keys actually live in the mesh tier.  Phase 1 measures: the
ledger's demand vector (Space-Saving rank distribution for the hot
table, occupancy + fold rate for the mesh tier) feeds the
water-filling advisor, which recommends moving most of the mesh
tier's idle rows to the hot table.  Phase 2 validates: the SAME seeded
workload replayed against the default split and against the advised
split (recommendation applied as static config — there is no live
repartition), comparing hot-tier hit rate ``1 - cold_served/rows``.
The advised split must win STRICTLY, without spending more device
bytes than the default split (both asserted from the ledger itself).

Writes ``MEMADVISOR_r01.json``: the dryrun-verdict keys
(``n_devices`` / ``rc`` / ``ok`` / ``skipped`` / ``tail``) plus a
``14_memadvisor`` bench-row block carrying the demand vector, the
recommendation, and both measured splits.

Usage::

    python tools/memadvisor_dryrun.py [--keys 6000] \
        [--json MEMADVISOR_r01.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NOW0 = 1_760_000_000_000
ZIPF_A = 1.1
#: device-row budget the two splits share: default gives half to the
#: hot table and half to the mesh-GLOBAL tier (the static-knob status
#: quo this PR's ROADMAP item wants replaced)
BUDGET_ROWS = 2048
DEFAULT_SPLIT = {"hot_table": 1024, "mesh_global": 1024}
N_GLOBAL_KEYS = 16


def _force_cpu():
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001
        pass
    return jax


def _workload(nkeys: int, phases: int, batch: int):
    """Deterministic shifting-Zipf batches: one full permutation pass
    (every key exists → the capped table MUST overflow into the cold
    tier), then ``phases`` hot bands that drift by nkeys//phases each
    phase — the demand a static split can only chase with spare rows."""
    import numpy as np

    rng = np.random.default_rng(1313)
    stream = [rng.permutation(nkeys)]
    shift = nkeys // max(phases, 1)
    for p in range(phases):
        draws = (rng.zipf(ZIPF_A, size=2 * batch) - 1 + p * shift) % nkeys
        stream.append(draws)
    flat = np.concatenate(stream)
    pad = (-len(flat)) % batch
    if pad:
        flat = np.concatenate([flat, flat[:pad]])
    return [flat[i:i + batch] for i in range(0, len(flat), batch)]


def _run_split(split: dict, batches, collect_advice: bool):
    """Serve the whole workload against one static split; returns the
    measured row (hit rate, ledger bytes) and — when asked — the
    demand-fed recommendation from this run's ledger."""
    from gubernator_tpu.config import Config
    from gubernator_tpu.instance import V1Instance
    from gubernator_tpu.parallel import make_mesh
    from gubernator_tpu.types import Behavior, RateLimitRequest

    greq = [RateLimitRequest(name="adv", unique_key=f"g{i}", hits=1,
                             limit=10 ** 9, duration=600_000,
                             behavior=Behavior.GLOBAL)
            for i in range(N_GLOBAL_KEYS)]
    hot_rows = int(split["hot_table"])
    prev_cap = os.environ.get("GUBER_MESH_GLOBAL_CAP")
    os.environ["GUBER_MESH_GLOBAL_CAP"] = str(int(split["mesh_global"]))
    try:
        inst = V1Instance(Config(cache_size=hot_rows,
                                 cache_autogrow_max=hot_rows,
                                 tier_cold=True,
                                 tier_promote_threshold=2,
                                 hot_set_capacity=0,
                                 sweep_interval_ms=0,
                                 global_mode="mesh"),
                          mesh=make_mesh(n=1))
    finally:
        if prev_cap is None:
            os.environ.pop("GUBER_MESH_GLOBAL_CAP", None)
        else:
            os.environ["GUBER_MESH_GLOBAL_CAP"] = prev_cap
    local_rows = 0
    try:
        now = NOW0
        for keys in batches:
            reqs = [RateLimitRequest(
                name="adv", unique_key=f"k{int(k)}", hits=1,
                limit=10 ** 9, duration=86_400_000) for k in keys]
            local_rows += len(reqs)
            now += 1
            inst.get_rate_limits(reqs + greq, now_ms=now)
        ana = inst.analytics
        if ana is not None:
            ana.flush(timeout=5.0)
        st = inst._tier.stats()
        snap = inst.memledger.snapshot()
        row = {
            "split": dict(split),
            "rows_sent": local_rows,
            "cold_served": st["cold_served"],
            "cold_keys": st["cold_keys"],
            "promotions": st["promotions"],
            "hot_hit_rate": round(1 - st["cold_served"]
                                  / max(local_rows, 1), 4),
            "device_bytes": snap["device_bytes"],
            "mesh_occupied": snap["consumers"].get(
                "mesh_global", {}).get("occupied_rows", 0),
        }
        advice = None
        if collect_advice:
            advice = inst.memledger.advise()
        return row, advice
    finally:
        inst.close()


def run(nkeys: int = 6000, phases: int = 4, batch: int = 984) -> dict:
    # batch + N_GLOBAL_KEYS must stay within the 1000-row wire cap
    jax = _force_cpu()
    assert jax.default_backend() == "cpu", jax.default_backend()
    batches = _workload(nkeys, phases, batch)

    # phase 1: measure demand under the default split, take the advice
    default_row, advice = _run_split(DEFAULT_SPLIT, batches,
                                     collect_advice=True)
    assert advice is not None and advice["advised"], advice
    assert advice["total_rows"] == BUDGET_ROWS, advice
    advised_split = {
        "hot_table": advice["advised_pow2"]["hot_table"],
        "mesh_global": advice["advised_pow2"]["mesh_global"]}

    # phase 2: replay the identical workload against the advised split
    advised_row, _ = _run_split(advised_split, batches,
                                collect_advice=False)

    hit_gain = advised_row["hot_hit_rate"] - default_row["hot_hit_rate"]
    strictly_better = advised_row["hot_hit_rate"] \
        > default_row["hot_hit_rate"]
    # the recommendation must not buy its hit rate with MORE silicon:
    # the mesh tier's rows cost replica + two accumulators each, so
    # trading 960 of them for 1024 hot rows nets fewer device bytes
    no_more_bytes = (advised_row["device_bytes"]
                     <= default_row["device_bytes"])
    # trim the rank vector for the artifact; the full curve fed advise()
    demand = {k: (dict(v, ranks=v["ranks"][:32],
                       ranks_len=len(v["ranks"])) if "ranks" in v
                  else v)
              for k, v in advice["demand"].items()}
    return {
        "key_domain": nkeys,
        "phases": phases,
        "batch": batch,
        "budget_rows": BUDGET_ROWS,
        "demand": demand,
        "recommendation": {k: advice[k] for k in
                           ("total_rows", "floor_rows", "current",
                            "advised", "advised_pow2")},
        "default": default_row,
        "advised": advised_row,
        "hit_rate_gain": round(hit_gain, 4),
        "advised_strictly_better": bool(strictly_better),
        "advised_no_more_device_bytes": bool(no_more_bytes),
        "ok": bool(strictly_better and no_more_bytes),
        "context": ("CPU mesh (n=1): the A/B compares static splits of "
                    "the same device-row budget on identical seeded "
                    "shifting-Zipf traffic; the advisor only ever "
                    "recommends — nothing repartitions live"),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate memledger.advise() on shifting-Zipf "
                    "traffic (advised vs default static split)")
    ap.add_argument("--keys", type=int, default=6000)
    ap.add_argument("--phases", type=int, default=4)
    ap.add_argument("--json", default=os.path.join(
        REPO, "MEMADVISOR_r01.json"))
    args = ap.parse_args(argv)
    try:
        block = run(nkeys=args.keys, phases=args.phases)
        ok = block["ok"]
        tail = (f"memadvisor_dryrun ok={ok}: advised "
                f"{block['recommendation']['advised_pow2']} vs default "
                f"{block['default']['split']} -> hot hit rate "
                f"{block['advised']['hot_hit_rate']} vs "
                f"{block['default']['hot_hit_rate']} "
                f"(gain {block['hit_rate_gain']}), device bytes "
                f"{block['advised']['device_bytes']} vs "
                f"{block['default']['device_bytes']}\n")
        verdict = {"n_devices": 1, "rc": 0 if ok else 1, "ok": ok,
                   "skipped": False, "tail": tail,
                   "14_memadvisor": block}
    except Exception as e:  # noqa: BLE001 - verdict artifact, not a trace
        verdict = {"n_devices": 1, "rc": 1, "ok": False,
                   "skipped": False,
                   "tail": f"memadvisor_dryrun failed: {e!r}\n"}
    doc = json.dumps(verdict, indent=2)
    print(doc)
    with open(args.json, "w", encoding="utf-8") as f:
        f.write(doc + "\n")
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

"""3-daemon fleet conservation smoke (ISSUE 19, `make fleet-audit`).

Boots a 3-daemon cluster, drives GLOBAL traffic from every daemon
(owned + remote-owned keys so the flush lane actually crosses the
wire), lets the flush discipline settle, then fetches each daemon's
OWN ``GET /debug/audit`` vector over HTTP — no test-harness walking —
and folds them with fleet.fold_audits: at steady state the fleet
drift must be exactly zero and the ring consistent.

    make fleet-audit        # wired into `make check`
    JAX_PLATFORMS=cpu python tools/fleet_audit_smoke.py

Exit 0 on a conserved, ring-consistent fleet; 1 otherwise (the folded
document is printed either way for diagnosis).
"""
from __future__ import annotations

import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from gubernator_tpu import (Behavior, RateLimitRequest, fleet,  # noqa: E402
                            cluster as cluster_mod)
from gubernator_tpu.config import BehaviorConfig  # noqa: E402

DAY = 24 * 3_600_000
SETTLE_S = 15.0


def main() -> int:
    c = cluster_mod.start(3, behaviors=BehaviorConfig(
        global_sync_wait_ms=40, global_broadcast_interval_ms=40,
        global_timeout_ms=5000), cache_size=1 << 12)
    try:
        now = int(time.time() * 1000)
        for i in range(3):
            inst = c.instance_at(i)
            reqs = [RateLimitRequest(
                name="fleet_smoke", unique_key=f"k{j}", hits=1,
                limit=10_000, duration=DAY, behavior=Behavior.GLOBAL)
                for j in range(32)]
            for _ in range(4):
                inst.get_rate_limits(reqs, now_ms=now)
        # settle: poke each daemon's flush loop until every vector
        # drains (bounded — steady state must drain in one window)
        deadline = time.monotonic() + SETTLE_S
        docs = []
        while time.monotonic() < deadline:
            for i in range(3):
                gm = c.instance_at(i).global_manager
                if gm is not None:
                    gm.poke()
            time.sleep(0.2)
            docs = [fetch_audit(c.http_address(i)) for i in range(3)]
            if all(d["conserved"] for d in docs):
                break
        fold = fleet.fold_audits(docs)
        fold["ring"] = fleet.ring_verdict(docs)
        print(json.dumps(fold, indent=2))
        ok = (fold["conserved"] and fold["ring"]["consistent"]
              and fold["totals"]["injected"] > 0)
        print(f"fleet-audit: drift={fold['drift']} "
              f"injected={fold['totals']['injected']} "
              f"ring={'ok' if fold['ring']['consistent'] else 'DIVERGED'}"
              f" -> {'PASS' if ok else 'FAIL'}")
        return 0 if ok else 1
    finally:
        c.stop()


def fetch_audit(base: str) -> dict:
    with urllib.request.urlopen(base.rstrip("/") + "/debug/audit",
                                timeout=5.0) as f:
        return json.loads(f.read())


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Cluster-wide trace assembly: stitch N daemons' span slices into
per-trace trees and render text waterfalls.

Each daemon's ``/debug/traces`` (OBSERVABILITY.md "Distributed
tracing") serves only its OWN slice of a distributed trace — the
caller's request span and ``peer.forward`` hop live on the caller,
the owner-side handler/wave spans on the owner.  Head sampling is
decided from the trace id itself, so every daemon keeps the same
traces and the slices always join.  This tool takes any mix of live
endpoints (``--url``, repeatable) and on-disk spill files
(``guber_traces_*.jsonl`` from ``GUBER_DEBUG_DUMP_DIR``, positional),
merges the spans (duplicate span ids dedup), and prints one waterfall
per assembled trace — the cross-daemon parent/child chain
(request → hop → owner request → wave → phases) reads as one tree.

Usage:
    python tools/trace_assemble.py --url http://d0:1050 --url http://d1:1050
    python tools/trace_assemble.py /var/dumps/guber_traces_*.jsonl
    python tools/trace_assemble.py --url http://d0:1050 --trace-id <32hex>

Exit status: 0 when at least one trace assembled (or --allow-empty),
1 on fetch/parse failure or when nothing assembled.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from gubernator_tpu.tracing import assemble, render_waterfall  # noqa: E402


def _fetch_spans(url: str, trace_id: str, timeout: float) -> list:
    if "/debug/traces" not in url:
        url = url.rstrip("/") + "/debug/traces"
    if trace_id:
        url += ("&" if "?" in url else "?") + f"trace_id={trace_id}"
    with urllib.request.urlopen(url, timeout=timeout) as r:
        body = json.loads(r.read().decode("utf-8"))
    return body.get("spans", [])


def _read_spans(path: str) -> list:
    """One span per JSONL line; ``trace_header`` metadata lines (and
    any event-dump lines that snuck in via a glob) are skipped."""
    spans = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if not isinstance(obj, dict) or "span_id" not in obj:
                continue
            spans.append(obj)
    return spans


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="stitch daemons' /debug/traces slices (and/or "
                    "guber_traces_*.jsonl spills) into waterfalls")
    ap.add_argument("files", nargs="*",
                    help="trace spill JSONL files (trace_header lines "
                         "are skipped)")
    ap.add_argument("--url", action="append", dest="urls", default=[],
                    help="daemon HTTP base url; repeatable")
    ap.add_argument("--trace-id", default="",
                    help="assemble only this trace")
    ap.add_argument("--width", type=int, default=40,
                    help="waterfall bar width in characters")
    ap.add_argument("--timeout", type=float, default=10.0)
    ap.add_argument("--json", action="store_true",
                    help="print the assembled trees as JSON instead")
    ap.add_argument("--allow-empty", action="store_true",
                    help="exit 0 even when nothing assembled")
    args = ap.parse_args(argv)
    if not args.files and not args.urls:
        ap.error("need at least one FILE or --url")

    spans = []
    for url in args.urls:
        try:
            spans.extend(_fetch_spans(url, args.trace_id, args.timeout))
        except Exception as e:  # noqa: BLE001
            print(f"trace_assemble: fetch failed ({url}): {e!r}",
                  file=sys.stderr)
            return 1
    for path in args.files:
        try:
            spans.extend(_read_spans(path))
        except OSError as e:
            print(f"trace_assemble: read failed ({path}): {e!r}",
                  file=sys.stderr)
            return 1

    traces = assemble(spans, trace_id=args.trace_id or None)
    if args.json:
        print(json.dumps(traces))
    else:
        for trace in traces:
            print(render_waterfall(trace, width=args.width))
            print()
    if not traces:
        print("trace_assemble: no traces assembled "
              f"({len(spans)} spans read)", file=sys.stderr)
        return 0 if args.allow_empty else 1
    if not args.json:
        print(f"trace_assemble: {len(traces)} trace(s) from "
              f"{len(spans)} span(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())

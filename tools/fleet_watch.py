"""Fleet watchtower fan-in (ISSUE 19, fleet.py).

Polls every daemon's debug endpoints and folds them through the exact
fleet merges: conservation audit (Σ backlog == fleet drift), ring
consistency (divergence printed as it fires/clears), cluster top-K,
tenant RED rollup, SLO burn, memory pressure.  One shot by default;
``--watch N`` loops every N seconds and edge-prints ring/conservation
transitions — a terminal-grade stand-in for the fleet tick a real
control plane would run.

    python tools/fleet_watch.py --url http://d1:1050 --url http://d2:1050
    python tools/fleet_watch.py --watch 5 --url ...    # follow mode
    python tools/fleet_watch.py --json --url ...       # one JSON doc

Exit: 0 when every daemon answered, the ring is consistent and the
fleet is conserved; 1 otherwise (watch mode exits on interrupt with
the last verdict).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))
from gubernator_tpu import fleet  # noqa: E402

#: endpoint → merge for the full sweep; audit is fetched first because
#: status + ring checks fold it too
ENDPOINTS = ("/debug/audit", "/healthz", "/debug/topkeys",
             "/debug/tenants", "/debug/slo", "/debug/memory")


def _fetch(url: str, timeout: float):
    with urllib.request.urlopen(url, timeout=timeout) as f:
        return json.loads(f.read())


def _fan(urls, path, timeout):
    """Per-daemon documents for one endpoint; None entries mark
    unreachable daemons (the sweep continues — a dead daemon is a
    finding, not a crash)."""
    docs = []
    for base in urls:
        try:
            docs.append(_fetch(base.rstrip("/") + path, timeout))
        except Exception as e:  # noqa: BLE001
            print(f"fetch failed ({base}{path}): {e!r}",
                  file=sys.stderr)
            docs.append(None)
    return docs


def sweep(urls, timeout: float, watch: fleet.RingWatch) -> dict:
    """One fleet tick: fetch everything, fold everything."""
    raw = {p: _fan(urls, p, timeout) for p in ENDPOINTS}
    audits = [d for d in raw["/debug/audit"] if d]
    health = [d or {"status": "unreachable"} for d in raw["/healthz"]]
    out = {
        "daemons": len(urls),
        "reachable": sum(1 for d in raw["/debug/audit"] if d),
        "status": fleet.merge_status(health, audits),
        "audit": fleet.fold_audits(audits),
        "ring": watch.check(audits),
        "topkeys": fleet.merge_topkeys(
            [d for d in raw["/debug/topkeys"] if d]),
        "tenants": fleet.merge_tenants(
            [d for d in raw["/debug/tenants"] if d]),
        "slo": fleet.merge_slo([d for d in raw["/debug/slo"] if d]),
        "memory": fleet.merge_memory(
            [d for d in raw["/debug/memory"] if d]),
    }
    out["ok"] = (out["reachable"] == out["daemons"]
                 and out["ring"]["consistent"]
                 and out["audit"]["conserved"]
                 and out["tenants"]["conserved"])
    return out


def render(doc: dict) -> None:
    a, ring = doc["audit"], doc["ring"]
    t = a["totals"]
    state = "CONSERVED" if a["conserved"] else "DRIFT"
    print(f"[fleet] {doc['reachable']}/{doc['daemons']} reachable  "
          f"drift={a['drift']} ({state})  "
          f"ring={'ok' if ring['consistent'] else 'DIVERGED'}  "
          f"breached={doc['slo']['breached'] or 'none'}")
    print(f"  injected={t['injected']} applied={t['applied']} "
          f"queued={t['queued']} in_flight={t['in_flight']} "
          f"lost={t['lost']}  max_drain_age={a['max_drain_age_s']}s "
          f"(bound {a['bound_s']}s)")
    for r in a["per_daemon"]:
        print(f"    {r['instance'] or '?':<24} drift={r['drift']:<8} "
              f"queued={r['queued']:<8} lost={r['lost']}")
    if not ring["consistent"]:
        print(f"  ring DIVERGED: {','.join(ring['reasons'])} "
              f"ejected={ring['ejected']}")
    keys = doc["topkeys"]["keys"][:5]
    if keys:
        tops = ", ".join(f"{e.get('key') or e['khash']}:{e['hits']}"
                         for e in keys)
        print(f"  top keys: {tops}")
    mem = doc["memory"]
    print(f"  memory: device={mem['device_bytes']} "
          f"max_pressure={mem['max_pressure']}  tenants: "
          f"{doc['tenants']['tenant_count']} "
          f"({'sum-ok' if doc['tenants']['conserved'] else 'MISMATCH'})")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="fan in every daemon's debug endpoints and fold "
                    "them into the fleet verdict (fleet.py)")
    ap.add_argument("--url", action="append", dest="urls", default=None,
                    help="daemon HTTP base url (repeat per daemon; "
                         "default http://localhost:1050)")
    ap.add_argument("--timeout", type=float, default=5.0)
    ap.add_argument("--watch", type=float, default=0.0, metavar="SEC",
                    help="loop every SEC seconds (edge-prints ring "
                         "divergence transitions)")
    ap.add_argument("--json", action="store_true",
                    help="print one folded JSON document per sweep")
    args = ap.parse_args(argv)
    urls = args.urls or ["http://localhost:1050"]
    watch = fleet.RingWatch()
    doc = None
    try:
        while True:
            doc = sweep(urls, args.timeout, watch)
            if args.json:
                print(json.dumps(doc))
            else:
                render(doc)
            if args.watch <= 0:
                break
            time.sleep(args.watch)
    except KeyboardInterrupt:
        pass
    return 0 if (doc and doc["ok"]) else 1


if __name__ == "__main__":
    sys.exit(main())

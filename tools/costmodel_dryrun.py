"""Collective cost-model dryrun (ISSUE 11): fit α-β on live folds.

Runs the mesh-GLOBAL reconcile collective (parallel/meshglobal.py) on
a forced 8-device CPU mesh at several fold sizes (the fold moves the
replicated value columns + accumulator, so bytes scale with the tier
capacity), feeds the timed samples to ``analytics.CostModel``, and
validates the fitted ``T(bytes) = α + β·bytes`` against a HELD-OUT
fold size the fit never saw — prediction vs the median observed time
at that size, with the relative error stated in the artifact.

Writes ``MULTICHIP_r06.json``: the r05-compatible verdict keys
(``n_devices`` / ``rc`` / ``ok`` / ``skipped`` / ``tail``) plus a
``cost_model`` block with the fitted constants — the same α/β the
``12_mesh_global`` bench row records from its live folds, here
cross-validated.  The hierarchical-reconcile ROADMAP item prices
levels with these constants.

Usage::

    python tools/costmodel_dryrun.py [--devices 8] \
        [--json MULTICHIP_r06.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NOW0 = 1_760_000_000_000

#: stated acceptance bound on the held-out relative error.  The α term
#: dominates on a host-CPU mesh (collective launch, not bandwidth), so
#: the model must land the held-out size well inside 2× even with
#: shared-host timer noise.
REL_ERR_BUDGET = 0.5


def _force_devices(n: int):
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    import jax

    # the sandbox sitecustomize pins jax_platforms at interpreter
    # start; update the config directly (no-op if backends are up)
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001
        pass
    return jax


def run(n_devices: int = 8, train_caps=(256, 1024, 4096),
        holdout_cap: int = 2048, reps: int = 12,
        warmup: int = 3) -> dict:
    jax = _force_devices(n_devices)
    if len(jax.devices()) < n_devices:
        raise RuntimeError(
            f"need {n_devices} devices, have {len(jax.devices())}; run "
            "with XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{n_devices} and a cpu jax platform")
    import numpy as np

    from gubernator_tpu.analytics import CostModel
    from gubernator_tpu.hashing import hash_key
    from gubernator_tpu.parallel import make_mesh
    from gubernator_tpu.parallel.meshglobal import MeshGlobalEngine
    from gubernator_tpu.types import RateLimitRequest

    mesh = make_mesh(n=n_devices)
    cm = CostModel()

    def fold_samples(cap: int):
        """(fold_nbytes, per-fold seconds) at one tier capacity, with
        exact conservation re-asserted — a timing run that loses hits
        would be fitting a broken collective."""
        mge = MeshGlobalEngine(mesh, capacity=cap, batch_per_chip=32)
        req = RateLimitRequest(name="cost", unique_key="k", hits=1,
                               limit=10 ** 9, duration=600_000)
        kh = hash_key("cost", "k")
        assert mge.pin(req, kh, NOW0)
        times = []
        for i in range(warmup + reps):
            mge.check_batch([req] * n_devices, [kh] * n_devices,
                            NOW0 + i)
            t0 = time.perf_counter()
            mge.fold(mge.swap_accum())
            mge.drain()  # block until the collective fully resolves
            dt = time.perf_counter() - t0
            if i >= warmup:  # compile + first-touch excluded
                times.append(dt)
        s = mge.stats()
        assert s["folded_hits"] == s["injected_hits"], s
        return mge.fold_nbytes, times

    observed = {}
    for cap in sorted(set(train_caps) | {holdout_cap}):
        nbytes, times = fold_samples(cap)
        observed[cap] = (nbytes, times)
        if cap != holdout_cap:
            for dt in times:
                cm.add("global_fold", nbytes, n_devices, dt)

    fit = cm.fit("global_fold", n_devices)
    assert fit is not None and fit["n"] == reps * len(set(train_caps))
    hold_bytes, hold_times = observed[holdout_cap]
    actual_s = float(np.median(hold_times))
    pred_s = cm.predict("global_fold", n_devices, hold_bytes)
    rel_err = abs(pred_s - actual_s) / actual_s
    return {
        "phase": "global_fold",
        "ndev": n_devices,
        "model": "T = alpha + beta * bytes",
        "alpha_us": round(fit["alpha_s"] * 1e6, 3),
        "beta_ns_per_byte": round(fit["beta_s_per_byte"] * 1e9, 6),
        "train_samples": fit["n"],
        "train_fold_bytes": sorted(observed[c][0] for c in train_caps),
        "holdout_fold_bytes": hold_bytes,
        "holdout_pred_us": round(pred_s * 1e6, 3),
        "holdout_actual_us": round(actual_s * 1e6, 3),
        "holdout_rel_err": round(rel_err, 4),
        "rel_err_budget": REL_ERR_BUDGET,
        "within_budget": bool(rel_err <= REL_ERR_BUDGET),
        "buckets": cm.snapshot()["buckets"],
        "context": ("host-CPU mesh: α (collective launch + rendezvous) "
                    "dominates and β is small/noisy — on TPU hardware "
                    "the per-byte term carries the interconnect "
                    "bandwidth; the held-out check validates the FIT "
                    "DISCIPLINE, the constants are host-class-local"),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="fit + hold-out-validate the collective cost model")
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--json", default=os.path.join(REPO,
                                                   "MULTICHIP_r06.json"))
    args = ap.parse_args(argv)
    try:
        block = run(n_devices=args.devices)
        ok = block["within_budget"]
        tail = (f"costmodel_dryrun ok: {args.devices} shards, "
                f"global_fold alpha={block['alpha_us']}us "
                f"beta={block['beta_ns_per_byte']}ns/B, held-out "
                f"{block['holdout_fold_bytes']}B rel_err="
                f"{block['holdout_rel_err']} "
                f"(budget {block['rel_err_budget']})\n")
        verdict = {"n_devices": args.devices, "rc": 0 if ok else 1,
                   "ok": ok, "skipped": False, "tail": tail,
                   "cost_model": block}
    except Exception as e:  # noqa: BLE001 - verdict artifact, not a trace
        verdict = {"n_devices": args.devices, "rc": 1, "ok": False,
                   "skipped": False,
                   "tail": f"costmodel_dryrun failed: {e!r}\n"}
    doc = json.dumps(verdict, indent=2)
    print(doc)
    with open(args.json, "w", encoding="utf-8") as f:
        f.write(doc + "\n")
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

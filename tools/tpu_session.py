"""One-shot TPU validation battery for when the axon tunnel is alive.

The tunnel wedges permanently if a client abandons an in-flight compile
(ROUND_NOTES round 1), so when a chip IS reachable every open question
must be answered in ONE session window, cheapest first.  This script
runs that battery and writes /tmp/tpu_session.json as it goes (each
stage's result lands immediately, so a later wedge loses nothing):

  1. trivial-op probe (is the tunnel alive at all?)
  2. step-mode duel at serving shapes: copy vs donated decide_batch at
     CAP 2^21 (answers PERF.md §5.1 — does the TPU lowering update
     in place, or serialize aliased scatters?)
  3. capacity sweep in the winning mode: CAP 2^21 → 2^24 (is the
     streaming wall broken — cost ~flat — or still linear?)
  4. config-5 probe: one donated step at CAP 2^27 (does the 100M-key
     table fit and run?)
  5. scan superstep (on-chip rate, launch latency excluded)
  6. full bench.py inner run (the driver-shaped JSON, both modes)

Usage (give it a LONG timeout — cold compiles took 444s in round 1;
never ctrl-C an in-flight stage):

    timeout 5400 python tools/tpu_session.py
"""
import json
import os
import sys
import time

# runnable as `python tools/tpu_session.py` from anywhere: the repo
# root must be on sys.path before gubernator_tpu/bench imports
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
import _jax_cache  # persistent compile cache (shared dir choice)

_jax_cache.setup()

OUT = "/tmp/tpu_session.json"
results: dict = {"started": time.strftime("%Y-%m-%d %H:%M:%S")}


def record(key, value):
    results[key] = value
    with open(OUT, "w") as f:
        json.dump(results, f, indent=1)
    print(f"[tpu_session] {key}: {value}", file=sys.stderr, flush=True)


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    t0 = time.time()
    backend = jax.default_backend()
    x = int(jnp.arange(8).sum())
    record("probe", {"backend": backend, "sum": x,
                     "seconds": round(time.time() - t0, 1)})
    if backend != "tpu":
        record("abort", f"backend is {backend}, not tpu")
        return 1

    from gubernator_tpu.core.batch import RequestBatch
    from gubernator_tpu.core.step import decide_batch, decide_batch_donated
    from gubernator_tpu.core.table import init_table

    # share the bench's key distribution + populate padding, so these
    # answers apply verbatim to the driver's bench run
    from bench import _keyhash as keyhash, pad_chunk

    i64 = jnp.int64
    B = int(os.environ.get("GUBER_BENCH_B", 65536))
    rng = np.random.default_rng(42)

    def mk(keys):
        n = keys.shape[0]
        return RequestBatch(
            key=jnp.asarray(keys), hits=jnp.ones(n, i64),
            limit=jnp.full(n, 100, i64), duration=jnp.full(n, 10_000, i64),
            eff_ms=jnp.full(n, 10_000, i64), greg_end=jnp.zeros(n, i64),
            behavior=jnp.zeros(n, jnp.int32),
            algorithm=jnp.zeros(n, jnp.int32),
            burst=jnp.full(n, 100, i64), valid=jnp.ones(n, bool))

    NOW = 1_760_000_000_000

    # Transfer-free hot loops: per-rep `jnp.asarray(NOW + r)` is a
    # SYNCHRONOUS host→device round trip over the axon tunnel (measured
    # 2026-08-01: ~26-216 ms per transfer on a degraded link, while
    # chained dispatch pipelines at 0.02 ms/step) — it turns every
    # sustained loop into a link-RTT measurement.  `now` lives on device
    # and advances with a jitted +1 instead (identical time semantics).
    bump1 = jax.jit(lambda tt: tt + 1)
    bump1(jnp.asarray(0, i64)).block_until_ready()  # compile up front:
    # never inside a timed region (cap27 uses it before any measure())

    def measure(step_fn, cap, n_keys, label, reps=64,
                init_fn=init_table):
        st = init_fn(cap)
        batches = [mk(keyhash((rng.zipf(1.1, size=B) % n_keys)
                              .astype(np.uint64))) for _ in range(4)]
        now0 = jnp.asarray(NOW, i64)
        t = time.time()
        st, out = step_fn(st, batches[0], now0)
        out.status.block_until_ready()
        compile_s = round(time.time() - t, 1)
        # populate (same padding policy as bench.populate)
        ids = np.arange(n_keys, dtype=np.uint64)
        for a in range(0, n_keys, B):
            ch = pad_chunk(ids[a:a + B], B)
            st, out = step_fn(st, mk(keyhash(ch)), now0)
        out.status.block_until_ready()
        now_dev = bump1(now0)
        t = time.time()
        for r in range(reps):
            st, out = step_fn(st, batches[r % 4], now_dev)
            now_dev = bump1(now_dev)
        out.status.block_until_ready()
        dt = time.time() - t
        rate = reps * B / dt
        # honest rate: err rows (table/bucket overflow) are NOT served
        # decisions — the fraction rides every row so a reader can see
        # whether a mode's rate covers the whole working set (the
        # pallas kernel's 8-slot buckets overflow sooner than the XLA
        # probe window)
        err_frac = round(float(np.asarray(out.err).mean()), 6)
        record(label, {"decisions_per_s": round(rate),
                       "ms_per_step": round(dt / reps * 1e3, 3),
                       "compile_s": compile_s, "cap": cap,
                       "n_keys": n_keys, "B": B,
                       "err_fraction": err_frac})
        return rate

    def stage(label, thunk, retries=1):
        """Stage isolation: one flaky remote_compile (observed
        2026-08-01: 'response body closed before all bytes were read'
        mid-compile) must cost ONE stage, not the battery.  Retries
        once after a settle pause; two total failures record an error
        row and the battery moves on."""
        for attempt in range(retries + 1):
            try:
                return thunk()
            except Exception as e:  # noqa: BLE001
                err = f"attempt {attempt + 1}: {str(e)[:300]}"
                record(f"{label}__error{attempt + 1}", err)
                if attempt < retries:  # settle pause only before a retry
                    time.sleep(20)
        return None

    # 2. step-mode duel at CAP 2^21 (1M keys)
    r_copy = stage("copy_cap21", lambda: measure(
        decide_batch, 1 << 21, 1_000_000, "copy_cap21")) or 0.0
    r_don = stage("donate_cap21", lambda: measure(
        decide_batch_donated, 1 << 21, 1_000_000, "donate_cap21")) or 0.0
    winner = decide_batch_donated if r_don > r_copy else decide_batch
    record("step_mode", "donate" if r_don > r_copy else "copy")

    # 3. capacity sweep in the winning mode (is cost flat in CAP?)
    stage("win_cap22", lambda: measure(winner, 1 << 22, 2_000_000,
                                       "win_cap22"))
    stage("win_cap24", lambda: measure(winner, 1 << 24, 10_000_000,
                                       "win_cap24"))

    # 3b. Pallas decision kernel (VERDICT r2 item 4): does the Mosaic
    # lowering compile on real hardware, does it match the XLA step
    # bit-for-bit on-chip, and what floor does it measure?  Isolated:
    # a Mosaic failure must not cost the remaining stages.
    try:
        from gubernator_tpu.ops.pallas_step import (decide_batch_pallas,
                                                    init_pallas_table)

        # on-chip parity spot-check before any timing
        ksm = keyhash(np.arange(1, 513, dtype=np.uint64))
        pt = init_pallas_table(1 << 12)
        stx = init_table(1 << 12)
        pt, po = decide_batch_pallas(pt, mk(ksm), jnp.asarray(NOW, i64))
        stx, xo = decide_batch(stx, mk(ksm), jnp.asarray(NOW, i64))
        mismatch = [f for f in ("status", "remaining", "reset_time",
                                "limit")
                    if not bool((getattr(po, f)
                                 == getattr(xo, f)).all())]
        if mismatch:
            record("pallas_step", {"ok": False,
                                   "mismatch_fields": mismatch})
        else:
            # 2× rows like bench's duel: the 8-slot buckets need the
            # headroom (the row's err_fraction shows what remains).
            # The row's "cap" field is the XLA-comparable parameter;
            # table_rows records what the kernel actually used.
            cap_p = 1 << 21
            measure(decide_batch_pallas, cap_p, 1_000_000,
                    "pallas_cap21", reps=16,
                    init_fn=lambda cap: init_pallas_table(cap * 2))
            record("pallas_step", {"ok": True,
                                   "table_rows": cap_p * 2})
    except Exception as e:  # noqa: BLE001
        record("pallas_step", {"ok": False, "error": str(e)[:400]})

    # 4. config-5 probe: CAP 2^27 fits only donated (one table copy)
    try:
        st5 = init_table(1 << 27)
        k5 = mk(keyhash(rng.integers(0, 100_000_000, size=B)
                        .astype(np.uint64)))
        t = time.time()
        st5, out = decide_batch_donated(st5, k5, jnp.asarray(NOW, i64))
        out.status.block_until_ready()
        first = time.time() - t
        now_dev = jnp.asarray(NOW, i64)
        t = time.time()
        for r in range(8):
            st5, out = decide_batch_donated(st5, k5, now_dev)
            now_dev = bump1(now_dev)
        out.status.block_until_ready()
        record("cap27_probe", {
            "ok": True, "first_step_s": round(first, 1),
            "decisions_per_s": round(8 * B / (time.time() - t))})
        # 4b. the ACTUAL config-5 workload at 2^27 (VERDICT r2 item 5):
        # Gregorian expirations + RESET_REMAINING churn, not just
        # capacity residence — reuses the live 2^27 table
        try:
            from gubernator_tpu.gregorian import gregorian_expiration
            from gubernator_tpu.types import Behavior, GregorianDuration

            greg_end = gregorian_expiration(NOW,
                                            int(GregorianDuration.HOURS))
            beh = np.full(B, int(Behavior.DURATION_IS_GREGORIAN),
                          np.int32)
            beh[::37] |= int(Behavior.RESET_REMAINING)
            kg = keyhash(rng.integers(0, 100_000_000, size=B)
                         .astype(np.uint64))
            bg = RequestBatch(
                key=jnp.asarray(kg), hits=jnp.ones(B, i64),
                limit=jnp.full(B, 100, i64),
                duration=jnp.full(B, int(GregorianDuration.HOURS), i64),
                eff_ms=jnp.full(B, 3_600_000, i64),
                greg_end=jnp.full(B, greg_end, i64),
                behavior=jnp.asarray(beh),
                algorithm=jnp.zeros(B, jnp.int32),
                burst=jnp.full(B, 100, i64), valid=jnp.ones(B, bool))
            st5, out = decide_batch_donated(st5, bg,
                                            jnp.asarray(NOW, i64))
            out.status.block_until_ready()  # compile
            now_dev = jnp.asarray(NOW + 1, i64)
            t = time.time()
            for r in range(8):
                st5, out = decide_batch_donated(st5, bg, now_dev)
                now_dev = bump1(now_dev)
            out.status.block_until_ready()
            record("cap27_gregorian_churn", {
                "ok": True, "capacity": 1 << 27,
                "decisions_per_s": round(8 * B / (time.time() - t))})
        except Exception as e:  # noqa: BLE001
            record("cap27_gregorian_churn", {"ok": False,
                                             "error": str(e)[:300]})
        del st5
    except Exception as e:  # noqa: BLE001
        record("cap27_probe", {"ok": False, "error": str(e)[:300]})

    # 5+6. the full driver-shaped bench (scan superstep, latency,
    # secondary configs, clustered service) in this same window.
    # Never SIGKILL it mid-compile (that's the tunnel-wedge mechanism):
    # the inner timeout is generous and expiry is RECORDED, not fatal —
    # stages 1–4 above already answered the load-bearing questions.
    os.environ["GUBER_BENCH_INNER"] = "1"
    import subprocess

    bench_timeout = int(os.environ.get("GUBER_SESSION_BENCH_TIMEOUT",
                                       "5400"))
    try:
        r = subprocess.run([sys.executable,
                            os.path.join(os.path.dirname(__file__), "..",
                                         "bench.py")],
                           stdout=subprocess.PIPE, timeout=bench_timeout)
        line = (r.stdout or b"").decode().strip().splitlines()
        record("bench", json.loads(line[-1]) if line and
               line[-1].startswith("{") else {"error": "no JSON line"})
    except subprocess.TimeoutExpired as e:
        partial = (e.stdout or b"").decode(errors="replace")[-1000:]
        record("bench", {"error": f"timed out after {bench_timeout}s "
                                  "(tunnel may now be wedged — probe "
                                  "before any further TPU work)",
                         "partial_stdout": partial})
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception as e:  # noqa: BLE001
        record("fatal", str(e)[:400])
        raise

"""One-shot TPU validation battery for when the axon tunnel is alive.

The tunnel wedges permanently if a client abandons an in-flight compile
(ROUND_NOTES round 1), so when a chip IS reachable every open question
must be answered in ONE session window — and the DRIVER-SHAPED record
must land first (round 3 spent its only live window on exploratory
duels and never reached the bench; VERDICT r3 item 2).

Stage order (each stage is its OWN subprocess — the tunnel is
single-client, so the orchestrator never imports jax; a stage exiting
releases its client for the next):

  1. probe      — trivial op in a child (is the tunnel alive at all?)
  2. cap_ab 22  — ONE compile: is the donated step still pathological
                  at CAP 2^22 after the unique/sorted scatter promises?
                  (VERDICT r3 item 1 — the question of the round.)
  3. bench.py   — the FULL driver-shaped bench (headline duel at the
                  north-star 10M-key/CAP 2^24 shape checkpoints itself
                  immediately; sections follow, each child-isolated).
                  Its JSON is mirrored into artifacts/ the moment it
                  exists: a wedge ANYWHERE later still leaves a
                  BENCH_rN-shaped TPU record on disk.
  4. extras     — exploratory stages, cheapest-first: Pallas on-chip
                  parity + cap21 timing (VERDICT item 3), LEAKY at
                  serving scale (item 7), cap27 probe + Gregorian
                  churn (item 6).

Every stage result is written to /tmp/tpu_session.json AND mirrored to
artifacts/tpu_session_live.json (the repo workspace persists across
sessions; /tmp does not).  After a stage timeout the orchestrator
checks relay-port liveness (127.0.0.1:8103 — refused ⇒ relay dead)
and aborts the battery instead of burning timeouts on a dead link.

Usage (give it a LONG timeout — cold compiles are 200-300 s each,
.jax_cache does NOT persist axon remote_compile results, and the
internal stage budgets sum to ~13050 s before stall extensions; on
SIGTERM the orchestrator kills the active stage's process group so no
orphan can hold the single-client tunnel):

    timeout 14400 python tools/tpu_session.py
"""
import json
import os
import signal
import socket
import subprocess
import sys
import time
from functools import partial

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.abspath(os.path.join(_HERE, ".."))
sys.path.insert(0, _REPO)

OUT = "/tmp/tpu_session.json"
MIRROR = os.path.join(_REPO, "artifacts", "tpu_session_live.json")
results: dict = {"started": time.strftime("%Y-%m-%d %H:%M:%S")}


def atomic_write_json(path, obj):
    """Atomic checkpoint write; a write failure (full /tmp, bad path)
    must cost the checkpoint, never the battery — the whole point of
    checkpointing is surviving worse failures than this."""
    try:
        with open(path + ".tmp", "w") as f:
            json.dump(obj, f, indent=1)
        os.replace(path + ".tmp", path)
    except OSError as e:
        print(f"[tpu_session] write {path} failed: {e}", file=sys.stderr)


def record(key, value):
    results[key] = value
    for path in (OUT, MIRROR):
        atomic_write_json(path, results)
    print(f"[tpu_session] {key}: {str(value)[:300]}", file=sys.stderr,
          flush=True)


_ACTIVE_STAGE_PID = None


def _sigterm(signum, frame):
    """An external timeout killing THIS process must not orphan the
    active stage's process group — an orphaned jax client would hold
    the single-client tunnel (and possibly an in-flight compile)
    indefinitely."""
    if _ACTIVE_STAGE_PID is not None:
        try:
            os.killpg(_ACTIVE_STAGE_PID, 9)
        except OSError:
            pass
    record("aborted_by_signal", signum)
    sys.exit(1)


def relay_alive(port=8103, timeout=5) -> bool:
    """The axon backend's only path is a local stdio relay; when the
    relay process dies every relay port refuses and jax.devices() hangs
    forever.  A raw connect answers 'is there any point probing JAX'
    without spending a JAX hang timeout."""
    s = socket.socket()
    s.settimeout(timeout)
    try:
        s.connect(("127.0.0.1", port))
        return True
    except OSError:
        return False
    finally:
        s.close()


def run_stage(name, argv, timeout, env_extra=None, progress_file=None,
              stall_timeout=900):
    """Run one battery stage as its own PROCESS GROUP.  Returns
    (ok, stdout).

    Killing a healthy child mid-remote-compile is the known permanent
    tunnel-wedge mechanism, so a stage is only killed when it is
    actually stuck, not merely slow: with a `progress_file` (the
    stage's own progressive checkpoint) the deadline extends as long as
    the file keeps advancing, and the kill fires only after
    `stall_timeout` seconds with NO checkpoint progress past the hard
    deadline.  The kill targets the whole process group — bench.py's
    watchdog spawns inner/section grandchildren, and an orphaned
    grandchild would silently hold the single-client tunnel and starve
    every later stage."""
    env = dict(os.environ)
    # a live battery must measure the REAL backend at the REAL shapes
    # in the REAL serving mode: stale operator exports would silently
    # corrupt it — cpu pin / any-backend gate run the escalation
    # ladder on CPU; KSPLIT makes the tier-1/tier-2 A/B identical;
    # EXTRAS_SMOKE runs the extras at toy shapes; STEP_IMPL flips the
    # engine under every bench row except 11_pallas_serving.  A stage
    # that NEEDS one of these sets it via env_extra.
    for stale in ("GUBER_CAP_AB_ANY_BACKEND", "GUBER_JAX_PLATFORM",
                  "GUBER_KSPLIT", "GUBER_EXTRAS_SMOKE",
                  "GUBER_STEP_IMPL", "GUBER_BENCH_FAST",
                  "GUBER_PROBES", "GUBER_BENCH_B"):
        if stale not in (env_extra or {}) and env.pop(stale, None) \
                is not None:
            # observable: an operator who exported one ON PURPOSE must
            # see the battery discarded it, not publish numbers for a
            # mode they never measured
            print(f"[{name}] scrubbed stale env {stale} (stage envs "
                  "are canonical; pass via env_extra in the script to "
                  "override)", file=sys.stderr)
    env.update(env_extra or {})
    t0 = time.time()
    proc = subprocess.Popen(argv, stdout=subprocess.PIPE, cwd=_REPO,
                            env=env, start_new_session=True)
    global _ACTIVE_STAGE_PID
    _ACTIVE_STAGE_PID = proc.pid

    def progress_mtime():
        try:
            return os.path.getmtime(progress_file)
        except OSError:
            return 0.0

    killed = None
    while True:
        try:
            proc.wait(timeout=15)
            break
        except subprocess.TimeoutExpired:
            pass
        now = time.time()
        if now - t0 < timeout:
            continue
        if progress_file and now - max(progress_mtime(), t0) < stall_timeout:
            continue  # past deadline but still checkpointing: let it run
        killed = round(now - t0, 1)
        try:
            os.killpg(proc.pid, 9)
        except OSError:
            proc.kill()
        proc.wait()
        break
    out = (proc.stdout.read() or b"").decode(errors="replace")
    dt = round(time.time() - t0, 1)
    if killed is not None:
        record(f"{name}__stage", {
            "rc": "timeout", "seconds": killed,
            "partial_stdout": out[-500:]})
        return False, out
    record(f"{name}__stage", {"rc": proc.returncode, "seconds": dt})
    return proc.returncode == 0, out


def merge_json_file(key, path, not_before):
    """Pull a stage's own checkpoint file into the session record (the
    stage wrote it progressively, so it survives the stage dying).
    Checkpoint paths are fixed, so a file older than the stage's start
    is a PREVIOUS run's data — recording it would publish stale numbers
    as this session's (same freshness rule as bench's salvage_partial)."""
    try:
        if os.path.getmtime(path) < not_before:
            record(key, {"error": f"checkpoint at {path} predates this "
                                  "stage (stale run) — discarded"})
            return False
        with open(path) as f:
            record(key, json.load(f))
        return True
    except (OSError, ValueError) as e:
        record(key, {"error": f"no checkpoint at {path}: {e}"})
        return False


def main() -> int:
    signal.signal(signal.SIGTERM, _sigterm)
    if not relay_alive():
        record("abort", "relay port 8103 refused — tunnel relay is "
                        "dead; nothing to measure")
        return 1

    # 1. probe: trivial op in a child (150 s: a live-but-degraded link
    # can take tens of seconds; a wedge hangs forever)
    ok, out = run_stage("probe", [
        sys.executable, "-c",
        "import jax, json; "
        "print(json.dumps({'backend': jax.default_backend(), "
        "'sum': int(jax.numpy.arange(8).sum())}))"], timeout=150)
    if not ok:
        record("abort", "probe failed/hung — not spending compiles")
        return 1
    try:
        probe = json.loads(out.strip().splitlines()[-1])
    except (ValueError, IndexError):
        probe = {"raw": out[-200:]}
    record("probe", probe)
    if probe.get("backend") != "tpu":
        record("abort", f"backend is {probe.get('backend')}, not tpu")
        return 1

    # 2. the scatter-pathology question: ONE compile at CAP 2^22
    # (~5 min cold).  cap_ab writes /tmp/cap_ab.json progressively.
    t_capab = time.time()
    ok, _ = run_stage("cap_ab22", [sys.executable,
                                   os.path.join(_HERE, "cap_ab.py"),
                                   "22"], timeout=1500,
                      progress_file="/tmp/cap_ab.json")
    merge_json_file("cap_ab22", "/tmp/cap_ab.json", t_capab)
    if not ok and not relay_alive():
        record("abort", "relay died during cap_ab — battery over; "
                        "commit what landed")
        return 1

    # 2b. if the unique/sorted scatter promises did NOT fix the
    # CAP >= 2^22 serialization, A/B the K-split fallback in the same
    # window (GUBER_KSPLIT=21: every table scatter becomes slice-local
    # scatters at the 2^21 operand size that lowers well) — one more
    # compile answers whether it is the large-CAP serving mode.
    verdict = (results.get("cap_ab22") or {}).get("verdict", "")
    ks_verdict = ""
    if ok and verdict == "still pathological":
        t_ks = time.time()
        run_stage("cap_ab22_ksplit", [sys.executable,
                                      os.path.join(_HERE, "cap_ab.py"),
                                      "22"], timeout=1500,
                  env_extra={"GUBER_KSPLIT": "21"},
                  progress_file="/tmp/cap_ab.json")
        merge_json_file("cap_ab22_ksplit", "/tmp/cap_ab.json", t_ks)
        if not relay_alive():
            record("abort", "relay died during cap_ab ksplit")
            return 1
        ks_verdict = (results.get("cap_ab22_ksplit") or {}).get(
            "verdict", "")
    # 2c. tier 3: unless SOME XLA tier verifiably fixed it, time the
    # Mosaic kernel at the same shape — the serving floor the
    # escalation ladder terminates in.  Gate on a good verdict
    # existing, not on a bad one: a stage that died/timed out without
    # writing any verdict (ok=False, verdict='') is exactly the
    # degraded window where the tier-3 number matters most.
    # --pallas-only skips the XLA arm tiers 1-2 already measured.
    if not {verdict, ks_verdict} & {"FIXED", "improved"}:
        t_p = time.time()
        run_stage("cap_ab22_pallas", [sys.executable,
                                      os.path.join(_HERE, "cap_ab.py"),
                                      "22", "--pallas-only"],
                  timeout=1800, progress_file="/tmp/cap_ab.json")
        merge_json_file("cap_ab22_pallas", "/tmp/cap_ab.json", t_p)
        if not relay_alive():
            record("abort", "relay died during cap_ab pallas")
            return 1

    # 3. THE DRIVER-SHAPED BENCH — before any exploratory stage.  The
    # headline duel (copy/donate/pallas at 10M keys / CAP 2^24) is the
    # north-star answer AND the BENCH_rN record; bench checkpoints it
    # to the partial file immediately after the duel, so even a bench
    # death at minute 30 leaves a driver-parseable fragment here.
    partial = "/tmp/guber_bench_partial_session.json"
    # bench.py's own watchdog budgets 5400 s for the device attempt +
    # 1800 s CPU fallback + probes; the stage timeout must sit OUTSIDE
    # that so the watchdog's salvage machinery (not our kill) decides
    bench_timeout = int(os.environ.get("GUBER_SESSION_BENCH_TIMEOUT",
                                       "7800"))
    t_bench = time.time()
    ok, out = run_stage("bench", [sys.executable,
                                  os.path.join(_REPO, "bench.py")],
                        timeout=bench_timeout,
                        env_extra={"GUBER_BENCH_PARTIAL": partial,
                                   # dispatcher wave-wait must outlast
                                   # a cold compile (VERDICT r5 item 6)
                                   "GUBER_RESULT_TIMEOUT_S": "900"},
                        progress_file=partial)
    lines = [ln for ln in out.strip().splitlines()
             if ln.startswith("{")]
    if ok and lines:
        try:
            record("bench", json.loads(lines[-1]))
        except ValueError:
            record("bench", {"error": "unparseable final line",
                             "raw": lines[-1][:500]})
            merge_json_file("bench_partial", partial, t_bench)
    else:
        # died or timed out: the partial checkpoint IS the record
        merge_json_file("bench_partial", partial, t_bench)
    if not relay_alive():
        record("abort", "relay died during/after bench — battery over")
        return 1

    # 4. exploratory extras (own subprocess, own progressive file).
    # ~5 cold compiles at the observed 250-440 s worst case plus
    # populate loops: the hard deadline assumes a warmish path, the
    # progress extension covers a slow-but-advancing cold one.
    extras_out = "/tmp/tpu_session_extras.json"
    t_extras = time.time()
    run_stage("extras", [sys.executable, os.path.abspath(__file__),
                         "--extras"], timeout=3600,
              env_extra={"GUBER_SESSION_EXTRAS_OUT": extras_out},
              progress_file=extras_out)
    merge_json_file("extras", extras_out, t_extras)

    record("finished", time.strftime("%Y-%m-%d %H:%M:%S"))
    print(json.dumps(results, indent=1))
    return 0


# ---- extras stage (runs as its own subprocess) --------------------------


def extras() -> int:
    import _jax_cache

    _jax_cache.setup()

    #: GUBER_EXTRAS_SMOKE: run every stage at toy shapes on any backend
    #: (offline dry-run of the battery code).  ONE boolean for every
    #: smoke gate below — mismatched truthiness (e.g. "=true" passing
    #: one gate, failing another) must not mix toy rows with real
    #: paths — and "=0"/"=false" mean OFF, not "non-empty ⇒ on".
    smoke_raw = os.environ.get("GUBER_EXTRAS_SMOKE", "").lower()
    smoke = smoke_raw in ("1", "true", "yes", "on")
    if smoke_raw and not smoke and smoke_raw not in ("0", "false",
                                                     "no", "off"):
        print(f"GUBER_EXTRAS_SMOKE={smoke_raw!r} not understood "
              "(want 1/true/yes/on or 0/false/no/off)",
              file=sys.stderr)
        return 2
    #: BOTH progressive outputs divert for smoke runs: the repo mirror
    #: (toy rows read like — or overwrite — a real session's record)
    #: AND the fixed /tmp checkpoint (a smoke concurrent with a live
    #: battery would otherwise pass merge_json_file's freshness check
    #: and publish toy rows as the live session's extras, while its
    #: mtime updates defeat the live stage's stall detection).
    out_path = os.environ.get(
        "GUBER_SESSION_EXTRAS_OUT",
        "/tmp/tpu_session_extras_smoke.json" if smoke
        else "/tmp/tpu_session_extras.json")
    mirror = ("/tmp/tpu_session_extras_smoke_mirror.json" if smoke
              else os.path.join(_REPO, "artifacts",
                                "tpu_session_extras_live.json"))
    ex: dict = {"started": time.strftime("%Y-%m-%d %H:%M:%S")}

    def rec(key, value):
        ex[key] = value
        atomic_write_json(out_path, ex)
        atomic_write_json(mirror, ex)
        print(f"[extras] {key}: {str(value)[:300]}", file=sys.stderr,
              flush=True)

    plat = os.environ.get("GUBER_JAX_PLATFORM", "")
    import jax

    if plat:
        # the sandbox sitecustomize overwrites the jax_platforms config
        # at interpreter start (env is ignored) — same dance as bench.py
        jax.config.update("jax_platforms", plat)
    import jax.numpy as jnp
    import numpy as np

    from bench import _keyhash as keyhash, pad_chunk
    from gubernator_tpu.core.batch import RequestBatch
    from gubernator_tpu.core.step import decide_batch, decide_batch_donated
    from gubernator_tpu.core.table import init_table

    if jax.default_backend() != "tpu" and not smoke:
        rec("abort", f"backend {jax.default_backend()}")
        return 1

    i64 = jnp.int64
    B = (256 if smoke
         else int(os.environ.get("GUBER_BENCH_B", 65536)))
    rng = np.random.default_rng(42)
    NOW = 1_760_000_000_000

    def mk(keys, **over):
        n = keys.shape[0]
        base = dict(
            key=jnp.asarray(keys), hits=jnp.ones(n, i64),
            limit=jnp.full(n, 100, i64), duration=jnp.full(n, 10_000, i64),
            eff_ms=jnp.full(n, 10_000, i64), greg_end=jnp.zeros(n, i64),
            behavior=jnp.zeros(n, jnp.int32),
            algorithm=jnp.zeros(n, jnp.int32),
            burst=jnp.full(n, 100, i64), valid=jnp.ones(n, bool))
        base.update(over)
        return RequestBatch(**base)

    bump1 = jax.jit(lambda tt: tt + 1)
    bump1(jnp.asarray(0, i64)).block_until_ready()

    def measure(step_fn, cap, n_keys, label, reps=64, init_fn=init_table,
                mk_over=None):
        if smoke:
            cap, n_keys, reps = 1 << 12, 2048, 4
        st = init_fn(cap)
        over = mk_over or {}
        batches = [mk(keyhash((rng.zipf(1.1, size=B) % n_keys)
                              .astype(np.uint64)), **over)
                   for _ in range(4)]
        now0 = jnp.asarray(NOW, i64)
        t = time.time()
        st, out = step_fn(st, batches[0], now0)
        out.status.block_until_ready()
        compile_s = round(time.time() - t, 1)
        ids = np.arange(n_keys, dtype=np.uint64)
        for a in range(0, n_keys, B):
            ch = pad_chunk(ids[a:a + B], B)
            st, out = step_fn(st, mk(keyhash(ch), **over), now0)
        out.status.block_until_ready()
        now_dev = bump1(now0)
        t = time.time()
        for r in range(reps):
            st, out = step_fn(st, batches[r % 4], now_dev)
            now_dev = bump1(now_dev)
        out.status.block_until_ready()
        dt = time.time() - t
        err_frac = round(float(np.asarray(out.err).mean()), 6)
        rec(label, {"decisions_per_s": round(reps * B / dt),
                    "ms_per_step": round(dt / reps * 1e3, 3),
                    "compile_s": compile_s, "cap": cap,
                    "n_keys": n_keys, "B": B,
                    "err_fraction": err_frac})
        return reps * B / dt

    def stage(label, thunk, retries=1):
        """One flaky remote_compile must cost ONE stage, not the
        battery (observed: 'response body closed before all bytes were
        read' mid-compile)."""
        for attempt in range(retries + 1):
            try:
                return thunk()
            except Exception as e:  # noqa: BLE001
                rec(f"{label}__error{attempt + 1}",
                    f"attempt {attempt + 1}: {str(e)[:300]}")
                if attempt < retries:
                    time.sleep(20)
        return None

    # 4a. Pallas decision kernel (VERDICT r3 item 3): on-chip parity
    # spot-check vs the XLA step (TOKEN and LEAKY batches), then cap21
    # timing.  (bench's duel already timed it at the CAP 2^24 shape.)
    def pallas_parity(label, over_fn=None):
        """512-key on-chip spot-check: kernel vs XLA step, all output
        fields.  `over_fn(n)` builds batch-field overrides at the
        parity size.  Returns True iff every field matched (records
        either way)."""
        try:
            from gubernator_tpu.ops.pallas_step import (
                decide_batch_pallas, init_pallas_table)

            npar = 512
            ksm = keyhash(np.arange(1, npar + 1, dtype=np.uint64))
            over = over_fn(npar) if over_fn else {}
            pt = init_pallas_table(1 << 12)
            stx = init_table(1 << 12)
            pt, po = decide_batch_pallas(pt, mk(ksm, **over),
                                         jnp.asarray(NOW, i64),
                                         interpret=smoke)
            stx, xo = decide_batch(stx, mk(ksm, **over),
                                   jnp.asarray(NOW, i64))
            mismatch = [f for f in ("status", "remaining", "reset_time",
                                    "limit")
                        if not bool((getattr(po, f)
                                     == getattr(xo, f)).all())]
            rec(label, {"ok": not mismatch,
                        "mismatch_fields": mismatch})
            return not mismatch
        except Exception as e:  # noqa: BLE001
            rec(label, {"ok": False, "error": str(e)[:400]})
            return False

    if pallas_parity("pallas_step"):
        try:
            from gubernator_tpu.ops.pallas_step import (
                decide_batch_pallas, init_pallas_table)

            cap_p = 1 << 12 if smoke else 1 << 21
            pal = (partial(decide_batch_pallas, interpret=True)
                   if smoke else decide_batch_pallas)
            measure(pal, cap_p, 1_000_000,
                    "pallas_cap21", reps=16,
                    init_fn=lambda cap: init_pallas_table(cap * 2))
        except Exception as e:  # noqa: BLE001
            rec("pallas_cap21__error", str(e)[:400])

    # LEAKY parity (round-4 kernel extension): the same spot-check on
    # an all-LEAKY batch.
    def leaky_over(n):
        return dict(algorithm=jnp.ones(n, jnp.int32),
                    limit=jnp.full(n, 10**6, i64),
                    burst=jnp.full(n, 10**6, i64),
                    duration=jnp.full(n, 60_000, i64),
                    eff_ms=jnp.full(n, 60_000, i64))

    pallas_parity("pallas_leaky", leaky_over)

    # 4b. LEAKY at serving scale (VERDICT r3 item 7): config 2 has had
    # no on-chip number since round 1.  1M keys / CAP 2^21 / B=65536 in
    # the donate mode — one compile.
    stage("leaky_cap21", lambda: measure(
        decide_batch_donated, 1 << 21, 1_000_000, "leaky_cap21",
        mk_over=leaky_over(B)))

    # 4c. config-5: CAP 2^27 residence probe + TRUE Gregorian/RESET
    # churn (VERDICT r3 item 6 — re-measure post scatter fix)
    try:
        cap5, u5 = ((1 << 14, 10_000) if smoke
                    else (1 << 27, 100_000_000))
        st5 = init_table(cap5)
        k5 = mk(keyhash(rng.integers(0, u5, size=B)
                        .astype(np.uint64)))
        t = time.time()
        st5, out = decide_batch_donated(st5, k5, jnp.asarray(NOW, i64))
        out.status.block_until_ready()
        first = time.time() - t
        now_dev = jnp.asarray(NOW, i64)
        t = time.time()
        for r in range(8):
            st5, out = decide_batch_donated(st5, k5, now_dev)
            now_dev = bump1(now_dev)
        out.status.block_until_ready()
        rec("cap27_probe", {
            "ok": True, "first_step_s": round(first, 1),
            "decisions_per_s": round(8 * B / (time.time() - t))})
        try:
            from gubernator_tpu.gregorian import gregorian_expiration
            from gubernator_tpu.types import Behavior, GregorianDuration

            greg_end = gregorian_expiration(NOW,
                                            int(GregorianDuration.HOURS))
            beh = np.full(B, int(Behavior.DURATION_IS_GREGORIAN),
                          np.int32)
            beh[::37] |= int(Behavior.RESET_REMAINING)
            kg = keyhash(rng.integers(0, u5, size=B)
                         .astype(np.uint64))
            bg = mk(kg, behavior=jnp.asarray(beh),
                    duration=jnp.full(B, int(GregorianDuration.HOURS),
                                      i64),
                    eff_ms=jnp.full(B, 3_600_000, i64),
                    greg_end=jnp.full(B, greg_end, i64))
            st5, out = decide_batch_donated(st5, bg,
                                            jnp.asarray(NOW, i64))
            out.status.block_until_ready()
            now_dev = jnp.asarray(NOW + 1, i64)
            t = time.time()
            for r in range(8):
                st5, out = decide_batch_donated(st5, bg, now_dev)
                now_dev = bump1(now_dev)
            out.status.block_until_ready()
            rec("cap27_gregorian_churn", {
                "ok": True, "capacity": cap5,
                "decisions_per_s": round(8 * B / (time.time() - t))})
        except Exception as e:  # noqa: BLE001
            rec("cap27_gregorian_churn", {"ok": False,
                                          "error": str(e)[:300]})
        del st5
    except Exception as e:  # noqa: BLE001
        rec("cap27_probe", {"ok": False, "error": str(e)[:300]})

    rec("finished", time.strftime("%Y-%m-%d %H:%M:%S"))
    return 0


if __name__ == "__main__":
    if "--extras" in sys.argv:
        sys.exit(extras())
    try:
        sys.exit(main())
    except Exception as e:  # noqa: BLE001
        record("fatal", str(e)[:400])
        raise

"""Thin shim: the metric/doc consistency checks moved into guberlint
as the ``docs`` pass family (ISSUE 14) — ``python -m tools.guberlint
--pass docs`` is the canonical entry point, ``make lint`` runs it with
everything else.  This CLI survives so existing callers
(tests/test_check_metrics.py, CI scripts) keep working unchanged.
"""
from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.guberlint.docs import (  # noqa: E402,F401  (re-exports: the
    _canonical,                     # helpers keep their import paths)
    documented_event_kinds,
    emitted_event_kinds,
    env_registry_doc_problems,
    faultpoint_doc_problems,
    main,
    slo_catalog_doc_problems,
    span_catalog_doc_problems,
)

if __name__ == "__main__":
    sys.exit(main())

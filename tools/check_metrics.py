"""Metric-catalog lint (tier-1 via tests/test_check_metrics.py).

Asserts, against a fresh ``Metrics()`` registry:

1. metric (family) names are unique — duplicate registration is a
   silent dashboard breaker (prometheus_client raises on exact dups,
   but two attributes pointing at lookalike names would not);
2. every registered metric is documented in OBSERVABILITY.md;
3. every ``gubernator_*`` name OBSERVABILITY.md documents actually
   exists — a stale doc is how the metrics.py docstring drifted before.

Exit 0 when clean; prints each violation and exits 1 otherwise.
"""
from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DOC = os.path.join(REPO, "OBSERVABILITY.md")

#: sample suffixes prometheus_client appends — doc names are family
#: names, but a doc mentioning the exposition form shouldn't fail lint
_SUFFIXES = ("_total", "_created", "_bucket", "_count", "_sum", "_info")


def _canonical(name: str, reg_set) -> str:
    """Map a documented name to its registered family: exact match
    wins; otherwise strip ONE sample suffix if that base is registered
    (family names themselves may legitimately end in _count etc., so a
    blind strip would corrupt real names)."""
    if name in reg_set:
        return name
    for s in _SUFFIXES:
        if name.endswith(s) and name[: -len(s)] in reg_set:
            return name[: -len(s)]
    return name


def main() -> int:
    from gubernator_tpu.metrics import Metrics

    m = Metrics()
    registered = [fam.name for fam in m.registry.collect()]
    problems = []

    dups = {n for n in registered if registered.count(n) > 1}
    if dups:
        problems.append(f"duplicate metric names: {sorted(dups)}")

    with open(DOC, encoding="utf-8") as f:
        doc = f.read()
    reg_set = set(registered)
    # the lookahead drops path-like mentions ("gubernator_tpu/metrics.py")
    documented = {_canonical(n, reg_set) for n in re.findall(
        r"gubernator_[a-z0-9_]+(?![a-z0-9_/.])", doc)}

    for name in sorted(reg_set - documented):
        problems.append(
            f"metric {name!r} is registered in metrics.py but missing "
            f"from OBSERVABILITY.md")
    for name in sorted(documented - reg_set):
        problems.append(
            f"OBSERVABILITY.md documents {name!r} but no such metric "
            f"is registered (stale doc entry)")

    if problems:
        for p in problems:
            print(f"check_metrics: {p}", file=sys.stderr)
        return 1
    print(f"check_metrics: OK ({len(reg_set)} metrics, all documented)")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Offline zero-loss audit for candidate flagship shapes (round 5).

The on-chip A/B showed today's backend compiler serializes the step at
the CAP 2^25 + 16-probe flagship shape (0.35M dec/s) while 8-probe
shapes lower well clear up to CAP 2^27 (564M dec/s, bench cfg5).  To
move the flagship to an 8-probe shape WITHOUT giving back VERDICT-r3
item 9 (populate_errs == 0: the headline must serve 100% of its
working set), this script reproduces bench.py's EXACT populate — ids
0..N_KEYS-1 through _keyhash, inserted in B-sized chunks — on the CPU
backend (slot placement is backend-independent: same keys, same probe
sequence, same claim rounds) and reports the insert-failure count per
(CAP, probes) candidate.

    JAX_PLATFORMS=cpu python tools/populate_errs_check.py 25:8 26:8

Each argument is log2cap:probes.  Results → /tmp/populate_errs.json.
"""
import json
import os
import subprocess
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.abspath(os.path.join(_HERE, ".."))

OUT = "/tmp/populate_errs.json"


def run_one(log2cap: int, probes: int, n_keys: int, B: int) -> dict:
    """One candidate per child process: GUBER_PROBES is read at module
    import, so probe-window variants can't share an interpreter."""
    code = f"""
import json, time
import numpy as np
import jax, jax.numpy as jnp
jax.config.update("jax_platforms", "cpu")
import sys
sys.path.insert(0, {_REPO!r})
from bench import _keyhash, pad_chunk, _mk_batch
from gubernator_tpu.core.step import decide_batch_donated, PROBES
from gubernator_tpu.core.table import init_table

assert PROBES == {probes}, f"probe env plumbing failed: {{PROBES}}"
i64 = jnp.int64
cap, n_keys, B = 1 << {log2cap}, {n_keys}, {B}
st = init_table(cap)
ids = np.arange(n_keys, dtype=np.uint64)
now = jnp.asarray(1_760_000_000_000, i64)
errs = 0
t0 = time.time()
for a in range(0, n_keys, B):
    chunk = pad_chunk(ids[a:a + B], B)
    st, out = decide_batch_donated(
        st, _mk_batch(jnp, _keyhash(chunk)), now)
    errs += int(np.asarray(out.err).sum())
print(json.dumps({{"errs": errs, "seconds": round(time.time() - t0, 1),
                   "load": round(n_keys / cap, 3)}}))
"""
    env = dict(os.environ, GUBER_PROBES=str(probes), JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       stdout=subprocess.PIPE, timeout=7200)
    line = r.stdout.decode().strip().splitlines()[-1]
    return json.loads(line)


def main() -> int:
    n_keys = int(os.environ.get("GUBER_BENCH_KEYS", "10000000"))
    B = int(os.environ.get("GUBER_BENCH_B", "65536"))
    res = {"n_keys": n_keys, "B": B,
           "started": time.strftime("%Y-%m-%d %H:%M:%S")}
    for arg in sys.argv[1:] or ["25:8", "26:8"]:
        log2cap, probes = (int(x) for x in arg.split(":"))
        t = time.time()
        try:
            res[arg] = run_one(log2cap, probes, n_keys, B)
        except Exception as e:  # noqa: BLE001
            res[arg] = {"error": (str(e) or repr(e))[:300]}
        res[arg]["wall_s"] = round(time.time() - t, 1)
        with open(OUT, "w") as f:
            json.dump(res, f, indent=1)
        print(f"[populate_errs] {arg}: {res[arg]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

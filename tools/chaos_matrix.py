"""Chaos matrix (ISSUE 5): every faultpoint × {error, delay} against a
live in-proc cluster, with a JSON verdict table.

For each cell the matrix arms ONE faultpoint on daemon 0 of a 2-daemon
loopback cluster (snapshot/restore run against a solo MockLoader
instance), drives the code path that owns the point, and classifies the
outcome:

- ``served``            the operation completed with clean rows
- ``served_degraded``   completed, rows carry the degraded flag
- ``error_rows``        completed, rows carry error text (visible, loud)
- ``raised``            the operation raised ``FaultInjected`` (loud)
- ``aborted_tick``      an async tick saw the fault and aborted safely
- ``not_reached``       the armed point never fired on this host
                        (e.g. ``dispatch_sync`` without a pipelined
                        engine) — recorded, not counted as failure
- ``hung``              the operation exceeded its wall bound — FAILURE

A cell passes (``ok``) when it did not hang and a clean probe call
succeeds after the fault is cleared (recovery).  The point of the
matrix is the invariant the resilience layer promises: an injected
fault may degrade or fail loudly, but may never wedge the daemon or
leave it broken after the fault clears.

Usage::

    python tools/chaos_matrix.py [--json out.json] [--verbose]
    make chaos

The full matrix additionally runs the SLO breach→recover cells
(ISSUE 11): a sustained ``global_psum`` delay must latch a
``global_staleness`` breach and clear it after repair, and sustained
``peer_send`` faults must do the same for ``error_ratio`` — the chaos
proof that the burn-rate plane sees what the fault plane injects.

Exit 0 when every exercised cell is ok; 1 otherwise.  Tier-1-safe:
in-proc daemons, loopback only, a few seconds of wall time
(tests/test_resilience.py runs a smoke of the same harness).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DAY = 24 * 3_600_000
NOW0 = 1_780_000_000_000
WALL_S = 30.0  # per-cell bound: anything slower than this is a hang


def _serialize(reqs):
    from gubernator_tpu.proto import gubernator_pb2 as pb

    msg = pb.GetRateLimitsReq()
    for r in reqs:
        m = msg.requests.add()
        for f in ("name", "unique_key", "hits", "limit", "duration",
                  "burst"):
            setattr(m, f, getattr(r, f))
        m.algorithm = int(r.algorithm)
        m.behavior = int(r.behavior)
    return msg.SerializeToString()


def _one(key, hits=1, behavior=0):
    from gubernator_tpu.types import RateLimitRequest

    return _serialize([RateLimitRequest(
        name="chaos", unique_key=key, hits=hits, limit=10 ** 6,
        duration=DAY, behavior=behavior)])


class _Ctx:
    """The live fixture the drivers run against."""

    def __init__(self):
        from gubernator_tpu import cluster as cluster_mod
        from gubernator_tpu.config import BehaviorConfig

        self.c = cluster_mod.start(2, behaviors=BehaviorConfig(
            batch_timeout_ms=300, batch_wait_ms=50,
            peer_retry_limit=1, peer_retry_backoff_ms=5,
            peer_circuit_threshold=2, peer_circuit_cooldown_ms=200,
            global_sync_wait_ms=50))
        self.i0 = self.c.instance_at(0)
        self.addr1 = self.c.peer_at(1).grpc_address
        # a key owned by daemon 1 (remote from daemon 0's view) and one
        # owned by daemon 0
        self.remote_key = self.local_key = None
        for i in range(200):
            k = f"ck{i}"
            owner = self.c.owner_daemon_of("chaos_" + k)
            if owner is self.c.daemon_at(1) and self.remote_key is None:
                self.remote_key = k
            if owner is self.c.daemon_at(0) and self.local_key is None:
                self.local_key = k
            if self.remote_key and self.local_key:
                break
        assert self.remote_key and self.local_key
        # solo instance with a MockLoader for snapshot/restore points
        from gubernator_tpu.config import Config
        from gubernator_tpu.instance import V1Instance
        from gubernator_tpu.store import MockLoader

        cfg = Config(behaviors=BehaviorConfig())
        cfg.loader = MockLoader()
        self.solo = V1Instance(cfg)
        # solo mesh-mode instance (ISSUE 7): the collective reconcile
        # faultpoints (global_psum / global_accum_swap) live on its
        # GlobalManager tick
        self.mesh = V1Instance(Config(
            global_mode="mesh",
            behaviors=BehaviorConfig(global_sync_wait_ms=50)))
        # solo tiered instance (ISSUE 10): a device table capped far
        # below the keyspace so the cold tier and its migration
        # faultpoints (tier_promote / tier_demote) see real traffic
        # (1024 rows is the engine's per-shard floor — hence n=1)
        from gubernator_tpu.parallel import make_mesh

        self.tier = V1Instance(Config(
            cache_size=1024, cache_autogrow_max=1024, tier_cold=True,
            tier_promote_threshold=2, behaviors=BehaviorConfig()),
            mesh=make_mesh(n=1))
        self.tier_hits = {}  # unique_key → hits issued (conservation)
        self.tier_cell = 0  # fresh key namespace per driven cell

    def close(self):
        try:
            self.tier.close()
        finally:
            try:
                self.mesh.close()
            finally:
                try:
                    self.solo.close()
                finally:
                    self.c.stop()


def _classify_rows(data: bytes) -> str:
    from gubernator_tpu.proto import gubernator_pb2 as pb

    out = pb.GetRateLimitsResp.FromString(data)
    if any(r.error for r in out.responses):
        return "error_rows"
    if any(r.metadata.get("degraded") == "true" for r in out.responses):
        return "served_degraded"
    return "served"


# ---- drivers: one per faultpoint -------------------------------------------
# each returns an outcome string; FaultInjected escaping is normalized
# to "raised" by the harness


def _drive_forward(ctx: _Ctx) -> str:
    """peer_send / peer_recv / peer_circuit: a client batch whose key
    the ring owns remotely — the forward path."""
    return _classify_rows(ctx.i0.get_rate_limits_wire(
        _one(ctx.remote_key), now_ms=NOW0))


def _drive_ingest(ctx: _Ctx) -> str:
    return _classify_rows(ctx.i0.get_rate_limits_wire(
        _one(ctx.local_key), now_ms=NOW0))


def _drive_dispatch(ctx: _Ctx) -> str:
    """dispatch_enqueue / dispatch_launch / dispatch_sync /
    device_step: a local batch forced through the QUEUED wave path (the
    inline fast path bypasses the dispatcher queue, so occupy it)."""
    disp = ctx.i0.dispatcher
    box = {}

    def call():
        try:
            box["out"] = _classify_rows(ctx.i0.get_rate_limits_wire(
                _one(ctx.local_key), now_ms=NOW0))
        except BaseException as e:  # noqa: BLE001 - classified by harness
            box["err"] = e

    with disp._inline_mu:  # the call below must take the queued path
        th = threading.Thread(target=call)
        th.start()
        th.join(0.05)  # let it enqueue while inline is blocked
    th.join(WALL_S)
    if th.is_alive():
        return "hung"
    if "err" in box:
        raise box["err"]
    return box["out"]


def _drive_global(loop_attr: str):
    def drive(ctx: _Ctx) -> str:
        from gubernator_tpu.types import Behavior

        # queue GLOBAL work on daemon 0 (owner side for local_key,
        # non-owner for remote_key), then force the tick
        ctx.i0.get_rate_limits_wire(
            _one(ctx.local_key, behavior=int(Behavior.GLOBAL)),
            now_ms=NOW0)
        ctx.i0.get_rate_limits_wire(
            _one(ctx.remote_key, behavior=int(Behavior.GLOBAL)),
            now_ms=NOW0)
        gm = ctx.i0.global_manager
        before = ctx.i0.faults.describe()
        fired0 = sum(p["fired"] for p in before["points"])
        getattr(gm, loop_attr).poke()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            now = sum(p["fired"]
                      for p in ctx.i0.faults.describe()["points"])
            if now > fired0:
                return "aborted_tick"
            time.sleep(0.02)
        return "served"  # tick ran without reaching the point

    return drive


def _drive_mesh(ctx: _Ctx) -> str:
    """global_psum / global_accum_swap (ISSUE 7): GLOBAL traffic on the
    solo mesh-mode instance, then force the reconcile tick.  An error
    at either point aborts the tick with the accumulators intact
    (swap-back); ``_mesh_probe`` re-verifies exact conservation after
    the harness clears the fault."""
    from gubernator_tpu.types import Behavior

    inst = ctx.mesh
    inst.get_rate_limits_wire(
        _one("meshkey", behavior=int(Behavior.GLOBAL)), now_ms=NOW0)
    fired0 = sum(p["fired"] for p in inst.faults.describe()["points"])
    inst.global_manager.poke()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if sum(p["fired"]
               for p in inst.faults.describe()["points"]) > fired0:
            return "aborted_tick"
        time.sleep(0.02)
    return "served"  # tick ran without reaching the point


def _mesh_probe(ctx: _Ctx) -> bool:
    """Post-clear recovery for the mesh cells: one clean reconcile
    tick must fold EVERY accumulated hit — folded == injected is the
    conservation oracle the collective path promises even after an
    injected swap/psum failure (nothing stranded, nothing doubled)."""
    inst = ctx.mesh
    try:
        inst._mesh_reconcile_tick()
        mge = inst._meshglobal
        if mge is None:
            return False
        mge.drain()
        return mge.folded_hits == mge.injected_hits
    except Exception:  # noqa: BLE001 - a raising probe is a failure
        return False


def _drive_mr(ctx: _Ctx) -> str:
    """mr_sync (ISSUE 7 satellite): multiregion reconciliation had
    zero fault coverage.  Queue MR hits, force the tick; an ERROR
    fault aborts BEFORE the queues pop, so the aggregate must survive
    intact (the conservation assertion) — a DELAY fault lets the tick
    proceed and consume the queue normally."""
    from gubernator_tpu.types import Behavior, RateLimitRequest

    inst = ctx.i0
    mr = inst._ensure_mr_manager()
    mr.queue_hits(RateLimitRequest(
        name="chaos", unique_key="mrkey", hits=7, limit=10 ** 6,
        duration=DAY, behavior=Behavior.MULTI_REGION))
    fired0 = sum(p["fired"] for p in inst.faults.describe()["points"])
    mr.poke()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if sum(p["fired"]
               for p in inst.faults.describe()["points"]) > fired0:
            time.sleep(0.1)  # let the tick finish either way
            with mr._mu:
                kept = {k: acc for k, (_r, acc, _s) in mr._hits.items()}
            if not kept:
                return "served"  # delay mode: flushed normally
            if kept.get("chaos_mrkey") != 7:
                # popped-but-partial would be a conservation loss
                return f"unexpected:queue_lost {kept}"
            return "aborted_tick"
        time.sleep(0.02)
    return "served"


def _drive_snapshot(ctx: _Ctx) -> str:
    ctx.solo.get_rate_limits_wire(_one("snapkey"), now_ms=NOW0)
    ctx.solo._save_to_loader()
    return "served"


def _drive_restore(ctx: _Ctx) -> str:
    ctx.solo._load_from_loader()
    return "served"


def _drive_tier(ctx: _Ctx) -> str:
    """tier_promote / tier_demote (ISSUE 10): overflow the 1024-row
    device table with a cell-fresh keyspace so keys land in the cold
    tier, then hammer a band of cold keys past the admission
    threshold — every promotion (and the demotion it triggers on the
    full table) crosses the armed faultpoint.  An ERROR fault must
    abort the migration cleanly: the row stays in its source tier and
    serving continues without error rows."""
    from gubernator_tpu.hashing import hash_key
    from gubernator_tpu.types import RateLimitRequest

    ctx.tier_cell += 1
    ns = f"t{ctx.tier_cell}k"

    def hit(key, hits=1):
        ctx.tier_hits[key] = ctx.tier_hits.get(key, 0) + hits
        return RateLimitRequest(name="chaos", unique_key=key, hits=hits,
                                limit=10 ** 6, duration=DAY)

    inst = ctx.tier
    for base in range(0, 2048, 512):
        out = inst.get_rate_limits(
            [hit(f"{ns}{i}") for i in range(base, base + 512)],
            now_ms=NOW0)
        if any(r.error for r in out):
            return "error_rows"
    cold = [i for i in range(2048) if inst._tier.peek_row(
        hash_key("chaos", f"{ns}{i}")) is not None][:8]
    if not cold:
        return "unexpected:no_cold_rows"
    for _ in range(6):  # past the threshold → promote (+ demote)
        out = inst.get_rate_limits([hit(f"{ns}{i}") for i in cold],
                                   now_ms=NOW0)
        if any(r.error for r in out):
            return "error_rows"
        time.sleep(0.1)  # let the async rank feed fold the wave
    return "served"


def _tier_probe(ctx: _Ctx) -> bool:
    """Post-fault oracle for the tier cells: EXACT conservation across
    every key ever driven, wherever its row now lives (device or cold,
    including rows whose migration the fault aborted mid-flight)."""
    from gubernator_tpu.types import RateLimitRequest

    for k, n in ctx.tier_hits.items():
        r = ctx.tier.get_rate_limits([RateLimitRequest(
            name="chaos", unique_key=k, hits=0, limit=10 ** 6,
            duration=DAY)], now_ms=NOW0)[0]
        if r.error or r.remaining != 10 ** 6 - n:
            return False
    return True


def _probe(ctx: _Ctx) -> bool:
    """Clean-path probe after clearing a fault: both a local and a
    forwarded row must serve without error rows."""
    try:
        a = _classify_rows(ctx.i0.get_rate_limits_wire(
            _one(ctx.local_key, hits=0), now_ms=NOW0 + 5_000))
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            b = _classify_rows(ctx.i0.get_rate_limits_wire(
                _one(ctx.remote_key, hits=0), now_ms=NOW0 + 5_000))
            if a == "served" and b == "served":
                return True
            time.sleep(0.1)  # circuit cooldown / readmit settling
        return False
    except Exception:  # noqa: BLE001 - a raising probe is a failure
        return False


#: point → (driver, where to arm: "cluster" daemon-0 instance or "solo")
MATRIX = {
    "peer_send": (_drive_forward, "cluster"),
    "peer_recv": (_drive_forward, "cluster"),
    "peer_circuit": (_drive_forward, "cluster"),
    "dispatch_enqueue": (_drive_dispatch, "cluster"),
    "dispatch_launch": (_drive_dispatch, "cluster"),
    "dispatch_sync": (_drive_dispatch, "cluster"),
    # the racer's preemption points (ISSUE 6): exercised by the same
    # dispatch driver — error fails the wave's callers, delay widens
    # the merge/carry/splice windows (tools/racer.py leans on these)
    "dispatch_merge": (_drive_dispatch, "cluster"),
    "dispatch_carry": (_drive_dispatch, "cluster"),
    "dispatch_splice": (_drive_dispatch, "cluster"),
    "device_step": (_drive_dispatch, "cluster"),
    "wire_ingest": (_drive_ingest, "cluster"),
    "global_broadcast": (_drive_global("_bcast_loop"), "cluster"),
    "global_hits": (_drive_global("_hits_loop"), "cluster"),
    # mesh-GLOBAL collective reconcile (ISSUE 7): armed on the solo
    # mesh-mode instance; each cell re-verifies exact conservation
    # after the fault clears
    "global_psum": (_drive_mesh, "mesh"),
    "global_accum_swap": (_drive_mesh, "mesh"),
    # multiregion reconciliation (ISSUE 7 satellite: ROADMAP flagged
    # zero fault coverage) — abort-before-pop keeps the queue intact
    "mr_sync": (_drive_mr, "cluster"),
    "snapshot": (_drive_snapshot, "solo"),
    "restore": (_drive_restore, "solo"),
    # tiered key store (ISSUE 10): armed on the capped solo instance;
    # the probe re-verifies exact conservation over every key driven
    "tier_promote": (_drive_tier, "tier"),
    "tier_demote": (_drive_tier, "tier"),
}

MODES = ("error", "delay")


# ---- SLO breach→recover cells (ISSUE 11) -----------------------------------
# The point×mode matrix proves a fault can't wedge the daemon; these
# cells prove the SLO plane SEES a sustained fault and un-sees its
# repair: the burn-rate engine must latch a breach while the fault
# holds and emit the matching recovery once it clears.  Run on the
# full matrix only (`make chaos`) — they cost real wall time (burn
# windows are wall-clock even at the 1s/2s chaos settings).

#: wall-clock window overrides for the SLO cells: tight enough that a
#: breach latches within a couple of folds and recovery within ~2 s
_SLO_ENV = {"GUBER_SLO_FAST": "1s", "GUBER_SLO_SLOW": "2s",
            "GUBER_SLO_TICK": "100ms", "GUBER_SLO_P99_MS": "60000"}


def _slo_events(inst, kind: str, slo: str) -> bool:
    return any(e.get("kind") == kind and e.get("slo") == slo
               for e in inst.recorder.events())


def _slo_staleness_cell() -> dict:
    """global_psum:delay → mesh-GLOBAL folds run late → measured
    coherence staleness exceeds 2× the reconcile interval →
    ``global_staleness`` breaches; clearing the fault and folding
    cleanly must emit ``slo_recovered``."""
    from gubernator_tpu.config import BehaviorConfig, Config
    from gubernator_tpu.instance import V1Instance
    from gubernator_tpu.types import Behavior

    spec = "global_psum:delay:400ms"
    cell = {"cell": "slo_staleness", "slo": "global_staleness",
            "spec": spec}
    t0 = time.perf_counter()
    inst = V1Instance(Config(
        global_mode="mesh",
        behaviors=BehaviorConfig(global_sync_wait_ms=100)))
    try:
        def drive():
            inst.get_rate_limits_wire(_one(
                "slokey", behavior=int(Behavior.GLOBAL)), now_ms=NOW0)
            inst._mesh_reconcile_tick()
            inst.slo.tick()

        drive()  # clean fold: the healthy baseline sample
        inst.faults.arm(spec, seed=7)
        deadline = time.monotonic() + 15.0
        breached = False
        while time.monotonic() < deadline and not breached:
            drive()  # each fold lands ≥400ms stale (target: 200ms)
            breached = _slo_events(inst, "slo_breach",
                                   "global_staleness")
        inst.faults.clear()
        recovered = False
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline and breached and not recovered:
            drive()  # clean folds: staleness back under target
            recovered = _slo_events(inst, "slo_recovered",
                                    "global_staleness")
            time.sleep(0.1)  # let the bad ticks age out of the window
    finally:
        inst.close()
    cell.update({"breached": breached, "recovered": recovered,
                 "elapsed_ms": round((time.perf_counter() - t0) * 1000,
                                     1),
                 "ok": breached and recovered})
    return cell


def _slo_error_ratio_cell() -> dict:
    """peer_send:error → every forwarded row degrades (or errors) →
    ``error_ratio`` burns past threshold and breaches; clearing the
    fault and serving clean traffic must emit ``slo_recovered``.
    The driven requests run under a trace context, so the degraded
    outcomes force-sample and the breach event must carry an
    ``exemplar_trace`` (ISSUE 12: page → waterfall in one hop)."""
    from gubernator_tpu import cluster as cluster_mod
    from gubernator_tpu.config import BehaviorConfig
    from gubernator_tpu.tracing import request_context

    spec = "peer_send:error"
    cell = {"cell": "slo_error_ratio", "slo": "error_ratio",
            "spec": spec}
    t0 = time.perf_counter()
    c = cluster_mod.start(2, behaviors=BehaviorConfig(
        batch_timeout_ms=300, batch_wait_ms=50,
        peer_retry_limit=1, peer_retry_backoff_ms=5,
        peer_circuit_threshold=2, peer_circuit_cooldown_ms=200))
    try:
        i0 = c.instance_at(0)
        remote = local = None
        for i in range(200):
            k = f"sk{i}"
            owner = c.owner_daemon_of("chaos_" + k)
            if owner is c.daemon_at(1) and remote is None:
                remote = k
            if owner is c.daemon_at(0) and local is None:
                local = k
            if remote and local:
                break
        ana = i0.dispatcher.analytics

        def drive(key):
            with request_context(None, recorder=i0.span_recorder):
                i0.get_rate_limits_wire(_one(key), now_ms=NOW0)
            if ana is not None:
                ana.flush(timeout=2.0)  # land the RED taps
            i0.slo.tick()

        drive(local)  # clean baseline sample
        i0.faults.arm(spec, seed=7)
        deadline = time.monotonic() + 15.0
        breached = False
        while time.monotonic() < deadline and not breached:
            drive(remote)  # forwarded row degrades/errors
            breached = _slo_events(i0, "slo_breach", "error_ratio")
        i0.faults.clear()
        recovered = False
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline and breached and not recovered:
            drive(local)  # clean rows dilute + age out the window
            recovered = _slo_events(i0, "slo_recovered", "error_ratio")
            time.sleep(0.1)
        exemplar = any(
            e.get("kind") == "slo_breach"
            and e.get("slo") == "error_ratio"
            and e.get("exemplar_trace")
            for e in i0.recorder.events())
    finally:
        c.stop()
    cell.update({"breached": breached, "recovered": recovered,
                 "exemplar": exemplar,
                 "elapsed_ms": round((time.perf_counter() - t0) * 1000,
                                     1),
                 "ok": breached and recovered and exemplar})
    return cell


def _memory_pressure_cell() -> dict:
    """tier churn against a capped hot table → byte-weighted occupancy
    climbs past GUBER_MEM_PRESSURE → ``hbm_pressure`` breaches while
    the rows are live, with the breach carrying an ``exemplar_trace``
    (the driven churn runs sampled, ISSUE 12 wiring); sweeping the
    expired churn keys drains occupancy and the engine must emit the
    matching ``slo_recovered`` (ISSUE 13)."""
    from gubernator_tpu.config import Config
    from gubernator_tpu.instance import V1Instance
    from gubernator_tpu.tracing import request_context
    from gubernator_tpu.types import RateLimitRequest

    cell = {"cell": "memory_pressure", "slo": "hbm_pressure",
            "spec": "tier_churn_vs_4k_cap"}
    t0 = time.perf_counter()
    # a target the churn phase clears decisively even where probe
    # exhaustion tops the open-addressed table out below 100% load
    prev = os.environ.get("GUBER_MEM_PRESSURE")
    os.environ["GUBER_MEM_PRESSURE"] = "0.6"
    try:
        inst = V1Instance(Config(
            cache_size=4096, cache_autogrow_max=4096,
            tier_cold=True, tier_promote_threshold=2,
            hot_set_capacity=0, sweep_interval_ms=0))
    finally:
        if prev is None:
            os.environ.pop("GUBER_MEM_PRESSURE", None)
        else:
            os.environ["GUBER_MEM_PRESSURE"] = prev
    try:
        inst.span_recorder.sample = 1.0  # every churn batch commits a
        # sampled trace, so the breach tick has an exemplar to link
        now = NOW0
        nkey = 0

        def churn(n=500):
            nonlocal now, nkey
            reqs = [RateLimitRequest(
                name="chaos", unique_key=f"mp{nkey + i}", hits=1,
                limit=10 ** 6, duration=30_000)
                for i in range(n)]
            nkey += n
            now += 1
            with request_context(None, recorder=inst.span_recorder):
                inst.get_rate_limits(reqs, now_ms=now)
            inst.slo.tick()

        churn(64)  # healthy baseline sample: occupancy well under target
        deadline = time.monotonic() + 15.0
        breached = False
        while time.monotonic() < deadline and not breached:
            churn()  # distinct 30s-lived keys: occupancy only climbs
            breached = _slo_events(inst, "slo_breach", "hbm_pressure")
        # relieve: everything driven above has expired; one sweep
        # reclaims the rows and occupancy collapses to ~zero
        now += 60_000
        recovered = False
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline and breached and not recovered:
            with inst._engine_mu:
                inst.engine.sweep(now)
            inst.slo.tick()
            recovered = _slo_events(inst, "slo_recovered",
                                    "hbm_pressure")
            time.sleep(0.1)  # let the bad ticks age out of the window
        exemplar = any(
            e.get("kind") == "slo_breach"
            and e.get("slo") == "hbm_pressure"
            and e.get("exemplar_trace")
            for e in inst.recorder.events())
        pressure, target = inst.memledger.pressure_sample()
    finally:
        inst.close()
    cell.update({"breached": breached, "recovered": recovered,
                 "exemplar": exemplar,
                 "final_pressure": round(pressure, 4), "target": target,
                 "elapsed_ms": round((time.perf_counter() - t0) * 1000,
                                     1),
                 "ok": breached and recovered and exemplar})
    return cell


def _trace_plane_cell() -> dict:
    """peer_send:error → the forwarded request serves degraded, its
    trace force-samples, and the CALLER-side slice still assembles
    end-to-end (request span → ``peer.forward`` hop → local degraded
    wave); after clearing the fault, a healthy forwarded request
    stitches ACROSS daemons — the owner's handler + wave spans hang
    under the caller's request span (ISSUE 12 acceptance shape)."""
    from gubernator_tpu import cluster as cluster_mod
    from gubernator_tpu.config import BehaviorConfig
    from gubernator_tpu.tracing import (assemble, current_trace_id,
                                        request_context, span)

    spec = "peer_send:error"
    cell = {"cell": "trace_plane", "spec": spec}
    t0 = time.perf_counter()
    c = cluster_mod.start(2, behaviors=BehaviorConfig(
        batch_timeout_ms=300, batch_wait_ms=50,
        peer_retry_limit=1, peer_retry_backoff_ms=5,
        peer_circuit_threshold=2, peer_circuit_cooldown_ms=200))
    try:
        i0, i1 = c.instance_at(0), c.instance_at(1)
        remote = None
        for i in range(200):
            k = f"tk{i}"
            if c.owner_daemon_of("chaos_" + k) is c.daemon_at(1):
                remote = k
                break
        assert remote
        r0, r1 = i0.span_recorder, i1.span_recorder
        old_sample = (r0.sample, r1.sample)
        r0.sample = r1.sample = 1.0

        def names(node, acc):
            acc.add(node["name"])
            for ch in node.get("children", []):
                names(ch, acc)
            return acc

        def drive():
            with request_context(None, recorder=r0):
                with span("grpc.GetRateLimits"):
                    tid = current_trace_id()
                    data = i0.get_rate_limits_wire(_one(remote),
                                                   now_ms=NOW0)
            return tid, _classify_rows(data)

        def assembled(tid, spans, want):
            traces = assemble(spans, trace_id=tid)
            if len(traces) != 1 or len(traces[0]["roots"]) != 1:
                return False  # still waiting on late wave spans
            root = traces[0]["roots"][0]
            return (root["name"] == "grpc.GetRateLimits"
                    and want <= names(root, set()))

        degraded_assembled = stitched = False
        try:
            i0.faults.arm(spec, seed=7)
            deadline = time.monotonic() + 15.0
            while (time.monotonic() < deadline
                   and not degraded_assembled):
                tid, outcome = drive()
                if outcome != "served_degraded":
                    continue
                # the degraded wave lands from the dispatcher thread;
                # poll until the caller slice holds the whole chain
                sub = time.monotonic() + 2.0
                while (time.monotonic() < sub
                       and not degraded_assembled):
                    degraded_assembled = assembled(
                        tid, r0.spans(),
                        {"peer.forward", "wave"})
                    if not degraded_assembled:
                        time.sleep(0.05)
            i0.faults.clear()
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline and not stitched:
                time.sleep(0.25)  # let the peer circuit half-open
                tid, outcome = drive()
                if outcome != "served":
                    continue
                sub = time.monotonic() + 2.0
                while time.monotonic() < sub and not stitched:
                    stitched = assembled(
                        tid, r0.spans() + r1.spans(),
                        {"peer.forward", "grpc.GetPeerRateLimits",
                         "wave"})
                    if not stitched:
                        time.sleep(0.05)
        finally:
            r0.sample, r1.sample = old_sample
    finally:
        c.stop()
    cell.update({"degraded_assembled": degraded_assembled,
                 "stitched": stitched,
                 "elapsed_ms": round((time.perf_counter() - t0) * 1000,
                                     1),
                 "ok": degraded_assembled and stitched})
    return cell


def _fleet_conservation_cell() -> dict:
    """peer_send:error partition → GLOBAL flushes to the owner fail
    and requeue → the daemons' OWN audit vectors (instance.audit_doc,
    the same document GET /debug/audit serves — no test-harness
    walking) show nonzero fleet drift and the ``fleet_conservation``
    SLO breaches once the backlog outlives its flush-window bound;
    healing the partition must drain the drift to EXACTLY zero and
    emit ``slo_recovered`` (ISSUE 19 acceptance)."""
    from gubernator_tpu import cluster as cluster_mod
    from gubernator_tpu import fleet
    from gubernator_tpu.config import BehaviorConfig
    from gubernator_tpu.types import Behavior

    spec = "peer_send:error"
    cell = {"cell": "fleet_conservation", "slo": "fleet_conservation",
            "spec": spec}
    t0 = time.perf_counter()
    c = cluster_mod.start(3, behaviors=BehaviorConfig(
        batch_timeout_ms=300, batch_wait_ms=50,
        peer_retry_limit=1, peer_retry_backoff_ms=5,
        peer_circuit_threshold=2, peer_circuit_cooldown_ms=200,
        global_sync_wait_ms=100))
    try:
        i0 = c.instance_at(0)
        remote = None
        for i in range(200):
            k = f"fc{i}"
            if c.owner_daemon_of("chaos_" + k) is not c.daemon_at(0):
                remote = k
                break
        assert remote

        def fold():
            return fleet.fold_audits(
                [c.instance_at(i).audit_doc() for i in range(3)])

        def drive():
            i0.get_rate_limits_wire(_one(
                remote, behavior=int(Behavior.GLOBAL)), now_ms=NOW0)
            gm = i0.global_manager
            if gm is not None:
                gm.poke()
            i0.slo.tick()

        drive()  # clean baseline: flush lands, drift drains
        i0.faults.arm(spec, seed=7)
        deadline = time.monotonic() + 15.0
        drift_seen = breached = False
        while time.monotonic() < deadline \
                and not (drift_seen and breached):
            drive()  # flush fails → requeue → backlog holds nonzero
            drift_seen = drift_seen or fold()["drift"] > 0
            breached = _slo_events(i0, "slo_breach",
                                   "fleet_conservation")
            time.sleep(0.05)
        i0.faults.clear()
        recovered = drained = False
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline and breached \
                and not (recovered and drained):
            drive()  # circuit half-opens, flush lands, backlog drains
            f = fold()
            drained = f["conserved"] and f["totals"]["injected"] > 0
            recovered = _slo_events(i0, "slo_recovered",
                                    "fleet_conservation")
            time.sleep(0.1)
        final = fold()
    finally:
        c.stop()
    cell.update({"drift_seen": drift_seen, "breached": breached,
                 "recovered": recovered, "drained": drained,
                 "final_drift": final["drift"],
                 "elapsed_ms": round((time.perf_counter() - t0) * 1000,
                                     1),
                 "ok": (drift_seen and breached and recovered
                        and drained and final["drift"] == 0)})
    return cell


def _fleet_ring_divergence_cell() -> dict:
    """sustained peer_send:error holds a peer's circuit open past
    ``peer_eject_after_ms`` → the routing gate ejects it → the audit
    docs' ring views disagree (routing != membership) and the fleet
    watch emits ``fleet_ring_divergence``; clearing the fault lets the
    peer recover and readmit, and the watch must emit the matching
    ``fleet_ring_converged`` (ISSUE 19 satellite)."""
    from gubernator_tpu import cluster as cluster_mod
    from gubernator_tpu import fleet
    from gubernator_tpu.config import BehaviorConfig

    spec = "peer_send:error"
    cell = {"cell": "fleet_ring_divergence", "spec": spec}
    t0 = time.perf_counter()
    c = cluster_mod.start(2, behaviors=BehaviorConfig(
        batch_timeout_ms=300, batch_wait_ms=50,
        peer_retry_limit=1, peer_retry_backoff_ms=5,
        peer_circuit_threshold=2, peer_circuit_cooldown_ms=250,
        peer_eject_after_ms=300, peer_readmit_after_ms=250))
    try:
        i0 = c.instance_at(0)
        remote = None
        for i in range(200):
            k = f"rd{i}"
            if c.owner_daemon_of("chaos_" + k) is c.daemon_at(1):
                remote = k
                break
        assert remote
        watch = fleet.RingWatch()

        def check():
            # the fleet tick: fold the daemons' own ring views; the
            # watch records divergence/convergence edges into daemon
            # 0's flight recorder
            return watch.check(
                [c.instance_at(i).audit_doc() for i in range(2)],
                recorder=i0.recorder)

        def fired(kind):
            return any(e.get("kind") == kind
                       for e in i0.recorder.events())

        assert check()["consistent"]
        i0.faults.arm(spec, seed=7)
        deadline = time.monotonic() + 15.0
        diverged = False
        while time.monotonic() < deadline and not diverged:
            # forwarded traffic trips the circuit; routing lookups
            # derive the gated picker, ejecting the dead peer
            i0.get_rate_limits_wire(_one(remote), now_ms=NOW0)
            diverged = not check()["consistent"] \
                and fired("fleet_ring_divergence")
            time.sleep(0.05)
        i0.faults.clear()
        converged = False
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline and diverged and not converged:
            # light traffic half-opens the circuit; once recovered
            # past readmit the gate clears and the views re-agree
            i0.get_rate_limits_wire(_one(remote), now_ms=NOW0)
            converged = check()["consistent"] \
                and fired("fleet_ring_converged")
            time.sleep(0.1)
    finally:
        c.stop()
    cell.update({"diverged": diverged, "converged": converged,
                 "elapsed_ms": round((time.perf_counter() - t0) * 1000,
                                     1),
                 "ok": diverged and converged})
    return cell


def run_slo_cells(verbose=False) -> list:
    old = {k: os.environ.get(k) for k in _SLO_ENV}
    os.environ.update(_SLO_ENV)
    cells = []
    try:
        for fn in (_slo_staleness_cell, _slo_error_ratio_cell,
                   _memory_pressure_cell, _trace_plane_cell,
                   _fleet_conservation_cell,
                   _fleet_ring_divergence_cell):
            cell = fn()
            cells.append(cell)
            if verbose:
                print(json.dumps(cell), file=sys.stderr)
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return cells


def run_scenario_cells(verbose=False) -> list:
    """Generated cells from the scenario lab (ISSUE 16): every spec in
    the committed library runs in fast mode and contributes one cell —
    its oracle verdicts ARE the cell verdict.  Adding a scenario file
    grows the chaos matrix with no code here."""
    from gubernator_tpu import scenarios as scn

    cells = []
    for spec in scn.load_library():
        try:
            row = scn.ScenarioRunner(spec, fast=True).run(fast=True)
            cell = {"cell": f"scenario:{spec.name}",
                    "stack": row["stack"], "ok": row["ok"],
                    "requests": row["requests"],
                    "error_rows": row["error_rows"],
                    "oracles": {k: v["ok"]
                                for k, v in row["oracles"].items()}}
        except Exception as e:  # noqa: BLE001 - recorded verdict
            cell = {"cell": f"scenario:{spec.name}", "ok": False,
                    "error": (str(e) or repr(e))[:200]}
        cells.append(cell)
        if verbose:
            print(json.dumps(cell), file=sys.stderr)
    return cells


def run_matrix(points=None, verbose=False) -> dict:
    from gubernator_tpu.faults import FAULT_POINTS, FaultInjected

    missing = set(FAULT_POINTS) - set(MATRIX)
    assert not missing, f"faultpoints without a matrix driver: {missing}"
    ctx = _Ctx()
    cells = []
    try:
        for point, (driver, where) in MATRIX.items():
            if points and point not in points:
                continue
            inst = {"solo": ctx.solo, "mesh": ctx.mesh,
                    "tier": ctx.tier}.get(where, ctx.i0)
            for mode in MODES:
                spec = (f"{point}:delay:5ms" if mode == "delay"
                        else f"{point}:error")
                inst.faults.arm(spec, seed=7)
                t0 = time.perf_counter()
                try:
                    outcome = driver(ctx)
                except FaultInjected:
                    outcome = "raised"
                except Exception as e:  # noqa: BLE001 - recorded verdict
                    outcome = f"unexpected:{type(e).__name__}"
                elapsed = time.perf_counter() - t0
                fired = sum(p["fired"]
                            for p in inst.faults.describe()["points"])
                inst.faults.clear()
                if fired == 0:
                    outcome = "not_reached"
                if where == "cluster":
                    recovered = _probe(ctx)
                elif where == "mesh":
                    recovered = _mesh_probe(ctx)
                elif where == "tier":
                    recovered = _tier_probe(ctx)
                else:
                    recovered = True
                ok = (outcome != "hung"
                      and not outcome.startswith("unexpected")
                      and recovered)
                cell = {"point": point, "mode": mode, "spec": spec,
                        "outcome": outcome, "fired": fired,
                        "elapsed_ms": round(elapsed * 1000, 1),
                        "recovered": recovered, "ok": ok}
                cells.append(cell)
                if verbose:
                    print(json.dumps(cell), file=sys.stderr)
    finally:
        ctx.close()
    # SLO breach→recover cells and generated scenario cells ride the
    # FULL matrix only (`make chaos`): a --point / smoke subset stays
    # fast
    slo_cells = run_slo_cells(verbose=verbose) if not points else []
    scenario_cells = (run_scenario_cells(verbose=verbose)
                      if not points else [])
    exercised = [c for c in cells if c["outcome"] != "not_reached"]
    return {
        "cells": cells,
        "slo_cells": slo_cells,
        "scenario_cells": scenario_cells,
        "exercised": len(exercised),
        "not_reached": [f"{c['point']}:{c['mode']}" for c in cells
                        if c["outcome"] == "not_reached"],
        "failed": ([f"{c['point']}:{c['mode']}" for c in cells
                    if not c["ok"]]
                   + [c["cell"] for c in slo_cells if not c["ok"]]
                   + [c["cell"] for c in scenario_cells
                      if not c["ok"]]),
        "ok": (all(c["ok"] for c in cells)
               and all(c["ok"] for c in slo_cells)
               and all(c["ok"] for c in scenario_cells)),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="run the faultpoint × mode chaos matrix")
    ap.add_argument("--json", default=None,
                    help="also write the verdict table to this path")
    ap.add_argument("--point", action="append", default=None,
                    help="restrict to these faultpoints (repeatable)")
    ap.add_argument("--verbose", action="store_true",
                    help="stream per-cell verdicts to stderr")
    args = ap.parse_args(argv)
    verdict = run_matrix(points=args.point, verbose=args.verbose)
    doc = json.dumps(verdict, indent=2)
    print(doc)
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            f.write(doc + "\n")
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

"""Scenario lab CLI (ISSUE 16): run the replayable workload library.

Runs every spec in the library directory (or a named subset) through
``gubernator_tpu.scenarios.ScenarioRunner`` and prints a verdict table:
per-scenario oracle results, decision digest, Jain's index where the
fairness oracle ran.  Exit 0 when every scenario's oracles pass.

Usage::

    python tools/scenario_lab.py                    # full library
    python tools/scenario_lab.py --fast             # CI-speed subset
    python tools/scenario_lab.py --only partition_reconcile
    python tools/scenario_lab.py --list             # specs + catalogs
    python tools/scenario_lab.py --json out.json    # machine verdict
    make scenarios                                  # --fast, in check

Environment: ``GUBER_SCENARIO_DIR`` relocates the library,
``GUBER_SCENARIO_FAST=1`` forces ``--fast``, ``GUBER_SCENARIO_SEED``
overrides every spec's seed (for sweeps).  The same document shape is
recorded by ``bench.py`` as the ``15_scenarios`` row, so a scenario
added here shows up in the BENCH trajectory and ``make bench-diff``
with no extra wiring.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="run the scenario-lab workload library")
    ap.add_argument("--dir", default=None,
                    help="spec library (default: GUBER_SCENARIO_DIR "
                         "or scenarios/)")
    ap.add_argument("--only", action="append", default=None,
                    metavar="NAME", help="run only these spec names "
                    "(repeatable)")
    ap.add_argument("--fast", action="store_true",
                    help="apply each spec's fast-mode overrides")
    ap.add_argument("--json", metavar="PATH",
                    help="write the aggregate verdict document here")
    ap.add_argument("--list", action="store_true",
                    help="list specs, source kinds and oracles; no run")
    args = ap.parse_args(argv)

    from gubernator_tpu import scenarios as scn

    specs = scn.load_library(args.dir)
    if args.only:
        known = {s.name for s in specs}
        missing = set(args.only) - known
        if missing:
            print(f"unknown scenario(s): {sorted(missing)} "
                  f"(library has {sorted(known)})")
            return 2
        specs = [s for s in specs if s.name in args.only]

    if args.list:
        print(f"library: {args.dir or scn.default_scenario_dir()}")
        for s in specs:
            print(f"  {s.name:24s} stack={s.stack:9s} "
                  f"oracles={','.join(s.oracles)}")
        print("source kinds:")
        for k, v in scn.SOURCE_KINDS.items():
            print(f"  {k:12s} {v}")
        print("oracles:")
        for k, v in scn.ORACLE_KINDS.items():
            print(f"  {k:14s} {v}")
        return 0

    fast = args.fast or scn.env_fast()
    doc = scn.run_scenarios(
        specs, fast=fast,
        progress=lambda s: print(f"-- {s.name} ({s.stack}) ...",
                                 flush=True))
    for name, row in doc["scenarios"].items():
        mark = "ok " if row["ok"] else "FAIL"
        extra = ""
        if "jain_index" in row:
            extra = f" jain={row['jain_index']}"
        print(f"  {mark} {name:24s} reqs={row['requests']:<6d} "
              f"digest={row['decision_digest'][:12]}"
              f" oracles=[{' '.join(k + ('+' if v['ok'] else '!') for k, v in row['oracles'].items())}]"
              f"{extra}")
    print(f"{doc['count']} scenarios, all_ok={doc['all_ok']}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.json}")
    return 0 if doc["all_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

"""One-question on-chip probe: is the donated step still pathological
at CAP >= 2^22 after the unique-indices scatter change?

Cheapest possible answer (one compile + populate + 32 reps, ~5 min
cold): run this FIRST in a live tunnel window, before tpu_session.py —
if ms_per_step is back near the round-2 0.45 ms @ 2^22, the full
battery's capacity sweep and bench will inherit the fix; if it still
reads ~217 ms, the Pallas floor is the headline plan and the battery
should still run (its duel covers all three modes).

Usage: timeout 1200 python tools/cap_ab.py [log2cap]
Writes /tmp/cap_ab.json; copy into artifacts/ and commit.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
import _jax_cache

_jax_cache.setup()


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench import _keyhash as keyhash, pad_chunk
    from gubernator_tpu.core.batch import RequestBatch
    from gubernator_tpu.core.step import decide_batch_donated
    from gubernator_tpu.core.table import init_table

    log2cap = int(sys.argv[1]) if len(sys.argv) > 1 else 22
    cap, n_keys = 1 << log2cap, (1 << log2cap) // 2
    B = 65536
    i64 = jnp.int64
    out_path = "/tmp/cap_ab.json"
    res = {"backend": jax.default_backend(), "cap": cap, "n_keys": n_keys,
           "B": B, "started": time.strftime("%Y-%m-%d %H:%M:%S"),
           "ksplit": int(os.environ.get("GUBER_KSPLIT", "0")),
           "probes": int(os.environ.get("GUBER_PROBES", "8"))}

    def dump():
        with open(out_path, "w") as f:
            json.dump(res, f, indent=1)

    dump()
    if res["backend"] != "tpu":
        res["abort"] = "not tpu"
        dump()
        return 1

    rng = np.random.default_rng(42)

    def mk(keys):
        n = keys.shape[0]
        return RequestBatch(
            key=jnp.asarray(keys), hits=jnp.ones(n, i64),
            limit=jnp.full(n, 100, i64), duration=jnp.full(n, 10_000, i64),
            eff_ms=jnp.full(n, 10_000, i64), greg_end=jnp.zeros(n, i64),
            behavior=jnp.zeros(n, jnp.int32),
            algorithm=jnp.zeros(n, jnp.int32),
            burst=jnp.full(n, 100, i64), valid=jnp.ones(n, bool))

    NOW = 1_760_000_000_000
    bump = jax.jit(lambda t: t + 1)
    now0 = jnp.asarray(NOW, i64)
    bump(now0).block_until_ready()

    st = init_table(cap)
    batches = [mk(keyhash((rng.zipf(1.1, size=B) % n_keys)
                          .astype(np.uint64))) for _ in range(4)]
    t = time.time()
    st, out = decide_batch_donated(st, batches[0], now0)
    out.status.block_until_ready()
    res["compile_s"] = round(time.time() - t, 1)
    dump()
    ids = np.arange(n_keys, dtype=np.uint64)
    for a in range(0, n_keys, B):
        st, out = decide_batch_donated(
            st, mk(keyhash(pad_chunk(ids[a:a + B], B))), now0)
    out.status.block_until_ready()
    now_dev = bump(now0)
    reps = 32
    t = time.time()
    for r in range(reps):
        st, out = decide_batch_donated(st, batches[r % 4], now_dev)
        now_dev = bump(now_dev)
    out.status.block_until_ready()
    dt = time.time() - t
    res["ms_per_step"] = round(dt / reps * 1e3, 3)
    res["decisions_per_s"] = round(reps * B / dt)
    res["verdict"] = ("FIXED" if dt / reps < 0.01 else
                      "still pathological" if dt / reps > 0.05 else
                      "improved")
    dump()
    print(json.dumps(res))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""One-question on-chip probe: is the donated step still pathological
at CAP >= 2^22 after the unique-indices scatter change?

Cheapest possible answer (one compile + populate + 32 reps, ~5 min
cold): run this FIRST in a live tunnel window, before tpu_session.py —
if ms_per_step is back near the round-2 0.45 ms @ 2^22, the full
battery's capacity sweep and bench will inherit the fix; if it still
reads ~217 ms, the Pallas floor is the headline plan and the battery
should still run (its duel covers all three modes).

Usage: timeout 1200 python tools/cap_ab.py [log2cap] [--pallas]
`--pallas` also times the Mosaic kernel at the SAME shape (one more
compile) — the tier-3 answer if tiers 1-2 stay pathological.
Writes /tmp/cap_ab.json; copy into artifacts/ and commit.
GUBER_CAP_AB_ANY_BACKEND=1 + GUBER_JAX_PLATFORM=cpu runs an offline
smoke (interpret-mode kernel) for plumbing checks.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
import _jax_cache

_jax_cache.setup()


def main() -> int:
    # before any jax use: axon backend init can HANG when the relay is
    # down, so an offline smoke must pin the platform first
    from gubernator_tpu.cmd import maybe_pin_platform

    maybe_pin_platform()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench import _keyhash as keyhash, pad_chunk
    from gubernator_tpu.core.batch import RequestBatch
    from gubernator_tpu.core.step import decide_batch_donated
    from gubernator_tpu.core.table import init_table

    flags = [a for a in sys.argv[1:] if a.startswith("-")]
    pos = [a for a in sys.argv[1:] if not a.startswith("-")]
    unknown = set(flags) - {"--pallas", "--pallas-only"}
    if unknown:
        # a silently-ignored typo would burn a live tunnel window
        # WITHOUT the measurement the operator asked for
        print(f"unknown flag(s): {sorted(unknown)} "
              "(known: --pallas, --pallas-only)", file=sys.stderr)
        return 2
    log2cap = int(pos[0]) if pos else 22
    pallas_only = "--pallas-only" in flags
    want_pallas = pallas_only or "--pallas" in flags
    cap, n_keys = 1 << log2cap, (1 << log2cap) // 2
    B = 65536
    i64 = jnp.int64
    out_path = "/tmp/cap_ab.json"
    res = {"backend": jax.default_backend(), "cap": cap, "n_keys": n_keys,
           "B": B, "started": time.strftime("%Y-%m-%d %H:%M:%S"),
           "ksplit": int(os.environ.get("GUBER_KSPLIT", "0")),
           "probes": int(os.environ.get("GUBER_PROBES", "8"))}

    def dump():
        with open(out_path, "w") as f:
            json.dump(res, f, indent=1)

    dump()
    smoke = os.environ.get("GUBER_CAP_AB_ANY_BACKEND") == "1"
    if res["backend"] != "tpu" and not smoke:
        res["abort"] = "not tpu"
        dump()
        return 1

    rng = np.random.default_rng(42)

    def mk(keys):
        n = keys.shape[0]
        return RequestBatch(
            key=jnp.asarray(keys), hits=jnp.ones(n, i64),
            limit=jnp.full(n, 100, i64), duration=jnp.full(n, 10_000, i64),
            eff_ms=jnp.full(n, 10_000, i64), greg_end=jnp.zeros(n, i64),
            behavior=jnp.zeros(n, jnp.int32),
            algorithm=jnp.zeros(n, jnp.int32),
            burst=jnp.full(n, 100, i64), valid=jnp.ones(n, bool))

    NOW = 1_760_000_000_000
    bump = jax.jit(lambda t: t + 1)
    now0 = jnp.asarray(NOW, i64)
    bump(now0).block_until_ready()

    batches = [mk(keyhash((rng.zipf(1.1, size=B) % n_keys)
                          .astype(np.uint64))) for _ in range(4)]
    ids = np.arange(n_keys, dtype=np.uint64)
    reps = 32
    now_dev = bump(now0)
    if not pallas_only:  # --pallas-only skips the XLA arm: in the
        # escalation ladder tier 1 was already measured twice by the
        # time tier 3 fires, and every extra minute on the wedge-prone
        # tunnel risks the one number this stage exists to capture
        st = init_table(cap)
        t = time.time()
        st, out = decide_batch_donated(st, batches[0], now0)
        out.status.block_until_ready()
        res["compile_s"] = round(time.time() - t, 1)
        dump()
        for a in range(0, n_keys, B):
            st, out = decide_batch_donated(
                st, mk(keyhash(pad_chunk(ids[a:a + B], B))), now0)
        out.status.block_until_ready()
        t = time.time()
        for r in range(reps):
            st, out = decide_batch_donated(st, batches[r % 4], now_dev)
            now_dev = bump(now_dev)
        out.status.block_until_ready()
        dt = time.time() - t
        res["ms_per_step"] = round(dt / reps * 1e3, 3)
        res["decisions_per_s"] = round(reps * B / dt)
        res["err_fraction"] = round(
            float(np.asarray(out.err).mean()), 6)
        res["verdict"] = ("FIXED" if dt / reps < 0.01 else
                          "still pathological" if dt / reps > 0.05 else
                          "improved")
        dump()
        del st

    # --pallas: also time the Mosaic kernel at the SAME shape — the
    # tier-3 answer (serve large CAP from the kernel) in the same
    # window, one extra compile.  The kernel owns its scatters, so its
    # number is independent of how the backend lowers a CAP-row XLA
    # scatter — if tier 1 and tier 2 both stay pathological, this is
    # the serving plan's throughput floor at the flagship shape.
    if want_pallas:
        try:
            from functools import partial

            from gubernator_tpu.ops.pallas_step import (
                decide_batch_pallas, init_pallas_table)

            if res["backend"] != "tpu":
                # off-TPU: interpret mode, like the extras stage —
                # keyed on the BACKEND, not the smoke env var, so a
                # stale smoke export on a real TPU run can never
                # record interpret numbers as the serving floor
                decide_batch_pallas = partial(decide_batch_pallas,
                                              interpret=True)
            pt = init_pallas_table(cap * 2)  # bucket layout, load /2
            t = time.time()
            pt, pout = decide_batch_pallas(pt, batches[0], now0)
            pout.status.block_until_ready()
            res["pallas_compile_s"] = round(time.time() - t, 1)
            dump()
            for a in range(0, n_keys, B):
                pt, pout = decide_batch_pallas(
                    pt, mk(keyhash(pad_chunk(ids[a:a + B], B))), now0)
            pout.status.block_until_ready()
            now_dev = bump(now_dev)
            t = time.time()
            for r in range(reps):
                pt, pout = decide_batch_pallas(pt, batches[r % 4],
                                               now_dev)
                now_dev = bump(now_dev)
            pout.status.block_until_ready()
            pdt = time.time() - t
            res["pallas_ms_per_step"] = round(pdt / reps * 1e3, 3)
            res["pallas_decisions_per_s"] = round(reps * B / pdt)
            # errs = bucket-overflow inserts etc.; without this the
            # floor number could hide cheaper error-path steps
            res["pallas_err_fraction"] = round(
                float(np.asarray(pout.err).mean()), 6)
        except Exception as e:  # noqa: BLE001
            res["pallas_error"] = str(e)[:400]
        dump()

    print(json.dumps(res))
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/bin/sh
# Poll the axon relay; the MOMENT it accepts, fire the full battery
# (tools/tpu_session.py: probe -> cap_ab ladder -> bench -> extras),
# whose results mirror progressively into artifacts/.
#
# Usage:  nohup sh tools/relay_watch_and_fire.sh >/tmp/relay_fire.log 2>&1 &
#
# SINGLE-CLIENT RULE: the tunnel serves ONE device client.  If this
# script fires, do NOT start another jax process until it finishes —
# and never kill it mid-compile (the known permanent wedge mechanism).
# The battery itself enforces stage ordering and artifact mirroring.
#
# Bounded: gives up after ~24 h of polling so it cannot outlive its
# usefulness; tpu_session carries its own 4 h budget.
# fail LOUDLY if the probe interpreter is missing — otherwise a broken
# python would read as "relay down" for 24 silent hours
command -v python >/dev/null 2>&1 || {
  echo "python not found on PATH - cannot probe the relay" >&2
  exit 2
}
tries=0
while [ "$tries" -lt 1440 ]; do
  if python - <<'EOF'
import socket, sys
s = socket.socket()
s.settimeout(3)
try:
    s.connect(("127.0.0.1", 8103))
except Exception:
    sys.exit(1)
finally:
    s.close()
EOF
  then
    echo "relay alive at $(date -u +%H:%M:%S) - firing battery"
    cd "$(dirname "$0")/.." || exit 1
    exec timeout 14400 python tools/tpu_session.py
  fi
  tries=$((tries + 1))
  sleep 60
done
echo "relay never returned within the watch window"
exit 1

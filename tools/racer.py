#!/usr/bin/env python
"""Deterministic interleaving harness: seeded adversarial preemptions
between serving lanes, with exact conservation as the oracle.

The dispatcher's merge/carry/splice faultpoints (faults.py ›
dispatch_merge / dispatch_carry / dispatch_splice, delay mode) are the
preemption points: arming them with seed-derived delays and
probabilities stretches the windows where concurrent lanes interleave —
a caller lands in the *next* wave instead of this one, a carried job
parks across a wave boundary, a result splice completes after a later
wave already launched.  On top of that, every caller thread follows a
seed-derived jitter schedule, so a given ``--seed`` replays the same
adversarial traffic shape run over run (faultpoint RNG streams are
per-point seeded — faults.py "Determinism").

The default scenario is the concurrent COLD-KEY conservation check
(ROADMAP: the one correctness debt found by PR 5's chaos soak): a
3-daemon in-proc cluster, N threads hammering a small set of
brand-new keys with 1-row wire batches through daemons 0 AND 1
concurrently, **no pre-warm**, then an exact audit — every hit sent
must be debited from its key's bucket, cluster-wide.  At the pre-fix
commit this FAILS for every seed (forwarded rows applied at the
owner's wall clock while local rows applied at the caller's ``now``:
two time bases in one bucket row, and the later base reads the
earlier-base row as expired → bucket reset → debits silently gone).
Post-fix (created_at forwarding, proto field 10) it passes for every
seed.

Usage:
    python tools/racer.py --seed 7
    python tools/racer.py --seed 7 --runs 3 --threads 16 --keys 10
    python tools/racer.py --seed 7 --warm     # control: pre-warmed keys

Exit status: 0 = exact conservation on every run; 1 = hits lost (the
per-key shortfall is printed).
"""
from __future__ import annotations

import argparse
import os
import random
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

DAY = 24 * 3_600_000
#: pinned time base for the run — deliberately far from the wall clock,
#: so any lane that silently substitutes its own clock for the caller's
#: time base turns the substitution into a visible conservation break
#: (exactly how the cold-key loss was found)
NOW0 = 1_760_000_000_000
LIMIT = 10 ** 6


def serialize(reqs):
    from gubernator_tpu.proto import gubernator_pb2 as pb

    msg = pb.GetRateLimitsReq()
    for r in reqs:
        m = msg.requests.add()
        m.name = r.name
        m.unique_key = r.unique_key
        m.hits = r.hits
        m.limit = r.limit
        m.duration = r.duration
        m.algorithm = int(r.algorithm)
        m.behavior = int(r.behavior)
        m.burst = r.burst
    return msg.SerializeToString()


def one_req(hits, key, name):
    from gubernator_tpu.types import RateLimitRequest

    return serialize([RateLimitRequest(
        name=name, unique_key=key, hits=hits, limit=LIMIT,
        duration=DAY)])


def fault_spec(rng: random.Random, tier: bool = False) -> str:
    """Seed-derived preemption schedule: each dispatcher merge/carry/
    splice point sleeps a small seed-chosen time with a seed-chosen
    probability.  Delays are ms-scale — enough to push a concurrent
    caller into the next wave, small enough that a run stays fast.
    ``tier`` adds the cold-tier migration points (ISSUE 10): a delayed
    promote/demote widens the window in which a concurrent caller can
    observe the key mid-migration — exactly the interleaving the tier's
    engine-lock protocol must make invisible."""
    points = ["dispatch_merge", "dispatch_carry", "dispatch_splice"]
    if tier:
        points += ["tier_promote", "tier_demote"]
    parts = []
    for point in points:
        delay_ms = rng.choice((1, 2, 3, 5))
        prob = rng.choice((0.2, 0.35, 0.5))
        parts.append(f"{point}:delay:{delay_ms}ms:{prob}")
    return ",".join(parts)


def run_once(seed: int, run_idx: int, threads: int, keys_n: int,
             reps: int, hits: int, warm: bool, verbose: bool,
             tier: bool = False) -> dict:
    from gubernator_tpu import cluster as cluster_mod
    from gubernator_tpu.proto import gubernator_pb2 as pb

    rng = random.Random(f"racer|{seed}|{run_idx}")
    tag = f"s{seed}r{run_idx}"
    name = f"racer-{tag}"
    keys = [f"racer-{tag}-k{i}" for i in range(keys_n)]
    spec = fault_spec(rng, tier=tier)
    if tier:
        # tiered mode (ISSUE 10): 1024-row tables (the per-shard
        # floor, n=1 mesh) pre-filled past capacity so the racer's
        # unwarmed keys land COLD — every hammered key then migrates
        # cold→hot mid-race under the delayed migration points
        from gubernator_tpu.parallel import make_mesh

        c = cluster_mod.start(3, mesh=make_mesh(n=1),
                              cache_size=1024,
                              cache_autogrow_max=1024)
    else:
        c = cluster_mod.start(3)
    try:
        if tier:
            from gubernator_tpu.proto import gubernator_pb2 as _pb

            for base in range(0, 5000, 500):
                msg = _pb.GetRateLimitsReq()
                for i in range(base, base + 500):
                    m = msg.requests.add()
                    m.name = name
                    m.unique_key = f"racer-{tag}-fill{i}"
                    m.hits = 0
                    m.limit = LIMIT
                    m.duration = DAY
                c.instance_at(0).get_rate_limits_wire(
                    msg.SerializeToString(), now_ms=NOW0)
        # warm each ENGINE with an unrelated key so the first wave's
        # compile cost doesn't serialize the whole schedule; the keys
        # under test stay COLD unless --warm asked for the control run
        for d in range(3):
            c.instance_at(d).get_rate_limits_wire(
                one_req(0, f"racer-{tag}-warmup", name), now_ms=NOW0)
        if warm:
            for d in range(3):
                for k in keys:
                    c.instance_at(d).get_rate_limits_wire(
                        one_req(0, k, name), now_ms=NOW0)
        # arm the seeded preemption schedule on every daemon (per-point
        # RNG streams replay bit-for-bit for a given seed)
        for d in range(3):
            c.instance_at(d).faults.arm(spec, seed=seed)
        if verbose:
            print(f"  armed: {spec}")

        errs: list = []
        barrier = threading.Barrier(threads)
        # per-thread seeded jitter schedule, drawn up front so the
        # traffic shape is a pure function of the seed
        jitter = [[rng.random() * 0.004 for _ in range(reps)]
                  for _ in range(threads)]

        def worker(t):
            import time as _time

            inst = c.instance_at(t % 2)  # daemons 0 AND 1
            try:
                barrier.wait(timeout=60)
                for r in range(reps):
                    _time.sleep(jitter[t][r])
                    out = pb.GetRateLimitsResp.FromString(
                        inst.get_rate_limits_wire(
                            one_req(hits, keys[(t + r) % keys_n], name),
                            now_ms=NOW0 + 1 + r))
                    if out.responses[0].error:
                        raise RuntimeError(out.responses[0].error)
            except Exception as e:  # noqa: BLE001 - audited below
                errs.append(repr(e))

        ths = [threading.Thread(target=worker, args=(t,))
               for t in range(threads)]
        for th in ths:
            th.start()
        for th in ths:
            th.join(timeout=180)
        stuck = any(th.is_alive() for th in ths)
        for d in range(3):
            c.instance_at(d).faults.clear()
        if stuck:
            return {"ok": False, "why": "stuck caller threads"}
        if errs:
            return {"ok": False, "why": f"caller errors: {errs[:3]}"}
        sent = threads * reps * hits
        debits = {}
        for k in keys:
            q = pb.GetRateLimitsResp.FromString(
                c.instance_at(0).get_rate_limits_wire(
                    one_req(0, k, name), now_ms=NOW0 + 1000))
            if q.responses[0].error:
                return {"ok": False,
                        "why": f"audit error: {q.responses[0].error}"}
            debits[k] = LIMIT - int(q.responses[0].remaining)
        total = sum(debits.values())
        ok = total == sent
        out = {"ok": ok, "sent": sent, "debited": total,
               "lost": sent - total}
        if not ok:
            out["per_key"] = {k.rsplit("-", 1)[1]: v
                              for k, v in debits.items()}
        return out
    finally:
        c.stop()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="seeded interleaving harness (conservation oracle)")
    ap.add_argument("--seed", type=int, required=True,
                    help="schedule seed: same seed → same preemption "
                         "delays, probabilities, and caller jitter")
    ap.add_argument("--runs", type=int, default=1,
                    help="independent cluster runs (default 1)")
    ap.add_argument("--threads", type=int, default=16)
    ap.add_argument("--keys", type=int, default=10,
                    help="cold keys under test (default 10)")
    ap.add_argument("--reps", type=int, default=4,
                    help="calls per thread (default 4)")
    ap.add_argument("--hits", type=int, default=2)
    ap.add_argument("--warm", action="store_true",
                    help="pre-warm every key on every daemon first "
                         "(the control that masked the bug)")
    ap.add_argument("--no-created-at", action="store_true",
                    help="disable caller-clock forwarding "
                         "(GUBER_CREATED_AT_FWD=0): reproduces the "
                         "pre-fix cold-key conservation loss")
    ap.add_argument("--tier", action="store_true",
                    help="tiered-store mode (ISSUE 10): capped tables "
                         "+ cold tier, delayed tier_promote/"
                         "tier_demote in the preemption schedule")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    if args.no_created_at:
        os.environ["GUBER_CREATED_AT_FWD"] = "0"
        print("caller-clock forwarding DISABLED "
              "(GUBER_CREATED_AT_FWD=0): expecting the pre-fix loss")
    if args.tier:
        os.environ["GUBER_TIER_COLD"] = "1"
        os.environ.setdefault("GUBER_TIER_PROMOTE", "2")
        print("tiered store ENABLED (GUBER_TIER_COLD=1): capped "
              "tables, racer keys start cold and migrate mid-race")
    failures = 0
    for i in range(args.runs):
        r = run_once(args.seed, i, args.threads, args.keys, args.reps,
                     args.hits, args.warm, args.verbose,
                     tier=args.tier)
        if r["ok"]:
            print(f"run {i}: OK   sent={r['sent']} debited={r['debited']}"
                  f" (seed {args.seed})")
        else:
            failures += 1
            detail = r.get("why") or (
                f"sent={r['sent']} debited={r['debited']} "
                f"LOST={r['lost']} per_key={r.get('per_key')}")
            print(f"run {i}: LOSS {detail}")
    if failures:
        print(f"{failures}/{args.runs} runs broke conservation "
              f"(seed {args.seed})")
        return 1
    print(f"conservation exact over {args.runs} run(s) at seed "
          f"{args.seed}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

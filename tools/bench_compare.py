"""Diff the newest BENCH round against the previous one (ISSUE 13).

The BENCH trajectory (BENCH_r01.json, BENCH_r02.json, ...) records what
each PR's flagship run measured, but nothing ever compared two rounds —
a silent throughput regression would ride a green PR.  `make bench-diff`
runs this gate: per-row relative deltas with per-metric tolerance,
skipping rows the run itself flagged as environment-dominated (a
``context`` note, ``skipped_*`` fields, or an ``error`` row measures
the host or the harness, not the code).

Artifacts come in two shapes: a raw ``bench.py`` result document, or a
driver wrapper ``{"n", "cmd", "rc", "tail", "parsed"}`` whose ``parsed``
may be null and whose ``tail`` holds only the last few KB of stdout.
When neither yields a result document the rounds are INCOMPARABLE —
that's a printed diagnosis and exit 0, not a failure: the gate must
never turn a truncated artifact into a fake regression.

Exit codes: 0 = no regression (or incomparable), 1 = regression beyond
tolerance, 2 = usage error.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

#: relative-change tolerance by metric kind; single-host rounds are
#: noisy, so throughput gets a wide band (BENCH_r* context notes pin
#: run-to-run spread at ~±15% on the shared build host)
THROUGHPUT_TOL = 0.30  # *_per_s: lower is worse
LATENCY_TOL = 0.50     # *_ms: higher is worse
#: Jain's fairness index is seeded and deterministic per scenario, but
#: admission boundaries can shift a little when a scenario spec's
#: volume knobs are retuned — compare with an ABSOLUTE band, both
#: directions (a fairness metric drifting either way means the
#: scenario changed character, not just got slower)
JAIN_TOL = 0.05

#: keys that flag a row as environment-dominated (the run said so)
_SKIP_KEYS = ("context", "error")


def _extract_result(doc):
    """A bench result document from either artifact shape, or None."""
    if not isinstance(doc, dict):
        return None
    if isinstance(doc.get("extra"), dict):
        return doc  # raw bench.py output
    if isinstance(doc.get("parsed"), dict) \
            and isinstance(doc["parsed"].get("extra"), dict):
        return doc["parsed"]
    # driver wrapper with parsed=null: scavenge the tail for the final
    # result line (bench.py prints exactly one JSON document)
    tail = doc.get("tail")
    if isinstance(tail, str):
        for line in reversed(tail.splitlines()):
            line = line.strip()
            if not (line.startswith("{") and line.endswith("}")):
                continue
            try:
                cand = json.loads(line)
            except ValueError:
                continue
            if isinstance(cand, dict) and isinstance(
                    cand.get("extra"), dict):
                return cand
    return None


def _rows(result) -> dict:
    cfgs = (result.get("extra") or {}).get("baseline_configs") or {}
    return {k: v for k, v in cfgs.items() if isinstance(v, dict)}


def _row_skip_reason(row: dict):
    for k in _SKIP_KEYS:
        if k in row:
            return k
    for k in row:
        if k.startswith("skipped_"):
            return k
    return None


def _numeric_metrics(row: dict, row_name=None) -> dict:
    """Scalar comparable metrics of one row (one level deep only —
    nested A/B blocks carry their own ok-verdicts, compared as bools).
    The ``15_scenarios`` row additionally surfaces its per-scenario
    verdict bools (``scenarios.<name>.ok``, per-oracle ``oracle_ok.*``)
    and each scenario's Jain's index, so a scenario whose oracles
    regress — or whose fairness character drifts — fails the gate by
    name instead of hiding inside an aggregate ``all_ok``."""
    out = {}
    for k, v in row.items():
        if isinstance(v, bool) or isinstance(v, (int, float)):
            out[k] = v
        elif isinstance(v, dict):
            for kk, vv in v.items():
                if isinstance(vv, bool) and kk.endswith("_ok"):
                    out[f"{k}.{kk}"] = vv
    if row_name == "15_scenarios":
        for sname, cell in (row.get("scenarios") or {}).items():
            if not isinstance(cell, dict):
                continue
            if isinstance(cell.get("ok"), bool):
                out[f"scenarios.{sname}.ok"] = cell["ok"]
            for orc, vv in (cell.get("oracle_ok") or {}).items():
                if isinstance(vv, bool):
                    out[f"scenarios.{sname}.oracle_ok.{orc}"] = vv
            ji = cell.get("jain_index")
            if isinstance(ji, (int, float)) \
                    and not isinstance(ji, bool):
                out[f"scenarios.{sname}.jain_index"] = ji
    return out


def _direction(key: str):
    """+1 when higher is better, -1 when lower is better, None when
    the metric carries no regression semantics (counts, capacities)."""
    leaf = key.rsplit(".", 1)[-1]
    if re.search(r"(_|^)per_s$", leaf) or leaf.endswith("_rate"):
        return +1
    if leaf.endswith("_ms"):
        return -1
    return None


def compare(prev_rows: dict, new_rows: dict) -> dict:
    regressions, skipped, compared = [], [], 0
    for name in sorted(set(prev_rows) & set(new_rows)):
        pr, nr = prev_rows[name], new_rows[name]
        reason = _row_skip_reason(pr) or _row_skip_reason(nr)
        if reason:
            skipped.append({"row": name, "reason": reason})
            continue
        pm, nm = _numeric_metrics(pr, name), _numeric_metrics(nr, name)
        for key in sorted(set(pm) & set(nm)):
            old, new = pm[key], nm[key]
            if isinstance(old, bool) or isinstance(new, bool):
                compared += 1
                if old is True and new is False:
                    regressions.append(
                        {"row": name, "metric": key,
                         "old": old, "new": new,
                         "why": "verdict flipped true -> false"})
                continue
            if key.rsplit(".", 1)[-1] == "jain_index":
                compared += 1
                if abs(new - old) > JAIN_TOL:
                    regressions.append(
                        {"row": name, "metric": key, "old": old,
                         "new": new,
                         "rel_change": round(new - old, 4),
                         "tolerance": JAIN_TOL,
                         "why": "fairness index drifted beyond "
                                "absolute tolerance"})
                continue
            sign = _direction(key)
            if sign is None or old == 0:
                continue
            compared += 1
            rel = (new - old) / abs(old)
            tol = THROUGHPUT_TOL if sign > 0 else LATENCY_TOL
            if sign * rel < -tol:
                regressions.append(
                    {"row": name, "metric": key, "old": old,
                     "new": new, "rel_change": round(rel, 4),
                     "tolerance": tol})
    return {"compared_metrics": compared, "regressions": regressions,
            "skipped_rows": skipped,
            "rows_in_both": sorted(set(prev_rows) & set(new_rows))}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="diff the newest BENCH_r*.json against the "
                    "previous round")
    ap.add_argument("--dir", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="directory holding BENCH_r*.json (default: repo root)")
    ap.add_argument("--pattern", default="BENCH_r*.json")
    ap.add_argument("--json", action="store_true",
                    help="one-line JSON verdict")
    args = ap.parse_args(argv)

    paths = sorted(glob.glob(os.path.join(args.dir, args.pattern)))
    if len(paths) < 2:
        print(f"incomparable: need >= 2 rounds matching "
              f"{args.pattern} in {args.dir}, found {len(paths)}")
        return 0
    prev_path, new_path = paths[-2], paths[-1]
    docs = []
    for p in (prev_path, new_path):
        try:
            with open(p) as f:
                docs.append(json.load(f))
        except (OSError, ValueError) as e:
            print(f"incomparable: {p}: {e}")
            return 0
    prev, new = (_extract_result(d) for d in docs)
    if prev is None or new is None:
        bad = prev_path if prev is None else new_path
        print(f"incomparable: {os.path.basename(bad)} holds no bench "
              "result document (truncated driver tail, parsed=null) — "
              "nothing to diff")
        return 0
    verdict = compare(_rows(prev), _rows(new))
    verdict["prev"] = os.path.basename(prev_path)
    verdict["new"] = os.path.basename(new_path)
    if args.json:
        print(json.dumps(verdict))
    else:
        print(f"{verdict['prev']} -> {verdict['new']}: "
              f"{verdict['compared_metrics']} metrics across "
              f"{len(verdict['rows_in_both'])} rows")
        for s in verdict["skipped_rows"]:
            print(f"  skip {s['row']} ({s['reason']}: "
                  "environment-dominated)")
        for r in verdict["regressions"]:
            print(f"  REGRESSION {r['row']}.{r['metric']}: "
                  f"{r['old']} -> {r['new']} "
                  f"({r.get('rel_change', 'verdict')})")
        if not verdict["regressions"]:
            print("  no regressions beyond tolerance")
    return 1 if verdict["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())

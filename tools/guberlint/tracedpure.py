"""tracedpure — no host side effects inside jit/shard_map/pallas traces.

Python inside ``jax.jit`` / ``shard_map`` / ``pallas_call`` runs ONCE,
at trace time — then never again.  A lock acquisition, metrics bump,
faultpoint check, wall-clock read, or mutation of non-local Python
state inside traced code therefore does the wrong thing twice over: it
executes at trace time (when no request is in flight) and is silently
absent from every steady-state wave.  The classic symptom is a counter
that advances exactly once per compile and then freezes — invisible in
tests that trigger a compile per call, wrong in production.

The pass builds the call graph rooted at every traced entry point —
the first argument of each ``jax.jit(...)`` / ``shard_map(...)`` /
``pallas_call(...)`` call (Name, lambda, or ``functools.partial``),
plus ``@jit``-decorated defs — resolving callees in the same file
first (including ``self.method``), then across the core package when
the name is globally unique, and audits every reached function for:

- lock acquisition (``with <lock>``, ``.acquire()``);
- metrics writes (``.inc()``, ``.observe()``, ``.labels()``) and
  telemetry (``.record()``, ``.record_error()``, ``.tap_flag()``);
- faultpoint checks (``self._fault(...)``, ``fs.fire/should(...)``);
- host clock reads (``time.*``, ``clock_ms``);
- mutation of non-local Python state (``global`` / ``nonlocal``,
  attribute assignment, subscript stores to module-level names —
  closure-captured subscript writes are exempt: that shape is the
  Pallas Ref-store idiom (``o_ref[...] = x`` inside a kernel's loop
  body), a device write, not host state);
- host callbacks (``jax.debug.callback`` / ``io_callback``) — legal
  escape hatches, but each must be *declared*;
- use-after-donate: an argument passed at a donated position of a
  ``jax.jit(..., donate_argnums=...)`` callable is dead after the call
  — reading it again aliases freed device memory.

Intentional escapes are blessed with ``# traced-ok: <reason>`` on the
statement (or the line above, or the ``def`` line for a whole
function).  Every ``# traced-ok:`` needs a REASON — the legal ones in
the tree today: trace-time-only constant reads, and debug callbacks
gated behind test-only flags.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from . import Violation
from .engine import LintContext, unparse

PASS_ID = "tracedpure"

_ENTRY_NAMES = {"jit", "shard_map", "pallas_call"}
_LOCK_RX = re.compile(r"(_mu\b|_lock\b|_cond\b|XLA_EXEC_MU|"
                      r"\bLock\(|\bRLock\(|\bCondition\()")
_METRIC_ATTRS = {"inc", "observe", "labels"}
_TELEMETRY_ATTRS = {"record", "record_error", "_record_event",
                    "tap_flag", "force_sample"}
_FAULT_NAMES = {"_fault", "_fault_point", "_fault_tick"}
_TIME_ATTRS = {"time", "time_ns", "perf_counter", "monotonic", "sleep"}
_CALLBACK_ATTRS = {"callback", "io_callback", "pure_callback"}


def _call_name(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


def _is_entry(node: ast.Call) -> bool:
    return _call_name(node) in _ENTRY_NAMES


def _stmt_blessed(sf, stmt: ast.stmt, key: str = "traced-ok") -> bool:
    """Blessed on the statement's own lines or the line above.  For
    compound statements (if/for/with/...) only the HEADER line counts —
    an annotation deep inside a long body blesses the nested statement
    it sits on, not the whole block."""
    if getattr(stmt, "body", None):
        lines = (stmt.lineno - 1, stmt.lineno)
    else:
        end = getattr(stmt, "end_lineno", None) or stmt.lineno
        lines = range(stmt.lineno - 1, end + 1)
    return any(sf.annotation(line, key) for line in lines)


def _callable_candidates(arg: ast.AST):
    """Yield the Name / Lambda nodes an entry-point argument may call
    (unwraps functools.partial)."""
    if isinstance(arg, (ast.Name, ast.Lambda)):
        yield arg
    elif isinstance(arg, ast.Call) and _call_name(arg) == "partial" \
            and arg.args:
        yield from _callable_candidates(arg.args[0])


def _donate_indices(call: ast.Call) -> Tuple[int, ...]:
    """Donated positional indices of a jit(...) call, () if none."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            return tuple(e.value for e in v.elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, int))
    return ()


class _Index:
    """Function-def resolution: same-file first, then globally-unique
    names across the core package, plus per-file core-module aliases."""

    def __init__(self, ctx: LintContext):
        self.by_file: Dict[str, Dict[str, list]] = {}
        self.global_idx: Dict[str, list] = {}
        self.aliases: Dict[str, Set[str]] = {}
        self.module_globals: Dict[str, Set[str]] = {}
        for sf in ctx.core_files():
            g: Set[str] = set()
            for stmt in sf.tree.body:
                for tgt in getattr(stmt, "targets", None) \
                        or ([stmt.target] if isinstance(
                            stmt, (ast.AnnAssign, ast.AugAssign))
                            else []):
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name):
                            g.add(n.id)
            self.module_globals[sf.rel] = g
        for sf in ctx.core_files():
            d: Dict[str, list] = {}
            for node in ast.walk(sf.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    d.setdefault(node.name, []).append(node)
                    self.global_idx.setdefault(node.name, []) \
                        .append((sf, node))
            self.by_file[sf.rel] = d
            al: Set[str] = set()
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.ImportFrom) and node.level:
                    al.update(a.asname or a.name for a in node.names)
                elif isinstance(node, ast.Import):
                    for a in node.names:
                        if a.name.startswith("gubernator_tpu"):
                            al.add(a.asname or a.name.split(".")[0])
            self.aliases[sf.rel] = al

    def resolve(self, sf, call: ast.Call):
        """(sf, FunctionDef) for a call, or None."""
        fn = call.func
        if isinstance(fn, ast.Name):
            return self._by_name(sf, fn.id)
        if isinstance(fn, ast.Attribute) and isinstance(fn.value,
                                                        ast.Name):
            base = fn.value.id
            if base == "self":
                local = self.by_file.get(sf.rel, {}).get(fn.attr, [])
                if len(local) == 1:
                    return sf, local[0]
                return None
            if base in self.aliases.get(sf.rel, set()):
                hits = self.global_idx.get(fn.attr, [])
                if len(hits) == 1:
                    return hits[0]
        return None

    def _by_name(self, sf, name: str):
        local = self.by_file.get(sf.rel, {}).get(name, [])
        if len(local) == 1:
            return sf, local[0]
        if not local:
            hits = self.global_idx.get(name, [])
            if len(hits) == 1:
                return hits[0]
        return None


class _TraceAuditor:
    def __init__(self, idx: _Index, out: List[Violation]):
        self.idx = idx
        self.out = out
        self.visited: Set[Tuple[str, int]] = set()

    def audit(self, sf, fn, root: str) -> None:
        key = (sf.rel, fn.lineno)
        if key in self.visited:
            return
        self.visited.add(key)
        if not isinstance(fn, ast.Lambda) and \
                sf.annotation(fn.lineno, "traced-ok"):
            return  # whole function blessed
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        locals_: Set[str] = set()
        for a in ast.walk(fn.args):
            if isinstance(a, ast.arg):
                locals_.add(a.arg)
        for n in ast.walk(fn):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                locals_.add(n.id)
        if isinstance(fn.body, list):
            self._stmts(sf, fn, body, locals_, root)
        else:  # lambda: one expression, no statements to bless
            self._expr_checks(sf, fn, fn.body, frozenset(), locals_,
                              root)
            self._follow_calls(sf, fn.body, root)

    # -- statement walk -------------------------------------------------

    def _stmts(self, sf, fn, body, locals_, root) -> None:
        for stmt in body:
            self._stmt(sf, fn, stmt, locals_, root)

    def _stmt(self, sf, fn, stmt, locals_, root) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # defined inside traced code → runs under the trace when
            # called (cond branches, fori bodies): audit it too
            self.audit(sf, stmt, root)
            return
        if _stmt_blessed(sf, stmt):
            return  # checks AND traversal skipped: declared escape
        # nested statements are walked individually below (where their
        # own blessings apply) — exclude them from this statement's
        # expression checks AND call-following, else a blessed nested
        # statement leaks through the enclosing compound's walk
        skip = self._nested_stmt_ids(stmt)
        self._stmt_checks(sf, fn, stmt, locals_, root)
        self._expr_checks(sf, fn, stmt, skip, locals_, root)
        self._follow_calls(sf, stmt, root, skip)
        for field in ("body", "orelse", "finalbody"):
            self._stmts(sf, fn, getattr(stmt, field, []) or [],
                        locals_, root)
        for h in getattr(stmt, "handlers", []) or []:
            self._stmts(sf, fn, h.body, locals_, root)

    def _stmt_checks(self, sf, fn, stmt, locals_, root) -> None:
        if isinstance(stmt, (ast.Global, ast.Nonlocal)):
            self._flag(sf, stmt.lineno, root,
                       f"'{unparse(stmt)}' mutates non-local Python "
                       f"state inside traced code — the write happens "
                       f"once at trace time, never per wave")
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                text = unparse(item.context_expr).replace(" ", "")
                if _LOCK_RX.search(text):
                    self._flag(sf, stmt.lineno, root,
                               f"lock acquisition 'with {text}' inside "
                               f"traced code — held at trace time only, "
                               f"guards nothing per wave")
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        for tgt in targets:
            for t in (tgt.elts if isinstance(tgt, (ast.Tuple, ast.List))
                      else [tgt]):
                if isinstance(t, ast.Attribute):
                    self._flag(sf, stmt.lineno, root,
                               f"attribute mutation "
                               f"'{unparse(t)} = ...' inside traced "
                               f"code — happens once at trace time, "
                               f"never per wave")
                elif isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id not in locals_ and \
                        t.value.id in self.idx.module_globals.get(
                            sf.rel, set()):
                    self._flag(sf, stmt.lineno, root,
                               f"subscript store to module global "
                               f"'{t.value.id}' inside traced code — "
                               f"happens once at trace time, never "
                               f"per wave")

    @staticmethod
    def _nested_stmt_ids(stmt) -> Set[int]:
        """ids of every node under this statement's nested statement
        bodies (if/for/try arms)."""
        nested = []
        for field in ("body", "orelse", "finalbody"):
            v = getattr(stmt, field, None)
            if isinstance(v, list):
                nested.extend(v)
        for h in getattr(stmt, "handlers", []) or []:
            nested.extend(h.body)
        return {id(n) for s in nested for n in ast.walk(s)}

    def _expr_checks(self, sf, fn, node, skip, locals_, root) -> None:
        for n in ast.walk(node):
            if id(n) in skip or not isinstance(n, ast.Call):
                continue
            name = _call_name(n)
            f = n.func
            if name == "acquire":
                self._flag(sf, n.lineno, root,
                           f"{unparse(f)}() inside traced code — lock "
                           f"taken at trace time only")
            elif isinstance(f, ast.Attribute) and \
                    name in _METRIC_ATTRS and \
                    not self._is_jnp_set_chain(f):
                self._flag(sf, n.lineno, root,
                           f"metrics write {unparse(f)}(...) inside "
                           f"traced code — bumps once at trace time, "
                           f"then freezes")
            elif isinstance(f, ast.Attribute) and \
                    name in _TELEMETRY_ATTRS:
                self._flag(sf, n.lineno, root,
                           f"telemetry call {unparse(f)}(...) inside "
                           f"traced code — records once at trace "
                           f"time, then never again")
            elif name in _FAULT_NAMES or (
                    isinstance(f, ast.Attribute)
                    and name in ("fire", "should")
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "fs"):
                self._flag(sf, n.lineno, root,
                           f"faultpoint check {unparse(f)}(...) inside "
                           f"traced code — evaluated at trace time, "
                           f"the armed fault never fires per wave")
            elif (isinstance(f, ast.Attribute)
                  and f.attr in _TIME_ATTRS
                  and isinstance(f.value, ast.Name)
                  and f.value.id in ("time", "_time")) or \
                    name == "clock_ms":
                self._flag(sf, n.lineno, root,
                           f"host clock read {unparse(f)}() inside "
                           f"traced code — frozen at its trace-time "
                           f"value in the compiled program")
            elif isinstance(f, ast.Attribute) and \
                    name in _CALLBACK_ATTRS:
                self._flag(sf, n.lineno, root,
                           f"host callback {unparse(f)}(...) escapes "
                           f"the trace — declare it with "
                           f"'# traced-ok: <reason>'")

    @staticmethod
    def _is_jnp_set_chain(f: ast.Attribute) -> bool:
        """``x.at[i].set/...`` lookalikes never collide with the metric
        attrs checked here, but ``.labels`` could in principle — keep
        the hook for future attr collisions."""
        return False

    def _follow_calls(self, sf, node, root,
                      skip=frozenset()) -> None:
        for n in ast.walk(node):
            if id(n) in skip or not isinstance(n, ast.Call):
                continue
            hit = self.idx.resolve(sf, n)
            if hit is not None:
                self.audit(hit[0], hit[1], root)
            # functions passed as operands (lax.cond branches,
            # fori_loop bodies) execute under the same trace
            for a in n.args:
                if isinstance(a, ast.Name):
                    h = self.idx._by_name(sf, a.id)
                    if h is not None:
                        self.audit(h[0], h[1], root)

    def _flag(self, sf, line: int, root: str, msg: str) -> None:
        self.out.append(Violation(
            sf.rel, line, PASS_ID,
            f"{msg} [traced via {root}; bless intentional escapes "
            f"with '# traced-ok: <reason>']"))


def _use_after_donate(ctx: LintContext, out: List[Violation]) -> None:
    for sf in ctx.core_files():
        donated: Dict[str, Tuple[int, ...]] = {}
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Assign):
                continue
            v = node.value
            if not (isinstance(v, ast.Call) and _call_name(v) == "jit"):
                continue
            idxs = _donate_indices(v)
            if not idxs:
                continue
            for tgt in node.targets:
                donated[unparse(tgt).replace(" ", "")] = idxs
        if not donated:
            continue
        for fn in (n for n in ast.walk(sf.tree)
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))):
            _donate_scan(sf, fn, donated, out)


def _donate_scan(sf, fn, donated, out: List[Violation]) -> None:
    """Linear scan: after ``f(x, ...)`` donates ``x`` (and the statement
    does not rebind it), any later load of ``x`` before a rebind reads
    freed device memory."""
    nested_ids = set()
    for n in ast.walk(fn):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)) and n is not fn:
            nested_ids.update(id(s) for s in ast.walk(n))
    stmts = [s for s in ast.walk(fn)
             if isinstance(s, ast.stmt) and s is not fn
             and id(s) not in nested_ids]
    stmts.sort(key=lambda s: s.lineno)
    dead: Dict[str, int] = {}  # donated text -> donation line
    for stmt in stmts:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        rebound: Set[str] = set()
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                for t in (tgt.elts if isinstance(tgt, (ast.Tuple,
                                                       ast.List))
                          else [tgt]):
                    rebound.add(unparse(t).replace(" ", ""))
        # loads of dead buffers (skip the rebinding statement's own
        # RHS only when it is the donation call itself, handled below)
        if dead and not _stmt_blessed(sf, stmt):
            for n in ast.walk(stmt):
                if isinstance(n, (ast.Name, ast.Attribute)) and \
                        isinstance(getattr(n, "ctx", None), ast.Load):
                    text = unparse(n).replace(" ", "")
                    if text in dead:
                        out.append(Violation(
                            sf.rel, n.lineno, PASS_ID,
                            f"use after donate: '{text}' was donated "
                            f"at line {dead[text]} "
                            f"(donate_argnums) and read again here — "
                            f"the buffer's device memory was reused "
                            f"by XLA; rebind the result first"))
                        del dead[text]
        for text in rebound:
            dead.pop(text, None)
        # new donations in this statement
        for n in ast.walk(stmt):
            if not isinstance(n, ast.Call):
                continue
            ftext = unparse(n.func).replace(" ", "")
            idxs = donated.get(ftext)
            if not idxs:
                continue
            for i in idxs:
                if i < len(n.args) and isinstance(
                        n.args[i], (ast.Name, ast.Attribute)):
                    atext = unparse(n.args[i]).replace(" ", "")
                    if atext not in rebound:
                        dead[atext] = n.lineno


def run(ctx: LintContext) -> List[Violation]:
    out: List[Violation] = []
    idx = _Index(ctx)
    auditor = _TraceAuditor(idx, out)
    for sf in ctx.core_files():
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and _is_entry(node) \
                    and node.args:
                for cand in _callable_candidates(node.args[0]):
                    if isinstance(cand, ast.Lambda):
                        auditor.audit(sf, cand,
                                      f"{sf.rel}:{node.lineno}")
                    else:
                        hit = idx._by_name(sf, cand.id)
                        if hit is not None:
                            auditor.audit(hit[0], hit[1],
                                          f"jit({cand.id})")
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    dn = dec if not isinstance(dec, ast.Call) \
                        else dec.func
                    name = dn.id if isinstance(dn, ast.Name) else (
                        dn.attr if isinstance(dn, ast.Attribute)
                        else "")
                    if name in _ENTRY_NAMES:
                        auditor.audit(sf, node, f"@{name} {node.name}")
    _use_after_donate(ctx, out)
    return out

"""clockdomain — clock-domain taint: the PR-6 bug class as a lint error.

The worst bug this repo has shipped mixed two *time bases* in one
bucket row: a forwarded request was applied at the OWNER's wall clock
instead of the CALLER's, the later base read the earlier one as
expired, and the bucket reset ate the debits (CONCURRENCY.md › racer;
fixed by forwarding ``created_at``, RateLimitReq field 10).  That bug
lived in dataflow, not annotations — this pass makes the flow itself
checkable, in three rules over the core package:

**Rule A — every clock read declares its domain.**  Each call to
``clock_ms()`` / ``time.time()`` / ``time.time_ns()`` must carry one of

    now = clock_ms()        # clock-domain: caller
    now = clock_ms()        # clock-domain: owner
    t0 = time.time()        # clock-ok: <reason — not a bucket time base>

on its statement (or the line above it, or the enclosing ``def`` line).
``caller`` means the read happens at the request's first hop (the
daemon IS the caller's entry — front doors); ``owner`` means the read
happens while applying rows that originated elsewhere (peer-wire hops,
deferred queue flushes).  ``# clock-ok:`` is for wall-clock reads that
are never a rate-limit time base (telemetry, tracing, sweep cadence).

**Rule B — owner-domain values must not become created_at stamps.**
Intra-function taint: names assigned from an owner-domain read (through
assignments, ternaries, arithmetic) must not reach a stamping sink — a
``created_at=`` / ``stamp_ms=`` keyword, ``tlv_with_created``'s time
argument, ``stamp_req_tlvs``'s time argument — unless the statement is
blessed with ``# clock-ok: <reason>`` (the legal reason in the tree:
first-hop-wins fallback stamps that only apply to rows no caller ever
stamped).

**Rule C — deferred-apply sinks must carry a caller stamp.**  Every
call site of a queue/egress sink whose rows are applied LATER under a
different clock must show its stamp lexically:

- ``queue_hits(...)`` (GLOBAL / multi-region object path): an argument
  derived from ``_req_stamped(...)`` / ``tlv_with_created(...)`` /
  ``stamp_req_tlvs(...)``;
- ``_raw_queue_groups(...)`` / ``_queue_mr_raw(...)`` (wire lane): a
  ``stamp_ms=`` keyword;
- ``forward_raw(...)`` (peer forward hop): a stamping call somewhere in
  the enclosing function (the stamp is applied to the TLV bytes being
  forwarded, not at the send call itself);

or carry ``# clock-ok: <reason>``.  Reverting a stamp site — the exact
PR-6 regression — trips this rule (sharpness pinned by the fixture
tests in tests/test_guberlint.py).
"""
from __future__ import annotations

import ast
from typing import List, Optional, Set

from . import Violation
from .engine import LintContext, unparse

PASS_ID = "clockdomain"

#: direct clock-source function names (the rate-limit time base)
_CLOCK_NAMES = {"clock_ms"}
#: ``<module>.<attr>()`` clock sources
_TIME_MODULES = {"time", "_time"}
_TIME_ATTRS = {"time", "time_ns"}

#: stamping sinks: (positional index or None) checked for owner taint
_STAMP_POS = {"tlv_with_created": 1, "stamp_req_tlvs": -1}
_STAMP_KWARGS = {"created_at", "stamp_ms"}

#: functions whose presence proves a caller stamp was applied
_STAMP_EVIDENCE = {"_req_stamped", "tlv_with_created", "stamp_req_tlvs"}

#: deferred-apply sinks requiring stamp evidence in their arguments
_ARG_EVIDENCE_SINKS = {"queue_hits"}
#: deferred-apply sinks requiring a stamp_ms= keyword
_KWARG_SINKS = {"_raw_queue_groups", "_queue_mr_raw"}
#: egress sinks requiring stamp evidence in the enclosing function
_FN_EVIDENCE_SINKS = {"forward_raw"}


def _call_name(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


def _is_clock_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Name) and fn.id in _CLOCK_NAMES:
        return True
    if isinstance(fn, ast.Attribute):
        if fn.attr in _CLOCK_NAMES:
            return True
        if (fn.attr in _TIME_ATTRS and isinstance(fn.value, ast.Name)
                and fn.value.id in _TIME_MODULES):
            return True
    return False


def _stmt_annotation(sf, stmt: ast.stmt, key: str) -> Optional[str]:
    """``# key: value`` on the statement's line range or the line
    above.  Compound statements (if/for/with/...) only honor their
    HEADER line — an annotation deep inside a body belongs to the
    nested statement it sits on."""
    if getattr(stmt, "body", None):
        lines = (stmt.lineno - 1, stmt.lineno)
    else:
        end = getattr(stmt, "end_lineno", None) or stmt.lineno
        lines = range(stmt.lineno - 1, end + 1)
    for line in lines:
        v = sf.annotation(line, key)
        if v:
            return v
    return None


def _domain(sf, stmt: ast.stmt, fn_stack) -> Optional[str]:
    """Resolved clock domain for a clock read inside ``stmt``:
    'caller' / 'owner' / 'ok' (blessed) / None (untagged)."""
    v = _stmt_annotation(sf, stmt, "clock-domain")
    if v in ("caller", "owner"):
        return v
    if _stmt_annotation(sf, stmt, "clock-ok"):
        return "ok"
    for fn in reversed(fn_stack):
        v = sf.annotation(fn.lineno, "clock-domain")
        if v in ("caller", "owner"):
            return v
        if sf.annotation(fn.lineno, "clock-ok"):
            return "ok"
    return None


def _blessed(sf, stmt: ast.stmt, fn_stack) -> bool:
    if _stmt_annotation(sf, stmt, "clock-ok"):
        return True
    return any(sf.annotation(fn.lineno, "clock-ok") for fn in fn_stack)


class _FnAuditor:
    """One function (or the module body): Rule A on every clock read,
    Rule B forward taint, Rule C sink-site stamping."""

    def __init__(self, sf, fn_stack, out: List[Violation]):
        self.sf = sf
        self.fn_stack = fn_stack  # enclosing (Async)FunctionDefs
        self.out = out
        self.tainted: Set[str] = set()
        self.fn_has_evidence = False

    def run(self, body) -> None:
        # function-scope pre-scan: is a stamping call present anywhere?
        # (Rule C's forward_raw sinks stamp the bytes upstream in the
        # same function, not at the send call)
        for stmt in body:
            for n in ast.walk(stmt):
                if (isinstance(n, ast.Call)
                        and _call_name(n) in _STAMP_EVIDENCE):
                    self.fn_has_evidence = True
        self._stmts(body)

    # -- statement walk (source order; branch taint is unioned) --------

    def _stmts(self, body) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _FnAuditor(self.sf, self.fn_stack + [stmt],
                       self.out).run(stmt.body)
            return
        self._check_clock_reads(stmt)
        self._check_sinks(stmt)
        self._propagate(stmt)
        for field in ("body", "orelse", "finalbody"):
            self._stmts(getattr(stmt, field, []) or [])
        for h in getattr(stmt, "handlers", []) or []:
            self._stmts(h.body)

    # -- Rule A ---------------------------------------------------------

    def _check_clock_reads(self, stmt: ast.stmt) -> None:
        for n in self._own_nodes(stmt):
            if _is_clock_call(n) and \
                    _domain(self.sf, stmt, self.fn_stack) is None:
                self.out.append(Violation(
                    self.sf.rel, n.lineno, PASS_ID,
                    f"untagged clock read {unparse(n.func)}() — declare "
                    f"its time base with '# clock-domain: caller|owner' "
                    f"(or '# clock-ok: <reason>' for non-bucket wall "
                    f"clock); see CONCURRENCY.md"))

    # -- Rule B / Rule C ------------------------------------------------

    def _check_sinks(self, stmt: ast.stmt) -> None:
        for n in self._own_nodes(stmt):
            if not isinstance(n, ast.Call):
                continue
            name = _call_name(n)
            # Rule B: owner taint into a stamping slot
            checked = []
            if name in _STAMP_POS and n.args:
                idx = _STAMP_POS[name]
                if idx == -1 or idx < len(n.args):
                    checked.append(n.args[idx])
            for kw in n.keywords:
                if kw.arg in _STAMP_KWARGS:
                    checked.append(kw.value)
            for expr in checked:
                if self._tainted_expr(expr, stmt) and \
                        not _blessed(self.sf, stmt, self.fn_stack):
                    self.out.append(Violation(
                        self.sf.rel, n.lineno, PASS_ID,
                        f"owner-domain clock value flows into the "
                        f"created_at stamp of {name}(...) — forwarded "
                        f"rows must carry the CALLER's time base, or "
                        f"the owner's clock resets cold bucket rows "
                        f"(bless first-hop-wins fallbacks with "
                        f"'# clock-ok: <reason>')"))
            # Rule C: deferred-apply sinks must show their stamp
            if name in _ARG_EVIDENCE_SINKS:
                ok = any(isinstance(sub, ast.Call)
                         and _call_name(sub) in _STAMP_EVIDENCE
                         for a in n.args for sub in ast.walk(a))
                if not ok and not _blessed(self.sf, stmt, self.fn_stack):
                    self.out.append(Violation(
                        self.sf.rel, n.lineno, PASS_ID,
                        f"{name}(...) enqueues rows for deferred apply "
                        f"without a created_at stamp — wrap the request "
                        f"in _req_stamped(...) (or bless with "
                        f"'# clock-ok: <reason>'): the PR-6 bug class"))
            elif name in _KWARG_SINKS:
                if not any(kw.arg == "stamp_ms" for kw in n.keywords) \
                        and not _blessed(self.sf, stmt, self.fn_stack):
                    self.out.append(Violation(
                        self.sf.rel, n.lineno, PASS_ID,
                        f"{name}(...) without stamp_ms= — wire-lane "
                        f"queue TLVs apply at the owner later and must "
                        f"carry the caller's created_at (or bless with "
                        f"'# clock-ok: <reason>'): the PR-6 bug class"))
            elif name in _FN_EVIDENCE_SINKS:
                if not self.fn_has_evidence and \
                        not _blessed(self.sf, stmt, self.fn_stack):
                    self.out.append(Violation(
                        self.sf.rel, n.lineno, PASS_ID,
                        f"{name}(...) forwards request TLVs but no "
                        f"stamping call (stamp_req_tlvs / "
                        f"tlv_with_created / _req_stamped) appears in "
                        f"this function — the owner would apply these "
                        f"rows at its own clock (the PR-6 bug class); "
                        f"stamp before sending or bless with "
                        f"'# clock-ok: <reason>'"))

    # -- taint machinery ------------------------------------------------

    def _tainted_expr(self, expr: ast.AST, stmt: ast.stmt) -> bool:
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                    and n.id in self.tainted:
                return True
            if _is_clock_call(n) and \
                    _domain(self.sf, stmt, self.fn_stack) == "owner":
                return True
        return False

    def _propagate(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.Assign):
            t = self._tainted_expr(stmt.value, stmt)
            for tgt in stmt.targets:
                for n in ast.walk(tgt):
                    if isinstance(n, ast.Name):
                        (self.tainted.add if t
                         else self.tainted.discard)(n.id)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            if isinstance(stmt.target, ast.Name):
                if self._tainted_expr(stmt.value, stmt):
                    self.tainted.add(stmt.target.id)
                else:
                    self.tainted.discard(stmt.target.id)
        elif isinstance(stmt, ast.AugAssign):
            if isinstance(stmt.target, ast.Name) and \
                    self._tainted_expr(stmt.value, stmt):
                self.tainted.add(stmt.target.id)

    # -- helpers --------------------------------------------------------

    def _own_nodes(self, stmt: ast.stmt):
        """Expression nodes belonging to this statement but NOT to a
        nested statement body (those are visited on their own, so their
        annotations resolve against the right line range)."""
        nested = []
        for field in ("body", "orelse", "finalbody"):
            nested.extend(getattr(stmt, field, []) or [])
        for h in getattr(stmt, "handlers", []) or []:
            nested.extend(h.body)
        skip = set()
        for s in nested:
            for n in ast.walk(s):
                skip.add(id(n))
        for n in ast.walk(stmt):
            if id(n) not in skip:
                yield n


def run(ctx: LintContext) -> List[Violation]:
    out: List[Violation] = []
    for sf in ctx.core_files():
        # module body + each top-level/nested function as its own scope
        _FnAuditor(sf, [], out).run(sf.tree.body)
    return out

"""Shared lint machinery: file discovery, parsed sources, comments.

Every pass consumes a :class:`LintContext`: lazily-parsed ASTs plus a
per-line comment map (pulled with ``tokenize`` so annotations survive
exactly as written).  Paths are repo-relative in diagnostics.
"""
from __future__ import annotations

import ast
import io
import tokenize
from pathlib import Path
from typing import Dict, List, Optional


class SourceFile:
    """One parsed source: tree + raw lines + per-line comments."""

    def __init__(self, path: Path, rel: str):
        self.path = path
        self.rel = rel
        text = path.read_text()
        self.lines = text.splitlines()
        self.tree = ast.parse(text)
        #: line no (1-based) → comment text without the leading '#'
        self.comments: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(text).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = \
                        tok.string.lstrip("#").strip()
        except tokenize.TokenError:  # pragma: no cover - parse caught it
            pass

    def comment_on(self, line: int) -> str:
        return self.comments.get(line, "")

    def annotation(self, line: int, key: str) -> Optional[str]:
        """Return the value of a ``# <key>: value`` annotation on
        ``line`` (or None).  Keys: guarded-by, lock-free."""
        c = self.comments.get(line, "")
        if c.startswith(key + ":"):
            return c[len(key) + 1:].strip()
        return None


#: packages whose sources the concurrency passes analyze (annotations
#: and faultpoints live here; tools/tests are out of scope for them)
CORE_PKG = "gubernator_tpu"

#: files the env-registry pass additionally scans for GUBER_* reads
ENV_EXTRA = ("bench.py",)


class LintContext:
    def __init__(self, root: Path,
                 extra_files: Optional[List[Path]] = None):
        self.root = root
        self._cache: Dict[str, SourceFile] = {}
        self.extra_files = [Path(p) for p in (extra_files or [])]

    def _load(self, path: Path) -> Optional[SourceFile]:
        rel = str(path.relative_to(self.root)) \
            if path.is_relative_to(self.root) else str(path)
        if rel not in self._cache:
            try:
                self._cache[rel] = SourceFile(path, rel)
            except (SyntaxError, UnicodeDecodeError, OSError):
                return None  # non-source or unparseable: not lintable
        return self._cache[rel]

    def _walk(self, base: Path) -> List[Path]:
        return sorted(
            p for p in base.rglob("*.py")
            if "__pycache__" not in p.parts and "_pb2" not in p.name)

    def core_files(self) -> List[SourceFile]:
        """gubernator_tpu/** sources (+ the fixtures' extra files)."""
        out = []
        for p in self._walk(self.root / CORE_PKG) + self.extra_files:
            sf = self._load(p)
            if sf is not None:
                out.append(sf)
        return out

    def env_scan_files(self) -> List[SourceFile]:
        """Everything that may read GUBER_* env vars: the core package,
        tools/ (guberlint itself excluded), and bench.py."""
        paths = self._walk(self.root / CORE_PKG)
        paths += [p for p in self._walk(self.root / "tools")
                  if "guberlint" not in p.parts]
        paths += [self.root / f for f in ENV_EXTRA
                  if (self.root / f).exists()]
        out = []
        for p in paths + self.extra_files:
            sf = self._load(p)
            if sf is not None:
                out.append(sf)
        return out


def unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - defensive
        return "<unparseable>"


def func_defs(tree: ast.AST):
    """Yield every (Async)FunctionDef in the tree."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node

"""guarded — the guarded-by checker.

A shared mutable attribute declares its lock at the assignment that
creates it::

    self._inflight = {}  # guarded-by: self._tel_mu

Every OTHER read/write of ``self._inflight`` inside the class must
then be lexically inside ``with self._tel_mu:`` — or carry an explicit
escape, either on the accessing statement or on the enclosing ``def``
line::

    depth = self._queued_rows  # lock-free: GIL-atomic int read
    def debug_stats(self):  # lock-free: monotonic snapshot, stale ok

Exemptions that need no annotation: the declaring assignment itself
and the whole constructor (``__init__`` runs happens-before
publication).  The analysis is LEXICAL: a helper that assumes its
caller holds the lock must say so with ``# lock-free: caller holds
<lock>`` — that sentence is exactly the convention the checker exists
to make visible.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from . import Violation
from .engine import LintContext, SourceFile, unparse

PASS_ID = "guarded"


def _norm(text: str) -> str:
    return text.replace(" ", "")


class _ClassAuditor(ast.NodeVisitor):
    """Walks ONE class body enforcing its guarded-by declarations."""

    def __init__(self, sf: SourceFile, cls: ast.ClassDef,
                 declared: Dict[str, Tuple[str, int]],
                 out: List[Violation]):
        self.sf = sf
        self.cls = cls
        self.declared = declared
        self.out = out
        self._locks: List[str] = []   # normalized held-lock texts
        self._stmt: List[ast.stmt] = []  # enclosing statement stack
        self._fn: List[ast.FunctionDef] = []

    # -- structure ----------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        if node is self.cls:
            self.generic_visit(node)
        # nested classes audit separately (their own declarations)

    def _visit_fn(self, node) -> None:
        if node.name == "__init__" and len(self._fn) == 0:
            return  # constructor: happens-before publication
        if any(self.sf.annotation(ln, "lock-free") is not None
               for ln in (node.lineno - 1, node.lineno)):
            return  # whole function blessed (def line or just above)
        self._fn.append(node)
        for stmt in node.body:
            self._visit_stmt(stmt)
        self._fn.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_With(self, node: ast.With) -> None:
        texts = [_norm(unparse(item.context_expr))
                 for item in node.items]
        self._locks.extend(texts)
        for stmt in node.body:
            self._visit_stmt(stmt)
        del self._locks[len(self._locks) - len(texts):]
        # context expressions themselves may read guarded state
        for item in node.items:
            self.visit(item.context_expr)

    visit_AsyncWith = visit_With

    def _visit_stmt(self, stmt: ast.stmt) -> None:
        self._stmt.append(stmt)
        self.visit(stmt)
        self._stmt.pop()

    def generic_visit(self, node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self._visit_stmt(child)
            else:
                self.visit(child)

    # -- the check ----------------------------------------------------

    def _stmt_annotated(self) -> bool:
        if not self._stmt:
            return False
        stmt = self._stmt[-1]
        end = getattr(stmt, "end_lineno", stmt.lineno) or stmt.lineno
        # the annotation may ride any line of the statement, or a
        # comment line immediately above it (79-col reality)
        return any(
            self.sf.annotation(ln, "lock-free") is not None
            for ln in range(stmt.lineno - 1, end + 1))

    def visit_Attribute(self, node: ast.Attribute) -> None:
        self.generic_visit(node)
        if not (isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr in self.declared):
            return
        lock, decl_line = self.declared[node.attr]
        if node.lineno == decl_line:
            return  # the declaring assignment
        if _norm(lock) in self._locks:
            return
        if self.sf.annotation(node.lineno, "lock-free") is not None:
            return
        if self._stmt_annotated():
            return
        self.out.append(Violation(
            self.sf.rel, node.lineno, PASS_ID,
            f"{self.cls.name}.{node.attr} accessed outside "
            f"'with {lock}' (declared guarded-by at line {decl_line}); "
            f"hold the lock or annotate '# lock-free: <reason>'"))


def _collect_declarations(sf: SourceFile, cls: ast.ClassDef,
                          out: List[Violation]
                          ) -> Dict[str, Tuple[str, int]]:
    declared: Dict[str, Tuple[str, int]] = {}
    for node in ast.walk(cls):
        if isinstance(node, ast.ClassDef) and node is not cls:
            continue
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        for t in targets:
            if not (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                continue
            lock = sf.annotation(node.lineno, "guarded-by")
            if lock is None:
                continue
            prev = declared.get(t.attr)
            if prev is not None and _norm(prev[0]) != _norm(lock):
                out.append(Violation(
                    sf.rel, node.lineno, PASS_ID,
                    f"{cls.name}.{t.attr} re-declared guarded-by "
                    f"{lock!r} but line {prev[1]} says {prev[0]!r} — "
                    f"one attribute, one lock"))
                continue
            if prev is None:
                declared[t.attr] = (lock, node.lineno)
    return declared


def run(ctx: LintContext) -> List[Violation]:
    out: List[Violation] = []
    for sf in ctx.core_files():
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            declared = _collect_declarations(sf, node, out)
            if declared:
                _ClassAuditor(sf, node, declared, out).visit(node)
    return out

"""CLI: ``python -m tools.guberlint`` (what ``make lint`` runs).

Exit 0 on a clean tree, 1 with one ``path:line: [pass] message`` line
per violation.  ``--pass`` restricts to one pass; ``--json`` emits the
violations as a JSON list (bench provenance uses this).

``--baseline FILE`` suppresses violations whose line-number-free key
(``path [pass] message``) appears in FILE — the mechanism for landing
a new pass incrementally against a not-yet-clean tree.
``--write-baseline FILE`` regenerates that file from the current
violations (and exits 0: writing a baseline IS the acknowledgement).
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from . import PASS_NAMES, baseline_key, load_baseline, run_passes


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="guberlint",
        description="concurrency-discipline lint (see CONCURRENCY.md)")
    ap.add_argument("--pass", dest="passes", action="append",
                    choices=PASS_NAMES,
                    help="run only this pass (repeatable)")
    ap.add_argument("--json", action="store_true",
                    help="emit violations as JSON")
    ap.add_argument("--baseline", metavar="FILE",
                    help="suppress violations listed in FILE "
                         "(line-number-free 'path [pass] message' keys)")
    ap.add_argument("--write-baseline", metavar="FILE",
                    help="write the current violations to FILE as a "
                         "baseline and exit 0")
    args = ap.parse_args(argv)
    baseline = load_baseline(args.baseline) if args.baseline else None
    violations = run_passes(passes=args.passes, baseline=baseline)
    if args.write_baseline:
        keys = sorted({baseline_key(v) for v in violations})
        Path(args.write_baseline).write_text(
            "# guberlint baseline — suppressed violations "
            "(regenerate: python -m tools.guberlint "
            "--write-baseline <file>)\n"
            + "".join(k + "\n" for k in keys))
        print(f"guberlint: wrote {len(keys)} baseline "
              f"key{'s' if len(keys) != 1 else ''} to "
              f"{args.write_baseline}")
        return 0
    if args.json:
        print(json.dumps([v.__dict__ for v in violations], indent=2))
    else:
        for v in violations:
            print(v.render())
        n = len(violations)
        print(f"guberlint: {n} violation{'s' if n != 1 else ''}"
              + ("" if n else " — tree is clean"))
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())

"""CLI: ``python -m tools.guberlint`` (what ``make lint`` runs).

Exit 0 on a clean tree, 1 with one ``path:line: [pass] message`` line
per violation.  ``--pass`` restricts to one pass; ``--json`` emits the
violations as a JSON list (bench provenance uses this).
"""
from __future__ import annotations

import argparse
import json
import sys

from . import PASS_NAMES, run_passes


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="guberlint",
        description="concurrency-discipline lint (see CONCURRENCY.md)")
    ap.add_argument("--pass", dest="passes", action="append",
                    choices=PASS_NAMES,
                    help="run only this pass (repeatable)")
    ap.add_argument("--json", action="store_true",
                    help="emit violations as JSON")
    args = ap.parse_args(argv)
    violations = run_passes(passes=args.passes)
    if args.json:
        print(json.dumps([v.__dict__ for v in violations], indent=2))
    else:
        for v in violations:
            print(v.render())
        n = len(violations)
        print(f"guberlint: {n} violation{'s' if n != 1 else ''}"
              + ("" if n else " — tree is clean"))
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())

"""threads — thread-spawn inventory and bounded-drain discipline.

Two rules over the core package:

1. Every ``threading.Thread(...)`` construction passes ``name=``: an
   anonymous ``Thread-7`` in a stack dump or the stall watchdog's
   output is undebuggable, and CONCURRENCY.md's thread inventory is
   keyed by these names (tools/check_metrics.py cross-checks).
2. No ``.join()`` without a timeout: an unbounded join turns one
   wedged worker into a hung drain — shutdown must bound every join
   (GUBER_DRAIN_GRACE is the budget; the IntervalLoop hang this rule
   was written against is pinned in tests/test_interval.py).

``collect_thread_names`` exposes the inventory (module, name-expr)
pairs for the CONCURRENCY.md doc check.
"""
from __future__ import annotations

import ast
from typing import List, Tuple

from . import Violation
from .engine import LintContext, unparse

PASS_ID = "threads"


def _is_thread_ctor(node: ast.Call) -> bool:
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id == "Thread"
    if isinstance(fn, ast.Attribute):
        return fn.attr == "Thread"
    return False


def collect_thread_names(ctx: LintContext) -> List[Tuple[str, str]]:
    """(module, name expression text) for every Thread construction —
    the raw material of CONCURRENCY.md's thread inventory."""
    out = []
    for sf in ctx.core_files():
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and _is_thread_ctor(node):
                name = next((kw.value for kw in node.keywords
                             if kw.arg == "name"), None)
                out.append((sf.rel,
                            unparse(name) if name is not None else ""))
    return out


def run(ctx: LintContext) -> List[Violation]:
    out: List[Violation] = []
    for sf in ctx.core_files():
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            if _is_thread_ctor(node):
                if not any(kw.arg == "name" for kw in node.keywords):
                    out.append(Violation(
                        sf.rel, node.lineno, PASS_ID,
                        "Thread(...) without name= — name every "
                        "thread (stack dumps, watchdog output, and "
                        "the CONCURRENCY.md inventory key on it)"))
                continue
            fn = node.func
            if (isinstance(fn, ast.Attribute) and fn.attr == "join"
                    and not node.args and not node.keywords):
                out.append(Violation(
                    sf.rel, node.lineno, PASS_ID,
                    f"unbounded {unparse(fn)}() — a wedged worker "
                    f"hangs this join forever; pass a timeout "
                    f"(drain budget: GUBER_DRAIN_GRACE)"))
    return out

"""lockorder — no lexically nested acquisition against the hierarchy.

LOCK_ORDER declares the repo's lock hierarchy, outermost first (the
operator-facing copy lives in CONCURRENCY.md and is cross-checked by
tools/check_metrics.py).  Inside one function, a ``with`` acquiring
lock B while a ``with`` holding lock A is open is legal only when B
ranks STRICTLY deeper than A; acquiring the same rank twice is flagged
as well (``threading.Lock`` is not reentrant).

The analysis is lexical (one function at a time): cross-function
chains — e.g. the dispatcher holding ``_engine_lock`` while the engine
takes ``XLA_EXEC_MU`` — are the hierarchy's *documentation* duty, not
this pass's.  That is exactly the race detector trade-off the
reference accepts with Go's lock conventions: the checker catches the
regression class it can see deterministically, the convention covers
the rest.
"""
from __future__ import annotations

import ast
import re
from typing import List, Tuple

from . import Violation
from .engine import LintContext, unparse

PASS_ID = "lockorder"

#: The lock hierarchy, OUTERMOST first.  Entries are regexes matched
#: against the normalized text of each ``with`` context expression.
#: Mirror of the CONCURRENCY.md table — keep both in sync (checked by
#: tools/check_metrics.py).
LOCK_ORDER: Tuple[Tuple[str, str], ...] = (
    ("submit_mu", r"^self\._submit_mu$"),
    ("inline_mu", r"^self\._inline_mu$"),
    ("peer_mu", r"^self\._peer_mu$"),
    ("send_cond", r"^self\._cond$"),
    ("engine_lock", r"^self\._engine_lock$"),
    ("xla_exec_mu", r"^XLA_EXEC_MU$"),
    ("tel_mu", r"^self\._tel_mu$"),
    ("leaf_mu", r"^(self|hs|fs|gm)\._mu$"),
)

_COMPILED = [(name, re.compile(pat)) for name, pat in LOCK_ORDER]


def _rank(with_text: str):
    for rank, (name, pat) in enumerate(_COMPILED):
        if pat.match(with_text):
            return rank, name
    return None


class _FnAuditor(ast.NodeVisitor):
    def __init__(self, sf, out: List[Violation]):
        self.sf = sf
        self.out = out
        self.held: List[Tuple[int, str, int]] = []  # (rank, name, line)

    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        for item in node.items:
            text = unparse(item.context_expr).replace(" ", "")
            r = _rank(text)
            if r is None:
                continue
            rank, name = r
            for h_rank, h_name, h_line in self.held:
                if rank <= h_rank:
                    self.out.append(Violation(
                        self.sf.rel, node.lineno, PASS_ID,
                        f"acquires '{name}' (rank {rank}) while "
                        f"holding '{h_name}' (rank {h_rank}, line "
                        f"{h_line}) — violates LOCK_ORDER "
                        f"(outermost-first; see CONCURRENCY.md)"))
            self.held.append((rank, name, node.lineno))
            pushed += 1
        self.generic_visit(node)
        for _ in range(pushed):
            self.held.pop()

    visit_AsyncWith = visit_With

    def _visit_fn(self, node) -> None:
        # nested function: fresh lexical scope — a closure runs later,
        # not under the enclosing with (callbacks, workers)
        saved, self.held = self.held, []
        self.generic_visit(node)
        self.held = saved

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn


def run(ctx: LintContext) -> List[Violation]:
    out: List[Violation] = []
    for sf in ctx.core_files():
        for node in sf.tree.body:
            _FnAuditor(sf, out).visit(node)
    return out

"""faultcat — faultpoint catalog consistency.

Every instrumented faultpoint site (``self._fault("x")``,
``fs.fire("x")``, ``fs.should("x")``, ``self._fault_point("x")``) must
name a point in ``faults.FAULT_POINTS``, and every cataloged point
must still have at least one site — so the chaos matrix can never arm
a point that silently tests nothing, and a removed call site can't
leave a ghost entry behind.  (RESILIENCE.md's operator-facing table is
checked against the same catalog by tools/check_metrics.py.)
"""
from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from . import Violation
from .engine import LintContext

PASS_ID = "faultcat"

_SITE_FUNCS = {"fire", "should", "_fault", "_fault_point",
               "_fault_tick"}


def _catalog(ctx: LintContext):
    for sf in ctx.core_files():
        if not sf.rel.endswith("faults.py"):
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "FAULT_POINTS"
                    for t in node.targets):
                if isinstance(node.value, ast.Dict):
                    return sf, {
                        k.value: k.lineno for k in node.value.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)}
    return None, None


def run(ctx: LintContext) -> List[Violation]:
    out: List[Violation] = []
    cat_sf, catalog = _catalog(ctx)
    if catalog is None:
        return out  # fixture trees without faults.py
    sites: Dict[str, Tuple[str, int]] = {}
    for sf in ctx.core_files():
        if sf.rel.endswith("faults.py"):
            continue  # the implementation's own generic fire(name)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else "")
            if name not in _SITE_FUNCS or not node.args:
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                continue
            point = arg.value
            sites.setdefault(point, (sf.rel, node.lineno))
            if point not in catalog:
                out.append(Violation(
                    sf.rel, node.lineno, PASS_ID,
                    f"faultpoint {point!r} fired here but missing from "
                    f"faults.FAULT_POINTS — add it to the catalog (and "
                    f"RESILIENCE.md)"))
    for point, line in catalog.items():
        if point not in sites:
            out.append(Violation(
                cat_sf.rel, line, PASS_ID,
                f"FAULT_POINTS catalogs {point!r} but no instrumented "
                f"site fires it — the chaos matrix would arm a no-op"))
    return out

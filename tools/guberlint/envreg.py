"""envreg — the GUBER_* environment-variable registry check.

Every ``GUBER_*`` read in the code (``os.environ.get``, ``os.getenv``,
``environ[...]``, ``"X" in os.environ``, config's ``src.get``) must be
declared in ``config.ENV_REGISTRY`` with a one-line description, and
every declared variable must still be read somewhere — so the operator
surface (docs, example.conf, runbooks) can never drift from the code.
tools/check_metrics.py lints the prose docs against the same registry.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Tuple

from . import Violation
from .engine import LintContext, unparse

PASS_ID = "envreg"

_GUBER = re.compile(r"^GUBER_[A-Z0-9_]+$")


def _str_const(node) -> str:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return ""


def _env_reads(sf) -> List[Tuple[str, int]]:
    """(var, line) for every GUBER_* env read shape in the file."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(sf.tree):
        var = ""
        if isinstance(node, ast.Call):
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else "")
            if name in ("get", "getenv", "_env_int") and node.args:
                var = _str_const(node.args[0])
        elif isinstance(node, ast.Subscript):
            if unparse(node.value).endswith("environ"):
                var = _str_const(node.slice)
        elif isinstance(node, ast.Compare):
            if (len(node.ops) == 1 and isinstance(node.ops[0], ast.In)
                    and unparse(node.comparators[0]).endswith("environ")):
                var = _str_const(node.left)
        if var and _GUBER.match(var):
            out.append((var, node.lineno))
    return out


def _registry(ctx: LintContext):
    """(entries: var → line, registry_line) from config.ENV_REGISTRY."""
    sf = None
    for f in ctx.core_files():
        if f.rel.endswith("config.py"):
            sf = f
            break
    if sf is None:
        return None, None, None
    for node in ast.walk(sf.tree):
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target]
                   if isinstance(node, ast.AnnAssign) else [])
        if any(isinstance(t, ast.Name) and t.id == "ENV_REGISTRY"
               for t in targets):
            if isinstance(node.value, ast.Dict):
                entries: Dict[str, int] = {}
                for k in node.value.keys:
                    v = _str_const(k)
                    if v:
                        entries[v] = k.lineno
                return sf, entries, node.lineno
    return sf, None, None


def run(ctx: LintContext) -> List[Violation]:
    out: List[Violation] = []
    cfg_sf, entries, reg_line = _registry(ctx)
    if cfg_sf is None:
        return out  # fixture trees without config.py: nothing to check
    if entries is None:
        out.append(Violation(
            cfg_sf.rel, 1, PASS_ID,
            "config.py has no ENV_REGISTRY dict literal — every "
            "GUBER_* env var must be declared there"))
        return out
    seen: Dict[str, Tuple[str, int]] = {}
    for sf in ctx.env_scan_files():
        for var, line in _env_reads(sf):
            seen.setdefault(var, (sf.rel, line))
            if var not in entries:
                out.append(Violation(
                    sf.rel, line, PASS_ID,
                    f"env var {var} read here but not declared in "
                    f"config.ENV_REGISTRY — register it with a "
                    f"one-line description"))
    for var, line in entries.items():
        if var not in seen:
            out.append(Violation(
                cfg_sf.rel, line, PASS_ID,
                f"ENV_REGISTRY declares {var} but nothing reads it — "
                f"remove the entry or the dead knob it describes"))
    return out

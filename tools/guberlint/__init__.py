"""guberlint — concurrency-discipline static analysis for gubernator-tpu.

The host serving path is a deeply threaded system (depth-K dispatcher
ring, pooled ingest buffers, per-peer send lanes resolving futures on
gRPC callback threads, analytics drain workers, interval loops).  The
reference leans on Go's race detector and lock conventions; this
package is the repo's equivalent: AST-based passes that make the lock
discipline *checkable*, run in tier-1 (tests/test_lint_clean.py) and
by `make lint`.

Passes (each one module in this package):

- ``guarded``   — guarded-by checker: shared mutable attributes are
  annotated ``# guarded-by: self._mu`` at their declaring assignment;
  every other read/write site must be lexically inside
  ``with self._mu`` (or carry ``# lock-free: <reason>``).
- ``lockorder`` — the declared lock hierarchy (LOCK_ORDER, documented
  in CONCURRENCY.md) admits no lexically nested acquisition against
  the order.
- ``envreg``    — every ``GUBER_*`` env read must appear in
  config.ENV_REGISTRY (and every registry entry must be read
  somewhere): the operator surface can't drift silently.
- ``faultcat``  — every instrumented faultpoint name must exist in
  faults.FAULT_POINTS and every cataloged point must have a site.
- ``threads``   — every Thread(...) names itself (``name=``) and no
  ``.join()`` runs unbounded (a dead worker must never hang drain
  forever — joins carry a timeout).
- ``clockdomain`` — clock-domain taint (ISSUE 14): every clock read
  declares ``# clock-domain: caller|owner``; owner-domain values
  must not become created_at stamps; deferred-apply queue/forward
  sinks must show their caller stamp — the PR-6 created_at
  clock-mixing loss as a lint error.
- ``tracedpure`` — no host side effects inside jit/shard_map/pallas
  traces: lock acquisition, metrics/telemetry writes, faultpoint
  checks, ``time.*``, non-local Python mutation, undeclared host
  callbacks, use-after-donate.  Escapes: ``# traced-ok: <reason>``.
- ``retrace``   — jit call sites must be retrace-stable: no dtype
  drift across a positional slot, no unhashable statics.  Escapes:
  ``# retrace-ok: <reason>``.  Cross-checked at runtime by the
  compile ledger (gubernator_tpu/compileledger.py).
- ``docs``      — the operator-doc consistency family (née
  tools/check_metrics.py): metrics ↔ OBSERVABILITY.md, event kinds,
  faultpoints ↔ RESILIENCE.md, GUBER_* table, SLO + span catalogs.

Annotation grammar (full spec in CONCURRENCY.md):

    self._inflight = {}          # guarded-by: self._tel_mu
    depth = self._queued_rows    # lock-free: GIL-atomic int read
    def stats(self):             # lock-free: snapshot, staleness ok
    now = clock_ms()             # clock-domain: caller
    t0 = time.time()             # clock-ok: telemetry wall clock
    jax.debug.callback(hook, x)  # traced-ok: test-only invariant hook
    f(x, 3.0)                    # retrace-ok: cold path, compiles once

A ``# lock-free:`` / ``# clock-domain:`` / ``# traced-ok:`` on a
``def`` line blesses the whole function body.  Declaring assignments
and the whole constructor (``__init__``) are exempt for ``guarded`` —
construction happens-before publication.
"""
from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Iterable, List, Optional


@dataclasses.dataclass(frozen=True)
class Violation:
    """One diagnostic: ``path:line: [pass_id] message``."""

    path: str
    line: int
    pass_id: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_id}] {self.message}"


#: pass registry, populated lazily (each pass module exposes
#: ``run(ctx) -> List[Violation]``)
PASS_NAMES = ("guarded", "lockorder", "envreg", "faultcat", "threads",
              "clockdomain", "tracedpure", "retrace", "docs")


def repo_root() -> Path:
    return Path(__file__).resolve().parent.parent.parent


def baseline_key(v: Violation) -> str:
    """The line-number-free identity a baseline file suppresses on —
    line numbers drift with every edit, so suppressions pin
    (path, pass, message) instead."""
    return f"{v.path} [{v.pass_id}] {v.message}"


def load_baseline(path) -> set:
    """Suppression keys from a ``--baseline`` file (one
    :func:`baseline_key` line each; blank lines and ``#`` comments
    ignored).  Missing file → empty set (a deleted baseline means
    nothing is suppressed, not an error)."""
    p = Path(path)
    if not p.exists():
        return set()
    out = set()
    for line in p.read_text().splitlines():
        line = line.strip()
        if line and not line.startswith("#"):
            out.add(line)
    return out


def run_passes(root: Optional[Path] = None,
               passes: Optional[Iterable[str]] = None,
               extra_files: Optional[List[Path]] = None,
               baseline: Optional[set] = None
               ) -> List[Violation]:
    """Run the requested passes (default: all) over the repo rooted at
    ``root``; returns violations sorted by (path, line).  ``extra_files``
    adds out-of-tree sources (the fixture tests use this).
    ``baseline`` is a set of :func:`baseline_key` strings to suppress —
    the incremental-landing mechanism for future passes."""
    import importlib

    from .engine import LintContext

    root = root if root is not None else repo_root()
    ctx = LintContext(root, extra_files=extra_files)
    out: List[Violation] = []
    for name in (passes if passes is not None else PASS_NAMES):
        if name not in PASS_NAMES:
            raise ValueError(
                f"unknown guberlint pass {name!r} (have: "
                f"{', '.join(PASS_NAMES)})")
        mod = importlib.import_module(f".{name}", __package__)
        out.extend(mod.run(ctx))
    if baseline:
        out = [v for v in out if baseline_key(v) not in baseline]
    return sorted(out, key=lambda v: (v.path, v.line, v.pass_id))

"""guberlint — concurrency-discipline static analysis for gubernator-tpu.

The host serving path is a deeply threaded system (depth-K dispatcher
ring, pooled ingest buffers, per-peer send lanes resolving futures on
gRPC callback threads, analytics drain workers, interval loops).  The
reference leans on Go's race detector and lock conventions; this
package is the repo's equivalent: AST-based passes that make the lock
discipline *checkable*, run in tier-1 (tests/test_lint_clean.py) and
by `make lint`.

Passes (each one module in this package):

- ``guarded``   — guarded-by checker: shared mutable attributes are
  annotated ``# guarded-by: self._mu`` at their declaring assignment;
  every other read/write site must be lexically inside
  ``with self._mu`` (or carry ``# lock-free: <reason>``).
- ``lockorder`` — the declared lock hierarchy (LOCK_ORDER, documented
  in CONCURRENCY.md) admits no lexically nested acquisition against
  the order.
- ``envreg``    — every ``GUBER_*`` env read must appear in
  config.ENV_REGISTRY (and every registry entry must be read
  somewhere): the operator surface can't drift silently.
- ``faultcat``  — every instrumented faultpoint name must exist in
  faults.FAULT_POINTS and every cataloged point must have a site.
- ``threads``   — every Thread(...) names itself (``name=``) and no
  ``.join()`` runs unbounded (a dead worker must never hang drain
  forever — joins carry a timeout).

Annotation grammar (full spec in CONCURRENCY.md):

    self._inflight = {}          # guarded-by: self._tel_mu
    depth = self._queued_rows    # lock-free: GIL-atomic int read
    def stats(self):             # lock-free: snapshot, staleness ok

A ``# lock-free:`` on a ``def`` line blesses the whole function body.
Declaring assignments and the whole constructor (``__init__``) are
exempt — construction happens-before publication.
"""
from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Iterable, List, Optional


@dataclasses.dataclass(frozen=True)
class Violation:
    """One diagnostic: ``path:line: [pass_id] message``."""

    path: str
    line: int
    pass_id: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_id}] {self.message}"


#: pass registry, populated lazily (each pass module exposes
#: ``run(ctx) -> List[Violation]``)
PASS_NAMES = ("guarded", "lockorder", "envreg", "faultcat", "threads")


def repo_root() -> Path:
    return Path(__file__).resolve().parent.parent.parent


def run_passes(root: Optional[Path] = None,
               passes: Optional[Iterable[str]] = None,
               extra_files: Optional[List[Path]] = None
               ) -> List[Violation]:
    """Run the requested passes (default: all) over the repo rooted at
    ``root``; returns violations sorted by (path, line).  ``extra_files``
    adds out-of-tree sources (the fixture tests use this)."""
    import importlib

    from .engine import LintContext

    root = root if root is not None else repo_root()
    ctx = LintContext(root, extra_files=extra_files)
    out: List[Violation] = []
    for name in (passes if passes is not None else PASS_NAMES):
        if name not in PASS_NAMES:
            raise ValueError(
                f"unknown guberlint pass {name!r} (have: "
                f"{', '.join(PASS_NAMES)})")
        mod = importlib.import_module(f".{name}", __package__)
        out.extend(mod.run(ctx))
    return sorted(out, key=lambda v: (v.path, v.line, v.pass_id))

"""docs — the operator-doc consistency pass family (née
tools/check_metrics.py; that CLI survives as a thin shim).

Asserts, against a fresh ``Metrics()`` registry and the live
declarative catalogs:

1. metric (family) names are unique — duplicate registration is a
   silent dashboard breaker (prometheus_client raises on exact dups,
   but two attributes pointing at lookalike names would not);
2. every registered metric is documented in OBSERVABILITY.md;
3. every ``gubernator_*`` name OBSERVABILITY.md documents actually
   exists — a stale doc is how the metrics.py docstring drifted before;
4. every flight-recorder event ``kind`` emitted through telemetry.py
   (literal first arguments to ``.record(...)`` / ``.record_error(...)``
   / ``._record_event(...)`` anywhere under gubernator_tpu/) appears in
   OBSERVABILITY.md's event table, and vice versa — an undocumented
   event kind is invisible to whoever greps the doc mid-incident;
5. RESILIENCE.md's faultpoint table matches faults.FAULT_POINTS both
   ways (the ``faultcat`` pass pins catalog ↔ code; this pins
   catalog ↔ doc — together the chaos surface can't drift anywhere);
6. CONCURRENCY.md's GUBER_* table matches config.ENV_REGISTRY both
   ways (the ``envreg`` pass pins registry ↔ code), and its
   lock-hierarchy table names every lock in guberlint's LOCK_ORDER;
7. OBSERVABILITY.md's "SLO catalog & burn windows" table matches
   slo.SLO_CATALOG both ways — the declarative SLO registry is an
   operator contract, so an SLO that exists but isn't documented (or
   a documented one that was removed) fails tier-1;
8. OBSERVABILITY.md's "Span catalog" table matches
   tracing.SPAN_CATALOG both ways — same contract for the trace
   plane: a span an operator meets in a waterfall must be in the doc,
   and a doc row must name a span the code can actually emit.
"""
from __future__ import annotations

import os
import re
import sys
from typing import List

from . import Violation, repo_root

PASS_ID = "docs"

REPO = str(repo_root())

DOC = os.path.join(REPO, "OBSERVABILITY.md")
RESILIENCE_DOC = os.path.join(REPO, "RESILIENCE.md")
CONCURRENCY_DOC = os.path.join(REPO, "CONCURRENCY.md")

#: sample suffixes prometheus_client appends — doc names are family
#: names, but a doc mentioning the exposition form shouldn't fail lint
_SUFFIXES = ("_total", "_created", "_bucket", "_count", "_sum", "_info")


def _canonical(name: str, reg_set) -> str:
    """Map a documented name to its registered family: exact match
    wins; otherwise strip ONE sample suffix if that base is registered
    (family names themselves may legitimately end in _count etc., so a
    blind strip would corrupt real names)."""
    if name in reg_set:
        return name
    for s in _SUFFIXES:
        if name.endswith(s) and name[: -len(s)] in reg_set:
            return name[: -len(s)]
    return name


#: literal event kinds at FlightRecorder call sites.  Variable-kind
#: calls (e.g. global_manager's _record_event(kind, ...) helper body)
#: don't match — their literal call sites do.
_KIND_RX = re.compile(
    r"\.(?:record|record_error|_record_event)\(\s*[\"']([a-z0-9_]+)[\"']")


def emitted_event_kinds(pkg_dir: str) -> set:
    kinds = set()
    for root, _dirs, files in os.walk(pkg_dir):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(root, fn), encoding="utf-8") as f:
                kinds.update(_KIND_RX.findall(f.read()))
    return kinds


def documented_event_kinds(doc: str) -> set:
    """Backticked names in the first column of the flight-recorder
    event table (the section between '## Flight recorder' and the next
    '## ' heading); one row may document several kinds."""
    try:
        section = doc.split("## Flight recorder", 1)[1]
    except IndexError:
        return set()
    section = section.split("\n## ", 1)[0]
    kinds = set()
    for line in section.splitlines():
        if not line.startswith("| `"):
            continue
        first_cell = line.split("|")[1]
        kinds.update(re.findall(r"`([a-z0-9_]+)`", first_cell))
    return kinds


def _table_cell_names(doc: str, heading: str, rx: str) -> set:
    """Backticked names matching ``rx`` in the first column of the
    table under ``heading`` (up to the next heading of any level)."""
    try:
        section = doc.split(heading, 1)[1]
    except IndexError:
        return set()
    section = re.split(r"\n#{1,6} ", section, 1)[0]
    names = set()
    for line in section.splitlines():
        if not line.startswith("| `"):
            continue
        first_cell = line.split("|")[1]
        names.update(re.findall(rx, first_cell))
    return names


def faultpoint_doc_problems() -> list:
    """RESILIENCE.md's faultpoint catalog table ↔ faults.FAULT_POINTS."""
    from gubernator_tpu.faults import FAULT_POINTS

    with open(RESILIENCE_DOC, encoding="utf-8") as f:
        doc = f.read()
    documented = _table_cell_names(doc, "### Faultpoint catalog",
                                   r"`([a-z0-9_]+)`")
    problems = []
    for point in sorted(set(FAULT_POINTS) - documented):
        problems.append(
            f"faultpoint {point!r} is in faults.FAULT_POINTS but "
            f"missing from RESILIENCE.md's catalog table")
    for point in sorted(documented - set(FAULT_POINTS)):
        problems.append(
            f"RESILIENCE.md's catalog table documents faultpoint "
            f"{point!r} but faults.FAULT_POINTS has no such point")
    return problems


def slo_catalog_doc_problems() -> list:
    """OBSERVABILITY.md's SLO table ↔ slo.SLO_CATALOG, both ways."""
    from gubernator_tpu.slo import SLO_CATALOG

    with open(DOC, encoding="utf-8") as f:
        doc = f.read()
    documented = _table_cell_names(doc, "## SLO catalog & burn windows",
                                   r"`([a-z0-9_]+)`")
    problems = []
    for name in sorted(set(SLO_CATALOG) - documented):
        problems.append(
            f"SLO {name!r} is in slo.SLO_CATALOG but missing from "
            f"OBSERVABILITY.md's SLO catalog table")
    for name in sorted(documented - set(SLO_CATALOG)):
        problems.append(
            f"OBSERVABILITY.md's SLO catalog table documents {name!r} "
            f"but slo.SLO_CATALOG has no such SLO")
    return problems


def span_catalog_doc_problems() -> list:
    """OBSERVABILITY.md's span-catalog table ↔ tracing.SPAN_CATALOG."""
    from gubernator_tpu.tracing import SPAN_CATALOG

    with open(DOC, encoding="utf-8") as f:
        doc = f.read()
    documented = _table_cell_names(doc, "### Span catalog",
                                   r"`([A-Za-z][A-Za-z0-9_.]*)`")
    problems = []
    for name in sorted(set(SPAN_CATALOG) - documented):
        problems.append(
            f"span {name!r} is in tracing.SPAN_CATALOG but missing "
            f"from OBSERVABILITY.md's span catalog table")
    for name in sorted(documented - set(SPAN_CATALOG)):
        problems.append(
            f"OBSERVABILITY.md's span catalog table documents span "
            f"{name!r} but tracing.SPAN_CATALOG has no such span")
    return problems


def env_registry_doc_problems() -> list:
    """CONCURRENCY.md's GUBER_* table ↔ config.ENV_REGISTRY, plus its
    lock-hierarchy table ↔ guberlint's LOCK_ORDER."""
    from gubernator_tpu.config import ENV_REGISTRY
    from tools.guberlint.lockorder import LOCK_ORDER

    problems = []
    if not os.path.exists(CONCURRENCY_DOC):
        return [f"{CONCURRENCY_DOC} is missing — the concurrency "
                f"tooling's operator doc"]
    with open(CONCURRENCY_DOC, encoding="utf-8") as f:
        doc = f.read()
    documented = _table_cell_names(doc, "## GUBER_* environment",
                                   r"`(GUBER_[A-Z0-9_]+)`")
    for var in sorted(set(ENV_REGISTRY) - documented):
        problems.append(
            f"env var {var} is in config.ENV_REGISTRY but missing from "
            f"CONCURRENCY.md's GUBER_* table")
    for var in sorted(documented - set(ENV_REGISTRY)):
        problems.append(
            f"CONCURRENCY.md's GUBER_* table documents {var} but "
            f"config.ENV_REGISTRY has no such entry")
    doc_locks = _table_cell_names(doc, "## Lock hierarchy",
                                  r"`([a-z_]+)`")
    for name, _pat in LOCK_ORDER:
        if name not in doc_locks:
            problems.append(
                f"lock {name!r} is in guberlint LOCK_ORDER but missing "
                f"from CONCURRENCY.md's lock-hierarchy table")
    for name in sorted(doc_locks - {n for n, _ in LOCK_ORDER}):
        problems.append(
            f"CONCURRENCY.md's lock-hierarchy table documents lock "
            f"{name!r} but guberlint LOCK_ORDER has no such rank")
    return problems


def metric_catalog_problems() -> list:
    """Checks 1-4: registry uniqueness, metrics ↔ OBSERVABILITY.md,
    event kinds ↔ the flight-recorder table."""
    from gubernator_tpu.metrics import Metrics

    m = Metrics()
    registered = [fam.name for fam in m.registry.collect()]
    problems = []

    dups = {n for n in registered if registered.count(n) > 1}
    if dups:
        problems.append(f"duplicate metric names: {sorted(dups)}")

    with open(DOC, encoding="utf-8") as f:
        doc = f.read()
    reg_set = set(registered)
    # the lookahead drops path-like mentions ("gubernator_tpu/metrics.py")
    documented = {_canonical(n, reg_set) for n in re.findall(
        r"gubernator_[a-z0-9_]+(?![a-z0-9_/.])", doc)}

    for name in sorted(reg_set - documented):
        problems.append(
            f"metric {name!r} is registered in metrics.py but missing "
            f"from OBSERVABILITY.md")
    for name in sorted(documented - reg_set):
        problems.append(
            f"OBSERVABILITY.md documents {name!r} but no such metric "
            f"is registered (stale doc entry)")

    emitted = emitted_event_kinds(os.path.join(REPO, "gubernator_tpu"))
    doc_kinds = documented_event_kinds(doc)
    for kind in sorted(emitted - doc_kinds):
        problems.append(
            f"event kind {kind!r} is emitted via telemetry.py but "
            f"missing from the OBSERVABILITY.md event table")
    for kind in sorted(doc_kinds - emitted):
        problems.append(
            f"OBSERVABILITY.md's event table documents kind {kind!r} "
            f"but nothing emits it (stale doc entry)")
    return problems


def run(ctx) -> List[Violation]:
    """guberlint pass entry point.  The doc checks bind to the REAL
    repo (they import live catalogs and read the operator docs);
    fixture trees exercise the other passes."""
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    groups = (
        ("OBSERVABILITY.md", metric_catalog_problems),
        ("RESILIENCE.md", faultpoint_doc_problems),
        ("CONCURRENCY.md", env_registry_doc_problems),
        ("OBSERVABILITY.md", slo_catalog_doc_problems),
        ("OBSERVABILITY.md", span_catalog_doc_problems),
    )
    out: List[Violation] = []
    for doc_rel, fn in groups:
        for problem in fn():
            out.append(Violation(doc_rel, 1, PASS_ID, problem))
    return out


def main() -> int:
    """The old tools/check_metrics.py CLI, preserved verbatim in
    behavior: exit 0 when clean; print each violation and exit 1."""
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    problems = (metric_catalog_problems() + faultpoint_doc_problems()
                + env_registry_doc_problems()
                + slo_catalog_doc_problems()
                + span_catalog_doc_problems())
    if problems:
        for p in problems:
            print(f"check_metrics: {p}", file=sys.stderr)
        return 1
    from gubernator_tpu.metrics import Metrics
    reg_set = {fam.name for fam in Metrics().registry.collect()}
    emitted = emitted_event_kinds(os.path.join(REPO, "gubernator_tpu"))
    print(f"check_metrics: OK ({len(reg_set)} metrics, "
          f"{len(emitted)} event kinds, all documented)")
    return 0

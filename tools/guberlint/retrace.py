"""retrace — jit call sites must be retrace-stable.

``jax.jit`` caches compiled programs by (shapes, dtypes, weak-type
flags, static-arg hashes).  A call site that drifts any of those
recompiles SILENTLY — a 250-305 s cold compile in the middle of
steady-state serving, surfacing only as a caller timeout (the exact
failure the dispatcher's stall watchdog was built for).  This pass
pins the two statically-checkable drift classes at every call site of
a jit-bound callable (``f = jax.jit(...)`` at module scope, or
``self._f = jax.jit(...)``):

- **dtype drift**: one positional slot fed Python-scalar ints at one
  site and floats (or a different ``np.<dtype>`` wrap) at another —
  each flavor compiles its own program, and alternating callers
  recompile per wave.  Weak-typed Python scalars are classified
  (``py-int`` / ``py-float`` / ``py-bool``) and only flagged when the
  slot actually sees more than one flavor.
- **unhashable statics**: a ``static_argnums`` / ``static_argnames``
  slot fed a list/dict/set literal — unhashable statics miss the
  cache on every single call.

Intentional drift (tests, escape hatches) is blessed with
``# retrace-ok: <reason>``.  The static pass is cross-checked at
runtime by the compile ledger (``gubernator_tpu/compileledger.py``):
what this pass proves about call sites, the ledger proves about the
live process — zero steady-state recompiles after warmup.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from . import Violation
from .engine import LintContext, unparse

PASS_ID = "retrace"

_NP_SCALARS = {"int8", "int16", "int32", "int64", "uint8", "uint16",
               "uint32", "uint64", "float16", "float32", "float64",
               "bool_"}
_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
               ast.SetComp)


def _call_name(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


def _static_spec(call: ast.Call):
    """(static positions, static names) declared on a jit(...) call."""
    pos, names = set(), set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                pos.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                pos.update(e.value for e in v.elts
                           if isinstance(e, ast.Constant)
                           and isinstance(e.value, int))
        elif kw.arg == "static_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                names.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                names.update(e.value for e in v.elts
                             if isinstance(e, ast.Constant)
                             and isinstance(e.value, str))
    return pos, names


def _kind(arg: ast.AST) -> Optional[str]:
    """Static dtype classification of a call argument, None = dynamic
    (an array / variable whose dtype this pass cannot see)."""
    if isinstance(arg, ast.Constant):
        if isinstance(arg.value, bool):
            return "py-bool"
        if isinstance(arg.value, int):
            return "py-int"
        if isinstance(arg.value, float):
            return "py-float"
        return None
    if isinstance(arg, ast.UnaryOp):
        return _kind(arg.operand)
    if isinstance(arg, ast.Call):
        f = arg.func
        if isinstance(f, ast.Name) and f.id in ("int", "float", "bool"):
            return f"py-{f.id}"
        if isinstance(f, ast.Attribute) and f.attr in _NP_SCALARS:
            return f.attr
    return None


def _blessed(sf, line: int) -> bool:
    return bool(sf.annotation(line, "retrace-ok")
                or sf.annotation(line - 1, "retrace-ok"))


def run(ctx: LintContext) -> List[Violation]:
    out: List[Violation] = []
    for sf in ctx.core_files():
        jitted: Dict[str, Tuple[set, set, int]] = {}
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Assign):
                continue
            v = node.value
            if not (isinstance(v, ast.Call) and _call_name(v) == "jit"):
                continue
            pos, names = _static_spec(v)
            for tgt in node.targets:
                jitted[unparse(tgt).replace(" ", "")] = (
                    pos, names, node.lineno)
        if not jitted:
            continue
        # (callable, position) -> {kind: [lines]}
        seen: Dict[Tuple[str, int], Dict[str, List[int]]] = {}
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            ftext = unparse(node.func).replace(" ", "")
            if ftext not in jitted:
                continue
            static_pos, static_names, decl_line = jitted[ftext]
            if node.lineno == decl_line:
                continue  # the jit(...) binding itself
            for i, arg in enumerate(node.args):
                if i in static_pos and isinstance(arg, _UNHASHABLE):
                    if not _blessed(sf, node.lineno):
                        out.append(Violation(
                            sf.rel, node.lineno, PASS_ID,
                            f"unhashable static argument "
                            f"{unparse(arg)[:40]!r} at position {i} of "
                            f"jit callable '{ftext}' — misses the jit "
                            f"cache and recompiles on EVERY call "
                            f"(bless with '# retrace-ok: <reason>')"))
                    continue
                k = _kind(arg)
                if k is not None:
                    seen.setdefault((ftext, i), {}) \
                        .setdefault(k, []).append(node.lineno)
            for kw in node.keywords:
                if kw.arg in static_names and \
                        isinstance(kw.value, _UNHASHABLE) and \
                        not _blessed(sf, node.lineno):
                    out.append(Violation(
                        sf.rel, node.lineno, PASS_ID,
                        f"unhashable static argument {kw.arg}= of jit "
                        f"callable '{ftext}' — misses the jit cache "
                        f"and recompiles on EVERY call (bless with "
                        f"'# retrace-ok: <reason>')"))
        for (ftext, i), kinds in sorted(seen.items()):
            if len(kinds) < 2:
                continue
            lines = sorted(ln for ls in kinds.values() for ln in ls)
            if any(_blessed(sf, ln) for ln in lines):
                continue
            out.append(Violation(
                sf.rel, lines[-1], PASS_ID,
                f"dtype drift at position {i} of jit callable "
                f"'{ftext}': call sites (lines "
                f"{', '.join(map(str, lines))}) pass "
                f"{' vs '.join(sorted(kinds))} — each flavor compiles "
                f"its own program; alternating callers recompile per "
                f"wave (pin one dtype, e.g. np.int64(...), or bless "
                f"with '# retrace-ok: <reason>')"))
    return out

"""Reproducible cProfile harness for the 1000-request wire call.

Decomposes one ``get_rate_limits_wire`` call into the PERF.md §4.2
buckets and prints them as JSON, so host-glue regressions (or wins —
ISSUE 2's overlapped wave pipeline) are measurable with one command:

    JAX_PLATFORMS=cpu python tools/hostpath_prof.py [--reqs 1000]
        [--reps 20]

Buckets (exclusive/tottime, summed per call):

- ``device_step``   — jax/XLA dispatch, transfers, and the blocking
                      result fetch (everything under the jax stack)
- ``parse_pack``    — C wire parse, key hashing, pack_columns, wave
                      routing + packed-buffer fill (core/batch.py,
                      hashing.py, parallel/sharded.py host helpers)
- ``dispatch_future`` — dispatcher machinery: queue/future/threading
                      handoffs, wave telemetry
- ``response_build`` — response serialization back to wire bytes
- ``other``         — everything else (pb2, instance routing, ...)

The split is by profile-entry attribution, so inclusive callers (e.g.
``get_rate_limits_wire`` itself) land in ``other`` only for their OWN
exclusive time — the buckets sum to the total.
"""
from __future__ import annotations

import argparse
import cProfile
import json
import os
import pstats
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NOW0 = 1_760_000_000_000


def _bucket_of(key) -> str:
    """Map one pstats entry key (file, line, name) to a §4.2 bucket."""
    filename, _line, name = key
    f = filename.replace("\\", "/")
    if "_native" in name or "build_rate_limit_resps" in name \
            or "build_responses_from_columns" in name \
            or "parse_get_rate_limits" in name \
            or "split_resp_items" in name:
        # C entry points: parse is ingest, builders are egress
        if "parse" in name or "split" in name:
            return "parse_pack"
        if "build" in name:
            return "response_build"
        return "parse_pack"
    if "/jax/" in f or "/jaxlib/" in f or "jax" in name.lower() \
            or "xla" in name.lower():
        return "device_step"
    if f.endswith("parallel/sharded.py") and name in (
            "_launch_arrays", "_finish_wave", "_launch_wave"):
        # the jitted step call is C-dispatched (no Python frame of its
        # own), so its time lands in the launching helper's exclusive
        # time — that IS the device dispatch+compute+fetch cost
        return "device_step"
    if f.endswith("dispatcher.py") or f.endswith("queue.py") \
            or f.endswith("threading.py") or "concurrent/futures" in f \
            or f.endswith("telemetry.py") or f.endswith("tracing.py"):
        return "dispatch_future"
    if f.endswith("core/batch.py") or f.endswith("hashing.py") \
            or (f.endswith("parallel/sharded.py")
                and name in ("_fill_packed", "_build_waves",
                             "_arrival_order", "pack_wave_host",
                             "lease", "_return")):
        return "parse_pack"
    if f.endswith("metrics.py") or "prometheus" in f:
        return "dispatch_future"
    return "other"


BUCKETS = ("device_step", "parse_pack", "dispatch_future",
           "response_build", "other")


def profile_wire_calls(inst, datas, reps: int, now0: int = NOW0 + 500
                       ) -> dict:
    """Profile ``reps`` wire calls on a WARM instance; returns the
    per-call §4.2 breakdown dict (bench.py folds this into the
    6_service_path row as ``host_glue``)."""
    prof = cProfile.Profile()
    prof.enable()
    for r in range(reps):
        inst.get_rate_limits_wire(datas[r % len(datas)],
                                  now_ms=now0 + r)
    prof.disable()
    st = pstats.Stats(prof)
    sums = {b: 0.0 for b in BUCKETS}
    for key, (_cc, _nc, tottime, _ct, _callers) in st.stats.items():
        sums[_bucket_of(key)] += tottime
    total = sum(sums.values())
    out = {"reps": reps,
           "total_ms_per_call": round(total / reps * 1e3, 3)}
    out["buckets_ms_per_call"] = {
        b: round(sums[b] / reps * 1e3, 3) for b in BUCKETS}
    host = total - sums["device_step"]
    out["host_glue_ms_per_call"] = round(host / reps * 1e3, 3)
    return out


def _mk_instance(cache_size: int):
    from gubernator_tpu.config import Config
    from gubernator_tpu.instance import V1Instance
    from gubernator_tpu.parallel import make_mesh

    return V1Instance(Config(cache_size=cache_size, sweep_interval_ms=0),
                      mesh=make_mesh(n=1))


def _mk_datas(n_reqs: int, n_batches: int = 4):
    import numpy as np

    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.types import RateLimitRequest
    from gubernator_tpu.wire import req_to_pb

    rng = np.random.default_rng(7)
    datas = []
    for _ in range(n_batches):
        m = pb.GetRateLimitsReq()
        m.requests.extend(
            req_to_pb(RateLimitRequest(
                name="prof", unique_key=f"k{int(k)}", hits=1,
                limit=100, duration=60_000))
            for k in rng.zipf(1.1, size=n_reqs) % 100_000)
        datas.append(m.SerializeToString())
    return datas


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--reqs", type=int, default=1000,
                    help="requests per wire call (default 1000)")
    ap.add_argument("--reps", type=int, default=20,
                    help="profiled calls (default 20)")
    ap.add_argument("--cache-size", type=int, default=1 << 16)
    args = ap.parse_args(argv)

    inst = _mk_instance(args.cache_size)
    try:
        datas = _mk_datas(args.reqs)
        # warm: compile both wave-bucket programs outside the profile
        if hasattr(inst.engine, "warmup"):
            inst.engine.warmup()
        inst.get_rate_limits_wire(datas[0], now_ms=NOW0)
        inst.get_rate_limits_wire(datas[1], now_ms=NOW0 + 1)
        out = profile_wire_calls(inst, datas, args.reps)
        out["reqs_per_call"] = args.reqs
        out["pipeline_depth"] = inst.dispatcher.debug_stats()[
            "pipeline_depth"]
        pool = getattr(inst.engine, "wave_pool", None)
        if pool is not None:
            out["buffer_pool"] = pool.stats()
        print(json.dumps(out))
    finally:
        inst.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())

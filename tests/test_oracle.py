"""M0 oracle unit tests — the §2.4 behavior contract, mirroring the
reference's functional tests (functional_test.go › TestTokenBucket,
TestLeakyBucket, TestOverTheLimit, TestChangeLimit, TestResetRemaining,
TestTokenBucketGregorian — reconstructed)."""
import datetime as dt


from gubernator_tpu import (
    Algorithm,
    Behavior,
    GregorianDuration,
    Oracle,
    RateLimitRequest,
    Status,
)
from gubernator_tpu.gregorian import gregorian_expiration

NOW = 1_760_000_000_000  # fixed epoch ms


def req(**kw):
    defaults = dict(name="test", unique_key="k", hits=1, limit=10,
                    duration=60_000, algorithm=Algorithm.TOKEN_BUCKET)
    defaults.update(kw)
    return RateLimitRequest(**defaults)


class TestTokenBucket:
    def test_basic_decrement(self):
        o = Oracle()
        for i in range(10):
            r = o.check(req(), NOW + i)
            assert r.status == Status.UNDER_LIMIT
            assert r.remaining == 9 - i
            assert r.limit == 10
            assert r.reset_time == NOW + 60_000

    def test_over_limit_no_decrement(self):
        o = Oracle()
        o.check(req(hits=10), NOW)
        r = o.check(req(hits=1), NOW + 1)
        assert r.status == Status.OVER_LIMIT
        assert r.remaining == 0
        # remaining unchanged by further over-limit hits
        r = o.check(req(hits=5), NOW + 2)
        assert r.status == Status.OVER_LIMIT
        assert r.remaining == 0

    def test_partial_over_limit_keeps_remaining(self):
        o = Oracle()
        o.check(req(hits=7), NOW)  # remaining 3
        r = o.check(req(hits=5), NOW + 1)  # 5 > 3 → OVER, no decrement
        assert r.status == Status.OVER_LIMIT
        assert r.remaining == 3
        r = o.check(req(hits=3), NOW + 2)  # still fits
        assert r.status == Status.UNDER_LIMIT
        assert r.remaining == 0

    def test_expiry_resets(self):
        o = Oracle()
        o.check(req(hits=10), NOW)
        r = o.check(req(hits=1), NOW + 60_000)  # exactly at expire_at
        assert r.status == Status.UNDER_LIMIT
        assert r.remaining == 9
        assert r.reset_time == NOW + 120_000

    def test_first_request_over_limit(self):
        o = Oracle()
        r = o.check(req(hits=11), NOW)
        assert r.status == Status.OVER_LIMIT
        assert r.remaining == 10  # fresh bucket not drained

    def test_hits_zero_is_pure_query(self):
        o = Oracle()
        o.check(req(hits=3), NOW)
        r = o.check(req(hits=0), NOW + 1)
        assert r.status == Status.UNDER_LIMIT
        assert r.remaining == 7
        # after an over-limit event the stored status is returned
        o.check(req(hits=100), NOW + 2)
        r = o.check(req(hits=0), NOW + 3)
        assert r.status == Status.OVER_LIMIT
        assert r.remaining == 7

    def test_change_limit_in_place(self):
        # functional_test.go › TestChangeLimit semantics
        o = Oracle()
        o.check(req(hits=1, limit=100), NOW)  # remaining 99
        r = o.check(req(hits=1, limit=50), NOW + 1)
        assert r.limit == 50
        assert r.remaining == 48  # 99 + (50-100) = 49, minus this hit
        r = o.check(req(hits=1, limit=200), NOW + 2)
        assert r.limit == 200
        assert r.remaining == 197

    def test_change_limit_clamps_at_zero(self):
        o = Oracle()
        o.check(req(hits=90, limit=100), NOW)  # remaining 10
        r = o.check(req(hits=0, limit=5), NOW + 1)
        assert r.remaining == 0  # 10 + (5-100) → clamped

    def test_change_duration_in_place(self):
        o = Oracle()
        o.check(req(hits=1), NOW)
        r = o.check(req(hits=1, duration=120_000), NOW + 1)
        assert r.reset_time == NOW + 120_000
        assert r.remaining == 8  # state preserved

    def test_change_duration_expiring_now_resets(self):
        o = Oracle()
        o.check(req(hits=5), NOW)
        # shrink duration so created+dur <= now → fresh bucket
        r = o.check(req(hits=1, duration=10), NOW + 50)
        assert r.status == Status.UNDER_LIMIT
        assert r.remaining == 9

    def test_reset_remaining(self):
        o = Oracle()
        o.check(req(hits=10), NOW)
        r = o.check(req(hits=1, behavior=Behavior.RESET_REMAINING), NOW + 1)
        assert r.status == Status.UNDER_LIMIT
        assert r.remaining == 9

    def test_drain_over_limit(self):
        o = Oracle()
        o.check(req(hits=7), NOW)  # remaining 3
        r = o.check(req(hits=5, behavior=Behavior.DRAIN_OVER_LIMIT), NOW + 1)
        assert r.status == Status.OVER_LIMIT
        assert r.remaining == 0  # drained

    def test_zero_limit(self):
        o = Oracle()
        r = o.check(req(hits=1, limit=0), NOW)
        assert r.status == Status.OVER_LIMIT
        assert r.remaining == 0


class TestGregorian:
    def test_minute_boundary(self):
        # 2026-01-15 10:30:30 UTC
        now = int(dt.datetime(2026, 1, 15, 10, 30, 30, tzinfo=dt.timezone.utc)
                  .timestamp() * 1000)
        end = gregorian_expiration(now, GregorianDuration.MINUTES)
        assert end == int(dt.datetime(2026, 1, 15, 10, 31, tzinfo=dt.timezone.utc)
                          .timestamp() * 1000)

    def test_month_boundary(self):
        now = int(dt.datetime(2026, 2, 10, tzinfo=dt.timezone.utc).timestamp() * 1000)
        end = gregorian_expiration(now, GregorianDuration.MONTHS)
        assert end == int(dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
                          .timestamp() * 1000)

    def test_week_starts_monday(self):
        # 2026-01-15 is a Thursday
        now = int(dt.datetime(2026, 1, 15, tzinfo=dt.timezone.utc).timestamp() * 1000)
        end = gregorian_expiration(now, GregorianDuration.WEEKS)
        assert end == int(dt.datetime(2026, 1, 19, tzinfo=dt.timezone.utc)
                          .timestamp() * 1000)

    def test_token_bucket_gregorian_reset(self):
        o = Oracle()
        now = int(dt.datetime(2026, 1, 15, 10, 30, 59, 500_000,
                              tzinfo=dt.timezone.utc).timestamp() * 1000)
        b = Behavior.DURATION_IS_GREGORIAN
        r = o.check(req(hits=5, duration=GregorianDuration.MINUTES, behavior=b), now)
        assert r.remaining == 5
        boundary = gregorian_expiration(now, GregorianDuration.MINUTES)
        assert r.reset_time == boundary
        # crossing the boundary resets every key
        r = o.check(req(hits=1, duration=GregorianDuration.MINUTES, behavior=b),
                    boundary + 1)
        assert r.remaining == 9


class TestLeakyBucket:
    def lreq(self, **kw):
        kw.setdefault("algorithm", Algorithm.LEAKY_BUCKET)
        return req(**kw)

    def test_fill_then_deny(self):
        o = Oracle()
        for i in range(10):
            r = o.check(self.lreq(), NOW)
            assert r.status == Status.UNDER_LIMIT, i
            assert r.remaining == 9 - i
        r = o.check(self.lreq(), NOW)
        assert r.status == Status.OVER_LIMIT
        assert r.remaining == 0

    def test_leak_replenishes_exactly(self):
        # limit 10 per 60s → one token per 6000 ms
        o = Oracle()
        for _ in range(10):
            o.check(self.lreq(), NOW)
        r = o.check(self.lreq(hits=0), NOW + 5_999)
        assert r.remaining == 0  # not yet a full token
        r = o.check(self.lreq(), NOW + 6_000)  # exactly one token leaked
        assert r.status == Status.UNDER_LIMIT
        assert r.remaining == 0

    def test_replenish_caps_at_burst(self):
        o = Oracle()
        o.check(self.lreq(hits=5), NOW)
        r = o.check(self.lreq(hits=0), NOW + 3_600_000)  # way past full
        assert r.remaining == 10

    def test_explicit_burst(self):
        o = Oracle()
        r = o.check(self.lreq(hits=15, burst=20), NOW)
        assert r.status == Status.UNDER_LIMIT
        assert r.remaining == 5

    def test_reset_time_is_one_token(self):
        o = Oracle()
        r = o.check(self.lreq(), NOW)
        assert r.reset_time == NOW + 6_000

    def test_sliding_expiry_forgets_idle_buckets(self):
        o = Oracle()
        for _ in range(10):
            o.check(self.lreq(), NOW)
        # idle for > duration → bucket forgotten, fresh burst available
        r = o.check(self.lreq(), NOW + 60_001)
        assert r.status == Status.UNDER_LIMIT
        assert r.remaining == 9

    def test_duration_change_rescales(self):
        o = Oracle()
        o.check(self.lreq(hits=4), NOW)  # remaining 6
        r = o.check(self.lreq(hits=0, duration=120_000), NOW)
        assert r.remaining == 6  # whole tokens preserved

    def test_drain_over_limit(self):
        o = Oracle()
        o.check(self.lreq(hits=8), NOW)
        r = o.check(self.lreq(hits=5, behavior=Behavior.DRAIN_OVER_LIMIT), NOW)
        assert r.status == Status.OVER_LIMIT
        assert r.remaining == 0

    def test_gregorian_flag_toggle_rescales_safely(self):
        # regression: behavior flag toggles between ms and Gregorian
        # interpretation of `duration` on the same key
        o = Oracle()
        o.check(self.lreq(hits=4, duration=60_000), NOW)  # remaining 6
        b = Behavior.DURATION_IS_GREGORIAN
        r = o.check(self.lreq(hits=0, duration=GregorianDuration.MINUTES,
                              behavior=b), NOW)
        assert r.remaining == 6  # whole tokens preserved, no crash
        r = o.check(self.lreq(hits=0, duration=60_000), NOW)
        assert r.remaining == 6  # and back

    def test_algorithm_switch_resets(self):
        o = Oracle()
        o.check(req(hits=5), NOW)
        r = o.check(self.lreq(hits=1), NOW + 1)
        assert r.remaining == 9  # token item replaced by fresh leaky


class TestHashing:
    def test_hash_stable_and_nonzero(self):
        from gubernator_tpu.hashing import hash_key, hash_keys
        h1 = hash_key("test", "k")
        assert h1 == hash_key("test", "k")
        assert h1 != 0
        import numpy as np
        hs = hash_keys(["test_k", "a_b", "a_c"])
        assert hs.dtype == np.uint64
        assert hs[0] == np.uint64(h1)
        assert len(set(hs.tolist())) == 3

    def test_shard_scalar_matches_array(self):
        # regression: scalar and vectorized shard_of must agree, including
        # non-power-of-two shard counts
        import numpy as np
        from gubernator_tpu.hashing import hash_keys, shard_of
        hs = hash_keys([f"k_{i}" for i in range(1000)])
        for n in (1, 2, 3, 5, 7, 8):
            arr = shard_of(hs, n)
            assert all(shard_of(int(h), n) == arr[i] for i, h in enumerate(hs))
            assert arr.min() >= 0 and arr.max() < n

    def test_shard_distribution(self):
        # hash_test.go analog: keys spread evenly across shards
        import numpy as np
        from gubernator_tpu.hashing import hash_keys, shard_of
        keys = [f"tenant{i}_user{i * 7}" for i in range(20_000)]
        shards = shard_of(hash_keys(keys), 8)
        counts = np.bincount(shards, minlength=8)
        assert counts.min() > 0.8 * counts.mean()
        assert counts.max() < 1.2 * counts.mean()

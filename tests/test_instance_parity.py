"""Instance-level parity: the FULL service stack (routing + dispatcher
+ engine + response assembly) must match the sequential oracle exactly
for every non-GLOBAL behavior.  (GLOBAL is eventually consistent by
contract and is covered by convergence tests instead.)"""
import numpy as np
import pytest

from gubernator_tpu import Algorithm, Behavior, Oracle, RateLimitRequest
from gubernator_tpu.config import Config
from gubernator_tpu.instance import V1Instance
from gubernator_tpu.parallel import make_mesh

NOW = 1_770_000_000_000


@pytest.fixture(scope="module")
def inst():
    i = V1Instance(Config(cache_size=1 << 12, sweep_interval_ms=0),
                   mesh=make_mesh(n=4))
    yield i
    i.close()


def test_mixed_stream_parity(inst):
    rng = np.random.default_rng(99)
    oracle = Oracle()
    now = NOW
    behaviors = [Behavior.BATCHING, Behavior.NO_BATCHING,
                 Behavior.RESET_REMAINING, Behavior.DRAIN_OVER_LIMIT,
                 Behavior.DURATION_IS_GREGORIAN]
    for wave in range(6):
        reqs = []
        for _ in range(200):
            b = behaviors[rng.integers(len(behaviors))]
            greg = bool(b & Behavior.DURATION_IS_GREGORIAN)
            reqs.append(RateLimitRequest(
                name="ipar", unique_key=f"k{rng.integers(0, 60)}",
                hits=int(rng.integers(0, 4)),
                limit=int(rng.integers(1, 25)),
                duration=int(rng.integers(0, 3)) if greg
                else int(rng.integers(500, 120_000)),
                algorithm=Algorithm.LEAKY_BUCKET
                if (not greg and rng.integers(2)) else Algorithm.TOKEN_BUCKET,
                behavior=b))
        want = oracle.check_batch(reqs, now)
        got = inst.get_rate_limits(reqs, now_ms=now)
        for i, (w, g) in enumerate(zip(want, got)):
            assert g.error == "", (i, g.error)
            assert (int(g.status), g.remaining, g.reset_time, g.limit) == \
                (int(w.status), w.remaining, w.reset_time, w.limit), \
                (wave, i, reqs[i])
        now += int(rng.integers(1, 30_000))


def test_invalid_gregorian_surfaces_error(inst):
    r = inst.get_rate_limits([RateLimitRequest(
        name="ipar", unique_key="bad", hits=1, limit=5, duration=99,
        behavior=Behavior.DURATION_IS_GREGORIAN)], now_ms=NOW)[0]
    assert "gregorian" in r.error

"""Overlapped wave pipeline semantics (ISSUE 2 tentpole).

The depth-K launch/sync pipeline + pooled wave buffers + caller-thread
response build must be INVISIBLE at the contract level: per-request
response bytes identical to the pure-Python oracle and to depth-1
(no-overlap) execution under 16 concurrent callers and ≥3 overlapped
waves; a mid-stream engine exception resolves only the affected wave's
jobs; buffer-pool leases come back on every path.
"""
import gc
import threading
import time

import numpy as np
import pytest

pytest.importorskip("gubernator_tpu.ops.native")

from gubernator_tpu.core.batch import WaveBufferPool, pack_columns
from gubernator_tpu.dispatcher import Dispatcher, ResultView
from gubernator_tpu.hashing import hash_request_keys
from gubernator_tpu.parallel import ShardedEngine, make_mesh

NOW = 1_781_000_000_000
N_THREADS = 16
N_CALLS = 4


def _mk_instance(monkeypatch, pipeline: str, depth: str, engine=None):
    from gubernator_tpu.config import Config
    from gubernator_tpu.instance import V1Instance

    monkeypatch.setenv("GUBER_PIPELINE", pipeline)
    monkeypatch.setenv("GUBER_PIPELINE_DEPTH", depth)
    mesh = None if engine is not None else make_mesh(n=1)
    return V1Instance(Config(cache_size=1 << 12, sweep_interval_ms=0),
                      mesh=mesh, engine=engine)


def _thread_datas():
    """Per-thread wire batches over THREAD-PRIVATE key namespaces, so
    results are deterministic under any caller interleaving (the shared
    engine applies each request at its own per-request now)."""
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.types import RateLimitRequest
    from gubernator_tpu.wire import req_to_pb

    datas = {}
    for t in range(N_THREADS):
        per_call = []
        for r in range(N_CALLS):
            m = pb.GetRateLimitsReq()
            m.requests.extend(
                req_to_pb(RateLimitRequest(
                    name="pipe", unique_key=f"t{t}k{i % 7}", hits=1,
                    limit=50, duration=60_000))
                for i in range(25))
            per_call.append(m.SerializeToString())
        datas[t] = per_call
    return datas


def _drive(inst, datas):
    """16 threads × N_CALLS wire calls; returns {(thread, call): bytes}."""
    out = {}
    lock = threading.Lock()

    def worker(t):
        for r in range(N_CALLS):
            raw = inst.get_rate_limits_wire(datas[t][r],
                                            now_ms=NOW + r)
            with lock:
                out[(t, r)] = raw

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(N_THREADS)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    return out


def test_overlapped_pipeline_byte_parity_oracle_and_depth1(monkeypatch):
    """≥3 overlapped waves under 16 concurrent callers: response bytes
    equal the oracle's and depth-1's, per request."""
    datas = _thread_datas()

    inst2 = _mk_instance(monkeypatch, pipeline="1", depth="2")
    try:
        got2 = _drive(inst2, datas)
        stats = inst2.dispatcher.debug_stats()
        assert stats["pipeline_depth"] == 2
        events = inst2.recorder.events()
        piped = [e for e in events if e["kind"] == "wave_launched"
                 and e.get("wave_kind") == "packed_pipelined"]
        assert len(piped) >= 3, (
            f"expected >=3 pipelined waves, got {len(piped)}")
        # the pipeline actually overlapped: some launch entered the
        # ring while an older wave was still in flight (slot > 0)
        assert any(e.get("slot", 0) > 0 for e in piped), piped[:5]
        pool = inst2.engine.wave_pool.stats()
        assert pool["outstanding"] == 0 and pool["leaks"] == 0, pool
    finally:
        inst2.close()

    inst1 = _mk_instance(monkeypatch, pipeline="1", depth="1")
    try:
        got1 = _drive(inst1, datas)
    finally:
        inst1.close()
    assert got1 == got2, "depth-1 vs depth-2 wire bytes diverged"

    # oracle reference: the pure-Python engine through the object path,
    # serialized with pb2 — must match the native-built wire bytes
    from gubernator_tpu.oracle import OracleEngine
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.wire import req_from_pb, resp_to_pb

    oracle_inst = _mk_instance(monkeypatch, pipeline="0", depth="1",
                               engine=OracleEngine())
    try:
        for (t, r), raw in sorted(got2.items()):
            msg = pb.GetRateLimitsReq.FromString(datas[t][r])
            reqs = [req_from_pb(m) for m in msg.requests]
            want = oracle_inst.get_rate_limits(reqs, now_ms=NOW + r)
            ref = pb.GetRateLimitsResp()
            ref.responses.extend(resp_to_pb(x) for x in want)
            assert raw == ref.SerializeToString(), (t, r)
    finally:
        oracle_inst.close()


@pytest.mark.parametrize("pipeline", ["0", "1"])
def test_midstream_engine_exception_fails_only_its_wave(pipeline,
                                                        monkeypatch):
    """An engine raise mid-stream resolves ONLY the affected wave's
    jobs with the error; earlier and later waves are untouched.
    Deterministic: the worker is held inside wave A while jobs B1/B2
    queue into wave B, whose sync/check raises."""
    monkeypatch.setenv("GUBER_PIPELINE", pipeline)
    monkeypatch.setenv("GUBER_PIPELINE_DEPTH", "2")
    eng = ShardedEngine(make_mesh(n=1), capacity_per_shard=1 << 9,
                        batch_per_shard=64)
    release = threading.Event()
    entered = threading.Event()
    calls = {"n": 0}
    orig_launch = eng.launch_packed
    orig_sync = eng.sync_packed
    orig_cp = eng.check_packed

    def gated_launch(batch, kh, now):
        calls["n"] += 1
        tag = calls["n"]
        if tag == 1:
            entered.set()
            release.wait(timeout=30)
        return (tag, orig_launch(batch, kh, now))

    def tagged_sync(token, engine_lock=None):
        tag, inner = token
        if tag == 2:
            raise RuntimeError("device on fire (wave B)")
        return orig_sync(inner, engine_lock=engine_lock)

    def gated_cp(batch, kh, now):
        calls["n"] += 1
        if calls["n"] == 1:
            entered.set()
            release.wait(timeout=30)
        if calls["n"] == 2:
            raise RuntimeError("device on fire (wave B)")
        return orig_cp(batch, kh, now)

    if pipeline == "1":
        eng.launch_packed = gated_launch
        eng.sync_packed = tagged_sync
    else:
        eng.check_packed = gated_cp
    disp = Dispatcher(eng, max_delay_ms=0.2)

    def cols(tag, now):
        kh = hash_request_keys(["pw"] * 4,
                               [f"{tag}{i}" for i in range(4)])
        b, _ = pack_columns(kh, np.ones(4, np.int64),
                            np.full(4, 50, np.int64),
                            np.full(4, 60_000, np.int64),
                            np.zeros(4, np.int32), np.zeros(4, np.int32),
                            np.zeros(4, np.int64), now)
        return b, kh

    results = {}

    def call(tag, now):
        b, kh = cols(tag, now)
        try:
            results[tag] = disp.check_packed(b, kh, now)
        except Exception as e:  # noqa: BLE001
            results[tag] = e

    # wave A blocks the worker inside the engine; B1/B2 queue behind it
    disp._inline_mu.acquire()
    try:
        threads = [threading.Thread(target=call, args=("a", NOW))]
        threads[0].start()
        assert entered.wait(timeout=30)
        threads.append(threading.Thread(target=call, args=("b1", NOW + 1)))
        threads.append(threading.Thread(target=call, args=("b2", NOW + 2)))
        for th in threads[1:]:
            th.start()
        deadline = time.monotonic() + 30
        while disp._queue.qsize() < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert disp._queue.qsize() >= 2
    finally:
        disp._inline_mu.release()
        release.set()
    for th in threads:
        th.join(timeout=60)
    # wave A resolved cleanly, both wave-B jobs carry THE error
    assert not isinstance(results["a"], Exception)
    assert results["a"][0].shape == (4,)
    for tag in ("b1", "b2"):
        assert isinstance(results[tag], RuntimeError), results[tag]
        assert "wave B" in str(results[tag])
    # the pipeline recovered: a later wave serves normally
    call("c", NOW + 3)
    assert not isinstance(results["c"], Exception), results["c"]
    # no lease stranded by the raise
    stats = eng.wave_pool.stats()
    assert stats["outstanding"] == 0 and stats["leaks"] == 0, stats
    disp.close()


def test_result_view_unpacks_like_tuple():
    cols = tuple(np.arange(10) + i for i in range(5))
    v = ResultView(cols, 2, 5)
    st, lim, rem, rst, full = v
    assert st.tolist() == [2, 3, 4]
    assert full.tolist() == [6, 7, 8]
    assert len(v) == 5
    assert v.sliced()[1].tolist() == [3, 4, 5]


def test_buffer_pool_reuse_error_release_and_leak_detection():
    pool = WaveBufferPool(max_per_width=2)
    l1 = pool.lease(128)
    l1.a64[0, 0] = 99
    l1.release()
    l2 = pool.lease(128)
    # pooled buffer comes back zeroed to empty-batch padding semantics
    assert l2.a64[0, 0] == 0 and l2.a64.shape == (8, 128)
    l2.release()
    l2.release()  # idempotent
    assert pool.stats()["hits"] == 1 and pool.stats()["misses"] == 1
    # a dropped lease is a counted leak, and its buffers are reclaimed
    l3 = pool.lease(128)
    del l3
    gc.collect()
    s = pool.stats()
    assert s["leaks"] == 1 and s["outstanding"] == 0, s


def test_engine_raise_releases_lease():
    eng = ShardedEngine(make_mesh(n=1), capacity_per_shard=1 << 9,
                        batch_per_shard=64)

    def boom(a64, a32, now):
        raise RuntimeError("launch failed")

    eng._launch_arrays = boom
    kh = hash_request_keys(["lr"] * 4, [f"k{i}" for i in range(4)])
    b, _ = pack_columns(kh, np.ones(4, np.int64),
                        np.full(4, 50, np.int64),
                        np.full(4, 60_000, np.int64),
                        np.zeros(4, np.int32), np.zeros(4, np.int32),
                        np.zeros(4, np.int64), NOW)
    with pytest.raises(RuntimeError, match="launch failed"):
        eng.check_packed(b, kh, NOW)
    s = eng.wave_pool.stats()
    assert s["outstanding"] == 0 and s["leaks"] == 0, s


def test_drain_wave_never_overshoots_max_wave():
    """A job that would push the wave past max_wave leads the NEXT wave
    (no sparse tail launch at the small bucket)."""

    class NopEngine:
        def check_packed(self, batch, khash, now):
            m = len(khash)
            return (np.zeros(m, np.int32), np.zeros(m, np.int64),
                    np.zeros(m, np.int64), np.zeros(m, np.int64),
                    np.zeros(m, bool))

    eng = NopEngine()
    sizes = []
    orig = eng.check_packed

    def spy(batch, kh, now):
        sizes.append(len(kh))
        return orig(batch, kh, now)

    eng.check_packed = spy
    disp = Dispatcher(eng, max_wave=2048, max_delay_ms=0.2)
    n = 1000
    kh = hash_request_keys(["ow"] * n, [f"k{i}" for i in range(n)])
    b, _ = pack_columns(kh, np.ones(n, np.int64),
                        np.full(n, 50, np.int64),
                        np.full(n, 60_000, np.int64),
                        np.zeros(n, np.int32), np.zeros(n, np.int32),
                        np.zeros(n, np.int64), NOW)
    # hold the inline mutex so all three jobs take the queue path, and
    # stall the worker's first wave until all are queued
    release = threading.Event()
    entered = threading.Event()

    def gated(batch, khash, now):
        entered.set()
        release.wait(timeout=30)
        return spy(batch, khash, now)

    eng.check_packed = gated
    threads = []
    disp._inline_mu.acquire()
    try:
        for t in range(4):
            th = threading.Thread(
                target=lambda t=t: disp.check_packed(b, kh, NOW + t))
            th.start()
            threads.append(th)
            if t == 0:
                assert entered.wait(timeout=30)
        deadline = time.monotonic() + 30
        while disp._queue.qsize() < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert disp._queue.qsize() >= 3
    finally:
        disp._inline_mu.release()
        release.set()
    for th in threads:
        th.join(timeout=60)
    # wave 1: the blocker alone; wave 2: exactly two jobs (2000 rows,
    # within max_wave 2048); wave 3: the carried job
    assert sizes == [1000, 2000, 1000], sizes
    disp.close()


def test_coalesce_window_env_override(monkeypatch):
    class E:
        def check_batch(self, reqs, now):
            return []

    monkeypatch.setenv("GUBER_COALESCE_US", "50000")
    d = Dispatcher(E())
    try:
        assert d.max_delay_s == pytest.approx(0.05)
    finally:
        d.close()
    monkeypatch.setenv("GUBER_COALESCE_US", "0")
    d = Dispatcher(E())
    try:
        assert d.max_delay_s == 0.0
    finally:
        d.close()
    monkeypatch.setenv("GUBER_COALESCE_US", "junk")
    d = Dispatcher(E())
    try:
        assert d.max_delay_s == pytest.approx(0.0002)
    finally:
        d.close()


def test_pipeline_depth_env_parsing(monkeypatch):
    class E:
        def check_batch(self, reqs, now):
            return []

    for raw, want in (("4", 4), ("1", 1), ("0", 1), ("-3", 1),
                      ("junk", 2), ("", 2)):
        monkeypatch.setenv("GUBER_PIPELINE_DEPTH", raw)
        d = Dispatcher(E())
        try:
            assert d.pipeline_depth == want, raw
        finally:
            d.close()

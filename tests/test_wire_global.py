"""Columnar solo-GLOBAL wire lane: the hot-set psum tier driven from
wire bytes (instance._wire_global_runner), vs the object path."""
import pytest

from gubernator_tpu.config import BehaviorConfig, Config
from gubernator_tpu.hashing import hash_key
from gubernator_tpu.instance import V1Instance, _wire_native
from gubernator_tpu.parallel import make_mesh
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.types import Behavior, RateLimitRequest
from gubernator_tpu.wire import req_to_pb

if _wire_native is None:  # pragma: no cover
    pytest.skip("native extension not built", allow_module_level=True)

NOW = 1_773_000_000_000


def mk_instance(threshold=4):
    # sync_wait effectively infinite: these tests assert exact
    # replica-local values, so the periodic psum fold must only run
    # when called explicitly (a tick mid-test legally changes
    # remaining — GLOBAL is eventually consistent)
    return V1Instance(
        Config(cache_size=1 << 10, sweep_interval_ms=0,
               hot_set_capacity=64, hot_promote_threshold=threshold,
               behaviors=BehaviorConfig(global_sync_wait_ms=10**9)),
        mesh=make_mesh(n=4))


def greq(key="wg", hits=1, limit=1000, duration=600_000, **kw):
    kw.setdefault("behavior", Behavior.GLOBAL)
    return RateLimitRequest(name="wgl", unique_key=key, hits=hits,
                            limit=limit, duration=duration, **kw)


def wire(reqs):
    m = pb.GetRateLimitsReq()
    m.requests.extend(req_to_pb(r) for r in reqs)
    return m.SerializeToString()


def send(inst, reqs, now):
    return list(pb.GetRateLimitsResp.FromString(
        inst.get_rate_limits_wire(wire(reqs), now_ms=now)).responses)


def test_wire_global_promotes_then_serves_hot():
    inst = mk_instance(threshold=4)
    try:
        kh = hash_key("wgl", "wg")
        rs = send(inst, [greq() for _ in range(6)], NOW)
        assert all(r.error == "" and int(r.status) == 0 for r in rs)
        # threshold crossed inside the batch → pinned after the drain
        assert inst._hotset is not None and inst._hotset.is_pinned(kh)
        # hot serving: replicas answer; one sync folds consumption
        rs = send(inst, [greq() for _ in range(40)], NOW + 1)
        assert all(r.error == "" and int(r.status) == 0 for r in rs)
        inst._hotset.sync()
        rs = send(inst, [greq(hits=0)] * 4, NOW + 2)
        assert len({r.remaining for r in rs}) == 1
        # 6 pre-promotion hits survive in the seed + 40 hot hits
        assert rs[0].remaining == 1000 - 46
    finally:
        inst.close()


def test_wire_vs_object_path_parity():
    """The same solo-GLOBAL stream through the wire lane and the object
    path lands on identical decisions (same engines, same routing)."""
    wi, oi = mk_instance(), mk_instance()
    try:
        streams = [[greq(key=f"k{i % 3}") for i in range(12)]
                   for _ in range(4)]
        for t, reqs in enumerate(streams):
            got_w = send(wi, reqs, NOW + t)
            got_o = oi.get_rate_limits(reqs, now_ms=NOW + t)
            for i, (w, o) in enumerate(zip(got_w, got_o)):
                assert (int(w.status), w.remaining, w.reset_time,
                        w.limit, w.error) == \
                    (int(o.status), o.remaining, o.reset_time, o.limit,
                     o.error), (t, i)
        assert wi._hotset is not None and len(wi._hotset.slots) == 3
        assert len(oi._hotset.slots) == 3
    finally:
        wi.close()
        oi.close()


def test_wire_global_config_change_demotes():
    inst = mk_instance(threshold=1)
    try:
        kh = hash_key("wgl", "cfg")
        send(inst, [greq(key="cfg", limit=100)], NOW)
        send(inst, [greq(key="cfg", limit=100) for _ in range(10)],
             NOW + 1)
        assert inst._hotset.is_pinned(kh)
        # changed limit → object-path fallback demotes and re-limits
        r = send(inst, [greq(key="cfg", limit=50)], NOW + 2)[0]
        assert not inst._hotset.is_pinned(kh)
        assert r.limit == 50
        # 11 consumed at limit 100 → 89; 100→50 adjust → 39; −1 → 38
        assert r.remaining == 38
    finally:
        inst.close()


def test_wire_global_flagged_pinned_key_falls_back():
    inst = mk_instance(threshold=1)
    try:
        kh = hash_key("wgl", "flg")
        send(inst, [greq(key="flg")], NOW)
        send(inst, [greq(key="flg")], NOW + 1)
        assert inst._hotset.is_pinned(kh)
        r = send(inst, [greq(
            key="flg",
            behavior=Behavior.GLOBAL | Behavior.RESET_REMAINING)],
            NOW + 2)[0]
        assert not inst._hotset.is_pinned(kh)  # demoted by object path
        assert r.remaining == 999  # RESET_REMAINING → full minus 1
    finally:
        inst.close()


def test_wire_mixed_global_and_local_batch():
    inst = mk_instance(threshold=2)
    try:
        reqs = [greq(key="mix") if i % 2 == 0 else
                RateLimitRequest(name="wgl", unique_key="loc", hits=1,
                                 limit=5, duration=60_000)
                for i in range(8)]
        rs = send(inst, reqs, NOW)
        assert all(r.error == "" for r in rs)
        # local key consumed 4 of 5
        assert rs[7].remaining == 1
    finally:
        inst.close()


def test_wire_global_leaky_rides_hot_tier():
    from gubernator_tpu.types import Algorithm

    inst = mk_instance(threshold=2)
    try:
        kh = hash_key("wgl", "lk")
        lr = [greq(key="lk", algorithm=Algorithm.LEAKY_BUCKET)
              for _ in range(10)]
        rs = send(inst, lr, NOW)
        assert all(int(r.status) == 0 for r in rs)
        assert inst._hotset.is_pinned(kh)
        rs = send(inst, lr, NOW + 1)
        assert all(int(r.status) == 0 for r in rs)
        inst._hotset.sync()
        rs = send(inst, [greq(key="lk", hits=0,
                              algorithm=Algorithm.LEAKY_BUCKET)],
                  NOW + 2)
        assert rs[0].remaining == 1000 - 20
    finally:
        inst.close()

"""Scenario lab (ISSUE 16): spec round-trips, schedule determinism,
per-stack smoke runs, oracle firing, and the clock-skew regression pin.

The determinism contract is the headline: the same spec + seed must
replay a byte-identical decision stream across two runs, and every
committed spec must serialize/round-trip losslessly.  The clock-skew
pin proves the PR-6 ``created_at`` first-hop-wins discipline END TO END
under the DSL: clients skewed ±5 s produce the same decision stream as
an unskewed twin — and flipping ``GUBER_CREATED_AT_FWD=0`` (the
pre-fix behavior) must break that equality, or the test pins nothing.
"""
import copy
import json
import os

import pytest

from gubernator_tpu import scenarios as scn
from gubernator_tpu.scenarios import (
    DecisionDigest,
    JudgeTap,
    ScenarioRunner,
    ScenarioSpec,
    compile_schedule,
    jain_index,
)
from gubernator_tpu.types import RateLimitRequest, RateLimitResponse

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "scenarios")


def _small(name="t", stack="object", **kw):
    kw.setdefault("seed", 9)
    kw.setdefault("ticks", 3)
    kw.setdefault("tick_ms", 250)
    kw.setdefault("clients", 2)
    kw.setdefault("sources", [
        {"kind": "zipf_drift", "name": "sm", "rows": 12, "n_keys": 10,
         "a0": 1.3, "a1": 1.8, "limit": 5000, "duration": 3_600_000}])
    kw.setdefault("oracles", ["parity", "conservation"])
    return ScenarioSpec(name=name, stack=stack, **kw)


# ---------------------------------------------------------------------------
# DSL: serialization, validation, schedule determinism


def test_spec_roundtrip_lossless():
    spec = _small(skew_ms=[-5, 5], expect={"jain_min": 0.2},
                  faults=[{"at_tick": 1, "arm": "device_step:error",
                           "seed": 3}],
                  fast={"ticks": 2, "rows_scale": 0.5})
    d = spec.to_dict()
    again = ScenarioSpec.from_dict(copy.deepcopy(d))
    assert again == spec
    assert again.to_dict() == d
    # JSON round trip too (what save_spec/load_spec do)
    assert ScenarioSpec.from_dict(
        json.loads(json.dumps(d))).to_dict() == d


def test_library_specs_load_validate_and_roundtrip():
    """Every committed spec parses, validates, compiles, and
    round-trips byte-losslessly — the spec library is the payload."""
    names = set()
    files = [f for f in sorted(os.listdir(LIB)) if f.endswith(".json")]
    assert len(files) >= 7, files
    stacks = set()
    for fn in files:
        with open(os.path.join(LIB, fn)) as f:
            raw = json.load(f)
        spec = ScenarioSpec.from_dict(raw)
        assert spec.to_dict() == raw, f"{fn} does not round-trip"
        names.add(spec.name)
        stacks.add(spec.stack)
        fast = spec.with_fast()
        sched = compile_schedule(fast)
        assert len(sched) == fast.ticks
        assert any(any(c for c in tick) for tick in sched), \
            f"{fn} compiles to an empty schedule"
    assert len(names) == len(files), "duplicate scenario names"
    assert stacks == set(scn.STACKS), \
        f"library must cover every stack class, got {stacks}"


def test_spec_validation_rejects_unknowns():
    with pytest.raises(ValueError, match="unknown scenario keys"):
        ScenarioSpec.from_dict({"name": "x", "bogus": 1})
    with pytest.raises(ValueError, match="unknown stack"):
        _small(stack="warp").validate()
    with pytest.raises(ValueError, match="unknown source kind"):
        ScenarioSpec(name="x", sources=[{"kind": "nope"}]).validate()
    with pytest.raises(ValueError, match="unknown oracle"):
        ScenarioSpec(name="x", oracles=["vibes"]).validate()
    with pytest.raises(ValueError, match="one offset per client"):
        ScenarioSpec(name="x", clients=3, skew_ms=[1]).validate()
    with pytest.raises(ValueError, match="schema"):
        ScenarioSpec.from_dict({"schema": 99, "name": "x"})


def test_schedule_is_deterministic_and_seed_sensitive():
    spec = _small()
    a = compile_schedule(spec)
    b = compile_schedule(spec)
    assert a == b  # RateLimitRequest is a frozen-enough dataclass: ==
    c = compile_schedule(ScenarioSpec.from_dict(
        {**spec.to_dict(), "seed": spec.seed + 1}))
    assert a != c, "seed must steer the schedule"


def test_sources_shape_traffic():
    """Each primitive leaves its fingerprint on the compiled rows."""
    # flash crowd: celebrity rows only inside the window
    spec = _small(sources=[{"kind": "flash_crowd", "name": "f",
                            "rows": 4, "n_keys": 50,
                            "celebrity": "star", "start_tick": 1,
                            "stop_tick": 2, "crowd_rows": 9}],
                  ticks=3)
    sched = compile_schedule(spec)
    per_tick = [sum(1 for c in tick for r in c
                    if r.unique_key == "star") for tick in sched]
    assert per_tick[0] == 0 and per_tick[1] == 9 and per_tick[2] == 0
    # tenant mix: ~90/10 split lands on tenant-prefixed names
    spec = _small(sources=[{"kind": "tenant_mix", "name": "api",
                            "rows": 200, "tenants": [
                                {"tenant": "hog", "weight": 90,
                                 "n_keys": 3},
                                {"tenant": "tiny", "weight": 10,
                                 "n_keys": 3}]}], ticks=1)
    rows = [r for c in compile_schedule(spec)[0] for r in c]
    hog = sum(1 for r in rows if r.name.startswith("hog/"))
    assert 150 < hog < 200 and len(rows) == 200
    # diurnal: volume varies across the period
    spec = _small(sources=[{"kind": "diurnal", "rows": 20,
                            "period_ticks": 4, "amplitude": 0.9,
                            "n_keys": 5}], ticks=4)
    vols = [sum(len(c) for c in tick)
            for tick in compile_schedule(spec)]
    assert max(vols) > min(vols)


def test_jain_index_bounds():
    assert jain_index([5, 5, 5, 5]) == pytest.approx(1.0)
    assert jain_index([100, 0, 0, 0]) == pytest.approx(1.0)  # 1 active
    assert jain_index([97, 1, 1, 1]) < 0.3
    assert jain_index([]) == 1.0


def test_judge_tap_retains_then_attributes():
    j = JudgeTap(delim="/")
    reqs = [RateLimitRequest(name="a/x", unique_key="k", hits=2,
                             limit=10, duration=1000),
            RateLimitRequest(name="b/x", unique_key="k", hits=1,
                             limit=10, duration=1000)]
    resps = [RateLimitResponse(status=0, limit=10, remaining=8,
                               reset_time=1),
             RateLimitResponse(status=1, limit=10, remaining=0,
                               reset_time=1)]
    j.observe(reqs, resps, 0)
    assert j.total == 0  # service-path half only retains
    j.finalize()
    assert j.total == 2 and j.over_limit == 1
    assert j.admitted == {"a/x_k": 2}
    assert j.tenants["a"]["admitted_hits"] == 2
    assert j.tenants["b"]["over_limit"] == 1
    d = DecisionDigest()
    d.update(0, 8, "")
    d.update(1, 0, "")
    assert j.digest.hex() == d.hex()
    j.finalize()  # idempotent
    assert j.total == 2


# ---------------------------------------------------------------------------
# runner: determinism + one small scenario per stack class


def test_run_replays_byte_identical_decision_stream():
    """Acceptance: same spec + seed -> byte-identical decision stream
    across two full runs (fresh stack each time)."""
    spec = _small(name="det")
    rows = [ScenarioRunner(spec).run() for _ in range(2)]
    assert rows[0]["decision_digest"] == rows[1]["decision_digest"]
    assert rows[0]["ok"] and rows[1]["ok"]
    assert rows[0]["requests"] == rows[1]["requests"] > 0


def test_smoke_object_stack_parity_and_conservation():
    row = ScenarioRunner(_small(name="sm_obj")).run()
    assert row["ok"], row
    assert row["oracles"]["parity"]["ok"]
    assert row["oracles"]["conservation"]["ok"]
    assert row["requests"] > 0 and row["error_rows"] == 0


def test_smoke_wire_stack():
    pytest.importorskip("gubernator_tpu.ops._native",
                        reason="wire lane needs the C++ codec")
    row = ScenarioRunner(_small(name="sm_wire", stack="wire")).run()
    assert row["ok"], row


def test_smoke_tiered_stack():
    row = ScenarioRunner(
        _small(name="sm_tier", stack="tiered",
               sources=[{"kind": "uniform", "name": "sm", "rows": 24,
                         "n_keys": 300, "limit": 5000,
                         "duration": 3_600_000}])).run()
    assert row["ok"], row


def test_smoke_mesh_stack():
    row = ScenarioRunner(
        _small(name="sm_mesh", stack="mesh",
               sources=[
                   {"kind": "uniform", "name": "g", "rows": 8,
                    "n_keys": 4, "behavior": "global",
                    "limit": 50_000, "duration": 3_600_000},
                   {"kind": "uniform", "name": "p", "rows": 8,
                    "n_keys": 6, "limit": 50_000,
                    "duration": 3_600_000}],
               oracles=["conservation"])).run()
    assert row["ok"], row


def test_smoke_clustered_stack_with_fairness():
    """Clustered smoke + the 90/10 fairness oracle firing for real:
    Jain's index lands in the unfair band and the run stays exact."""
    spec = _small(
        name="sm_clu", stack="clustered", clients=2, ticks=3,
        sources=[{"kind": "tenant_mix", "name": "api", "rows": 30,
                  "limit": 100_000, "duration": 3_600_000,
                  "tenants": [
                      {"tenant": "hog", "weight": 90, "n_keys": 3},
                      {"tenant": "t1", "weight": 5, "n_keys": 2},
                      {"tenant": "t2", "weight": 5, "n_keys": 2}]}],
        oracles=["conservation", "fairness"],
        expect={"jain_min": 0.05, "jain_max": 0.75})
    row = ScenarioRunner(spec).run(fast=True)
    assert row["ok"], row
    assert 0.0 < row["jain_index"] < 0.9
    assert row["oracles"]["conservation"]["ok"]


def test_fairness_oracle_exact_ledger_conservation():
    """Solo stack: the analytics plane's per-tenant (requests, hits)
    must equal the judge's own counts exactly."""
    spec = _small(
        name="fair", stack="object", ticks=4,
        sources=[{"kind": "tenant_mix", "name": "api", "rows": 40,
                  "limit": 100_000, "duration": 3_600_000,
                  "tenants": [
                      {"tenant": "abuser", "weight": 9, "n_keys": 4},
                      {"tenant": "meek", "weight": 1, "n_keys": 4}]}],
        oracles=["fairness"], expect={"jain_min": 0.1,
                                      "jain_max": 0.9})
    row = ScenarioRunner(spec).run()
    fair = row["oracles"]["fairness"]
    assert fair["ok"], fair
    assert fair["ledger_conserved"] is True
    assert fair["ledger_mismatches"] == []
    assert row["ok"], row


def test_partition_scenario_conserves_after_reconcile():
    """The committed partition spec (fast mode): hits admitted during
    the partition debit exactly once after the heal — the conservation
    oracle converges to zero mismatches."""
    spec = scn.load_spec(
        os.path.join(LIB, "partition_reconcile.json"))
    row = ScenarioRunner(spec, fast=True).run(fast=True)
    assert row["ok"], row
    cons = row["oracles"]["conservation"]
    assert cons["ok"] and cons["mismatches"] == []
    assert cons["keys"] > 0


def test_replay_capture_assembles_end_to_end():
    """The committed trace capture replays through a fresh cluster and
    the new run's spans assemble into stitched multi-span traces."""
    spec = scn.load_spec(os.path.join(LIB, "replay_trace.json"))
    row = ScenarioRunner(spec, fast=True).run(fast=True)
    assert row["ok"], row
    tr = row["oracles"]["trace_assembly"]
    assert tr["assembled"] >= 1 and tr["spans"] > 0


def test_scenario_events_and_metric_recorded():
    spec = _small(name="ev", oracles=[])
    runner = ScenarioRunner(spec)
    handle = runner._build()
    handle.close()
    row = runner.run()
    assert row["ok"]
    # the runner's own instance is closed; assert via a fresh run's
    # recorder by driving the pieces directly
    h = ScenarioRunner(_small(name="ev2", oracles=[]))._build()
    try:
        inst = h.instances[0]
        r = ScenarioRunner(_small(name="ev2", oracles=[]))
        judge = JudgeTap()
        r._drive(h, judge)
        inst.recorder.record("scenario_started", name="ev2")
        inst.recorder.record("scenario_finished", name="ev2", ok=True)
        kinds = {e["kind"] for e in inst.recorder.events()}
        assert {"scenario_started", "scenario_finished"} <= kinds
        inst.metrics.scenario_runs.labels(verdict="ok").inc()
    finally:
        h.close()


# ---------------------------------------------------------------------------
# clock-skew regression pin (satellite): created_at first-hop-wins


def _skew_spec(skew):
    return ScenarioSpec(
        name="skewpin", stack="clustered", seed=77, ticks=3,
        tick_ms=1000, clients=3, daemons=3, skew_ms=skew,
        sources=[{"kind": "zipf_drift", "name": "skw", "rows": 10,
                  "n_keys": 12, "a0": 1.4, "a1": 1.4, "limit": 5000,
                  "duration": 86_400_000}],
        oracles=[])


def test_clock_skew_decisions_byte_identical_to_unskewed():
    """±5 s client skew must not change a single decision: created_at
    rides the first hop, owners apply rows at the caller's time base,
    and token-bucket windows dwarf the skew."""
    skewed = ScenarioRunner(_skew_spec([-5000, 0, 5000])).run()
    unskewed = ScenarioRunner(_skew_spec([])).run()
    assert skewed["requests"] == unskewed["requests"] > 0
    assert skewed["error_rows"] == unskewed["error_rows"] == 0
    assert skewed["decision_digest"] == unskewed["decision_digest"]


def test_clock_skew_pin_is_sharp(monkeypatch):
    """GUBER_CREATED_AT_FWD=0 (the pre-PR-6 escape: owners stamp their
    own wall clock on forwarded rows) must BREAK the byte-identity —
    the owner's real clock sits years past the virtual NOW0, so every
    forwarded bucket expires on arrival and the decision stream
    visibly diverges.  If this stops failing, the pin above proves
    nothing."""
    monkeypatch.setenv("GUBER_CREATED_AT_FWD", "0")
    skewed = ScenarioRunner(_skew_spec([-5000, 0, 5000])).run()
    unskewed_digest = None
    monkeypatch.delenv("GUBER_CREATED_AT_FWD")
    unskewed = ScenarioRunner(_skew_spec([])).run()
    unskewed_digest = unskewed["decision_digest"]
    assert skewed["decision_digest"] != unskewed_digest

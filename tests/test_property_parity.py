"""Property-based parity fuzzing (hypothesis): for ANY request stream,
the device engine must match the sequential oracle bit-for-bit, and
oracle invariants must hold."""
import numpy as np
from hypothesis import HealthCheck, given, settings
import os as _os

#: deep-fuzz multiplier: GUBER_FUZZ_X=20 turns the quick CI
#: budgets into a long adversarial run (same strategies)
_FX = int(_os.environ.get("GUBER_FUZZ_X", "1"))
from hypothesis import strategies as st

from gubernator_tpu import Algorithm, Behavior, Oracle, RateLimitRequest
from gubernator_tpu.parallel import ShardedEngine, make_mesh

NOW = 1_771_000_000_000

_behavior = st.sampled_from([
    Behavior.BATCHING, Behavior.NO_BATCHING, Behavior.RESET_REMAINING,
    Behavior.DRAIN_OVER_LIMIT,
    Behavior.RESET_REMAINING | Behavior.DRAIN_OVER_LIMIT,
])

_request = st.builds(
    RateLimitRequest,
    name=st.just("prop"),
    unique_key=st.integers(0, 11).map(lambda i: f"k{i}"),  # forced dups
    hits=st.integers(0, 6),
    limit=st.integers(0, 30),
    duration=st.integers(1, 50_000),
    algorithm=st.sampled_from([Algorithm.TOKEN_BUCKET,
                               Algorithm.LEAKY_BUCKET]),
    behavior=_behavior,
    burst=st.integers(0, 40),
)

_stream = st.lists(
    st.tuples(st.lists(_request, min_size=1, max_size=40),
              st.integers(0, 40_000)),  # time advance per batch
    min_size=1, max_size=5)


@settings(max_examples=_FX * 25, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(_stream)
def test_engine_matches_oracle_on_any_stream(stream):
    # fixed shapes across examples → one compiled program (cache hit)
    eng = ShardedEngine(make_mesh(n=2), capacity_per_shard=1 << 10,
                        batch_per_shard=64)
    oracle = Oracle()
    now = NOW
    for reqs, dt in stream:
        now += dt
        want = oracle.check_batch(reqs, now)
        got = eng.check_batch(reqs, now)
        for i, (w, g) in enumerate(zip(want, got)):
            assert g.error == ""
            assert (int(g.status), g.remaining, g.reset_time, g.limit) == \
                (int(w.status), w.remaining, w.reset_time, w.limit), \
                (i, reqs[i])


@settings(max_examples=_FX * 200, deadline=None)
@given(_request, st.integers(0, 10**6))
def test_oracle_invariants(req, dt):
    """remaining ∈ [0, max(limit,burst)], reset_time ≥ now, and a
    hits=0 query never mutates state."""
    o = Oracle()
    r1 = o.check(req, NOW)
    assert 0 <= r1.remaining <= max(req.limit, req.burst, 0)
    assert r1.reset_time >= NOW
    frozen = {k: {s: getattr(v, s) for s in v.__slots__}
              for k, v in o.items.items()}
    q = RateLimitRequest(name=req.name, unique_key=req.unique_key, hits=0,
                         limit=req.limit, duration=req.duration,
                         algorithm=req.algorithm, behavior=Behavior.BATCHING,
                         burst=req.burst)
    o.check(q, NOW + dt)
    # hits=0 may advance leaky bookkeeping (replenish timestamps) but
    # must never DECREASE remaining
    for k, item in o.items.items():
        assert item.remaining >= frozen[k]["remaining"]


@settings(max_examples=_FX * 20, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(st.lists(st.tuples(st.lists(_request, min_size=1, max_size=12),
                          st.integers(0, 5_000)),
                min_size=2, max_size=4))
def test_merged_cross_time_batch_matches_sequential_oracle(jobs):
    """One engine launch holding several jobs packed at DIFFERENT times
    (per-request now column) must equal sequential per-time application
    — including RESET/DRAIN flags and algorithm mixes on keys whose
    requests straddle instants (the while_loop path with non-uniform
    now)."""
    import numpy as np

    from gubernator_tpu.core.batch import pack_requests
    from gubernator_tpu.hashing import hash_request_keys

    eng = ShardedEngine(make_mesh(n=2), capacity_per_shard=1 << 10,
                        batch_per_shard=64)
    oracle = Oracle()
    now = NOW
    packed_parts = []
    want_parts = []
    for reqs, dt in jobs:
        now += dt
        kh = hash_request_keys([r.name for r in reqs],
                               [r.unique_key for r in reqs])
        b, errs = pack_requests(reqs, now, size=len(reqs), key_hashes=kh)
        assert not any(errs)
        packed_parts.append((b, kh))
        want_parts.append(oracle.check_batch(reqs, now))
    batch = type(packed_parts[0][0])(*[
        np.concatenate([np.asarray(p[0][f]) for p in packed_parts])
        for f in range(len(packed_parts[0][0]))])
    khash = np.concatenate([p[1] for p in packed_parts])
    st_, lim, rem, rst, full = eng.check_packed(batch, khash, now)
    assert not full.any()
    g = 0
    for (reqs, _), want in zip(jobs, want_parts):
        for i, w in enumerate(want):
            assert (int(st_[g]), int(rem[g]), int(rst[g]), int(lim[g])) \
                == (int(w.status), w.remaining, w.reset_time, w.limit), \
                (g, i, reqs[i])
            g += 1


_i64_request = st.builds(
    RateLimitRequest,
    name=st.just("prop64"),
    unique_key=st.integers(0, 7).map(lambda i: f"w{i}"),  # forced dups
    hits=st.integers(0, 2**40),
    limit=st.integers(0, 2**50),
    # spans the interesting clamp boundaries: FRAC_SAFE (2^31),
    # EFF_MAX (2^35), DURATION_MAX (2^53) and beyond
    duration=st.one_of(
        st.integers(1, 10**6),
        st.integers(2**31 - 10, 2**31 + 10),
        st.integers(2**35 - 10, 2**35 + 10),
        st.integers(2**40, 2**60)),
    algorithm=st.sampled_from([Algorithm.TOKEN_BUCKET,
                               Algorithm.LEAKY_BUCKET]),
    behavior=_behavior,
    burst=st.integers(0, 2**45),
)


@settings(max_examples=_FX * 25, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(st.lists(
    st.tuples(st.lists(_i64_request, min_size=1, max_size=24),
              st.integers(0, 2**36)),  # time jumps past leaky windows
    min_size=1, max_size=4))
def test_engine_matches_oracle_on_int64_ranges(stream):
    """The round-2 int64 clamp contract (DURATION_MAX/EFF_MAX/TD_BOUND
    + the rescale/replenish guards) must hold bit-for-bit for ANY
    stream mixing calendar-scale durations, clamp-boundary values, and
    duration changes on live keys."""
    eng = ShardedEngine(make_mesh(n=2), capacity_per_shard=1 << 10,
                        batch_per_shard=64)
    oracle = Oracle()
    now = NOW
    for reqs, dt in stream:
        now += dt
        want = oracle.check_batch(reqs, now)
        got = eng.check_batch(reqs, now)
        for i, (w, g) in enumerate(zip(want, got)):
            assert g.error == ""
            assert (int(g.status), g.remaining, g.reset_time, g.limit) == \
                (int(w.status), w.remaining, w.reset_time, w.limit), \
                (i, reqs[i])

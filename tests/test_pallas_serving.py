"""GUBER_ENGINE=pallas — the fused serving engine (ISSUE 8).

One device program per wave: decisions + on-device heavy-hitter tap
(+ the mesh-GLOBAL replica decide and accumulator scatter when that
tier is bound).  Pins:

- engine selection (auto on TPU, compiled XLA flavor on CPU opt-in,
  legacy GUBER_STEP_IMPL untouched, loud fallback on construction
  failure — no error rows);
- byte-parity vs the ShardedEngine oracle on seeded wire + object
  traffic, single- and multi-shard;
- 16-caller exact conservation through the fused dispatcher path;
- mesh-GLOBAL fused-scatter conservation (folded == injected) under
  global_psum / device_step faults;
- the PhaseLedger collapse: fused waves carry no `pack` segment and
  the exact wave-time partition (sum of segments == duration) holds —
  the proof of what fusion deleted;
- the device tap feeds the heavy-hitter sketch without host copies.
"""
import threading

import numpy as np
import pytest

from gubernator_tpu.config import BehaviorConfig, Config
from gubernator_tpu.hashing import hash_key, hash_request_keys
from gubernator_tpu.instance import V1Instance
from gubernator_tpu.parallel import ShardedEngine, make_mesh
from gubernator_tpu.parallel.pallas_engine import (
    PallasServingEngine, XlaFusedEngine, resolve_engine_kind)
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.types import Behavior, RateLimitRequest

NOW = 1_790_000_000_000


def ser(reqs):
    m = pb.GetRateLimitsReq()
    for r in reqs:
        q = m.requests.add()
        q.name, q.unique_key = r.name, r.unique_key
        q.hits, q.limit, q.duration = r.hits, r.limit, r.duration
        q.behavior = int(r.behavior)
        q.algorithm = int(r.algorithm)
    return m.SerializeToString()


def req(key, name="fs", **kw):
    d = dict(hits=1, limit=1_000_000, duration=600_000)
    d.update(kw)
    return RateLimitRequest(name=name, unique_key=key, **d)


def seeded_reqs(seed, n=120, keys=17, **kw):
    rng = np.random.default_rng(seed)
    return [req(f"k{int(k) % keys}", **kw)
            for k in rng.zipf(1.2, size=n)]


@pytest.fixture()
def clean_env(monkeypatch):
    monkeypatch.delenv("GUBER_ENGINE", raising=False)
    monkeypatch.delenv("GUBER_STEP_IMPL", raising=False)
    return monkeypatch


def fused_instance(n=1, **cfg):
    d = dict(cache_size=1 << 12, sweep_interval_ms=0, engine="pallas")
    d.update(cfg)
    return V1Instance(Config(**d), mesh=make_mesh(n=n))


class TestEngineSelection:
    def test_resolver_matrix(self):
        r = resolve_engine_kind
        # auto: fused pallas on TPU, classic elsewhere (pre-ISSUE-8
        # default preserved on CPU)
        assert r("", "xla", "cpu") == "xla-classic"
        assert r("auto", "xla", "cpu") == "xla-classic"
        assert r("", "xla", "tpu") == "pallas-fused"
        # explicit opt-in: fused everywhere, compiled XLA flavor off-TPU
        assert r("pallas", "xla", "cpu") == "xla-fused"
        assert r("pallas", "xla", "tpu") == "pallas-fused"
        assert r("xla", "pallas", "cpu") == "xla-classic"
        assert r("sharded", "xla", "tpu") == "xla-classic"
        # legacy knob keeps meaning the bucket-kernel engine
        assert r("", "pallas", "cpu") == "pallas-kernel"
        # GUBER_ENGINE wins when both are set
        assert r("pallas", "pallas", "cpu") == "xla-fused"
        with pytest.raises(ValueError, match="GUBER_ENGINE"):
            r("bogus", "xla", "cpu")

    def test_cpu_opt_in_builds_compiled_fused_engine(self, clean_env):
        inst = fused_instance()
        try:
            assert isinstance(inst.engine, XlaFusedEngine)
            assert inst.engine.fused_serving and inst.engine.fused_tap
            # analytics sink wired before serving
            assert inst.engine.tap_sink is not None
        finally:
            inst.close()

    def test_env_overrides_config(self, clean_env):
        clean_env.setenv("GUBER_ENGINE", "xla")
        inst = fused_instance()  # Config says pallas; env wins
        try:
            assert type(inst.engine) is ShardedEngine
        finally:
            inst.close()

    def test_engine_fallback_is_loud_and_serves(self, clean_env):
        """Fused engine unavailable → classic sharded engine, one
        engine_fallback event, NO error rows on traffic."""
        import gubernator_tpu.parallel.pallas_engine as pe

        orig = pe.XlaFusedEngine.__init__

        def boom(self, *a, **kw):
            raise RuntimeError("no fused engine on this stack")

        pe.XlaFusedEngine.__init__ = boom
        try:
            inst = fused_instance()
        finally:
            pe.XlaFusedEngine.__init__ = orig
        try:
            assert type(inst.engine) is ShardedEngine
            kinds = [e.get("kind") for e in inst.recorder.events()]
            assert "engine_fallback" in kinds
            resps = inst.get_rate_limits(
                [req(f"fb{i}") for i in range(8)], now_ms=NOW)
            assert all(r.error == "" for r in resps)
        finally:
            inst.close()


class TestFusedParity:
    def test_wire_and_object_byte_parity_vs_sharded(self, clean_env):
        """The acceptance pin: identical seeded traffic through the
        fused engine and the classic XLA path — responses byte-equal
        on the wire lane, field-equal on the object lane."""
        fi = fused_instance()
        xi = V1Instance(Config(cache_size=1 << 12, sweep_interval_ms=0,
                               engine="xla"), mesh=make_mesh(n=1))
        try:
            datas = [ser(seeded_reqs(s, limit=40)) for s in range(4)]
            outs_f = [fi.get_rate_limits_wire(d, now_ms=NOW + i)
                      for i, d in enumerate(datas)]
            outs_x = [xi.get_rate_limits_wire(d, now_ms=NOW + i)
                      for i, d in enumerate(datas)]
            assert outs_f == outs_x  # byte identity, deny region incl.
            of = fi.get_rate_limits(seeded_reqs(9, limit=40),
                                    now_ms=NOW + 10)
            ox = xi.get_rate_limits(seeded_reqs(9, limit=40),
                                    now_ms=NOW + 10)
            assert [(int(a.status), a.remaining, a.reset_time, a.limit,
                     a.error) for a in of] == \
                   [(int(b.status), b.remaining, b.reset_time, b.limit,
                     b.error) for b in ox]
        finally:
            fi.close()
            xi.close()

    def test_multishard_engine_parity(self):
        """Direct engine A/B on a 2-shard mesh (the dryrun shape)."""
        fe = XlaFusedEngine(make_mesh(n=2), capacity_per_shard=1 << 9)
        xe = ShardedEngine(make_mesh(n=2), capacity_per_shard=1 << 9,
                           batch_per_shard=64)
        reqs = seeded_reqs(3, n=96, keys=23, limit=25)
        for t in (0, 1, 2, 30):
            rf = fe.check_batch(reqs, NOW + t)
            rx = xe.check_batch(reqs, NOW + t)
            for i, (a, b) in enumerate(zip(rf, rx)):
                assert (int(a.status), a.remaining, a.reset_time,
                        a.limit) == (int(b.status), b.remaining,
                                     b.reset_time, b.limit), (t, i)
        assert fe.over_count == xe.over_count
        assert fe.insert_count == xe.insert_count

    def test_pallas_kernel_flavor_emits_device_tap(self):
        """The Mosaic-kernel flavor (interpret off-TPU) emits the same
        fused tap: khash/hits/over rows match the wave's decisions."""
        from gubernator_tpu.core.batch import pack_requests

        taps = []
        pe = PallasServingEngine(make_mesh(n=1),
                                 capacity_per_shard=1 << 9,
                                 batch_per_shard=64)
        pe.tap_sink = taps.append
        reqs = [req(f"t{i % 3}", limit=2) for i in range(8)]
        kh = hash_request_keys([r.name for r in reqs],
                               [r.unique_key for r in reqs])
        batch, _ = pack_requests(reqs, NOW, size=len(reqs),
                                 key_hashes=kh)
        st, _, _, _, full = pe.check_packed(batch, kh, NOW)
        assert not full.any()
        tap = np.asarray(taps[-1])
        served = tap[3] != 0
        assert int(served.sum()) == len(reqs)
        assert set(tap[0][served].view(np.uint64).tolist()) == \
            set(np.asarray(kh).tolist())
        # over flags in the tap == over decisions in the outputs
        assert int(tap[2][served].sum()) == int((np.asarray(st) == 1)
                                                .sum())


class TestFusedConservation:
    def test_16_caller_exact_conservation(self, clean_env):
        """16 threads hammer shared keys through the fused dispatcher
        path; every consumed hit is accounted for exactly."""
        inst = fused_instance()
        threads, errs = [], []
        per_thread, calls, keys = 20, 6, 4

        def worker(t):
            try:
                for c in range(calls):
                    reqs = [req(f"cons{i % keys}")
                            for i in range(per_thread)]
                    rs = inst.get_rate_limits(reqs, now_ms=NOW + c)
                    assert all(r.error == "" for r in rs)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        try:
            for t in range(16):
                th = threading.Thread(target=worker, args=(t,))
                th.start()
                threads.append(th)
            for th in threads:
                th.join(timeout=120)
            assert not errs, errs
            total = 16 * per_thread * calls
            queries = [req(f"cons{i}", hits=0) for i in range(keys)]
            rs = inst.get_rate_limits(queries, now_ms=NOW + 100)
            consumed = sum(1_000_000 - r.remaining for r in rs)
            assert consumed == total, (consumed, total)
        finally:
            inst.close()


class TestMeshFusedScatter:
    def mesh_inst(self, monkeypatch, **cfg):
        monkeypatch.delenv("GUBER_ENGINE", raising=False)
        monkeypatch.delenv("GUBER_STEP_IMPL", raising=False)
        monkeypatch.setenv("GUBER_MESH_GLOBAL_CAP", "256")
        d = dict(cache_size=1 << 12, sweep_interval_ms=0,
                 engine="pallas", global_mode="mesh", batch_rows=64,
                 behaviors=BehaviorConfig(global_sync_wait_ms=100))
        d.update(cfg)
        return V1Instance(Config(**d), mesh=make_mesh(n=8))

    def g(self, key, hits=2):
        return req(key, name="mf", hits=hits, limit=100_000,
                   behavior=Behavior.GLOBAL)

    def drive(self, inst, waves=3, keys=5):
        for w in range(waves):
            out = inst.get_rate_limits_wire(
                ser([self.g(f"k{i % keys}") for i in range(4 * keys)]),
                now_ms=NOW + 1 + w)
            assert out  # serves, no exception

    def test_fused_scatter_serves_and_conserves(self, monkeypatch):
        """Mesh rows serve INSIDE the fused wave (mesh_fused_hits
        grows; the separate meshglobal dispatch is gone) and the fold's
        conservation oracle stays exact."""
        inst = self.mesh_inst(monkeypatch)
        try:
            # mesh mode pre-builds + binds the tier at construction
            # (the warmup contract) — waves are fusable from wave one
            assert inst.engine.mesh_bound
            self.drive(inst)
            assert inst.engine.mesh_fused_hits == 3 * 20 * 2
            inst._mesh_reconcile_tick()
            mge = inst._meshglobal
            mge.drain()
            s = mge.stats()
            assert s["folded_hits"] == s["injected_hits"] == 120, s
            gm = inst.global_manager
            assert not gm._hits and not gm._hits_raw  # zero gRPC lanes
        finally:
            inst.close()

    def test_fused_ab_identical_vs_grpc_mode(self, monkeypatch):
        """12_mesh_global's ab_identical pin over the FUSED engine:
        mesh-mode responses byte-equal the grpc-mode (sharded) path on
        identical seeded GLOBAL traffic."""
        mi = self.mesh_inst(monkeypatch)
        gi = V1Instance(Config(cache_size=1 << 12, sweep_interval_ms=0,
                               hot_set_capacity=0, batch_rows=64),
                        mesh=make_mesh(n=8))
        try:
            datas = [ser([self.g(f"k{i % 5}") for i in range(20)])
                     for _ in range(3)]
            m = [mi.get_rate_limits_wire(d, now_ms=NOW + 1 + i)
                 for i, d in enumerate(datas)]
            g = [gi.get_rate_limits_wire(d, now_ms=NOW + 1 + i)
                 for i, d in enumerate(datas)]
            assert m == g
        finally:
            mi.close()
            gi.close()

    def test_conservation_under_psum_and_device_step_faults(
            self, monkeypatch):
        """The chaos pin: a failing fold (global_psum) swaps back and
        loses nothing; a device_step fault fails its wave BEFORE any
        state moved (nothing applied → nothing injected); after
        recovery folded == injected exactly."""
        inst = self.mesh_inst(monkeypatch)
        try:
            self.drive(inst, waves=2)
            inst.faults.arm("global_psum:error", seed=7)
            inst._mesh_reconcile_tick()  # fold aborts, swap-back
            assert inst.metrics.mesh_global_fold_errors._value.get() \
                >= 1
            self.drive(inst, waves=1)  # hits keep accumulating
            inst.faults.arm("device_step:error", seed=7)
            with pytest.raises(Exception):
                self.drive(inst, waves=1)  # wave dies pre-application
            inst.faults.clear()
            self.drive(inst, waves=1)
            inst._mesh_reconcile_tick()  # clean fold recovers all
            mge = inst._meshglobal
            mge.drain()
            s = mge.stats()
            # 4 successful waves × 20 rows × 2 hits; the faulted wave
            # applied nothing and injected nothing
            assert s["folded_hits"] == s["injected_hits"] == 160, s
        finally:
            inst.close()


class TestPhaseCollapse:
    def test_pack_collapses_into_device_with_exact_partition(
            self, clean_env):
        """Fused waves carry no `pack` segment — `device` absorbs it —
        and the wave-time partition stays exact (the PhaseLedger proof
        the bench A/B records as phase_deleted)."""
        fi = fused_instance()
        xi = V1Instance(Config(cache_size=1 << 12, sweep_interval_ms=0,
                               engine="xla"), mesh=make_mesh(n=1))
        try:
            data = ser(seeded_reqs(5))
            for i in range(3):
                fi.get_rate_limits_wire(data, now_ms=NOW + i)
                xi.get_rate_limits_wire(data, now_ms=NOW + i)
            fp = fi.dispatcher.analytics.phases.snapshot()
            xp = xi.dispatcher.analytics.phases.snapshot()
            assert "pack" not in fp and "device" in fp, fp
            assert "pack" in xp and "device" in xp, xp
            for inst in (fi, xi):
                seen = 0
                for ev in inst.recorder.events():
                    if ev.get("kind") == "wave_completed" \
                            and ev.get("phases"):
                        seen += 1
                        drift = abs(sum(ev["phases"].values())
                                    - ev["duration_ms"])
                        assert drift <= 0.01, ev
                        if inst is fi:
                            assert "pack" not in ev["phases"], ev
                assert seen > 0
        finally:
            fi.close()
            xi.close()

    def test_device_tap_feeds_sketch_without_host_tap(self, clean_env):
        """The fused engine's device tap is the sketch's only columnar
        feed (the dispatcher's host-side copies are off): heavy keys
        still surface in /debug/topkeys."""
        inst = fused_instance()
        try:
            assert inst.dispatcher._fused_tap is True
            data = ser([req("hot", hits=3) for _ in range(50)])
            for i in range(2):
                inst.get_rate_limits_wire(data, now_ms=NOW + i)
            ana = inst.dispatcher.analytics
            assert ana.flush()
            snap = ana.topkeys_snapshot()
            kh = hash_key("fs", "hot")
            hot = [k for k in snap["keys"]
                   if int(k["khash"], 16) == int(kh)]
            assert hot and hot[0]["hits"] == 2 * 50 * 3, snap["keys"][:3]
        finally:
            inst.close()

"""MULTI_REGION through the columnar wire lanes (round 3): MR batches
previously demoted the whole batch to the pb2 object path.  These tests
pin that MR rows now ride `wire_local`/`wire_clustered`/`peer_wire`
with replication queued as raw TLV prototypes — and that cross-region
convergence and no-ping-pong semantics are unchanged."""
import time

import pytest

from gubernator_tpu import cluster as cluster_mod
from gubernator_tpu.config import BehaviorConfig, DaemonConfig
from gubernator_tpu.netutil import free_port
from gubernator_tpu.parallel import make_mesh
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.types import Behavior, RateLimitRequest

DAY = 86_400_000


def ser(reqs):
    m = pb.GetRateLimitsReq()
    for r in reqs:
        q = m.requests.add()
        q.name, q.unique_key = r.name, r.unique_key
        q.hits, q.limit, q.duration = r.hits, r.limit, r.duration
        q.behavior = int(r.behavior)
    return m.SerializeToString()


def mr_req(key, hits=1, name="wmr", behavior=Behavior.MULTI_REGION):
    return RateLimitRequest(name=name, unique_key=key, hits=hits,
                            limit=100, duration=DAY, behavior=behavior)


def lane(inst, lane_name):
    return inst.metrics.wire_lane_counter.labels(
        lane=lane_name)._value.get()


def check_wire(inst, reqs, now=None):
    out = pb.GetRateLimitsResp.FromString(inst.get_rate_limits_wire(
        ser(reqs), now_ms=now or int(time.time() * 1000)))
    return list(out.responses)


@pytest.fixture(scope="module")
def regions():
    behaviors = BehaviorConfig(
        batch_timeout_ms=30, batch_wait_ms=30,
        multi_region_sync_wait_ms=50, multi_region_timeout_ms=5000)
    cfgs = [DaemonConfig(
        grpc_listen_address=f"127.0.0.1:{free_port()}",
        http_listen_address="", cache_size=1 << 10,
        data_center="dc-east" if i < 2 else "dc-west",
        behaviors=behaviors) for i in range(4)]
    c = cluster_mod.start_with(cfgs, mesh=make_mesh(n=2))
    yield c
    c.stop()


def _west_remaining(regions, key, name="wmr"):
    [r] = check_wire(regions.instance_at(2),
                     [mr_req(key, hits=0, name=name)])
    return int(r.remaining)


def test_mr_rides_columnar_lane_and_converges(regions):
    """An MR batch through an east daemon's wire entry must take a
    columnar lane (zero pb2 fallback) and still replicate west."""
    inst = regions.instance_at(0)
    fb0 = lane(inst, "pb2_fallback")
    col0 = lane(inst, "wire_clustered") + lane(inst, "wire_local")
    key = "wa:1"
    for _ in range(3):
        rs = check_wire(inst, [mr_req(key, hits=2)])
        assert rs[0].error == ""
    assert lane(inst, "pb2_fallback") == fb0
    assert (lane(inst, "wire_clustered")
            + lane(inst, "wire_local")) - col0 == 3
    # east sees its own hits now; west converges async
    [r] = check_wire(inst, [mr_req(key, hits=0)])
    assert int(r.remaining) == 94
    deadline = time.time() + 6
    while time.time() < deadline and _west_remaining(regions, key) != 94:
        time.sleep(0.05)
    assert _west_remaining(regions, key) == 94


def test_mr_forwarded_owner_queues_via_peer_wire(regions):
    """A key owned by the OTHER east daemon: the serving daemon
    forwards over the peer wire; the owner's peer-wire lane (not a pb2
    fallback) must queue the cross-region replication."""
    inst = regions.instance_at(0)
    # find a key owned by daemon 1 (east's other daemon)
    key = None
    for i in range(200):
        cand = f"wb:{i}"
        d = regions.owner_daemon_of(f"wmr2_{cand}")
        if d is regions.daemon_at(1):
            key = cand
            break
    assert key is not None
    own = regions.instance_at(1)
    pfb0 = lane(own, "peer_pb2_fallback")
    pw0 = lane(own, "peer_wire")
    rs = check_wire(inst, [mr_req(key, hits=7, name="wmr2")])
    assert rs[0].error == "" and int(rs[0].remaining) == 93
    assert lane(own, "peer_wire") > pw0
    assert lane(own, "peer_pb2_fallback") == pfb0
    deadline = time.time() + 6
    while (time.time() < deadline
           and _west_remaining(regions, key, "wmr2") != 93):
        time.sleep(0.05)
    assert _west_remaining(regions, key, "wmr2") == 93


def test_mr_wire_no_ping_pong(regions):
    """Replicated copies strip MULTI_REGION; counters stay put after
    convergence even with every hop on the columnar lanes."""
    key = "wc:1"
    inst = regions.instance_at(1)
    check_wire(inst, [mr_req(key, hits=5, name="wmr3")])
    deadline = time.time() + 6
    while (time.time() < deadline
           and _west_remaining(regions, key, "wmr3") != 95):
        time.sleep(0.05)
    assert _west_remaining(regions, key, "wmr3") == 95
    time.sleep(0.5)
    assert _west_remaining(regions, key, "wmr3") == 95
    [r] = check_wire(inst, [mr_req(key, hits=0, name="wmr3")])
    assert int(r.remaining) == 95


def test_mixed_mr_and_plain_batch(regions):
    """MR rows and plain rows in one wire batch: both served, only MR
    replicated."""
    inst = regions.instance_at(0)
    reqs = [mr_req("wd:m", hits=4, name="wmr4"),
            RateLimitRequest(name="wmr4", unique_key="wd:p", hits=1,
                             limit=9, duration=DAY)]
    rs = check_wire(inst, reqs)
    assert rs[0].error == "" and int(rs[0].remaining) == 96
    assert rs[1].error == "" and int(rs[1].remaining) == 8
    deadline = time.time() + 6
    while (time.time() < deadline
           and _west_remaining(regions, "wd:m", "wmr4") != 96):
        time.sleep(0.05)
    assert _west_remaining(regions, "wd:m", "wmr4") == 96
    # the plain key must NOT have replicated west: its first hit there
    # starts from a fresh bucket (9 - 1), not from east's drained one
    [r2] = check_wire(regions.instance_at(2),
                      [RateLimitRequest(name="wmr4", unique_key="wd:p",
                                        hits=1, limit=9, duration=DAY)])
    assert int(r2.remaining) == 8

"""Compile ledger (ISSUE 14, gubernator_tpu/compileledger.py): the
runtime half of the retrace-stability contract.

The static ``retrace`` guberlint pass proves jit call SITES cannot
drift; this file proves the live process agrees, both ways:

- the WARMED service path performs zero XLA compiles (the tier-1
  steady-state gate `make check` runs);
- a deliberate dtype-drift escape — the exact bug class the static
  pass hunts — makes the detector fire (a detector that cannot fire
  certifies nothing).

Also pinned: the logging-hook lifecycle (install is idempotent,
uninstall restores the jax logger's level/propagate/handlers exactly),
metric mirroring into ``gubernator_jit_compiles``, and the
GUBER_COMPILE_LEDGER=0 off switch.
"""
import logging

import jax
import jax.numpy as jnp
import pytest

from gubernator_tpu.compileledger import (_JAX_COMPILE_LOGGER,
                                          CompileLedger, LEDGER, enabled,
                                          install_if_enabled)

NOW = 1_793_000_000_000


@pytest.fixture
def ledger():
    """A fresh ledger installed on the real jax compile logger,
    uninstalled afterwards no matter what."""
    led = CompileLedger()
    assert led.install()
    try:
        yield led
    finally:
        led.uninstall()


def test_jax_compile_logger_exists_and_records(ledger):
    """Pins the hook point: jax must emit per-compile records on
    _JAX_COMPILE_LOGGER — a jax upgrade that moves the logger must
    fail HERE, loudly, not silently record nothing forever."""

    def _cl_probe(x):
        return x + 1

    f = jax.jit(_cl_probe)
    f(jnp.ones(3, jnp.int32))
    counts = ledger.counts()
    assert "_cl_probe" in counts and counts["_cl_probe"] == 1


def test_steady_state_zero_then_drift_fires(ledger):
    def _cl_drift(x):
        return x * 2

    f = jax.jit(_cl_drift)
    f(jnp.ones(4, jnp.int32))  # warmup compile
    ledger.mark_steady()
    f(jnp.ones(4, jnp.int32))  # cache hit: no compile
    assert ledger.steady_compiles() == {}
    assert ledger.verdict()["steady"] is True
    # the deliberate escape: dtype drift at the call site recompiles
    f(jnp.ones(4, jnp.float32))
    steady = ledger.steady_compiles()
    assert steady.get("_cl_drift") == 1, steady
    v = ledger.verdict()
    assert v["steady"] is False
    assert v["steady_recompiles"]["_cl_drift"] == 1
    assert v["marked_steady"] is True and v["installed"] is True


def test_uninstall_restores_logger_state():
    lg = logging.getLogger(_JAX_COMPILE_LOGGER)
    level0, prop0, handlers0 = lg.level, lg.propagate, list(lg.handlers)
    led = CompileLedger()
    led.install()
    assert lg.level == logging.DEBUG and lg.propagate is False
    assert len(lg.handlers) == len(handlers0) + 1
    led.uninstall()
    assert lg.level == level0 and lg.propagate is prop0
    assert lg.handlers == handlers0
    led.uninstall()  # idempotent


def test_metrics_mirroring(ledger):
    from gubernator_tpu.metrics import Metrics

    m = Metrics()
    ledger.attach_metrics(m)
    ledger.attach_metrics(m)  # idempotent: no double bump

    def _cl_metric(x):
        return x - 1

    jax.jit(_cl_metric)(jnp.ones(2, jnp.int32))
    sample = m.registry.get_sample_value(
        "gubernator_jit_compiles_total", {"fn": "_cl_metric"})
    assert sample == 1.0


def test_env_gate(monkeypatch):
    monkeypatch.setenv("GUBER_COMPILE_LEDGER", "0")
    assert enabled() is False
    assert install_if_enabled() is False
    monkeypatch.delenv("GUBER_COMPILE_LEDGER")
    assert enabled() is True


def test_service_path_steady_state_zero_recompiles():
    """The tier-1 gate: a warmed V1Instance serving the wire lane must
    not compile ANYTHING per wave — the runtime proof behind bench row
    6_service_path's compile_ledger block."""
    from gubernator_tpu.config import Config
    from gubernator_tpu.instance import V1Instance, _wire_native
    from gubernator_tpu.parallel import make_mesh
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.types import RateLimitRequest
    from gubernator_tpu.wire import req_to_pb

    if _wire_native is None:  # pragma: no cover
        pytest.skip("native extension not built")
    inst = V1Instance(Config(cache_size=1 << 12, sweep_interval_ms=0),
                      mesh=make_mesh(n=1))
    try:
        # instance construction installs the process singleton
        assert inst.compile_ledger is LEDGER
        assert LEDGER.installed
        datas = []
        for b in range(3):
            m = pb.GetRateLimitsReq()
            m.requests.extend(
                req_to_pb(RateLimitRequest(
                    name="ledger", unique_key=f"k{b}_{i}", hits=1,
                    limit=100, duration=60_000))
                for i in range(32))
            datas.append(m.SerializeToString())
        for r in range(4):  # warmup: compiles happen here
            inst.get_rate_limits_wire(datas[r % 3], now_ms=NOW + r)
        LEDGER.mark_steady()
        for r in range(12):  # steady state: same shapes, same dtypes
            inst.get_rate_limits_wire(datas[r % 3], now_ms=NOW + 10 + r)
        steady = LEDGER.steady_compiles()
        assert steady == {}, (
            f"steady-state service path recompiled: {steady} — a jit "
            f"call site is retrace-unstable (see guberlint's retrace "
            f"pass and CONCURRENCY.md › Retrace stability)")
        assert LEDGER.verdict()["steady"] is True
    finally:
        inst.close()

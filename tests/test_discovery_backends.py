"""etcd / k8s discovery backends against in-process fake API servers
(reference: etcd.go › EtcdPool, kubernetes.go › K8sPool)."""
import base64
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from gubernator_tpu.discovery import EtcdDiscovery, K8sDiscovery
from gubernator_tpu.netutil import free_port
from gubernator_tpu.types import PeerInfo


class FakeEtcd:
    """Minimal etcd v3 JSON gateway: lease/grant, lease/keepalive,
    kv/put, kv/range, kv/deleterange, and a streaming /v3/watch
    (newline-delimited JSON frames, as grpc-gateway emits them)."""

    def __init__(self):
        self.kv = {}  # bytes key → bytes value
        self.leases = {}
        self.next_lease = 100
        self.keepalives = 0
        self.watchers = []  # list of queue.Queue for open watch streams
        self.watch_mu = threading.Lock()
        fake = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
                if self.path == "/v3/watch":
                    fake.serve_watch(self, body)
                    return
                out = fake.handle(self.path, body)
                data = json.dumps(out).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self.server = ThreadingHTTPServer(("127.0.0.1", free_port()), H)
        self.url = f"http://127.0.0.1:{self.server.server_address[1]}"
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def serve_watch(self, handler, body):
        import queue

        q = queue.Queue()
        with self.watch_mu:
            self.watchers.append(q)
        try:
            handler.send_response(200)
            handler.end_headers()
            handler.wfile.write(json.dumps(
                {"result": {"created": True}}).encode() + b"\n")
            handler.wfile.flush()
            while True:
                ev = q.get(timeout=60)
                if ev is None:
                    return
                handler.wfile.write(json.dumps(
                    {"result": {"events": [ev]}}).encode() + b"\n")
                handler.wfile.flush()
        except Exception:  # noqa: BLE001 - client went away / shutdown
            pass
        finally:
            with self.watch_mu:
                if q in self.watchers:
                    self.watchers.remove(q)

    def _emit(self, ev_type, key):
        ev = {"type": ev_type,
              "kv": {"key": base64.b64encode(key).decode()}}
        with self.watch_mu:
            for q in self.watchers:
                q.put(ev)

    def handle(self, path, body):
        if path == "/v3/lease/grant":
            lid = str(self.next_lease)
            self.next_lease += 1
            self.leases[lid] = True
            return {"ID": lid, "TTL": body["TTL"]}
        if path == "/v3/lease/keepalive":
            self.keepalives += 1
            alive = self.leases.get(body["ID"], False)
            # etcd convention: expired lease → 200 with TTL 0/absent
            return {"result": {"ID": body["ID"],
                               "TTL": "30" if alive else "0"}}
        if path == "/v3/kv/put":
            key = base64.b64decode(body["key"])
            self.kv[key] = base64.b64decode(body["value"])
            self._emit("PUT", key)
            return {}
        if path == "/v3/kv/range":
            start = base64.b64decode(body["key"])
            end = base64.b64decode(body["range_end"])
            kvs = [{"key": base64.b64encode(k).decode(),
                    "value": base64.b64encode(v).decode()}
                   for k, v in sorted(self.kv.items())
                   if start <= k < end]
            return {"kvs": kvs, "count": str(len(kvs))}
        if path == "/v3/kv/deleterange":
            key = base64.b64decode(body["key"])
            if self.kv.pop(key, None) is not None:
                self._emit("DELETE", key)
            return {}
        return {}

    def close(self):
        with self.watch_mu:
            for q in self.watchers:
                q.put(None)
        self.server.shutdown()
        self.server.server_close()


def test_etcd_register_poll_and_departure():
    fake = FakeEtcd()
    got_a, got_b = [], []
    try:
        a = EtcdDiscovery(got_a.append, [fake.url], "/gub/peers/",
                          PeerInfo(grpc_address="10.0.0.1:1051"), ttl_s=3)
        b = EtcdDiscovery(got_b.append, [fake.url], "/gub/peers/",
                          PeerInfo(grpc_address="10.0.0.2:1051"), ttl_s=3)
        deadline = time.time() + 10
        while time.time() < deadline:
            if (got_a and len(got_a[-1]) == 2
                    and got_b and len(got_b[-1]) == 2):
                break
            time.sleep(0.1)
        assert len(got_a[-1]) == 2 and len(got_b[-1]) == 2
        assert {p.grpc_address for p in got_a[-1]} == {
            "10.0.0.1:1051", "10.0.0.2:1051"}
        # departure: b closes and deletes its key; a sees 1 peer
        b.close()
        deadline = time.time() + 10
        while time.time() < deadline and len(got_a[-1]) != 1:
            time.sleep(0.1)
        assert len(got_a[-1]) == 1
        assert fake.keepalives >= 0
        a.close()
        assert not fake.kv, "close() must deregister"
    finally:
        fake.close()


def test_etcd_endpoint_failover():
    fake = FakeEtcd()
    got = []
    try:
        d = EtcdDiscovery(got.append,
                          ["127.0.0.1:1", fake.url],  # first is dead
                          "/gub/peers/",
                          PeerInfo(grpc_address="10.0.0.9:1051"), ttl_s=3)
        assert got and got[-1][0].grpc_address == "10.0.0.9:1051"
        d.close()
    finally:
        fake.close()


def test_etcd_requires_endpoints():
    with pytest.raises(ValueError):
        EtcdDiscovery(lambda p: None, [], "/p/",
                      PeerInfo(grpc_address="x:1"))


def test_etcd_expired_lease_reregisters():
    """A lost lease answers keepalive with TTL=0 (HTTP 200) — the pool
    must detect it and re-register."""
    fake = FakeEtcd()
    got = []
    try:
        d = EtcdDiscovery(got.append, [fake.url], "/gub/peers/",
                          PeerInfo(grpc_address="10.0.0.3:1051"), ttl_s=3)
        # simulate server-side lease expiry + key loss
        fake.leases.clear()
        fake.kv.clear()
        d._keepalive()
        assert fake.kv, "expired lease did not trigger re-registration"
        assert d.lease_id in fake.leases
        d.close()
    finally:
        fake.close()


def test_etcd_watch_driven_membership():
    """Membership changes must arrive through the watch stream, not the
    range poll: with ttl 3600 the poll interval is 20 minutes, so only
    watch events can explain sub-second convergence (reference etcd.go
    watch-driven SetPeers)."""
    fake = FakeEtcd()
    got = []
    try:
        d = EtcdDiscovery(got.append, [fake.url], "/gub/peers/",
                          PeerInfo(grpc_address="10.0.0.1:1051"),
                          ttl_s=3600)
        deadline = time.time() + 5
        while time.time() < deadline and not fake.watchers:
            time.sleep(0.05)
        assert fake.watchers, "watch stream never attached"
        # a second peer registers straight into the kv store
        fake.handle("/v3/kv/put", {
            "key": base64.b64encode(b"/gub/peers/10.0.0.2:1051").decode(),
            "value": base64.b64encode(json.dumps(
                {"grpc_address": "10.0.0.2:1051"}).encode()).decode()})
        deadline = time.time() + 5
        while time.time() < deadline and not (got and len(got[-1]) == 2):
            time.sleep(0.05)
        assert got and {p.grpc_address for p in got[-1]} == {
            "10.0.0.1:1051", "10.0.0.2:1051"}, \
            "watch events did not drive membership"
        # departure: delete propagates the same way
        fake.handle("/v3/kv/deleterange", {
            "key": base64.b64encode(b"/gub/peers/10.0.0.2:1051").decode()})
        deadline = time.time() + 5
        while time.time() < deadline and len(got[-1]) != 1:
            time.sleep(0.05)
        assert [p.grpc_address for p in got[-1]] == ["10.0.0.1:1051"]
        d.close()
    finally:
        fake.close()


def test_etcd_range_end_edge_cases():
    assert EtcdDiscovery._range_end(b"/gub/") == b"/gub0"
    assert EtcdDiscovery._range_end(b"a\xff") == b"b"
    assert EtcdDiscovery._range_end(b"\xff\xff") == b"\x00"
    assert EtcdDiscovery._range_end(b"") == b"\x00"


class FakeK8s:
    """Minimal API server: /api/v1/namespaces/{ns}/pods and /endpoints,
    plus `?watch=1` streaming (newline-delimited watch events, as the
    real API server emits them)."""

    def __init__(self, pods=None, endpoints=None):
        fake = self
        self.pods = pods or []
        self.endpoints = endpoints or []
        self.auth_seen = []
        self.watchers = []
        self.watch_mu = threading.Lock()

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                fake.auth_seen.append(self.headers.get("Authorization", ""))
                fake.paths = getattr(fake, "paths", [])
                fake.paths.append(self.path)
                if "watch=1" in self.path:
                    fake.serve_watch(self)
                    return
                if "/pods" in self.path:
                    out = {"items": fake.pods}
                else:
                    # named-endpoints GET returns ONE Endpoints object
                    out = fake.endpoints
                data = json.dumps(out).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self.server = ThreadingHTTPServer(("127.0.0.1", free_port()), H)
        self.url = f"http://127.0.0.1:{self.server.server_address[1]}"
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def serve_watch(self, handler):
        import queue

        q = queue.Queue()
        with self.watch_mu:
            self.watchers.append(q)
        try:
            handler.send_response(200)
            handler.end_headers()
            while True:
                ev = q.get(timeout=60)
                if ev is None:
                    return
                handler.wfile.write(json.dumps(ev).encode() + b"\n")
                handler.wfile.flush()
        except Exception:  # noqa: BLE001 - client went away / shutdown
            pass
        finally:
            with self.watch_mu:
                if q in self.watchers:
                    self.watchers.remove(q)

    def emit(self, ev_type, obj=None):
        with self.watch_mu:
            for q in self.watchers:
                q.put({"type": ev_type, "object": obj or {}})

    def close(self):
        with self.watch_mu:
            for q in self.watchers:
                q.put(None)
        self.server.shutdown()
        self.server.server_close()


def test_k8s_pod_selector():
    fake = FakeK8s(pods=[
        {"status": {"podIP": "10.1.0.5", "phase": "Running"}},
        {"status": {"podIP": "10.1.0.6", "phase": "Running"}},
        {"status": {"podIP": "10.1.0.7", "phase": "Pending"}},
        {"status": {"phase": "Running"}},
    ])
    got = []
    try:
        d = K8sDiscovery(got.append, "default", "app in (gub,gub2)", 1051,
                         api_base=fake.url, token="tok-123",
                         poll_interval_ms=60_000)
        assert got
        assert [p.grpc_address for p in got[-1]] == [
            "10.1.0.5:1051", "10.1.0.6:1051"]
        assert fake.auth_seen[-1] == "Bearer tok-123"
        # set-based selectors must be percent-encoded in the URL
        assert "labelSelector=app%20in%20%28gub%2Cgub2%29" in fake.paths[-1]
        d.close()
    finally:
        fake.close()


def test_k8s_named_endpoints_mode():
    fake = FakeK8s(endpoints={
        "subsets": [{"addresses": [{"ip": "10.2.0.1"},
                                   {"ip": "10.2.0.2"}]}]})
    got = []
    try:
        d = K8sDiscovery(got.append, "default", "", 1051,
                         service="gubernator-tpu-peers",
                         api_base=fake.url, token="t",
                         poll_interval_ms=60_000)
        assert {p.grpc_address for p in got[-1]} == {
            "10.2.0.1:1051", "10.2.0.2:1051"}
        # must target the NAMED Endpoints object, not the namespace list
        # (paths[-1] may be the concurrent watch request)
        assert any(p.endswith("/endpoints/gubernator-tpu-peers")
                   for p in fake.paths)
        d.close()
    finally:
        fake.close()


def test_k8s_watch_driven_membership():
    """Pod churn must arrive through the `?watch=1` stream: with a
    60-second poll interval, only watch events can explain sub-second
    convergence (the raw form of client-go informers)."""
    fake = FakeK8s(pods=[
        {"status": {"podIP": "10.3.0.1", "phase": "Running"}}])
    got = []
    try:
        d = K8sDiscovery(got.append, "default", "app=gub", 1051,
                         api_base=fake.url, token="t",
                         poll_interval_ms=60_000)
        assert [p.grpc_address for p in got[-1]] == ["10.3.0.1:1051"]
        deadline = time.time() + 5
        while time.time() < deadline and not fake.watchers:
            time.sleep(0.05)
        assert fake.watchers, "watch stream never attached"
        # a new pod starts; the API server streams an ADDED event
        fake.pods.append({"status": {"podIP": "10.3.0.2",
                                     "phase": "Running"}})
        fake.emit("ADDED")
        deadline = time.time() + 5
        while time.time() < deadline and len(got[-1]) != 2:
            time.sleep(0.05)
        assert {p.grpc_address for p in got[-1]} == {
            "10.3.0.1:1051", "10.3.0.2:1051"}, \
            "watch event did not drive membership"
        # pod deletion propagates the same way
        fake.pods.pop(0)
        fake.emit("DELETED")
        deadline = time.time() + 5
        while time.time() < deadline and len(got[-1]) != 1:
            time.sleep(0.05)
        assert [p.grpc_address for p in got[-1]] == ["10.3.0.2:1051"]
        d.close()
    finally:
        fake.close()


def test_k8s_requires_selector_or_service():
    with pytest.raises(ValueError, match="POD_SELECTOR or"):
        K8sDiscovery(lambda p: None, "default", "", 1051,
                     api_base="http://127.0.0.1:1")


def test_k8s_outside_cluster_raises(monkeypatch):
    monkeypatch.delenv("KUBERNETES_SERVICE_HOST", raising=False)
    with pytest.raises(RuntimeError, match="not in a cluster"):
        K8sDiscovery(lambda p: None, "default", "app=x", 1051)

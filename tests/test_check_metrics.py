"""tools/check_metrics.py as a tier-1 gate: every metric registered on
the per-instance registry must be documented in OBSERVABILITY.md (and
no stale doc entries) — the metric catalog can't silently drift the way
the round-5 wave layer silently had no metrics at all."""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_metric_catalog_is_documented_and_unique():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "check_metrics.py")],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr + r.stdout
    assert "OK" in r.stdout


def test_lint_catches_an_undocumented_metric(tmp_path, monkeypatch):
    """The lint must actually fail on drift — prove it by running the
    same checks against a doc with one catalog row removed."""
    import re

    from gubernator_tpu.metrics import Metrics

    with open(os.path.join(REPO, "OBSERVABILITY.md")) as f:
        doc = f.read()
    assert "gubernator_dispatcher_stalled" in doc
    doc_broken = doc.replace("gubernator_dispatcher_stalled", "")
    documented = set(re.findall(r"gubernator_[a-z0-9_]+", doc_broken))
    registered = {fam.name for fam in Metrics().registry.collect()}
    assert "gubernator_dispatcher_stalled" in registered - documented

"""guberlint pass semantics: fixture modules with KNOWN violations
must produce exactly the expected diagnostics, and the blessed
variants of the same code must produce none.

tests/test_lint_clean.py pins the other half of the contract (the
real tree is clean at HEAD); this file pins that the checker actually
catches what it claims to catch — a lint that never fires is worse
than no lint, because it certifies discipline nobody is keeping.
"""
import textwrap
from pathlib import Path

import pytest

from tools.guberlint import Violation, run_passes


def lint_fixture(tmp_path: Path, source: str, passes):
    """Write ``source`` as a fixture module and run the given passes
    over JUST it (plus the real tree's config/faults for registries)."""
    mod = tmp_path / "fixture_mod.py"
    mod.write_text(textwrap.dedent(source))
    return [v for v in run_passes(passes=passes, extra_files=[mod])
            if v.path.endswith("fixture_mod.py")]


class TestGuardedPass:
    BAD = """
        import threading

        class Counter:
            def __init__(self):
                self._mu = threading.Lock()
                self._n = 0  # guarded-by: self._mu

            def bump(self):
                with self._mu:
                    self._n += 1

            def peek(self):
                return self._n
    """

    def test_unlocked_access_is_flagged_exactly(self, tmp_path):
        vs = lint_fixture(tmp_path, self.BAD, ["guarded"])
        assert len(vs) == 1
        v = vs[0]
        assert v.pass_id == "guarded"
        assert v.line == 14
        assert "Counter._n" in v.message
        assert "with self._mu" in v.message

    def test_lock_free_annotation_clears_it(self, tmp_path):
        ok = self.BAD.replace(
            "return self._n",
            "return self._n  # lock-free: GIL-atomic int read")
        assert lint_fixture(tmp_path, ok, ["guarded"]) == []

    def test_def_level_annotation_blesses_function(self, tmp_path):
        ok = self.BAD.replace(
            "def peek(self):",
            "def peek(self):  # lock-free: snapshot, staleness ok")
        assert lint_fixture(tmp_path, ok, ["guarded"]) == []

    def test_with_lock_access_is_clean(self, tmp_path):
        ok = self.BAD.replace(
            "return self._n",
            "with self._mu:\n            return self._n")
        assert lint_fixture(tmp_path, ok, ["guarded"]) == []

    def test_init_is_exempt(self, tmp_path):
        src = """
            import threading

            class C:
                def __init__(self):
                    self._mu = threading.Lock()
                    self._n = 0  # guarded-by: self._mu
                    self._n = self._n + 1  # construction: no lock yet
        """
        assert lint_fixture(tmp_path, src, ["guarded"]) == []

    def test_conflicting_declarations_flagged(self, tmp_path):
        src = """
            import threading

            class C:
                def __init__(self):
                    self._mu = threading.Lock()
                    self._other = threading.Lock()
                    self._n = 0  # guarded-by: self._mu

                def reset(self):
                    with self._other:
                        self._n = 0  # guarded-by: self._other
        """
        vs = lint_fixture(tmp_path, src, ["guarded"])
        assert any("one attribute, one lock" in v.message for v in vs)


class TestLockOrderPass:
    def test_inverted_nesting_flagged(self, tmp_path):
        src = """
            import threading

            class C:
                def __init__(self):
                    self._tel_mu = threading.Lock()
                    self._submit_mu = threading.Lock()

                def bad(self):
                    with self._tel_mu:
                        with self._submit_mu:
                            pass
        """
        vs = lint_fixture(tmp_path, src, ["lockorder"])
        assert len(vs) == 1
        assert vs[0].line == 11
        assert "submit_mu" in vs[0].message
        assert "tel_mu" in vs[0].message

    def test_correct_nesting_clean(self, tmp_path):
        src = """
            import threading

            class C:
                def __init__(self):
                    self._tel_mu = threading.Lock()
                    self._submit_mu = threading.Lock()

                def good(self):
                    with self._submit_mu:
                        with self._tel_mu:
                            pass
        """
        assert lint_fixture(tmp_path, src, ["lockorder"]) == []

    def test_same_lock_twice_flagged(self, tmp_path):
        src = """
            import threading

            class C:
                def __init__(self):
                    self._tel_mu = threading.Lock()

                def deadlock(self):
                    with self._tel_mu:
                        with self._tel_mu:
                            pass
        """
        vs = lint_fixture(tmp_path, src, ["lockorder"])
        assert len(vs) == 1

    def test_nested_function_resets_held_set(self, tmp_path):
        src = """
            import threading

            class C:
                def __init__(self):
                    self._tel_mu = threading.Lock()
                    self._submit_mu = threading.Lock()

                def ok(self):
                    with self._tel_mu:
                        def callback():
                            with self._submit_mu:
                                pass
                        return callback
        """
        assert lint_fixture(tmp_path, src, ["lockorder"]) == []


class TestEnvRegPass:
    def test_unregistered_var_flagged(self, tmp_path):
        src = """
            import os

            KNOB = os.environ.get("GUBER_DEFINITELY_NOT_REGISTERED", "")
        """
        vs = lint_fixture(tmp_path, src, ["envreg"])
        assert len(vs) == 1
        assert "GUBER_DEFINITELY_NOT_REGISTERED" in vs[0].message
        assert "ENV_REGISTRY" in vs[0].message

    def test_registered_var_clean(self, tmp_path):
        src = """
            import os

            KNOB = os.environ.get("GUBER_COALESCE_US", "")
        """
        assert lint_fixture(tmp_path, src, ["envreg"]) == []

    def test_subscript_and_in_shapes_detected(self, tmp_path):
        src = """
            import os

            A = os.environ["GUBER_NOT_IN_REGISTRY_A"]
            B = "GUBER_NOT_IN_REGISTRY_B" in os.environ
        """
        vs = lint_fixture(tmp_path, src, ["envreg"])
        assert {m for v in vs for m in v.message.split()
                if m.startswith("GUBER_NOT")} == {
            "GUBER_NOT_IN_REGISTRY_A", "GUBER_NOT_IN_REGISTRY_B"}


class TestFaultCatPass:
    def test_unknown_point_flagged(self, tmp_path):
        src = """
            class C:
                def go(self):
                    self._fault("definitely_not_a_faultpoint")
        """
        vs = lint_fixture(tmp_path, src, ["faultcat"])
        assert len(vs) == 1
        assert "definitely_not_a_faultpoint" in vs[0].message

    def test_cataloged_point_clean(self, tmp_path):
        src = """
            class C:
                def go(self):
                    self._fault("device_step")
        """
        assert lint_fixture(tmp_path, src, ["faultcat"]) == []


class TestThreadsPass:
    def test_anonymous_thread_flagged(self, tmp_path):
        src = """
            import threading

            def spawn(fn):
                t = threading.Thread(target=fn, daemon=True)
                t.start()
                return t
        """
        vs = lint_fixture(tmp_path, src, ["threads"])
        assert len(vs) == 1
        assert "name=" in vs[0].message

    def test_unbounded_join_flagged(self, tmp_path):
        src = """
            import threading

            def drain(t):
                t.join()
        """
        vs = lint_fixture(tmp_path, src, ["threads"])
        assert len(vs) == 1
        assert "timeout" in vs[0].message
        assert "GUBER_DRAIN_GRACE" in vs[0].message

    def test_named_thread_and_bounded_join_clean(self, tmp_path):
        src = """
            import threading

            def spawn(fn):
                t = threading.Thread(target=fn, daemon=True, name="w")
                t.start()
                t.join(timeout=5)
                return t
        """
        assert lint_fixture(tmp_path, src, ["threads"]) == []


class TestCliAndApi:
    def test_violation_render_format(self):
        v = Violation("a/b.py", 7, "guarded", "boom")
        assert v.render() == "a/b.py:7: [guarded] boom"

    def test_unknown_pass_is_loud(self):
        with pytest.raises(ValueError, match="unknown guberlint pass"):
            run_passes(passes=["nope"])

class TestClockDomainPass:
    """The PR-6 bug class as lint fixtures: every diagnostic has a
    seeded mutant that trips it and a blessed/fixed twin that doesn't."""

    def test_untagged_clock_read_flagged(self, tmp_path):
        src = """
            def front_door(now_ms=None):
                now = clock_ms()
                return now
        """
        vs = lint_fixture(tmp_path, src, ["clockdomain"])
        assert len(vs) == 1
        assert vs[0].pass_id == "clockdomain"
        assert "untagged clock read" in vs[0].message
        assert "clock-domain" in vs[0].message

    def test_time_module_reads_also_require_tag(self, tmp_path):
        src = """
            import time

            def probe():
                return time.time_ns()
        """
        vs = lint_fixture(tmp_path, src, ["clockdomain"])
        assert len(vs) == 1 and "time.time_ns" in vs[0].message

    def test_domain_tag_clears_it(self, tmp_path):
        src = """
            def front_door(now_ms=None):
                now = clock_ms()  # clock-domain: caller
                return now
        """
        assert lint_fixture(tmp_path, src, ["clockdomain"]) == []

    def test_clock_ok_clears_it(self, tmp_path):
        src = """
            import time

            def probe():
                return time.time()  # clock-ok: telemetry wall clock
        """
        assert lint_fixture(tmp_path, src, ["clockdomain"]) == []

    def test_def_line_tag_blesses_function(self, tmp_path):
        src = """
            def sweep_tick(self):  # clock-ok: sweep cadence, not a bucket stamp
                t = clock_ms()
                return t
        """
        assert lint_fixture(tmp_path, src, ["clockdomain"]) == []

    def test_owner_taint_into_stamp_kwarg_flagged(self, tmp_path):
        src = """
            class Inst:
                def apply_peer(self, parsed, data, mr):
                    now = clock_ms()  # clock-domain: owner
                    self._queue_mr_raw(parsed, data, mr, stamp_ms=now)
        """
        vs = lint_fixture(tmp_path, src, ["clockdomain"])
        assert len(vs) == 1
        assert "owner-domain clock value flows" in vs[0].message
        assert "_queue_mr_raw" in vs[0].message

    def test_owner_taint_propagates_through_assignments(self, tmp_path):
        src = """
            class Inst:
                def apply_peer(self, req):
                    now = clock_ms()  # clock-domain: owner
                    stamp = now + 5
                    return tlv_with_created(req, stamp)
        """
        vs = lint_fixture(tmp_path, src, ["clockdomain"])
        assert len(vs) == 1
        assert "tlv_with_created" in vs[0].message

    def test_caller_domain_stamp_is_clean(self, tmp_path):
        src = """
            class Inst:
                def front_door(self, parsed, data, mr):
                    now = clock_ms()  # clock-domain: caller
                    self._queue_mr_raw(parsed, data, mr, stamp_ms=now)
        """
        assert lint_fixture(tmp_path, src, ["clockdomain"]) == []

    def test_first_hop_wins_bless_clears_owner_stamp(self, tmp_path):
        src = """
            class Inst:
                def apply_peer(self, parsed, data, mr):
                    now = clock_ms()  # clock-domain: owner
                    # clock-ok: first-hop-wins — only fills rows missing created_at
                    self._queue_mr_raw(parsed, data, mr, stamp_ms=now)
        """
        assert lint_fixture(tmp_path, src, ["clockdomain"]) == []

    def test_reverted_stamp_site_trips_queue_hits_rule(self, tmp_path):
        # the exact PR-6 regression: drop _req_stamped from the
        # deferred-apply enqueue and the pass must fire
        bad = """
            class GM:
                def record_hit(self, req, now):
                    self.queue_hits(req, 1)
        """
        vs = lint_fixture(tmp_path, bad, ["clockdomain"])
        assert len(vs) == 1
        assert "queue_hits" in vs[0].message
        assert "created_at stamp" in vs[0].message
        good = """
            class GM:
                def record_hit(self, req, now):
                    self.queue_hits(self._req_stamped(req, now), 1)
        """
        assert lint_fixture(tmp_path, good, ["clockdomain"]) == []

    def test_raw_queue_without_stamp_ms_flagged(self, tmp_path):
        bad = """
            class Inst:
                def fan_out(self, parsed, data, mask):
                    for k in self._raw_queue_groups(parsed, data, mask):
                        pass
        """
        vs = lint_fixture(tmp_path, bad, ["clockdomain"])
        assert len(vs) == 1 and "stamp_ms=" in vs[0].message
        good = bad.replace("(parsed, data, mask)",
                           "(parsed, data, mask, stamp_ms=now)")
        assert lint_fixture(tmp_path, good, ["clockdomain"]) == []

    def test_forward_without_stamp_evidence_flagged(self, tmp_path):
        bad = """
            class Lane:
                def flush(self, peer, data):
                    return peer.forward_raw(data, 4)
        """
        vs = lint_fixture(tmp_path, bad, ["clockdomain"])
        assert len(vs) == 1
        assert "forward_raw" in vs[0].message
        assert "PR-6" in vs[0].message
        good = """
            class Lane:
                def flush(self, peer, data, toff, tlen, created, now):
                    sub = stamp_req_tlvs(data, toff, tlen, created, now)
                    return peer.forward_raw(sub, 4)
        """
        assert lint_fixture(tmp_path, good, ["clockdomain"]) == []


class TestTracedPurePass:
    """Host side effects inside jit/shard_map/pallas traces: each
    diagnostic has a mutant fixture and a blessed/idiomatic twin."""

    def test_lock_acquisition_in_trace_flagged(self, tmp_path):
        src = """
            import threading
            import jax

            _mu = threading.Lock()

            def _impl(x):
                with _mu:
                    return x

            step = jax.jit(_impl)
        """
        vs = lint_fixture(tmp_path, src, ["tracedpure"])
        assert len(vs) == 1
        assert "lock acquisition" in vs[0].message
        assert "jit(_impl)" in vs[0].message

    def test_metrics_write_in_trace_flagged(self, tmp_path):
        src = """
            import jax

            def _impl(x, counter):
                counter.inc()
                return x

            step = jax.jit(_impl)
        """
        vs = lint_fixture(tmp_path, src, ["tracedpure"])
        assert len(vs) == 1 and "metrics write" in vs[0].message

    def test_clock_read_in_trace_flagged(self, tmp_path):
        src = """
            import time
            import jax

            def _impl(x):
                t0 = time.time()
                return x

            step = jax.jit(_impl)
        """
        vs = lint_fixture(tmp_path, src, ["tracedpure"])
        assert len(vs) == 1 and "host clock read" in vs[0].message

    def test_violation_reached_through_call_graph(self, tmp_path):
        src = """
            import time
            import jax

            def _helper(x):
                time.sleep(0.1)
                return x

            def _impl(x):
                return _helper(x)

            step = jax.jit(_impl)
        """
        vs = lint_fixture(tmp_path, src, ["tracedpure"])
        assert len(vs) == 1
        assert "time.sleep" in vs[0].message or "host clock" in vs[0].message

    def test_undeclared_callback_flagged_blessed_twin_clean(self, tmp_path):
        bad = """
            import jax

            def _hook(v):
                pass

            def _impl(x):
                jax.debug.callback(_hook, x)
                return x

            step = jax.jit(_impl)
        """
        vs = lint_fixture(tmp_path, bad, ["tracedpure"])
        assert len(vs) == 1 and "host callback" in vs[0].message
        good = bad.replace(
            "jax.debug.callback(_hook, x)",
            "jax.debug.callback(_hook, x)  # traced-ok: test-only hook")
        assert lint_fixture(tmp_path, good, ["tracedpure"]) == []

    def test_blessed_compound_header_skips_body_and_traversal(self, tmp_path):
        # blessing the guard's HEADER line must also stop traversal
        # into the callback target (its module-global store is part of
        # the declared escape) — even when the guard sits inside a loop
        src = """
            import jax

            _CHECKS = {"n": 0}

            def _hook(v):
                _CHECKS["n"] += 1

            def _impl(x):
                for i in range(2):
                    if True:  # traced-ok: test-only invariant hook
                        jax.debug.callback(_hook, x)
                return x

            step = jax.jit(_impl)
        """
        assert lint_fixture(tmp_path, src, ["tracedpure"]) == []

    def test_module_global_store_flagged_ref_store_exempt(self, tmp_path):
        bad = """
            import jax

            _COUNTS = {"a": 0}

            def _impl(x):
                _COUNTS["a"] = 1
                return x

            step = jax.jit(_impl)
        """
        vs = lint_fixture(tmp_path, bad, ["tracedpure"])
        assert len(vs) == 1
        assert "module global '_COUNTS'" in vs[0].message
        # the Pallas Ref-store idiom: a closure-captured out-ref
        # written by subscript inside a kernel body is a DEVICE write
        good = """
            import jax

            def _kernel(x_ref, o_ref):
                def body(i, acc):
                    o_ref[i] = acc
                    return acc
                return jax.lax.fori_loop(0, 4, body, x_ref[0])

            step = jax.jit(_kernel)
        """
        assert lint_fixture(tmp_path, good, ["tracedpure"]) == []

    def test_use_after_donate_flagged_rebind_clean(self, tmp_path):
        bad = """
            import jax

            _write = jax.jit(_write_impl, donate_argnums=0)

            def advance(state, x):
                out = _write(state, x)
                return state
        """
        vs = lint_fixture(tmp_path, bad, ["tracedpure"])
        assert len(vs) == 1
        assert "use after donate" in vs[0].message
        good = """
            import jax

            _write = jax.jit(_write_impl, donate_argnums=0)

            def advance(state, x):
                state = _write(state, x)
                return state
        """
        assert lint_fixture(tmp_path, good, ["tracedpure"]) == []


class TestRetracePass:
    def test_dtype_drift_across_sites_flagged(self, tmp_path):
        src = """
            import jax

            f = jax.jit(_impl)

            def a(x):
                return f(x, 3)

            def b(x):
                return f(x, 3.0)
        """
        vs = lint_fixture(tmp_path, src, ["retrace"])
        assert len(vs) == 1
        assert "dtype drift at position 1" in vs[0].message
        assert "py-float" in vs[0].message and "py-int" in vs[0].message

    def test_consistent_sites_clean(self, tmp_path):
        src = """
            import jax

            f = jax.jit(_impl)

            def a(x):
                return f(x, 3)

            def b(x):
                return f(x, 4)
        """
        assert lint_fixture(tmp_path, src, ["retrace"]) == []

    def test_pinned_np_dtype_vs_py_scalar_is_drift(self, tmp_path):
        src = """
            import jax
            import numpy as np

            f = jax.jit(_impl)

            def a(x):
                return f(x, np.int64(3))

            def b(x):
                return f(x, 3)
        """
        vs = lint_fixture(tmp_path, src, ["retrace"])
        assert len(vs) == 1 and "int64" in vs[0].message

    def test_retrace_ok_bless_clears_drift(self, tmp_path):
        src = """
            import jax

            f = jax.jit(_impl)

            def a(x):
                return f(x, 3)

            def b(x):
                return f(x, 3.0)  # retrace-ok: cold path, compiles once
        """
        assert lint_fixture(tmp_path, src, ["retrace"]) == []

    def test_unhashable_static_flagged_tuple_clean(self, tmp_path):
        bad = """
            import jax

            g = jax.jit(_impl, static_argnums=1)

            def go(x):
                return g(x, [1, 2])
        """
        vs = lint_fixture(tmp_path, bad, ["retrace"])
        assert len(vs) == 1
        assert "unhashable static" in vs[0].message
        assert "EVERY call" in vs[0].message
        good = bad.replace("[1, 2]", "(1, 2)")
        assert lint_fixture(tmp_path, good, ["retrace"]) == []

    def test_unhashable_static_kwarg_flagged(self, tmp_path):
        src = """
            import jax

            g = jax.jit(_impl, static_argnames="opts")

            def go(x):
                return g(x, opts=[1])
        """
        vs = lint_fixture(tmp_path, src, ["retrace"])
        assert len(vs) == 1 and "opts=" in vs[0].message


class TestDocsPassAndShim:
    def test_docs_pass_clean_at_head(self):
        vs = run_passes(passes=["docs"])
        assert vs == [], [v.render() for v in vs]

    def test_docs_problems_map_to_violations(self, monkeypatch):
        from tools.guberlint import docs

        monkeypatch.setattr(docs, "metric_catalog_problems",
                            lambda: ["metric gubernator_fake is fake"])
        vs = [v for v in docs.run(None) if "fake" in v.message]
        assert len(vs) == 1
        assert vs[0].pass_id == "docs"
        assert vs[0].path == "OBSERVABILITY.md"

    def test_check_metrics_shim_reexports_docs(self):
        import tools.check_metrics as cm
        from tools.guberlint import docs

        assert cm.main is docs.main
        assert cm.emitted_event_kinds is docs.emitted_event_kinds
        assert cm.main() == 0  # the old CLI contract: 0 on a clean tree


class TestBaselineMechanism:
    BAD = """
        import threading

        class Counter:
            def __init__(self):
                self._mu = threading.Lock()
                self._n = 0  # guarded-by: self._mu

            def peek(self):
                return self._n
    """

    def test_baseline_suppresses_by_key_not_line(self, tmp_path):
        from tools.guberlint import baseline_key

        mod = tmp_path / "fixture_mod.py"
        mod.write_text(textwrap.dedent(self.BAD))
        vs = [v for v in run_passes(passes=["guarded"], extra_files=[mod])
              if v.path.endswith("fixture_mod.py")]
        assert len(vs) == 1
        key = baseline_key(vs[0])
        assert str(vs[0].line) not in key  # line-free: survives edits
        suppressed = [
            v for v in run_passes(passes=["guarded"], extra_files=[mod],
                                  baseline={key})
            if v.path.endswith("fixture_mod.py")]
        assert suppressed == []

    def test_load_baseline_ignores_comments_and_missing(self, tmp_path):
        from tools.guberlint import load_baseline

        f = tmp_path / "base.txt"
        f.write_text("# header\n\na.py [guarded] boom\n")
        assert load_baseline(f) == {"a.py [guarded] boom"}
        assert load_baseline(tmp_path / "nope.txt") == set()

    def test_write_baseline_cli_roundtrip(self, tmp_path, capsys):
        from tools.guberlint.__main__ import main

        out = tmp_path / "base.txt"
        assert main(["--write-baseline", str(out)]) == 0
        # HEAD is clean, so the baseline is empty (header only) — and
        # feeding it back changes nothing
        assert main(["--baseline", str(out)]) == 0

"""guberlint pass semantics: fixture modules with KNOWN violations
must produce exactly the expected diagnostics, and the blessed
variants of the same code must produce none.

tests/test_lint_clean.py pins the other half of the contract (the
real tree is clean at HEAD); this file pins that the checker actually
catches what it claims to catch — a lint that never fires is worse
than no lint, because it certifies discipline nobody is keeping.
"""
import textwrap
from pathlib import Path

import pytest

from tools.guberlint import Violation, run_passes


def lint_fixture(tmp_path: Path, source: str, passes):
    """Write ``source`` as a fixture module and run the given passes
    over JUST it (plus the real tree's config/faults for registries)."""
    mod = tmp_path / "fixture_mod.py"
    mod.write_text(textwrap.dedent(source))
    return [v for v in run_passes(passes=passes, extra_files=[mod])
            if v.path.endswith("fixture_mod.py")]


class TestGuardedPass:
    BAD = """
        import threading

        class Counter:
            def __init__(self):
                self._mu = threading.Lock()
                self._n = 0  # guarded-by: self._mu

            def bump(self):
                with self._mu:
                    self._n += 1

            def peek(self):
                return self._n
    """

    def test_unlocked_access_is_flagged_exactly(self, tmp_path):
        vs = lint_fixture(tmp_path, self.BAD, ["guarded"])
        assert len(vs) == 1
        v = vs[0]
        assert v.pass_id == "guarded"
        assert v.line == 14
        assert "Counter._n" in v.message
        assert "with self._mu" in v.message

    def test_lock_free_annotation_clears_it(self, tmp_path):
        ok = self.BAD.replace(
            "return self._n",
            "return self._n  # lock-free: GIL-atomic int read")
        assert lint_fixture(tmp_path, ok, ["guarded"]) == []

    def test_def_level_annotation_blesses_function(self, tmp_path):
        ok = self.BAD.replace(
            "def peek(self):",
            "def peek(self):  # lock-free: snapshot, staleness ok")
        assert lint_fixture(tmp_path, ok, ["guarded"]) == []

    def test_with_lock_access_is_clean(self, tmp_path):
        ok = self.BAD.replace(
            "return self._n",
            "with self._mu:\n            return self._n")
        assert lint_fixture(tmp_path, ok, ["guarded"]) == []

    def test_init_is_exempt(self, tmp_path):
        src = """
            import threading

            class C:
                def __init__(self):
                    self._mu = threading.Lock()
                    self._n = 0  # guarded-by: self._mu
                    self._n = self._n + 1  # construction: no lock yet
        """
        assert lint_fixture(tmp_path, src, ["guarded"]) == []

    def test_conflicting_declarations_flagged(self, tmp_path):
        src = """
            import threading

            class C:
                def __init__(self):
                    self._mu = threading.Lock()
                    self._other = threading.Lock()
                    self._n = 0  # guarded-by: self._mu

                def reset(self):
                    with self._other:
                        self._n = 0  # guarded-by: self._other
        """
        vs = lint_fixture(tmp_path, src, ["guarded"])
        assert any("one attribute, one lock" in v.message for v in vs)


class TestLockOrderPass:
    def test_inverted_nesting_flagged(self, tmp_path):
        src = """
            import threading

            class C:
                def __init__(self):
                    self._tel_mu = threading.Lock()
                    self._submit_mu = threading.Lock()

                def bad(self):
                    with self._tel_mu:
                        with self._submit_mu:
                            pass
        """
        vs = lint_fixture(tmp_path, src, ["lockorder"])
        assert len(vs) == 1
        assert vs[0].line == 11
        assert "submit_mu" in vs[0].message
        assert "tel_mu" in vs[0].message

    def test_correct_nesting_clean(self, tmp_path):
        src = """
            import threading

            class C:
                def __init__(self):
                    self._tel_mu = threading.Lock()
                    self._submit_mu = threading.Lock()

                def good(self):
                    with self._submit_mu:
                        with self._tel_mu:
                            pass
        """
        assert lint_fixture(tmp_path, src, ["lockorder"]) == []

    def test_same_lock_twice_flagged(self, tmp_path):
        src = """
            import threading

            class C:
                def __init__(self):
                    self._tel_mu = threading.Lock()

                def deadlock(self):
                    with self._tel_mu:
                        with self._tel_mu:
                            pass
        """
        vs = lint_fixture(tmp_path, src, ["lockorder"])
        assert len(vs) == 1

    def test_nested_function_resets_held_set(self, tmp_path):
        src = """
            import threading

            class C:
                def __init__(self):
                    self._tel_mu = threading.Lock()
                    self._submit_mu = threading.Lock()

                def ok(self):
                    with self._tel_mu:
                        def callback():
                            with self._submit_mu:
                                pass
                        return callback
        """
        assert lint_fixture(tmp_path, src, ["lockorder"]) == []


class TestEnvRegPass:
    def test_unregistered_var_flagged(self, tmp_path):
        src = """
            import os

            KNOB = os.environ.get("GUBER_DEFINITELY_NOT_REGISTERED", "")
        """
        vs = lint_fixture(tmp_path, src, ["envreg"])
        assert len(vs) == 1
        assert "GUBER_DEFINITELY_NOT_REGISTERED" in vs[0].message
        assert "ENV_REGISTRY" in vs[0].message

    def test_registered_var_clean(self, tmp_path):
        src = """
            import os

            KNOB = os.environ.get("GUBER_COALESCE_US", "")
        """
        assert lint_fixture(tmp_path, src, ["envreg"]) == []

    def test_subscript_and_in_shapes_detected(self, tmp_path):
        src = """
            import os

            A = os.environ["GUBER_NOT_IN_REGISTRY_A"]
            B = "GUBER_NOT_IN_REGISTRY_B" in os.environ
        """
        vs = lint_fixture(tmp_path, src, ["envreg"])
        assert {m for v in vs for m in v.message.split()
                if m.startswith("GUBER_NOT")} == {
            "GUBER_NOT_IN_REGISTRY_A", "GUBER_NOT_IN_REGISTRY_B"}


class TestFaultCatPass:
    def test_unknown_point_flagged(self, tmp_path):
        src = """
            class C:
                def go(self):
                    self._fault("definitely_not_a_faultpoint")
        """
        vs = lint_fixture(tmp_path, src, ["faultcat"])
        assert len(vs) == 1
        assert "definitely_not_a_faultpoint" in vs[0].message

    def test_cataloged_point_clean(self, tmp_path):
        src = """
            class C:
                def go(self):
                    self._fault("device_step")
        """
        assert lint_fixture(tmp_path, src, ["faultcat"]) == []


class TestThreadsPass:
    def test_anonymous_thread_flagged(self, tmp_path):
        src = """
            import threading

            def spawn(fn):
                t = threading.Thread(target=fn, daemon=True)
                t.start()
                return t
        """
        vs = lint_fixture(tmp_path, src, ["threads"])
        assert len(vs) == 1
        assert "name=" in vs[0].message

    def test_unbounded_join_flagged(self, tmp_path):
        src = """
            import threading

            def drain(t):
                t.join()
        """
        vs = lint_fixture(tmp_path, src, ["threads"])
        assert len(vs) == 1
        assert "timeout" in vs[0].message
        assert "GUBER_DRAIN_GRACE" in vs[0].message

    def test_named_thread_and_bounded_join_clean(self, tmp_path):
        src = """
            import threading

            def spawn(fn):
                t = threading.Thread(target=fn, daemon=True, name="w")
                t.start()
                t.join(timeout=5)
                return t
        """
        assert lint_fixture(tmp_path, src, ["threads"]) == []


class TestCliAndApi:
    def test_violation_render_format(self):
        v = Violation("a/b.py", 7, "guarded", "boom")
        assert v.render() == "a/b.py:7: [guarded] boom"

    def test_unknown_pass_is_loud(self):
        with pytest.raises(ValueError, match="unknown guberlint pass"):
            run_passes(passes=["nope"])

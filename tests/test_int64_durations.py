"""int64 duration parity: calendar-scale millisecond durations (30 days,
1 year) must pass through un-truncated on both algorithms, with device ==
oracle bit-for-bit — including the in-kernel guards (rescale whole-token
clamp + fraction floor above FRAC_SAFE; replenish elapsed guard).

Lifts round-1's 2^31-1 ms (~24.8 day) input ceiling (VERDICT.md missing
item 4): reference algorithms.go takes int64 ms durations, so a plain
30-day TOKEN_BUCKET/LEAKY_BUCKET window is a first-class input.
"""
import numpy as np
import pytest

from gubernator_tpu import Algorithm, Behavior, Oracle, RateLimitRequest
from gubernator_tpu.core import decide_batch, init_table, pack_requests
from gubernator_tpu.types import DURATION_MAX, EFF_MAX, FRAC_SAFE, TD_BOUND

NOW = 1_772_000_000_000
DAY = 86_400_000
MONTH_30 = 30 * DAY            # 2_592_000_000 ms > 2^31-1
YEAR = 365 * DAY


def run_parity(batches, cap=1 << 12):
    oracle = Oracle()
    state = init_table(cap)
    for bi, (reqs, now) in enumerate(batches):
        want = oracle.check_batch(reqs, now)
        packed, errs = pack_requests(reqs, now)
        state, out = decide_batch(state, packed, now)
        for i, w in enumerate(want):
            assert not errs[i] and not bool(out.err[i]), (bi, i)
            got = (int(out.status[i]), int(out.remaining[i]),
                   int(out.reset_time[i]), int(out.limit[i]))
            exp = (int(w.status), int(w.remaining), int(w.reset_time),
                   int(w.limit))
            assert got == exp, (bi, i, reqs[i], exp, got)
    return state


def mk(key="k", **kw):
    d = dict(hits=1, limit=10, duration=MONTH_30,
             algorithm=Algorithm.TOKEN_BUCKET)
    d.update(kw)
    return RateLimitRequest(name="i64", unique_key=key, **d)


class TestThirtyDayDurations:
    def test_token_30d_reset_time_untruncated(self):
        """A 30-day token window expires at exactly now + 30d."""
        oracle = Oracle()
        state = init_table(1 << 10)
        packed, _ = pack_requests([mk()], NOW)
        state, out = decide_batch(state, packed, NOW)
        assert int(out.reset_time[0]) == NOW + MONTH_30
        w = oracle.check_batch([mk()], NOW)[0]
        assert int(w.reset_time) == NOW + MONTH_30

    def test_token_30d_stream(self):
        # spend the bucket across days inside one 30-day window, then
        # cross the boundary and watch it reset
        times = [NOW, NOW + DAY, NOW + 15 * DAY, NOW + MONTH_30 - 1,
                 NOW + MONTH_30, NOW + MONTH_30 + DAY]
        run_parity([([mk(hits=3)], t) for t in times])

    def test_leaky_30d_replenish(self):
        # limit 30 per 30 days = 1 token/day; drain the burst then watch
        # single tokens leak back at day granularity
        r = lambda h: mk(key="lk", hits=h, limit=30, duration=MONTH_30,
                         algorithm=Algorithm.LEAKY_BUCKET)
        batches = [([r(30)], NOW)]                  # drain the bucket
        batches += [([r(1)], NOW + i * DAY) for i in range(1, 8)]
        batches += [([r(0)], NOW + 8 * DAY)]        # query
        run_parity(batches)

    def test_year_long_token(self):
        run_parity([([mk(key="y", duration=YEAR, hits=2)],
                     NOW + i * 30 * DAY) for i in range(14)])


class TestRescaleGuards:
    def test_leaky_rescale_small_to_30d(self):
        """eff crosses FRAC_SAFE: the rescale floors to whole tokens —
        identically on device and oracle."""
        small = mk(key="rs", limit=100, duration=3_600_000,
                   algorithm=Algorithm.LEAKY_BUCKET)
        big = mk(key="rs", limit=100, duration=MONTH_30,
                 algorithm=Algorithm.LEAKY_BUCKET)
        assert MONTH_30 > FRAC_SAFE  # the guard is actually exercised
        run_parity([
            ([small], NOW), ([small], NOW + 1_000),
            ([big], NOW + 2_000),          # rescale up (frac dropped)
            ([big], NOW + DAY),
            ([small], NOW + DAY + 1_000),  # rescale back down
            ([small], NOW + DAY + 2_000),
        ])

    def test_leaky_elapsed_guard(self):
        """Duration shrinks 30d → 1s with a huge limit: elapsed × limit
        would overflow, so the guard must declare the bucket full."""
        big_lim = TD_BOUND // 1000 - 7  # near the 1s-duration ceiling
        first = mk(key="eg", limit=10, duration=MONTH_30,
                   algorithm=Algorithm.LEAKY_BUCKET)
        second = mk(key="eg", hits=5, limit=big_lim, duration=1000,
                    algorithm=Algorithm.LEAKY_BUCKET, burst=big_lim)
        run_parity([([first], NOW),
                    ([second], NOW + 20 * DAY),  # elapsed >> safe bound
                    ([second], NOW + 20 * DAY + 100)])

    def test_duration_above_max_clamps(self):
        """Past DURATION_MAX both sides clamp identically (no wrap)."""
        run_parity([([mk(key="dm", duration=2**60, hits=1)], NOW),
                    ([mk(key="dm", duration=2**60, hits=1)], NOW + 50)])
        assert min(2**60, DURATION_MAX) == DURATION_MAX

    def test_leaky_eff_ceiling(self):
        """Leaky eff clamps at EFF_MAX (~1.09y) — a 2-year leaky window
        behaves as an EFF_MAX window, same on both sides."""
        r = mk(key="ec", limit=100, duration=2 * YEAR,
               algorithm=Algorithm.LEAKY_BUCKET)
        assert 2 * YEAR > EFF_MAX
        run_parity([([r], NOW), ([r], NOW + DAY), ([r], NOW + 100 * DAY)])


class TestFuzzInt64:
    def test_random_durations_parity(self):
        rng = np.random.default_rng(20260730)
        keys = [f"f{i}" for i in range(24)]
        batches = []
        now = NOW
        for _ in range(30):
            reqs = []
            for _ in range(16):
                dur = int(rng.integers(1, 2**40))
                lim = int(rng.integers(1, 2**45))
                reqs.append(RateLimitRequest(
                    name="i64f", unique_key=str(rng.choice(keys)),
                    hits=int(rng.integers(0, 4)),
                    limit=lim, duration=dur,
                    algorithm=(Algorithm.LEAKY_BUCKET
                               if rng.random() < 0.5
                               else Algorithm.TOKEN_BUCKET),
                    burst=int(rng.integers(0, lim + 1)),
                    behavior=(Behavior.RESET_REMAINING
                              if rng.random() < 0.05 else 0)))
            batches.append((reqs, now))
            now += int(rng.integers(1, 10**7))
        run_parity(batches)

"""An out-of-range wire algorithm must degrade to TOKEN_BUCKET and
still enforce the limit — an unclamped value would re-create the bucket
fresh on every request (limit bypass)."""
from gubernator_tpu.parallel import ShardedEngine
from gubernator_tpu.types import RateLimitRequest, Status

NOW = 1_773_000_000_000


def test_unknown_algorithm_still_rate_limits(cpu_mesh):
    eng = ShardedEngine(cpu_mesh, capacity_per_shard=1 << 10,
                        batch_per_shard=64)
    req = RateLimitRequest(name="alg", unique_key="x", hits=1, limit=2,
                           duration=60_000, algorithm=7)  # not 0/1
    r1 = eng.check_batch([req], NOW)[0]
    r2 = eng.check_batch([req], NOW + 1)[0]
    r3 = eng.check_batch([req], NOW + 2)[0]
    assert (int(r1.status), r1.remaining) == (0, 1)
    assert (int(r2.status), r2.remaining) == (0, 0)
    assert int(r3.status) == int(Status.OVER_LIMIT), \
        "unknown algorithm bypassed the limit (fresh-bucket loop)"

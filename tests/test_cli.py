"""CLI end-to-end: real subprocess daemon + healthcheck + load CLI
(reference: cmd/ binaries — SURVEY.md §2.1)."""
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from gubernator_tpu.netutil import free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def daemon_proc():
    grpc_port, http_port = free_port(), free_port()
    env = dict(
        os.environ,
        # GUBER_JAX_PLATFORM goes through jax.config inside the daemon;
        # the plain env vars are overridden by the sandbox sitecustomize
        # (see tests/conftest.py) and alone would land on the TPU tunnel.
        GUBER_JAX_PLATFORM="cpu",
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
        # JAX_COMPILATION_CACHE_DIR is inherited from os.environ
        # (conftest ran _jax_cache.setup()), so the daemon subprocess
        # shares the warm repo-local cache
        GUBER_CACHE_SIZE="4096",
    )
    p = subprocess.Popen(
        [sys.executable, "-m", "gubernator_tpu.cmd.daemon",
         "--grpc", f"127.0.0.1:{grpc_port}",
         "--http", f"127.0.0.1:{http_port}"],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    # wait until healthy (first compile can take a while)
    url = f"http://127.0.0.1:{http_port}/v1/HealthCheck"
    deadline = time.time() + 120
    last = None
    while time.time() < deadline:
        if p.poll() is not None:
            out, err = p.communicate()
            raise RuntimeError(f"daemon died: {err.decode()[-2000:]}")
        try:
            with urllib.request.urlopen(url, timeout=2) as f:
                if json.loads(f.read())["status"] == "healthy":
                    break
        except Exception as e:  # noqa: BLE001
            last = e
            time.sleep(0.5)
    else:
        p.kill()
        raise RuntimeError(f"daemon never became healthy: {last}")
    yield {"grpc": f"127.0.0.1:{grpc_port}",
           "http": f"127.0.0.1:{http_port}", "proc": p}
    p.send_signal(signal.SIGTERM)
    try:
        p.wait(timeout=15)
    except subprocess.TimeoutExpired:
        p.kill()


def run_cmd(mod, *args, timeout=120):
    return subprocess.run(
        [sys.executable, "-m", mod, *args], cwd=REPO, env=dict(
            os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=timeout)


def test_healthcheck_cli(daemon_proc):
    r = run_cmd("gubernator_tpu.cmd.healthcheck",
                "--url", f"http://{daemon_proc['http']}/v1/HealthCheck")
    assert r.returncode == 0, r.stderr
    assert "healthy" in r.stdout


def test_healthcheck_cli_down():
    r = run_cmd("gubernator_tpu.cmd.healthcheck",
                "--url", "http://127.0.0.1:1/v1/HealthCheck", "--timeout", "1")
    assert r.returncode == 1


def test_load_cli_grpc(daemon_proc):
    r = run_cmd("gubernator_tpu.cmd.cli",
                "--address", daemon_proc["grpc"],
                "--rate-limits", "500", "--batch", "50",
                "--concurrency", "2", "--duration", "2", "--json")
    assert r.returncode == 0, r.stderr
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["decisions"] > 0
    assert out["p99_ms"] is not None


def test_load_cli_http(daemon_proc):
    r = run_cmd("gubernator_tpu.cmd.cli",
                "--address", daemon_proc["http"], "--http",
                "--rate-limits", "100", "--batch", "20",
                "--concurrency", "1", "--duration", "1", "--json")
    assert r.returncode == 0, r.stderr
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["decisions"] > 0

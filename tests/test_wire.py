"""Wire-converter round-trips (dataclass ↔ pb2) and enum parity with
the reference contract (SURVEY.md §2.4)."""
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.proto import peers_pb2 as peers_pb
from gubernator_tpu.types import (
    Algorithm,
    Behavior,
    HealthCheckResponse,
    RateLimitRequest,
    RateLimitResponse,
    Status,
)
from gubernator_tpu.wire import (
    health_to_pb,
    req_from_pb,
    req_to_pb,
    reqs_to_pb,
    resp_from_pb,
    resp_to_pb,
)


def test_enum_values_match_reference_contract():
    assert pb.TOKEN_BUCKET == 0 and pb.LEAKY_BUCKET == 1
    assert pb.UNDER_LIMIT == 0 and pb.OVER_LIMIT == 1
    assert (pb.BATCHING, pb.NO_BATCHING, pb.GLOBAL,
            pb.DURATION_IS_GREGORIAN, pb.RESET_REMAINING,
            pb.MULTI_REGION, pb.DRAIN_OVER_LIMIT) == (0, 1, 2, 4, 8, 16, 32)


def test_request_round_trip_with_combined_flags():
    r = RateLimitRequest(
        name="svc", unique_key="user:1", hits=3, limit=50, duration=9000,
        algorithm=Algorithm.LEAKY_BUCKET,
        behavior=Behavior.GLOBAL | Behavior.RESET_REMAINING,  # 10: no alias
        burst=70, metadata={"trace": "abc"})
    m = req_to_pb(r)
    assert m.behavior == 10  # open enum preserves bit combos on the wire
    back = req_from_pb(pb.RateLimitReq.FromString(m.SerializeToString()))
    assert back == r


def test_response_round_trip():
    r = RateLimitResponse(status=Status.OVER_LIMIT, limit=5, remaining=0,
                          reset_time=1_760_000_000_123, error="x",
                          metadata={"m": "1"})
    back = resp_from_pb(pb.RateLimitResp.FromString(
        resp_to_pb(r).SerializeToString()))
    assert back == r


def test_batch_and_health():
    m = reqs_to_pb([RateLimitRequest(name="a", unique_key="b"),
                    RateLimitRequest(name="c", unique_key="d")])
    assert len(m.requests) == 2 and m.requests[1].name == "c"
    h = health_to_pb(HealthCheckResponse(status="unhealthy", message="m",
                                         peer_count=3))
    assert (h.status, h.message, h.peer_count) == ("unhealthy", "m", 3)


def test_update_peer_global_message_shape():
    g = peers_pb.UpdatePeerGlobal(
        key="a_b", algorithm=pb.LEAKY_BUCKET, duration=1000,
        created_at=123, behavior=pb.GLOBAL, burst=9,
        update=pb.RateLimitResp(status=pb.OVER_LIMIT, limit=5, remaining=0,
                                reset_time=456))
    back = peers_pb.UpdatePeerGlobal.FromString(g.SerializeToString())
    assert back.key == "a_b" and back.update.reset_time == 456
    assert back.burst == 9 and back.behavior == pb.GLOBAL


def test_grpc_method_paths_match_reference():
    from gubernator_tpu.grpc_api import PEERS_SERVICE, V1_SERVICE

    assert V1_SERVICE == "pb.gubernator.V1"
    assert PEERS_SERVICE == "pb.gubernator.PeersV1"

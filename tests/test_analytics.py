"""Key-level analytics (ISSUE 4): Space-Saving sketch accuracy against
an exact-count oracle (≤ K distinct keys → exact), the documented error
bound on a skewed Zipf workload (≥ 10× K keys), bounded metric label
cardinality, the never-block tap queue, and the live /debug endpoints
(topkeys / phases / profile) on a real daemon."""
import json
import threading
import time
import urllib.error
import urllib.request
from collections import Counter

import numpy as np
import pytest

from gubernator_tpu.analytics import (HeavyHitterSketch, KeyAnalytics,
                                      PhaseLedger)
from gubernator_tpu.metrics import Metrics

# ---- sketch accuracy ----------------------------------------------------


def _fold_stream(sketch, khashes, hits=None, over=None, wave=500):
    """Feed a key stream through the sketch in wave-sized column
    chunks, the way the dispatcher taps it."""
    khashes = np.asarray(khashes, np.uint64)
    n = len(khashes)
    hits = (np.ones(n, np.int64) if hits is None
            else np.asarray(hits, np.int64))
    over = (np.zeros(n, bool) if over is None
            else np.asarray(over, bool))
    for a in range(0, n, wave):
        b = min(a + wave, n)
        sketch.update(khashes[a:b], hits[a:b], over[a:b], t_ms=123)


def test_exact_oracle_when_domain_fits_k():
    """ISSUE 4 acceptance: on a workload with ≤ K distinct keys the
    ledger IS an exact counter — hits and over-limit tallies match the
    oracle and every error bound is 0."""
    rng = np.random.default_rng(7)
    k = 16
    sk = HeavyHitterSketch(k=k, width=4 * k)
    keys = rng.integers(1, k + 1, size=5000).astype(np.uint64)
    hits = rng.integers(1, 5, size=5000).astype(np.int64)
    over = rng.random(5000) < 0.25
    _fold_stream(sk, keys, hits, over)

    oracle_hits = Counter()
    oracle_over = Counter()
    for kh, h, o in zip(keys, hits, over):
        oracle_hits[int(kh)] += int(h)
        oracle_over[int(kh)] += int(o)

    top = sk.topk()
    assert len(top) == len(oracle_hits)
    for e in top:
        assert e["err"] == 0
        assert e["hits"] == oracle_hits[e["khash"]]
        assert e["over_limit"] == oracle_over[e["khash"]]
    # ranked by true count
    assert [e["hits"] for e in top] == sorted(
        (e["hits"] for e in top), reverse=True)
    assert sk.error_bound() == 0
    assert sk.total_weight == int(hits.sum())


def test_zipf_workload_respects_documented_error_bound():
    """ISSUE 4 acceptance: on a Zipf-skewed stream over ≥ 10× K keys,
    every reported count obeys the Space-Saving guarantee
    ``true <= reported <= true + err`` with
    ``err <= total_weight / width``, and every key heavier than
    total/width is tracked (the guaranteed-heavy-hitter property)."""
    rng = np.random.default_rng(11)
    k, width = 16, 64
    sk = HeavyHitterSketch(k=k, width=width)
    domain = 10 * k
    # zipf(1.3) clipped to the domain: a realistic hot-key skew
    keys = (rng.zipf(1.3, size=40_000) % domain + 1).astype(np.uint64)
    _fold_stream(sk, keys)

    truth = Counter(int(x) for x in keys)
    total = len(keys)
    assert sk.total_weight == total
    bound = total / width
    assert sk.error_bound() <= bound

    top = sk.topk()
    assert len(top) == k
    for e in top:
        true = truth[e["khash"]]
        assert e["hits"] >= true, "Space-Saving must never undercount"
        assert e["hits"] - true <= e["err"], \
            f"overestimate {e['hits'] - true} exceeds its err {e['err']}"
        assert e["err"] <= bound
    # guaranteed heavy hitters: every key with true count > total/width
    # is tracked (its counter can never have been the eviction minimum)
    tracked = {e["khash"] for e in sk.topk(width)}
    for kh, c in truth.items():
        if c > bound:
            assert kh in tracked, f"guaranteed heavy hitter {kh} evicted"


def test_eviction_inherits_count_but_not_overlimit():
    sk = HeavyHitterSketch(k=2, width=2)
    sk.update(np.array([1, 2], np.uint64), np.array([5, 3], np.int64),
              np.array([1, 1], bool), t_ms=1)
    # key 3 evicts the minimum (key 2, count 3): inherits count as err,
    # but NOT the old key's over-limit tally
    sk.update(np.array([3], np.uint64), np.array([2], np.int64),
              np.array([1], bool), t_ms=2)
    by_kh = {e["khash"]: e for e in sk.topk()}
    assert set(by_kh) == {1, 3}
    assert by_kh[3]["hits"] == 5 and by_kh[3]["err"] == 3
    assert by_kh[3]["over_limit"] == 1  # its own, not key 2's


def test_zero_hit_status_queries_still_register_presence():
    sk = HeavyHitterSketch(k=4)
    sk.update(np.array([9], np.uint64), np.array([0], np.int64),
              np.array([0], bool), t_ms=1)
    assert sk.topk()[0]["hits"] == 1  # clamped weight >= 1


# ---- phase ledger -------------------------------------------------------


def test_phase_ledger_snapshot_percentiles():
    led = PhaseLedger()
    for ms in (1, 2, 3, 4, 100):
        led.observe("device", ms / 1e3)
    snap = led.snapshot()["device"]
    assert snap["count"] == 5
    assert snap["total_ms"] == pytest.approx(110.0)
    assert snap["p50_ms"] == pytest.approx(3.0)
    assert snap["max_ms"] == pytest.approx(100.0)


# ---- KeyAnalytics: taps, worker, publish bounds -------------------------


def test_tap_worker_folds_columns_and_recovers_names():
    ka = KeyAnalytics(metrics=None, k=8, width=32)
    try:
        from gubernator_tpu.hashing import hash_request_keys
        from gubernator_tpu.types import RateLimitRequest, RateLimitResponse

        reqs = [RateLimitRequest(name="ana", unique_key="hot", hits=3,
                                 limit=10, duration=60_000)]
        resps = [RateLimitResponse(status=1)]
        assert ka.tap_reqs(reqs, resps)
        # the same key later goes hot through a columnar wire tap that
        # only knows the hash — the name side-table must resolve it
        kh = hash_request_keys(["ana"], ["hot"])
        assert ka.tap_packed(np.repeat(kh, 4), np.full(4, 2, np.int64),
                             np.array([1, 0, 0, 1]))
        assert ka.flush(timeout=10)
        snap = ka.topkeys_snapshot()
        assert snap["waves_tapped"] == 2
        (e,) = snap["keys"]
        assert e["key"] == "ana_hot"
        assert e["hits"] == 3 + 8
        assert e["over_limit"] == 1 + 2
        assert e["khash"] == f"0x{int(kh[0]):016x}"
    finally:
        ka.close()


def test_full_queue_drops_wave_without_blocking_caller():
    """Analytics must shed load, never backpressure serving: with the
    worker wedged and the queue full, a tap returns False fast."""
    ka = KeyAnalytics(metrics=None, k=4, queue_cap=1)
    gate = threading.Event()
    applied = threading.Event()
    orig_fold = ka._fold_cols

    def stuck(cols):
        if cols:
            applied.set()
            assert gate.wait(timeout=30)
        orig_fold(cols)

    ka._fold_cols = stuck
    try:
        kh = np.array([1], np.uint64)
        one = np.array([1], np.int64)
        assert ka.tap_packed(kh, one, one)  # worker picks this up...
        assert applied.wait(timeout=10)     # ...and wedges in _apply
        assert ka.tap_packed(kh, one, one)  # fills the 1-slot queue
        t0 = time.perf_counter()
        dropped = [ka.tap_packed(kh, one, one) for _ in range(50)]
        elapsed = time.perf_counter() - t0
        assert not any(dropped)
        assert elapsed < 1.0, "a full analytics queue must not block"
        assert ka.stats()["taps_dropped"] == 50
    finally:
        gate.set()
        ka.close()


def test_topkey_gauge_label_cardinality_bounded_by_k():
    """ISSUE 4 acceptance: the exported top-K gauge's label set is
    provably ≤ K at every scrape, even after far more distinct keys
    than K churned through — departed keys' labels are removed."""
    m = Metrics()
    k = 4
    ka = KeyAnalytics(metrics=m, k=k, width=2 * k)
    try:
        rng = np.random.default_rng(3)
        for wave in range(6):
            keys = rng.integers(wave * 100, wave * 100 + 50,
                                size=200).astype(np.uint64)
            assert ka.tap_packed(keys, np.ones(200, np.int64),
                                 np.zeros(200))
            assert ka.flush(timeout=10)  # republish after each wave
            text = m.render().decode()
            labels = [ln for ln in text.splitlines()
                      if ln.startswith("gubernator_topkey_overlimit_total{")]
            assert 0 < len(labels) <= k, labels
        assert ka.stats()["tracked_keys"] <= 2 * k
        assert "gubernator_analytics_waves_tapped_total 6.0" \
            in m.render().decode()
    finally:
        ka.close()


def test_observe_phase_feeds_histogram_and_ledger():
    m = Metrics()
    ka = KeyAnalytics(metrics=m, k=4)
    try:
        ka.observe_phase("peer_flush", 0.005)
        text = m.render().decode()
        assert ('gubernator_phase_duration_count{phase="peer_flush"} 1.0'
                in text)
        assert ka.phases_snapshot()["phases"]["peer_flush"]["count"] == 1
    finally:
        ka.close()


def test_env_knobs_and_disable(monkeypatch):
    monkeypatch.setenv("GUBER_TOPK", "32")
    monkeypatch.setenv("GUBER_SKETCH_WIDTH", "99")
    ka = KeyAnalytics()
    try:
        assert ka.sketch.k == 32 and ka.sketch.width == 99
    finally:
        ka.close()
    # malformed values keep defaults
    monkeypatch.setenv("GUBER_TOPK", "banana")
    monkeypatch.delenv("GUBER_SKETCH_WIDTH")
    ka = KeyAnalytics()
    try:
        assert ka.sketch.k == 256 and ka.sketch.width == 4 * 256
    finally:
        ka.close()


# ---- end-to-end: dispatcher tap + daemon endpoints ----------------------


@pytest.fixture(scope="module")
def daemon():
    from gubernator_tpu.config import DaemonConfig
    from gubernator_tpu.daemon import spawn_daemon
    from gubernator_tpu.netutil import free_port
    from gubernator_tpu.oracle import OracleEngine

    d = spawn_daemon(DaemonConfig(
        grpc_listen_address=f"127.0.0.1:{free_port()}",
        http_listen_address=f"127.0.0.1:{free_port()}",
        cache_size=1 << 10), engine=OracleEngine())
    yield d
    d.close()


def _get(daemon, path, timeout=10):
    url = f"http://127.0.0.1:{daemon.http_port}{path}"
    with urllib.request.urlopen(url, timeout=timeout) as f:
        return json.loads(f.read())


def _post_check(daemon, key, hits=1, limit=100, timeout=60):
    body = json.dumps({"requests": [{
        "name": "ana_e2e", "unique_key": key, "hits": hits,
        "limit": limit, "duration": 60_000}]}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{daemon.http_port}/v1/GetRateLimits",
        data=body, headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as f:
        return json.loads(f.read())


def test_debug_topkeys_end_to_end(daemon):
    """Requests through the real serving path land in /debug/topkeys
    with recovered key NAMES, exact counts (domain ≤ K), and the
    over-limit tally of the keys actually driven over."""
    for _ in range(5):
        _post_check(daemon, "hotkey", hits=2)
    for _ in range(3):
        _post_check(daemon, "overkey", hits=60, limit=100)  # 3rd is over
    body = _get(daemon, "/debug/topkeys")
    assert body["taps_dropped"] == 0
    by_name = {e["key"]: e for e in body["keys"]}
    assert by_name["ana_e2e_hotkey"]["hits"] >= 10
    assert by_name["ana_e2e_hotkey"]["err"] == 0
    assert by_name["ana_e2e_overkey"]["over_limit"] >= 1
    assert by_name["ana_e2e_overkey"]["khash"].startswith("0x")
    # solo daemon: no ring owner to report
    assert by_name["ana_e2e_hotkey"]["owner"] is None
    # ?limit= truncates
    limited = _get(daemon, "/debug/topkeys?limit=1")["keys"]
    assert len(limited) == 1
    # the topkey gauge rode along, label-bounded
    with urllib.request.urlopen(
            f"http://127.0.0.1:{daemon.http_port}/metrics") as f:
        text = f.read().decode()
    lines = [ln for ln in text.splitlines()
             if ln.startswith("gubernator_topkey_overlimit_total{")]
    assert 0 < len(lines) <= 256


def test_debug_phases_end_to_end(daemon):
    _post_check(daemon, "phasekey")
    body = _get(daemon, "/debug/phases")
    phases = body["phases"]
    # the oracle engine path always crosses pack/device/resolve
    for ph in ("pack", "device", "resolve"):
        assert phases[ph]["count"] >= 1, phases.keys()
        assert phases[ph]["total_ms"] >= 0
    assert body["waves"]["waves"] >= 1


def test_debug_profile_on_demand(daemon):
    """ISSUE 4 satellite: runtime profiling start/stop + concurrent-
    capture 409 (GUBER_PROFILE_DIR used to be the only way in)."""
    status = _get(daemon, "/debug/profile")
    assert status["active"] is False
    body = _get(daemon, "/debug/profile?seconds=1.5")
    assert body["profiling"] is True and body["dir"]
    # concurrent capture rejected with 409
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(daemon, "/debug/profile?seconds=1")
    assert ei.value.code == 409
    assert _get(daemon, "/debug/profile")["active"] is True
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if not _get(daemon, "/debug/profile")["active"]:
            break
        time.sleep(0.2)
    else:
        pytest.fail("profile capture never stopped")
    import glob
    import os

    files = glob.glob(os.path.join(body["dir"], "**", "*"),
                      recursive=True)
    assert any(os.path.isfile(f) for f in files), "no trace written"
    kinds = [e["kind"] for e in daemon.instance.recorder.events()]
    assert "profile_start" in kinds and "profile_stop" in kinds
    # a fresh capture may start once the previous one finished
    body2 = _get(daemon, "/debug/profile?seconds=0.2")
    assert body2["profiling"] is True


def test_debug_profile_rejects_bad_seconds(daemon):
    for bad in ("nope", "-1", "0", "301"):
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(daemon, f"/debug/profile?seconds={bad}")
        assert ei.value.code == 400


def test_analytics_disabled_turns_endpoints_off(monkeypatch):
    from gubernator_tpu.config import Config
    from gubernator_tpu.instance import V1Instance
    from gubernator_tpu.oracle import OracleEngine

    monkeypatch.setenv("GUBER_ANALYTICS", "0")
    inst = V1Instance(Config(cache_size=1 << 8), engine=OracleEngine())
    try:
        assert inst.analytics is None
        assert inst.dispatcher.debug_stats()["analytics"] is None
    finally:
        inst.close()

"""Clustered wire-lane soak: raw-bytes GetRateLimits against a live
3-daemon cluster WHILE membership churns (a daemon restarts).  The
columnar clustered lane (ring split → raw-TLV forwards → ordered
splice) must keep serving: per-request errors are allowed only as
transient peer-forward failures during the churn window, a strict key
conserves its budget (± one re-home), and the lane itself — not the
pb2 fallback — carries the traffic."""
import threading
import time

import grpc
import numpy as np
import pytest

# the lane-took-the-traffic assertion below is meaningless without the
# C++ parser (conftest auto-builds it; skip only if that failed)
pytest.importorskip("gubernator_tpu.ops.native")

from gubernator_tpu import cluster as cluster_mod
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.types import RateLimitRequest
from gubernator_tpu.wire import req_to_pb

LIMIT = 150


def serialize(reqs):
    m = pb.GetRateLimitsReq()
    m.requests.extend(req_to_pb(r) for r in reqs)
    return m.SerializeToString()


def mk(i):
    if i % 3 == 0:  # strict conservation key (token, usually forwarded)
        return RateLimitRequest(name="sw", unique_key="strict", hits=1,
                                limit=LIMIT, duration=3_600_000)
    return RateLimitRequest(name="sw", unique_key=f"k{i % 41}", hits=1,
                            limit=100_000, duration=600_000)


def test_wire_soak_with_daemon_restart():
    # resilience fallbacks OFF (ISSUE 5): this soak pins the wire-lane
    # + buffer-pool invariants under churn with the LEGACY forward
    # semantics (error rows, single-bucket strict admission).  With
    # degraded fallback on, a slow restart window serves the strict
    # key from multiple local shards by design — that bounded-staleness
    # trade is pinned by tests/test_resilience.py instead.
    from gubernator_tpu.config import BehaviorConfig

    cluster = cluster_mod.start(3, behaviors=BehaviorConfig(
        peer_degraded_fallback=False, peer_health_gate=False))
    lock = threading.Lock()
    hard_errors = []
    transient = []
    admitted = {"strict": 0}
    churning = threading.Event()

    def worker(w, rounds):
        addr = cluster.grpc_address(w % 3 if w % 3 != 2 else 0)
        ch = grpc.insecure_channel(addr)
        call = ch.unary_unary("/pb.gubernator.V1/GetRateLimits")
        try:
            for r in range(rounds):
                reqs = [mk(w * 997 + r * 31 + i) for i in range(30)]
                data = serialize(reqs)
                try:
                    raw = call(data, timeout=60)
                except grpc.RpcError as e:
                    with lock:
                        (transient if churning.is_set()
                         else hard_errors).append(repr(e)[:200])
                    continue
                resp = pb.GetRateLimitsResp.FromString(raw)
                with lock:
                    for req, rr in zip(reqs, resp.responses):
                        if rr.error:
                            # peer-forward failures are expected ONLY
                            # while the ring churns
                            if "from peer" in rr.error:
                                transient.append(rr.error[:120])
                            else:
                                hard_errors.append(rr.error[:200])
                        elif (req.unique_key == "strict"
                              and int(rr.status) == 0):
                            admitted["strict"] += 1
        finally:
            ch.close()

    try:
        # phase 1: steady traffic on the full ring
        threads = [threading.Thread(target=worker, args=(w, 10))
                   for w in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not hard_errors, hard_errors[:5]

        # phase 2: restart daemon 1 WHILE traffic flows (clients hit
        # daemons 0/2 only, so every request still exercises forwards)
        churning.set()
        threads = [threading.Thread(target=worker, args=(w, 12))
                   for w in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.3)
        cluster.restart(1)
        for t in threads:
            t.join()
        churning.clear()

        # phase 3: settled ring serves cleanly again
        threads = [threading.Thread(target=worker, args=(w, 6))
                   for w in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not hard_errors, hard_errors[:5]

        # strict-key conservation: 16 workers' strict attempts far
        # exceed LIMIT; one restart may re-home the key once (reset or
        # handover), never more
        assert LIMIT <= admitted["strict"] <= 2 * LIMIT, admitted
        # the clustered columnar lane carried the front-door traffic
        # (not the pb2 fallback), and owners served forwarded columns
        # over the peer wire lane (clients hit d0/d1; d1's counters
        # reset at restart, so d0 is the stable witness)
        lane0 = cluster.instance_at(0).metrics.wire_lane_counter.labels(
            lane="wire_clustered")._value.get()
        assert lane0 > 0, "daemon 0 never took the clustered lane"
        peer_wire = sum(
            cluster.instance_at(i).metrics.wire_lane_counter.labels(
                lane="peer_wire")._value.get() for i in range(3))
        assert peer_wire > 0, "no owner served forwarded columns"
        # ISSUE 2: buffer-pool leases returned on every path — the
        # churn window exercises the error paths (peer-forward
        # failures, daemon restart mid-wave)
        for i in (0, 2):
            pool = getattr(cluster.instance_at(i).engine, "wave_pool",
                           None)
            if pool is not None:
                s = pool.stats()
                assert s["leaks"] == 0 and s["outstanding"] == 0, s
    finally:
        cluster.stop()

"""C++ wire-ingest lane parity (ops/_native.cpp parse/build +
instance.get_rate_limits_wire vs the pb2 object path).

The fast lane must be byte-behavior identical to the slow path for every
batch it accepts, and must fall back (not misbehave) for everything else.
"""
import numpy as np
import pytest

from gubernator_tpu.config import Config
from gubernator_tpu.instance import V1Instance, _wire_native
from gubernator_tpu.parallel import make_mesh
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.types import (
    Behavior,
    GregorianDuration,
    RateLimitRequest,
)
from gubernator_tpu.wire import req_to_pb

if _wire_native is None:  # pragma: no cover
    pytest.skip("native extension not built", allow_module_level=True)

NOW = 1_766_000_000_000


def mk_instance():
    return V1Instance(Config(cache_size=1 << 12, sweep_interval_ms=0),
                      mesh=make_mesh(n=2))


def to_wire(reqs):
    m = pb.GetRateLimitsReq()
    m.requests.extend(req_to_pb(r) for r in reqs)
    return m.SerializeToString()


def run_both(reqs, now=NOW):
    """Same request stream through a fast-lane instance and a slow-path
    instance; returns (fast pb2 responses, slow responses)."""
    fast, slow = mk_instance(), mk_instance()
    try:
        out = pb.GetRateLimitsResp.FromString(
            fast.get_rate_limits_wire(to_wire(reqs), now_ms=now))
        slow_rs = slow.get_rate_limits(reqs, now_ms=now)
        return list(out.responses), slow_rs
    finally:
        fast.close()
        slow.close()


def assert_match(fast_pb, slow_rs):
    assert len(fast_pb) == len(slow_rs)
    for i, (f, s) in enumerate(zip(fast_pb, slow_rs)):
        assert (int(f.status), f.limit, f.remaining, f.reset_time,
                f.error) == (int(s.status), s.limit, s.remaining,
                             s.reset_time, s.error), f"request {i}"


def test_parity_random_stream():
    rng = np.random.default_rng(11)
    reqs = []
    for i in range(400):
        alg = int(rng.integers(0, 2))
        beh = int(rng.choice([0, int(Behavior.RESET_REMAINING),
                              int(Behavior.DRAIN_OVER_LIMIT),
                              int(Behavior.NO_BATCHING)]))
        reqs.append(RateLimitRequest(
            name=f"wf{int(rng.integers(0, 5))}",
            unique_key=f"k{int(rng.integers(0, 40))}",
            hits=int(rng.integers(0, 4)),
            limit=int(rng.integers(1, 50)),
            duration=int(rng.integers(1000, 100_000)),
            algorithm=alg, behavior=beh,
            burst=int(rng.choice([0, 10, 100]))))
    fast, slow = run_both(reqs)
    assert_match(fast, slow)


def test_parity_gregorian_and_invalid_ordinal():
    reqs = [
        RateLimitRequest(name="g", unique_key="a", hits=1, limit=100,
                         duration=int(GregorianDuration.HOURS),
                         behavior=Behavior.DURATION_IS_GREGORIAN),
        RateLimitRequest(name="g", unique_key="bad", hits=1, limit=100,
                         duration=999,  # invalid ordinal → error resp
                         behavior=Behavior.DURATION_IS_GREGORIAN),
        RateLimitRequest(name="g", unique_key="a", hits=1, limit=100,
                         duration=int(GregorianDuration.HOURS),
                         behavior=Behavior.DURATION_IS_GREGORIAN),
    ]
    fast, slow = run_both(reqs)
    assert fast[1].error and "gregorian" in fast[1].error
    assert_match(fast, slow)


def test_parity_duplicate_heavy_single_key():
    reqs = [RateLimitRequest(name="dup", unique_key="k", hits=1, limit=10,
                             duration=60_000) for _ in range(25)]
    fast, slow = run_both(reqs)
    assert_match(fast, slow)
    assert sum(1 for f in fast if int(f.status) == 0) == 10


def test_fallback_paths_still_correct():
    # metadata → pb2 fallback; empty unique_key → per-request error;
    # GLOBAL → slow path (solo: local + global manager)
    reqs = [
        RateLimitRequest(name="m", unique_key="k", hits=1, limit=5,
                         duration=10_000, metadata={"trace": "x"}),
        RateLimitRequest(name="e", unique_key="", hits=1, limit=5,
                         duration=10_000),
        RateLimitRequest(name="gl", unique_key="k", hits=1, limit=5,
                         duration=10_000, behavior=Behavior.GLOBAL),
    ]
    fast, slow = run_both(reqs)
    assert fast[1].error  # empty unique_key surfaces as error response
    assert_match(fast, slow)


def test_wire_eligible_batch_parses_natively():
    # guard: the parity tests above exercise the fast lane only if this
    # payload actually qualifies for it
    data = to_wire([RateLimitRequest(name="q", unique_key="k", hits=1,
                                     limit=5, duration=1000)])
    assert _wire_native.parse_get_rate_limits(data) is not None


def test_empty_batch_returns_empty_response():
    inst = mk_instance()
    try:
        out = pb.GetRateLimitsResp.FromString(
            inst.get_rate_limits_wire(
                pb.GetRateLimitsReq().SerializeToString(), now_ms=NOW))
        assert len(out.responses) == 0
    finally:
        inst.close()


def test_malformed_bytes_raise_value_error():
    inst = mk_instance()
    try:
        with pytest.raises(ValueError, match="invalid GetRateLimitsReq"):
            inst.get_rate_limits_wire(b"\x99\x99 not a proto", now_ms=NOW)
    finally:
        inst.close()


def test_invalid_utf8_falls_back_not_accepted():
    # name bytes 0xFF 0xFE are not UTF-8: pb2 rejects the message, so the
    # fast lane must not silently accept it (same request, same outcome,
    # regardless of which lane runs)
    bad = bytes([0x0A, 0x08, 0x0A, 0x02, 0xFF, 0xFE, 0x12, 0x02, 0x6B,
                 0x31])
    assert _wire_native.parse_get_rate_limits(bad) is None


def test_multibyte_utf8_accepted_on_fast_lane():
    reqs = [RateLimitRequest(name="名前", unique_key="ключ", hits=1,
                             limit=5, duration=60_000)]
    assert _wire_native.parse_get_rate_limits(to_wire(reqs)) is not None
    fast, slow = run_both(reqs)
    assert_match(fast, slow)


def test_oversize_batch_raises():
    inst = mk_instance()
    try:
        reqs = [RateLimitRequest(name="o", unique_key=f"k{i}", hits=1,
                                 limit=5, duration=1000)
                for i in range(1001)]
        with pytest.raises(ValueError, match="too large"):
            inst.get_rate_limits_wire(to_wire(reqs), now_ms=NOW)
    finally:
        inst.close()


def test_sequential_state_carries_across_wire_calls():
    inst = mk_instance()
    try:
        data = to_wire([RateLimitRequest(name="s", unique_key="k", hits=1,
                                         limit=3, duration=60_000)])
        statuses = []
        for i in range(5):
            out = pb.GetRateLimitsResp.FromString(
                inst.get_rate_limits_wire(data, now_ms=NOW + i))
            statuses.append(int(out.responses[0].status))
        assert statuses == [0, 0, 0, 1, 1]
    finally:
        inst.close()


def test_wire_lane_auto_grows_under_live_pressure():
    """The wire lane inherits auto-grow: a tiny table fills with live
    keys and capacity doubles instead of surfacing 'table full'."""
    inst = V1Instance(
        Config(cache_size=1 << 8, cache_autogrow_max=1 << 14,
               sweep_interval_ms=0),
        mesh=make_mesh(n=2))
    try:
        reqs = [RateLimitRequest(name="wag", unique_key=f"k{i}", hits=1,
                                 limit=9, duration=10**7)
                for i in range(900)]
        out = pb.GetRateLimitsResp.FromString(
            inst.get_rate_limits_wire(to_wire(reqs), now_ms=NOW))
        assert all(r.error == "" for r in out.responses)
        assert inst.engine.cap_local * inst.engine.n >= 1024
        # every key re-findable at its consumed value
        out = pb.GetRateLimitsResp.FromString(
            inst.get_rate_limits_wire(to_wire(reqs), now_ms=NOW + 1))
        assert {r.remaining for r in out.responses} == {7}
    finally:
        inst.close()

"""SO_REUSEPORT front-door group: N daemon subprocesses share one
client port; the kernel spreads connections; keys stay ring-consistent
because every process forwards non-owned sub-batches over the peer
wire lane.

reference: the reference scales its front door with goroutines inside
one process (workers.go); a GIL-bound host scales with processes, so
the equivalent deployment is this group (VERDICT r1 item 5).
"""
from __future__ import annotations

import socket
import sys

import grpc
import pytest

from gubernator_tpu.cluster import start_subprocess_group
from gubernator_tpu.proto import gubernator_pb2 as pb

pytestmark = pytest.mark.skipif(
    not hasattr(socket, "SO_REUSEPORT") or not sys.platform.startswith("linux"),
    reason="SO_REUSEPORT group is a Linux deployment shape")


def _raw_channel(addr: str) -> grpc.Channel:
    # use_local_subchannel_pool: each channel gets its own TCP
    # connection, so SO_REUSEPORT can spread them across processes
    # (the global pool would collapse same-target channels onto one
    # subchannel = one process).
    return grpc.insecure_channel(
        addr, options=[("grpc.use_local_subchannel_pool", 1)])


def _batch(key: str, hits: int, limit: int = 1_000_000) -> bytes:
    m = pb.GetRateLimitsReq()
    r = m.requests.add()
    r.name = "group"
    r.unique_key = key
    r.hits = hits
    r.limit = limit
    r.duration = 60_000
    return m.SerializeToString()


@pytest.fixture(scope="module")
def group():
    g = start_subprocess_group(2, cache_size=1 << 12, batch_rows=256)
    yield g
    g.stop()


def test_group_conserves_hits_across_connections(group):
    """The same key hit over many distinct connections (landing on
    whichever process the kernel picks) must drain exactly once per
    hit: ownership is ring-global, not per-process."""
    chans = [_raw_channel(group.client_address) for i in range(12)]
    calls = [c.unary_unary("/pb.gubernator.V1/GetRateLimits")
             for c in chans]
    try:
        total = 0
        for i, call in enumerate(calls):
            data = call(_batch("shared-key", hits=3), timeout=30)
            total += 3
            resp = pb.GetRateLimitsResp.FromString(data)
            assert resp.responses[0].status == 0  # UNDER_LIMIT
        # hits=0 query reads without consuming
        data = calls[0](_batch("shared-key", hits=0), timeout=30)
        resp = pb.GetRateLimitsResp.FromString(data)
        assert resp.responses[0].remaining == 1_000_000 - total
    finally:
        for c in chans:
            c.close()


def test_group_spreads_connections(group):
    """With 12 distinct connections over 2 processes, both processes
    should see client traffic (P[all land on one] ≈ 2^-11)."""
    import urllib.request

    chans = [_raw_channel(group.client_address) for i in range(12)]
    calls = [c.unary_unary("/pb.gubernator.V1/GetRateLimits")
             for c in chans]
    try:
        for i, call in enumerate(calls):
            call(_batch(f"spread-{i}", hits=1), timeout=30)
    finally:
        for c in chans:
            c.close()
    seen = 0
    for addr in group.http_addresses:
        with urllib.request.urlopen(f"http://{addr}/metrics",
                                    timeout=10) as f:
            text = f.read().decode()
        # any client-lane counter > 0 means this process served ingress
        got = any(
            line.split()[-1] not in ("0", "0.0")
            for line in text.splitlines()
            if line.startswith("gubernator_wire_lane_requests_total")
            and ('lane="wire_local"' in line
                 or 'lane="wire_clustered"' in line
                 or 'lane="pb2_fallback"' in line))
        seen += bool(got)
    assert seen == 2, "kernel did not spread connections (or metrics lane missing)"


def test_group_health_on_shared_port(group):
    ch = _raw_channel(group.client_address)
    try:
        check = ch.unary_unary("/grpc.health.v1.Health/Check")
        assert check(b"", timeout=10) == bytes([0x08, 0x01])
    finally:
        ch.close()

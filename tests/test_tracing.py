"""Tracing/profiling hooks (SURVEY.md §5.1 analog)."""
import glob
import os

from gubernator_tpu.metrics import Metrics
from gubernator_tpu.tracing import DeviceProfiler, span, step_annotation


def test_span_records_duration_metric():
    m = Metrics()
    with span("TestSection", metrics=m):
        pass
    rendered = m.render().decode()
    assert 'gubernator_func_duration_count{name="TestSection"}' in rendered


def test_span_noop_without_metrics():
    with span("nothing"):
        pass  # must not raise even with no OTEL installed


def test_step_annotation_wraps_device_work():
    import jax.numpy as jnp

    with step_annotation("unit-test-step"):
        assert int(jnp.arange(4).sum()) == 6


def test_device_profiler_writes_trace(tmp_path):
    import jax.numpy as jnp

    d = str(tmp_path / "prof")
    prof = DeviceProfiler(d)
    jnp.arange(128).sum().block_until_ready()
    prof.stop()
    files = glob.glob(os.path.join(d, "**", "*"), recursive=True)
    assert any(os.path.isfile(f) for f in files), "no trace files written"


def test_from_env_disabled(monkeypatch):
    monkeypatch.delenv("GUBER_PROFILE_DIR", raising=False)
    assert DeviceProfiler.from_env() is None

"""Tracing/profiling hooks (SURVEY.md §5.1 analog) + W3C traceparent
propagation across the peer wire (otelgrpc interceptor parity —
VERDICT r1 missing item 5)."""
import glob
import os
import threading
import time

import grpc
import pytest

from gubernator_tpu import tracing
from gubernator_tpu.metrics import Metrics
from gubernator_tpu.tracing import (DeviceProfiler, current_traceparent,
                                    parse_traceparent, request_context,
                                    span, step_annotation)


def test_span_records_duration_metric():
    m = Metrics()
    with span("TestSection", metrics=m):
        pass
    rendered = m.render().decode()
    assert 'gubernator_func_duration_count{name="TestSection"}' in rendered


def test_span_noop_without_metrics():
    with span("nothing"):
        pass  # must not raise even with no OTEL installed


def test_step_annotation_wraps_device_work():
    import jax.numpy as jnp

    with step_annotation("unit-test-step"):
        assert int(jnp.arange(4).sum()) == 6


def test_device_profiler_writes_trace(tmp_path):
    import jax.numpy as jnp

    d = str(tmp_path / "prof")
    prof = DeviceProfiler(d)
    jnp.arange(128).sum().block_until_ready()
    prof.stop()
    files = glob.glob(os.path.join(d, "**", "*"), recursive=True)
    assert any(os.path.isfile(f) for f in files), "no trace files written"


def test_from_env_disabled(monkeypatch):
    monkeypatch.delenv("GUBER_PROFILE_DIR", raising=False)
    assert DeviceProfiler.from_env() is None


TID = "4bf92f3577b34da6a3ce929d0e0e4736"


class TestTraceparent:
    def test_parse_roundtrip(self):
        assert parse_traceparent(f"00-{TID}-00f067aa0ba902b7-01") == \
            (TID, "01")

    @pytest.mark.parametrize("bad", [
        None, "", "garbage", "01-" + TID + "-00f067aa0ba902b7-01",
        "00-" + "0" * 32 + "-00f067aa0ba902b7-01",
        "00-" + TID + "-" + "0" * 16 + "-01",
        "00-xyz-00f067aa0ba902b7-01",
    ])
    def test_parse_rejects(self, bad):
        assert parse_traceparent(bad) is None

    def test_context_adopts_trace_id_with_fresh_span_id(self):
        assert current_traceparent() is None
        with request_context(f"00-{TID}-00f067aa0ba902b7-01"):
            out1 = current_traceparent()
            out2 = current_traceparent()
            assert out1.split("-")[1] == TID
            # a fresh span id per hop, never the parent's
            assert out1.split("-")[2] != "00f067aa0ba902b7"
            assert out1.split("-")[2] != out2.split("-")[2]
        assert current_traceparent() is None

    def test_context_starts_new_trace_when_absent(self):
        with request_context(None):
            tp = current_traceparent()
            assert parse_traceparent(tp) is not None


class TestPropagationAcrossPeers:
    def test_trace_id_reaches_the_owning_peer(self):
        """Client → daemon 0 (gRPC, traceparent metadata) → forwarded
        to the key's owner over the peer wire: the owner's servicer
        must see the SAME trace id with a different span id."""
        from gubernator_tpu import cluster as cluster_mod
        from gubernator_tpu.proto import gubernator_pb2 as pb
        from gubernator_tpu.wire import req_to_pb
        from gubernator_tpu.types import RateLimitRequest

        seen = []
        mu = threading.Lock()

        def hook(header):
            with mu:
                seen.append(header)

        c = cluster_mod.start(3)
        tracing.inbound_hook = hook
        try:
            msg = pb.GetRateLimitsReq()
            msg.requests.extend(req_to_pb(RateLimitRequest(
                name="tp", unique_key=f"k{i}", hits=1, limit=10,
                duration=60_000)) for i in range(40))
            ch = grpc.insecure_channel(c.grpc_address(0))
            call = ch.unary_unary(
                "/pb.gubernator.V1/GetRateLimits",
                request_serializer=pb.GetRateLimitsReq.SerializeToString,
                response_deserializer=pb.GetRateLimitsResp.FromString)
            parent = f"00-{TID}-00f067aa0ba902b7-01"
            resp = call(msg, timeout=60,
                        metadata=[("traceparent", parent)])
            assert len(resp.responses) == 40
            deadline = time.time() + 5
            while time.time() < deadline:
                with mu:
                    tids = {parse_traceparent(h)[0] for h in seen
                            if parse_traceparent(h)}
                # daemon 0 saw the client's header; ≥1 peer saw a
                # propagated one (40 keys spread over 3 owners)
                if len([h for h in seen if h]) >= 2 and TID in tids:
                    break
                time.sleep(0.1)
            with mu:
                headers = [h for h in seen if h]
                tids = [parse_traceparent(h)[0] for h in headers
                        if parse_traceparent(h)]
                spans = [h.split("-")[2] for h in headers]
            assert tids.count(TID) >= 2, (
                "trace id did not propagate to the owning peer: "
                f"{headers}")
            # hops got fresh span ids, not the client's
            assert spans.count("00f067aa0ba902b7") <= 1
        finally:
            tracing.inbound_hook = None
            c.stop()

    def test_trace_id_on_owner_daemons_forwarded_hop_events(self):
        """ISSUE 4 regression guard for the PR-3 raw send lanes: the
        client's 32-hex trace id must come out the far end — on the
        OWNER daemons' flight-recorder wave events for the forwarded
        hop (grpc metadata → raw-TLV lane flush → owner servicer →
        dispatcher wave), not just on the inbound-header hook."""
        from gubernator_tpu import cluster as cluster_mod
        from gubernator_tpu.proto import gubernator_pb2 as pb
        from gubernator_tpu.types import RateLimitRequest
        from gubernator_tpu.wire import req_to_pb

        tid = "feedfacefeedfacefeedfacefeedface"
        c = cluster_mod.start(3)
        try:
            msg = pb.GetRateLimitsReq()
            msg.requests.extend(req_to_pb(RateLimitRequest(
                name="fhop", unique_key=f"fk{i}", hits=1, limit=10,
                duration=60_000)) for i in range(60))
            ch = grpc.insecure_channel(c.grpc_address(0))
            call = ch.unary_unary(
                "/pb.gubernator.V1/GetRateLimits",
                request_serializer=pb.GetRateLimitsReq.SerializeToString,
                response_deserializer=pb.GetRateLimitsResp.FromString)
            resp = call(msg, timeout=60,
                        metadata=[("traceparent",
                                   f"00-{tid}-00f067aa0ba902b7-01")])
            assert len(resp.responses) == 60
            # 60 keys spread across 3 owners: both non-entry daemons
            # served a forwarded sub-batch.  The lanes resolve futures
            # before the client call returns, so the owner-side wave
            # events exist by now — but poll briefly anyway (recorder
            # writes happen on the owners' servicer threads).
            deadline = time.time() + 10
            hits = {}
            while time.time() < deadline:
                hits = {
                    i: [e for e in c.instance_at(i).recorder.events()
                        if e.get("trace") == tid
                        and e["kind"].startswith("wave_")]
                    for i in (1, 2)}
                if all(hits.values()):
                    break
                time.sleep(0.1)
            for i, evs in hits.items():
                assert evs, (f"owner daemon {i} recorded no wave event "
                             f"with the client's trace id")
                kinds = {e["kind"] for e in evs}
                assert "wave_completed" in kinds, kinds
        finally:
            ch.close()
            c.stop()

"""Device-memory ledger (ISSUE 13): the exactness audit.

The ledger's claim is strong — accounted bytes equal the live jax-array
``nbytes`` at any instant, on every engine configuration — so the audit
independently walks the instance's device-resident state (engine table
leaves, mesh-GLOBAL replica + both hit accumulators, hot-set replica +
base buffers) and compares against ``memledger.snapshot()`` totals.
Covered configs: classic sharded, fused XLA serving, mesh-GLOBAL bound,
and the tiered store (whose cold tier must land on the HOST ledger, not
the device one).  Enrollment is leak-free across engine stand-down, and
the two-tier snapshot/restore round trip keeps the audit exact because
probes re-read the live rebinding state.  Plus the ledger unit surface:
pressure edge-triggering, suspend/resume, republish label hygiene, and
the advisor's floor/budget invariants on synthetic demand."""
import jax
import pytest

from gubernator_tpu.config import Config
from gubernator_tpu.instance import V1Instance
from gubernator_tpu.memledger import MemoryLedger, _pow2_ceil
from gubernator_tpu.parallel import make_mesh
from gubernator_tpu.store import MockLoader
from gubernator_tpu.types import Behavior, RateLimitRequest

NOW = 1_793_000_000_000
DAY = 86_400_000


def _reqs(prefix, n, behavior=Behavior.BATCHING, duration=DAY):
    return [RateLimitRequest(name="led", unique_key=f"{prefix}{i}",
                             hits=1, limit=10 ** 6, duration=duration,
                             behavior=behavior)
            for i in range(n)]


def _expected_device_bytes(inst) -> int:
    """The audit's independent walk of every device-resident array the
    instance owns — deliberately NOT via the ledger's probes."""
    total = sum(int(a.nbytes)
                for a in jax.tree.leaves(inst.engine.state))
    mge = inst._meshglobal
    if mge is not None:
        with mge._state_mu:
            total += sum(int(a.nbytes)
                         for a in jax.tree.leaves(mge.state))
            total += sum(int(a.nbytes) for a in mge._acc)
    hs = inst._hotset
    if hs is not None:
        with hs._state_mu:
            total += sum(int(a.nbytes)
                         for a in jax.tree.leaves(hs.state))
            total += int(hs.base_rem.nbytes) + int(hs.base_t.nbytes)
    return total


def _audit(inst):
    snap = inst.memledger.snapshot()
    assert snap["enabled"] is True
    for name, rec in snap["consumers"].items():
        assert "error" not in rec, (name, rec)
    assert snap["device_bytes"] == _expected_device_bytes(inst), \
        snap["consumers"]
    assert 0.0 <= snap["pressure"] <= 1.0
    return snap


def test_exact_classic():
    inst = V1Instance(Config(cache_size=2048, sweep_interval_ms=0),
                      mesh=make_mesh(n=1))
    try:
        inst.get_rate_limits(_reqs("c", 200), now_ms=NOW)
        snap = _audit(inst)
        hot = snap["consumers"]["hot_table"]
        assert hot["capacity_rows"] >= 2048
        assert hot["occupied_rows"] >= 200
        assert hot["advisable"] is True and hot["host"] is False
    finally:
        inst.close()


def test_exact_fused_xla(monkeypatch):
    monkeypatch.setenv("GUBER_ENGINE", "pallas")  # → fused XLA off-TPU
    inst = V1Instance(Config(cache_size=2048, sweep_interval_ms=0),
                      mesh=make_mesh(n=1))
    try:
        assert type(inst.engine).__name__ == "XlaFusedEngine"
        inst.get_rate_limits(_reqs("f", 200), now_ms=NOW)
        _audit(inst)
    finally:
        inst.close()


def test_exact_mesh_global_bound():
    inst = V1Instance(Config(cache_size=2048, sweep_interval_ms=0,
                             global_mode="mesh"), mesh=make_mesh(n=1))
    try:
        # GLOBAL traffic builds the mesh tier lazily; its replica and
        # BOTH accumulator buffers must land on the device ledger
        inst.get_rate_limits(_reqs("g", 32, behavior=Behavior.GLOBAL),
                             now_ms=NOW)
        snap = _audit(inst)
        mg = snap["consumers"]["mesh_global"]
        assert mg["bytes"] > 0 and mg["occupied_rows"] >= 32
        assert mg["advisable"] is True
    finally:
        inst.close()


def test_exact_tiered_and_snapshot_restore_roundtrip():
    """Cap 1024 vs a 3000-key domain: overflow rows live in the HOST
    cold store; the audit stays exact through spill and through the
    two-tier snapshot/restore round trip (probes re-read the live
    rebinding state, so a restored instance audits exactly too)."""
    loader = MockLoader()

    def _cfg():
        return Config(cache_size=1024, cache_autogrow_max=1024,
                      tier_cold=True, tier_promote_threshold=2,
                      hot_set_capacity=0, sweep_interval_ms=0,
                      loader=loader)

    inst = V1Instance(_cfg(), mesh=make_mesh(n=1))
    try:
        for base in range(0, 3000, 500):
            inst.get_rate_limits(_reqs(f"t{base}_", 500),
                                 now_ms=NOW + base)
        snap = _audit(inst)
        cold = snap["consumers"]["cold_store"]
        assert cold["host"] is True and cold["bytes"] > 0
        assert cold["occupied_rows"] > 0
        assert inst._tier.mem_bytes() == cold["bytes"]
        assert snap["host_bytes"] >= cold["bytes"]
    finally:
        inst.close()  # saves BOTH tiers through the loader
    assert loader.called["save"] == 1
    inst2 = V1Instance(_cfg(), mesh=make_mesh(n=1))
    try:
        snap2 = _audit(inst2)
        assert snap2["consumers"]["cold_store"]["occupied_rows"] > 0, \
            "restore overflow rows did not land cold"
    finally:
        inst2.close()


def test_enroll_release_leak_free_across_stand_down():
    inst = V1Instance(Config(cache_size=1024, sweep_interval_ms=0),
                      mesh=make_mesh(n=1))
    led = inst.memledger
    assert "hot_table" in led.consumers()
    inst.close()
    assert led.consumers() == [], "close() must drain every enrollment"
    assert led.release("hot_table") is False
    # a released ledger still snapshots (empty plane, no stale probes)
    snap = led.snapshot()
    assert snap["device_bytes"] == 0 and snap["consumers"] == {}


def test_disabled_by_env(monkeypatch):
    monkeypatch.setenv("GUBER_MEM_LEDGER", "0")
    inst = V1Instance(Config(cache_size=1024, sweep_interval_ms=0),
                      mesh=make_mesh(n=1))
    try:
        assert inst.memledger is None
    finally:
        inst.close()


# ---- ledger unit surface (no instance) ------------------------------------


class _Recorder:
    def __init__(self):
        self.events = []

    def record(self, kind, **fields):
        self.events.append(dict(fields, kind=kind))


def test_pressure_edge_triggering():
    rec = _Recorder()
    led = MemoryLedger(recorder=rec)
    occ = {"n": 0}
    led.enroll("tbl", lambda: {"bytes": 1 << 20, "capacity_rows": 100,
                               "occupied_rows": occ["n"]},
               advisable=True)
    assert led.pressure_sample() == (0.0, led.pressure_target)
    occ["n"] = 95  # above the 0.85 default target
    p, _t = led.pressure_sample()
    assert p == pytest.approx(0.95)
    led.pressure_sample()  # still hot: must NOT re-record
    kinds = [e["kind"] for e in rec.events]
    assert kinds == ["memory_pressure"], rec.events
    assert rec.events[0]["occupancy"] == {"tbl": 0.95}
    occ["n"] = 10  # excursion ends → the edge re-arms
    led.pressure_sample()
    occ["n"] = 95
    led.pressure_sample()
    assert [e["kind"] for e in rec.events] == ["memory_pressure"] * 2


def test_suspend_resume_and_probe_error_containment():
    led = MemoryLedger()
    led.enroll("ok", lambda: {"bytes": 64})
    led.enroll("boom", lambda: (_ for _ in ()).throw(RuntimeError("x")))
    snap = led.snapshot()
    assert snap["device_bytes"] == 64
    assert "error" in snap["consumers"]["boom"]
    led.suspend()
    assert led.enabled is False
    empty = led.snapshot()
    assert empty["device_bytes"] == 0 and empty["consumers"] == {}
    led.resume()
    assert led.snapshot()["device_bytes"] == 64
    assert sorted(led.consumers()) == ["boom", "ok"]


def test_advise_floor_and_budget_invariants():
    led = MemoryLedger()
    led.enroll("hot", lambda: {
        "bytes": 1 << 20, "capacity_rows": 1024, "occupied_rows": 1024,
        "demand": {"ranks": [1000 - i for i in range(512)]}},
        advisable=True)
    led.enroll("idle", lambda: {
        "bytes": 1 << 20, "capacity_rows": 1024, "occupied_rows": 8,
        "demand": {"fold_rate": 2.0}}, advisable=True)
    led.enroll("host_thing", lambda: {"bytes": 123}, host=True)
    adv = led.advise(total_rows=2048)
    assert set(adv["advised"]) == {"hot", "idle"}, \
        "host consumers must never enter the advised split"
    assert sum(adv["advised"].values()) == 2048, adv
    assert all(v >= adv["floor_rows"] for v in adv["advised"].values())
    # demand concentrates on `hot`: the idle tier keeps its floor only
    assert adv["advised"]["idle"] == adv["floor_rows"]
    assert adv["advised"]["hot"] == 2048 - adv["floor_rows"]
    assert adv["advised_pow2"]["hot"] == _pow2_ceil(
        adv["advised"]["hot"])
    assert adv["demand"]["hot"]["ranks"][0] == 1000


def test_republish_removes_departed_labels():
    from gubernator_tpu.metrics import Metrics

    m = Metrics()
    led = MemoryLedger()
    led.enroll("a", lambda: {"bytes": 10, "capacity_rows": 4,
                             "occupied_rows": 2})
    led.republish(m)
    text = m.render().decode()
    assert 'gubernator_memledger_bytes{consumer="a"} 10.0' in text
    assert ('gubernator_memledger_rows{consumer="a",state="capacity"} '
            '4.0') in text
    led.release("a")
    led.enroll("b", lambda: {"bytes": 7})
    led.republish(m)
    text = m.render().decode()
    assert 'consumer="a"' not in text, "departed label set must go"
    assert 'gubernator_memledger_bytes{consumer="b"} 7.0' in text


def test_memledger_cli_and_debug_endpoint(capsys):
    """`GET /debug/memory?advise=1` and `guber-cli debug memory` over a
    live daemon: the fourth debug plane round-trips, and deep health
    carries the memory block."""
    import json
    import urllib.request

    from gubernator_tpu.cmd.cli import main
    from gubernator_tpu.config import DaemonConfig
    from gubernator_tpu.daemon import spawn_daemon
    from gubernator_tpu.netutil import free_port

    d = spawn_daemon(DaemonConfig(
        grpc_listen_address=f"127.0.0.1:{free_port()}",
        http_listen_address=f"127.0.0.1:{free_port()}",
        cache_size=1 << 10), mesh=make_mesh(n=1))
    try:
        base = f"http://127.0.0.1:{d.http_port}"
        with urllib.request.urlopen(f"{base}/debug/memory?advise=1",
                                    timeout=10) as r:
            body = json.loads(r.read())
        assert body["enabled"] is True
        assert "hot_table" in body["consumers"]
        assert body["device_bytes"] > 0
        assert "advise" in body and "advised" in body["advise"]
        with urllib.request.urlopen(f"{base}/healthz?deep=1",
                                    timeout=10) as r:
            deep = json.loads(r.read())
        assert deep["memory"]["device_bytes"] == body["device_bytes"]
        assert main(["debug", "memory", "--url", base,
                     "--advise"]) == 0
        out = capsys.readouterr().out
        assert "hot_table" in out and "advised" in out
    finally:
        d.close()

"""Instance-level hot-set integration: promotion, psum convergence,
fallback rules."""
import time

from gubernator_tpu.config import BehaviorConfig, Config
from gubernator_tpu.instance import V1Instance
from gubernator_tpu.parallel import make_mesh
from gubernator_tpu.types import Algorithm, Behavior, RateLimitRequest, Status

NOW = 1_765_000_000_000


def req(key="h1", hits=1, **kw):
    d = dict(limit=100_000, duration=600_000, behavior=Behavior.GLOBAL)
    d.update(kw)
    return RateLimitRequest(name="hotinst", unique_key=key, hits=hits, **d)


def mk_instance(threshold=8):
    return V1Instance(
        Config(cache_size=1 << 10, sweep_interval_ms=0,
               hot_set_capacity=64, hot_promote_threshold=threshold,
               behaviors=BehaviorConfig(global_sync_wait_ms=25)),
        mesh=make_mesh(n=4))


def test_promotion_and_convergence():
    inst = mk_instance(threshold=8)
    try:
        # below threshold: standard GLOBAL path
        for _ in range(7):
            r = inst.get_rate_limits([req()], now_ms=NOW)[0]
            assert r.error == "" and r.status == Status.UNDER_LIMIT
        assert inst._hotset is None
        # crossing the threshold promotes the key
        inst.get_rate_limits([req()], now_ms=NOW + 1)
        assert inst._hotset is not None and len(inst._hotset.slots) == 1
        # hot-path traffic: served by replicas, folded by the sync loop
        for i in range(20):
            rs = inst.get_rate_limits([req() for _ in range(10)],
                                      now_ms=NOW + 2 + i)
            assert all(r.error == "" for r in rs)
        deadline = time.time() + 10
        while time.time() < deadline:
            time.sleep(0.05)
            if inst._hotset.sync_count > 0:
                rs = inst.get_rate_limits([req(hits=0)] * 4,
                                          now_ms=NOW + 100)
                if len({r.remaining for r in rs}) == 1:
                    break
        assert inst._hotset.sync_count > 0
        rs = inst.get_rate_limits([req(hits=0)] * 8, now_ms=NOW + 101)
        assert len({r.remaining for r in rs}) == 1, "replicas not converged"
    finally:
        inst.close()


def test_flagged_requests_bypass_hot_set():
    inst = mk_instance(threshold=1)
    try:
        inst.get_rate_limits(
            [req(key="flg",
                 behavior=Behavior.GLOBAL | Behavior.RESET_REMAINING)],
            now_ms=NOW)
        hs = inst._hotset
        assert hs is None or len(hs.slots) == 0
    finally:
        inst.close()


def test_leaky_promotes_and_demotes_preserving_consumption():
    """LEAKY_BUCKET GLOBAL keys ride the psum tier too; consumption
    survives promote → hot serving → demote."""
    from gubernator_tpu.hashing import hash_key
    from gubernator_tpu.types import PeerInfo

    inst = mk_instance(threshold=1)
    try:
        kh = hash_key("hotinst", "lk")

        def lr(hits=1):
            return req(key="lk", hits=hits, limit=1000,
                       duration=600_000, algorithm=Algorithm.LEAKY_BUCKET)

        inst.get_rate_limits([lr()], now_ms=NOW)  # promotes
        assert inst._hotset is not None and inst._hotset.is_pinned(kh)
        rs = inst.get_rate_limits([lr() for _ in range(10)], now_ms=NOW + 1)
        assert all(r.status == Status.UNDER_LIMIT and r.error == ""
                   for r in rs)
        # peers joining demotes; the merged leaky row lands in the table
        inst.set_peers([PeerInfo(grpc_address="127.0.0.1:1"),
                        PeerInfo(grpc_address="127.0.0.1:2")])
        assert not inst._hotset.is_pinned(kh)
        assert inst.metrics.hot_demotion_counter.labels(
            reason="membership_change")._value.get() >= 1
        import numpy as np

        found, cols = inst.engine.gather_rows(np.array([kh], np.uint64))
        assert found[0]
        assert int(cols["meta"][0]) & 1 == 1  # still a leaky row
        # 11 hits of 600_000 td each against burst 1000×600_000;
        # ≤ 1 ms of replenish (1000/600s) rounds to 0 whole tokens
        assert int(cols["remaining"][0]) // 600_000 == 1000 - 11
    finally:
        inst.close()


def test_config_change_demotes_preserving_consumption():
    from gubernator_tpu.hashing import hash_key

    inst = mk_instance(threshold=1)
    try:
        kh = hash_key("hotinst", "cfg")
        inst.get_rate_limits([req(key="cfg", limit=100)], now_ms=NOW)
        assert inst._hotset.is_pinned(kh)
        # consume 10 more on the hot path
        inst.get_rate_limits([req(key="cfg", limit=100) for _ in range(10)],
                             now_ms=NOW + 1)
        # limit change → demotion: state migrates back, new limit applies
        before = inst.metrics.hot_demotion_counter.labels(
            reason="config_change")._value.get()
        r = inst.get_rate_limits([req(key="cfg", limit=50)], now_ms=NOW + 2)[0]
        assert not inst._hotset.is_pinned(kh)
        # the perf-cliff is observable: demotion shows up at /metrics
        after = inst.metrics.hot_demotion_counter.labels(
            reason="config_change")._value.get()
        assert after == before + 1, (before, after)
        assert r.limit == 50
        # 11 consumed at limit 100 → remaining 89; limit 100→50 adjust:
        # clamp(89 + (50-100), 0, 50) = 39; this request takes 1 → 38
        assert r.remaining == 38, r
    finally:
        inst.close()


def test_peers_joining_demotes_hot_keys():
    from gubernator_tpu.hashing import hash_key
    from gubernator_tpu.types import PeerInfo

    inst = mk_instance(threshold=1)
    try:
        kh = hash_key("hotinst", "join")
        inst.get_rate_limits([req(key="join")], now_ms=NOW)
        inst.get_rate_limits([req(key="join") for _ in range(5)],
                             now_ms=NOW + 1)
        assert inst._hotset.is_pinned(kh)
        inst.set_peers([PeerInfo(grpc_address="127.0.0.1:1"),
                        PeerInfo(grpc_address="127.0.0.1:2")])
        assert not inst._hotset.is_pinned(kh)
        assert inst.metrics.hot_demotion_counter.labels(
            reason="membership_change")._value.get() >= 1
        # migrated consumption is visible in the sharded table
        import numpy as np

        found, cols = inst.engine.gather_rows(np.array([kh], np.uint64))
        assert found[0]
        assert int(cols["remaining"][0]) == 100_000 - 6
    finally:
        inst.close()

"""Property-based wire-lane parity (hypothesis): for ANY wire-encodable
request stream, get_rate_limits_wire (C++ columnar lane when eligible,
pb2 fallback otherwise) must match the sequential oracle bit-for-bit —
the same referee the object path answers to in test_property_parity."""
import pytest
from hypothesis import HealthCheck, given, settings
import os as _os

#: deep-fuzz multiplier: GUBER_FUZZ_X=20 turns the quick CI
#: budgets into a long adversarial run (same strategies)
_FX = int(_os.environ.get("GUBER_FUZZ_X", "1"))
from hypothesis import strategies as st

from gubernator_tpu import Algorithm, Behavior, Oracle, RateLimitRequest
from gubernator_tpu.config import Config
from gubernator_tpu.instance import V1Instance, _wire_native
from gubernator_tpu.parallel import make_mesh
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.wire import req_to_pb

if _wire_native is None:  # pragma: no cover
    pytest.skip("native extension not built", allow_module_level=True)

NOW = 1_772_000_000_000

_behavior = st.sampled_from([
    Behavior.BATCHING, Behavior.NO_BATCHING, Behavior.RESET_REMAINING,
    Behavior.DRAIN_OVER_LIMIT,
    Behavior.RESET_REMAINING | Behavior.DRAIN_OVER_LIMIT,
])

_request = st.builds(
    RateLimitRequest,
    # unicode names exercise the C++ UTF-8 path against pb2's encoder
    name=st.sampled_from(["prop", "προπ", "属性"]),
    unique_key=st.integers(0, 11).map(lambda i: f"k{i}"),  # forced dups
    hits=st.integers(0, 6) | st.just(2**40),  # clamp coverage
    limit=st.integers(0, 30) | st.just(2**40),
    duration=st.integers(1, 50_000),
    algorithm=st.sampled_from([Algorithm.TOKEN_BUCKET,
                               Algorithm.LEAKY_BUCKET]),
    behavior=_behavior,
    burst=st.integers(0, 40),
)

_stream = st.lists(
    st.tuples(st.lists(_request, min_size=1, max_size=40),
              st.integers(0, 40_000)),
    min_size=1, max_size=4)


def _wire(reqs):
    m = pb.GetRateLimitsReq()
    m.requests.extend(req_to_pb(r) for r in reqs)
    return m.SerializeToString()


@settings(max_examples=_FX * 20, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(_stream)
def test_wire_lane_matches_oracle_on_any_stream(stream):
    inst = V1Instance(Config(cache_size=1 << 11, sweep_interval_ms=0),
                      mesh=make_mesh(n=2))
    try:
        oracle = Oracle()
        now = NOW
        for reqs, dt in stream:
            now += dt
            want = oracle.check_batch(reqs, now)
            out = pb.GetRateLimitsResp.FromString(
                inst.get_rate_limits_wire(_wire(reqs), now_ms=now))
            assert len(out.responses) == len(want)
            for i, (w, g) in enumerate(zip(want, out.responses)):
                assert g.error == ""
                assert (int(g.status), g.remaining, g.reset_time,
                        g.limit) == (int(w.status), w.remaining,
                                     w.reset_time, w.limit), (i, reqs[i])
    finally:
        inst.close()

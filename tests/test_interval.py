"""Interval ticker tests (reference: interval_test.go analog)."""
import threading
import time

from gubernator_tpu.interval import Interval, IntervalLoop


def test_interval_ticks_and_stops():
    iv = Interval(period_ms=10)
    assert iv.wait() is True  # period elapsed
    iv.stop()
    assert iv.wait() is False


def test_interval_fire_wakes_early():
    iv = Interval(period_ms=10_000)
    t0 = time.monotonic()
    threading.Timer(0.02, iv.fire).start()
    assert iv.wait() is True
    assert time.monotonic() - t0 < 5


def test_interval_loop_runs_and_flushes_on_close():
    calls = []
    loop = IntervalLoop(5, lambda: calls.append(1), name="t")
    time.sleep(0.08)
    loop.close()
    n = len(calls)
    assert n >= 2  # ticked several times + final flush
    time.sleep(0.03)
    assert len(calls) == n  # no ticks after close


def test_netutil():
    from gubernator_tpu.netutil import free_port, resolve_host_ip, split_host_port

    assert split_host_port("a.b.c:80") == ("a.b.c", 80)
    assert resolve_host_ip("localhost:99").endswith(":99")
    ip = resolve_host_ip("0.0.0.0:1051")
    assert not ip.startswith("0.0.0.0")
    p = free_port()
    assert 1024 < p < 65536

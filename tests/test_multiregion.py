"""MULTI_REGION cross-datacenter replication tests (reference:
mutliregion.go + region_picker.go behavior — SURVEY.md §2.1/§5.8).
Two regions × 2 daemons each, all in-process."""
import time

import pytest

from gubernator_tpu import cluster as cluster_mod
from gubernator_tpu.client import Client
from gubernator_tpu.config import BehaviorConfig, DaemonConfig
from gubernator_tpu.netutil import free_port
from gubernator_tpu.parallel import make_mesh
from gubernator_tpu.types import Behavior, RateLimitRequest, Status


@pytest.fixture(scope="module")
def regions():
    behaviors = BehaviorConfig(
        batch_timeout_ms=30, batch_wait_ms=30,
        multi_region_sync_wait_ms=50, multi_region_timeout_ms=5000)
    cfgs = []
    for i in range(4):
        cfgs.append(DaemonConfig(
            grpc_listen_address=f"127.0.0.1:{free_port()}",
            http_listen_address="",
            cache_size=1 << 10,
            data_center="dc-east" if i < 2 else "dc-west",
            behaviors=behaviors))
    c = cluster_mod.start_with(cfgs, mesh=make_mesh(n=2))
    yield c
    c.stop()


def req(key, **kw):
    d = dict(hits=1, limit=100, duration=60_000,
             behavior=Behavior.MULTI_REGION)
    d.update(kw)
    return RateLimitRequest(name="mr_test", unique_key=key, **d)


def _remaining_in_region(cluster, daemon_idx, key):
    with Client(cluster.grpc_address(daemon_idx)) as c:
        r = c.check(req(key, hits=0))
        return r.remaining


def test_region_pickers_split(regions):
    inst = regions.instance_at(0)
    pickers = inst.region_pickers()
    assert set(pickers) == {"dc-east", "dc-west"}
    assert len(pickers["dc-east"].peers()) == 2
    assert len(pickers["dc-west"].peers()) == 2


def test_cross_region_hits_converge(regions):
    """Hits applied in dc-east must appear in dc-west's counter within
    the multi-region sync window (eventual consistency)."""
    key = "account:300"
    with Client(regions.grpc_address(0)) as c:  # dc-east daemon
        for _ in range(3):
            r = c.check(req(key, hits=2))
            assert r.error == "" and r.status == Status.UNDER_LIMIT
    # east region sees its own hits immediately
    east = _remaining_in_region(regions, 0, key)
    assert east == 94
    # west region converges asynchronously
    deadline = time.time() + 5
    west = None
    while time.time() < deadline:
        west = _remaining_in_region(regions, 2, key)
        if west == 94:
            break
        time.sleep(0.05)
    assert west == 94, f"west never converged (remaining={west})"


def test_no_ping_pong(regions):
    """The replicated copy must strip MULTI_REGION: counters must NOT
    keep drifting after convergence (double-replication bug guard)."""
    key = "account:301"
    with Client(regions.grpc_address(1)) as c:
        c.check(req(key, hits=5))
    deadline = time.time() + 5
    while time.time() < deadline:
        if _remaining_in_region(regions, 2, key) == 95:
            break
        time.sleep(0.05)
    assert _remaining_in_region(regions, 2, key) == 95
    # let several sync ticks pass; the value must stay put
    time.sleep(0.5)
    assert _remaining_in_region(regions, 2, key) == 95
    assert _remaining_in_region(regions, 0, key) == 95


def test_mr_sync_fault_conservation(regions):
    """ISSUE 7 satellite: multiregion reconciliation fault coverage.
    An armed `mr_sync` fault aborts the flush tick BEFORE the queues
    pop, so the aggregated hits survive intact; once the fault clears,
    the other region converges with the EXACT total — cross-region
    conservation holds through the chaos window."""
    # a key whose dc-east owner IS daemon 0 (the MR queue lives on the
    # region owner, and that is whose faults we arm)
    key = None
    for i in range(200):
        cand = f"account:77{i}"
        if regions.owner_daemon_of(f"mr_test_{cand}") \
                is regions.daemon_at(0):
            key = cand
            break
    assert key is not None
    inst = regions.instance_at(0)  # dc-east owner of `key`
    # arm BEFORE queueing: every flush tick aborts pre-pop, so the
    # aggregate cannot leak out on a clean tick racing the assertions
    inst.faults.arm("mr_sync:error", seed=5)
    try:
        with Client(regions.grpc_address(0)) as c:
            for _ in range(4):
                r = c.check(req(key, hits=3))
                assert r.error == ""
        mr = inst._ensure_mr_manager()
        fired0 = sum(p["fired"]
                     for p in inst.faults.describe()["points"])
        mr.poke()
        deadline = time.time() + 5
        while time.time() < deadline:
            if sum(p["fired"]
                   for p in inst.faults.describe()["points"]) > fired0:
                break
            time.sleep(0.02)
        assert sum(p["fired"]
                   for p in inst.faults.describe()["points"]) > fired0
        # aborted before the pop: the aggregate is still queued whole
        with mr._mu:
            accs = {k: acc for k, (_r, acc, _s) in mr._hits.items()}
            accs.update({k: acc for k, (_t, acc, _s)
                         in mr._hits_raw.items()})
        assert sum(accs.values()) == 12, accs
    finally:
        inst.faults.clear()
    # conservation: after the fault clears, dc-west converges to the
    # exact total (4 × 3 hits) within the sync window
    deadline = time.time() + 8
    west = None
    while time.time() < deadline:
        west = _remaining_in_region(regions, 2, key)
        if west == 88:
            break
        inst.mr_manager.poke()
        time.sleep(0.05)
    assert west == 88, f"west never converged exactly (remaining={west})"

"""Test config: force an 8-device CPU platform (the reference's
cluster/cluster.go in-process multi-daemon analog, SURVEY.md §4) and
enable x64 before jax initializes."""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

"""Test config: force an 8-device CPU platform (the reference's
cluster/cluster.go in-process multi-daemon analog, SURVEY.md §4).

The sandbox's sitecustomize registers the axon TPU plugin at interpreter
start and overwrites the jax_platforms CONFIG (not just the env var) to
"axon,cpu" — so tests must override via jax.config.update, before any
backend initialization.  Env vars alone do not work here.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Persistent compile cache: the step program is large; don't re-pay XLA
# compilation on every pytest invocation (_jax_cache owns the dir choice).
import sys as _sys

_sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _jax_cache

_jax_cache.setup()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# Build the C++ host-ops extension if this checkout hasn't yet (fresh
# clones ship no artifacts) — the wire-lane tests exercise it, and a
# silent pb2 fallback would turn them into false greens.  Best-effort:
# where a toolchain is genuinely absent, the native-dependent tests
# skip via ops.native importorskip instead.
try:
    from gubernator_tpu.ops import _native  # noqa: F401
except ImportError:
    import subprocess
    import sys

    subprocess.run([sys.executable, "gubernator_tpu/ops/setup_native.py",
                    "build_ext", "--inplace"],
                   cwd=os.path.dirname(os.path.dirname(__file__)),
                   check=False, capture_output=True)


@pytest.fixture(scope="session")
def cpu_mesh():
    """Shared 4-device mesh (one compiled step program per mesh shape)."""
    from gubernator_tpu.parallel import make_mesh

    return make_mesh(n=4)

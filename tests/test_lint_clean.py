"""Tier-1 invariant: guberlint reports ZERO violations at HEAD.

This is the enforcement half of the correctness tooling
(tools/guberlint/, CONCURRENCY.md): the checker's semantics are pinned
by tests/test_guberlint.py; this test pins that the tree actually
SATISFIES them — every guarded-by annotation holds, the lock hierarchy
is respected, the GUBER_* registry and faultpoint catalog match the
code, every thread is named and every join bounded, every clock read
declares its time base, traced code is side-effect free, jit call
sites are retrace-stable, and the operator docs match the code.  A red
run here points at the exact file:line to fix (or to annotate, with a
reason).

The suite also carries a wall-clock budget: `make lint` must finish in
under 30 s on the 1-core build host, because a lint gate nobody waits
for is a lint gate nobody runs.
"""
import time

from tools.guberlint import PASS_NAMES, run_passes

#: `make lint` wall-clock budget in seconds (CONCURRENCY.md ›
#: "Running the tooling").  The full 9-pass suite measures ~3 s on the
#: 1-core build host — 30 s is ~10× headroom, not a tight race.
LINT_BUDGET_S = 30.0


def test_tree_is_lint_clean_at_head_within_budget():
    t0 = time.perf_counter()
    violations = run_passes()
    elapsed = time.perf_counter() - t0
    assert not violations, \
        "guberlint violations at HEAD:\n" + "\n".join(
            v.render() for v in violations)
    assert elapsed < LINT_BUDGET_S, \
        f"full guberlint suite took {elapsed:.1f}s — over the " \
        f"{LINT_BUDGET_S:.0f}s budget CONCURRENCY.md promises"


def test_all_passes_ran():
    # run_passes with no filter must cover the full suite — a pass
    # silently dropped from PASS_NAMES would turn the invariant above
    # into a partial check
    assert set(PASS_NAMES) == {"guarded", "lockorder", "envreg",
                               "faultcat", "threads", "clockdomain",
                               "tracedpure", "retrace", "docs"}

"""Tier-1 invariant: guberlint reports ZERO violations at HEAD.

This is the enforcement half of the concurrency-discipline tooling
(tools/guberlint/, CONCURRENCY.md): the checker's semantics are pinned
by tests/test_guberlint.py; this test pins that the tree actually
SATISFIES them — every guarded-by annotation holds, the lock hierarchy
is respected, the GUBER_* registry and faultpoint catalog match the
code, every thread is named and every join bounded.  A red run here
points at the exact file:line to fix (or to annotate, with a reason).
"""
from tools.guberlint import PASS_NAMES, run_passes


def test_tree_is_lint_clean_at_head():
    violations = run_passes()
    assert not violations, \
        "guberlint violations at HEAD:\n" + "\n".join(
            v.render() for v in violations)


def test_all_passes_ran():
    # run_passes with no filter must cover the full suite — a pass
    # silently dropped from PASS_NAMES would turn the invariant above
    # into a partial check
    assert set(PASS_NAMES) == {"guarded", "lockorder", "envreg",
                               "faultcat", "threads"}

"""Multi-device tests on the 8-device virtual CPU mesh — the analog of
the reference's in-process cluster tests (cluster/cluster.go +
functional_test.go › TestGlobalRateLimits, SURVEY.md §4)."""
import numpy as np
import pytest

from gubernator_tpu import Algorithm, Oracle, RateLimitRequest
from gubernator_tpu.parallel import ShardedEngine, make_mesh

NOW = 1_760_000_000_000


def mk(key, **kw):
    d = dict(hits=1, limit=10, duration=60_000)
    d.update(kw)
    return RateLimitRequest(name="shard", unique_key=key, **d)


@pytest.fixture(scope="module")
def engine():
    mesh = make_mesh(n=4)
    return ShardedEngine(mesh, capacity_per_shard=1 << 10, batch_per_shard=64)


class TestShardedEngine:
    def test_parity_vs_oracle(self, engine):
        oracle = Oracle()
        rng = np.random.default_rng(3)
        now = NOW
        for _ in range(4):
            reqs = [mk(f"k{rng.integers(0, 50)}",
                       hits=int(rng.integers(0, 3)),
                       algorithm=Algorithm.LEAKY_BUCKET if rng.integers(2)
                       else Algorithm.TOKEN_BUCKET)
                    for _ in range(120)]
            want = oracle.check_batch(reqs, now)
            got = engine.check_batch(reqs, now)
            for i, (w, g) in enumerate(zip(want, got)):
                assert g.error == ""
                assert (int(g.status), g.remaining, g.reset_time, g.limit) == \
                    (int(w.status), w.remaining, w.reset_time, w.limit), (i, reqs[i])
            now += 7_000

    def test_keys_spread_across_shards(self, engine):
        # distribution sanity: hash-range ownership covers all shards
        from gubernator_tpu.hashing import hash_keys, shard_of
        ks = [mk(f"spread{i}").key for i in range(2000)]
        shards = shard_of(hash_keys(ks), engine.n)
        assert len(set(shards.tolist())) == engine.n

    def test_expired_rows_reclaimed_by_sweep(self):
        # key churn beyond capacity: expired rows must be swept so new
        # keys keep landing (lrucache.go eviction analog)
        eng = ShardedEngine(make_mesh(n=2), capacity_per_shard=64,
                            batch_per_shard=64)
        now = NOW
        for gen in range(6):
            reqs = [mk(f"gen{gen}_{i}", duration=5_000) for i in range(60)]
            got = eng.check_batch(reqs, now)
            n_err = sum(1 for r in got if r.error)
            assert n_err == 0, f"gen {gen}: {n_err} table-full errors"
            now += 60_000  # previous generation fully expired
        assert eng.sweep_count > 0

    def test_overflow_wave_splitting(self, engine):
        # more same-shard requests than B: served in multiple waves
        reqs = [mk("hotkey", limit=1000) for _ in range(150)]
        got = engine.check_batch(reqs, NOW + 10**6)
        assert all(r.error == "" for r in got)
        assert [r.remaining for r in got] == list(range(999, 849, -1))


class TestOnDeviceGrow:
    def test_grow_preserves_every_row(self):
        eng = ShardedEngine(make_mesh(n=4), capacity_per_shard=1 << 9,
                            batch_per_shard=64)
        reqs = [mk(f"g{i}", limit=100) for i in range(600)]
        eng.check_batch(reqs, NOW)
        eng.check_batch(reqs[:200], NOW + 1)  # consume extra on some keys
        from gubernator_tpu.hashing import hash_request_keys

        khash = hash_request_keys(["shard"] * 600,
                                  [f"g{i}" for i in range(600)])
        found0, cols0 = eng.gather_rows(khash)
        assert found0.all()
        dropped = eng.grow(1 << 11)
        assert dropped == 0
        assert eng.cap_local == 1 << 11
        found1, cols1 = eng.gather_rows(khash)
        assert found1.all()
        for f in cols0:
            assert (cols0[f] == cols1[f]).all(), f
        # decisions continue against the migrated state
        got = eng.check_batch(reqs[:200], NOW + 2)
        assert [r.remaining for r in got] == [97] * 200

    def test_shrink_reports_drops_best_effort(self):
        eng = ShardedEngine(make_mesh(n=2), capacity_per_shard=1 << 9,
                            batch_per_shard=64)
        reqs = [mk(f"s{i}") for i in range(700)]
        got = eng.check_batch(reqs, NOW)
        live = sum(1 for r in got if not r.error)
        dropped = eng.grow(1 << 6)  # 128 slots total for ~700 keys
        assert dropped > 0
        from gubernator_tpu.core.table import occupancy

        assert int(occupancy(eng.state)) == live - dropped
        # surviving rows still serve correct decisions
        got2 = eng.check_batch(reqs, NOW + 1)
        assert any(not r.error and r.remaining == 8 for r in got2)

    def test_auto_grow_on_live_key_pressure(self):
        # tiny table + live keys only: without auto-grow this returns
        # "rate limit table full"; with it, capacity doubles on device
        # and every insert succeeds (the reference's LRU contract)
        eng = ShardedEngine(make_mesh(n=2), capacity_per_shard=1 << 6,
                            batch_per_shard=64,
                            auto_grow_limit=1 << 12)
        reqs = [mk(f"ag{i}", duration=10**7) for i in range(400)]
        got = eng.check_batch(reqs, NOW)
        assert all(r.error == "" for r in got)
        assert eng.cap_local > 1 << 6
        # and the packed lane takes the same path
        eng2 = ShardedEngine(make_mesh(n=2), capacity_per_shard=1 << 6,
                             batch_per_shard=64,
                             auto_grow_limit=1 << 12)
        from gubernator_tpu.core.batch import pack_columns
        from gubernator_tpu.hashing import hash_request_keys
        import numpy as np

        kh = hash_request_keys(["shard"] * 400,
                               [f"ag{i}" for i in range(400)])
        batch, errs = pack_columns(
            kh, np.ones(400, np.int64), np.full(400, 10, np.int64),
            np.full(400, 10**7, np.int64), np.zeros(400, np.int32),
            np.zeros(400, np.int32), np.zeros(400, np.int64), NOW)
        assert not errs
        _, _, _, _, full = eng2.check_packed(batch, kh, NOW)
        assert not full.any()
        assert eng2.cap_local > 1 << 6

    def test_proactive_grow_on_sweep_at_high_occupancy(self):
        import numpy as np

        from gubernator_tpu.hashing import hash_request_keys

        eng = ShardedEngine(make_mesh(n=2), capacity_per_shard=1 << 9,
                            batch_per_shard=64,
                            auto_grow_limit=1 << 12)
        # place ~68% live occupancy directly (upsert_rows never grows,
        # so this models traffic that built up between sweep ticks)
        n = 700
        kh = hash_request_keys(["shard"] * n,
                               [f"pg{i}" for i in range(n)])
        cols = {"meta": np.zeros(n, np.int32),
                "limit": np.full(n, 10, np.int64),
                "duration": np.full(n, 10**7, np.int64),
                "eff_ms": np.full(n, 10**7, np.int64),
                "burst": np.full(n, 10, np.int64),
                "remaining": np.full(n, 9, np.int64),
                "t_ms": np.full(n, NOW, np.int64),
                "expire_at": np.full(n, NOW + 10**7, np.int64)}
        placed = eng.upsert_rows(kh, cols)
        assert placed > 0.6 * eng.cap_local * eng.n
        cap0 = eng.cap_local
        eng.sweep(NOW + 1)
        assert eng.cap_local == cap0 * 2  # grew off the serving path
        found, got = eng.gather_rows(kh[:placed])
        # rows survive the proactive reshard with their values
        assert found.sum() >= placed - 5  # minus any upsert dup drops
        assert (got["remaining"][found] == 9).all()

    def test_grow_is_device_resident(self):
        # the whole point: no host column staging — state stays sharded
        eng = ShardedEngine(make_mesh(n=4), capacity_per_shard=1 << 8,
                            batch_per_shard=32)
        eng.check_batch([mk(f"d{i}") for i in range(100)], NOW)
        eng.grow(1 << 10)
        from jax.sharding import PartitionSpec as P

        assert eng.state.key.sharding.spec == P("shard")
        assert eng.state.key.shape[0] == 4 * (1 << 10)


def test_graft_entry_single():
    import __graft_entry__ as ge
    import jax

    fn, args = ge.entry()
    out_state, out = jax.jit(fn)(*args)
    assert int(out.status.sum()) >= 0


def test_graft_entry_multichip():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_aggregate_capacity_exceeds_any_single_shard():
    """The config-5 scale argument (SURVEY §5.7) at test scale: a key
    universe far beyond one shard's capacity fits the MESH because
    hash-range sharding spreads it across every shard's table —
    aggregate capacity is n x cap_local.  This is the mechanism that
    carries the 100M-key workload across chips when one HBM can't
    hold it."""
    import numpy as np

    from gubernator_tpu.core.batch import pack_columns
    from gubernator_tpu.hashing import mix64_np, shard_of

    n = 8
    cap_local = 1 << 11                      # 2048 rows per shard
    # auto-grow headroom: open addressing with 8 probes starts failing
    # inserts near 60% load, and the never-fail-insert contract answers
    # with growth (exactly how a production config-5 table is run)
    eng = ShardedEngine(make_mesh(n=n), capacity_per_shard=cap_local,
                        batch_per_shard=256,
                        auto_grow_limit=cap_local * 4)
    n_keys = int(n * cap_local * 0.6)        # 9830 keys: ~5x one shard
    assert n_keys > cap_local * 2

    ids = np.arange(1, n_keys + 1, dtype=np.uint64)
    kh = mix64_np(ids)
    kh = np.where(kh == 0, np.uint64(1), kh)
    B = 2048
    for a in range(0, n_keys, B):
        chunk = kh[a:a + B]
        m = len(chunk)
        batch, errs = pack_columns(
            chunk, np.ones(m, np.int64), np.full(m, 100, np.int64),
            np.full(m, 600_000, np.int64), np.zeros(m, np.int32),
            np.zeros(m, np.int32), np.zeros(m, np.int64),
            1_760_000_000_000)
        assert not errs
        st, lim, rem, rst, full = eng.check_packed(
            batch, chunk, 1_760_000_000_000)
        assert not full.any(), f"dropped rows at {a}"
        assert (np.asarray(rem) == 99).all()

    # every key is resident and readable (no silent resets)
    found, cols = eng.gather_rows(kh[:4096])
    assert found.all()
    assert (np.asarray(cols["remaining"])[:4096] == 99).all()

    # and genuinely spread: every shard holds a fair share
    shards = shard_of(kh, n)
    counts = np.bincount(shards, minlength=n)
    assert counts.min() > 0.6 * n_keys / n, counts.tolist()
    from gubernator_tpu.core.table import occupancy

    assert int(occupancy(eng.state)) == n_keys

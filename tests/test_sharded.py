"""Multi-device tests on the 8-device virtual CPU mesh — the analog of
the reference's in-process cluster tests (cluster/cluster.go +
functional_test.go › TestGlobalRateLimits, SURVEY.md §4)."""
import numpy as np
import pytest

from gubernator_tpu import Algorithm, Behavior, Oracle, RateLimitRequest, Status
from gubernator_tpu.parallel import ShardedEngine, make_mesh

NOW = 1_760_000_000_000


def mk(key, **kw):
    d = dict(hits=1, limit=10, duration=60_000)
    d.update(kw)
    return RateLimitRequest(name="shard", unique_key=key, **d)


@pytest.fixture(scope="module")
def engine():
    mesh = make_mesh(n=4)
    return ShardedEngine(mesh, capacity_per_shard=1 << 10, batch_per_shard=64)


class TestShardedEngine:
    def test_parity_vs_oracle(self, engine):
        oracle = Oracle()
        rng = np.random.default_rng(3)
        now = NOW
        for _ in range(4):
            reqs = [mk(f"k{rng.integers(0, 50)}",
                       hits=int(rng.integers(0, 3)),
                       algorithm=Algorithm.LEAKY_BUCKET if rng.integers(2)
                       else Algorithm.TOKEN_BUCKET)
                    for _ in range(120)]
            want = oracle.check_batch(reqs, now)
            got = engine.check_batch(reqs, now)
            for i, (w, g) in enumerate(zip(want, got)):
                assert g.error == ""
                assert (int(g.status), g.remaining, g.reset_time, g.limit) == \
                    (int(w.status), w.remaining, w.reset_time, w.limit), (i, reqs[i])
            now += 7_000

    def test_keys_spread_across_shards(self, engine):
        # distribution sanity: hash-range ownership covers all shards
        from gubernator_tpu.hashing import hash_keys, shard_of
        ks = [mk(f"spread{i}").key for i in range(2000)]
        shards = shard_of(hash_keys(ks), engine.n)
        assert len(set(shards.tolist())) == engine.n

    def test_expired_rows_reclaimed_by_sweep(self):
        # key churn beyond capacity: expired rows must be swept so new
        # keys keep landing (lrucache.go eviction analog)
        eng = ShardedEngine(make_mesh(n=2), capacity_per_shard=64,
                            batch_per_shard=64)
        now = NOW
        for gen in range(6):
            reqs = [mk(f"gen{gen}_{i}", duration=5_000) for i in range(60)]
            got = eng.check_batch(reqs, now)
            n_err = sum(1 for r in got if r.error)
            assert n_err == 0, f"gen {gen}: {n_err} table-full errors"
            now += 60_000  # previous generation fully expired
        assert eng.sweep_count > 0

    def test_overflow_wave_splitting(self, engine):
        # more same-shard requests than B: served in multiple waves
        reqs = [mk("hotkey", limit=1000) for _ in range(150)]
        got = engine.check_batch(reqs, NOW + 10**6)
        assert all(r.error == "" for r in got)
        assert [r.remaining for r in got] == list(range(999, 849, -1))


def test_graft_entry_single():
    import __graft_entry__ as ge
    import jax

    fn, args = ge.entry()
    out_state, out = jax.jit(fn)(*args)
    assert int(out.status.sum()) >= 0


def test_graft_entry_multichip():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)

"""Peer-picker tests (reference: hash_test.go analog — distribution
uniformity + stability under membership change)."""
from dataclasses import dataclass, field

import pytest

from gubernator_tpu.peers import (
    ConsistentHash,
    RegionPeerPicker,
    ReplicatedConsistentHash,
    crc64_hash,
)
from gubernator_tpu.types import PeerInfo


@dataclass
class FakePeer:
    info: PeerInfo = field(default_factory=PeerInfo)


def mk_peers(n, dc=""):
    return [FakePeer(PeerInfo(grpc_address=f"10.0.0.{i}:1051",
                              datacenter=dc)) for i in range(n)]


@pytest.mark.parametrize("picker_cls", [ConsistentHash, ReplicatedConsistentHash])
def test_distribution_uniform(picker_cls):
    """hash_test.go analog: keys spread evenly across peers."""
    picker = picker_cls()
    peers = mk_peers(8)
    for p in peers:
        picker.add(p)
    counts = {p.info.grpc_address: 0 for p in peers}
    n_keys = 50_000
    for i in range(n_keys):
        counts[picker.get(f"user_{i}").info.grpc_address] += 1
    mean = n_keys / len(peers)
    for addr, c in counts.items():
        # modulo hash is near-perfect; ring with 512 replicas within ~40%
        assert abs(c - mean) / mean < 0.45, (addr, c, mean)


@pytest.mark.parametrize("picker_cls", [ConsistentHash, ReplicatedConsistentHash])
def test_deterministic_across_instances(picker_cls):
    a, b = picker_cls(), picker_cls()
    for p in mk_peers(5):
        a.add(p)
    for p in mk_peers(5):
        b.add(p)
    for i in range(1000):
        k = f"k{i}"
        assert a.get(k).info.grpc_address == b.get(k).info.grpc_address


def test_ring_minimal_remap():
    """replicated_hash.go property: removing one of 8 peers remaps ~1/8
    of keys, not all of them (unlike the modulo picker)."""
    full = ReplicatedConsistentHash()
    for p in mk_peers(8):
        full.add(p)
    small = ReplicatedConsistentHash()
    for p in mk_peers(8)[:-1]:
        small.add(p)
    moved = sum(
        1 for i in range(20_000)
        if full.get(f"k{i}").info.grpc_address
        != small.get(f"k{i}").info.grpc_address)
    assert moved / 20_000 < 0.25  # ideal 1/8; allow slack


def test_get_by_peer_info_and_new():
    picker = ReplicatedConsistentHash()
    peers = mk_peers(3)
    for p in peers:
        picker.add(p)
    assert picker.get_by_peer_info(peers[1].info) is peers[1]
    assert picker.get_by_peer_info(PeerInfo(grpc_address="nope:1")) is None
    fresh = picker.new()
    assert fresh.peers() == []
    assert fresh.replicas == picker.replicas


def test_alternate_hash_fn():
    picker = ReplicatedConsistentHash(hash_fn=crc64_hash, replicas=64)
    for p in mk_peers(4):
        picker.add(p)
    assert picker.get("some_key") in picker.peers()


def test_empty_picker_raises():
    for picker in (ConsistentHash(), ReplicatedConsistentHash(),
                   RegionPeerPicker("dc1")):
        with pytest.raises(RuntimeError):
            picker.get("k")


def test_region_picker():
    picker = RegionPeerPicker("us-east")
    east, west = mk_peers(3, "us-east"), mk_peers(2, "us-west")
    for p in east + west:
        picker.add(p)
    assert len(picker.peers()) == 5
    assert picker.get("k1") in east  # local-region resolution
    assert picker.get_in_region("k1", "us-west") in west
    assert picker.get_in_region("k1", "eu") is None
    assert picker.get_by_peer_info(west[0].info) is west[0]

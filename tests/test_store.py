"""Store/Loader persistence tests (reference: store_test.go analog) and
engine snapshot/restore round-trip."""
import numpy as np

from gubernator_tpu.store import (
    CacheItem,
    FileLoader,
    MockLoader,
    MockStore,
    arrays_from_items,
    items_from_arrays,
)
from gubernator_tpu.types import Algorithm, RateLimitRequest, Status


def test_mock_store_records_calls():
    s = MockStore()
    req = RateLimitRequest(name="a", unique_key="u", limit=5, duration=1000)
    item = CacheItem(key=req.key, limit=5, remaining=4)
    s.on_change(req, item)
    assert s.called["on_change"] == 1
    got = s.get(req)
    assert s.called["get"] == 1 and got is item
    s.remove(req.key)
    assert s.called["remove"] == 1 and s.get(req) is None


def test_mock_loader_round_trip():
    ld = MockLoader()
    items = [CacheItem(key=f"a_k{i}", limit=10, remaining=i) for i in range(5)]
    ld.save(iter(items))
    assert ld.called["save"] == 1
    out = list(ld.load())
    assert ld.called["load"] == 1
    assert [i.remaining for i in out] == [0, 1, 2, 3, 4]


def test_item_array_round_trip():
    items = [
        CacheItem(key="a_1", algorithm=int(Algorithm.LEAKY_BUCKET), limit=7,
                  duration=5000, eff_ms=5000, burst=7, remaining=3 * 5000,
                  t_ms=123, expire_at=456, status=int(Status.OVER_LIMIT)),
        CacheItem(key="b_2", algorithm=int(Algorithm.TOKEN_BUCKET), limit=2,
                  duration=100, eff_ms=100, remaining=1, t_ms=1, expire_at=101),
    ]
    arrays = arrays_from_items(items)
    assert (arrays["key"] != 0).all()
    back = items_from_arrays(arrays)
    assert back[0].algorithm == int(Algorithm.LEAKY_BUCKET)
    assert back[0].status == int(Status.OVER_LIMIT)
    assert back[0].remaining == 3 * 5000
    assert back[1].limit == 2
    # key hashes must be the canonical identity hashes
    from gubernator_tpu.hashing import hash_key

    assert back[0].key_hash == hash_key("a", "1")


def test_file_loader(tmp_path):
    path = str(tmp_path / "snap" / "state.npz")
    ld = FileLoader(path)
    assert list(ld.load()) == []  # missing file → empty
    items = [CacheItem(key=f"n_k{i}", limit=5, remaining=5 - i,
                       duration=1000, eff_ms=1000, expire_at=10_000)
             for i in range(3)]
    ld.save(iter(items))
    out = list(ld.load())
    assert len(out) == 3
    assert sorted(i.remaining for i in out) == [3, 4, 5]


def test_engine_snapshot_restore(cpu_mesh):
    """Shutdown snapshot → fresh engine restore → decisions continue
    exactly where they left off (daemon.go › Loader wiring analog)."""
    from gubernator_tpu.parallel import ShardedEngine
    from gubernator_tpu.types import RateLimitRequest

    now = 1_760_000_000_000
    reqs = [RateLimitRequest(name="s", unique_key=f"k{i}", hits=3, limit=5,
                             duration=60_000) for i in range(40)]
    eng = ShardedEngine(cpu_mesh, capacity_per_shard=1 << 10,
                        batch_per_shard=64)
    r1 = eng.check_batch(reqs, now)
    assert all(r.remaining == 2 for r in r1)
    snap = eng.snapshot()
    assert len(snap["key"]) == 40

    eng2 = ShardedEngine(cpu_mesh, capacity_per_shard=1 << 10,
                        batch_per_shard=64)
    placed = eng2.restore(snap)
    assert placed == 40
    # 3 more hits: 2 remaining → OVER_LIMIT, remaining stays 2
    r2 = eng2.check_batch(reqs, now + 1000)
    assert all(int(r.status) == int(Status.OVER_LIMIT) for r in r2)
    assert all(r.remaining == 2 for r in r2)


def test_snapshot_npz_round_trip(tmp_path, cpu_mesh):
    from gubernator_tpu.parallel import ShardedEngine
    from gubernator_tpu.store import save_arrays

    now = 1_760_000_000_000
    eng = ShardedEngine(cpu_mesh, capacity_per_shard=1 << 10,
                        batch_per_shard=64)
    eng.check_batch(
        [RateLimitRequest(name="z", unique_key=f"k{i}", hits=1, limit=9,
                          duration=30_000) for i in range(10)], now)
    path = str(tmp_path / "s.npz")
    save_arrays(path, eng.snapshot())
    arrays = dict(np.load(path))
    eng2 = ShardedEngine(cpu_mesh, capacity_per_shard=1 << 10,
                        batch_per_shard=64)
    assert eng2.restore(arrays) == 10
    r = eng2.check_batch(
        [RateLimitRequest(name="z", unique_key="k3", hits=0, limit=9,
                          duration=30_000)], now + 5)
    assert r[0].remaining == 8

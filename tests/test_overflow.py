"""Adversarial-input parity: huge hits/limit/burst/duration must not
overflow int64 fixed-point products, and the device must agree with the
oracle after clamping (oracle.py "Input clamps"; bounds in types.py)."""
import numpy as np
import pytest

from gubernator_tpu import Algorithm, Oracle, RateLimitRequest
from gubernator_tpu.types import EFF_MAX, TD_BOUND
from gubernator_tpu.parallel import ShardedEngine, make_mesh

NOW = 1_772_000_000_000

HUGE = [2**31, 2**40, 2**62, 2**63 - 1]


@pytest.fixture(scope="module")
def engine():
    return ShardedEngine(make_mesh(n=2), capacity_per_shard=1 << 10,
                        batch_per_shard=64)


def test_huge_inputs_parity(engine):
    oracle = Oracle()
    reqs = []
    for j, h in enumerate(HUGE):
        for alg in (Algorithm.TOKEN_BUCKET, Algorithm.LEAKY_BUCKET):
            reqs.append(RateLimitRequest(
                name="ovf", unique_key=f"h{j}_{int(alg)}", hits=h,
                limit=h, duration=h, algorithm=alg, burst=h))
            reqs.append(RateLimitRequest(
                name="ovf", unique_key=f"m{j}_{int(alg)}", hits=1,
                limit=h, duration=h, algorithm=alg))
    now = NOW
    for wave in range(2):
        want = oracle.check_batch(reqs, now)
        got = engine.check_batch(reqs, now)
        for i, (w, g) in enumerate(zip(want, got)):
            assert g.error == ""
            assert (int(g.status), g.remaining, g.reset_time, g.limit) == \
                (int(w.status), w.remaining, w.reset_time, w.limit), \
                (wave, i, reqs[i])
        now += 10_000


def test_clamped_values_stay_in_int64(engine):
    """The leaky td fixed point at the clamp ceiling must not wrap."""
    r = RateLimitRequest(name="ovf", unique_key="edge", hits=1,
                         limit=2**63 - 1, duration=2**63 - 1,
                         algorithm=Algorithm.LEAKY_BUCKET, burst=2**63 - 1)
    got = engine.check_batch([r], NOW)[0]
    assert got.error == ""
    # duration clamps to DURATION_MAX, eff to EFF_MAX, and the leaky
    # value ceiling is TD_BOUND // eff
    cap_v = TD_BOUND // EFF_MAX
    assert 0 <= got.remaining <= cap_v
    assert got.limit == cap_v


def test_negative_inputs_clamp_to_zero(engine):
    oracle = Oracle()
    r = RateLimitRequest(name="ovf", unique_key="neg", hits=-5, limit=-1,
                         duration=-100)
    w = oracle.check_batch([r], NOW)[0]
    g = engine.check_batch([r], NOW)[0]
    assert (int(g.status), g.remaining, g.limit) == \
        (int(w.status), w.remaining, w.limit)

"""Wave-bucket routing (ShardedEngine._build_waves): coalesced bursts
must ride one big launch with a small-launch overflow tail — never a
second nearly-empty big launch — while preserving per-shard request
order (duplicate-key sequential parity depends on it)."""
import numpy as np
import pytest

from gubernator_tpu.hashing import shard_of
from gubernator_tpu.parallel import ShardedEngine, make_mesh


@pytest.fixture(scope="module")
def eng():
    return ShardedEngine(make_mesh(n=2), capacity_per_shard=1 << 10,
                         batch_per_shard=64)


def keys_for_shard(eng, shard, count, rng):
    """Uniform random hashes filtered to one shard."""
    out = []
    while len(out) < count:
        h = rng.integers(1, 2**64, dtype=np.uint64)
        if int(shard_of(int(h), eng.n)) == shard:
            out.append(h)
    return np.array(out, np.uint64)


class TestBuildWaves:
    def test_small_batch_takes_small_bucket(self, eng):
        rng = np.random.default_rng(3)
        kh = rng.integers(1, 2**64, size=40, dtype=np.uint64)
        waves = eng._build_waves(kh, np.arange(40))
        assert len(waves) == 1
        idx, slots, bw = waves[0]
        assert bw == eng.wave_buckets[0]
        assert sorted(idx.tolist()) == list(range(40))
        assert slots.max() < eng.n * bw

    def test_burst_rides_big_bucket_with_small_tail(self, eng):
        big = eng.wave_buckets[-1]
        rng = np.random.default_rng(4)
        n = eng.n * big + 70  # overflow past one full big wave
        kh = rng.integers(1, 2**64, size=n, dtype=np.uint64)
        waves = eng._build_waves(kh, np.arange(n))
        assert len(waves) == 2
        assert waves[0][2] == big
        # the overflow tail (≤ ~70 per shard) must NOT pay a second
        # big-shaped launch
        assert waves[1][2] == eng.wave_buckets[0]

    def test_slots_unique_and_in_range(self, eng):
        rng = np.random.default_rng(5)
        n = eng.n * eng.wave_buckets[-1] + 200
        kh = rng.integers(1, 2**64, size=n, dtype=np.uint64)
        covered = set()
        for idx, slots, bw in eng._build_waves(kh, np.arange(n)):
            assert len(np.unique(slots)) == len(slots)
            assert slots.min() >= 0 and slots.max() < eng.n * bw
            # slot's shard block must match the key's shard
            assert np.array_equal(slots // bw, shard_of(kh[idx], eng.n))
            covered.update(idx.tolist())
        assert covered == set(range(n))

    def test_per_shard_request_order_preserved(self, eng):
        """Within a shard, earlier pending positions get earlier slots
        (and earlier waves): duplicate keys apply in submission order."""
        rng = np.random.default_rng(6)
        kh0 = keys_for_shard(eng, 0, 150, rng)  # one hot shard
        waves = eng._build_waves(kh0, np.arange(150))
        seen = []
        for idx, slots, bw in waves:
            order = np.argsort(slots)
            seen.extend(idx[order].tolist())
        assert seen == list(range(150))

    def test_skewed_shard_picks_bucket_for_busiest(self, eng):
        """90 keys on one shard, 5 on the other: bucket must cover the
        busiest shard (90 > 64 → the 8× bucket on base 64)."""
        rng = np.random.default_rng(7)
        kh = np.concatenate([keys_for_shard(eng, 0, 90, rng),
                             keys_for_shard(eng, 1, 5, rng)])
        waves = eng._build_waves(kh, np.arange(95))
        assert len(waves) == 1
        assert waves[0][2] == next(b for b in eng.wave_buckets if b >= 90)

"""Write-through + read-through Store wiring on the instance
(reference: store.go › Store{OnChange, Get} around cache ops)."""
from gubernator_tpu.config import Config
from gubernator_tpu.instance import V1Instance
from gubernator_tpu.parallel import make_mesh
from gubernator_tpu.store import CacheItem, MockStore
from gubernator_tpu.types import RateLimitRequest, Status

NOW = 1_762_000_000_000


def req(key="k1", **kw):
    d = dict(hits=1, limit=10, duration=60_000)
    d.update(kw)
    return RateLimitRequest(name="rt", unique_key=key, **d)


def test_write_through_and_read_through():
    store = MockStore()
    inst = V1Instance(Config(cache_size=1 << 10, store=store,
                             sweep_interval_ms=0), mesh=make_mesh(n=2))
    try:
        r = inst.get_rate_limits([req()], now_ms=NOW)[0]
        assert r.remaining == 9
        # write-through recorded the mutation
        assert store.called["on_change"] == 1
        item = store.items["rt_k1"]
        assert item.remaining == 9 and item.status == int(Status.UNDER_LIMIT)
        # read-through consulted only on miss: second hit finds the row
        inst.get_rate_limits([req()], now_ms=NOW + 5)
        assert store.called["get"] == 1  # only the first (miss) batch
    finally:
        inst.close()


def test_read_through_seeds_fresh_instance():
    """A new instance with a populated Store serves from persisted state
    without a Loader snapshot."""
    store = MockStore()
    store.items["rt_k1"] = CacheItem(
        key="rt_k1", limit=10, duration=60_000, eff_ms=60_000,
        remaining=3, t_ms=NOW, expire_at=NOW + 60_000)
    inst = V1Instance(Config(cache_size=1 << 10, store=store,
                             sweep_interval_ms=0), mesh=make_mesh(n=2))
    try:
        r = inst.get_rate_limits([req(hits=0)], now_ms=NOW + 1000)[0]
        assert r.remaining == 3, "store state not seeded"
        r = inst.get_rate_limits([req(hits=3)], now_ms=NOW + 1001)[0]
        assert (int(r.status), r.remaining) == (0, 0)
    finally:
        inst.close()


def test_expired_store_item_starts_fresh():
    store = MockStore()
    store.items["rt_k2"] = CacheItem(
        key="rt_k2", limit=10, duration=60_000, eff_ms=60_000,
        remaining=0, t_ms=NOW - 120_000, expire_at=NOW - 60_000)
    inst = V1Instance(Config(cache_size=1 << 10, store=store,
                             sweep_interval_ms=0), mesh=make_mesh(n=2))
    try:
        r = inst.get_rate_limits([req(key="k2")], now_ms=NOW)[0]
        assert r.remaining == 9  # expired persisted item → fresh bucket
    finally:
        inst.close()

"""Soak/chaos integration: a 3-daemon cluster under mixed traffic with
membership churn (stateful handover on), asserting global conservation
and zero unexpected errors — the scaled-up analog of the reference's
functional suite driving real daemons over loopback gRPC."""
import threading
import time

import numpy as np

from gubernator_tpu.client import Client
from gubernator_tpu.cluster import start_with
from gubernator_tpu.config import BehaviorConfig, DaemonConfig
from gubernator_tpu.netutil import free_port
from gubernator_tpu.parallel import make_mesh
from gubernator_tpu.types import Algorithm, Behavior, RateLimitRequest

NOW = 1_778_000_000_000


def assert_pool_drained(cluster, n, deadline_s=10.0):
    """ISSUE 2 invariant, load-tolerant form (deflaked in ISSUE 16):
    ``leaks`` is zero-tolerance immediately — a leaked lease regrows
    the per-wave allocations the pool exists to remove.  ``outstanding``
    is different: the last client call returning does not mean the last
    wave landed (an async GLOBAL flush retrying through a
    DEADLINE_EXCEEDED can hold its lease for a beat), so it gets a
    drain window instead of an instant assert."""
    pools = [p for i in range(n)
             if (p := getattr(cluster.instance_at(i).engine,
                              "wave_pool", None)) is not None]
    for pool in pools:
        assert pool.stats()["leaks"] == 0, pool.stats()
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        snaps = [p.stats() for p in pools]
        if all(s["outstanding"] == 0 for s in snaps):
            break
        time.sleep(0.05)
    for pool in pools:
        s = pool.stats()
        assert s["leaks"] == 0 and s["outstanding"] == 0, s


def cfgs(n, handover=True):
    return [DaemonConfig(
        grpc_listen_address=f"127.0.0.1:{free_port()}",
        http_listen_address="",
        cache_size=1 << 11,
        handover_on_reshard=handover,
        behaviors=BehaviorConfig(batch_wait_ms=5, global_sync_wait_ms=50),
    ) for _ in range(n)]


def test_soak_mixed_traffic_with_churn():
    mesh = make_mesh(n=2)
    cluster = start_with(cfgs(3), mesh=mesh, batch_rows=64)
    rng = np.random.default_rng(17)
    errors = []
    admitted = {"strict": 0}
    lock = threading.Lock()
    LIMIT = 200

    def mk(i):
        kind = i % 4
        if kind == 0:  # the strict conservation key (token, forwarded)
            return RateLimitRequest(name="soak", unique_key="strict",
                                    hits=1, limit=LIMIT,
                                    duration=3_600_000)
        if kind == 1:  # leaky spread keys
            return RateLimitRequest(name="soak",
                                    unique_key=f"lk{i % 37}", hits=1,
                                    limit=10_000, duration=600_000,
                                    algorithm=Algorithm.LEAKY_BUCKET)
        if kind == 2:  # GLOBAL keys (wire tier / queues, multi-peer)
            return RateLimitRequest(name="soak", unique_key=f"g{i % 11}",
                                    hits=1, limit=10_000,
                                    duration=600_000,
                                    behavior=Behavior.GLOBAL)
        return RateLimitRequest(name="soak", unique_key=f"t{i % 53}",
                                hits=1, limit=10_000, duration=600_000)

    def worker(w):
        addr = cluster.grpc_address(w % 3)
        with Client(addr) as c:
            for r in range(12):
                reqs = [mk(w * 1000 + r * 40 + i) for i in range(40)]
                try:
                    rs = c.get_rate_limits(reqs)
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errors.append(repr(e))
                    continue
                with lock:
                    for req, resp in zip(reqs, rs):
                        if resp.error:
                            errors.append(resp.error)
                        elif (req.unique_key == "strict"
                              and int(resp.status) == 0):
                            admitted["strict"] += 1

    try:
        # phase 1: 6 clients across 3 daemons
        threads = [threading.Thread(target=worker, args=(w,))
                   for w in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # membership churn mid-life: daemon 2 leaves (its keys re-home;
        # survivors hand over nothing for keys they keep)
        infos2 = [cluster.peer_at(0), cluster.peer_at(1)]
        cluster.daemons[0].set_peers(infos2)
        cluster.daemons[1].set_peers(infos2)
        # phase 2: traffic continues against the shrunken ring
        threads = [threading.Thread(target=worker, args=(w,))
                   for w in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:5]
        # strict key: 10 workers × 12 rounds × 10 strict requests = 1200
        # attempts against capacity 200.  The key lives on ONE owner at
        # a time; churn may re-home it (reset or handover), so admitted
        # lies in [LIMIT, 2×LIMIT] — never more than one extra bucket.
        assert LIMIT <= admitted["strict"] <= 2 * LIMIT, admitted
        assert_pool_drained(cluster, 2)
    finally:
        cluster.stop()


def test_soak_pallas_serving_mode_with_churn(monkeypatch):
    """The same chaos shape over step_impl=pallas: mixed token/leaky/
    GLOBAL traffic + membership churn with stateful handover, so the
    kernel serving mode's row ops (gather/upsert/remove — the
    vectorized bucket paths) carry a real cluster's re-homing, not
    just unit fixtures.  Condensed load: interpret-mode steps on CPU
    are the cost, the coverage is the cluster mechanics."""
    monkeypatch.delenv("GUBER_STEP_IMPL", raising=False)
    mesh = make_mesh(n=2)
    confs = cfgs(3)
    for c in confs:
        c.step_impl = "pallas"
    cluster = start_with(confs, mesh=mesh, batch_rows=64)
    errors = []
    admitted = {"strict": 0}
    lock = threading.Lock()
    LIMIT = 60

    def mk(i):
        kind = i % 3
        if kind == 0:
            return RateLimitRequest(name="psoak", unique_key="strict",
                                    hits=1, limit=LIMIT,
                                    duration=3_600_000)
        if kind == 1:
            return RateLimitRequest(name="psoak",
                                    unique_key=f"lk{i % 19}", hits=1,
                                    limit=10_000, duration=600_000,
                                    algorithm=Algorithm.LEAKY_BUCKET)
        return RateLimitRequest(name="psoak", unique_key=f"g{i % 7}",
                                hits=1, limit=10_000, duration=600_000,
                                behavior=Behavior.GLOBAL)

    def worker(w):
        addr = cluster.grpc_address(w % 3)
        with Client(addr) as c:
            for r in range(6):
                reqs = [mk(w * 500 + r * 24 + i) for i in range(24)]
                try:
                    rs = c.get_rate_limits(reqs)
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errors.append(repr(e))
                    continue
                with lock:
                    for req, resp in zip(reqs, rs):
                        if resp.error:
                            errors.append(resp.error)
                        elif (req.unique_key == "strict"
                              and int(resp.status) == 0):
                            admitted["strict"] += 1

    try:
        threads = [threading.Thread(target=worker, args=(w,))
                   for w in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        infos2 = [cluster.peer_at(0), cluster.peer_at(1)]
        cluster.daemons[0].set_peers(infos2)
        cluster.daemons[1].set_peers(infos2)
        threads = [threading.Thread(target=worker, args=(w,))
                   for w in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:5]
        # 7 workers x 6 rounds x 8 strict reqs = 336 attempts against
        # capacity 60; churn may re-home the key once (reset or
        # handover) so admitted lies in [LIMIT, 2*LIMIT]
        assert LIMIT <= admitted["strict"] <= 2 * LIMIT, admitted
        assert_pool_drained(cluster, 2)
    finally:
        cluster.stop()

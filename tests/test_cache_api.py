"""Cache-interface parity: each/remove over the device table
(reference: cache.go › Cache{Each, Remove} — SURVEY.md §2.1)."""

from gubernator_tpu.config import Config
from gubernator_tpu.hashing import hash_keys
from gubernator_tpu.instance import V1Instance
from gubernator_tpu.parallel import ShardedEngine, make_mesh
from gubernator_tpu.store import MockStore
from gubernator_tpu.types import RateLimitRequest

NOW = 1_769_500_000_000


def req(key, **kw):
    d = dict(hits=1, limit=9, duration=60_000)
    d.update(kw)
    return RateLimitRequest(name="cache", unique_key=key, **d)


def test_each_iterates_live_rows(cpu_mesh):
    eng = ShardedEngine(cpu_mesh, capacity_per_shard=1 << 10,
                        batch_per_shard=64)
    eng.check_batch([req(f"k{i}") for i in range(12)], NOW)
    items = list(eng.each())
    assert len(items) == 12
    assert all(i.remaining == 8 for i in items)
    want = set(hash_keys([f"cache_k{i}" for i in range(12)]).tolist())
    assert {i.key_hash for i in items} == want


def test_remove_rows(cpu_mesh):
    eng = ShardedEngine(cpu_mesh, capacity_per_shard=1 << 10,
                        batch_per_shard=64)
    eng.check_batch([req(f"k{i}") for i in range(10)], NOW)
    kh = hash_keys([f"cache_k{i}" for i in range(10)])
    assert eng.remove_rows(kh[:4]) == 4
    assert eng.remove_rows(kh[:4]) == 0  # already gone
    # removed keys start fresh; the rest keep their state
    out = eng.check_batch([req(f"k{i}", hits=0) for i in range(10)], NOW + 5)
    assert [r.remaining for r in out] == [9] * 4 + [8] * 6


def test_instance_remove_including_hot_and_store():
    store = MockStore()
    inst = V1Instance(Config(cache_size=1 << 10, sweep_interval_ms=0,
                             store=store), mesh=make_mesh(n=2))
    try:
        inst.get_rate_limits([req("gone")], now_ms=NOW)
        assert inst.remove("cache", "gone") is True
        assert store.called["remove"] == 1
        assert inst.remove("cache", "gone") is False
        r = inst.get_rate_limits([req("gone", hits=0)], now_ms=NOW + 1)[0]
        assert r.remaining == 9  # fresh after removal
    finally:
        inst.close()

"""Fleet watchtower (ISSUE 19): merge exactness + audit conservation.

Pins the three acceptance-critical properties of the fleet plane:

- **Sketch merge exactness** — a 3-daemon key-partitioned workload's
  merged heavy-hitter sketch is byte-equal (canonical_bytes) to a
  single ground-truth sketch fed the union stream.
- **Tenant rollup Σ-equality** — the fleet tenant RED rollup's
  per-tenant sums equal the per-daemon ledgers' sums, exactly, on a
  live cluster.
- **Audit conservation under chaos** — 16 threads hammer GLOBAL keys
  across a 3-daemon cluster through a peer_send:error window; once the
  fault clears, every daemon's OWN /debug/audit vector drains to
  drift == 0 with zero lost weight (the identity
  ``injected == applied + queued + in_flight + lost`` settles).

Plus unit coverage for the pure fold functions (fold_audits,
ring_verdict, RingWatch, merge_slo/memory/status/tenants) and the
AuditTap ledger itself.
"""
import threading
import time

import numpy as np
import pytest

from gubernator_tpu import Behavior, RateLimitRequest
from gubernator_tpu import cluster as cluster_mod
from gubernator_tpu import fleet
from gubernator_tpu.analytics import HeavyHitterSketch
from gubernator_tpu.config import BehaviorConfig
from gubernator_tpu.fleet import (AuditTap, RingWatch, drift_bound_s,
                                  fold_audits, merge_memory, merge_slo,
                                  merge_status, merge_tenants,
                                  merge_topkeys, ring_verdict)
from gubernator_tpu.proto import gubernator_pb2 as pb

DAY = 24 * 3_600_000
NOW0 = 1_790_000_000_000
LIMIT = 10 ** 6


def serialize(reqs):
    msg = pb.GetRateLimitsReq()
    for r in reqs:
        m = msg.requests.add()
        m.name = r.name
        m.unique_key = r.unique_key
        m.hits = r.hits
        m.limit = r.limit
        m.duration = r.duration
        m.algorithm = int(r.algorithm)
        m.behavior = int(r.behavior)
        m.burst = r.burst
    return msg.SerializeToString()


def g_one(key: str, hits: int, name: str = "fleet") -> bytes:
    return serialize([RateLimitRequest(
        name=name, unique_key=key, hits=hits, limit=LIMIT,
        duration=DAY, behavior=Behavior.GLOBAL)])


def wait_until(pred, timeout=30.0, interval=0.05, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


# ---------------------------------------------------------------------------
# AuditTap: the sender-side double-entry ledger
# ---------------------------------------------------------------------------


class TestAuditTap:
    def test_identity_settles(self):
        tap = AuditTap()
        tap.inject(10)
        tap.inject(5, degraded=True)
        s = tap.snapshot()
        assert (s["injected"], s["deg_injected"]) == (15, 5)
        assert s["applied"] == 0
        tap.apply(10)
        tap.apply(5, deg=5)
        s = tap.snapshot()
        assert s["injected"] == s["applied"] == 15
        assert s["deg_applied"] == 5
        # backlog (the drift gauge) is now exactly zero
        assert s["injected"] - s["applied"] == 0
        assert s["deg_injected"] - s["deg_applied"] == 0

    def test_lose_settles_degraded_share(self):
        tap = AuditTap()
        tap.inject(8, degraded=True)
        tap.lose(8, deg=8)
        s = tap.snapshot()
        # lost weight never applies, but its degraded debt is settled
        assert s["lost"] == 8 and s["applied"] == 0
        assert s["deg_injected"] - s["deg_applied"] == 0
        # backlog stays nonzero forever: the loss detector
        assert s["injected"] - s["applied"] == 8

    def test_absorbed_is_subset_of_applied(self):
        tap = AuditTap()
        tap.inject(7)
        tap.apply(3, absorbed=True)
        tap.apply(4)
        s = tap.snapshot()
        assert s["applied"] == 7 and s["absorbed"] == 3

    def test_nonpositive_noop(self):
        tap = AuditTap()
        tap.inject(0)
        tap.inject(-3, degraded=True)
        tap.apply(0)
        tap.lose(-1)
        assert tap.snapshot() == {"injected": 0, "applied": 0,
                                  "deg_injected": 0, "deg_applied": 0,
                                  "absorbed": 0, "lost": 0}


class TestDriftBound:
    def test_default_is_two_flush_windows(self, monkeypatch):
        monkeypatch.delenv("GUBER_FLEET_DRIFT_BOUND", raising=False)
        b = BehaviorConfig(global_sync_wait_ms=250)
        assert drift_bound_s(b) == pytest.approx(0.5)

    def test_floor_at_100ms_window(self, monkeypatch):
        monkeypatch.delenv("GUBER_FLEET_DRIFT_BOUND", raising=False)
        b = BehaviorConfig(global_sync_wait_ms=10)
        assert drift_bound_s(b) == pytest.approx(0.2)

    def test_env_override_and_bad_value_fallback(self, monkeypatch):
        b = BehaviorConfig(global_sync_wait_ms=250)
        monkeypatch.setenv("GUBER_FLEET_DRIFT_BOUND", "1500ms")
        assert drift_bound_s(b) == pytest.approx(1.5)
        monkeypatch.setenv("GUBER_FLEET_DRIFT_BOUND", "bogus")
        assert drift_bound_s(b) == pytest.approx(0.5)

    def test_audit_enabled_gate(self, monkeypatch):
        monkeypatch.delenv("GUBER_FLEET_AUDIT", raising=False)
        assert fleet.audit_enabled()
        monkeypatch.setenv("GUBER_FLEET_AUDIT", "0")
        assert not fleet.audit_enabled()


# ---------------------------------------------------------------------------
# pure folds over synthetic /debug documents
# ---------------------------------------------------------------------------


def _audit_doc(inst, backlog=0, queued=0, lost=0, injected=100,
               drain_age=0.0, membership=("a:1", "b:1"),
               ejected=(), flush_ms=100, mesh_backlog=None):
    applied = injected - backlog
    lanes = {"global": {
        "injected": injected, "applied": applied,
        "deg_injected": 0, "deg_applied": 0, "absorbed": 0,
        "lost": lost, "queued": queued, "deg_queued": 0,
        "backlog": backlog,
        "in_flight": backlog - queued - lost, "deg_pending": 0}}
    drift = backlog
    if mesh_backlog is not None:
        lanes["mesh"] = {"injected": mesh_backlog + 50,
                         "folded": 50, "backlog": mesh_backlog,
                         "generation": 1, "pinned_keys": 0,
                         "last_staleness_s": 0.0}
        drift += mesh_backlog
    membership = list(membership)
    ejected = list(ejected)
    return {"instance": inst, "enabled": True, "drift": drift,
            "conserved": drift == 0, "lost": lost,
            "drain_age_s": drain_age, "bound_s": 0.2,
            "flush_window_ms": flush_ms,
            "lanes": lanes,
            "ring": {"generation": 3, "self": inst,
                     "membership": membership,
                     "routing": [a for a in membership
                                 if a not in set(ejected)],
                     "ejected": ejected}}


class TestFoldAudits:
    def test_conserved_fleet(self):
        docs = [_audit_doc("a:1"), _audit_doc("b:1")]
        f = fold_audits(docs)
        assert f["daemons"] == 2 and f["conserved"]
        assert f["drift"] == 0
        assert f["totals"]["injected"] == 200
        assert f["totals"]["applied"] == 200
        assert len(f["per_daemon"]) == 2
        assert f["staleness_bound_s"] == pytest.approx(0.1)

    def test_drift_sums_exactly(self):
        docs = [_audit_doc("a:1", backlog=7, queued=4),
                _audit_doc("b:1", backlog=5, queued=0, lost=5,
                           drain_age=3.5),
                _audit_doc("c:1", mesh_backlog=2)]
        f = fold_audits(docs)
        assert f["drift"] == 14 and not f["conserved"]
        assert f["totals"]["queued"] == 4
        assert f["totals"]["lost"] == 5
        assert f["totals"]["in_flight"] == 3
        assert f["totals"]["mesh_injected"] == 52
        assert f["totals"]["mesh_folded"] == 50
        assert f["max_drain_age_s"] == pytest.approx(3.5)
        by = {r["instance"]: r for r in f["per_daemon"]}
        assert by["a:1"]["drift"] == 7 and by["b:1"]["lost"] == 5


class TestRingVerdict:
    def test_consistent(self):
        v = ring_verdict([_audit_doc("a:1"), _audit_doc("b:1")])
        assert v["consistent"] and v["reasons"] == []
        assert v["ejected"] == []

    def test_membership_mismatch(self):
        v = ring_verdict([
            _audit_doc("a:1", membership=("a:1", "b:1")),
            _audit_doc("b:1", membership=("a:1", "b:1", "c:1"))])
        assert not v["consistent"]
        assert "membership_mismatch" in v["reasons"]

    def test_ejection_diverges_routing(self):
        v = ring_verdict([
            _audit_doc("a:1", ejected=("b:1",)),
            _audit_doc("b:1")])
        assert not v["consistent"]
        assert "peers_ejected" in v["reasons"]
        assert "routing_mismatch" in v["reasons"]
        assert v["ejected"] == ["b:1"]

    def test_generations_reported_never_compared(self):
        docs = [_audit_doc("a:1"), _audit_doc("b:1")]
        docs[0]["ring"]["generation"] = 2
        docs[1]["ring"]["generation"] = 9
        v = ring_verdict(docs)
        # per-daemon local counters: disagreement is NOT divergence
        assert v["consistent"]
        assert v["generations"] == {"a:1": 2, "b:1": 9}


class _Recorder:
    def __init__(self):
        self.events = []

    def record(self, kind, **fields):
        self.events.append((kind, fields))


class TestRingWatch:
    def test_edge_triggered_latch(self):
        rec = _Recorder()
        w = RingWatch()
        ok = [_audit_doc("a:1"), _audit_doc("b:1")]
        bad = [_audit_doc("a:1", ejected=("b:1",)), _audit_doc("b:1")]
        w.check(ok, recorder=rec)
        assert rec.events == []  # consistent start: nothing fires
        w.check(bad, recorder=rec)
        w.check(bad, recorder=rec)  # held divergence does NOT refire
        kinds = [k for k, _ in rec.events]
        assert kinds == ["fleet_ring_divergence"]
        assert rec.events[0][1]["reasons"] != ""
        w.check(ok, recorder=rec)
        w.check(ok, recorder=rec)  # held convergence does NOT refire
        kinds = [k for k, _ in rec.events]
        assert kinds == ["fleet_ring_divergence",
                         "fleet_ring_converged"]


class TestMergeSlo:
    def _doc(self, breached, fast, slow, ticks=10):
        return {"ticks": ticks, "slos": [
            {"slo": "availability", "kind": "ratio",
             "objective": 0.999, "breached": breached,
             "fast_burn": fast, "slow_burn": slow},
            {"slo": "fleet_conservation", "kind": "threshold",
             "objective": 0.95, "breached": False,
             "fast_burn": 0.0, "slow_burn": 0.0,
             "value": 0.0, "target": 0.2}]}

    def test_worst_of_latch_and_summed_burn(self):
        f = merge_slo([self._doc(False, 0.5, 0.1),
                       self._doc(True, 2.0, 0.4)])
        assert f["daemons"] == 2 and f["ticks"] == 20
        assert f["breached"] == ["availability"]
        row = {r["slo"]: r for r in f["slos"]}["availability"]
        assert row["breached"] and row["daemons"] == 2
        assert row["fast_burn_max"] == pytest.approx(2.0)
        assert row["fast_burn_sum"] == pytest.approx(2.5)
        assert row["slow_burn_sum"] == pytest.approx(0.5)
        fc = {r["slo"]: r for r in f["slos"]}["fleet_conservation"]
        assert fc["value_max"] == 0.0 and fc["target"] == 0.2


class TestMergeTenants:
    def _doc(self, a, b):
        return {"enabled": True,
                "tenants": {
                    "acme": {f: a for f in fleet.TENANT_FIELDS},
                    "bob": {f: b for f in fleet.TENANT_FIELDS}},
                "totals": {f: a + b for f in fleet.TENANT_FIELDS}}

    def test_sum_equality_asserted(self):
        f = merge_tenants([self._doc(3, 5), self._doc(7, 11)])
        assert f["conserved"] and f["mismatched_daemons"] == []
        assert f["tenants"]["acme"]["requests"] == 10
        assert f["tenants"]["bob"]["hits"] == 16
        assert f["totals"]["requests"] == 26

    def test_mismatch_flags_source_daemon(self):
        bad = self._doc(3, 5)
        bad["totals"]["hits"] += 1  # daemon lies about its own sum
        f = merge_tenants([self._doc(1, 1), bad])
        assert not f["conserved"]
        assert f["mismatched_daemons"] == [1]

    def test_disabled_daemon_skipped(self):
        f = merge_tenants([self._doc(2, 2), {"enabled": False}])
        assert f["enabled_daemons"] == 1 and f["conserved"]


class TestMergeMemoryAndStatus:
    def test_memory_fold(self):
        f = merge_memory([
            {"device_bytes": 100, "host_bytes": 10, "pressure": 0.2,
             "consumers": {"cache": {"bytes": 100}}},
            {"device_bytes": 50, "host_bytes": 20, "pressure": 0.9,
             "consumers": {"cache": {"bytes": 40},
                           "sketch": {"bytes": 10}}}])
        assert f["device_bytes"] == 150 and f["host_bytes"] == 30
        assert f["max_pressure"] == pytest.approx(0.9)
        assert f["consumer_bytes"] == {"cache": 140, "sketch": 10}

    def test_status_with_conservation(self):
        f = merge_status(
            [{"status": "healthy", "peer_count": 3},
             {"status": "unreachable"}],
            audit_docs=[_audit_doc("a:1", backlog=4)])
        assert f["daemons"] == 2 and f["healthy"] == 1
        assert f["ring"]["consistent"]
        assert f["conservation"] == {"drift": 4, "conserved": False}


# ---------------------------------------------------------------------------
# sketch merge exactness: key-partitioned fleet == union-stream truth
# ---------------------------------------------------------------------------


class TestSketchMergeExactness:
    K, WIDTH, DAEMONS, KEYS_PER = 64, 256, 3, 60

    def _waves(self):
        """Per-daemon key-partitioned waves: daemon d owns khashes
        d*1000+i — disjoint sets, 180 distinct keys total < width, so
        every sketch (per-daemon, merged, ground truth) is EXACT."""
        rng = np.random.default_rng(19)
        out = []
        for d in range(self.DAEMONS):
            kh = np.arange(d * 1000 + 1,
                           d * 1000 + 1 + self.KEYS_PER,
                           dtype=np.uint64)
            waves = []
            for w in range(4):
                pick = rng.integers(0, self.KEYS_PER, size=120)
                hits = rng.integers(1, 40, size=120).astype(np.int64)
                over = hits > 35
                waves.append((kh[pick], hits, over,
                              NOW0 + 1000 * w))
            out.append(waves)
        return out

    def test_merged_sketch_byte_equals_union_ground_truth(self):
        per_daemon = self._waves()
        truth = HeavyHitterSketch(k=self.K, width=self.WIDTH)
        docs = []
        for d, waves in enumerate(per_daemon):
            sk = HeavyHitterSketch(k=self.K, width=self.WIDTH)
            for kh, hits, over, t in waves:
                sk.update(kh, hits, over, t)
                truth.update(kh, hits, over, t)
            # the /debug/topkeys document shape merge_topkeys consumes
            rows = sk.topk(self.WIDTH)
            assert all(e["err"] == 0 for e in rows), "per-daemon exact"
            docs.append({
                "k": self.K, "width": self.WIDTH,
                "total_hits_observed": int(sk.total_weight),
                "keys": [dict(e, khash=f"0x{e['khash']:016x}",
                              owner=f"d{d}:105{d}") for e in rows]})
        merged = HeavyHitterSketch(k=self.K, width=self.WIDTH)
        for doc in docs:
            merged.merge_entries(doc["keys"],
                                 total_weight=doc
                                 ["total_hits_observed"])
        assert merged.canonical_bytes() == truth.canonical_bytes()

    def test_merge_topkeys_fold_matches_truth(self):
        per_daemon = self._waves()
        truth = HeavyHitterSketch(k=self.K, width=self.WIDTH)
        docs = []
        for d, waves in enumerate(per_daemon):
            sk = HeavyHitterSketch(k=self.K, width=self.WIDTH)
            for kh, hits, over, t in waves:
                sk.update(kh, hits, over, t)
                truth.update(kh, hits, over, t)
            docs.append({
                "k": self.K, "width": self.WIDTH,
                "total_hits_observed": int(sk.total_weight),
                "keys": [dict(e, khash=f"0x{e['khash']:016x}",
                              owner=f"d{d}:105{d}")
                         for e in sk.topk(self.WIDTH)]})
        out = merge_topkeys(docs, k=self.K)
        assert out["daemons"] == self.DAEMONS
        assert out["total_hits_observed"] == int(truth.total_weight)
        assert out["admission_error_bound"] == 0
        want = {f"0x{e['khash']:016x}": e["hits"]
                for e in truth.topk(self.K)}
        got = {e["khash"]: e["hits"] for e in out["keys"]}
        assert got == want
        # ring-owner attribution survives the merge
        owners = {e["khash"]: e["owner"] for e in out["keys"]}
        for h, o in owners.items():
            d = (int(h, 16) - 1) // 1000
            assert o == f"d{d}:105{d}"


# ---------------------------------------------------------------------------
# live cluster: tenant Σ-equality + conservation under chaos soak
# ---------------------------------------------------------------------------

SOAK_B = BehaviorConfig(
    batch_timeout_ms=400, batch_wait_ms=100,
    peer_retry_limit=1, peer_retry_backoff_ms=5,
    peer_circuit_threshold=2, peer_circuit_cooldown_ms=250,
    peer_eject_after_ms=300, peer_readmit_after_ms=250,
    global_sync_wait_ms=100)


def _settle_conserved(c, n, timeout=30.0):
    """Poke every daemon's GLOBAL flush loop until every daemon's OWN
    audit vector reports conserved (no test-harness ledger walking)."""
    def drained():
        docs = []
        for i in range(n):
            inst = c.instance_at(i)
            gm = inst.global_manager
            if gm is not None:
                gm.poke()
            docs.append(inst.audit_doc())
        return all(d["conserved"] for d in docs)
    wait_until(drained, timeout=timeout, interval=0.2,
               what="fleet audit drift to drain to zero")
    return [c.instance_at(i).audit_doc() for i in range(n)]


class TestFleetClusterLive:
    def test_tenant_rollup_sum_equality(self):
        pytest.importorskip("gubernator_tpu.ops._native")
        c = cluster_mod.start(3, behaviors=BehaviorConfig(
            global_sync_wait_ms=50))
        try:
            sent = {f"team{t}": 0 for t in range(3)}
            for i in range(3):
                inst = c.instance_at(i)
                for r in range(12):
                    t = f"team{r % 3}"
                    inst.get_rate_limits_wire(
                        g_one(f"trk{i}_{r}", 2, name=f"{t}/svc"),
                        now_ms=NOW0 + r)
                    sent[t] += 1
                assert inst.analytics.flush(timeout=10.0)
            docs = [c.instance_at(i).analytics.tenants_snapshot()
                    for i in range(3)]
            f = merge_tenants(docs)
            assert f["conserved"], f["mismatched_daemons"]
            assert f["enabled_daemons"] == 3
            for t, n in sent.items():
                assert f["tenants"][t]["requests"] == n
                assert f["tenants"][t]["hits"] == 2 * n
            # GLOBAL reconcile/broadcast rows land in other buckets
            # (hash-only columnar rows have no tenant name), so the
            # fleet totals dominate the named sends; the Σ-equality
            # proper is f["conserved"] above
            assert f["totals"]["requests"] >= sum(sent.values())
        finally:
            c.stop()

    def test_audit_conservation_under_chaos_soak(self):
        """16 threads × GLOBAL keys × a peer_send:error window: the
        fault forces flush retries/requeues mid-soak; after it clears,
        every daemon's own audit vector settles to drift == 0 with
        zero lost weight, and the fleet fold proves Σinjected ==
        Σapplied."""
        pytest.importorskip("gubernator_tpu.ops._native")
        c = cluster_mod.start(3, behaviors=SOAK_B)
        try:
            keys = [f"soak{i}" for i in range(12)]
            errs = []
            fault_on = threading.Event()

            def worker(t):
                inst = c.instance_at(t % 3)
                try:
                    for r in range(24):
                        if t == 0 and r == 8:
                            # mid-soak partition: daemon 0's sends err
                            c.instance_at(0).faults.arm(
                                "peer_send:error", seed=7)
                            fault_on.set()
                        out = pb.GetRateLimitsResp.FromString(
                            inst.get_rate_limits_wire(
                                g_one(keys[(t + r) % len(keys)], 1),
                                now_ms=NOW0 + 1 + r))
                        assert len(out.responses) == 1
                        # GLOBAL serves from the local replica: the
                        # partition must not surface caller errors
                        assert out.responses[0].error == ""
                except Exception as e:  # noqa: BLE001
                    errs.append(repr(e))

            ths = [threading.Thread(target=worker, args=(t,))
                   for t in range(16)]
            for th in ths:
                th.start()
            for th in ths:
                th.join(timeout=120)
            assert not any(th.is_alive() for th in ths), "stuck caller"
            assert not errs, errs[:3]
            assert fault_on.is_set()
            c.instance_at(0).faults.clear()

            docs = _settle_conserved(c, 3)
            f = fold_audits(docs)
            assert f["conserved"] and f["drift"] == 0
            assert f["totals"]["injected"] > 0
            assert f["totals"]["injected"] == f["totals"]["applied"]
            assert f["totals"]["lost"] == 0
            assert f["totals"]["queued"] == 0
            assert f["totals"]["in_flight"] == 0
            for d in docs:
                g = d["lanes"]["global"]
                assert g["injected"] == (g["applied"] + g["queued"]
                                         + g["in_flight"] + g["lost"])
            # the ring reconverges once the readmit window passes;
            # readmission needs live probes (the circuit half-opens
            # on traffic), so keep a trickle flowing while we wait
            probe = [0]

            def reconverged():
                probe[0] += 1
                for i in range(3):
                    inst = c.instance_at(i)
                    inst.get_rate_limits_wire(
                        g_one(keys[probe[0] % len(keys)], 0),
                        now_ms=NOW0 + 10_000 + probe[0])
                    gm = inst.global_manager
                    if gm is not None:
                        gm.poke()
                return ring_verdict(
                    [c.instance_at(i).audit_doc()
                     for i in range(3)])["consistent"]

            wait_until(reconverged, timeout=15.0, interval=0.2,
                       what="ring reconvergence")
            docs = _settle_conserved(c, 3)
            assert fold_audits(docs)["conserved"]
        finally:
            c.stop()

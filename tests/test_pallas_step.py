"""Bit-parity of the Pallas decision-step kernel (interpret mode) vs
the XLA step on shared TOKEN_BUCKET request streams.

The kernel owns its table layout (bucketized AoS vs the XLA SoA), so
parity is asserted on DECISIONS (status/remaining/reset/limit/err) and
on the aggregate counters — exactly the contract the oracle-parity
suite pins for the XLA step itself (tests/test_step_parity.py).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gubernator_tpu.core.batch import RequestBatch
from gubernator_tpu.core.step import decide_batch
from gubernator_tpu.core.table import init_table
from gubernator_tpu.ops.pallas_step import (EFF_BOUND, SLOTS, VALUE_BOUND,
                                            decide_batch_pallas,
                                            init_pallas_table,
                                            pallas_qualifies)
from gubernator_tpu.types import Behavior

i64, i32 = jnp.int64, jnp.int32
NOW = 1_760_000_000_000
FIELDS = ("status", "remaining", "reset_time", "limit", "err")


def mk_batch(keys, **over):
    B = len(keys)
    cols = dict(
        key=jnp.asarray(np.asarray(keys, np.uint64)),
        hits=jnp.ones(B, i64), limit=jnp.full(B, 10, i64),
        duration=jnp.full(B, 10_000, i64),
        eff_ms=jnp.full(B, 10_000, i64), greg_end=jnp.zeros(B, i64),
        behavior=jnp.zeros(B, i32), algorithm=jnp.zeros(B, i32),
        burst=jnp.full(B, 10, i64), valid=jnp.ones(B, bool),
        now=jnp.zeros(B, i64))
    cols.update(over)
    return RequestBatch(**cols)


def keyify(ids):
    k = (np.asarray(ids, np.uint64) + np.uint64(1)) \
        * np.uint64(0x9E3779B97F4A7C15)
    return np.where(k == 0, np.uint64(1), k)


def run_both(batches, nows, cap=1 << 12):
    pt, st = init_pallas_table(cap), init_table(cap)
    for b, now in zip(batches, nows):
        assert pallas_qualifies(b)
        pt, po = decide_batch_pallas(pt, b, jnp.asarray(now, i64),
                                     interpret=True)
        st, xo = decide_batch(st, b, jnp.asarray(now, i64))
        for f in FIELDS:
            a, c = np.asarray(getattr(po, f)), np.asarray(getattr(xo, f))
            assert (a == c).all(), \
                (f, np.nonzero(a != c)[0][:5].tolist())
        assert int(po.over_count) == int(xo.over_count)
        assert int(po.insert_count) == int(xo.insert_count)
    return pt, st


class TestPallasStepParity:
    def test_zipf_duplicates_multi_batch(self):
        rng = np.random.default_rng(1)
        batches, nows = [], []
        for w in range(6):
            ids = rng.zipf(1.3, size=512) % 200
            hits = rng.integers(0, 4, size=512)  # includes queries
            batches.append(mk_batch(keyify(ids),
                                    hits=jnp.asarray(hits, i64)))
            nows.append(NOW + w * 700)
        run_both(batches, nows)

    def test_expiry_and_refresh(self):
        keys = keyify(np.arange(64))
        batches = [mk_batch(keys, hits=jnp.full(64, 3, i64)),
                   mk_batch(keys, hits=jnp.full(64, 3, i64)),
                   # past expiry: buckets refresh
                   mk_batch(keys, hits=jnp.full(64, 3, i64))]
        run_both(batches, [NOW, NOW + 5_000, NOW + 25_000])

    def test_limit_and_duration_change_in_place(self):
        keys = keyify(np.arange(40))
        b1 = mk_batch(keys, hits=jnp.full(40, 4, i64))
        b2 = mk_batch(keys, limit=jnp.full(40, 25, i64))  # limit up
        b3 = mk_batch(keys, limit=jnp.full(40, 25, i64),
                      duration=jnp.full(40, 60_000, i64),
                      eff_ms=jnp.full(40, 60_000, i64))  # duration change
        b4 = mk_batch(keys, limit=jnp.full(40, 3, i64))  # limit down
        run_both([b1, b2, b3, b4],
                 [NOW, NOW + 100, NOW + 200, NOW + 300])

    def test_reset_and_drain_flags(self):
        rng = np.random.default_rng(2)
        keys = keyify(rng.integers(0, 30, size=256))
        beh = np.zeros(256, np.int32)
        beh[::7] = int(Behavior.RESET_REMAINING)
        beh[3::11] = int(Behavior.DRAIN_OVER_LIMIT)
        hits = rng.integers(0, 6, size=256)
        batches = [mk_batch(keys, hits=jnp.asarray(hits, i64),
                            behavior=jnp.asarray(beh))
                   for _ in range(3)]
        run_both(batches, [NOW, NOW + 50, NOW + 90])

    def test_gregorian_expiry_column(self):
        keys = keyify(np.arange(32))
        greg = np.full(32, NOW + 3_600_000, np.int64)
        beh = np.full(32, int(Behavior.DURATION_IS_GREGORIAN), np.int32)
        b = mk_batch(keys, behavior=jnp.asarray(beh),
                     greg_end=jnp.asarray(greg),
                     eff_ms=jnp.full(32, 3_600_000, i64))
        b2 = mk_batch(keys, behavior=jnp.asarray(beh),
                      greg_end=jnp.asarray(greg + 3_600_000),
                      eff_ms=jnp.full(32, 3_600_000, i64))
        # second batch past the boundary: fresh window adopts new end
        run_both([b, b, b2], [NOW, NOW + 1000, NOW + 3_700_000])

    def test_mixed_per_request_now(self):
        rng = np.random.default_rng(3)
        keys = keyify(rng.integers(0, 20, size=256))
        nows = NOW + rng.integers(0, 3_000, size=256).astype(np.int64)
        b = mk_batch(keys, now=jnp.asarray(nows, i64))
        # XLA path orders by (row, now); the kernel applies in batch
        # order — parity requires per-key-sorted arrival, so sort the
        # batch by (key, now) first, which preserves per-key time order
        order = np.lexsort((np.asarray(nows), np.asarray(b.key)))
        b = RequestBatch(*[jnp.asarray(np.asarray(c)[order]) for c in b])
        run_both([b], [NOW + 5_000])

    def test_invalid_rows_masked(self):
        keys = keyify(np.arange(64))
        valid = np.ones(64, bool)
        valid[10:20] = False
        b = mk_batch(keys, valid=jnp.asarray(valid))
        pt, po = decide_batch_pallas(init_pallas_table(1 << 10), b,
                                     jnp.asarray(NOW, i64),
                                     interpret=True)
        assert (np.asarray(po.status)[10:20] == 0).all()
        assert (np.asarray(po.remaining)[10:20] == 0).all()
        st, xo = decide_batch(init_table(1 << 10), b,
                              jnp.asarray(NOW, i64))
        for f in FIELDS:
            assert (np.asarray(getattr(po, f))
                    == np.asarray(getattr(xo, f))).all(), f

    def test_invalid_first_occupant_does_not_starve_bucket(self):
        """An invalid row that would be a bucket's tile-first occurrence
        must not become its representative: the later VALID same-bucket
        request still gets a real gather + decision + writeback."""
        keys = keyify(np.arange(1, 9))
        # row 0: invalid, same key (→ same bucket) as valid row 5
        key_col = np.concatenate([[np.asarray(keys)[5]], keys[:8]])
        valid = np.ones(9, bool)
        valid[0] = False
        b = mk_batch(key_col, valid=jnp.asarray(valid),
                     hits=jnp.full(9, 4, i64))
        pt, po = decide_batch_pallas(init_pallas_table(1 << 10), b,
                                     jnp.asarray(NOW, i64),
                                     interpret=True)
        st, xo = decide_batch(init_table(1 << 10), b,
                              jnp.asarray(NOW, i64))
        for f in FIELDS:
            assert (np.asarray(getattr(po, f))
                    == np.asarray(getattr(xo, f))).all(), f
        # and the debit persisted to the table
        b2 = mk_batch(key_col, valid=jnp.asarray(valid),
                      hits=jnp.zeros(9, i64))
        pt, po2 = decide_batch_pallas(pt, b2, jnp.asarray(NOW + 1, i64),
                                      interpret=True)
        assert int(po2.remaining[6]) == 6  # 10 - 4, row persisted

    def test_bucket_full_errors_without_corruption(self):
        """> SLOTS distinct keys forced into one bucket: the overflow
        keys err ('table full' contract), the resident keys still
        serve correctly."""
        cap = 256
        nb = cap // SLOTS
        # same low bits → same bucket; distinct high bits
        keys = np.array([(j << 40) | 5 for j in range(1, SLOTS + 4)],
                        np.uint64)
        b = mk_batch(keys)
        pt = init_pallas_table(cap)
        pt, po = decide_batch_pallas(pt, b, jnp.asarray(NOW, i64),
                                     interpret=True)
        err = np.asarray(po.err)
        assert err.sum() == 3  # 11 keys, 8 slots
        assert (np.asarray(po.status)[~err] == 0).all()
        assert (np.asarray(po.remaining)[~err] == 9).all()
        # the survivors keep serving (their state was not clobbered)
        pt, po2 = decide_batch_pallas(pt, b, jnp.asarray(NOW + 1, i64),
                                      interpret=True)
        assert (np.asarray(po2.remaining)[~np.asarray(po2.err)] == 8).all()

    def test_sustained_stream_parity(self):
        """Longer adversarial stream: hot keys, queries, flag churn,
        limit churn, expiry windows — 10 sequential batches."""
        rng = np.random.default_rng(7)
        batches, nows = [], []
        t = NOW
        for w in range(10):
            n = 384
            ids = rng.zipf(1.2, size=n) % 100
            hits = rng.integers(0, 5, size=n)
            lim = np.full(n, 10 + (w % 3) * 5, np.int64)
            beh = np.where(rng.random(n) < 0.05,
                           int(Behavior.RESET_REMAINING), 0)
            beh = np.where(rng.random(n) < 0.05,
                           beh | int(Behavior.DRAIN_OVER_LIMIT), beh)
            batches.append(mk_batch(
                keyify(ids), hits=jnp.asarray(hits, i64),
                limit=jnp.asarray(lim),
                behavior=jnp.asarray(beh.astype(np.int32))))
            t += int(rng.integers(0, 6_000))
            nows.append(t)
        run_both(batches, nows)


def mk_leaky(keys, **over):
    base = dict(algorithm=jnp.ones(len(keys), i32),
                limit=jnp.full(len(keys), 10, i64),
                burst=jnp.full(len(keys), 10, i64),
                duration=jnp.full(len(keys), 10_000, i64),
                eff_ms=jnp.full(len(keys), 10_000, i64))
    base.update(over)
    return mk_batch(keys, **base)


class TestPallasLeakyParity:
    """LEAKY_BUCKET parity: the kernel's paired-i32 td fixed point
    (in-kernel 64÷32 restoring division + 32×32→64 multiplies) vs the
    XLA step's native int64 arithmetic — every decision field, every
    wave (mirrors oracle.apply_leaky through test_step_parity's
    XLA-vs-oracle contract)."""

    def test_drain_and_replenish_over_time(self):
        keys = keyify(np.arange(48))
        n = 48
        batches, nows = [], []
        # drain 3/step at rate limit=10 per 10s → leak 1 token/s
        for w in range(8):
            batches.append(mk_leaky(keys, hits=jnp.full(n, 3, i64)))
            nows.append(NOW + w * 700)  # partial-token replenish steps
        run_both(batches, nows)

    def test_burst_differs_from_limit(self):
        keys = keyify(np.arange(32))
        b_hi = mk_leaky(keys, burst=jnp.full(32, 25, i64),
                        hits=jnp.full(32, 4, i64))
        b_lo = mk_leaky(keys, burst=jnp.full(32, 3, i64),
                        hits=jnp.full(32, 2, i64))
        run_both([b_hi, b_hi, b_hi], [NOW, NOW + 100, NOW + 5_000])
        run_both([b_lo, b_lo], [NOW, NOW + 30_000])

    def test_queries_and_flags(self):
        rng = np.random.default_rng(5)
        keys = keyify(rng.integers(0, 24, size=192))
        beh = np.zeros(192, np.int32)
        beh[::5] = int(Behavior.RESET_REMAINING)
        beh[2::7] = int(Behavior.DRAIN_OVER_LIMIT)
        hits = rng.integers(0, 5, size=192)  # queries included
        batches = [mk_leaky(keys, hits=jnp.asarray(hits, i64),
                            behavior=jnp.asarray(beh))
                   for _ in range(4)]
        run_both(batches, [NOW, NOW + 400, NOW + 900, NOW + 12_000])

    def test_eff_change_rescales_td(self):
        keys = keyify(np.arange(40))
        b1 = mk_leaky(keys, hits=jnp.full(40, 4, i64))
        # same window, new denominator: td rescales, fraction kept
        b2 = mk_leaky(keys, duration=jnp.full(40, 60_000, i64),
                      eff_ms=jnp.full(40, 60_000, i64))
        # back down mid-window
        b3 = mk_leaky(keys, duration=jnp.full(40, 7_000, i64),
                      eff_ms=jnp.full(40, 7_000, i64),
                      hits=jnp.full(40, 2, i64))
        run_both([b1, b2, b3], [NOW, NOW + 333, NOW + 666])

    def test_limit_change_and_alg_switch(self):
        keys = keyify(np.arange(24))
        lk = mk_leaky(keys, hits=jnp.full(24, 5, i64))
        lk2 = mk_leaky(keys, limit=jnp.full(24, 30, i64),
                       burst=jnp.full(24, 30, i64))
        tok = mk_batch(keys, hits=jnp.full(24, 2, i64))
        # leaky → leaky(limit change) → TOKEN (alg switch = fresh)
        # → back to leaky (fresh again)
        run_both([lk, lk2, tok, lk],
                 [NOW, NOW + 50, NOW + 100, NOW + 150])

    def test_mixed_token_and_leaky_rows_one_batch(self):
        rng = np.random.default_rng(9)
        n = 256
        ids = rng.integers(0, 40, size=n)
        alg = (ids % 2).astype(np.int32)  # per-key algorithm (stable)
        b = mk_batch(keyify(ids), algorithm=jnp.asarray(alg),
                     hits=jnp.asarray(rng.integers(0, 4, size=n), i64),
                     burst=jnp.full(n, 10, i64))
        run_both([b, b], [NOW, NOW + 800])

    def test_gregorian_leaky_rate(self):
        """DURATION_IS_GREGORIAN leaky: eff is the fixed-width rate
        duration (precomputed eff_ms column), expiry = now + eff."""
        from gubernator_tpu.gregorian import gregorian_rate_duration_ms
        from gubernator_tpu.types import GregorianDuration

        eff = gregorian_rate_duration_ms(int(GregorianDuration.HOURS))
        keys = keyify(np.arange(16))
        beh = np.full(16, int(Behavior.DURATION_IS_GREGORIAN), np.int32)
        b = mk_leaky(keys, behavior=jnp.asarray(beh),
                     duration=jnp.full(16, int(GregorianDuration.HOURS),
                                       i64),
                     eff_ms=jnp.full(16, eff, i64),
                     greg_end=jnp.full(16, NOW + 3_600_000, i64),
                     hits=jnp.full(16, 2, i64))
        run_both([b, b], [NOW, NOW + 60_000])

    def test_td_bounds_stress_carry_paths(self):
        """Counters and eff at the domain edge: td products near 2^61
        drive carries through every paired-i32 primitive (mul halves,
        add/sub borrows, 32-step division with sign-wrapped words)."""
        big_v = VALUE_BOUND - 1       # 2^30 - 1
        big_e = EFF_BOUND - 1         # 2^31 - 1
        keys = keyify(np.arange(12))
        b = mk_leaky(keys, limit=jnp.full(12, big_v, i64),
                     burst=jnp.full(12, big_v, i64),
                     duration=jnp.full(12, big_e, i64),
                     eff_ms=jnp.full(12, big_e, i64),
                     hits=jnp.full(12, big_v // 2, i64))
        # second wave replenishes with a large elapsed × limit product
        run_both([b, b, b], [NOW, NOW + 1_000_000, NOW + big_e + 5])
        # odd eff/hits mixes: division remainders on every lane
        b2 = mk_leaky(keys, limit=jnp.full(12, 999_983, i64),
                      burst=jnp.full(12, 1_000_003, i64),
                      duration=jnp.full(12, 2_147_483_629, i64),
                      eff_ms=jnp.full(12, 2_147_483_629, i64),
                      hits=jnp.full(12, 7, i64))
        run_both([b2, b2], [NOW, NOW + 777_777])

    def test_leaky_bucket_full_errors(self):
        """Overflowing bucket: leaky rows err like token rows."""
        keys = np.array([(j << 40) | 9 for j in range(1, SLOTS + 3)],
                        np.uint64)
        b = mk_leaky(keys)
        pt, po = decide_batch_pallas(init_pallas_table(256), b,
                                     jnp.asarray(NOW, i64),
                                     interpret=True)
        err = np.asarray(po.err)
        assert err.sum() == 2
        assert (np.asarray(po.remaining)[~err] == 9).all()

    def test_sustained_mixed_stream(self):
        """10 waves of mixed token/leaky traffic with churn on every
        axis the kernel branches on."""
        rng = np.random.default_rng(11)
        batches, nows = [], []
        t = NOW
        for w in range(10):
            n = 256
            ids = rng.zipf(1.2, size=n) % 60
            alg = (ids % 2).astype(np.int32)
            beh = np.where(rng.random(n) < 0.06,
                           int(Behavior.RESET_REMAINING), 0)
            beh = np.where(rng.random(n) < 0.06,
                           beh | int(Behavior.DRAIN_OVER_LIMIT), beh)
            dur = np.where(ids % 5 == 0, 25_000, 10_000).astype(np.int64)
            batches.append(mk_batch(
                keyify(ids), algorithm=jnp.asarray(alg),
                hits=jnp.asarray(rng.integers(0, 5, size=n), i64),
                limit=jnp.full(n, 10 + (w % 4) * 7, i64),
                burst=jnp.full(n, 10 + (w % 4) * 7, i64),
                duration=jnp.asarray(dur),
                eff_ms=jnp.asarray(dur),
                behavior=jnp.asarray(beh.astype(np.int32))))
            t += int(rng.integers(0, 9_000))
            nows.append(t)
        run_both(batches, nows)


class TestPropertyParity:
    """Hypothesis fuzz: ANY token/leaky stream inside the kernel's
    domain must match the XLA step exactly (same pattern as
    test_property_parity.py, scaled by GUBER_FUZZ_X)."""

    def test_any_stream_matches_xla(self):
        import os as _os

        from hypothesis import HealthCheck, given, settings
        from hypothesis import strategies as st

        _FX = int(_os.environ.get("GUBER_FUZZ_X", "1"))

        _beh = st.sampled_from([0, int(Behavior.RESET_REMAINING),
                                int(Behavior.DRAIN_OVER_LIMIT),
                                int(Behavior.RESET_REMAINING
                                    | Behavior.DRAIN_OVER_LIMIT)])
        _row = st.tuples(
            st.integers(0, 11),     # key id (forced dups)
            st.integers(0, 6),      # hits
            st.integers(0, 30),     # limit
            st.integers(1, 50_000),  # duration
            _beh,
            st.integers(0, 1),      # algorithm (token/leaky)
            st.integers(0, 35),     # burst (leaky; 0 → limit upstream,
                                    # here passed through as-is)
        )
        _stream = st.lists(
            st.tuples(st.lists(_row, min_size=1, max_size=32),
                      st.integers(0, 40_000)),
            min_size=1, max_size=4)

        B = 32  # fixed batch shape → one compiled program per mode

        @settings(max_examples=_FX * 15, deadline=None,
                  suppress_health_check=[HealthCheck.too_slow])
        @given(_stream)
        def run(stream):
            pt, st_x = init_pallas_table(1 << 9), init_table(1 << 9)
            now = NOW
            for rows, dt in stream:
                now += dt
                n = len(rows)
                ids = np.array([r[0] for r in rows])
                pad = B - n
                b = mk_batch(
                    np.pad(keyify(ids), (0, pad), constant_values=1),
                    hits=jnp.asarray(np.pad(
                        [r[1] for r in rows], (0, pad)), i64),
                    limit=jnp.asarray(np.pad(
                        [r[2] for r in rows], (0, pad)), i64),
                    duration=jnp.asarray(np.pad(
                        [r[3] for r in rows], (0, pad),
                        constant_values=1), i64),
                    eff_ms=jnp.asarray(np.pad(
                        [r[3] for r in rows], (0, pad),
                        constant_values=1), i64),
                    behavior=jnp.asarray(np.pad(
                        [r[4] for r in rows], (0, pad)).astype(np.int32)),
                    algorithm=jnp.asarray(np.pad(
                        [r[5] for r in rows], (0, pad)).astype(np.int32)),
                    burst=jnp.asarray(np.pad(
                        [max(r[6], 1) for r in rows], (0, pad),
                        constant_values=1), i64),
                    valid=jnp.asarray(
                        np.arange(B) < n))
                assert pallas_qualifies(b)
                pt, po = decide_batch_pallas(
                    pt, b, jnp.asarray(now, i64), interpret=True)
                st_x, xo = decide_batch(st_x, b, jnp.asarray(now, i64))
                for f in FIELDS:
                    a, c = (np.asarray(getattr(po, f)),
                            np.asarray(getattr(xo, f)))
                    assert (a == c).all(), \
                        (f, rows, np.nonzero(a != c)[0].tolist())

        run()


class TestQualifier:
    def test_domain_bounds(self):
        keys = keyify(np.arange(8))
        assert pallas_qualifies(mk_batch(keys))
        # leaky now qualifies (round-4 kernel extension) …
        assert pallas_qualifies(
            mk_batch(keys, algorithm=jnp.ones(8, i32)))
        # … but unknown algorithm values do not
        assert not pallas_qualifies(
            mk_batch(keys, algorithm=jnp.full(8, 2, i32)))
        assert not pallas_qualifies(
            mk_batch(keys, limit=jnp.full(8, VALUE_BOUND, i64)))
        assert not pallas_qualifies(
            mk_batch(keys, hits=jnp.full(8, -1, i64)))
        # leaky eff must fit the one-word divisor bound
        assert not pallas_qualifies(
            mk_batch(keys, algorithm=jnp.ones(8, i32),
                     eff_ms=jnp.full(8, EFF_BOUND, i64)))
        assert not pallas_qualifies(
            mk_batch(keys, algorithm=jnp.ones(8, i32),
                     eff_ms=jnp.zeros(8, i64)))
        # a token row with huge eff is fine (eff is not divided there)
        assert pallas_qualifies(
            mk_batch(keys, eff_ms=jnp.full(8, EFF_BOUND * 16, i64),
                     duration=jnp.full(8, EFF_BOUND * 16, i64)))
        # invalid rows don't disqualify (they're masked anyway)
        bad_invalid = mk_batch(
            keys, algorithm=jnp.full(8, 2, i32),
            valid=jnp.zeros(8, bool))
        assert pallas_qualifies(bad_invalid)

    def test_rejects_time_inverted_duplicates(self):
        """Same key with DECREASING now in batch order serializes
        differently in the kernel (batch order) than in the XLA path
        (arrival order) — the qualifier must route it to XLA."""
        keys = keyify(np.array([1, 2, 1]))
        nows = np.array([NOW + 100, NOW, NOW + 50], np.int64)
        assert not pallas_qualifies(
            mk_batch(keys, now=jnp.asarray(nows, i64)))
        # sorted per key: qualifies
        nows_ok = np.array([NOW, NOW + 50, NOW + 100], np.int64)
        assert pallas_qualifies(
            mk_batch(keyify(np.array([1, 1, 2])),
                     now=jnp.asarray(nows_ok, i64)))
        # an INVALID row between two time-inverted valid duplicates
        # must not mask the inversion (adjacency check runs on valid
        # rows only)
        keys3 = keyify(np.array([1, 1, 1]))
        nows3 = np.array([NOW + 100, NOW, NOW + 50], np.int64)
        valid3 = np.array([True, False, True])
        assert not pallas_qualifies(
            mk_batch(keys3, now=jnp.asarray(nows3, i64),
                     valid=jnp.asarray(valid3)))

"""cmd/cluster.py end-to-end: the last untested entry point (VERDICT r2
weak #7).  Boots the real subprocess CLI in both topologies, drives a
request through the printed addresses, and shuts down via SIGTERM."""
import json
import os
import re
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from gubernator_tpu.netutil import free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ENV = dict(
    os.environ,
    GUBER_JAX_PLATFORM="cpu",
    JAX_PLATFORMS="cpu",
    XLA_FLAGS="--xla_force_host_platform_device_count=2",
    GUBER_CACHE_SIZE="4096",
)


def _wait_lines(proc, pattern, n, timeout=180):
    """Read stdout lines until `pattern` matched n times (startup is
    slow on a cold compile; the daemon prints addresses when ready)."""
    lines, deadline = [], time.time() + timeout
    while len(lines) < n and time.time() < deadline:
        line = proc.stdout.readline().decode()
        if not line:
            if proc.poll() is not None:
                raise AssertionError(
                    f"cluster CLI exited early: "
                    f"{proc.stderr.read().decode()[-800:]}")
            time.sleep(0.05)
            continue
        m = re.search(pattern, line)
        if m:
            lines.append(m)
    assert len(lines) == n, f"only {len(lines)}/{n} matches"
    return lines


def _check_http(addr, name="cmdcl", key="k1"):
    body = json.dumps({"requests": [{
        "name": name, "uniqueKey": key, "hits": 1, "limit": 5,
        "duration": 60_000}]}).encode()
    req = urllib.request.Request(
        f"http://{addr}/v1/GetRateLimits", body,
        {"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as f:
        return json.loads(f.read())["responses"][0]


def _stop(proc):
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
        raise


def test_cluster_cli_in_process_topology():
    base = free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "gubernator_tpu.cmd.cluster",
         "--count", "2", "--base-port", str(base),
         "--cache-size", "4096"],
        cwd=REPO, env=ENV, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE)
    try:
        ms = _wait_lines(proc, r"daemon\[\d\] grpc=(\S+) http=(\S+)", 2)
        r = _check_http(ms[0].group(2))
        assert int(r.get("remaining", -1)) == 4, r
        # same bucket through daemon 1 (ring-shared ownership)
        r2 = _check_http(ms[1].group(2))
        assert int(r2.get("remaining", -1)) == 3, r2
    finally:
        _stop(proc)
    assert proc.returncode == 0


def test_cluster_cli_group_topology():
    proc = subprocess.Popen(
        [sys.executable, "-m", "gubernator_tpu.cmd.cluster",
         "--group", "--count", "2", "--cache-size", "4096"],
        cwd=REPO, env=ENV, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE)
    try:
        [mc] = _wait_lines(proc, r"group client=(\S+)", 1)
        ws = _wait_lines(proc, r"worker\[\d\] peer-grpc=\S+ http=(\S+)", 2)
        # front door serves through the shared reuseport address via
        # each worker's HTTP port (the gRPC shared port is exercised by
        # test_reuseport_group; here the CLI wiring is the subject)
        r = _check_http(ws[0].group(1), name="cmdgrp")
        assert int(r.get("remaining", -1)) == 4, r
        r2 = _check_http(ws[1].group(1), name="cmdgrp")
        assert int(r2.get("remaining", -1)) == 3, r2
    finally:
        _stop(proc)
    assert proc.returncode == 0


def test_cluster_cli_rejects_base_port_with_group():
    r = subprocess.run(
        [sys.executable, "-m", "gubernator_tpu.cmd.cluster",
         "--group", "--base-port", "12345"],
        cwd=REPO, env=ENV, capture_output=True, timeout=60)
    assert r.returncode != 0
    assert b"--base-port applies only without --group" in r.stderr

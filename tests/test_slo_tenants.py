"""Tenant-aware SLO plane (ISSUE 11): per-tenant attribution, the
burn-rate SLO engine, the collective cost model, and crash forensics.

Covers the tentpole invariants — bounded tenant cardinality with EXACT
``__other__`` folding (per-tenant sums == totals, conservation), the
multi-window breach→recover lifecycle (fake clock, deterministic), the
chaos staleness story on a real 8-device mesh (fold failures breach
``global_staleness``, a clean fold recovers it), the α-β cost-model fit
on held-out samples — plus the satellites: the ``/debug/tenants`` /
``/debug/slo`` / ``/debug/costmodel`` endpoints, the ``?tenant=`` event
filter, the drain debug dump, and ``healthcheck --fail-on-burn``."""
import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from gubernator_tpu.analytics import CostModel, TenantLedger
from gubernator_tpu.config import BehaviorConfig, Config, DaemonConfig
from gubernator_tpu.instance import V1Instance
from gubernator_tpu.oracle import OracleEngine
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.slo import SLO, SLO_CATALOG, SLOEngine
from gubernator_tpu.telemetry import FlightRecorder
from gubernator_tpu.types import RateLimitRequest

NOW = 1_791_000_000_000


def req(name, key, hits=1, **kw):
    d = dict(limit=100_000, duration=600_000)
    d.update(kw)
    return RateLimitRequest(name=name, unique_key=key, hits=hits, **d)


def ser(reqs):
    m = pb.GetRateLimitsReq()
    for r in reqs:
        q = m.requests.add()
        q.name, q.unique_key = r.name, r.unique_key
        q.hits, q.limit, q.duration = r.hits, r.limit, r.duration
        q.behavior = int(r.behavior)
        q.algorithm = int(r.algorithm)
    return m.SerializeToString()


def drain_analytics(ana):
    """Fold every queued tap (learn items included) into the ledgers."""
    ana.flush(timeout=5.0)
    ana.flush(timeout=5.0)  # second pass: learns land before re-counts


# ---- TenantLedger: bounded cardinality + exact conservation ------------


def test_tenant_ledger_bounded_cardinality(monkeypatch):
    """10× max distinct prefixes stay bounded at max+1 buckets and the
    overflow folds into ``__other__`` EXACTLY (conservation)."""
    monkeypatch.setenv("GUBER_TENANT_MAX", "8")
    tl = TenantLedger()
    n = 80  # 10× the max
    for i in range(n):
        idx = tl.index_of(f"t{i:03d}/api")
        tl.add(idx, "requests", 3)
    snap = tl.snapshot()
    assert snap["tenant_count"] <= 8 + 1  # + __other__
    assert snap["overflowed"] is True
    # conservation: every request landed somewhere
    per_tenant = sum(c["requests"] for c in snap["tenants"].values())
    assert per_tenant == snap["totals"]["requests"] == n * 3
    assert snap["tenants"][TenantLedger.OTHER]["requests"] == \
        (n - 8) * 3


def test_tenant_ledger_fold_conservation(monkeypatch):
    """Vectorized fold: hits/over counts distribute by bucket index
    with nothing lost, including rows folded to ``__other__``."""
    monkeypatch.setenv("GUBER_TENANT_MAX", "4")
    tl = TenantLedger()
    idxs = np.array([tl.index_of(f"p{i}/k") for i in range(12)])
    hits = np.arange(12, dtype=np.int64) + 1
    over = np.arange(12) % 3 == 0
    tl.fold(idxs, hits, over)
    tot = tl.totals()
    assert tot["requests"] == 12
    assert tot["hits"] == int(hits.sum())
    assert tot["over_limit"] == int(over.sum())
    snap = tl.snapshot()
    assert sum(c["hits"] for c in snap["tenants"].values()) == \
        int(hits.sum())


def test_tenant_ledger_chaos_soak_16_threads(monkeypatch):
    """16 threads hammer assignment, folds, flags, and snapshots
    concurrently; totals conserve exactly afterwards."""
    monkeypatch.setenv("GUBER_TENANT_MAX", "16")
    tl = TenantLedger()
    N_THREADS, PER = 16, 200
    errs = []

    def worker(w):
        try:
            rng = np.random.default_rng(w)
            for i in range(PER):
                idx = tl.index_of(f"ten{int(rng.integers(0, 40))}/x")
                tl.add(idx, "requests", 1)
                if i % 7 == 0:
                    idxs = np.array([idx, tl.index_of("soak/y")])
                    tl.fold(idxs, np.array([2, 1], np.int64),
                            np.array([False, True]))
                if i % 13 == 0:
                    tl.snapshot()
                    tl.red("shed")
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=worker, args=(w,))
          for w in range(N_THREADS)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert not errs
    snap = tl.snapshot()
    folds = sum(2 for w in range(N_THREADS)
                for i in range(PER) if i % 7 == 0)
    expect = N_THREADS * PER + folds
    assert snap["totals"]["requests"] == expect
    assert sum(c["requests"] for c in snap["tenants"].values()) == expect
    assert snap["tenant_count"] <= 16 + 1


# ---- instance-level attribution (both lanes) ---------------------------


def test_instance_tenant_attribution_conservation():
    """Object + wire lanes attribute every request to its key-prefix
    tenant; per-tenant sums equal the ledger totals equal the traffic
    actually sent (nothing dropped, nothing double-counted).  Default
    (sharded jax) engine: the wire lane needs check_packed."""
    inst = V1Instance(Config(cache_size=1 << 10, sweep_interval_ms=0,
                             batch_rows=64))
    try:
        sent = 0
        for w in range(3):
            reqs = [req(f"acme{i % 3}/api", f"u{w}_{i}")
                    for i in range(24)]
            inst.get_rate_limits(reqs, now_ms=NOW + w)
            sent += len(reqs)
            out = inst.get_rate_limits_wire(ser(reqs), now_ms=NOW + w)
            assert out
            sent += len(reqs)
        ana = inst.dispatcher.analytics
        drain_analytics(ana)
        snap = ana.tenants_snapshot()
        assert snap["enabled"]
        names = set(snap["tenants"])
        assert {"acme0", "acme1", "acme2"} <= names
        per_tenant = sum(c["requests"] for c in snap["tenants"].values())
        assert per_tenant == snap["totals"]["requests"] == sent
        # the three named tenants got equal shares; nothing leaked to
        # __other__ (cardinality 3 « the default max)
        for t in ("acme0", "acme1", "acme2"):
            assert snap["tenants"][t]["requests"] == sent // 3
    finally:
        inst.close()


def test_shed_attributed_to_tenant():
    """A drained dispatcher sheds with the triggering tenant on both
    the admission_shed event and the tenant ledger."""
    from gubernator_tpu.dispatcher import ResourceExhausted

    inst = V1Instance(Config(cache_size=1 << 10, sweep_interval_ms=0),
                      engine=OracleEngine())
    try:
        inst.get_rate_limits([req("shedco/api", "warm")], now_ms=NOW)
        ana = inst.dispatcher.analytics
        drain_analytics(ana)
        inst.dispatcher.drain()
        with pytest.raises(ResourceExhausted):
            inst.get_rate_limits([req("shedco/api", "k1")],
                                 now_ms=NOW + 1)
        evs = inst.recorder.events(kind="admission_shed")
        assert evs and evs[-1]["tenant"] == "shedco"
        drain_analytics(ana)
        assert ana.tenant_totals()["shed"] == 1
        assert ana.tenants_snapshot()["tenants"]["shedco"]["shed"] == 1
    finally:
        inst.close()


def test_wave_events_carry_tenant():
    inst = V1Instance(Config(cache_size=1 << 10, sweep_interval_ms=0),
                      engine=OracleEngine())
    try:
        inst.get_rate_limits([req("waveco/api", "k")], now_ms=NOW)
        evs = inst.recorder.events(kind="wave_completed")
        assert evs and evs[-1].get("tenant") == "waveco"
        # server-side tenant filter round trip
        assert inst.recorder.events(tenant="waveco")
        assert not inst.recorder.events(tenant="nobody")
    finally:
        inst.close()


# ---- SLO engine: deterministic breach → recover ------------------------


def test_slo_breach_recover_lifecycle():
    """Multi-window burn: a sustained bad period breaches (fast AND
    slow over threshold), a good period recovers (fast back under);
    events latch exactly once each."""
    rec = FlightRecorder()
    state = {"bad": 0.0, "total": 0.0}
    eng = SLOEngine(metrics=None, recorder=rec, fast_s=10.0,
                    slow_s=30.0, burn_threshold=2.0)
    eng.register(SLO("err", "ratio", 0.99,
                     lambda: (state["bad"], state["total"])))
    t = 1000.0
    for _ in range(35):  # healthy baseline fills both windows
        state["total"] += 100
        eng.tick(now=t)
        t += 1.0
    assert not rec.events(kind="slo_breach")
    for _ in range(35):  # 50% bad → burn 50 ≫ 2 in both windows
        state["total"] += 100
        state["bad"] += 50
        eng.tick(now=t)
        t += 1.0
    breaches = rec.events(kind="slo_breach")
    assert len(breaches) == 1 and breaches[0]["slo"] == "err"
    assert breaches[0]["fast_burn"] > 2.0
    for _ in range(40):  # clean again → fast window drains → recover
        state["total"] += 100
        eng.tick(now=t)
        t += 1.0
    recs = rec.events(kind="slo_recovered")
    assert len(recs) == 1 and recs[0]["slo"] == "err"
    assert len(rec.events(kind="slo_breach")) == 1  # latched once
    # verdicts() reports the latched state without re-evaluating
    v = {r["slo"]: r["breached"] for r in eng.verdicts()}
    assert v == {"err": False}


def test_slo_tenant_group_breach_is_attributed():
    rec = FlightRecorder()
    state = {"t-bad": (0.0, 0.0)}
    eng = SLOEngine(recorder=rec, fast_s=10.0, slow_s=20.0,
                    burn_threshold=2.0)
    eng.register_group("tenant_err", 0.99,
                       lambda: {"t-bad": state["t-bad"],
                                "t-good": (0.0, state["t-bad"][1])})
    t = 0.0
    for i in range(40):
        state["t-bad"] = (i * 60.0, i * 100.0)  # 60% bad
        eng.tick(now=t)
        t += 1.0
    evs = rec.events(kind="slo_breach")
    assert evs and evs[0]["slo"] == "tenant_err"
    assert evs[0]["tenant"] == "t-bad"
    assert not any(e.get("tenant") == "t-good" for e in evs)


def test_slo_threshold_kind_counts_out_of_bounds_ticks():
    eng = SLOEngine(fast_s=10.0, slow_s=20.0, burn_threshold=2.0)
    val = {"v": 0.0}
    eng.register(SLO("stale", "threshold", 0.95,
                     lambda: (val["v"], 1.0)))
    t = 0.0
    for _ in range(30):
        eng.tick(now=t)
        t += 1.0
    rows = eng.tick(now=t)
    assert rows[0]["fast_burn"] == 0.0
    val["v"] = 5.0  # out of bounds from here on
    for _ in range(15):
        rows = eng.tick(now=t)
        t += 1.0
    assert rows[0]["breached"]
    assert rows[0]["value"] == 5.0 and rows[0]["target"] == 1.0


# ---- chaos staleness on a real mesh ------------------------------------


def test_mesh_staleness_slo_breach_and_recover(monkeypatch):
    """The acceptance chaos story: fold failures stop the coherence
    clock, ``global_staleness`` breaches past 2× the reconcile
    interval, and a clean fold recovers it — pinned via the recorder
    events and the /debug/slo snapshot shape."""
    from gubernator_tpu.parallel import make_mesh

    monkeypatch.setenv("GUBER_MESH_GLOBAL_CAP", "256")
    monkeypatch.setenv("GUBER_SLO_FAST", "1s")
    monkeypatch.setenv("GUBER_SLO_SLOW", "2s")
    inst = V1Instance(
        Config(cache_size=1 << 12, sweep_interval_ms=0,
               global_mode="mesh", batch_rows=64,
               behaviors=BehaviorConfig(global_sync_wait_ms=100)),
        mesh=make_mesh(n=8))
    try:
        from gubernator_tpu.types import Behavior

        reqs = [req("mesh-t/api", f"k{i}", behavior=Behavior.GLOBAL)
                for i in range(8)]
        inst.get_rate_limits(reqs, now_ms=NOW)
        inst._mesh_reconcile_tick()  # clean fold: staleness clock set
        assert inst._mesh_last_fold_ok is not None
        eng = inst.slo
        t = 0.0
        for _ in range(12):  # healthy baseline
            eng.tick(now=t)
            t += 0.1
        assert not inst.recorder.events(kind="slo_breach")
        # chaos: every fold fails → the last-good-fold age grows past
        # the 2×interval target (0.2 s) in real time
        inst.faults.arm("global_psum:error", seed=5)
        inst._mesh_reconcile_tick()
        time.sleep(0.25)
        for _ in range(12):  # every tick now sees staleness > target
            eng.tick(now=t)
            t += 0.1
        breaches = inst.recorder.events(kind="slo_breach")
        assert any(e["slo"] == "global_staleness" for e in breaches), \
            breaches
        # recovery: clear the fault, one clean fold resets the clock
        inst.faults.clear()
        inst._mesh_reconcile_tick()
        for _ in range(25):
            eng.tick(now=t)
            t += 0.1
        recovered = inst.recorder.events(kind="slo_recovered")
        assert any(e["slo"] == "global_staleness" for e in recovered), \
            recovered
        snap = eng.snapshot()
        row = next(r for r in snap["slos"]
                   if r["slo"] == "global_staleness")
        assert not row["breached"] and row["value"] < row["target"]
        # the fold also fed the cost model
        cm = inst.dispatcher.analytics.costmodel_snapshot()
        assert any(b["phase"] == "global_fold" and b["ndev"] == 8
                   for b in cm["buckets"])
    finally:
        inst.close()


# ---- cost model: fit + held-out prediction -----------------------------


def test_cost_model_recovers_alpha_beta_held_out():
    """Noisy synthetic α-β samples: the closed-form fit predicts
    held-out durations within 10% relative error."""
    rng = np.random.default_rng(7)
    cm = CostModel()
    alpha, beta = 200e-6, 0.8e-9  # 200 µs + 0.8 ns/byte
    sizes = rng.integers(10_000, 5_000_000, size=60)
    for s in sizes:
        noise = 1.0 + float(rng.normal(0, 0.01))
        cm.add("fold", int(s), 8, (alpha + beta * int(s)) * noise)
    fit = cm.fit("fold", 8)
    assert fit is not None
    for s in (25_000, 400_000, 4_000_000):  # held out
        pred = cm.predict("fold", 8, s)
        truth = alpha + beta * s
        assert abs(pred - truth) / truth < 0.10, (s, pred, truth)
    assert abs(fit["alpha_s"] - alpha) / alpha < 0.25
    assert abs(fit["beta_s_per_byte"] - beta) / beta < 0.10
    snap = cm.snapshot()
    assert snap["model"].startswith("T = alpha")
    assert snap["buckets"][0]["samples"] == 60


# ---- crash forensics: the drain dump -----------------------------------


def test_debug_dump_on_close(tmp_path, monkeypatch):
    monkeypatch.setenv("GUBER_DEBUG_DUMP_DIR", str(tmp_path))
    monkeypatch.setenv("GUBER_INSTANCE_ID", "dump-test")
    inst = V1Instance(Config(cache_size=1 << 10, sweep_interval_ms=0),
                      engine=OracleEngine())
    inst.get_rate_limits([req("dumpco/api", "k")], now_ms=NOW)
    inst.close()
    files = sorted(tmp_path.glob("guber_dump_dump-test_*.jsonl"))
    assert len(files) == 1
    lines = files[0].read_text().splitlines()
    header = json.loads(lines[0])
    assert header["kind"] == "dump_header"
    assert header["instance"] == "dump-test"
    assert isinstance(header["slo_verdicts"], list)
    assert {v["slo"] for v in header["slo_verdicts"]} >= \
        {"decision_p99", "error_ratio", "shed_ratio"}
    events = [json.loads(ln) for ln in lines[1:]]
    assert len(events) == header["events"] >= 1
    assert any(e["kind"] == "wave_completed" for e in events)
    # the write itself left a breadcrumb in the (post-dump) ring
    assert inst.recorder.events(kind="debug_dump_written")


def test_debug_dump_disabled_by_default(tmp_path, monkeypatch):
    monkeypatch.delenv("GUBER_DEBUG_DUMP_DIR", raising=False)
    inst = V1Instance(Config(cache_size=1 << 10, sweep_interval_ms=0),
                      engine=OracleEngine())
    inst.get_rate_limits([req("a/b", "k")], now_ms=NOW)
    inst.close()
    assert not inst.recorder.events(kind="debug_dump_written")


# ---- daemon endpoints + CLI + healthcheck ------------------------------


@pytest.fixture(scope="module")
def daemon():
    from gubernator_tpu.daemon import spawn_daemon
    from gubernator_tpu.netutil import free_port

    # a lax p99 target + quick ticks: the SLO plane must not flap the
    # endpoint tests on a loaded CI box
    os.environ["GUBER_SLO_P99_MS"] = "60000"
    os.environ["GUBER_SLO_TICK"] = "100ms"
    try:
        d = spawn_daemon(DaemonConfig(
            grpc_listen_address=f"127.0.0.1:{free_port()}",
            http_listen_address=f"127.0.0.1:{free_port()}",
            cache_size=1 << 10), engine=OracleEngine())
    finally:
        del os.environ["GUBER_SLO_P99_MS"]
        del os.environ["GUBER_SLO_TICK"]
    yield d
    d.close()


def _get(daemon, path, timeout=10):
    url = f"http://127.0.0.1:{daemon.http_port}{path}"
    with urllib.request.urlopen(url, timeout=timeout) as f:
        return json.loads(f.read())


def _post_check(daemon, name, key):
    body = json.dumps({"requests": [{
        "name": name, "unique_key": key, "hits": 1, "limit": 100,
        "duration": 60_000}]}).encode()
    r = urllib.request.Request(
        f"http://127.0.0.1:{daemon.http_port}/v1/GetRateLimits",
        data=body, headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(r, timeout=30) as f:
        return json.loads(f.read())


def test_debug_tenants_endpoint(daemon):
    for i in range(6):
        _post_check(daemon, f"team{i % 2}/svc", f"k{i}")
    body = _get(daemon, "/debug/tenants")
    assert body["enabled"]
    assert {"team0", "team1"} <= set(body["tenants"])
    assert sum(c["requests"] for c in body["tenants"].values()) == \
        body["totals"]["requests"]


def test_debug_slo_endpoint(daemon):
    body = _get(daemon, "/debug/slo")
    assert body["burn_threshold"] > 0
    names = {r["slo"] for r in body["slos"]}
    # instance-wide SLOs always present; tenant groups appear once
    # attributed traffic exists (the test above sent some)
    assert {"decision_p99", "global_staleness", "error_ratio",
            "shed_ratio"} <= names
    for r in body["slos"]:
        assert r["slo"] in SLO_CATALOG
        assert "fast_burn" in r and "breached" in r


def test_debug_costmodel_endpoint(daemon):
    body = _get(daemon, "/debug/costmodel")
    assert body["model"] == "T = alpha + beta * bytes"
    assert isinstance(body["buckets"], list)


def test_healthz_deep_has_slo_block(daemon):
    body = _get(daemon, "/healthz?deep=1")
    assert "slo" in body
    assert set(body["slo"]) >= {"breached", "burning", "max_fast_burn",
                                "burn_threshold"}


def test_debug_events_tenant_filter_endpoint(daemon):
    _post_check(daemon, "filterco/svc", "fk")
    evs = _get(daemon, "/debug/events?tenant=filterco")["events"]
    assert evs and all(e["tenant"] == "filterco" for e in evs)
    assert not _get(daemon, "/debug/events?tenant=ghost")["events"]


def test_cli_debug_tenants_and_slo(daemon, capsys):
    from gubernator_tpu.cmd.cli import main

    url = f"http://127.0.0.1:{daemon.http_port}"
    assert main(["debug", "tenants", "--url", url]) == 0
    out = capsys.readouterr().out
    assert "team0" in out and "TOTAL" in out
    assert main(["debug", "slo", "--url", url, "--json"]) == 0
    body = json.loads(capsys.readouterr().out)
    assert {r["slo"] for r in body["slos"]} >= {"decision_p99"}


def test_healthcheck_fail_on_burn(daemon, capsys):
    from gubernator_tpu.cmd.healthcheck import main

    url = f"http://127.0.0.1:{daemon.http_port}/healthz"
    # nothing breached (lax targets) → ready
    assert main(["--url", url, "--fail-on-burn"]) == 0
    capsys.readouterr()


def test_healthcheck_fail_on_burn_exits_1_on_breach(capsys):
    """Flag logic against a canned /healthz: a breached SLO flips the
    exit code; without the flag the same body stays healthy."""
    from http.server import BaseHTTPRequestHandler, HTTPServer

    from gubernator_tpu.cmd.healthcheck import main

    body = json.dumps({
        "status": "healthy", "message": "", "peer_count": 0,
        "slo": {"breached": ["error_ratio"], "burning": ["error_ratio"],
                "max_fast_burn": 9.5, "burn_threshold": 2.0}}).encode()

    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        url = f"http://127.0.0.1:{srv.server_address[1]}/healthz"
        assert main(["--url", url, "--fail-on-burn"]) == 1
        assert "SLO breached: error_ratio" in capsys.readouterr().err
        assert main(["--url", url]) == 0  # plain probe ignores burn
    finally:
        srv.shutdown()
        srv.server_close()
